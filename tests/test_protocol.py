"""End-to-end protocol tests: in-process leader + two colocated servers vs a
brute-force heavy-hitters oracle (the integration-test shape of the
reference's collect_test.rs: known multiset in, exact counts out —
SURVEY.md §4)."""

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import collect, driver
from fuzzyheavyhitters_tpu.utils import bits as bitutils


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Colocated-driver e2e on the CPU backend: the same flow runs against
    the real device in tests/test_rpc.py; duplicating it on the tunnel
    costs ~10 s per compile (see conftest)."""
    yield


def brute_force_hitters(pts, ball, L, thresh):
    """All leaves x where #{clients whose saturated L∞ ball contains x} >=
    thresh, with exact counts.  pts: int[N, d]."""
    pts = np.asarray(pts)
    n, d = pts.shape
    lo = np.clip(pts - ball, 0, (1 << L) - 1)
    hi = np.clip(pts + ball, 0, (1 << L) - 1)
    out = {}
    grid = np.stack(
        np.meshgrid(*[np.arange(1 << L)] * d, indexing="ij"), axis=-1
    ).reshape(-1, d)
    for x in grid:
        c = int(np.sum(np.all((x >= lo) & (x <= hi), axis=1)))
        if c >= thresh:
            out[tuple(int(v) for v in x)] = c
    return out


def run_protocol(pts, ball, L, threshold, f_max=128):
    pts = np.asarray(pts)
    n, d = pts.shape
    rng = np.random.default_rng(99)
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, ball, rng)
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=f_max)
    res = lead.run(nreqs=n, threshold=threshold)
    got = {}
    for i in range(res.paths.shape[0]):
        key = tuple(int(v) for v in res.decode_ints()[i])
        got[key] = int(res.counts[i])
    return got


@pytest.mark.parametrize("d,L,ball", [(1, 6, 3), (2, 5, 2)])
def test_heavy_hitters_match_brute_force(rng, d, L, ball):
    n = 40
    # clustered points so some leaves clear the threshold
    centers = rng.integers(0, 1 << L, size=(4, d))
    pts = centers[rng.integers(0, 4, size=n)] + rng.integers(-1, 2, size=(n, d))
    pts = np.clip(pts, 0, (1 << L) - 1)
    threshold = 0.1  # thresh = max(1, 4)
    got = run_protocol(pts, ball, L, threshold, f_max=512 if d == 2 else 128)
    want = brute_force_hitters(pts, ball, L, max(1, int(threshold * n)))
    assert got == want


def test_no_survivors_early_exit(rng):
    pts = np.array([[3], [10], [40]])
    got = run_protocol(pts, 1, 6, threshold=0.99)  # thresh=2, balls disjoint
    assert got == {}


def test_single_client_threshold_one(rng):
    """threshold*nreqs < 1 floors to 1 (ref: leader.rs:193)."""
    pts = np.array([[17]])
    got = run_protocol(pts, 2, 6, threshold=0.0001)
    want = brute_force_hitters(pts, 2, 6, 1)
    assert got == want


def test_liveness_flag_gates_counts(rng):
    """Disabling a client's liveness flag removes it from every count
    (ref: collect.rs:495 — the hook the sketch verification uses)."""
    pts = np.array([[8], [8], [8], [50]])
    L, ball = 6, 1
    pts_bits = np.array([[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts])
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, ball, np.random.default_rng(5))
    s0, s1 = driver.make_servers(k0, k1)
    s0.alive_keys[0] = False
    s1.alive_keys[0] = False
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=128)
    res = lead.run(nreqs=4, threshold=0.5)  # thresh=2
    got = {tuple(r): c for r, c in zip(res.decode_ints(), res.counts)}
    # only two live clients at 8 remain above threshold
    assert set(got) == {(7,), (8,), (9,)}
    assert all(c == 2 for c in got.values())


def test_f_max_overflow_raises(rng):
    pts = np.tile(np.arange(0, 64, 2)[:, None], (1, 1))  # 32 spread clients
    with pytest.raises(ValueError, match="f_max"):
        run_protocol(pts, 3, 6, threshold=0.001, f_max=4)


def test_pattern_masks_layout():
    m = collect.pattern_masks(2)
    assert m.shape == (4,)
    # pattern 0: dirs (0,0) -> bits at (j*4 + s*2 + 0)
    assert m[0] == sum(1 << (j * 4 + s * 2) for j in range(2) for s in range(2))
    # pattern 3: dirs (1,1)
    assert m[3] == sum(1 << (j * 4 + s * 2 + 1) for j in range(2) for s in range(2))


def test_bucket_for_and_compact_survivors():
    """Bucketed-frontier helpers: power-of-2 sizing with the f_max cap and
    the min_bucket pin, and compact_survivors padding to the bucket."""
    assert [collect.bucket_for(n, 64) for n in (0, 1, 2, 3, 4, 5, 33, 64)] == [
        1, 1, 2, 4, 4, 8, 64, 64,
    ]
    assert collect.bucket_for(3, 64, min_bucket=16) == 16
    assert collect.bucket_for(60, 64, min_bucket=16) == 64
    with pytest.raises(ValueError, match="f_max"):
        collect.bucket_for(65, 64)
    keep = np.zeros((4, 2), bool)
    keep[0, 1] = keep[2, 0] = keep[3, 1] = True
    parent, pattern, n_alive = collect.compact_survivors(keep, 64)
    assert n_alive == 3 and parent.shape == (4,)  # padded to bucket 4
    assert parent[:3].tolist() == [0, 2, 3]
    assert pattern[:3].tolist() == [1, 0, 1]
    assert parent[3] == 0 and pattern[3] == 0  # zero padding


@pytest.mark.parametrize("on_chip", [False, True])
def test_streamed_crawl_matches_resident(rng, on_chip):
    """The HBM-overflow streaming mode (host-resident keys, per-level cw
    upload, cache-free donated advance) produces the identical crawl as
    the resident-key driver — on the CPU/XLA engine and, where a chip is
    present, on the planar Pallas engine (which exercises the in-layout
    gather -> kernel-expand -> select advance)."""
    import jax

    if on_chip and jax.devices()[0].platform != "tpu":
        pytest.skip("needs a TPU backend")
    # the module fixture pins CPU; the chip variant must override it back
    ctx = jax.default_device(
        jax.devices()[0] if on_chip else jax.devices("cpu")[0]
    )
    with ctx:
        L, n, d = 12, 300, 1
        centers = rng.integers(0, 1 << L, size=(5, d))
        pts = np.clip(
            centers[rng.integers(0, 5, size=n)]
            + rng.integers(-2, 3, size=(n, d)),
            0, (1 << L) - 1,
        )
        pts_bits = np.array(
            [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
        )
        k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
        host = lambda k: type(k)(*[np.asarray(x) for x in k])
        s0, s1 = driver.make_servers(k0, k1)
        res = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=128).run(
            nreqs=n, threshold=0.05
        )
        t0, t1 = driver.make_servers(host(k0), host(k1))
        res_s = driver.Leader(
            t0, t1, n_dims=d, data_len=L, f_max=128, stream=True
        ).run(nreqs=n, threshold=0.05)
        np.testing.assert_array_equal(res.paths, res_s.paths)
        np.testing.assert_array_equal(
            np.asarray(res.counts), np.asarray(res_s.counts)
        )
        assert res.paths.shape[0] >= 1


def test_checkpoint_resume_matches_uninterrupted(rng, tmp_path):
    """A crawl interrupted after a mid-crawl checkpoint and resumed by a
    FRESH leader (same keys, state restored from disk) produces the exact
    uninterrupted heavy hitters — including the leader-side path
    bookkeeping and liveness flags the checkpoint must carry."""
    L, d, n, ball, threshold = 8, 1, 40, 2, 0.1
    centers = rng.integers(0, 1 << L, size=(4, d))
    pts = np.clip(
        centers[rng.integers(0, 4, size=n)] + rng.integers(-1, 2, size=(n, d)),
        0, (1 << L) - 1,
    )
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    krng = np.random.default_rng(99)
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, ball, krng, engine="np")

    def as_dict(res):
        return {
            tuple(int(v) for v in r): int(c)
            for r, c in zip(res.decode_ints(), res.counts)
        }

    s0, s1 = driver.make_servers(k0, k1)
    want = as_dict(
        driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=64).run(
            nreqs=n, threshold=threshold
        )
    )
    assert want  # non-degenerate scenario

    ck = str(tmp_path / "crawl.npz")
    # first leader: run HALF the levels with periodic checkpoints, then
    # "crash" (simply stop driving it)
    s0a, s1a = driver.make_servers(k0, k1)
    lead_a = driver.Leader(s0a, s1a, n_dims=d, data_len=L, f_max=64)
    lead_a.tree_init()
    for level in range(L // 2):
        assert lead_a.run_level(level, nreqs=n, threshold=threshold) > 0
    lead_a.checkpoint(ck, L // 2 - 1)

    # resume-safety guards, checked against the on-disk file BEFORE the
    # successful resume consumes it:
    # (a) different leader shape -> refused
    s0c, s1c = driver.make_servers(k0, k1)
    lead_c = driver.Leader(s0c, s1c, n_dims=d, data_len=L, f_max=128)
    with pytest.raises(ValueError, match="checkpoint shape"):
        lead_c.restore(ck)
    # (b) same shape, DIFFERENT key batches -> refused (resuming crawl A's
    # frontier under crawl B's keys would yield silently wrong counts)
    ok0, ok1 = ibdcf.gen_l_inf_ball(
        pts_bits, ball, np.random.default_rng(7), engine="np"
    )
    s0d, s1d = driver.make_servers(ok0, ok1)
    lead_d = driver.Leader(s0d, s1d, n_dims=d, data_len=L, f_max=64)
    with pytest.raises(ValueError, match="different key batches"):
        lead_d.restore(ck)
    # (b') same RNG seed, DIFFERENT ball radius -> refused.  Root seeds
    # are identical here and the correction words diverge only at the
    # DEEP levels (the radius perturbs the interval endpoints' low bits),
    # so this pins that the fingerprint covers the full level axis.
    bk0, bk1 = ibdcf.gen_l_inf_ball(
        pts_bits, ball + 1, np.random.default_rng(99), engine="np"
    )
    np.testing.assert_array_equal(
        np.asarray(bk0.root_seed), np.asarray(k0.root_seed)
    )  # the scenario is real: only the cw planes differ
    s0g, s1g = driver.make_servers(bk0, bk1)
    lead_g = driver.Leader(s0g, s1g, n_dims=d, data_len=L, f_max=64)
    with pytest.raises(ValueError, match="different key batches"):
        lead_g.restore(ck)

    # fresh leader over the SAME keys resumes from disk; run()-written
    # checkpoints also carry (nreqs, threshold), so a mid-crawl file from
    # run() refuses a resume under a different pruning regime — exercise
    # that via a run()-produced checkpoint after this resume completes
    import os

    s0b, s1b = driver.make_servers(k0, k1)
    lead_b = driver.Leader(s0b, s1b, n_dims=d, data_len=L, f_max=64)
    got = as_dict(
        lead_b.run(nreqs=n, threshold=threshold, checkpoint_path=ck, resume=True)
    )
    assert got == want
    # (c) a COMPLETED crawl removes its checkpoint: the always-resume
    # invocation starts the next crawl fresh instead of resuming this one
    assert not os.path.exists(ck)

    # (d) param guard: a run()-written mid-crawl checkpoint refuses resume
    # under a different threshold
    s0e, s1e = driver.make_servers(k0, k1)
    lead_e = driver.Leader(s0e, s1e, n_dims=d, data_len=L, f_max=64)
    lead_e.tree_init()
    for level in range(L // 2):
        lead_e.run_level(level, nreqs=n, threshold=threshold)
    lead_e.checkpoint(ck, L // 2 - 1, nreqs=n, threshold=threshold)
    s0f, s1f = driver.make_servers(k0, k1)
    lead_f = driver.Leader(s0f, s1f, n_dims=d, data_len=L, f_max=64)
    with pytest.raises(ValueError, match="crawl params"):
        lead_f.run(
            nreqs=n, threshold=0.5, checkpoint_path=ck, resume=True
        )


@pytest.mark.parametrize("client", [2, 79])
def test_key_fingerprint_covers_every_client(client):
    """The fingerprint's client-axis checksum covers EVERY client: two
    key batches with identical roots that diverge at any single client —
    an interior one (2: unsampled by any 64-slot prefix or spread
    sample of 80) or the endpoint (79) — must fingerprint differently."""
    L, d, n = 6, 1, 80
    rng = np.random.default_rng(3)
    pts = rng.integers(0, 1 << L, size=(n, d))
    pts2 = pts.copy()
    pts2[client] = (pts2[client] + 1) % (1 << L)  # ONE client differs

    def keys(p):
        bits = np.array(
            [[bitutils.int_to_bits(L, int(v)) for v in row] for row in p]
        )
        return ibdcf.gen_l_inf_ball(
            bits, 1, np.random.default_rng(11), engine="np"
        )

    def fingerprint(k0, k1):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=64)
        return lead._key_fingerprint()

    ka = keys(pts)
    kb = keys(pts2)
    # the scenario is real: same rng seed -> identical roots, so only the
    # cw planes (at the one divergent client) can tell the batches apart
    np.testing.assert_array_equal(
        np.asarray(ka[0].root_seed), np.asarray(kb[0].root_seed)
    )
    fp_a, fp_b = fingerprint(*ka), fingerprint(*kb)
    assert not np.array_equal(fp_a, fp_b)
    # and identical batches still agree (the fingerprint is deterministic)
    assert np.array_equal(fp_a, fingerprint(*keys(pts)))


def test_checkpoint_resume_streaming_mode(rng, tmp_path):
    """Checkpoint/resume under the STREAMING crawl mode (host-resident
    keys, per-level cw upload — the mode the flagship 512-level runs use):
    a streamed crawl interrupted mid-crawl and resumed by a fresh streamed
    leader matches the uninterrupted resident-key result, with the cw
    window caches rebuilt lazily after restore."""
    L, d, n = 8, 1, 60
    centers = rng.integers(0, 1 << L, size=(4, d))
    pts = np.clip(
        centers[rng.integers(0, 4, size=n)] + rng.integers(-1, 2, size=(n, d)),
        0, (1 << L) - 1,
    )
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(
        pts_bits, 2, np.random.default_rng(5), engine="np"
    )
    host = lambda k: type(k)(*[np.asarray(x) for x in k])

    def as_dict(res):
        return {
            tuple(int(v) for v in r): int(c)
            for r, c in zip(res.decode_ints(), res.counts)
        }

    s0, s1 = driver.make_servers(k0, k1)
    want = as_dict(
        driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=64).run(
            nreqs=n, threshold=0.1
        )
    )
    assert want

    ck = str(tmp_path / "stream.npz")
    t0, t1 = driver.make_servers(host(k0), host(k1))
    lead_a = driver.Leader(
        t0, t1, n_dims=d, data_len=L, f_max=64, stream=True, stream_window=4
    )
    lead_a.tree_init()
    for level in range(5):  # crosses a stream-window boundary (4)
        assert lead_a.run_level(level, nreqs=n, threshold=0.1) > 0
    lead_a.checkpoint(ck, 4)

    u0, u1 = driver.make_servers(host(k0), host(k1))
    lead_b = driver.Leader(
        u0, u1, n_dims=d, data_len=L, f_max=64, stream=True, stream_window=4
    )
    got = as_dict(
        lead_b.run(nreqs=n, threshold=0.1, checkpoint_path=ck, resume=True)
    )
    assert got == want


def test_checkpoint_layout_conversion_roundtrip(rng):
    """_convert_layout is the involutive planar<->interleaved transpose
    pair (the engine edges of collect.advance): converting a synthetic
    interleaved state to planar and back is the identity, and the planar
    form has the documented [4, d, 2, F, N] / [d, 2, F, N] shapes."""
    from fuzzyheavyhitters_tpu.ops.ibdcf import EvalState

    F, N, d = 3, 7, 2
    st = EvalState(
        seed=rng.integers(0, 2**32, size=(F, N, d, 2, 4), dtype=np.uint32),
        bit=rng.integers(0, 2, size=(F, N, d, 2)).astype(bool),
        y_bit=rng.integers(0, 2, size=(F, N, d, 2)).astype(bool),
    )
    planar = driver._convert_layout(st, from_planar=False)
    assert planar.seed.shape == (4, d, 2, F, N)
    assert planar.bit.shape == (d, 2, F, N)
    back = driver._convert_layout(planar, from_planar=True)
    for a, b in zip(st, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_covid_crawl_end_to_end(rng, tmp_path):
    """COVID workload driven end to end: the f64-bit domain (data_len=64,
    n_dims=2, ref: sample_covid_data.rs:32-35) through the full driver
    crawl, checked against a direct interval oracle in u64 bit-space.
    Jitterless sampling makes same-county clients bit-identical, so the
    heavy hitters are each hot county's f64 pattern plus its L∞-ball
    neighbourhood in ulp space."""
    from fuzzyheavyhitters_tpu.workloads import covid

    csv_path = tmp_path / "county_centroids.csv"
    csv_path.write_text(
        "fips_code,latitude,longitude\n"
        "01001,32.53,-86.64\n"
        "06037,34.05,-118.24\n"
        "48453,30.26,-97.74\n"
    )
    n, L, ball = 24, 64, 1
    pts = covid.sample_covid_locations(
        str(tmp_path / "absent.csv"), str(csv_path), n,
        fuzz_factor=None, seed=3,
    )
    assert pts.shape == (n, 2, L)
    k0, k1 = ibdcf.gen_l_inf_ball(pts, ball, rng, engine="np")
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(
        s0, s1, n_dims=2, data_len=L, f_max=64, min_bucket=64
    )
    threshold = 0.2  # thresh = max(1, 4)
    res = lead.run(nreqs=n, threshold=threshold)
    got = {
        tuple(int(v) for v in res.decode_ints()[i]): int(res.counts[i])
        for i in range(res.paths.shape[0])
    }

    # oracle: u64 interpretation of the f64 bit patterns; ball membership
    # is a saturating per-dim interval test (utils/bits semantics)
    ints = np.zeros((n, 2), np.uint64)
    for i in range(n):
        for d_ in range(2):
            v = 0
            for b in pts[i, d_]:
                v = (v << 1) | int(b)
            ints[i, d_] = v
    lo = np.maximum(ints, ball) - ball  # saturating p - ball
    hi = ints + ball
    hi[hi < ints] = np.uint64(2**64 - 1)  # saturating p + ball
    thresh = max(1, int(threshold * n))
    cand = set()
    for i in range(n):
        for dx in range(-ball, ball + 1):
            for dy in range(-ball, ball + 1):
                x = int(ints[i, 0]) + dx
                y = int(ints[i, 1]) + dy
                if 0 <= x < 2**64 and 0 <= y < 2**64:
                    cand.add((x, y))
    want = {}
    for x, y in cand:
        c = int(np.sum((lo[:, 0] <= x) & (x <= hi[:, 0])
                       & (lo[:, 1] <= y) & (y <= hi[:, 1])))
        if c >= thresh:
            want[(x, y)] = c
    assert got == want
    assert len(got) >= 3  # every hot county survives with its ulp ball
    # decoded leaves round-trip to the sampled coordinates
    lats = {round(covid.bool_vec_to_f64(pts[i, 0]), 2) for i in range(n)}
    got_lats = {
        round(covid.bool_vec_to_f64(bitutils.int_to_bits(64, x)), 2)
        for (x, _) in got
    }
    assert got_lats <= {l for l in lats} | {32.53, 34.05, 30.26}
