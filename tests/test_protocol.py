"""End-to-end protocol tests: in-process leader + two colocated servers vs a
brute-force heavy-hitters oracle (the integration-test shape of the
reference's collect_test.rs: known multiset in, exact counts out —
SURVEY.md §4)."""

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import collect, driver
from fuzzyheavyhitters_tpu.utils import bits as bitutils


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Colocated-driver e2e on the CPU backend: the same flow runs against
    the real device in tests/test_rpc.py; duplicating it on the tunnel
    costs ~10 s per compile (see conftest)."""
    yield


def brute_force_hitters(pts, ball, L, thresh):
    """All leaves x where #{clients whose saturated L∞ ball contains x} >=
    thresh, with exact counts.  pts: int[N, d]."""
    pts = np.asarray(pts)
    n, d = pts.shape
    lo = np.clip(pts - ball, 0, (1 << L) - 1)
    hi = np.clip(pts + ball, 0, (1 << L) - 1)
    out = {}
    grid = np.stack(
        np.meshgrid(*[np.arange(1 << L)] * d, indexing="ij"), axis=-1
    ).reshape(-1, d)
    for x in grid:
        c = int(np.sum(np.all((x >= lo) & (x <= hi), axis=1)))
        if c >= thresh:
            out[tuple(int(v) for v in x)] = c
    return out


def run_protocol(pts, ball, L, threshold, f_max=128):
    pts = np.asarray(pts)
    n, d = pts.shape
    rng = np.random.default_rng(99)
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, ball, rng)
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=f_max)
    res = lead.run(nreqs=n, threshold=threshold)
    got = {}
    for i in range(res.paths.shape[0]):
        key = tuple(int(v) for v in res.decode_ints()[i])
        got[key] = int(res.counts[i])
    return got


@pytest.mark.parametrize("d,L,ball", [(1, 6, 3), (2, 5, 2)])
def test_heavy_hitters_match_brute_force(rng, d, L, ball):
    n = 40
    # clustered points so some leaves clear the threshold
    centers = rng.integers(0, 1 << L, size=(4, d))
    pts = centers[rng.integers(0, 4, size=n)] + rng.integers(-1, 2, size=(n, d))
    pts = np.clip(pts, 0, (1 << L) - 1)
    threshold = 0.1  # thresh = max(1, 4)
    got = run_protocol(pts, ball, L, threshold, f_max=512 if d == 2 else 128)
    want = brute_force_hitters(pts, ball, L, max(1, int(threshold * n)))
    assert got == want


def test_no_survivors_early_exit(rng):
    pts = np.array([[3], [10], [40]])
    got = run_protocol(pts, 1, 6, threshold=0.99)  # thresh=2, balls disjoint
    assert got == {}


def test_single_client_threshold_one(rng):
    """threshold*nreqs < 1 floors to 1 (ref: leader.rs:193)."""
    pts = np.array([[17]])
    got = run_protocol(pts, 2, 6, threshold=0.0001)
    want = brute_force_hitters(pts, 2, 6, 1)
    assert got == want


def test_liveness_flag_gates_counts(rng):
    """Disabling a client's liveness flag removes it from every count
    (ref: collect.rs:495 — the hook the sketch verification uses)."""
    pts = np.array([[8], [8], [8], [50]])
    L, ball = 6, 1
    pts_bits = np.array([[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts])
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, ball, np.random.default_rng(5))
    s0, s1 = driver.make_servers(k0, k1)
    s0.alive_keys[0] = False
    s1.alive_keys[0] = False
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=128)
    res = lead.run(nreqs=4, threshold=0.5)  # thresh=2
    got = {tuple(r): c for r, c in zip(res.decode_ints(), res.counts)}
    # only two live clients at 8 remain above threshold
    assert set(got) == {(7,), (8,), (9,)}
    assert all(c == 2 for c in got.values())


def test_f_max_overflow_raises(rng):
    pts = np.tile(np.arange(0, 64, 2)[:, None], (1, 1))  # 32 spread clients
    with pytest.raises(ValueError, match="f_max"):
        run_protocol(pts, 3, 6, threshold=0.001, f_max=4)


def test_pattern_masks_layout():
    m = collect.pattern_masks(2)
    assert m.shape == (4,)
    # pattern 0: dirs (0,0) -> bits at (j*4 + s*2 + 0)
    assert m[0] == sum(1 << (j * 4 + s * 2) for j in range(2) for s in range(2))
    # pattern 3: dirs (1,1)
    assert m[3] == sum(1 << (j * 4 + s * 2 + 1) for j in range(2) for s in range(2))


def test_bucket_for_and_compact_survivors():
    """Bucketed-frontier helpers: power-of-2 sizing with the f_max cap and
    the min_bucket pin, and compact_survivors padding to the bucket."""
    assert [collect.bucket_for(n, 64) for n in (0, 1, 2, 3, 4, 5, 33, 64)] == [
        1, 1, 2, 4, 4, 8, 64, 64,
    ]
    assert collect.bucket_for(3, 64, min_bucket=16) == 16
    assert collect.bucket_for(60, 64, min_bucket=16) == 64
    with pytest.raises(ValueError, match="f_max"):
        collect.bucket_for(65, 64)
    keep = np.zeros((4, 2), bool)
    keep[0, 1] = keep[2, 0] = keep[3, 1] = True
    parent, pattern, n_alive = collect.compact_survivors(keep, 64)
    assert n_alive == 3 and parent.shape == (4,)  # padded to bucket 4
    assert parent[:3].tolist() == [0, 2, 3]
    assert pattern[:3].tolist() == [1, 0, 1]
    assert parent[3] == 0 and pattern[3] == 0  # zero padding
