"""Fault-tolerance tests: retry/deadline policy, the chaos proxy, the
reconnecting client's idempotent replay, and end-to-end crawl recovery.

The e2e scenarios are the acceptance surface of the resilience layer: a
SECURE (GC+OT) crawl severed mid-flight on the leader↔server control
link AND a server killed+restarted at a checkpoint boundary completes
with heavy hitters bit-identical to a fault-free run, with no verb
double-applied (the dedup-cache hit counter proves replays were answered
from cache).  Shapes mirror tests/test_secure.py (L=5, d=1, n=12) so the
crawl kernels compile once across both files.
"""

import asyncio

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.resilience import policy as respolicy
from fuzzyheavyhitters_tpu.resilience.chaos import ChaosProxy, parse_faults
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 21631


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the resilience layer under test is host-side glue;
    its device programs are the same crawl kernels test_secure.py
    compiles (shapes harmonized)."""
    yield


# ---------------------------------------------------------------------------
# policy: backoff, deadlines, classification
# ---------------------------------------------------------------------------


def test_retry_policy_full_jitter_envelope():
    pol = respolicy.RetryPolicy(
        base_s=0.1, cap_s=1.0, factor=2.0, attempts=6, rand=lambda: 1.0
    )
    # undithered envelope: base·2^k capped
    assert [pol.delay(k) for k in range(5)] == [0.1, 0.2, 0.4, 0.8, 1.0]
    half = respolicy.RetryPolicy(
        base_s=0.1, cap_s=1.0, factor=2.0, attempts=6, rand=lambda: 0.5
    )
    assert half.delay(3) == pytest.approx(0.4)  # jitter scales the envelope
    assert list(pol.delays()) and len(list(pol.delays())) == 5


def test_deadline_remaining_and_expiry():
    d = respolicy.Deadline(100.0)
    rem = d.remaining()
    assert 0 < rem <= 100.0 and not d.expired()
    assert respolicy.Deadline(None).remaining() is None
    assert not respolicy.Deadline(None).expired()
    z = respolicy.Deadline(0.0)
    assert z.expired() and z.remaining() == 0.0


def test_deadline_wait_for_times_out():
    async def run():
        d = respolicy.Deadline(0.05)
        with pytest.raises(asyncio.TimeoutError):
            await d.wait_for(asyncio.sleep(5))

    asyncio.run(run())


def test_is_transient_classification():
    assert respolicy.is_transient(ConnectionResetError())
    assert respolicy.is_transient(asyncio.IncompleteReadError(b"", 8))
    assert respolicy.is_transient(TimeoutError())
    assert respolicy.is_transient(OSError(111, "refused"))
    assert not respolicy.is_transient(ValueError("bug"))
    assert not respolicy.is_transient(RuntimeError("server error on x"))
    assert not respolicy.is_transient(asyncio.CancelledError())


def test_retry_async_retries_transient_then_succeeds():
    calls = []

    async def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("blip")
        return "ok"

    pol = respolicy.RetryPolicy(base_s=0.001, attempts=5, rand=lambda: 0.0)

    async def run():
        return await respolicy.retry_async(flaky, pol, what="t")

    assert asyncio.run(run()) == "ok"
    assert len(calls) == 3


def test_retry_async_fatal_and_exhaustion():
    async def fatal():
        raise ValueError("bug")

    async def always_down():
        raise ConnectionResetError("down")

    pol = respolicy.RetryPolicy(base_s=0.001, attempts=3, rand=lambda: 0.0)

    async def run_fatal():
        await respolicy.retry_async(fatal, pol)

    async def run_down():
        await respolicy.retry_async(always_down, pol)

    with pytest.raises(ValueError):
        asyncio.run(run_fatal())
    with pytest.raises(ConnectionResetError):
        asyncio.run(run_down())


def test_retry_async_respects_shared_deadline():
    calls = []

    async def always_down():
        calls.append(1)
        raise ConnectionResetError("down")

    pol = respolicy.RetryPolicy(base_s=0.05, attempts=100, rand=lambda: 1.0)

    async def run():
        await respolicy.retry_async(
            always_down, pol, deadline=respolicy.Deadline(0.12)
        )

    with pytest.raises(ConnectionResetError):
        asyncio.run(run())
    assert len(calls) < 10  # the wall clock, not attempts, stopped it


def test_verb_budgets_lookup():
    b = respolicy.VerbBudgets()
    assert b.budget("tree_crawl") == b.default_s
    assert b.budget("reset") == 300.0
    assert b.deadline("reset").budget_s == 300.0


# ---------------------------------------------------------------------------
# chaos: fault-spec grammar + proxy behavior
# ---------------------------------------------------------------------------


def test_parse_faults_grammar():
    faults = parse_faults(
        "ctl0:sever@msg=12;plane:delay@msg=3,ms=50;"
        "ctl1:blackhole@msg=2,count=4,dir=s2c"
    )
    assert [f.action for f in faults] == ["sever", "delay", "blackhole"]
    assert faults[0].link == "ctl0" and faults[0].at_msg == 12
    assert faults[1].ms == 50 and faults[1].direction == "c2s"
    assert faults[2].count == 4 and faults[2].direction == "s2c"
    assert parse_faults("") == [] and parse_faults(None) == []


@pytest.mark.parametrize(
    "bad",
    [
        "ctl0:sever",  # no trigger
        "ctl0:sever@ms=5",  # missing msg=
        "ctl0:explode@msg=1",  # unknown action
        "ctl0:sever@msg=0",  # 1-indexed
        "ctl0:sever@msg=1,dir=sideways",  # unknown direction
        "justgarbage",
    ],
)
def test_parse_faults_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_faults(bad)


def _echo_server_port(offset):
    return BASE_PORT + 80 + offset


def test_chaos_proxy_forwards_delays_blackholes_and_severs():
    """One framed echo server behind a proxy: clean forwarding first,
    then a blackholed frame (dropped, connection alive), then a sever —
    and the listener survives the sever so a redial works."""
    port_s, port_p = _echo_server_port(0), _echo_server_port(1)

    async def run():
        async def echo(reader, writer):
            try:
                while True:
                    obj = await rpc._recv(reader)
                    await rpc._send(writer, ("echo", obj))
            except (asyncio.IncompleteReadError, ConnectionResetError):
                writer.close()

        srv = await asyncio.start_server(echo, "127.0.0.1", port_s)
        faults = parse_faults("t:blackhole@msg=2;t:sever@msg=4")
        px = await ChaosProxy(
            "127.0.0.1", port_p, "127.0.0.1", port_s, faults, link="t"
        ).start()

        r, w = await asyncio.open_connection("127.0.0.1", port_p)
        await rpc._send(w, "one")  # frame 1: forwarded
        assert await rpc._recv(r) == ("echo", "one")
        await rpc._send(w, "two")  # frame 2: black-holed silently
        await rpc._send(w, "three")  # frame 3: forwarded (echo of three)
        assert await rpc._recv(r) == ("echo", "three")
        await rpc._send(w, "four")  # frame 4: sever
        with pytest.raises((asyncio.IncompleteReadError, ConnectionResetError)):
            await rpc._recv(r)
        # the listener survives: a fresh dial works end-to-end
        r2, w2 = await asyncio.open_connection("127.0.0.1", port_p)
        await rpc._send(w2, "again")
        assert await rpc._recv(r2) == ("echo", "again")
        assert ("blackhole", "c2s", 2) in px.fired
        assert ("sever", "c2s", 4) in px.fired
        w2.close()
        await px.stop()
        srv.close()
        await srv.wait_closed()

    asyncio.run(run())


def test_chaos_proxy_truncate_tears_the_frame():
    port_s, port_p = _echo_server_port(2), _echo_server_port(3)

    async def run():
        got = []

        async def sink(reader, writer):
            try:
                got.append(await rpc._recv(reader))
            except (asyncio.IncompleteReadError, ConnectionResetError) as e:
                got.append(("torn", type(e).__name__))

        srv = await asyncio.start_server(sink, "127.0.0.1", port_s)
        px = await ChaosProxy(
            "127.0.0.1", port_p, "127.0.0.1", port_s,
            parse_faults("t:truncate@msg=1"), link="t",
        ).start()
        r, w = await asyncio.open_connection("127.0.0.1", port_p)
        await rpc._send(w, {"payload": list(range(100))})
        await asyncio.sleep(0.2)
        assert got and got[0][0] == "torn"
        await px.stop()
        srv.close()
        await srv.wait_closed()

    asyncio.run(run())


def test_chaos_proxy_delay_defers_the_frame():
    port_s, port_p = _echo_server_port(4), _echo_server_port(5)

    async def run():
        async def echo(reader, writer):
            while True:
                await rpc._send(writer, await rpc._recv(reader))

        srv = await asyncio.start_server(echo, "127.0.0.1", port_s)
        px = await ChaosProxy(
            "127.0.0.1", port_p, "127.0.0.1", port_s,
            parse_faults("t:delay@msg=1,ms=150"), link="t",
        ).start()
        r, w = await asyncio.open_connection("127.0.0.1", port_p)
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        await rpc._send(w, "slow")
        assert await rpc._recv(r) == "slow"
        assert loop.time() - t0 >= 0.14
        await px.stop()
        srv.close()
        await srv.wait_closed()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# protocol-level resilience: sessions, dedup, budgets
# ---------------------------------------------------------------------------


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=5,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=32,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, L, n):
    pts = np.concatenate(
        [np.full(n - 4, 11), rng.integers(0, 1 << L, size=4)]
    )[:, None]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


async def _start_servers(cfg, port_base, ckpt_dir=None):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port_base + 10, "127.0.0.1", port_base + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port_base, "127.0.0.1", port_base + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


def test_session_replay_answers_from_cache():
    """The idempotent-replay contract at the frame level: resending the
    SAME (session, req_id) does not re-execute the verb — the second
    response comes from the dedup cache (stateful add_keys appends once)."""
    port = BASE_PORT

    async def run():
        cfg = _cfg(port)
        s0, s1 = await _start_servers(cfg, port)
        r, w = await asyncio.open_connection("127.0.0.1", port)
        await rpc._send(w, (1, "__hello__", {"session": "t-sess", "epoch": 1}))
        hello = await rpc._recv(r)
        assert hello[0] == 1 and "boot_id" in hello[1]
        await rpc._send(w, (2, "reset", {}))
        assert (await rpc._recv(r))[1] is True
        k0, _ = _client_keys(np.random.default_rng(7), 5, 6)
        chunk = tuple(np.asarray(x) for x in k0)
        frame = (3, "add_keys", {"keys": chunk})
        await rpc._send(w, frame)
        assert (await rpc._recv(r))[1] is True
        await rpc._send(w, frame)  # replay: same req_id, same session
        assert (await rpc._recv(r))[1] is True
        assert len(s0.keys_parts) == 1  # applied ONCE
        await rpc._send(w, (4, "status", {}))
        st = (await rpc._recv(r))[1]
        assert st["dedup_hits"] == 1
        # a replayed ERROR response is also served from cache
        await rpc._send(w, (5, "tree_restore", {"level": 0}))
        e1 = (await rpc._recv(r))[1]
        await rpc._send(w, (5, "tree_restore", {"level": 0}))
        e2 = (await rpc._recv(r))[1]
        assert "__error__" in e1 and e1 == e2
        w.close()
        await s0.aclose()
        await s1.aclose()

    asyncio.run(run())


def test_client_reconnects_and_replays_across_sever():
    """Sever the response direction (verb EXECUTED, response lost): the
    client redials through the same proxy and replays; the server answers
    from the dedup cache — visible as a dedup hit, and reset ran once."""
    port, pxport = BASE_PORT + 100, BASE_PORT + 101

    async def run():
        cfg = _cfg(port)
        s0, s1 = await _start_servers(cfg, port)
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:sever@msg=2,dir=s2c"), link="ctl0",
        ).start()
        c0 = await rpc.CollectorClient.connect("127.0.0.1", pxport)
        # frame 1 s2c = hello response; frame 2 s2c = reset response: the
        # reset executes, its response is severed, the client replays
        assert await c0.call("reset") is True
        st = await c0.call("status")
        assert c0.epoch == 2  # reconnected exactly once
        assert st["dedup_hits"] == 1  # the replayed reset hit the cache
        await px.stop()
        await c0.aclose()
        await s0.aclose()
        await s1.aclose()

    asyncio.run(run())


def test_reset_clears_stale_checkpoints(tmp_path):
    """A new collection must not be resumable from the previous one's
    checkpoint files: reset wipes this server's level-stamped blobs
    (regression: the keep=0 prune path once sliced to the empty list)."""
    s = rpc.CollectorServer(0, _cfg(BASE_PORT + 300), ckpt_dir=str(tmp_path))
    for lvl in (1, 3):
        (tmp_path / f"fhh_server0_l{lvl}.npz").write_bytes(b"x")
    (tmp_path / "fhh_server1_l1.npz").write_bytes(b"x")  # peer's: untouched
    asyncio.run(s.reset({}))
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["fhh_server1_l1.npz"]


def test_session_cache_is_byte_bounded():
    """Bulky responses must not pin unbounded memory: the dedup cache
    evicts by BYTES as well as count, but always keeps the newest entry
    (its own replay needs it)."""
    sess = rpc._Session()
    big = np.zeros(rpc._SESSION_CACHE_BYTES // 4, np.uint8)  # ~32 MB each
    for i in range(1, 8):
        sess.put(i, {"shares": big})
    assert len(sess.cache) < 7  # byte bound evicted old entries
    assert 7 in sess.cache  # newest always survives
    assert sess.bytes_total <= rpc._SESSION_CACHE_BYTES + big.nbytes
    one = rpc._Session()
    one.put(1, np.zeros(rpc._SESSION_CACHE_BYTES + 1024, np.uint8))
    assert 1 in one.cache  # over-cap singleton survives


def test_run_supervised_malicious_requires_sketch_material(rng):
    """Malicious mode IS supervisable now (the challenge ratchet), but
    only with the sketch key batches along — without them the crawl
    would silently run semi-honest, so the refusal comes before any
    server is touched."""
    cfg = _cfg(BASE_PORT + 310, malicious=True)
    k0, k1 = _client_keys(rng, 5, 6)

    async def run():
        from types import SimpleNamespace

        stub = SimpleNamespace()  # never dialed: the refusal comes first
        lead = RpcLeader(cfg, stub, SimpleNamespace())
        await lead.run_supervised(6, k0, k1)

    with pytest.raises(ValueError, match="malicious"):
        asyncio.run(run())


def test_blackhole_exhausts_verb_budget_loudly():
    """Frames silently dropped (no FIN/RST): the per-verb wall-clock
    budget converts the would-be infinite hang into TimeoutError."""
    port, pxport = BASE_PORT + 120, BASE_PORT + 121

    async def run():
        cfg = _cfg(port)
        s0, s1 = await _start_servers(cfg, port)
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:blackhole@msg=2,count=99"), link="ctl0",
        ).start()
        c0 = await rpc.CollectorClient.connect(
            "127.0.0.1", pxport,
            budgets=respolicy.VerbBudgets(default_s=0.6, per_verb={}),
        )
        with pytest.raises(TimeoutError):
            await c0.call("reset")
        await px.stop()
        await c0.aclose()
        await s0.aclose()
        await s1.aclose()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# e2e recovery: the acceptance scenario
# ---------------------------------------------------------------------------


async def _crawl_with_chaos(cfg, k0, k1, nreqs, *, ckpt_dir, ctl0_proxy=None,
                            assassin=None, checkpoint_every=2,
                            sk0=None, sk1=None, budgets=None):
    """One supervised crawl with optional chaos: a proxy on the
    leader↔server0 control link and/or an assassin coroutine (given the
    live servers dict + leader) that kills/restarts servers mid-crawl.
    ``sk0``/``sk1`` ride along for malicious (sketch) mode; ``budgets``
    overrides the clients' per-verb wall-clock budgets.
    Returns (result, leader, (c0, c1), live-servers dict)."""
    host0, p0 = cfg.server0.rsplit(":", 1)
    host1, p1 = cfg.server1.rsplit(":", 1)
    p0, p1 = int(p0), int(p1)
    live = {}
    live["s0"], live["s1"] = await _start_servers(cfg, p0, ckpt_dir=ckpt_dir)
    dial0 = (host0, p0)
    if ctl0_proxy is not None:
        dial0 = (ctl0_proxy.listen_host, ctl0_proxy.listen_port)
    c0 = await rpc.CollectorClient.connect(*dial0, budgets=budgets)
    c1 = await rpc.CollectorClient.connect(host1, p1, budgets=budgets)
    lead = RpcLeader(cfg, c0, c1)
    kill_task = (
        asyncio.create_task(assassin(live, lead))
        if assassin is not None
        else None
    )
    res = await lead.run_supervised(
        nreqs, k0, k1, sk0, sk1, checkpoint_every=checkpoint_every
    )
    if kill_task is not None:
        await kill_task
    return res, lead, (c0, c1), live


async def _teardown(clients, live, *proxies):
    for px in proxies:
        await px.stop()
    for c in clients:
        await c.aclose()
    for s in live.values():
        await s.aclose()


def _hitters(res):
    return {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }


def _kill_and_restart_s1_at_first_checkpoint(cfg, port, ck):
    """Assassin: the moment the leader banks its first checkpoint
    (level 1 with checkpoint_every=2), kill server 1 — every-loop-tick
    polling on the leader's own counter, so the kill always lands
    mid-crawl — and bring a FRESH CollectorServer up on the same ports
    shortly after (the in-process equivalent of process death: all
    in-memory protocol state gone, checkpoint files survive)."""

    async def assassin(live, lead):
        while lead.obs.counter_value("crawl_checkpoints") < 1:
            await asyncio.sleep(0)
        await live["s1"].aclose()
        await asyncio.sleep(0.3)
        live["s1"] = rpc.CollectorServer(1, cfg, ckpt_dir=str(ck))
        await live["s1"].start(
            "127.0.0.1", port + 10, "127.0.0.1", port + 11
        )

    return assassin


@pytest.mark.parametrize("secure", [False, True], ids=["trusted", "secure"])
def test_e2e_chaos_recovery_bit_identical(rng, tmp_path, secure):
    """THE acceptance scenario: a crawl whose leader↔server0 control link
    is severed mid-crawl (response direction: the verb executed, its
    response was lost — forcing a true idempotent replay) AND whose
    server 1 is killed and restarted at a checkpoint boundary completes
    bit-identical to a fault-free run, with no verb double-applied (the
    dedup-hit counter proves the replay came from cache; set equality
    proves nothing applied twice).  The secure variant runs the full
    GC+OT data plane and re-keys it on recovery (fresh base-OT via
    _plane_handshake)."""
    L, n = 5, 12
    port = BASE_PORT + (140 if secure else 180)
    pxport = port + 20
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port, secure_exchange=secure)
    ck = tmp_path / "ckpt"
    ck_ff = tmp_path / "ckpt_ff"
    ck.mkdir(), ck_ff.mkdir()

    async def faulty():
        # sever the s2c (response) direction mid-crawl: the severed verb
        # has already executed server-side, so the post-reconnect resend
        # MUST be answered from the dedup cache, not re-applied
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:sever@msg=9,dir=s2c"), link="ctl0",
        ).start()
        res, lead, (c0, c1), live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck), ctl0_proxy=px,
            assassin=_kill_and_restart_s1_at_first_checkpoint(cfg, port, ck),
        )
        st0 = await c0.call("status")
        epochs = (c0.epoch, c1.epoch)
        await _teardown((c0, c1), live, px)
        return res, lead, st0, epochs

    async def fault_free():
        res, lead, (c0, c1), live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck_ff)
        )
        await _teardown((c0, c1), live)
        return res

    res_ff = asyncio.run(fault_free())
    res, lead, st0, epochs = asyncio.run(faulty())

    # bit-identical results: faulty == fault-free == colocated oracle
    want_res = driver.Leader(
        *driver.make_servers(k0, k1), n_dims=1, data_len=L, f_max=cfg.f_max
    ).run(nreqs=n, threshold=cfg.threshold)
    assert _hitters(res) == _hitters(res_ff) == _hitters(want_res)
    assert _hitters(res)  # non-empty: the stacked clients clear threshold
    np.testing.assert_array_equal(res.paths, res_ff.paths)
    np.testing.assert_array_equal(res.counts, res_ff.counts)

    # the faults actually happened AND were survived:
    assert epochs[0] >= 2  # leader↔s0 reconnected across the sever
    assert st0["dedup_hits"] >= 1  # replayed verb answered from cache
    assert lead.obs.counter_value("recoveries") >= 1  # s1 restart recovered


def test_supervised_without_ckpt_dir_degrades_gracefully(rng, tmp_path):
    """Servers without FHH_CKPT_DIR refuse tree_checkpoint; supervision
    must degrade (checkpointing disabled after one warn) and still
    complete the crawl."""
    L, n = 5, 12
    port = BASE_PORT + 220
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port)

    async def run():
        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=None
        )
        await _teardown(clients, live)
        return res, lead

    res, lead = asyncio.run(run())
    want_res = driver.Leader(
        *driver.make_servers(k0, k1), n_dims=1, data_len=L, f_max=cfg.f_max
    ).run(nreqs=n, threshold=cfg.threshold)
    assert _hitters(res) == _hitters(want_res)
    assert lead.obs.counter_value("crawl_checkpoints") == 0


# ---------------------------------------------------------------------------
# challenge ratchet: unit semantics + restartable sketch crawls
# ---------------------------------------------------------------------------


def test_ratchet_seed_deterministic_and_sensitive():
    """The restartability contract: identical (root, level, transcript)
    -> identical challenge; ANY divergence -> a different challenge.
    Bucket padding must not perturb the transcript (min_bucket varies
    between hosts but the crawl is the same crawl)."""
    from fuzzyheavyhitters_tpu.protocol import sketch as sketchmod

    root = np.arange(4, dtype=np.uint32)
    d0 = sketchmod.transcript_init()
    a = sketchmod.ratchet_seed(root, 3, d0)
    assert a.dtype == np.uint32 and a.shape == (4,)
    np.testing.assert_array_equal(a, sketchmod.ratchet_seed(root, 3, d0))
    assert not np.array_equal(a, sketchmod.ratchet_seed(root, 4, d0))
    assert not np.array_equal(
        a, sketchmod.ratchet_seed(root ^ np.uint32(1), 3, d0)
    )
    parent = np.array([0, 0], np.int32)
    bits = np.array([[True], [False]])
    d1 = sketchmod.transcript_absorb(d0, 0, parent, bits, 1)
    assert d1 != d0
    assert not np.array_equal(a, sketchmod.ratchet_seed(root, 3, d1))
    # only the REAL survivor entries are absorbed: padding is invisible
    padded = sketchmod.transcript_absorb(
        d0, 0, np.array([0, 99], np.int32),
        np.array([[True], [True]]), 1,
    )
    assert padded == d1


def test_e2e_sketch_recovery_bit_identical(rng, tmp_path):
    """THE sketch acceptance scenario: a MALICIOUS-mode crawl whose
    leader↔server0 control link is severed mid-crawl AND whose server 1
    is killed and restarted at a checkpoint boundary completes
    bit-identically to a fault-free malicious run — cheater exclusion
    included (the ratchet replays each recovered level's challenge
    exactly, so re-opened Beaver slabs reveal nothing new and honest
    clients' liveness flags land identically), with the recovery
    distinguishable in the run report."""
    from fuzzyheavyhitters_tpu.obs import report as obsreport
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
    from fuzzyheavyhitters_tpu.protocol import sketch as sketchmod

    L, n = 5, 12
    port = BASE_PORT + 340
    pxport = port + 20
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketchmod.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)
    # client 3 forges its level-2 payload (handed identically to both):
    # its exclusion must SURVIVE the recovery re-runs
    bad = np.asarray(sk0.key.cw_val).copy()
    bad[3, 0, 2, 0] = (int(bad[3, 0, 2, 0]) + 1) % FE62.P
    import jax.numpy as jnp

    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))
    cfg = _cfg(
        port, malicious=True, threshold=0.5, addkey_batch_size=12
    )
    ck, ck_ff = tmp_path / "ckpt", tmp_path / "ckpt_ff"
    ck.mkdir(), ck_ff.mkdir()

    async def faulty():
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:sever@msg=9,dir=s2c"), link="ctl0",
        ).start()
        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck), ctl0_proxy=px,
            assassin=_kill_and_restart_s1_at_first_checkpoint(cfg, port, ck),
            sk0=sk0, sk1=sk1,
        )
        alive = live["s0"].alive_keys.copy()
        rep = obsreport.run_report(
            [lead.obs, live["s0"].obs, live["s1"].obs]
        )
        epochs = clients[0].epoch
        await _teardown(clients, live, px)
        return res, lead, alive, rep, epochs

    async def fault_free():
        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck_ff), sk0=sk0, sk1=sk1
        )
        alive = live["s0"].alive_keys.copy()
        rep = obsreport.run_report(
            [lead.obs, live["s0"].obs, live["s1"].obs]
        )
        await _teardown(clients, live)
        return res, alive, rep

    res_ff, alive_ff, rep_ff = asyncio.run(fault_free())
    res, lead, alive, rep, epochs = asyncio.run(faulty())

    # bit-identical results AND liveness: the cheater (client 3) stays
    # excluded, every honest client stays alive, counts match exactly
    want_alive = np.ones(n, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(alive, want_alive)
    np.testing.assert_array_equal(alive_ff, want_alive)
    assert _hitters(res) == _hitters(res_ff) == {(10,): 7, (11,): 7, (12,): 7}
    np.testing.assert_array_equal(res.paths, res_ff.paths)
    np.testing.assert_array_equal(res.counts, res_ff.counts)

    # the faults happened, were survived, and are visible in the report
    assert epochs >= 2  # leader↔s0 reconnected across the sever
    assert lead.obs.counter_value("recoveries") >= 1
    assert rep["recovery"]["count"] >= 1
    assert rep["recovery"]["levels_rerun"] >= 1
    assert rep["recovery"]["dedup_hits"] >= 1
    assert rep["recovery"]["dedup_hit_rate"] > 0
    assert rep_ff["recovery"]["count"] == 0  # distinguishable


def test_sketch_recover_refuses_scratch_restart(rng):
    """Stash-less recovery in sketch mode must refuse BEFORE touching any
    server: re-uploading the same Beaver triple shares under a freshly
    coin-flipped ratchet root opens the same slabs under two challenges —
    the <r - r', x> leak the ratchet exists to prevent."""
    from types import SimpleNamespace

    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
    from fuzzyheavyhitters_tpu.protocol import sketch as sketchmod

    cfg = _cfg(BASE_PORT + 520, malicious=True)
    k0, k1 = _client_keys(rng, 5, 6)
    seeds = rng.integers(0, 2**32, size=(6, 1, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketchmod.gen(
        seeds, rng.integers(0, 2, size=(6, 1, 5)).astype(bool),
        FE62, F255, cseed,
    )
    lead = RpcLeader(cfg, SimpleNamespace(), SimpleNamespace())  # no dials

    async def run():
        await lead._recover(k0, k1, sk0, sk1, None)

    with pytest.raises(ValueError, match="fresh sketch keys"):
        asyncio.run(run())


def test_sketch_early_fault_recovers_via_init_checkpoint(rng, tmp_path):
    """A sketch-mode fault BEFORE any level checkpoint must roll back to
    the init (level -1) checkpoint — committed root, empty transcript —
    and replay from level 0 bit-identically, never restart from scratch.
    checkpoint_every=5 at L=5 means the init checkpoint is the ONLY one,
    so the restore path is deterministic."""
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
    from fuzzyheavyhitters_tpu.protocol import sketch as sketchmod

    L, n = 5, 12
    port = BASE_PORT + 540
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketchmod.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)
    cfg = _cfg(port, malicious=True, threshold=0.5, addkey_batch_size=12)
    ck = tmp_path / "ckpt"
    ck.mkdir()

    def kill_after_level0(cfg, port, ck):
        async def assassin(live, lead):
            # level 0 done (paths grew) but no level checkpoint exists:
            # the only rollback point is the init (-1) blob
            while lead.paths is None or lead.paths.shape[-1] < 1:
                await asyncio.sleep(0)
            await live["s1"].aclose()
            await asyncio.sleep(0.3)
            live["s1"] = rpc.CollectorServer(1, cfg, ckpt_dir=str(ck))
            await live["s1"].start(
                "127.0.0.1", port + 10, "127.0.0.1", port + 11
            )

        return assassin

    async def run():
        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck), sk0=sk0, sk1=sk1,
            checkpoint_every=5,
            assassin=kill_after_level0(cfg, port, ck),
        )
        alive = live["s0"].alive_keys.copy()
        await _teardown(clients, live)
        return res, lead, alive

    res, lead, alive = asyncio.run(run())
    assert (ck / "fhh_server0_l-1.npz").exists()  # the init checkpoint
    assert lead.obs.counter_value("recoveries") >= 1
    # honest batch (8 clients at 11, nobody forged): all 8 count
    assert _hitters(res) == {(10,): 8, (11,): 8, (12,): 8}
    assert alive.all()  # nobody excluded by the replayed challenges


# ---------------------------------------------------------------------------
# sharded mid-level retry
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("secure", [False, True], ids=["trusted", "secure"])
def test_sharded_crawl_matches_unsharded(rng, secure):
    """crawl_shard_nodes splits every level into per-span verbs; with no
    faults the assembled counts must be bit-identical to the one-verb
    crawl (mask rows, children and leaf shares all reassemble exactly)."""
    L, n = 5, 12
    port = BASE_PORT + (400 if secure else 440)
    k0, k1 = _client_keys(rng, L, n)

    async def run(shard_nodes, port_base):
        cfg = _cfg(
            port_base, secure_exchange=secure, crawl_shard_nodes=shard_nodes
        )
        s0, s1 = await _start_servers(cfg, port_base)
        c0 = await rpc.CollectorClient.connect("127.0.0.1", port_base)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", port_base + 10)
        lead = RpcLeader(cfg, c0, c1)
        await lead._both("reset")
        await lead.upload_keys(k0, k1)
        res = await lead.run(n)
        await _teardown((c0, c1), {"s0": s0, "s1": s1})
        return res

    res_sharded = asyncio.run(run(1, port))
    res_whole = asyncio.run(run(0, port + 30))
    assert _hitters(res_sharded) == _hitters(res_whole) and _hitters(res_whole)
    np.testing.assert_array_equal(res_sharded.counts, res_whole.counts)
    np.testing.assert_array_equal(res_sharded.paths, res_whole.paths)


def test_e2e_mid_level_shard_loss_bit_identical(rng, tmp_path):
    """The mid-level acceptance scenario: one crawl-shard request is
    black-holed mid-level (no FIN — the verb budget converts it into a
    loud timeout), and the leader re-runs ONLY that shard (fresh data
    plane, same span) instead of rolling the level back.  Results are
    bit-identical to the fault-free run; the shard re-run is counted in
    the run report."""
    from fuzzyheavyhitters_tpu.obs import report as obsreport

    L, n = 5, 12
    port = BASE_PORT + 480
    pxport = port + 20
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port, crawl_shard_nodes=1)
    ck, ck_ff = tmp_path / "ckpt", tmp_path / "ckpt_ff"
    ck.mkdir(), ck_ff.mkdir()
    # generous enough for a warm level, small enough to keep the test
    # quick: level 0 (the compile) runs before the fault ordinal
    budgets = respolicy.VerbBudgets(default_s=10.0, per_verb={})

    async def faulty():
        # c2s frame 9 is a level-1 shard request (hello, reset, 2x
        # add_keys, tree_init, L0 crawl, L0 prune, then the level-1
        # spans): drop exactly one — the leader must re-run that span
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:blackhole@msg=9,count=1"), link="ctl0",
        ).start()
        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck), ctl0_proxy=px, budgets=budgets
        )
        rep = obsreport.run_report([lead.obs, live["s0"].obs, live["s1"].obs])
        await _teardown(clients, live, px)
        return res, lead, rep

    async def fault_free():
        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck_ff), budgets=budgets
        )
        await _teardown(clients, live)
        return res

    res_ff = asyncio.run(fault_free())
    res, lead, rep = asyncio.run(faulty())

    want_res = driver.Leader(
        *driver.make_servers(k0, k1), n_dims=1, data_len=L, f_max=cfg.f_max
    ).run(nreqs=n, threshold=cfg.threshold)
    assert _hitters(res) == _hitters(res_ff) == _hitters(want_res)
    assert _hitters(res)
    np.testing.assert_array_equal(res.counts, res_ff.counts)

    # the shard — not the level, not the crawl — was the retry unit
    assert lead.obs.counter_value("shards_rerun") >= 1
    assert lead.obs.counter_value("levels_rerun") == 0
    assert rep["recovery"]["shards_rerun"] >= 1
    assert rep["recovery"]["levels_rerun"] == 0


# ---------------------------------------------------------------------------
# checkpoint negative paths + prune ordering
# ---------------------------------------------------------------------------


def _server_with_ckpt(tmp_path, level=1, seed=7, port_off=500):
    """A lone server with keys, a root frontier, and one checkpoint at
    ``level`` (checkpoint/restore never touch the data plane, so no peer
    or listener is needed)."""
    s = rpc.CollectorServer(0, _cfg(BASE_PORT + port_off), ckpt_dir=str(tmp_path))
    k0, _ = _client_keys(np.random.default_rng(seed), 5, 6)

    async def go():
        await s.add_keys({"keys": tuple(np.asarray(x) for x in k0)})
        await s.tree_init({})
        await s.tree_checkpoint({"level": level})

    asyncio.run(go())
    return s


def test_tree_restore_rejects_mismatched_key_fingerprint(tmp_path):
    """A checkpoint written under one key batch must refuse to restore
    under another — and leave the refusing server's state untouched."""
    _server_with_ckpt(tmp_path, seed=7)
    other = rpc.CollectorServer(
        0, _cfg(BASE_PORT + 502), ckpt_dir=str(tmp_path)
    )
    k_other, _ = _client_keys(np.random.default_rng(8), 5, 6)

    async def go():
        await other.add_keys({"keys": tuple(np.asarray(x) for x in k_other)})
        with pytest.raises(RuntimeError, match="different key batch"):
            await other.tree_restore({"level": 1})

    asyncio.run(go())
    assert other.frontier is None  # nothing mutated on the failed path


def test_tree_restore_rejects_truncated_npz(tmp_path):
    """A torn/partially-written blob (crash mid-write of a NON-atomic
    copy, disk-full tail loss) must fail loudly as corruption and leave
    the live frontier exactly as it was."""
    s = _server_with_ckpt(tmp_path, port_off=504)
    path = s._ckpt_path(1)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])
    frontier_before = s.frontier
    alive_before = s.alive_keys.copy()

    async def go():
        with pytest.raises(RuntimeError, match="corrupt or truncated"):
            await s.tree_restore({"level": 1})

    asyncio.run(go())
    assert s.frontier is frontier_before
    np.testing.assert_array_equal(s.alive_keys, alive_before)


def test_tree_restore_rejects_deeper_level_than_tree(tmp_path):
    """A blob stamped deeper than this key batch's tree (data_len=5 ->
    deepest resumable level is 3) is a wrong-collection artifact, not a
    resume point."""
    s = _server_with_ckpt(tmp_path, level=7, port_off=506)

    async def go():
        with pytest.raises(RuntimeError, match="deeper than"):
            await s.tree_restore({"level": 7})

    asyncio.run(go())


def test_tree_restore_rejects_renamed_level_stamp(tmp_path):
    """The filename stamp and the blob's recorded level must agree — a
    renamed (or mis-copied) checkpoint restores the WRONG level's
    frontier otherwise."""
    import os as _os

    s = _server_with_ckpt(tmp_path, level=1, port_off=508)
    _os.rename(s._ckpt_path(1), s._ckpt_path(3))

    async def go():
        with pytest.raises(RuntimeError, match="records level"):
            await s.tree_restore({"level": 3})

    asyncio.run(go())


def test_ckpt_prune_and_latest_order_numerically(tmp_path):
    """Regression for levels >= 10: the keep-2 prune and the ckpt_levels
    listing must order level stamps NUMERICALLY — lexicographic ordering
    would rank l9 above l10/l11 and prune the two newest checkpoints."""
    s = rpc.CollectorServer(0, _cfg(BASE_PORT + 510), ckpt_dir=str(tmp_path))
    for lvl in (2, 9, 10, 11):
        (tmp_path / f"fhh_server0_l{lvl}.npz").write_bytes(b"x")
    assert s._ckpt_levels() == [2, 9, 10, 11]
    s._ckpt_prune(keep=2)
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["fhh_server0_l10.npz", "fhh_server0_l11.npz"]
    assert s._ckpt_levels() == [10, 11]


@pytest.mark.slow
def test_e2e_chaos_storm_multiple_faults(rng, tmp_path):
    """Stress variant (redundant coverage of the same recovery paths at a
    nastier schedule): a data-plane sever AND a server kill+restart AND a
    second control-link sever in one crawl."""
    L, n = 5, 12
    port = BASE_PORT + 260
    pxport = port + 20
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port)
    ck = tmp_path / "ckpt"
    ck.mkdir()

    async def run():
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:sever@msg=7,dir=s2c;ctl0:sever@msg=10"),
            link="ctl0",
        ).start()
        base = _kill_and_restart_s1_at_first_checkpoint(cfg, port, ck)

        async def assassin(live, lead):
            # cut the data plane out from under the live crawl first
            while lead.obs.counter_value("crawl_checkpoints") < 1:
                await asyncio.sleep(0)
            if live["s0"]._peer_writer is not None:
                live["s0"]._peer_writer.close()
            await base(live, lead)

        res, lead, clients, live = await _crawl_with_chaos(
            cfg, k0, k1, n, ckpt_dir=str(ck), ctl0_proxy=px,
            assassin=assassin,
        )
        await _teardown(clients, live, px)
        return res, lead

    res, lead = asyncio.run(run())
    want_res = driver.Leader(
        *driver.make_servers(k0, k1), n_dims=1, data_len=L, f_max=cfg.f_max
    ).run(nreqs=n, threshold=cfg.threshold)
    assert _hitters(res) == _hitters(want_res) and _hitters(res)
    assert lead.obs.counter_value("recoveries") >= 1
