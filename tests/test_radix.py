"""Radix-2^k level fusion (``Config.crawl_radix_bits``): crawl k bits
per round trip.

Acceptance surface of the radix tentpole:

- bit-identity: k in {2, 3} crawls produce the SAME heavy-hitter sets,
  paths, and client liveness as k=1 — trusted, secure (ot2s AND the
  S' > 6 GC ladder), and malicious/sketch lanes, single-device and
  sharded-mesh, including tail levels (data_len % k != 0);
- pruning equivalence: fused pruning at depths k, 2k, ... equals
  sequential per-level pruning (count monotonicity makes intermediate
  thresholds subsumed) — property-tested against an exact oracle;
- round-trip accounting: a k=2 secure crawl issues ceil(L/2) crawl
  verbs per server (vs L at k=1), observed through the per-session
  ``rpc:{verb}`` histograms, and the leader's run report shrinks its
  level count by the same factor;
- warmup contract: a warmed k=2 crawl triggers ZERO fresh XLA
  compiles (``compile_cache.backend_compiles`` fence);
- cross-radix blobs refuse validate-before-mutate, BOTH directions:
  driver checkpoints, server ``tree_checkpoint``/``tree_restore``
  blobs, and ``session_export``/``session_import`` migration blobs
  all stamp the radix.

Shapes mirror tests/test_secure_kernels.py (L=5, d=1, f_max=8) so the
k=1 baselines reuse programs those suites already compiled; the fused
shapes are this suite's own compiles.
"""

import asyncio
import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.protocol import collect, driver, rpc, secure, sketch
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils, compile_cache
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 28131

L, N = 5, 12


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the fused shapes compile once and are shared across
    every test in this module."""
    yield


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=L,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=8,
        secure_exchange=True,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, data_len=L, n=N, d=1):
    pts = np.concatenate(
        [np.full((n - 4, d), 11 % (1 << data_len)),
         rng.integers(0, 1 << data_len, size=(4, d))]
    )
    pts_bits = np.array(
        [[bitutils.int_to_bits(data_len, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


async def _run_crawl(cfg, port, k0, k1, sk0=None, sk1=None, nreqs=N,
                     warmup=False):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11))
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port, "127.0.0.1", port + 11))
    await asyncio.gather(t0, t1)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    await lead.upload_keys(k0, k1, sk0, sk1)
    if warmup:
        await lead.warmup()
    res = await lead.run(nreqs)
    out = {
        "res": res,
        "alive": None if s0.alive_keys is None else s0.alive_keys.copy(),
        "lead_report": lead.obs.report(),
        "server_reports": [s._default().obs.report() for s in (s0, s1)],
    }
    for c in (c0, c1):
        await c.aclose()
    for s in (s0, s1):
        await s.aclose()
    return out


def _crawl(cfg, port, k0, k1, **kw):
    return asyncio.run(_run_crawl(cfg, port, k0, k1, **kw))


def _assert_parity(base, got, ctx):
    np.testing.assert_array_equal(
        base["res"].counts, got["res"].counts, err_msg=str(ctx))
    np.testing.assert_array_equal(
        base["res"].paths, got["res"].paths, err_msg=str(ctx))


def _crawl_verbs(report):
    hists = report["hists"]
    return sum(
        hists[v]["count"] for v in ("rpc:tree_crawl", "rpc:tree_crawl_last")
        if v in hists
    )


# ---------------------------------------------------------------------------
# host-side units: dim caps, survivor ordering, bit packing
# ---------------------------------------------------------------------------


def test_radix_dim_caps_and_pattern_order():
    # packed per-(dim, side) layout holds 2^(r+1)-2 bits; 2*d*T <= 32
    for d, r in ((8, 1), (2, 2), (1, 3)):
        collect.check_radix(d, r)
    for d, r in ((9, 1), (3, 2), (2, 3), (1, 4), (1, 0)):
        with pytest.raises(ValueError):
            collect.check_radix(d, r)
    # S' = 2*d*r picks the kernel: ot2s through S' <= 6, GC past it —
    # d=2 at k=2 is the first forced-GC shape (the slow-marked
    # gc-route e2e crawls it; the routing decision stays in tier-1)
    assert secure.ot_path(2 * 2 * 1, "auto") == "ot2s"
    assert secure.ot_path(2 * 2 * 2, "auto") == "gc"

    # r=1 visit order is the identity — the radix path degenerates to
    # exactly the pre-radix survivor walk
    for d in (1, 2, 3):
        np.testing.assert_array_equal(
            collect.radix_pattern_order(d, 1), np.arange(1 << d))

    # fused ids are step-major (c = sum_t p_t * 2^(t*d)); the visit
    # order ranks by the SEQUENTIAL tree walk (earlier steps most
    # significant), so order[rank] must invert the rank formula
    for d, r in ((1, 2), (1, 3), (2, 2)):
        order = np.asarray(collect.radix_pattern_order(d, r))
        assert sorted(order.tolist()) == list(range(1 << (d * r)))
        for rank, c in enumerate(order.tolist()):
            steps = [(c >> (t * d)) & ((1 << d) - 1) for t in range(r)]
            want_rank = 0
            for t, p in enumerate(steps):
                want_rank += p << ((r - 1 - t) * d)
            assert rank == want_rank, (d, r, c)

    # pattern_to_bits_radix: [F, r, d] step bits reassemble the fused id
    d, r = 2, 2
    pat = np.arange(1 << (d * r), dtype=np.int32)
    bits = collect.pattern_to_bits_radix(pat, d, r)
    assert bits.shape == (pat.size, r, d)
    shift = np.arange(r)[:, None] * d + np.arange(d)[None, :]
    back = (bits.astype(np.int64) << shift).sum(axis=(1, 2))
    np.testing.assert_array_equal(back, pat)


def test_radix_fused_expand_matches_sequential():
    """One fused r=2 expand == two chained r=1 expand/advance rounds:
    same reconstructed counts for every fused child, and the fused
    child-state cache advances to bit-identical frontier states."""
    rng = np.random.default_rng(0)
    Lx, n, d, r = 6, 24, 2, 2
    pts = rng.integers(0, 1 << Lx, size=(n, d))
    pts_bits = ((pts[..., None] >> np.arange(Lx - 1, -1, -1)) & 1) > 0
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
    k0 = jax.tree.map(jnp.asarray, ibdcf.IbDcfKeyBatch(*k0))
    k1 = jax.tree.map(jnp.asarray, ibdcf.IbDcfKeyBatch(*k1))
    alive_keys = jnp.ones(n, bool)

    fr0 = collect.tree_init(k0, 1, planar=False)
    fr1 = collect.tree_init(k1, 1, planar=False)
    p0, ch0 = collect.expand_share_bits_radix(k0, fr0, 0, r, use_pallas=False)
    p1, _ = collect.expand_share_bits_radix(k1, fr1, 0, r, use_pallas=False)
    masks = jnp.asarray(collect.pattern_masks_radix(d, r))
    fused = np.asarray(
        collect.counts_by_pattern(p0, p1, masks, alive_keys, fr0.alive)
    )  # [1, 2^(r*d)]

    # sequential oracle: expand level 0, advance EVERY child, expand 1
    _, c0 = collect.expand_share_bits(k0, fr0, 0, use_pallas=False)
    _, c1 = collect.expand_share_bits(k1, fr1, 0, use_pallas=False)
    C1 = 1 << d
    parent = jnp.zeros(C1, jnp.int32)
    pb = jnp.asarray(collect.pattern_to_bits(np.arange(C1, dtype=np.int32), d))
    g0 = collect.advance_from_children(c0, parent, pb, C1)
    g1 = collect.advance_from_children(c1, parent, pb, C1)
    r0, cc0 = collect.expand_share_bits(k0, g0, 1, use_pallas=False)
    r1, _ = collect.expand_share_bits(k1, g1, 1, use_pallas=False)
    ref = np.asarray(collect.counts_by_pattern(
        r0, r1, jnp.asarray(collect.pattern_masks(d)), alive_keys, g0.alive
    ))  # [C1, C1]

    # fused child c = a + (b << d): depth-1 node a, then its child b
    for c in range(1 << (r * d)):
        a, b = c & (C1 - 1), (c >> d) & (C1 - 1)
        assert fused[0, c] == ref[a, b], (c, a, b)

    # fused advance over the banked child cache == two r=1 advances
    keep = np.zeros((1, 1 << (r * d)), bool)
    keep[0, :] = fused[0] >= 1
    par, pat, na = collect.compact_survivors(keep, 64)
    pbits = collect.pattern_to_bits_radix(pat, d, r)
    fr_fused = collect.advance_from_children_radix(
        ch0, jnp.asarray(par), jnp.asarray(pbits), na, r)
    a_all = pat & (C1 - 1)
    b_all = (pat >> d) & (C1 - 1)
    h0 = collect.advance_from_children(
        c0, jnp.zeros(par.shape[0], jnp.int32),
        jnp.asarray(collect.pattern_to_bits(a_all, d)), na)
    _, hc0 = collect.expand_share_bits(k0, h0, 1, use_pallas=False)
    fr_seq = collect.advance_from_children(
        hc0, jnp.arange(par.shape[0]),
        jnp.asarray(collect.pattern_to_bits(b_all, d)), na)
    for x, y in zip(fr_fused.states, fr_seq.states):
        np.testing.assert_array_equal(
            np.asarray(x)[:na], np.asarray(y)[:na])


def test_radix_prune_equivalence_property():
    """Fused pruning visits only depths k, 2k, ... — yet keeps exactly
    the prefixes sequential per-level pruning keeps.  The invariant that
    makes this an equivalence, not an approximation: prefix counts are
    monotone (count(p) >= count(p + suffix)), so a depth-t survivor's
    every ancestor also clears the threshold and the skipped
    intermediate prunes are subsumed.  Property-checked against an
    exact-oracle recursion over random datasets."""
    rng = np.random.default_rng(42)

    def survivors(pts, grid, thresh):
        """Exact crawl over the named depth grid: count each frontier
        node's depth-t extensions, keep those clearing the threshold."""
        frontier = {()}
        out = {}
        prev = 0
        for depth in grid:
            counts = {}
            for v in pts:
                p = v[:depth]
                if p[:prev] in frontier:
                    counts[p] = counts.get(p, 0) + 1
            frontier = {p for p, c in counts.items() if c >= thresh}
            out[depth] = frontier
            prev = depth
        return out

    for trial in range(25):
        Lx = 6
        k = int(rng.integers(2, 4))
        n = int(rng.integers(15, 50))
        thresh = int(rng.integers(1, 5))
        # cluster: heavy values + noise, as strings of bits
        vals = rng.integers(0, 1 << Lx, size=n)
        vals[: n // 2] = vals[0]
        pts = [tuple(bool((v >> (Lx - 1 - t)) & 1) for t in range(Lx))
               for v in vals]

        seq = survivors(pts, list(range(1, Lx + 1)), thresh)
        fused_grid = [min(b + k, Lx) for b in range(0, Lx, k)]
        fused = survivors(pts, fused_grid, thresh)
        for depth in fused_grid:
            assert fused[depth] == seq[depth], (trial, k, depth)


# ---------------------------------------------------------------------------
# in-process driver: parity incl. tail levels, cross-radix checkpoints
# ---------------------------------------------------------------------------


def _driver_keys(Lx, n, d, seed=0):
    rng = np.random.default_rng(seed)
    pts = rng.integers(0, 1 << Lx, size=(n, d))
    pts[: n // 3] = pts[0]
    pts[n // 3: n // 2] = pts[n // 3]
    bits = ((pts[..., None] >> np.arange(Lx - 1, -1, -1)) & 1) > 0
    k0, k1 = ibdcf.gen_l_inf_ball(bits, 2, rng, engine="np")
    k0 = jax.tree.map(jnp.asarray, ibdcf.IbDcfKeyBatch(*k0))
    k1 = jax.tree.map(jnp.asarray, ibdcf.IbDcfKeyBatch(*k1))
    return k0, k1


def _driver_crawl(k0, k1, Lx, d, radix, n=40):
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(
        s0, s1, n_dims=d, data_len=Lx, f_max=128, radix=radix)
    return lead.run(n, 0.1)


def test_radix_driver_parity_and_tail_levels():
    """Trusted in-process crawls, k in {2, 3} vs k=1 — both (L, d)
    scenarios leave a tail level (data_len % k != 0), so the final
    round fuses r = L mod k < k bits and must stay bit-exact."""
    for Lx, d, ks in ((7, 1, (2, 3)), (5, 2, (2,))):
        k0, k1 = _driver_keys(Lx, 40, d)
        base = _driver_crawl(k0, k1, Lx, d, 1)
        assert base.paths.shape[0] >= 1
        for k in ks:
            assert Lx % k != 0  # the scenario really exercises the tail
            got = _driver_crawl(k0, k1, Lx, d, k)
            np.testing.assert_array_equal(base.paths, got.paths)
            np.testing.assert_array_equal(base.counts, got.counts)


def test_radix_driver_checkpoint_refuses_cross_radix(tmp_path):
    """Driver checkpoints stamp the crawl radix; a k=2 blob refuses a
    k=1 leader and vice versa (validate-before-mutate), while a
    same-radix resume completes bit-identically."""
    Lx, d, n, thr = 6, 1, 40, 0.1
    k0, k1 = _driver_keys(Lx, n, d, seed=3)
    base = _driver_crawl(k0, k1, Lx, d, 2, n=n)

    ck2 = str(tmp_path / "k2.npz")
    s0a, s1a = driver.make_servers(k0, k1)
    lead_a = driver.Leader(s0a, s1a, n_dims=d, data_len=Lx, f_max=128,
                           radix=2)
    lead_a.tree_init()
    assert lead_a.run_level(0, nreqs=n, threshold=thr) > 0  # bits 0..1
    lead_a.checkpoint(ck2, 0)

    # k=2 blob -> k=1 leader: refused, live state untouched
    s0b, s1b = driver.make_servers(k0, k1)
    lead_1 = driver.Leader(s0b, s1b, n_dims=d, data_len=Lx, f_max=128)
    with pytest.raises(ValueError, match="crawl radix 2"):
        lead_1.restore(ck2)
    assert lead_1.paths is None and s0b.frontier is None

    # k=1 blob -> k=2 leader: refused too (the other direction)
    ck1 = str(tmp_path / "k1.npz")
    lead_1.tree_init()
    lead_1.run_level(0, nreqs=n, threshold=thr)
    lead_1.checkpoint(ck1, 0)
    s0c, s1c = driver.make_servers(k0, k1)
    lead_b = driver.Leader(s0c, s1c, n_dims=d, data_len=Lx, f_max=128,
                           radix=2)
    with pytest.raises(ValueError, match="crawl radix 1"):
        lead_b.restore(ck1)
    assert lead_b.paths is None and s0c.frontier is None

    # positive control: the k=2 blob resumes a fresh k=2 leader to the
    # exact uninterrupted result (restore returns base + r = 2)
    s0d, s1d = driver.make_servers(k0, k1)
    lead_c = driver.Leader(s0d, s1d, n_dims=d, data_len=Lx, f_max=128,
                           radix=2)
    got = lead_c.run(nreqs=n, threshold=thr, checkpoint_path=ck2,
                     resume=True)
    np.testing.assert_array_equal(base.paths, got.paths)
    np.testing.assert_array_equal(base.counts, got.counts)


# ---------------------------------------------------------------------------
# RPC end-to-end: secure parity + round-trip accounting, GC route,
# malicious lane, sharded mesh, warm-compile contract
# ---------------------------------------------------------------------------


def test_radix_secure_parity_and_round_trip_count():
    """Secure (ot2s, S' = 2k <= 6) crawls at k in {2, 3} are
    bit-identical to k=1 and issue exactly ceil(L/k) crawl verbs per
    server — the fused round trips the tentpole buys, asserted through
    the per-session ``rpc:{verb}`` histograms and the leader's
    level-latency report (L=5: tails for both k)."""
    rng = np.random.default_rng(7)
    k0, k1 = _client_keys(rng)
    port = BASE_PORT
    base = _crawl(_cfg(port), port, k0, k1)
    assert base["res"].paths.shape[0] >= 1
    assert base["lead_report"]["hists"]["level_latency"]["count"] == L
    for s_rep in base["server_reports"]:
        assert _crawl_verbs(s_rep) == L
    port += 40
    for k in (2, 3):
        got = _crawl(_cfg(port, crawl_radix_bits=k), port, k0, k1)
        port += 40
        _assert_parity(base, got, {"k": k})
        levels = math.ceil(L / k)
        # run report shrinks its level count by k
        assert got["lead_report"]["hists"]["level_latency"]["count"] == levels
        # structural round-trip bound from the issue: <= ceil(L/k) + 1
        # crawl verbs per server — and in fact exactly ceil(L/k)
        for s_rep in got["server_reports"]:
            assert _crawl_verbs(s_rep) == levels


# The three heaviest radix e2e lanes below (GC route, malicious,
# sharded mesh — full socket crawls at distinct compile shapes) are
# @pytest.mark.slow so tier-1 stays inside its 870 s wall clock on one
# core; scripts/chaos.sh runs tests/test_radix.py with `-m ""` so they
# execute on every chaos/CI pass (the PR-19 pattern).  The cheap tier-1
# lanes above them keep every fused program shape covered: secure
# parity + verb counts (ot2s), warmed-zero-compiles, driver tail
# levels, and the pruning property.


@pytest.mark.slow
def test_radix_gc_route_parity():
    """d=2 at k=2 gives S' = 2*d*k = 8 > OT2S ceiling: the fused level
    must route through the GC ladder and still match k=1 (which runs
    ot2s at S=4) bit-for-bit."""
    assert secure.ot_path(2 * 2 * 1, "auto") == "ot2s"
    assert secure.ot_path(2 * 2 * 2, "auto") == "gc"
    rng = np.random.default_rng(9)
    Lx, d = 4, 2
    k0, k1 = _client_keys(rng, data_len=Lx, d=d)
    port = BASE_PORT + 200
    base = _crawl(_cfg(port, data_len=Lx, n_dims=d, f_max=32), port, k0, k1)
    assert base["res"].counts.size
    port += 40
    got = _crawl(
        _cfg(port, data_len=Lx, n_dims=d, f_max=32, crawl_radix_bits=2),
        port, k0, k1)
    _assert_parity(base, got, "gc-route")


def _sketch_material(rng):
    """Malicious-lane client material with client 3's sketch payload
    forged at bit level 2 (mirrors tests/test_sketch_shard.py): an
    honest run must exclude exactly that client."""
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(N, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)
    bad = np.asarray(sk0.key.cw_val).copy()
    bad[3, 0, 2, 0] = (int(bad[3, 0, 2, 0]) + 1) % FE62.P
    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))
    return k0, k1, sk0, sk1


@pytest.mark.slow
def test_radix_malicious_parity_excludes_forged_payload():
    """Sketch lane under fusion: the fused prune banks one gated pair
    share per fused BIT level and the final verify opens each under its
    own ratcheted challenge — so a payload forged at an intermediate
    depth is still caught, the cheater's keys go dead, and counts,
    paths, and liveness all match k=1 exactly."""
    rng = np.random.default_rng(11)
    k0, k1, sk0, sk1 = _sketch_material(rng)
    kw = dict(f_max=32, malicious=True, threshold=0.5)
    port = BASE_PORT + 400
    base = _crawl(_cfg(port, **kw), port, k0, k1, sk0=sk0, sk1=sk1)
    want_alive = np.ones(N, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(base["alive"], want_alive)
    port += 40
    for k in (2, 3):
        got = _crawl(_cfg(port, crawl_radix_bits=k, **kw), port, k0, k1,
                     sk0=sk0, sk1=sk1)
        port += 40
        np.testing.assert_array_equal(got["alive"], want_alive)
        _assert_parity(base, got, {"malicious-k": k})


@pytest.mark.slow
def test_radix_mesh_parity():
    """Sharded mesh lane (server_data_devices=4 on the 8-device CPU
    mesh): fused crawls match k=1 under both exchanges — the sharded
    kernel plan binds the widened S' = 2k strings per shard."""
    rng = np.random.default_rng(77)
    k0, k1 = _client_keys(rng)
    port = BASE_PORT + 600
    for mode_kw in (dict(secure_exchange=True), dict(secure_exchange=False)):
        base = _crawl(
            _cfg(port, server_data_devices=4, **mode_kw), port, k0, k1)
        port += 40
        got = _crawl(
            _cfg(port, server_data_devices=4, crawl_radix_bits=2, **mode_kw),
            port, k0, k1)
        port += 40
        _assert_parity(base, got, mode_kw)


def test_radix_warmed_crawl_zero_fresh_compiles():
    """The warmup ladder covers the fused shapes: a second, fully-warmed
    k=2 secure crawl triggers ZERO fresh XLA compiles (the
    ``backend_compiles`` fence the ISSUE names)."""
    rng = np.random.default_rng(5)
    k0, k1 = _client_keys(rng)
    port = BASE_PORT + 800
    kw = dict(crawl_radix_bits=2, secure_exchange=True)
    first = _crawl(_cfg(port, **kw), port, k0, k1, warmup=True)
    before = compile_cache.backend_compiles()
    second = _crawl(_cfg(port + 40, **kw), port + 40, k0, k1, warmup=True)
    fresh = compile_cache.backend_compiles() - before
    _assert_parity(first, second, "warmed")
    assert fresh == 0, f"{fresh} fresh compiles in a fully-warmed k=2 crawl"


# ---------------------------------------------------------------------------
# cross-radix blob refusals: tree_restore + session_import (both ways)
# ---------------------------------------------------------------------------


def _chunk(k, sl):
    return tuple(np.asarray(x)[sl] for x in k)


def test_radix_tree_restore_and_session_import_refuse_cross_radix(tmp_path):
    """Server-side blobs stamp the radix too: ``tree_restore`` and
    ``session_import`` refuse a blob written under the other radix — in
    BOTH directions — with live state untouched, and a same-radix
    restore still lands."""
    port = BASE_PORT + 1000
    k0, _ = _client_keys(np.random.default_rng(13))
    dir2, dir1 = str(tmp_path / "k2"), str(tmp_path / "k1")
    os.makedirs(dir2)
    os.makedirs(dir1)

    async def run():
        sub = {"window": 0, "sub_id": "a", "client_id": "c",
               "keys": _chunk(k0, slice(0, 2))}
        src2 = rpc.CollectorServer(
            0, _cfg(port, crawl_radix_bits=2), ckpt_dir=dir2)
        await src2.submit_keys(sub)
        await src2.tree_checkpoint({"level": 0, "ingest_only": True})
        x2 = await src2.session_export({})
        src1 = rpc.CollectorServer(0, _cfg(port), ckpt_dir=dir1)
        await src1.submit_keys(sub)
        await src1.tree_checkpoint({"level": 0, "ingest_only": True})
        x1 = await src1.session_export({})

        # k=2 blob -> k=1 session (and the reverse): refused untouched
        dst1 = rpc.CollectorServer(0, _cfg(port), ckpt_dir=dir2)
        with pytest.raises(RuntimeError, match="crawl_radix_bits=2"):
            await dst1.tree_restore({"level": 0})
        with pytest.raises(RuntimeError, match="crawl_radix_bits=2"):
            await dst1.session_import(
                {"path": x2["path"], "boot": x2["boot"],
                 "epoch": x2["epoch"]})
        assert dst1._default()._ingest_pools == {}
        assert dst1._default().frontier is None

        dst2 = rpc.CollectorServer(
            0, _cfg(port, crawl_radix_bits=2), ckpt_dir=dir1)
        with pytest.raises(RuntimeError, match="crawl_radix_bits=1"):
            await dst2.tree_restore({"level": 0})
        with pytest.raises(RuntimeError, match="crawl_radix_bits=1"):
            await dst2.session_import(
                {"path": x1["path"], "boot": x1["boot"],
                 "epoch": x1["epoch"]})
        assert dst2._default()._ingest_pools == {}

        # positive control: the SAME radix restores/imports fine
        ok = rpc.CollectorServer(
            0, _cfg(port, crawl_radix_bits=2), ckpt_dir=dir2)
        await ok.tree_restore({"level": 0})
        assert len(ok._default()._ingest_pools[0].entries) == 1
        ok2 = rpc.CollectorServer(
            0, _cfg(port, crawl_radix_bits=2), ckpt_dir=dir2)
        await ok2.session_import(
            {"path": x2["path"], "boot": x2["boot"], "epoch": x2["epoch"]})
        assert len(ok2._default()._ingest_pools[0].entries) == 1

    asyncio.run(run())
