"""Multi-tenant collection sessions: the per-collection session
subsystem (protocol/sessions.py), the tenant scheduler + shared warmup
ladder (protocol/tenancy.py), and the multi-collection driver
(protocol/leader_rpc.MultiCollectionDriver).

The acceptance surface (ISSUE 12): N=4 concurrent collections on ONE
server pair each produce heavy-hitter sets BIT-IDENTICAL to their solo
single-session runs — trusted AND secure — with per-session ingest
gates isolating a flooding tenant, session-namespaced checkpoints
refusing cross-namespace blobs, and the tenant-isolation chaos leg
(flood tenant A + kill/restart s1 mid-crawl of tenant B's window)
green; scripts/chaos.sh re-runs that leg under FHH_DEBUG_GUARDS=1.

Shapes mirror tests/test_resilience.py (L=5, d=1) so the crawl kernels
compile once across the suites.
"""

import asyncio
import os

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.ops.ibdcf import IbDcfKeyBatch
from fuzzyheavyhitters_tpu.protocol import rpc, sessions, tenancy
from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
    MultiCollectionDriver,
    RpcLeader,
    WindowedIngest,
)
from fuzzyheavyhitters_tpu.resilience import policy as respolicy
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 26431


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: session plumbing over the same crawl kernels the
    other protocol suites compile."""
    yield


def _cfg(port, **kw):
    base = dict(
        data_len=5, n_dims=1, ball_size=1, addkey_batch_size=64,
        num_sites=4, threshold=0.05, zipf_exponent=1.0,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=16, backend="cpu",
    )
    base.update(kw)
    return Config(**base)


def _client_keys(seed, L, n):
    r = np.random.default_rng(seed)
    sites = r.integers(0, 1 << L, size=4)
    pts = sites[r.integers(0, 4, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, r, engine="np")


def _chunk(k, sl):
    return tuple(np.asarray(x)[sl] for x in k)


async def _start_pair(cfg, port, ckpt_dir=None):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


async def _solo_run(cfg, port, k0, k1, n):
    """Reference: one collection alone on a fresh pair (the default
    session — exactly the pre-multi-tenant deployment)."""
    s0, s1 = await _start_pair(cfg, port)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    await lead.upload_keys(k0, k1)
    res = await lead.run(n)
    for c in (c0, c1):
        await c.aclose()
    for s in (s0, s1):
        await s.aclose()
    return res


# ---------------------------------------------------------------------------
# units: session table, scheduler, warm ladder, plane mux
# ---------------------------------------------------------------------------


def test_session_table_bound_eviction_and_bad_keys():
    cfg = _cfg(1, collection_sessions_max=2)
    table = sessions.SessionTable(0, cfg, None, None)

    async def run():
        a = table.get("a")
        table.get("b")
        # at the cap: an IDLE session (a: nothing uploaded) evicts
        # oldest-first, so c fits
        table.get("c")
        assert sorted(table.keys()) == ["b", "c"]
        # both live sessions busy -> a new collection refuses loudly
        table.get("b").keys_parts.append("x")
        table.get("c").keys_parts.append("x")
        with pytest.raises(RuntimeError, match="session bound"):
            table.get("d")
        # key validation: filename/channel safety ("" is NOT invalid —
        # it resolves to the default collection by design)
        for bad in ("a/b", "x" * 65, "sp ace", "tab\t"):
            with pytest.raises(ValueError):
                table.get(bad)
        assert a.key == "a"
        # a session with a live connection BINDING is never idle-evicted,
        # even with no state yet (evicting it would orphan the bound
        # leader and let a same-key successor share its plane channel)
        table.get("b").keys_parts.clear()
        table.get("b").bound += 1
        with pytest.raises(RuntimeError, match="session bound"):
            table.get("e")
        table.get("b").bound -= 1
        table.get("e")  # unbound + stateless again: evictable
        assert "b" not in table.keys()

    asyncio.run(run())


def test_tenant_scheduler_counts_stall_fills():
    sched = tenancy.TenantScheduler()

    async def run():
        async with sched.device_turn("a"):
            pass  # nobody on the wire: a plain turn
        with sched.wire_wait("a"):
            async with sched.device_turn("b"):
                pass  # b dispatched while a waited: a stall fill
            with sched.wire_wait("b"):
                async with sched.device_turn("a"):
                    pass  # and symmetrically
        sched.note_dispatch("c")  # nobody waiting anymore

    asyncio.run(run())
    st = sched.stats()
    assert st["device_turns"] == 4
    assert st["stall_fills"] == 2
    assert st["fills_by_session"] == {"a": 1, "b": 1}
    assert st["fill_ratio"] == pytest.approx(0.5)


def test_warm_ladder_marks_and_skips():
    tenancy.ladder_reset()
    key = ("warm", (4, 1, 5, 2, 4), 2, 5, False, True, "auto", 0, 0, True)
    assert not tenancy.warmed(key)
    tenancy.mark_warmed(key)
    assert tenancy.warmed(key)
    assert tenancy.ladder_size() == 1
    tenancy.ladder_reset()
    assert not tenancy.warmed(key)


def test_plane_mux_demux_fifo_and_failure():
    """Frames interleaved across channels demux into per-channel FIFO
    order; a transport death surfaces to every blocked recv as
    ConnectionError; attach() supersedes the old pump."""

    async def run():
        mux = sessions.PlaneMux()
        reader = asyncio.StreamReader()

        async def read_frame(r):
            line = await r.readexactly(4)
            # fake framing: b"Axy1" -> channel "A"+"xy", payload int
            return 4, (line[:1].decode() + line[1:3].decode(), line[3])

        mux.attach(reader, read_frame)
        reader.feed_data(b"Axy1Bzz9Axy2")
        assert await mux.recv("Axy") == ord("1")
        assert await mux.recv("Bzz") == ord("9")
        assert await mux.recv("Axy") == ord("2")
        # a blocked recv learns of the transport death
        waiter = asyncio.ensure_future(mux.recv("Axy"))
        await asyncio.sleep(0)
        reader.feed_eof()
        with pytest.raises(ConnectionError):
            await waiter
        # and later recvs on ANY channel fail too, until re-attach
        with pytest.raises(ConnectionError):
            await mux.recv("Bzz")
        r2 = asyncio.StreamReader()
        epoch = mux.attach(r2, read_frame)
        assert epoch == 2
        r2.feed_data(b"Axy7")
        assert await mux.recv("Axy") == ord("7")
        mux.close()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# session-namespaced checkpoints
# ---------------------------------------------------------------------------


def test_session_namespaced_checkpoints_and_cross_session_refusal(tmp_path):
    """Each collection checkpoints into its own filename namespace; a
    blob renamed across namespaces refuses at the session stamp, and a
    restore refuses a torn session tail — all BEFORE any state mutates
    (the PR-4 validate-before-mutate contract, extended)."""
    port = BASE_PORT
    cfg = _cfg(port)
    k0, k1 = _client_keys(11, 5, 6)

    async def run():
        s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        ca = s._table.get("tenA")
        cb = s._table.get("tenB")
        # tree_init needs the live data plane (coin flip) — this is a
        # one-server unit, so build the crawl state through the session
        # helpers instead
        from fuzzyheavyhitters_tpu.protocol import collect

        for cs in (ca, cb):
            await s.add_keys({"keys": _chunk(k0, slice(0, 6))}, cs)
            cs.concat_keys()
            cs.alive_keys = np.ones(6, bool)
            cs.frontier = collect.tree_init(cs.keys, 1)
        await s.tree_checkpoint({"level": 1}, ca)
        await s.tree_checkpoint({"level": 1}, cb)
        # distinct namespaces, legacy name untouched for the default
        assert os.path.exists(tmp_path / "fhh_server0_ctenA_l1.npz")
        assert os.path.exists(tmp_path / "fhh_server0_ctenB_l1.npz")
        assert ca.ckpt_levels() == [1] and cb.ckpt_levels() == [1]
        # cross-namespace rename: refused at the session stamp, state
        # untouched
        os.replace(
            tmp_path / "fhh_server0_ctenA_l1.npz",
            tmp_path / "fhh_server0_ctenB_l1.npz",
        )
        frontier_before = cb.frontier
        with pytest.raises(RuntimeError, match="stamped for collection"):
            await s.tree_restore({"level": 1}, cb)
        assert cb.frontier is frontier_before
        # torn session tail: a session-namespaced blob whose ingest tail
        # is torn refuses before any pool mutates
        pool = cb.ingest_pool(0)
        pool.apply(
            "sub1", _chunk(k0, slice(0, 2)),
            cb._admission.admit(pool.wa, "c", 2),
        )
        await s.tree_checkpoint({"level": 2}, cb)
        path = cb.ckpt_path(2)
        blob = dict(np.load(path))
        del blob["ing0_lens"]  # tear the ingest tail
        with open(path, "wb") as f:
            np.savez(f, **blob)
        pools_before = dict(cb._ingest_pools)
        with pytest.raises(RuntimeError, match="missing ingest fields"):
            await s.tree_restore({"level": 2}, cb)
        assert cb._ingest_pools == pools_before
        await s.aclose()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# THE acceptance: N=4 concurrent collections bit-identical to solo
# ---------------------------------------------------------------------------


def _multi_vs_solo(port, cfg_kw, n, n_collections=4, supervised=False):
    cfgs = _cfg(port, **cfg_kw)
    keysets = {
        f"t{i}": _client_keys(100 + i, 5, n) for i in range(n_collections)
    }

    async def solo_all():
        out = {}
        for i, (key, (k0, k1)) in enumerate(keysets.items()):
            out[key] = await _solo_run(
                _cfg(port + 100 + 20 * i, **cfg_kw),
                port + 100 + 20 * i, k0, k1, n,
            )
        return out

    async def multi():
        s0, s1 = await _start_pair(cfgs, port)
        drv = MultiCollectionDriver(
            cfgs, "127.0.0.1", port, "127.0.0.1", port + 10
        )
        jobs = [
            {"collection": key, "nreqs": n, "keys0": k0, "keys1": k1}
            for key, (k0, k1) in keysets.items()
        ]
        res = await drv.run_collections(jobs, supervised=supervised)
        # telemetry: status sessions section + run-report sessions rollup
        st = await drv.leaders["t0"].c0.call("status")
        regs = [ld.obs for ld in drv.leaders.values()]
        regs += [s0.obs, s1.obs]
        regs += [cs.obs for _, cs in s0._table.items()]
        regs += [cs.obs for _, cs in s1._table.items()]
        rep = obsreport.run_report(regs)
        await drv.close()
        for s in (s0, s1):
            await s.aclose()
        return res, st, rep

    solo = asyncio.run(solo_all())
    got, st, rep = asyncio.run(multi())
    for key in keysets:
        res = got[key]
        assert not isinstance(res, BaseException), (key, res)
        np.testing.assert_array_equal(res.counts, solo[key].counts)
        np.testing.assert_array_equal(res.paths, solo[key].paths)
    return st, rep


def test_multi_tenant_trusted_n4_bit_identical_to_solo():
    st, rep = _multi_vs_solo(BASE_PORT + 40, {}, n=48, n_collections=4)
    sess = st["sessions"]
    assert sess["count"] == 4
    assert sess["scheduler"]["device_turns"] > 0
    # every tenant appears in the per-session status rows
    assert sorted(sess["per_session"]) == ["t0", "t1", "t2", "t3"]
    for row in sess["per_session"].values():
        assert set(row) >= {
            "phase", "level", "queue_depth", "dedup_entries", "ckpt_levels"
        }
    # run-report sessions rollup: the four tenants' crawl seconds land
    rsess = rep["sessions"]
    assert rsess["count"] == 4
    assert rsess["device_turns"] > 0
    assert all(
        rsess["per_session"][k]["crawl_seconds"] > 0 for k in rsess["per_session"]
    )


def test_multi_tenant_secure_n4_bit_identical_to_solo():
    """Secure 2PC: four independent OT/GC transcripts interleaved on one
    demuxed data plane, each tenant's heavy hitters bit-identical to its
    solo run."""
    st, rep = _multi_vs_solo(
        BASE_PORT + 400, {"secure_exchange": True}, n=24, n_collections=4
    )
    assert st["sessions"]["count"] == 4


def test_multi_tenant_stall_fills_observed():
    """The scheduler actually observes cross-tenant fill: with two
    tenants crawling concurrently, some device turns run while the
    other tenant waits on the GC/OT wire."""
    st, _rep = _multi_vs_solo(
        BASE_PORT + 700, {"secure_exchange": True}, n=16, n_collections=2
    )
    sched = st["sessions"]["scheduler"]
    assert sched["stall_fills"] > 0
    assert 0 < sched["fill_ratio"] <= 1


# ---------------------------------------------------------------------------
# per-session ingest gates: a flooding tenant cannot starve another
# ---------------------------------------------------------------------------


def test_per_session_gates_flooding_tenant_isolated():
    """Tenant A floods its rate bucket dry; tenant B's submissions all
    admit — the buckets are PER SESSION (each collection has its own
    AdmissionController), so A's rejections never consume B's tokens."""
    port = BASE_PORT + 140
    cfg = _cfg(
        port,
        ingest_rate_keys_per_s=64.0,
        ingest_burst_keys=8,
    )
    kA = _client_keys(21, 5, 64)
    kB = _client_keys(22, 5, 8)

    async def run():
        s0, s1 = await _start_pair(cfg, port)
        drv = MultiCollectionDriver(
            cfg, "127.0.0.1", port, "127.0.0.1", port + 10
        )
        leadA = await drv.open("ta")
        leadB = await drv.open("tb")
        wiA = WindowedIngest(
            leadA, checkpoint=False,
            policy=respolicy.RetryPolicy(
                base_s=0.001, cap_s=0.002, factor=1.0, attempts=2
            ),
        )
        wiB = WindowedIngest(leadB, checkpoint=False)
        rejA = 0

        async def flood():
            nonlocal rejA
            from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
                IngestOverloadedError,
            )

            for i in range(0, 64, 8):
                try:
                    await wiA.submit(
                        "flooder", _chunk(kA[0], slice(i, i + 8)),
                        _chunk(kA[1], slice(i, i + 8)),
                    )
                except IngestOverloadedError:
                    rejA += 1

        async def honest():
            for i in range(8):
                await wiB.submit(
                    f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                    _chunk(kB[1], slice(i, i + 1)),
                )
                await asyncio.sleep(0.005)

        await asyncio.gather(flood(), honest())
        stA = await wiA.seal_window()
        stB = await wiB.seal_window()
        await drv.close()
        for s in (s0, s1):
            await s.aclose()
        return rejA, stA, stB

    rejA, stA, stB = asyncio.run(run())
    # the flood hit A's OWN bucket: per-attempt rejections recorded at
    # A's gate (rejA counts only submissions that exhausted every
    # backoff — the hint-honoring retry usually lands, so the gate-side
    # counter is the reliable signal)
    assert stA["rejected"] > 0
    assert stB["keys"] == 8 and stB["rejected"] == 0  # B untouched


# ---------------------------------------------------------------------------
# tenant-isolation chaos: flood A + kill/restart s1 mid-crawl of B
# (scripts/chaos.sh re-runs this leg under FHH_DEBUG_GUARDS=1)
# ---------------------------------------------------------------------------


def test_tenant_isolation_flood_and_kill_restart_mid_crawl(tmp_path):
    """THE tenant-isolation scenario: tenant A floods its gate while
    tenant B runs a windowed crawl; server 1 is killed and restarted
    MID-CRAWL.  Tenant B's window stays bit-exact vs a fault-free batch
    crawl over the same admitted keys, and B's admission counters are
    untouched by A's flood (no rejections leak across gates)."""
    port = BASE_PORT + 200
    L, nB = 5, 10
    cfg = _cfg(
        port,
        ingest_rate_keys_per_s=200.0,
        ingest_burst_keys=16,
    )
    kA = _client_keys(31, L, 96)
    kB = _client_keys(32, L, nB)
    ck = tmp_path / "ck"
    ck.mkdir()

    async def run():
        live = {}
        live["s0"], live["s1"] = await _start_pair(
            cfg, port, ckpt_dir=str(ck)
        )
        drv = MultiCollectionDriver(
            cfg, "127.0.0.1", port, "127.0.0.1", port + 10
        )
        leadA = await drv.open("ta")
        leadB = await drv.open("tb")
        wiA = WindowedIngest(
            leadA, checkpoint=False,
            policy=respolicy.RetryPolicy(
                base_s=0.001, cap_s=0.002, factor=1.0, attempts=2
            ),
        )
        wiB = WindowedIngest(leadB)  # checkpointing ON
        # B's window 0 fills, seals, and crawls
        for i in range(nB):
            await wiB.submit(
                f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                _chunk(kB[1], slice(i, i + 1)),
            )
        await wiB.seal_window()

        async def assassin():
            # kill s1 once tenant B's window crawl is actually underway
            # on it (its tb session starts billing fss seconds)
            while True:
                cs = live["s1"]._table.peek("tb")
                if cs is not None and cs.obs.timer_seconds("fss") > 0:
                    break
                await asyncio.sleep(0.01)
            await live["s1"].aclose()
            await asyncio.sleep(0.3)
            live["s1"] = rpc.CollectorServer(1, cfg, ckpt_dir=str(ck))
            await live["s1"].start(
                "127.0.0.1", port + 10, "127.0.0.1", port + 11
            )

        async def flood():
            from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
                IngestOverloadedError,
            )

            rej = 0
            for i in range(0, 96, 8):
                try:
                    await wiA.submit(
                        "flooder", _chunk(kA[0], slice(i, i + 8)),
                        _chunk(kA[1], slice(i, i + 8)),
                    )
                except (IngestOverloadedError,
                        *respolicy.TRANSIENT_ERRORS, RuntimeError):
                    rej += 1  # Overloaded or mid-kill transport loss
                await asyncio.sleep(0.01)
            return rej

        kill = asyncio.create_task(assassin())
        fl = asyncio.create_task(flood())
        resB = await wiB.crawl_window(0, max_recoveries=8)
        await kill
        await fl
        stB = await leadB.c0.call("status")
        stA = await leadA.c0.call("status")
        await drv.close()
        for s in live.values():
            await s.aclose()
        return resB, stA, stB

    resB, stA, stB = asyncio.run(run())
    # fault-free reference over the same admitted set
    want = asyncio.run(
        _solo_run(
            _cfg(port + 60), port + 60,
            IbDcfKeyBatch(*_chunk(kB[0], slice(0, nB))),
            IbDcfKeyBatch(*_chunk(kB[1], slice(0, nB))),
            nB,
        )
    )
    np.testing.assert_array_equal(resB.counts, want.counts)
    np.testing.assert_array_equal(resB.paths, want.paths)
    # B's gate never rejected anything: A's flood hit only A's bucket
    ingB = stB["ingest"]
    assert ingB["rejected"] == 0
    assert ingB["admitted"] == nB
    # ...and A's own gate actually rejected (the flood was real)
    assert stA["ingest"]["rejected"] > 0


# ---------------------------------------------------------------------------
# shared warmup ladder
# ---------------------------------------------------------------------------


def test_warmup_ladder_shared_across_tenants():
    """A second collection with the same batch shape pays ZERO fresh
    warm executions: the process-level WarmLadder answers its warmup
    from the first tenant's pass (the compiled programs are already in
    the process jit cache)."""
    port = BASE_PORT + 340
    cfg = _cfg(port)
    k0, k1 = _client_keys(41, 5, 8)

    async def run():
        tenancy.ladder_reset()
        s = rpc.CollectorServer(0, cfg)
        ca = s._table.get("wa")
        cb = s._table.get("wb")
        for cs in (ca, cb):
            await s.add_keys({"keys": _chunk(k0, slice(0, 8))}, cs)
        r1 = await s.warmup({"f_buckets": [1, 2]}, ca)
        r2 = await s.warmup({"f_buckets": [1, 2]}, cb)
        await s.aclose()
        return r1, r2

    r1, r2 = asyncio.run(run())
    assert r1["shapes"] > 0 and r1["ladder_hits"] == 0
    assert r2["shapes"] == 0 and r2["ladder_hits"] == r1["shapes"]
