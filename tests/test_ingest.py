"""Streaming ingest front door: admission control, backpressure, load
shedding, windowed crawls, and window-consistent recovery.

The acceptance surface of the overload-robustness layer: a windowed crawl
over a frozen ingest window is BIT-EXACT vs a batch crawl over the same
admitted key set — with ingest running concurrently, under a duplicate-
delivery (flood) chaos schedule, and across a server kill/restart
mid-window.  Overload never corrupts: a flooding client is rejected
(retryable Overloaded) or its submissions shed into a seeded reservoir
sample; other clients' keys all land; every verdict is idempotent per
``sub_id`` so at-least-once delivery never double-admits.

Shapes mirror tests/test_resilience.py (L=5, d=1) so the crawl kernels
compile once across the suites.
"""

import asyncio

import numpy as np
import pytest

from fuzzyheavyhitters_tpu import native
from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.ops.ibdcf import IbDcfKeyBatch
from fuzzyheavyhitters_tpu.protocol import driver, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
    IngestOverloadedError,
    RpcLeader,
    WindowedIngest,
)
from fuzzyheavyhitters_tpu.resilience import admission
from fuzzyheavyhitters_tpu.resilience import policy as respolicy
from fuzzyheavyhitters_tpu.resilience.chaos import ChaosProxy, parse_faults
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 23231


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the front door is host-side glue over the same crawl
    kernels the other protocol suites compile."""
    yield


# ---------------------------------------------------------------------------
# admission: token bucket, quotas, shed policies (pure units)
# ---------------------------------------------------------------------------


def test_token_bucket_deterministic_under_manual_clock():
    """The rate limit is a pure function of the (clock, take) sequence —
    the determinism the gate/mirror protocol and the tests stand on."""
    clock = admission.ManualClock()
    tb = admission.TokenBucket(rate_per_s=10.0, burst=5, clock=clock)
    takes = [tb.try_take(1) for _ in range(7)]
    assert takes == [True] * 5 + [False, False]  # burst spent, no refill
    assert tb.wait_s(1) == pytest.approx(0.1)
    clock.advance(0.35)  # 3.5 tokens back
    assert [tb.try_take(1) for _ in range(4)] == [True, True, True, False]
    clock.advance(100.0)  # refill caps at burst
    assert tb.tokens <= 5 or tb.try_take(5)
    # an identical second run makes identical decisions
    clock2 = admission.ManualClock()
    tb2 = admission.TokenBucket(rate_per_s=10.0, burst=5, clock=clock2)
    takes2 = [tb2.try_take(1) for _ in range(7)]
    assert takes2 == takes


def test_admission_quota_and_capacity_verdicts():
    ctl = admission.AdmissionController(
        max_window_keys=10, client_quota=4, shed="reject", seed=1
    )
    wa = ctl.window(0)
    assert ctl.admit(wa, "a", 3).admitted
    v = ctl.admit(wa, "a", 3)  # 6 > quota 4
    assert not v.admitted and v.scope == "quota"
    assert ctl.admit(wa, "b", 4).admitted
    assert ctl.admit(wa, "c", 3).admitted  # 10/10
    v = ctl.admit(wa, "d", 1)
    assert not v.admitted and v.scope == "capacity"


def test_admission_rate_verdict_carries_retry_hint():
    clock = admission.ManualClock()
    ctl = admission.AdmissionController(
        max_window_keys=1000, rate_keys_per_s=10.0, burst_keys=4,
        shed="reject", seed=1, clock=clock,
    )
    wa = ctl.window(0)
    assert ctl.admit(wa, "a", 4).admitted
    v = ctl.admit(wa, "a", 4)
    assert not v.admitted and v.scope == "rate" and v.retry_after_s > 0
    clock.advance(v.retry_after_s)
    assert ctl.admit(wa, "a", 4).admitted  # the hint was honest


def test_quota_rejection_never_drains_the_shared_bucket():
    """A quota-doomed flooder's retries must not convert into `rate`
    rejections for honest clients: the quota precheck runs before any
    tokens are spent."""
    clock = admission.ManualClock()
    ctl = admission.AdmissionController(
        max_window_keys=1000, rate_keys_per_s=10.0, burst_keys=10,
        client_quota=4, shed="reject", seed=1, clock=clock,
    )
    wa = ctl.window(0)
    assert ctl.admit(wa, "flooder", 4).admitted  # quota spent (4 tokens)
    for _ in range(50):  # futile flood: every retry is quota-rejected
        assert ctl.admit(wa, "flooder", 4).scope == "quota"
    v = ctl.admit(wa, "honest", 4)  # 6 tokens still there
    assert v.admitted, v


def test_burst_oversize_chunk_gets_distinct_scope():
    """n_keys > burst can never fit the bucket: the verdict says so
    (scope 'burst') instead of promising a refill horizon that cannot
    be kept."""
    ctl = admission.AdmissionController(
        max_window_keys=10**6, rate_keys_per_s=100.0, burst_keys=8,
        shed="reject", seed=1, clock=admission.ManualClock(),
    )
    wa = ctl.window(0)
    v = ctl.admit(wa, "a", 9)
    assert not v.admitted and v.scope == "burst"


def test_reservoir_mode_rejects_mismatched_chunk_size():
    """The slot-table pool bound rests on uniform chunks: a mismatched
    size is capacity-rejected BEFORE any sampler draw, so the sampling
    stream is untouched by the refusal."""
    ctl = admission.AdmissionController(
        max_window_keys=4, shed="reservoir", seed=3
    )
    wa = ctl.window(0)
    for i in range(6):  # engage the reservoir with 1-key chunks
        ctl.admit(wa, f"c{i}", 1)
    seen_before = wa.reservoir.seen
    v = ctl.admit(wa, "big", 2)
    assert not v.admitted and v.scope == "capacity"
    assert wa.reservoir.seen == seen_before  # no draw consumed
    # an oversized FIRST submission is rejected too (never an IndexError)
    wa2 = ctl.window(1)
    v2 = ctl.admit(wa2, "huge", 99)
    assert not v2.admitted and v2.scope == "capacity"


def test_reservoir_shed_is_seed_reproducible():
    """Same seed + same offer sequence -> identical slot decisions (and
    the native library, when present, matches the pure-Python twin
    bit-for-bit)."""
    def run(seed):
        ctl = admission.AdmissionController(
            max_window_keys=4, shed="reservoir", seed=seed
        )
        wa = ctl.window(0)
        out = []
        for i in range(20):
            v = ctl.admit(wa, f"c{i}", 1)
            out.append((v.admitted, v.slot, v.shed))
        return out

    a, b = run(7), run(7)
    assert a == b
    assert a[:4] == [(True, None, False)] * 4  # fill phase appends
    assert any(s is not None for _, s, _ in a[4:])  # replacements happened
    assert run(8) != a  # a different seed samples differently


def test_native_reservoir_matches_python_twin_and_state_roundtrip():
    r = native.Reservoir(4, 12345)
    slots = r.offer(40)
    py = native.Reservoir.__new__(native.Reservoir)
    py.k, py._lib, py._handle = 4, None, None
    py._py, py._seen = native._PyXoshiro256(12345), 0
    np.testing.assert_array_equal(slots, py.offer(40))
    # state round-trips mid-stream: the restored sampler continues the
    # SAME stream (what the checkpoint carries across a server restart)
    st = r.state()
    cont = native.Reservoir.from_state(st)
    fresh = native.Reservoir(4, 12345)
    fresh.offer(40)
    np.testing.assert_array_equal(cont.offer(25), fresh.offer(25))


# ---------------------------------------------------------------------------
# chaos grammar: flood + slowclient
# ---------------------------------------------------------------------------


def test_parse_faults_flood_and_slowclient():
    faults = parse_faults(
        "ctl0:flood@msg=3,count=4;ctl0:slowclient@msg=1,ms=40,count=3"
    )
    assert [f.action for f in faults] == ["flood", "slowclient"]
    assert faults[0].count == 4 and faults[1].ms == 40


def test_chaos_flood_duplicates_the_frame():
    """A flood clause delivers the trigger frame 1 + count times — the
    at-least-once pathology the dedup machinery must absorb."""
    port_s, port_p = BASE_PORT + 70, BASE_PORT + 71

    async def run():
        got = []

        async def sink(reader, writer):
            try:
                while True:
                    got.append(await rpc._recv(reader))
            except (asyncio.IncompleteReadError, ConnectionResetError):
                pass

        srv = await asyncio.start_server(sink, "127.0.0.1", port_s)
        px = await ChaosProxy(
            "127.0.0.1", port_p, "127.0.0.1", port_s,
            parse_faults("t:flood@msg=2,count=2"), link="t",
        ).start()
        r, w = await asyncio.open_connection("127.0.0.1", port_p)
        await rpc._send(w, "one")
        await rpc._send(w, "two")  # duplicated twice -> arrives 3x
        await rpc._send(w, "three")
        await asyncio.sleep(0.3)
        assert got == ["one", "two", "two", "two", "three"]
        assert ("flood", "c2s", 2) in px.fired
        w.close()
        await px.stop()
        srv.close()
        await srv.wait_closed()

    asyncio.run(run())


def test_chaos_slowclient_trickles_frames():
    port_s, port_p = BASE_PORT + 72, BASE_PORT + 73

    async def run():
        async def echo(reader, writer):
            while True:
                await rpc._send(writer, await rpc._recv(reader))

        srv = await asyncio.start_server(echo, "127.0.0.1", port_s)
        px = await ChaosProxy(
            "127.0.0.1", port_p, "127.0.0.1", port_s,
            parse_faults("t:slowclient@msg=1,ms=80,count=2"), link="t",
        ).start()
        r, w = await asyncio.open_connection("127.0.0.1", port_p)
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        for m in ("a", "b", "c"):
            await rpc._send(w, m)
            assert await rpc._recv(r) == m
        # two frames trickled ~80 ms each; the third was full speed
        assert loop.time() - t0 >= 0.15
        assert [f[0] for f in px.fired] == ["slowclient", "slowclient"]
        await px.stop()
        srv.close()
        await srv.wait_closed()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# protocol harness
# ---------------------------------------------------------------------------


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=5,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=32,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, L, n):
    pts = np.concatenate(
        [np.full(n - 4, 11), rng.integers(0, 1 << L, size=4)]
    )[:, None]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


async def _start_servers(cfg, port_base, ckpt_dir=None):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port_base + 10, "127.0.0.1", port_base + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port_base, "127.0.0.1", port_base + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


async def _bring_up(cfg, port, ckpt_dir=None, dial0=None, budgets=None):
    live = {}
    live["s0"], live["s1"] = await _start_servers(cfg, port, ckpt_dir)
    d0 = ("127.0.0.1", port) if dial0 is None else dial0
    c0 = await rpc.CollectorClient.connect(*d0, budgets=budgets)
    c1 = await rpc.CollectorClient.connect(
        "127.0.0.1", port + 10, budgets=budgets
    )
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    return lead, c0, c1, live


async def _teardown(clients, live, *proxies):
    for px in proxies:
        await px.stop()
    for c in clients:
        await c.aclose()
    for s in live.values():
        await s.aclose()


def _chunk(k, sl):
    return tuple(np.asarray(x)[sl] for x in k)


def _hitters(res):
    return {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }


async def _batch_crawl(cfg, port, k0, k1, idx):
    """Reference: a batch (upload_keys + run) crawl over the key subset
    ``idx`` — what every windowed result must be bit-exact against."""
    lead, c0, c1, live = await _bring_up(cfg, port)
    await lead.upload_keys(
        IbDcfKeyBatch(*(np.asarray(x)[idx] for x in k0)),
        IbDcfKeyBatch(*(np.asarray(x)[idx] for x in k1)),
    )
    res = await lead.run(len(idx))
    await _teardown((c0, c1), live)
    return res


# ---------------------------------------------------------------------------
# submit_keys semantics: idempotency, Overloaded retry, shed
# ---------------------------------------------------------------------------


def test_replayed_submit_admits_exactly_once():
    """At-least-once delivery never double-admits: the same frame
    re-sent under its req_id is answered from the session dedup cache,
    and a NEW request reusing the sub_id (a recovery journal replay)
    answers the recorded verdict — one pool entry either way."""
    port = BASE_PORT

    async def run():
        cfg = _cfg(port)
        s0, s1 = await _start_servers(cfg, port)
        k0, _ = _client_keys(np.random.default_rng(3), 5, 6)
        chunk = _chunk(k0, slice(0, 2))
        r, w = await asyncio.open_connection("127.0.0.1", port)
        await rpc._send(w, (1, "__hello__", {"session": "ing", "epoch": 1}))
        await rpc._recv(r)
        frame = (
            2,
            "submit_keys",
            {"window": 0, "sub_id": "s-1", "client_id": "c", "keys": chunk},
        )
        await rpc._send(w, frame)
        first = (await rpc._recv(r))[1]
        assert first["admitted"] is True
        await rpc._send(w, frame)  # transport replay: same req_id
        assert (await rpc._recv(r))[1] == first
        # journal-style replay: NEW req_id, same sub_id
        await rpc._send(
            w,
            (3, "submit_keys",
             {"window": 0, "sub_id": "s-1", "client_id": "c",
              "keys": chunk}),
        )
        again = (await rpc._recv(r))[1]
        assert again["admitted"] is True and again.get("dup") is True
        assert len(s0._ingest_pools[0].entries) == 1  # admitted ONCE
        w.close()
        await s0.aclose()
        await s1.aclose()

    asyncio.run(run())


def test_overloaded_is_retryable_and_lands(monkeypatch):
    """Quota-free rate limiting: a burst over the bucket gets a
    retryable Overloaded verdict; the driver's backoff lands every key
    (counters prove rejections happened)."""
    port = BASE_PORT + 20

    async def run():
        cfg = _cfg(port)
        lead, c0, c1, live = await _bring_up(cfg, port)
        # a tight REAL-clock bucket on the gate: 2-key burst, 200 keys/s
        live["s0"]._admission = admission.AdmissionController(
            max_window_keys=10_000, rate_keys_per_s=200.0, burst_keys=2,
            shed="reject", seed=1,
        )
        k0, k1 = _client_keys(np.random.default_rng(3), 5, 12)
        wi = WindowedIngest(lead, checkpoint=False)
        for i in range(6):
            sl = slice(2 * i, 2 * i + 2)
            await wi.submit("c", _chunk(k0, sl), _chunk(k1, sl))
        stats = await wi.seal_window()
        rejected = wi.obs.counter_value("ingest_rejected")
        await _teardown((c0, c1), live)
        return stats, rejected

    stats, rejected = asyncio.run(run())
    assert stats["keys"] == 12  # every key landed eventually
    assert rejected >= 1  # ...through at least one backed-off retry


def test_flooding_client_is_limited_others_land():
    """Per-client quotas isolate a flooder: its submissions exhaust the
    quota and fail with IngestOverloadedError after the backoff budget,
    while the honest clients' keys ALL land and the window crawls
    bit-exact vs batch over exactly the admitted set."""
    port = BASE_PORT + 40
    rng = np.random.default_rng(7)
    k0, k1 = _client_keys(rng, 5, 12)

    async def run():
        cfg = _cfg(port, ingest_client_quota=4)
        lead, c0, c1, live = await _bring_up(cfg, port)
        wi = WindowedIngest(
            lead,
            checkpoint=False,
            # quota rejections never clear within a window: keep the
            # flooder's futile backoff short
            policy=respolicy.RetryPolicy(
                base_s=0.001, cap_s=0.002, attempts=3, rand=lambda: 0.0
            ),
        )
        # honest clients: 8 keys in 4 submissions, 2 clients
        for i in range(4):
            sl = slice(2 * i, 2 * i + 2)
            await wi.submit(f"honest{i % 2}", _chunk(k0, sl), _chunk(k1, sl))
        # the flooder: quota 4, tries to push 4 chunks of 2
        flooded = 0
        for i in range(4, 6):
            sl = slice(2 * i, 2 * i + 2)
            await wi.submit("flooder", _chunk(k0, sl), _chunk(k1, sl))
        for i in range(4):
            sl = slice(8, 10)
            try:
                await wi.submit("flooder", _chunk(k0, sl), _chunk(k1, sl))
            except IngestOverloadedError:
                flooded += 1
        stats = await wi.seal_window()
        res = await wi.crawl_window(0)
        rejected = wi.obs.counter_value("ingest_rejected")
        await _teardown((c0, c1), live)
        return res, stats, flooded, rejected

    res, stats, flooded, rejected = asyncio.run(run())
    assert flooded == 4  # every over-quota push failed loudly
    assert rejected >= 4
    assert stats["keys"] == 12  # honest 8 + flooder's first quota-ful 4
    want = asyncio.run(
        _batch_crawl(_cfg(port + 60), port + 60, k0, k1, list(range(12)))
    )
    assert _hitters(res) == _hitters(want)


def test_reservoir_shed_window_is_reproducible_sample(tmp_path):
    """Reservoir shed mode: over capacity the pool becomes a seeded
    uniform sample; the admitted slot table is exactly what a local
    reservoir with the same seed predicts, and the windowed crawl is
    bit-exact vs a batch crawl over that predicted sample."""
    port = BASE_PORT + 100
    rng = np.random.default_rng(11)
    k0, k1 = _client_keys(rng, 5, 12)
    cap = 6  # keys; submissions are 1 key each -> 6 slots

    async def run():
        cfg = _cfg(
            port, ingest_window_keys=cap, ingest_shed="reservoir",
            ingest_seed=42,
        )
        lead, c0, c1, live = await _bring_up(cfg, port)
        wi = WindowedIngest(lead, checkpoint=False)
        for i in range(12):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        stats = await wi.seal_window()
        res = await wi.crawl_window(0)
        await _teardown((c0, c1), live)
        return res, stats

    res, stats = asyncio.run(run())
    assert stats["keys"] == cap and stats["shed_keys"] == 12 - cap
    # predict the slot table with the same per-window seed derivation
    ctl = admission.AdmissionController(
        max_window_keys=cap, shed="reservoir", seed=42
    )
    wa = ctl.window(0)
    table = {}
    for i in range(12):
        v = ctl.admit(wa, f"c{i}", 1)
        if v.admitted:
            table[len(table) if v.slot is None else v.slot] = i
    idx = [table[s] for s in range(cap)]
    want = asyncio.run(_batch_crawl(_cfg(port + 40), port + 40, k0, k1, idx))
    assert _hitters(res) == _hitters(want)
    np.testing.assert_array_equal(res.counts, want.counts)


# ---------------------------------------------------------------------------
# windowed crawls: concurrency, status, report
# ---------------------------------------------------------------------------


def test_windowed_crawl_concurrent_ingest_bit_exact():
    """THE streaming contract: window 0's crawl runs on the frozen
    snapshot WHILE window 1 ingests (submit_keys bypasses the verb
    lock); both windows' results are bit-exact vs batch crawls over the
    same key subsets, the status verb reports front-door health, and
    the run report grows the ingest section."""
    port = BASE_PORT + 140
    rng = np.random.default_rng(7)
    k0, k1 = _client_keys(rng, 5, 12)

    async def run():
        cfg = _cfg(port)
        lead, c0, c1, live = await _bring_up(cfg, port)
        wi = WindowedIngest(lead, checkpoint=False)
        for i in range(6):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        await wi.seal_window()
        crawl = asyncio.create_task(wi.crawl_window(0))
        submitted_during = 0
        for i in range(6, 12):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
            if not crawl.done():
                submitted_during += 1
        res0 = await crawl
        st = await c0.call("status")
        await wi.seal_window()
        res1 = await wi.crawl_window(1)
        rep = obsreport.run_report([wi.obs])
        await _teardown((c0, c1), live)
        return res0, res1, st, rep, submitted_during

    res0, res1, st, rep, submitted_during = asyncio.run(run())
    want0 = asyncio.run(
        _batch_crawl(_cfg(port + 40), port + 40, k0, k1, list(range(6)))
    )
    want1 = asyncio.run(
        _batch_crawl(_cfg(port + 80), port + 80, k0, k1, list(range(6, 12)))
    )
    np.testing.assert_array_equal(res0.counts, want0.counts)
    np.testing.assert_array_equal(res0.paths, want0.paths)
    np.testing.assert_array_equal(res1.counts, want1.counts)
    np.testing.assert_array_equal(res1.paths, want1.paths)
    assert submitted_during >= 1  # ingest genuinely overlapped the crawl
    # status: front-door health
    ing = st["ingest"]
    assert ing["windows"]["1"]["sealed"] is False
    assert ing["queue_depth"] >= 1
    # run report: the ingest section
    assert rep["ingest"]["admitted"] == 12
    assert rep["ingest"]["windows"] == 2
    assert rep["ingest"]["keys_per_sec"] is None or (
        rep["ingest"]["keys_per_sec"] > 0
    )
    assert rep["ingest"]["window_crawl_seconds"] > 0


def test_window_seal_idempotent_and_sealed_window_refuses():
    port = BASE_PORT + 180

    async def run():
        cfg = _cfg(port)
        s0, s1 = await _start_servers(cfg, port)
        k0, _ = _client_keys(np.random.default_rng(3), 5, 6)
        await s0.submit_keys(
            {"window": 0, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        st1 = await s0.window_seal({"window": 0})
        st2 = await s0.window_seal({"window": 0})  # idempotent
        assert st1 == st2
        with pytest.raises(RuntimeError, match="sealed"):
            await s0.submit_keys(
                {"window": 0, "sub_id": "b", "client_id": "c",
                 "keys": _chunk(k0, slice(2, 4))}
            )
        # live-window bound refuses loudly, never grows silently
        for w in range(1, s0.cfg.ingest_windows_retained):
            await s0.submit_keys(
                {"window": w, "sub_id": f"w{w}", "client_id": "c",
                 "keys": _chunk(k0, slice(0, 1))}
            )
        with pytest.raises(RuntimeError, match="live-window bound"):
            await s0.submit_keys(
                {"window": 99, "sub_id": "x", "client_id": "c",
                 "keys": _chunk(k0, slice(0, 1))}
            )
        await s0.aclose()
        await s1.aclose()

    asyncio.run(run())


def test_ingest_report_section_absent_without_streaming():
    from fuzzyheavyhitters_tpu.obs import metrics as obsmetrics

    reg = obsmetrics.Registry("t-ing-absent")
    reg.count("keys_uploaded", 5)
    assert "ingest" not in obsreport.run_report([reg])


# ---------------------------------------------------------------------------
# recovery: ingest checkpoint/restore + kill mid-window
# ---------------------------------------------------------------------------


def test_ingest_pools_ride_checkpoint_restore(tmp_path):
    """The server-side recovery contract in isolation: pools (entries,
    recorded verdicts, reservoir RNG state) round-trip an ingest-only
    checkpoint; a replayed submit after restore admits exactly once and
    the shed stream continues seed-identically."""
    port = BASE_PORT + 220
    rng = np.random.default_rng(5)
    k0, _ = _client_keys(rng, 5, 12)

    async def run():
        cfg = _cfg(
            port, ingest_window_keys=4, ingest_shed="reservoir",
            ingest_seed=9,
        )
        s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        for i in range(8):
            await s.submit_keys(
                {"window": 0, "sub_id": f"s{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
        await s.window_seal({"window": 0})
        await s.submit_keys(
            {"window": 1, "sub_id": "w1", "client_id": "c",
             "keys": _chunk(k0, slice(0, 1))}
        )
        await s.tree_checkpoint({"level": -1, "ingest_only": True})

        s2 = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await s2.tree_restore({"level": -1})
        # identical pools
        for w in (0, 1):
            p1, p2 = s._ingest_pools[w], s2._ingest_pools[w]
            assert p1.stats() == p2.stats()
            for e1, e2 in zip(p1.entries, p2.entries):
                for a, b in zip(e1, e2):
                    np.testing.assert_array_equal(a, b)
        # replay dedups; fresh offers continue the SAME sampler stream
        dup = await s2.submit_keys(
            {"window": 1, "sub_id": "w1", "client_id": "c",
             "keys": _chunk(k0, slice(0, 1))}
        )
        assert dup.get("dup") is True
        for srv in (s, s2):
            for i in range(8, 12):
                await srv.submit_keys(
                    {"window": 1, "sub_id": f"n{i}", "client_id": "c",
                     "keys": _chunk(k0, slice(i, i + 1))}
                )
        st1 = await s.window_seal({"window": 1})
        st2 = await s2.window_seal({"window": 1})
        assert st1 == st2
        p1, p2 = s._ingest_pools[1], s2._ingest_pools[1]
        for e1, e2 in zip(p1.entries, p2.entries):
            for a, b in zip(e1, e2):
                np.testing.assert_array_equal(a, b)

    asyncio.run(run())


def test_restored_gate_reservoir_stream_survives_journal_replay(tmp_path):
    """The shed stream is window-consistent across a GATE restart: a
    restored gate rebuilt from the checkpoint + a mirror-form journal
    replay of the post-checkpoint submissions makes the SAME live
    decisions afterwards as the never-faulted gate (the replayed draws
    advance the restored sampler)."""
    port = BASE_PORT + 340
    rng = np.random.default_rng(5)
    k0, _ = _client_keys(rng, 5, 12)

    async def run():
        cfg = _cfg(
            port, ingest_window_keys=4, ingest_shed="reservoir",
            ingest_seed=21,
        )
        s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        for i in range(6):  # fill + engage
            await s.submit_keys(
                {"window": 0, "sub_id": f"s{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
        await s.tree_checkpoint({"level": -1, "ingest_only": True})
        # post-checkpoint traffic (the journal's tail) + future verdicts
        # on the never-faulted gate
        journal = []
        for i in range(6, 9):
            r = await s.submit_keys(
                {"window": 0, "sub_id": f"s{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
            journal.append((f"s{i}", i, r))
        want_future = [
            await s.submit_keys(
                {"window": 0, "sub_id": f"f{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
            for i in range(9, 12)
        ]
        # the restarted gate: restore + mirror-form journal replay
        s2 = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await s2.tree_restore({"level": -1})
        for sub_id, i, r in journal:
            await s2.submit_keys(
                {"window": 0, "sub_id": sub_id, "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1)),
                 "mirror": {"slot": r.get("slot"),
                            "shed": bool(r.get("shed"))}}
            )
        got_future = [
            await s2.submit_keys(
                {"window": 0, "sub_id": f"f{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
            for i in range(9, 12)
        ]
        assert got_future == want_future
        st1 = await s.window_seal({"window": 0})
        st2 = await s2.window_seal({"window": 0})
        assert st1 == st2

    asyncio.run(run())


def test_gate_reservoir_stream_survives_replay_without_engaged_checkpoint(
    tmp_path,
):
    """The harder recovery case: the reservoir engaged only AFTER the
    last checkpoint, so there is no RNG state to restore — the replayed
    draws are banked (pending_draws) and the re-engagement fast-forwards
    past them, keeping the live stream identical to the fault-free
    gate's."""
    port = BASE_PORT + 360
    rng = np.random.default_rng(5)
    k0, _ = _client_keys(rng, 5, 12)

    async def run():
        cfg = _cfg(
            port, ingest_window_keys=3, ingest_shed="reservoir",
            ingest_seed=33,
        )
        s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        for i in range(2):  # fill only: reservoir NOT engaged yet
            await s.submit_keys(
                {"window": 0, "sub_id": f"s{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
        await s.tree_checkpoint({"level": -1, "ingest_only": True})
        journal = []
        for i in range(2, 8):  # fill completes + engages post-checkpoint
            r = await s.submit_keys(
                {"window": 0, "sub_id": f"s{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
            journal.append((f"s{i}", i, r))
        want = [
            await s.submit_keys(
                {"window": 0, "sub_id": f"f{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
            for i in range(8, 12)
        ]
        s2 = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await s2.tree_restore({"level": -1})
        for sub_id, i, r in journal:
            await s2.submit_keys(
                {"window": 0, "sub_id": sub_id, "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1)),
                 "mirror": {"slot": r.get("slot"),
                            "shed": bool(r.get("shed"))}}
            )
        got = [
            await s2.submit_keys(
                {"window": 0, "sub_id": f"f{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
            for i in range(8, 12)
        ]
        assert got == want
        assert (await s.window_seal({"window": 0})) == (
            await s2.window_seal({"window": 0})
        )

    asyncio.run(run())


def test_idle_sealed_windows_are_evicted_not_wedged():
    """A quiet stretch — many consecutive EMPTY sealed windows — must
    not exhaust the live-window bound: sealed empty pools (never
    window_load-ed) evict oldest-first when a new window needs the
    slot."""
    port = BASE_PORT + 380

    async def run():
        cfg = _cfg(port, ingest_windows_retained=3)
        s = rpc.CollectorServer(0, cfg)
        for w in range(8):  # far past the bound: every seal is idle
            st = await s.window_seal({"window": w})
            assert st["keys"] == 0 and st["sealed"]
        k0, _ = _client_keys(np.random.default_rng(3), 5, 6)
        r = await s.submit_keys(
            {"window": 8, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        assert r["admitted"] is True
        assert len(s._ingest_pools) <= 3

    asyncio.run(run())


def test_restore_refuses_torn_ingest_tail(tmp_path):
    """Validate-before-mutate covers the ing_* fields: a blob whose
    ingest tail is truncated refuses loudly and leaves live state
    untouched."""
    port = BASE_PORT + 260
    rng = np.random.default_rng(5)
    k0, _ = _client_keys(rng, 5, 6)

    async def run():
        cfg = _cfg(port)
        s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await s.submit_keys(
            {"window": 0, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        await s.tree_checkpoint({"level": -1, "ingest_only": True})
        path = s._ckpt_path(-1)
        with np.load(path) as z:
            blob = {k: z[k] for k in z.files}
        del blob["ing0_sub_codes"]  # tear the verdict table
        with open(path, "wb") as f:
            np.savez(f, **blob)
        s2 = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        with pytest.raises(RuntimeError, match="ingest|truncated"):
            await s2.tree_restore({"level": -1})
        assert s2._ingest_pools == {}  # nothing mutated
        await s.aclose()

    asyncio.run(run())


def test_e2e_kill_mid_window_under_flood_bit_exact(rng, tmp_path):
    """THE acceptance scenario: sustained ingest concurrent with a
    windowed crawl, a duplicate-delivery flood on the gate link, and
    server 1 killed + restarted MID-WINDOW — the window results stay
    bit-exact vs fault-free batch crawls over the same admitted sets,
    and the recovery + ingest counters land in the run report."""
    L, n = 5, 12
    port = BASE_PORT + 300
    pxport = port + 20
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port)
    ck = tmp_path / "ck"
    ck.mkdir()

    async def run():
        # flood: duplicate an early gate-bound frame 3x (at-least-once
        # delivery made real; the session dedup absorbs it)
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:flood@msg=6,count=3"), link="ctl0",
        ).start()
        lead, c0, c1, live = await _bring_up(
            cfg, port, ckpt_dir=str(ck), dial0=("127.0.0.1", pxport)
        )
        wi = WindowedIngest(lead)  # checkpointing ON
        for i in range(6):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        await wi.seal_window()

        async def assassin():
            # kill s1 the moment the window-0 crawl is underway (its
            # frontier roots at tree_init, right after window_load)
            while live["s1"].frontier is None:
                await asyncio.sleep(0.01)
            await live["s1"].aclose()
            await asyncio.sleep(0.3)
            live["s1"] = rpc.CollectorServer(1, cfg, ckpt_dir=str(ck))
            await live["s1"].start(
                "127.0.0.1", port + 10, "127.0.0.1", port + 11
            )

        kill = asyncio.create_task(assassin())
        crawl = asyncio.create_task(wi.crawl_window(0))
        for i in range(6, 12):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
            await asyncio.sleep(0.02)  # sustained, not a burst
        res0 = await crawl
        await kill
        await wi.seal_window()
        res1 = await wi.crawl_window(1)
        rep = obsreport.run_report([wi.obs, lead.obs, live["s0"].obs])
        await _teardown((c0, c1), live, px)
        return res0, res1, rep, px.fired

    res0, res1, rep, fired = asyncio.run(run())
    want0 = asyncio.run(
        _batch_crawl(_cfg(port + 40), port + 40, k0, k1, list(range(6)))
    )
    want1 = asyncio.run(
        _batch_crawl(_cfg(port + 60), port + 60, k0, k1, list(range(6, 12)))
    )
    np.testing.assert_array_equal(res0.counts, want0.counts)
    np.testing.assert_array_equal(res0.paths, want0.paths)
    np.testing.assert_array_equal(res1.counts, want1.counts)
    np.testing.assert_array_equal(res1.paths, want1.paths)
    assert any(f[0] == "flood" for f in fired)  # the flood actually fired
    # the kill actually happened AND was recovered, visibly
    assert rep["ingest"]["admitted"] == n
    assert rep["ingest"]["windows"] == 2
    ing_reg = rep["registries"]["ingest"]["counters"]
    assert ing_reg["ingest_recoveries"]["total"] >= 1
    assert ing_reg["ingest_journal_replays"]["total"] >= 1
