"""PRG tests: JAX/NumPy bit-exactness, reference semantics (mask quirk,
length-doubling interface), statistical sanity (ref test model: prg.rs:337-373
non-degeneracy tests)."""

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import prg


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Unit-scale module: run on the CPU backend (see conftest)."""
    yield



def test_jax_matches_numpy_block(rng):
    blocks = rng.integers(0, 2**32, size=(64, 4), dtype=np.uint32)
    out_np = prg.np_chacha_block(blocks)
    out_jax = np.asarray(prg.chacha_block(blocks))
    np.testing.assert_array_equal(out_np, out_jax)


def test_unrolled_rounds_bit_exact(rng):
    """CHACHA_UNROLL (the TPU hot-path form, bin/server.py + bench.py) and
    the default scan form compute identical blocks."""
    import jax

    blocks = rng.integers(0, 2**32, size=(8, 4), dtype=np.uint32)
    want = prg.np_chacha_block(blocks)
    old = prg.CHACHA_UNROLL
    try:
        prg.CHACHA_UNROLL = True
        # fresh trace: chacha_block reads the flag at trace time
        # fhh-lint: disable=recompile-churn (a fresh trace IS the test)
        got = np.asarray(jax.jit(lambda b: prg.chacha_block(b))(blocks))
    finally:
        prg.CHACHA_UNROLL = old
    np.testing.assert_array_equal(got, want)


def test_expand_matches_bytes_interface(rng):
    for _ in range(8):
        seed = rng.bytes(16)
        s_l, s_r, bits, y_bits = prg.np_expand_bytes(seed)
        arr = prg.seeds_from_bytes(seed)[0]
        jl, jr, jb, jy = prg.expand(arr)
        assert prg.seed_to_bytes(jl) == s_l
        assert prg.seed_to_bytes(jr) == s_r
        assert tuple(np.asarray(jb)) == bits
        assert tuple(np.asarray(jy)) == y_bits


def test_rfc8439_quarter_round():
    # RFC 8439 §2.1.1 test vector for the quarter round.
    import jax.numpy as jnp

    a = jnp.uint32(0x11111111)
    b = jnp.uint32(0x01020304)
    c = jnp.uint32(0x9B8D6F43)
    d = jnp.uint32(0x01234567)
    a, b, c, d = prg._quarter_round(a, b, c, d)
    assert int(a) == 0xEA2A92F4
    assert int(b) == 0xCB1CF8CE
    assert int(c) == 0x4581472E
    assert int(d) == 0x5881C4BB


def test_mask_quirk(rng):
    """Seeds differing only in the low nibble of byte 0 expand identically
    (prg.rs:97), and the observed-mode t/y bits are the constants (1,1)
    (prg.rs:103-104)."""
    seed = rng.integers(0, 2**32, size=(4,), dtype=np.uint32)
    seed2 = seed.copy()
    seed2[0] ^= np.uint32(0x0000000B)  # flip masked-away bits
    l1, r1, b1, y1 = prg.expand(seed, derived_bits=False)
    l2, r2, b2, y2 = prg.expand(seed2, derived_bits=False)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    assert np.all(np.asarray(b1)) and np.all(np.asarray(y1))
    # the seed mask applies in BOTH modes (prg.rs:97 masks before expanding)
    ld, _, _, _ = prg.expand(seed, derived_bits=True)
    ld2, _, _, _ = prg.expand(seed2, derived_bits=True)
    np.testing.assert_array_equal(np.asarray(ld), np.asarray(ld2))


def test_children_differ_and_nondegenerate(rng):
    """Left/right children differ from each other and the parent; bit balance
    across many seeds is ~50% (ref: prg.rs:337-373)."""
    seeds = rng.integers(0, 2**32, size=(4096, 4), dtype=np.uint32)
    s_l, s_r, _, _ = prg.expand(seeds)
    s_l, s_r = np.asarray(s_l), np.asarray(s_r)
    assert not np.any(np.all(s_l == s_r, axis=-1))
    assert not np.any(np.all(s_l == seeds, axis=-1))
    # per-bit balance over the batch
    bits = np.unpackbits(np.ascontiguousarray(s_l).view(np.uint8), axis=-1)
    frac = bits.mean(axis=0)
    assert np.all(np.abs(frac - 0.5) < 0.05)


def test_derived_bits_mode(rng):
    seeds = rng.integers(0, 2**32, size=(2048, 4), dtype=np.uint32)
    _, _, bits, y_bits = prg.expand(seeds, derived_bits=True)
    for arr in (np.asarray(bits), np.asarray(y_bits)):
        frac = arr.mean(axis=0)
        assert np.all(np.abs(frac - 0.5) < 0.08)


def test_stream_words(rng):
    seed = rng.integers(0, 2**32, size=(4,), dtype=np.uint32)
    w = np.asarray(prg.stream_words(seed, 40))
    assert w.shape == (40,)
    # deterministic and prefix-consistent
    w2 = np.asarray(prg.stream_words(seed, 16))
    np.testing.assert_array_equal(w[:16], w2)
    # distinct seeds -> distinct streams
    seed2 = seed.copy()
    seed2[3] ^= np.uint32(1)
    assert not np.array_equal(w, np.asarray(prg.stream_words(seed2, 40)))


def test_oracle_accepts_chacha_prg(rng):
    """The spec oracle runs unchanged with the ChaCha PRG injected —
    the device PRG is a drop-in for the protocol semantics."""
    import oracle

    alpha = rng.integers(0, 2, size=8).astype(bool)
    k0, k1 = oracle.gen_ibdcf(alpha, True, rng, prg=prg.np_expand_bytes)
    for x in range(256):
        xb = np.array([(x >> (7 - i)) & 1 == 1 for i in range(8)])
        s0 = oracle.eval_prefix(k0, xb, prg=prg.np_expand_bytes)
        s1 = oracle.eval_prefix(k1, xb, prg=prg.np_expand_bytes)
        alpha_int = int("".join("1" if b else "0" for b in alpha), 2)
        assert (oracle.share_bit(s0) ^ oracle.share_bit(s1)) == (x < alpha_int)
