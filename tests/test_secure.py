"""Secure data-plane tests: the GC+OT 2PC pipeline sans-IO, the string
extraction's equivalence with the trusted compare, and a full two-server
socket run in secure mode that must (a) match trusted-mode heavy hitters
bit-for-bit and (b) never send a packed share-bit tensor to the peer."""

import asyncio
import secrets as pysecrets

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import gc, ibdcf, otext
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.protocol import collect, driver, rpc, secure
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """All tests in this module run on the CPU backend (see conftest)."""
    yield


@pytest.fixture(scope="module")
def ot_pair():
    return otext.inprocess_pair()


@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
def test_pipeline_sans_io(ot_pair, rng, field):
    """garble -> Δ-OT labels -> eval -> b2a: v0 - v1 == [x == y] per test
    (the r1-r0=1 trick, ref: collect.rs:439-471; F255 payloads ride two
    blocks — the BlockPair double OT of collect.rs:775-916)."""
    snd, rcv = ot_pair
    B, S = 16, 33  # matches test_gc's delta shape -> shared compiles
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    flip = rng.integers(0, 2, size=B).astype(bool)
    y[flip, rng.integers(0, S, size=B)[flip]] ^= True
    eq = np.all(x == y, axis=1)

    gc_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    b2a_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    u, t_rows = secure.ev_step1(rcv, y)
    batch, mask = secure.gb_step1(snd, np.asarray(u), x, gc_seed)
    e = secure.ev_step2(batch, t_rows, B, S)
    np.testing.assert_array_equal(np.asarray(mask) ^ np.asarray(e), eq)
    u2, t2, idx0 = secure.ev_step3(rcv, np.asarray(e))
    c0, c1, v0 = secure.gb_step2(snd, np.asarray(u2), mask, b2a_seed, field)
    v1 = secure.ev_step4(rcv, t2, idx0, np.asarray(c0), np.asarray(c1), e, field)
    diff = np.asarray(field.canon(field.sub(v0, v1)))
    if field is F255:
        np.testing.assert_array_equal(diff[:, 0], eq.astype(np.uint32))
        assert not diff[:, 1:].any()
    else:
        np.testing.assert_array_equal(diff, eq.astype(np.uint64))


@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
@pytest.mark.parametrize("garbler", [0, 1])
def test_pipeline_fused_sans_io(ot_pair, rng, field, garbler):
    """The FUSED flow (b2a payloads under the GC output labels — one
    protocol round trip, secure.gb_step_fused/ev_open_fused): v0 - v1 ==
    [x == y] per test REGARDLESS of which side garbles (the r1 = r0 ± 1
    sign trick), exactly like the two-round flow it replaces."""
    snd, rcv = ot_pair
    B, S = 16, 33
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    flip = rng.integers(0, 2, size=B).astype(bool)
    y[flip, rng.integers(0, S, size=B)[flip]] ^= True
    eq = np.all(x == y, axis=1)

    gc_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    b2a_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    u, t_rows, idx0 = secure.ev_step1_fused(rcv, y)
    msg, v_gb = secure.gb_step_fused(
        snd, np.asarray(u), x, gc_seed, b2a_seed, field, garbler
    )
    v_ev = secure.ev_open_fused(rcv, t_rows, np.asarray(msg), B, S, field, idx0)
    v0, v1 = (v_gb, v_ev) if garbler == 0 else (v_ev, v_gb)
    diff = np.asarray(field.canon(field.sub(v0, v1)))
    want = eq.astype(np.uint64)
    if field is F255:
        np.testing.assert_array_equal(diff[:, 0], want.astype(np.uint32))
        assert not diff[:, 1:].any()
    else:
        np.testing.assert_array_equal(diff, want)


def test_gf128_double_linearity_and_carry():
    """gf128_double: shift-with-carry semantics and linearity over XOR —
    the properties the 1-of-4 pad-offset distinctness proof rests on."""
    rng = np.random.default_rng(7)
    x = rng.integers(0, 2**32, size=(8, 4), dtype=np.uint32)
    y = rng.integers(0, 2**32, size=(8, 4), dtype=np.uint32)
    dbl = lambda a: np.asarray(otext.gf128_double(a))
    # linearity: 2(x ^ y) == 2x ^ 2y
    np.testing.assert_array_equal(dbl(x ^ y), dbl(x) ^ dbl(y))
    # no-carry case: plain 128-bit left shift
    lo = np.array([[0x40000000, 1, 0x80000000, 0x3FFFFFFF]], np.uint32)
    np.testing.assert_array_equal(
        dbl(lo), [[0x80000000, 2, 0, 0x7FFFFFFF]]
    )
    # carry case: x^127 wraps to the reduction constant 0x87
    hi = np.zeros((1, 4), np.uint32)
    hi[0, 3] = 0x80000000
    np.testing.assert_array_equal(dbl(hi), [[0x87, 0, 0, 0]])
    # doubling is invertible (linear + injective on a sample)
    assert len({bytes(r) for r in dbl(x)}) == len(x)
    # {0, s, 2s, 3s} pairwise distinct for s != 0 — the 4 pad offsets
    s = rng.integers(1, 2**32, size=(1, 4), dtype=np.uint32)
    offs = [np.zeros((1, 4), np.uint32), s, dbl(s), s ^ dbl(s)]
    assert len({bytes(o[0]) for o in offs}) == 4


@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
@pytest.mark.parametrize("garbler", [0, 1])
def test_pipeline_ot4_sans_io(ot_pair, rng, field, garbler):
    """The S = 2 fast path (1-of-4 chosen-payload OT, secure.gb_step_ot4 /
    ev_open_ot4): v0 - v1 == [x == y] per test on both garbling sides —
    the same contract as the GC fused flow it replaces for 1-dim crawls."""
    snd, rcv = ot_pair
    B, S = 64, 2
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    flip = rng.integers(0, 2, size=B).astype(bool)
    y[flip, rng.integers(0, S, size=B)[flip]] ^= True
    eq = np.all(x == y, axis=1)

    b2a_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    u, t_rows, idx0 = secure.ev_step1_fused(rcv, y)
    msg, v_snd = secure.gb_step_ot4(
        snd, np.asarray(u), x, b2a_seed, field, garbler
    )
    v_rcv = secure.ev_open_ot4(
        rcv, t_rows, y, np.asarray(msg), B, field, idx0
    )
    v0, v1 = (v_snd, v_rcv) if garbler == 0 else (v_rcv, v_snd)
    diff = np.asarray(field.canon(field.sub(v0, v1)))
    want = eq.astype(np.uint64)
    if field is F255:
        np.testing.assert_array_equal(diff[:, 0], want.astype(np.uint32))
        assert not diff[:, 1:].any()
    else:
        np.testing.assert_array_equal(diff, want)


def test_ot4_receiver_learns_exactly_one_payload(ot_pair, rng):
    """1-of-4 privacy shape: decrypting with a WRONG choice (a string the
    receiver does not hold rows for) yields pad-garbage, not a payload —
    i.e. the table holds exactly one opening per receiver."""
    snd, rcv = ot_pair
    B = 32
    x = rng.integers(0, 2, size=(B, 2)).astype(bool)
    y = rng.integers(0, 2, size=(B, 2)).astype(bool)
    b2a_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    u, t_rows, idx0 = secure.ev_step1_fused(rcv, y)
    msg, _ = secure.gb_step_ot4(snd, np.asarray(u), x, b2a_seed, FE62, 0)
    good = np.asarray(FE62.canon(
        secure.ev_open_ot4(rcv, t_rows, y, np.asarray(msg), B, FE62, idx0)
    ))
    bad = np.asarray(FE62.canon(
        secure.ev_open_ot4(rcv, t_rows, ~y, np.asarray(msg), B, FE62, idx0)
    ))
    # wrong-choice openings decrypt the wrong row with the wrong pad:
    # they must not reproduce the correct payloads (w.h.p.)
    assert (good != bad).sum() >= B - 1


def test_evaluator_share_is_masked(ot_pair, rng):
    """The evaluator's GC output alone must not reveal equality: its share
    differs from the plaintext wherever the garbler's mask bit is set."""
    snd, rcv = ot_pair
    B, S = 16, 33  # same shape as the pipeline test (one garble program)
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    u, t_rows = secure.ev_step1(rcv, x)  # y == x: all equal
    gc_seed = np.frombuffer(pysecrets.token_bytes(16), "<u4")
    batch, mask = secure.gb_step1(snd, np.asarray(u), x, gc_seed)
    e = np.asarray(secure.ev_step2(batch, t_rows, B, S))
    m = np.asarray(mask)
    assert m.any() and not m.all()
    np.testing.assert_array_equal(e, ~m)  # eq=1 everywhere -> e = 1 ^ mask


def test_child_strings_match_pattern_masks(rng):
    """String equality on extracted per-pattern strings ⇔ the packed-mask
    compare used by the trusted path (same membership predicate)."""
    d = 2
    F, N = 5, 17
    p0 = rng.integers(0, 1 << (4 * d), size=(F, N), dtype=np.uint32)
    p1 = rng.integers(0, 1 << (4 * d), size=(F, N), dtype=np.uint32)
    # force some exact agreements
    p1[:, ::3] = p0[:, ::3]
    s0 = np.asarray(secure.child_strings(jnp.asarray(p0), d))  # [F,C,N,S]
    s1 = np.asarray(secure.child_strings(jnp.asarray(p1), d))
    eq_strings = np.all(s0 == s1, axis=-1)  # [F, C, N]
    masks = collect.pattern_masks(d)
    diff = p0 ^ p1
    eq_masks = (diff[:, None, :] & masks[None, :, None]) == 0
    np.testing.assert_array_equal(eq_strings, eq_masks)


def test_node_share_sums_gating(rng):
    vals = rng.integers(0, 100, size=(2, 2, 6)).astype(np.uint64)
    w = np.ones((2, 2, 6), bool)
    w[0, 0, 0] = False  # dead client contribution
    w[1, :, :] = False  # dead node
    out = np.asarray(secure.node_share_sums(FE62, jnp.asarray(vals), jnp.asarray(w)))
    assert out[0, 0] == vals[0, 0, 1:].sum()
    assert out[0, 1] == vals[0, 1].sum()
    assert not out[1].any()


# ---------------------------------------------------------------------------
# Full two-server socket run in secure mode (ref test shape:
# equalitytest.rs:222-266 — both roles in one process over a duplex pipe)
# ---------------------------------------------------------------------------

BASE_PORT = 21331


def _cfg(port_base=BASE_PORT, **kw):
    defaults = dict(
        data_len=5,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=32,
    )
    defaults.update(kw)
    return Config(**defaults)


async def _run_protocol(cfg, keys0, keys1, nreqs):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    host0, port0 = cfg.server0.rsplit(":", 1)
    host1, port1 = cfg.server1.rsplit(":", 1)
    port0, port1 = int(port0), int(port1)
    peer_port = port1 + 1
    t1 = asyncio.create_task(s1.start(host1, port1, host1, peer_port))
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(s0.start(host0, port0, host1, peer_port))
    c0 = await rpc.CollectorClient.connect(host0, port0)
    c1 = await rpc.CollectorClient.connect(host1, port1)
    await asyncio.gather(t0, t1)
    lead = RpcLeader(cfg, c0, c1)
    try:
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        await lead.upload_keys(keys0, keys1)
        return await lead.run(nreqs)
    finally:
        # a leaked listener (held alive by reference cycles until a gc
        # pass) keeps its port bound into LATER tests — close everything
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()


def _client_keys(rng, L, n):
    pts = np.concatenate([np.full(n - 4, 11), rng.integers(0, 1 << L, size=4)])[
        :, None
    ]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


@pytest.mark.parametrize("eq_ot4", [True, False], ids=["ot4", "gc"])
def test_secure_socket_run_matches_trusted(rng, monkeypatch, eq_ot4):
    """n_dims = 1 -> S = 2: runs the 1-of-4 fast path (the production
    default) AND the GC parity path through the full socket flow."""
    monkeypatch.setattr(secure, "EQ_OT4", eq_ot4)
    L, n = 5, 12
    port_base = BASE_PORT + (0 if eq_ot4 else 40)  # distinct ports per run
    k0, k1 = _client_keys(rng, L, n)

    # record every data/control-plane payload and every packed tensor
    sent, packed_tensors = [], []
    real_send = rpc._send
    real_expand = collect.expand_share_bits

    async def spy_send(writer, obj, count=None, flush=True):
        sent.append(obj)
        await real_send(writer, obj, count, flush)

    def spy_expand(keys, frontier, level, **kw):
        packed, children = real_expand(keys, frontier, level, **kw)
        packed_tensors.append(np.asarray(packed))
        return packed, children

    monkeypatch.setattr(rpc, "_send", spy_send)
    monkeypatch.setattr(collect, "expand_share_bits", spy_expand)

    cfg = _cfg(port_base=port_base, secure_exchange=True)
    res = asyncio.run(_run_protocol(cfg, k0, k1, n))
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }

    # trusted-mode oracle (colocated driver)
    s0, s1 = driver.make_servers(k0, k1)
    want_res = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=cfg.f_max).run(
        nreqs=n, threshold=cfg.threshold
    )
    want = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(want_res.decode_ints(), want_res.counts)
    }
    assert got == want and got

    # no packed share-bit tensor ever crossed a socket
    assert packed_tensors
    def leaves(obj):
        if isinstance(obj, np.ndarray):
            yield obj
        elif isinstance(obj, (tuple, list)):
            for o in obj:
                yield from leaves(o)
        elif isinstance(obj, dict):
            for o in obj.values():
                yield from leaves(o)

    for obj in sent:
        for leaf in leaves(obj):
            for p in packed_tensors:
                assert not (
                    leaf.shape == p.shape and leaf.dtype == p.dtype
                    and np.array_equal(leaf, p)
                ), "packed share-bit tensor crossed the wire in secure mode"
