"""Two-PROCESS mesh execution: the multi-host seams run for real.

Spawns two worker processes (tests/mp_worker.py) that join one JAX
distributed runtime over a local coordinator — 4 virtual CPU devices
each, a global 2×4 mesh with one mesh ROW per process (the amazon.json
two-host shape).  Each process supplies only its own party's key batch
(MeshRunner.from_process_local), so the ingest seam
(make_array_from_process_local_data) and, in secure mode, the
agreed-from-process-0 session material are exercised exactly as a real
two-host deployment would."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver
from fuzzyheavyhitters_tpu.utils import bits as bitutils

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(secure: bool, port: int):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=4"
        " --xla_backend_optimization_level=1"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "tests", "mp_worker.py"),
             str(pid), "2", f"127.0.0.1:{port}", "1" if secure else "0"],
            env=env, cwd=_REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        if (
            p.returncode != 0
            and "Multiprocess computations aren't implemented" in err
        ):
            # environment limitation, not a regression: this jaxlib's
            # XLA:CPU backend refuses cross-process collectives
            # ("Multiprocess computations aren't implemented on the CPU
            # backend"), so the two-host seam cannot execute on a
            # CPU-only host at all.  The test stays live — a TPU session
            # (or a jaxlib whose CPU collectives work) runs it for real.
            for q in procs:
                q.kill()
            pytest.xfail(
                "jax CPU backend refuses multiprocess collectives on "
                "this host (XlaRuntimeError: Multiprocess computations "
                "aren't implemented on the CPU backend)"
            )
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][-1]
        outs.append(json.loads(line[len("RESULT "):]))
    return outs


def _oracle():
    """Colocated-driver heavy hitters for the worker's scenario."""
    rng = np.random.default_rng(7)
    L, d, n = 6, 2, 32
    centers = rng.integers(0, 1 << L, size=(3, d))
    pts = centers[rng.integers(0, 3, size=n)] + rng.integers(-1, 2, size=(n, d))
    pts = np.clip(pts, 0, (1 << L) - 1)
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
    with jax.default_device(jax.devices("cpu")[0]):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=128)
        res = lead.run(nreqs=n, threshold=0.1)
    return sorted(
        [[int(v) for v in row] + [int(c)]
         for row, c in zip(res.decode_ints(), res.counts)]
    )


def test_two_process_mesh_trusted():
    outs = _spawn(secure=False, port=21941)
    want = _oracle()
    assert want  # non-degenerate
    for o in outs:
        assert o["hitters"] == want, o


def test_two_process_mesh_secure():
    """The full GC+OT 2PC across two processes — session material agreed
    from process 0 (the executable form of the multi-host secure seam;
    ~80 s of CPU compile on this 1-core host, kept in the default suite
    because it is the only cross-process secure-mode coverage)."""
    outs = _spawn(secure=True, port=21951)
    want = _oracle()
    for o in outs:
        assert o["hitters"] == want, o
