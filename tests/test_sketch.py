"""Malicious-secure sketch + Beaver MPC verification tests.

Covers: payload-DPF one-hot reconstruction, honest sketches passing at
every level (FE62 inner + F255 last), malformed-key detection for each of
the three check relations, batch chunking via sketch_batch_size, and the
end-to-end exclusion of a cheating client from counts through the
alive_keys gate — over the full two-server RPC protocol at
sketch_batch_size=100000 (the north-star setting)."""

import asyncio

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import dpf, ibdcf
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.protocol import mpc, rpc, sketch
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Unit-scale module: run on the CPU backend (see conftest)."""
    yield


def _gen(rng, N=6, L=5):
    alpha = rng.integers(0, 2, size=(N, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(N, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    shared = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, alpha, FE62, F255, cseed)
    return alpha, sk0, sk1, shared, L


def test_dpf_one_hot_reconstruction(rng):
    """share0 + share1 is the payload at the client's prefix, 0 elsewhere —
    at every level, both fields (the BGI payload-DPF contract the sketch
    rides on, ref: sketch.rs:8-24)."""
    N, L, lanes = 6, 5, 2  # (N, L) match _gen so eval programs compile once
    alpha = rng.integers(0, 2, size=(N, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(N, 2, 4), dtype=np.uint32)
    vals = jnp.asarray(rng.integers(1, 100, size=(N, L - 1, lanes)).astype(np.uint64))
    vlast = F255.sample(
        jnp.asarray(rng.integers(0, 2**32, size=(N, lanes, 8), dtype=np.uint32))
    )
    k0, k1 = dpf.gen_pair(seeds, alpha, vals, vlast, FE62, F255, lanes)
    sk0 = sketch.SketchKeyBatch(
        k0, None, None, None, None, None, None
    )
    sk1 = sketch.SketchKeyBatch(k1, None, None, None, None, None, None)
    for j in range(L):
        fld = FE62 if j < L - 1 else F255
        s0 = sketch.eval_level_full(sk0, j, FE62, F255, L)
        s1 = sketch.eval_level_full(sk1, j, FE62, F255, L)
        rec = np.asarray(fld.canon(fld.add(s0, s1)))
        for i in range(N):
            idx = int("".join("1" if b else "0" for b in alpha[i, : j + 1]), 2)
            want = np.zeros_like(rec[i])
            want[idx] = np.asarray(vals[i, j] if j < L - 1 else vlast[i])
            np.testing.assert_array_equal(rec[i], want, err_msg=f"lvl {j} client {i}")


def test_honest_sketches_pass_all_levels(rng):
    _, sk0, sk1, shared, L = _gen(rng)
    for level in range(L):
        ok = sketch.verify_level(sk0, sk1, level, FE62, F255, L, shared)
        assert ok.all(), (level, ok)


def test_malformed_value_cw_flagged(rng):
    """A client handing both servers a non-unit payload (additive attack)
    fails check 1 at exactly the tampered level, only for that client."""
    _, sk0, sk1, shared, L = _gen(rng)
    bad = np.asarray(sk0.key.cw_val).copy()  # [N, d=1, L-1, lanes]
    bad[2, 0, 1, 0] = (int(bad[2, 0, 1, 0]) + 5) % FE62.P
    j = jnp.asarray(bad)
    sk0b = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1b = sk1._replace(key=sk1.key._replace(cw_val=j))
    ok = sketch.verify_level(sk0b, sk1b, 1, FE62, F255, L, shared)
    assert not ok[2] and ok[[0, 1, 3, 4, 5]].all()
    assert sketch.verify_level(sk0b, sk1b, 0, FE62, F255, L, shared).all()


def test_forged_mac_lane_flagged_last_level(rng):
    """Forging the k·x lane breaks check 3 in the F255 last level."""
    _, sk0, sk1, shared, L = _gen(rng)
    bad = np.asarray(sk0.key.cw_val_last).copy()  # [N, d=1, lanes, limbs]
    bad[0, 0, 1, 0] ^= 3
    j = jnp.asarray(bad)
    ok = sketch.verify_level(
        sk0._replace(key=sk0.key._replace(cw_val_last=j)),
        sk1._replace(key=sk1.key._replace(cw_val_last=j)),
        L - 1, FE62, F255, L, shared,
    )
    assert not ok[0] and ok[1:].all()


def test_inconsistent_mac_key_share_flagged(rng):
    """Tampered k share breaks check 2 (k·k - k² != 0) for every client
    whose share was touched."""
    _, sk0, sk1, shared, L = _gen(rng)
    bad = jnp.asarray(FE62.add(sk0.mac_key, FE62.from_int(1)))
    ok = sketch.verify_level(
        sk0._replace(mac_key=bad), sk1, 2, FE62, F255, L, shared
    )
    assert not ok.any()


def test_sketch_batch_chunking_equivalent(rng):
    """sketch_batch_size chunking must not change verdicts."""
    _, sk0, sk1, shared, L = _gen(rng)  # N=6: bs=3 -> two equal chunks
    a = sketch.verify_level(sk0, sk1, 2, FE62, F255, L, shared,
                            sketch_batch_size=100_000)
    b = sketch.verify_level(sk0, sk1, 2, FE62, F255, L, shared,
                            sketch_batch_size=3)
    np.testing.assert_array_equal(a, b)
    assert a.all()


def test_triple_verify_catches_bad_product(rng):
    """Direct MPC layer check: x*y + z == 0 passes, x*y + z != 0 fails."""
    N = 5
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    t0, t1 = mpc.gen_triples(FE62, (N, mpc.CHECKS), seed)
    x = jnp.asarray(rng.integers(0, FE62.P, size=(N, 3)).astype(np.uint64))
    y = jnp.asarray(rng.integers(0, FE62.P, size=(N, 3)).astype(np.uint64))
    z_good = FE62.neg(FE62.mul(x, y))
    r = jnp.asarray(rng.integers(1, FE62.P, size=(N, 3)).astype(np.uint64))
    zero = FE62.zeros((N, 3))

    def run(z0, z1):
        s0 = mpc.MulStateBatch(xs=x, ys=zero, zs=z0, rs=r, triples=t0)
        s1 = mpc.MulStateBatch(xs=zero, ys=y, zs=z1, rs=r, triples=t1)
        opened = mpc.cor(FE62, mpc.cor_share(FE62, s0), mpc.cor_share(FE62, s1))
        o0 = mpc.out_share(FE62, False, s0, opened)
        o1 = mpc.out_share(FE62, True, s1, opened)
        return np.asarray(mpc.verify(FE62, o0, o1))

    assert run(z_good, zero).all()
    z_bad = FE62.add(z_good, FE62.from_int(1))
    assert not run(z_bad, zero).any()


# ---------------------------------------------------------------------------
# End-to-end: cheating client excluded from counts through alive_keys,
# over the full two-server RPC protocol, sketch_batch_size=100000
# ---------------------------------------------------------------------------

BASE_PORT = 21531


def _run_rpc_protocol(cfg, k0, k1, sk0, sk1, n, port):
    async def run():
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
        )
        c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
        await asyncio.gather(t0, t1)
        lead = RpcLeader(cfg, c0, c1)
        try:
            await asyncio.gather(c0.call("reset"), c1.call("reset"))
            await lead.upload_keys(k0, k1, sk0, sk1)
            res = await lead.run(n)
            alive = s0.alive_keys.copy()
        finally:
            # a leaked listener (kept alive by reference cycles until a
            # gc pass) holds its port bound into LATER tests — close
            # everything before the loop goes away
            for c in (c0, c1):
                await c.aclose()
            for s in (s0, s1):
                await s.aclose()
        return res, alive

    return asyncio.run(run())


def test_multidim_sketch_per_dim_detection(rng):
    """d=2 sketch: honest clients pass every level; a payload forged in
    ONE dimension flags exactly that client (per-dim DPFs sharing the
    client's MAC key — the flagship fuzzy shape)."""
    N, d, L = 5, 2, 5
    alpha = rng.integers(0, 2, size=(N, d, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(N, d, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    shared = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, alpha, FE62, F255, cseed)
    for level in (0, 2, L - 1):
        assert sketch.verify_level(sk0, sk1, level, FE62, F255, L, shared).all()
    bad = np.asarray(sk0.key.cw_val).copy()  # [N, d, L-1, lanes]
    bad[2, 1, 1, 0] = (int(bad[2, 1, 1, 0]) + 5) % FE62.P
    j = jnp.asarray(bad)
    sk0b = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1b = sk1._replace(key=sk1.key._replace(cw_val=j))
    ok = sketch.verify_level(sk0b, sk1b, 1, FE62, F255, L, shared)
    assert not ok[2] and ok[[0, 1, 3, 4]].all()


def test_multidim_malicious_e2e_excluded(rng):
    """Flagship shape end to end: n_dims=2 fuzzy balls with malicious
    security over the full two-server RPC protocol — a client whose
    dim-1 sketch payload is forged is excluded from every gated count."""
    L, n, d = 5, 12, 2
    pts = np.array([[11, 20]] * 8 + [[25, 3], [2, 9], [30, 30], [7, 18]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(n, d, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, pts_bits, FE62, F255, cseed)
    bad = np.asarray(sk0.key.cw_val).copy()
    bad[3, 1, 2, 0] = (int(bad[3, 1, 2, 0]) + 1) % FE62.P
    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))

    cfg = Config(
        data_len=L, n_dims=d, ball_size=1, addkey_batch_size=12, num_sites=4,
        threshold=0.5, zipf_exponent=1.03,
        server0="127.0.0.1:21571", server1="127.0.0.1:21581",
        distribution="zipf", f_max=64, sketch_batch_size=100_000,
    )
    res, alive = _run_rpc_protocol(cfg, k0, k1, sk0, sk1, n, 21571)
    want_alive = np.ones(n, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(alive, want_alive)
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }
    # threshold 6: the ball product around (11, 20) survives with the 7
    # honest clients there; the cheater is excluded from every count
    assert got and all(c == 7 for c in got.values())
    assert (11, 20) in got


def test_secure_plus_malicious_e2e(rng):
    """The combined reference-intent deployment: GC+OT secure exchange AND
    sketch verification in one protocol run — the cheater is excluded and
    the secure-mode counts match."""
    L, n = 5, 12
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)
    bad = np.asarray(sk0.key.cw_val).copy()
    bad[3, 0, 2, 0] = (int(bad[3, 0, 2, 0]) + 1) % FE62.P
    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))

    cfg = Config(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=12, num_sites=4,
        threshold=0.5, zipf_exponent=1.03,
        server0="127.0.0.1:21591", server1="127.0.0.1:21601",
        distribution="zipf", f_max=32, sketch_batch_size=100_000,
        secure_exchange=True,
    )
    res, alive = _run_rpc_protocol(cfg, k0, k1, sk0, sk1, n, 21591)
    want_alive = np.ones(n, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(alive, want_alive)
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }
    assert got == {(10,): 7, (11,): 7, (12,): 7}


def test_malformed_key_excluded_from_counts(rng):
    # (L, n, f_max, d) match test_secure.py's socket e2e so the trusted
    # crawl kernels compile once for both files
    L, n = 5, 12
    # clients 0..7 at point 11, clients 8..11 elsewhere; client 3 cheats
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    alpha = pts_bits[:, 0, :]
    seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, alpha, FE62, F255, cseed)
    # client 3's payload forged at level 2 (handed identically to both)
    bad = np.asarray(sk0.key.cw_val).copy()  # [N, d=1, L-1, lanes]
    bad[3, 0, 2, 0] = (int(bad[3, 0, 2, 0]) + 1) % FE62.P
    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))

    cfg = Config(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=12, num_sites=4,
        threshold=0.5, zipf_exponent=1.03,
        server0=f"127.0.0.1:{BASE_PORT}", server1=f"127.0.0.1:{BASE_PORT + 10}",
        distribution="zipf", f_max=32, sketch_batch_size=100_000,
    )

    res, alive = _run_rpc_protocol(cfg, k0, k1, sk0, sk1, n, BASE_PORT)
    # the cheater was excluded exactly
    want_alive = np.ones(n, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(alive, want_alive)
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }
    # threshold 0.5*12 = 6: the 7 honest clients at 11 clear it; counts
    # exclude the cheater (7, not 8)
    assert got == {(10,): 7, (11,): 7, (12,): 7}
