"""fhh-lint: rule fixtures, suppression semantics, baseline machinery,
CLI plumbing, and the repo self-lint.

Each rule gets positive (seeded violation detected) and negative (idiomatic
clean code passes) fixtures; the self-lint test at the bottom is the tier-1
enforcement point: the tree must be clean at default severity under the
checked-in baseline, with no pytest marker so the driver's default
invocation always runs it.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from fuzzyheavyhitters_tpu.analysis import (
    ALL_RULES,
    LintConfig,
    apply_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    load_config,
    write_baseline,
)
from fuzzyheavyhitters_tpu.analysis.rules import RULES_BY_NAME

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, relpath="fuzzyheavyhitters_tpu/protocol/fake.py", cfg=None,
          rule=None):
    cfg = cfg or LintConfig()
    rules = [RULES_BY_NAME[rule]] if rule else None
    return lint_source(textwrap.dedent(src), relpath, cfg, rules)


def _names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule: host-sync-in-hot-loop
# ---------------------------------------------------------------------------


def test_host_sync_in_loop_detected():
    src = """
    import numpy as np
    def run(levels, x):
        for level in levels:
            y = np.asarray(x)  # device fetch per level
        return y
    """
    fs = _lint(src, rule="host-sync-in-hot-loop")
    assert _names(fs) == ["host-sync-in-hot-loop"]
    assert fs[0].line == 5


def test_host_sync_via_hot_root_transitive():
    src = """
    import numpy as np
    def helper(x):
        return np.asarray(x)
    def tree_crawl(x):
        return helper(x)
    """
    fs = _lint(src, rule="host-sync-in-hot-loop")
    assert len(fs) == 1 and "helper" in fs[0].message


def test_host_sync_item_and_block_until_ready():
    src = """
    def run_level(x):
        a = x.item()
        x.block_until_ready()
        return a
    """
    assert len(_lint(src, rule="host-sync-in-hot-loop")) == 2


def test_host_sync_cast_inside_jit():
    src = """
    import jax
    @jax.jit
    def f(x):
        return bool(x.sum())
    """
    fs = _lint(src, "some/other/module.py", rule="host-sync-in-hot-loop")
    assert len(fs) == 1 and "jit-compiled" in fs[0].message


def test_host_sync_clean_cases():
    src = """
    import numpy as np
    import jax.numpy as jnp
    def setup(x):
        return np.asarray(x)  # not hot: no loop, not a hot root
    def run_level(x):
        return jnp.asarray(x)  # device-side, never flagged
    def other(p):
        return bool(p)  # plain cast outside jit
    """
    assert _lint(src, rule="host-sync-in-hot-loop") == []


def test_host_sync_not_hot_outside_hot_modules():
    src = """
    import numpy as np
    def f(xs):
        for x in xs:
            y = np.asarray(x)
        return y
    """
    assert _lint(src, "fuzzyheavyhitters_tpu/workloads/w.py",
                 rule="host-sync-in-hot-loop") == []


# ---------------------------------------------------------------------------
# rule: secret-to-sink
# ---------------------------------------------------------------------------


def test_secret_to_emit_detected():
    src = """
    from .. import obs
    def f(gc_seed):
        obs.emit("level.done", seed=gc_seed)
    """
    fs = _lint(src, rule="secret-to-sink")
    assert len(fs) == 1 and "gc_seed" in fs[0].message


def test_secret_to_print_and_raise_detected():
    src = """
    def f(self):
        print(self.cw_seed)
        raise ValueError(f"bad key: {self._sec_seed}")
    """
    fs = _lint(src, rule="secret-to-sink")
    assert len(fs) == 2


def test_secret_sink_clean_cases():
    src = """
    from .. import obs
    def f(level, seconds, seed_len):
        obs.emit("level.done", level=level, fss_s=seconds)
        raise ValueError(f"bad level {level}")
    """
    # NB 'seed_len' segments are ('seed','len') — present but unused: only
    # flow INTO a sink counts
    assert _lint(src, rule="secret-to-sink") == []


def test_secret_kwarg_name_counts_as_flow():
    src = """
    def f(emit, x):
        emit("evt", mac_key=x)
    """
    fs = _lint(src, rule="secret-to-sink")
    assert len(fs) == 1


# ---------------------------------------------------------------------------
# rule: recompile-churn
# ---------------------------------------------------------------------------


def test_jit_wrapper_in_function_detected():
    src = """
    import jax, numpy as np
    def to_ints(v):
        return np.asarray(jax.jit(canon)(v))
    """
    fs = _lint(src, rule="recompile-churn")
    assert len(fs) == 1 and "hoist" in fs[0].message


def test_jit_wrapper_at_module_level_clean():
    src = """
    import jax
    def canon(v):
        return v
    canon_jit = jax.jit(canon)
    @jax.jit
    def g(x):
        return x
    """
    assert _lint(src, rule="recompile-churn") == []


def test_static_arg_unhashable_literal_detected():
    src = """
    import jax
    from functools import partial
    @partial(jax.jit, static_argnames=("shape",))
    def f(x, shape):
        return x
    def caller(x):
        return f(x, shape=[1, 2])
    """
    fs = _lint(src, rule="recompile-churn")
    assert len(fs) == 1 and "unhashable" in fs[0].message


def test_static_arg_loop_variable_detected():
    src = """
    import jax
    from functools import partial
    @partial(jax.jit, static_argnums=(1,))
    def f(x, width):
        return x
    def caller(x, widths):
        for w in widths:
            x = f(x, w)
        return x
    """
    fs = _lint(src, rule="recompile-churn")
    assert len(fs) == 1 and "loop variable" in fs[0].message


def test_static_arg_clean_call():
    src = """
    import jax
    from functools import partial
    @partial(jax.jit, static_argnames=("width",))
    def f(x, width):
        return x
    def caller(x):
        return f(x, width=8)
    """
    assert _lint(src, rule="recompile-churn") == []


# ---------------------------------------------------------------------------
# rule: unguarded-shared-state
# ---------------------------------------------------------------------------

_SHARED_PATH = "fuzzyheavyhitters_tpu/obs/fake.py"


def test_unguarded_write_detected():
    src = """
    import threading
    _lock = threading.Lock()
    _cache = {}
    def put(k, v):
        _cache[k] = v
    """
    fs = _lint(src, _SHARED_PATH, rule="unguarded-shared-state")
    assert len(fs) == 1 and "_cache" in fs[0].message


def test_unguarded_global_rebind_and_method_detected():
    src = """
    import threading
    _lock = threading.Lock()
    _items = []
    _count = 0
    def add(v):
        global _count
        _count += 1
        _items.append(v)
    """
    fs = _lint(src, _SHARED_PATH, rule="unguarded-shared-state")
    assert len(fs) == 2


def test_locked_write_clean():
    src = """
    import threading
    _lock = threading.RLock()
    _cache = {}
    _n = 0
    def put(k, v):
        global _n
        with _lock:
            _cache[k] = v
            _n += 1
    """
    assert _lint(src, _SHARED_PATH, rule="unguarded-shared-state") == []


def test_shared_state_rule_scoped_to_configured_modules():
    src = """
    _cache = {}
    def put(k, v):
        _cache[k] = v
    """
    assert _lint(src, "fuzzyheavyhitters_tpu/workloads/w.py",
                 rule="unguarded-shared-state") == []


# ---------------------------------------------------------------------------
# rules: broad-except, bare-print
# ---------------------------------------------------------------------------


def test_broad_except_detected_and_reraise_clean():
    src = """
    def f():
        try:
            g()
        except Exception:
            pass
    def g():
        try:
            h()
        except:
            return None
    def ok():
        try:
            h()
        except Exception:
            cleanup()
            raise
    def ok2():
        try:
            h()
        except ValueError:
            return None
    """
    fs = _lint(src, rule="broad-except")
    assert len(fs) == 2
    assert "bare" in fs[1].message


def test_broad_except_pytest_skip_counts_as_raise():
    src = """
    import pytest
    def probe():
        try:
            g()
        except Exception:
            pytest.skip("no backend")
    """
    assert _lint(src, rule="broad-except") == []


def test_bare_print_detected_and_scoped():
    src = """
    def f(x):
        print("crawl done", x)
    """
    assert len(_lint(src, rule="bare-print")) == 1
    # out of scope: tests and the allowlisted plot scripts
    assert _lint(src, "tests/test_x.py", rule="bare-print") == []
    assert _lint(
        src,
        "fuzzyheavyhitters_tpu/workloads/ride_austin_visualization.py",
        rule="bare-print",
    ) == []


# ---------------------------------------------------------------------------
# rule: unbounded-await
# ---------------------------------------------------------------------------


def test_unbounded_await_reads_and_waits_detected():
    src = """
    import asyncio

    async def f(reader, ev, tasks):
        hdr = await reader.readexactly(8)
        line = await reader.readline()
        await ev.wait()
        done, pending = await asyncio.wait(tasks)
    """
    found = _lint(src, rule="unbounded-await")
    assert len(found) == 4
    assert all(f.rule == "unbounded-await" for f in found)


def test_unbounded_await_dial_and_disguised_wait_for_detected():
    src = """
    import asyncio

    async def f(fut):
        r, w = await asyncio.open_connection("h", 1)
        await asyncio.wait_for(fut, None)
        await asyncio.wait_for(fut, timeout=None)
    """
    found = _lint(src, rule="unbounded-await")
    assert len(found) == 3


def test_unbounded_await_bounded_forms_clean():
    src = """
    import asyncio

    async def f(reader, tasks, fut, deadline):
        hdr = await asyncio.wait_for(reader.readexactly(8), 5.0)
        done, pending = await asyncio.wait(tasks, timeout=30)
        resp = await asyncio.wait_for(fut, deadline.remaining())
        body = await reader.read(n, timeout=2.0)
        return await fut  # awaiting a plain future is not a net call
    """
    assert _lint(src, rule="unbounded-await") == []


def test_unbounded_await_scoped_to_transport_modules():
    src = """
    async def f(reader):
        return await reader.readexactly(8)
    """
    assert len(_lint(src, rule="unbounded-await")) == 1
    assert _lint(
        src, "fuzzyheavyhitters_tpu/resilience/fake.py", rule="unbounded-await"
    )  # resilience is transport scope too
    assert _lint(
        src, "fuzzyheavyhitters_tpu/parallel/fake.py", rule="unbounded-await"
    )  # ... and parallel (mesh transport awaits need deadlines too)
    assert _lint(
        src, "fuzzyheavyhitters_tpu/ops/fake.py", rule="unbounded-await"
    ) == []
    assert _lint(src, "tests/test_x.py", rule="unbounded-await") == []


def test_unbounded_await_suppression():
    src = """
    async def f(reader):
        # fhh-lint: disable=unbounded-await (serve loop: waits for the
        # next command by design)
        return await reader.readexactly(8)
    """
    assert _lint(src, rule="unbounded-await") == []


# ---------------------------------------------------------------------------
# rule: unbounded-queue
# ---------------------------------------------------------------------------


def test_unbounded_queue_detected():
    """The exact bug class the streaming front door exists to prevent:
    a buffer with no bound between a producer and a slower consumer."""
    src = """
    import asyncio
    import collections

    q = asyncio.Queue()
    d = collections.deque()
    s = queue.SimpleQueue()
    zero = asyncio.Queue(maxsize=0)
    none = collections.deque(maxlen=None)
    """
    fs = _lint(src, rule="unbounded-queue")
    assert len(fs) == 5
    assert all(f.rule == "unbounded-queue" for f in fs)


def test_unbounded_queue_bounded_forms_clean():
    src = """
    import asyncio
    import collections

    q = asyncio.Queue(maxsize=64)
    qpos = asyncio.Queue(64)
    d = collections.deque(maxlen=8)
    dpos = collections.deque([], 8)
    dyn = asyncio.Queue(maxsize=cap)
    """
    assert _lint(src, rule="unbounded-queue") == []


# ---------------------------------------------------------------------------
# rule: span-discipline
# ---------------------------------------------------------------------------


def test_span_discipline_flags_non_context_manager_spans():
    """A span created outside a with statement records nothing (never
    entered) or dangles forever (entered, never exited) — both read as
    instrumentation while measuring nothing."""
    src = """
    def leak(reg):
        sp = reg.span("gc_ot", level=1)     # never entered
        ctx = self.obs.span("ingest")       # manually entered, leakable
        ctx.__enter__()
        reg.span("fss")                     # bare expression statement
    """
    fs = _lint(src, rule="span-discipline")
    assert len(fs) == 3
    assert all(f.rule == "span-discipline" for f in fs)


def test_span_discipline_with_forms_and_other_attrs_clean():
    src = """
    def ok(reg, cs):
        with reg.span("level", level=0) as sp:
            with cs.obs.span("fss", level=0):
                pass
        sp2 = reg.current_span()            # not span()
        n = numpy.span(3)                   # attr named span, still a
        # span-shaped call: deliberately flagged only as a with-item
        return sp, sp2, n
    """
    fs = _lint(src, rule="span-discipline")
    # numpy.span(3) IS flagged (attr name is the signal — suppressions
    # cover false positives); the with-forms and current_span are clean
    assert len(fs) == 1 and fs[0].line == 7


def test_span_discipline_flags_telemetry_in_jit_bodies():
    src = """
    import jax

    @jax.jit
    def kernel(x, reg):
        obs.emit("level.done", n=3)         # records once per COMPILE
        reg.observe("level_latency", 0.1)   # ditto
        return x + 1

    def host(reg):
        obs.emit("level.done", n=3)         # host-side: fine
        reg.observe("level_latency", 0.1)
    """
    fs = _lint(src, rule="span-discipline")
    assert len(fs) == 2
    assert all("jit" in f.message for f in fs)


def test_span_discipline_scope_and_suppression():
    src = """
    def leak(reg):
        sp = reg.span("gc_ot")
    """
    # out of scope (span_modules): clean
    assert _lint(
        src, relpath="fuzzyheavyhitters_tpu/workloads/x.py",
        rule="span-discipline",
    ) == []
    suppressed = """
    def managed(reg):
        # fhh-lint: disable=span-discipline (enter/exit managed across seal boundaries)
        sp = reg.span("ingest")
        sp.__enter__()
    """
    assert _lint(suppressed, rule="span-discipline") == []


def test_unbounded_queue_scoped_and_suppressible():
    src = """
    import collections
    d = collections.deque()
    """
    assert len(_lint(src, rule="unbounded-queue")) == 1
    assert _lint(
        src, "fuzzyheavyhitters_tpu/resilience/fake.py",
        rule="unbounded-queue",
    )
    assert _lint(
        src, "fuzzyheavyhitters_tpu/ops/fake.py", rule="unbounded-queue"
    ) == []
    assert _lint(src, "tests/test_x.py", rule="unbounded-queue") == []
    sup = """
    import collections
    # fhh-lint: disable=unbounded-queue (bounded by construction: the
    # refill loop never holds more than `depth` entries)
    d = collections.deque()
    """
    assert _lint(sup, rule="unbounded-queue") == []


# ---------------------------------------------------------------------------
# rule: metric-naming
# ---------------------------------------------------------------------------


def test_metric_naming_bad_registry_names_detected():
    src = """
    def f(reg, n):
        reg.count("Fresh-Compiles", n)
        reg.gauge("queue.depth", n)
        reg.observe("levelLatency", 0.5)
    """
    fs = _lint(src, rule="metric-naming")
    assert _names(fs) == ["metric-naming"] * 3
    assert [f.line for f in fs] == [3, 4, 5]


def test_metric_naming_valid_and_nonname_literals_clean():
    src = """
    def f(reg, log, n):
        reg.count("fresh_compiles", n)
        reg.count("fresh_compiles:rt_keygen", n)
        reg.observe("level_latency", 0.5)
        reg.timer_add("xla_compile", 0.5)
        log.count("alert fired {rule}")  # spaces/braces: str.count search
        return "some. punctuation!"  # not even identifier-like
    """
    assert _lint(src, rule="metric-naming") == []


def test_metric_naming_exported_literal_needs_unit_suffix():
    src = """
    GOOD = ("fhh_data_bytes_sent_total", "fhh_session_queue_depth_keys")
    BAD = "fhh_alert"
    """
    fs = _lint(src, rule="metric-naming")
    assert _names(fs) == ["metric-naming"]
    assert fs[0].line == 3
    # f-string fragments are assembly, never whole series names
    frag = """
    def render(name):
        return f"fhh_{name}_total 1"
    """
    assert _lint(frag, rule="metric-naming") == []


def test_metric_naming_scoped_to_metric_modules():
    src = """
    def f(reg, n):
        reg.count("Fresh-Compiles", n)
    """
    # tests/ ARE in scope (they hand-roll scrape keys); workloads are not
    assert len(_lint(src, "tests/test_x.py", rule="metric-naming")) == 1
    assert _lint(
        src,
        "fuzzyheavyhitters_tpu/workloads/fake.py",
        rule="metric-naming",
    ) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_inline_suppression_same_line():
    src = """
    def f(x):
        print(x)  # fhh-lint: disable=bare-print (demo tool)
    """
    assert _lint(src, rule="bare-print") == []


def test_suppression_standalone_comment_applies_to_next_code_line():
    src = """
    def f(x):
        # fhh-lint: disable=bare-print (a justification
        # that continues over two comment lines)
        print(x)
    """
    assert _lint(src, rule="bare-print") == []


def test_suppression_is_per_rule():
    src = """
    def f(x):
        print(x.cw_seed)  # fhh-lint: disable=bare-print
    """
    # bare-print silenced; secret-to-sink still fires
    names = _names(_lint(src))
    assert names == ["secret-to-sink"]


def test_suppression_multiple_rules_one_comment():
    src = """
    def f(x):
        print(x.cw_seed)  # fhh-lint: disable=bare-print,secret-to-sink
    """
    assert _lint(src) == []


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------

_BASE_SRC = """
def run_level(x):
    import numpy as np
    a = np.asarray(x)
    b = np.asarray(x)
    return a, b
"""


def _base_findings():
    return _lint(_BASE_SRC, rule="host-sync-in-hot-loop")


def test_baseline_absorbs_up_to_count(tmp_path):
    fs = _base_findings()
    assert len(fs) == 2
    path = str(tmp_path / "b.json")
    write_baseline(path, fs)
    counts = load_baseline(path)
    res = apply_baseline(fs, counts)
    assert res.new == [] and res.absorbed == 2 and res.stale == []


def test_baseline_growth_is_new(tmp_path):
    fs = _base_findings()
    path = str(tmp_path / "b.json")
    write_baseline(path, fs[:1])  # baseline holds count=1
    res = apply_baseline(fs, load_baseline(path))
    assert len(res.new) == 1 and res.absorbed == 1
    # the reported NEW finding is the later one in line order
    assert res.new[0].line == max(f.line for f in fs)


def test_baseline_shrink_reports_stale(tmp_path):
    fs = _base_findings()
    path = str(tmp_path / "b.json")
    write_baseline(path, fs)
    res = apply_baseline(fs[:1], load_baseline(path))
    assert res.new == [] and res.absorbed == 1
    assert res.stale == [
        ("host-sync-in-hot-loop", "fuzzyheavyhitters_tpu/protocol/fake.py", 1)
    ]


def test_baseline_remove_via_update(tmp_path):
    path = str(tmp_path / "b.json")
    write_baseline(path, _base_findings())
    write_baseline(path, [])  # burn-down complete
    assert load_baseline(path) == {}


def test_baseline_partial_update_keeps_unscanned_entries(tmp_path):
    """write_baseline(keep=...) — the CLI passes entries for files outside
    the scanned path set so a partial --update-baseline run cannot erase
    another subtree's grandfathered findings."""
    fs = _base_findings()  # all in fuzzyheavyhitters_tpu/protocol/fake.py
    path = str(tmp_path / "b.json")
    keep = {"host-sync-in-hot-loop": {"other/subtree.py": 3},
            "recompile-churn": {"gone/now_clean.py": 0}}
    write_baseline(path, fs, keep=keep)
    counts = load_baseline(path)
    assert counts["host-sync-in-hot-loop"]["other/subtree.py"] == 3
    assert counts["host-sync-in-hot-loop"][
        "fuzzyheavyhitters_tpu/protocol/fake.py"
    ] == 2
    assert "recompile-churn" not in counts  # zero-count entries dropped


def test_baseline_stale_scoped_to_scanned_paths():
    """A partial-scope run must not report unscanned files' baseline
    entries as stale burn-down wins."""
    counts = {"host-sync-in-hot-loop": {"pkg/unscanned.py": 8}}
    res = apply_baseline([], counts, scanned={"pkg/scanned.py"})
    assert res.stale == []
    res = apply_baseline([], counts, scanned={"pkg/unscanned.py"})
    assert res.stale == [("host-sync-in-hot-loop", "pkg/unscanned.py", 8)]


def test_cli_update_baseline_drops_deleted_files_keeps_unscanned(tmp_path):
    """Partial --update-baseline: entries for files outside the scan scope
    survive IF the file still exists; deleted files' entries drop out."""
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    (pkg / "live.py").write_text("def f(x):\n    print(x)\n")
    (sub / "other.py").write_text("def g(x):\n    print(x)\n")
    base = tmp_path / "lint_baseline.json"
    base.write_text(json.dumps({
        "schema": "fhh-lint-baseline/1",
        "counts": {"bare-print": {
            "pkg/live.py": 1,          # scanned: rewritten from findings
            "pkg/sub/other.py": 1,     # unscanned but alive: kept
            "pkg/deleted.py": 4,       # gone from disk: dropped
        }},
    }))
    cfg_toml = tmp_path / "pyproject.toml"
    cfg_toml.write_text(
        "[tool.fhh-lint]\nprint_scope = [\"pkg\"]\n"
        "baseline = \"lint_baseline.json\"\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "fuzzyheavyhitters_tpu.analysis",
         "pkg/live.py", "--update-baseline", "--root", str(tmp_path)],
        cwd=str(tmp_path), capture_output=True, text=True, env=env,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    counts = load_baseline(str(base))
    assert counts == {"bare-print": {
        "pkg/live.py": 1, "pkg/sub/other.py": 1,
    }}, counts


def test_cli_rejects_non_python_file_and_empty_scan(tmp_path):
    """A non-.py file argument (or a path set yielding zero .py files) is
    a usage error (exit 2), never a silent green."""
    (tmp_path / "wrapper.sh").write_text("echo hi\n")
    empty = tmp_path / "empty"
    empty.mkdir()
    env = dict(os.environ, PYTHONPATH=REPO)
    for arg in ("wrapper.sh", "empty"):
        proc = subprocess.run(
            [sys.executable, "-m", "fuzzyheavyhitters_tpu.analysis",
             arg, "--root", str(tmp_path)],
            cwd=str(tmp_path), capture_output=True, text=True, env=env,
            timeout=300,
        )
        assert proc.returncode == 2, (arg, proc.stdout, proc.stderr)


def test_baseline_rejects_unknown_schema(tmp_path):
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"schema": "nope", "counts": {}}))
    with pytest.raises(ValueError):
        load_baseline(str(path))


# ---------------------------------------------------------------------------
# config loading
# ---------------------------------------------------------------------------


def test_pyproject_config_loads():
    cfg = load_config(REPO)
    assert "run_level" in cfg.hot_roots
    assert "seed" in cfg.secret_lexicon
    assert cfg.severity_overrides.get("host-sync-in-hot-loop") == "warning"
    assert cfg.baseline == "lint_baseline.json"


def test_config_defaults_without_pyproject(tmp_path):
    cfg = load_config(str(tmp_path))
    assert cfg.hot_roots  # built-in defaults apply
    assert cfg.baseline == "lint_baseline.json"


def test_pyproject_and_dataclass_defaults_do_not_drift():
    """pyproject.toml [tool.fhh-lint] is the operative tuning and the
    LintConfig defaults mirror it (fixture tests build bare LintConfig()s).
    If this fails you edited one copy — update the other to match."""
    operative = load_config(REPO)
    defaults = LintConfig()
    for key in (
        "hot_modules", "hot_roots", "secret_lexicon", "sink_calls",
        "print_scope", "print_allowed", "shared_state_modules",
        "await_modules", "readback_modules", "queue_modules",
        "span_modules", "metric_modules", "metric_calls",
        "metric_unit_suffixes", "race_modules", "guards",
        "default_paths", "baseline",
    ):
        assert getattr(operative, key) == getattr(defaults, key), key


# ---------------------------------------------------------------------------
# self-lint: the repo is clean under the checked-in baseline
# ---------------------------------------------------------------------------


def test_self_lint_repo_clean_under_baseline():
    """Tier-1 enforcement: zero non-baselined findings at ANY severity over
    the package + tests, under the checked-in baseline.  A finding here
    means: fix it, suppress it with a justification, or consciously grow
    the baseline — never merge it silently."""
    cfg = load_config(REPO)
    findings, errors = lint_paths(
        ["fuzzyheavyhitters_tpu", "tests"], cfg, REPO
    )
    assert errors == []
    counts = load_baseline(os.path.join(REPO, cfg.baseline))
    res = apply_baseline(findings, counts)
    assert res.new == [], "new lint findings:\n" + "\n".join(
        f.render() for f in res.new
    )
    # the baseline must not rot silently either: stale entries mean a
    # finding was fixed — bank it with --update-baseline
    assert res.stale == [], (
        "baseline entries no longer needed (run "
        "`python -m fuzzyheavyhitters_tpu.analysis --update-baseline`): "
        f"{res.stale}"
    )


# ---------------------------------------------------------------------------
# rule: chunked-device-readback
# ---------------------------------------------------------------------------

_READBACK_SCOPE = "fuzzyheavyhitters_tpu/protocol/secure.py"


def test_chunked_readback_loop_fetches_detected():
    """Every readback form — the sanctioned ``_fetch`` included — trips
    the rule when it sits inside a per-chunk loop in a readback module:
    a loop of fetches is one device round trip per chunk no matter how
    each individual fetch is dressed."""
    src = """
    import numpy as np
    import jax

    async def crawl(chunks, reg):
        out = []
        for c in chunks:
            out.append(await _fetch(c, reg))
        for c in chunks:
            out.append(np.asarray(c))
        for c in chunks:
            out.append(jax.device_get(c))
        for c in chunks:
            c.copy_to_host_async()
        return out
    """
    found = _lint(src, _READBACK_SCOPE, rule="chunked-device-readback")
    assert len(found) == 4
    assert all(f.rule == "chunked-device-readback" for f in found)


def test_chunked_readback_whole_level_fetch_clean():
    """The sanctioned shape — stack on device inside the loop, ONE fetch
    after it — is clean, as are readbacks outside any loop."""
    src = """
    import numpy as np

    async def crawl(chunks, reg):
        parts = []
        for c in chunks:
            parts.append(transform(c))  # device-side, no readback
        whole = await _fetch(stack(parts), reg)
        direct = np.asarray(whole)
        return whole, direct
    """
    assert _lint(src, _READBACK_SCOPE, rule="chunked-device-readback") == []


def test_chunked_readback_scoped_to_readback_modules():
    src = """
    async def f(chunks):
        return [await _fetch(c) for c in chunks]
    """
    # comprehensions are loops too
    assert _lint(src, _READBACK_SCOPE, rule="chunked-device-readback")
    assert _lint(
        src, "fuzzyheavyhitters_tpu/ops/fake.py",
        rule="chunked-device-readback",
    )
    # rpc.py and parallel/ joined the scope with the multi-chip refactor
    # (the crawl verbs' expand/open stages and the sharded mesh paths
    # must never regrow per-chunk fetch loops); the sanctioned wire
    # fetches there carry inline suppressions with justifications
    assert _lint(
        src, "fuzzyheavyhitters_tpu/protocol/rpc.py",
        rule="chunked-device-readback",
    )
    assert _lint(
        src, "fuzzyheavyhitters_tpu/parallel/server_mesh.py",
        rule="chunked-device-readback",
    )
    # the control/driver layers stay out: their wire-input conversions
    # are host numpy by construction
    assert _lint(
        src, "fuzzyheavyhitters_tpu/protocol/leader_rpc.py",
        rule="chunked-device-readback",
    ) == []
    assert _lint(src, "tests/test_x.py", rule="chunked-device-readback") == []


def test_chunked_readback_device_side_asarray_clean():
    """jnp.asarray is a device-side cast, not a readback — must not trip."""
    src = """
    import jax.numpy as jnp

    def f(chunks):
        return [jnp.asarray(c) for c in chunks]
    """
    assert _lint(src, _READBACK_SCOPE, rule="chunked-device-readback") == []


def test_every_rule_has_fixture_coverage():
    """Each shipped rule appears in at least one positive fixture — here,
    or (the fhh-race pair) in tests/test_concurrency.py — guards against
    a rule being added but never exercised."""
    covered = {
        "host-sync-in-hot-loop",
        "secret-to-sink",
        "recompile-churn",
        "unguarded-shared-state",
        "broad-except",
        "bare-print",
        "chunked-device-readback",
        "unbounded-await",
        "unbounded-queue",
        "span-discipline",
        "metric-naming",
        # fixtures in tests/test_concurrency.py
        "guarded-state-unlocked",
        "stale-read-across-await",
        # fixtures in tests/test_taint.py
        "secret-to-sink-flow",
        "secret-branch",
        "unmasked-wire",
    }
    assert {r.name for r in ALL_RULES} == covered


def test_cli_json_strict_on_repo():
    """The CLI contract the driver and scripts/lint.sh rely on: strict
    mode exits 0 on the current tree and the JSON document parses."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "fuzzyheavyhitters_tpu.analysis",
            "fuzzyheavyhitters_tpu",
            "tests",
            "--strict",
            "--format",
            "json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["schema"] == "fhh-lint-report/1"
    assert doc["findings"] == [] and doc["failing"] == 0


def test_cli_exit_codes(tmp_path):
    """Seeded violation -> exit 1 under --no-baseline; clean file -> 0."""
    bad = tmp_path / "fuzzyheavyhitters_tpu"
    bad.mkdir()
    (bad / "mod.py").write_text(
        "def f(x):\n    print(x)\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [
            sys.executable, "-m", "fuzzyheavyhitters_tpu.analysis",
            "fuzzyheavyhitters_tpu", "--no-baseline",
            "--root", str(tmp_path),
        ],
        cwd=str(tmp_path), capture_output=True, text=True, env=env,
        timeout=300,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "bare-print" in proc.stdout
    (bad / "mod.py").write_text("def f(x):\n    return x\n")
    proc = subprocess.run(
        [
            sys.executable, "-m", "fuzzyheavyhitters_tpu.analysis",
            "fuzzyheavyhitters_tpu", "--no-baseline",
            "--root", str(tmp_path),
        ],
        cwd=str(tmp_path), capture_output=True, text=True, env=env,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
