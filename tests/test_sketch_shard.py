"""Row-sharded device-resident sketch verify + streaming sketch windows.

The malicious-secure fast lane's acceptance surface (parallel/
sketch_shard.py + protocol/rpc.py sketch_verify + the windowed sketch
material):

- the BIT-IDENTITY MATRIX: at sketch shards {1, 2, 4, 8} × {FE62, F255}
  (including a non-dividing client batch that degrades), the trusted
  challenge stream, the cor-share wire, the out-share wire, and the
  verdict vector are all byte/bit-identical between the sharded
  shard_map programs and the single fused program — the gate that
  catches a CTR-seek bug end-to-end results cannot (honest clients pass
  under ANY challenge);
- the WINDOWED MALICIOUS e2e: submit_keys carries sketch material,
  window_seal commits a per-window challenge root, crawl_window runs
  the malicious level loop — the cheater is excluded and the results
  are bit-exact vs a batch malicious crawl over the same admitted set;
- the KILL/RESTART recovery leg: server 1 killed and restarted
  mid-window-crawl — the recovered window re-runs under the IDENTICAL
  committed challenge root (re-opening its Beaver slabs is a replay,
  never a second opening), results bit-exact, recovery counters in the
  report.

Shapes mirror tests/test_ingest.py (L=5, d=1) so the crawl kernels
compile once across the suites.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.parallel import sketch_shard
from fuzzyheavyhitters_tpu.protocol import mpc, rpc, sketch
from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
    RpcLeader,
    WindowedIngest,
)
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 26810


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend (the 8 virtual host devices exist for the shard
    legs; see conftest)."""
    yield


def _devs(k):
    return tuple(jax.devices("cpu")[:k])


# ---------------------------------------------------------------------------
# The bit-identity matrix: sharded vs single fused program
# ---------------------------------------------------------------------------


def test_binding_degrades_on_non_dividing_batch():
    """The active shard count is the largest divisor of the client
    batch <= the budget — a non-dividing batch degrades, never fails,
    and a one-shard binding collapses to the single-program path."""
    assert sketch_shard.sketch_shards(16, 8) == 8
    assert sketch_shard.sketch_shards(12, 8) == 6  # 8 ∤ 12 -> 6
    assert sketch_shard.sketch_shards(13, 8) == 1  # prime -> 1
    ss = sketch_shard.bind(_devs(8), 12, 1, 8)
    assert ss is not None and ss.k == 6
    assert sketch_shard.bind(_devs(8), 13, 1, 8) is None
    assert sketch_shard.bind(_devs(8), 16, 1, 1) is None


@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
def test_challenge_stream_bit_identical_matrix(rng, field):
    """Shard i derives EXACTLY its rows of the single-device challenge
    stream (r replicated, rand rows by CTR seek) at shards {2, 4, 8}
    and on a non-dividing batch — byte-identical to the
    ``shared_r_stream`` reference draw."""
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    N, d, m, level = 16, 2, 8, 3
    r_ref, rands_ref = sketch.shared_r_stream(field, seed, level, m, N * d)
    r_ref, rands_ref = np.asarray(r_ref), np.asarray(rands_ref)
    r1, ra1 = sketch_shard.stream_parts(None, field, seed, level, m, N, d)
    np.testing.assert_array_equal(r_ref, r1)
    np.testing.assert_array_equal(rands_ref, ra1)
    for k in (2, 4, 8):
        ss = sketch_shard.bind(_devs(k), N, d, k)
        assert ss is not None and ss.k == k
        rk, rak = sketch_shard.stream_parts(ss, field, seed, level, m, N, d)
        np.testing.assert_array_equal(r_ref, rk, err_msg=f"k={k}")
        np.testing.assert_array_equal(rands_ref, rak, err_msg=f"k={k}")
    # non-dividing batch: 8-device budget degrades to 6 shards and the
    # stream still matches its own single-program reference
    N2 = 12
    ss = sketch_shard.bind(_devs(8), N2, d, 8)
    assert ss.k == 6
    _, ra_ref2 = sketch.shared_r_stream(field, seed, level, m, N2 * d)
    _, ra2 = sketch_shard.stream_parts(ss, field, seed, level, m, N2, d)
    np.testing.assert_array_equal(np.asarray(ra_ref2), ra2)


@pytest.mark.parametrize(
    "field",
    [
        FE62,
        # ~110 s on one core: the F255 leg exercises the same sharded
        # vs fused code path as FE62 over the wider field — tier-1
        # keeps the FE62 leg, chaos.sh (-m "") runs both
        pytest.param(F255, marks=pytest.mark.slow),
    ],
    ids=["FE62", "F255"],
)
def test_cor_out_verdict_wire_bit_identical_matrix(rng, field):
    """Both wire messages and the verdict vector are byte/bit-identical
    between the sharded and single fused programs, for honest states
    AND a tampered one (the verdict flip itself must agree)."""
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    N, d, m, level = 16, 1, 4, 2
    w = 8 if field.limb_shape else 4

    def rnd(shape):
        return field.sample(jnp.asarray(
            rng.integers(0, 2**32, size=shape + (w,), dtype=np.uint32)
        ))

    t0, t1 = mpc.gen_triples(field, (N, d, mpc.CHECKS), seed)
    pairs0, pairs1 = rnd((m, N, d, 2)), rnd((m, N, d, 2))
    mk0, mk1 = rnd((N,)), rnd((N,))
    mk = field.add(mk0, mk1)
    k2 = field.mul(mk, mk)
    mk2_0 = rnd((N,))
    mk2_1 = field.sub(k2, mk2_0)

    def party(ss, pairs, trip, a, a2, idx, peer_cor=None, peer_o=None):
        cor, st = sketch_shard.cor_state(
            ss, field, pairs, trip, a, a2, seed, level
        )
        cw = sketch_shard.wire(cor)
        if peer_cor is None:
            return cor, st, cw
        o = sketch_shard.out_shares(ss, field, st, cor, peer_cor, idx)
        ow = sketch_shard.wire(o)
        if peer_o is None:
            return o, ow
        ok = sketch_shard.verdicts(ss, field, o, peer_o)
        return np.asarray(ok), ow

    def run(ss):
        c0, s0, cw0 = party(ss, pairs0, t0, mk0, mk2_0, False)
        c1, s1, cw1 = party(ss, pairs1, t1, mk1, mk2_1, True)
        o0 = sketch_shard.out_shares(ss, field, s0, c0, cw1, False)
        o1 = sketch_shard.out_shares(ss, field, s1, c1, cw0, True)
        ow0, ow1 = sketch_shard.wire(o0), sketch_shard.wire(o1)
        ok0 = np.asarray(sketch_shard.verdicts(ss, field, o0, ow1))
        ok1 = np.asarray(sketch_shard.verdicts(ss, field, o1, ow0))
        np.testing.assert_array_equal(ok0, ok1)
        return cw0, cw1, ow0, ow1, ok0

    ref = run(None)
    for k in (2, 4, 8):
        ss = sketch_shard.bind(_devs(k), N, d, k)
        got = run(ss)
        for a, b, what in zip(
            ref, got, ("cor0", "cor1", "out0", "out1", "verdict")
        ):
            np.testing.assert_array_equal(
                a, b, err_msg=f"{what} diverged at k={k}"
            )
    # non-dividing batch: slice the inputs to N=12 (degrades to k=6)
    sl = slice(0, 12)
    pairs0_s = jax.tree.map(lambda a: a[:, sl], pairs0)
    pairs1_s = jax.tree.map(lambda a: a[:, sl], pairs1)
    t0_s = jax.tree.map(lambda a: a[sl], t0)
    t1_s = jax.tree.map(lambda a: a[sl], t1)
    # the closures in run()/party() read these at call time
    pairs0, pairs1, t0, t1 = pairs0_s, pairs1_s, t0_s, t1_s
    mk0, mk1 = mk0[sl], mk1[sl]
    mk2_0, mk2_1 = mk2_0[sl], mk2_1[sl]
    ref = run(None)
    ss = sketch_shard.bind(_devs(8), 12, d, 8)
    assert ss.k == 6
    got = run(ss)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Streaming sketch material: windowed malicious crawls
# ---------------------------------------------------------------------------

L, N = 5, 12


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=12,
        num_sites=4, threshold=0.5, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf", f_max=32, malicious=True,
    )
    defaults.update(kw)
    return Config(**defaults)


def _material(rng):
    """12 clients (8 clustered at 11), client 3's dim-0 sketch payload
    forged at level 2 — handed identically to both servers (the
    additive-attack shape test_sketch pins)."""
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(N, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)
    bad = np.asarray(sk0.key.cw_val).copy()
    bad[3, 0, 2, 0] = (int(bad[3, 0, 2, 0]) + 1) % FE62.P
    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))
    return k0, k1, sk0, sk1


def _chunk(k, sl):
    return tuple(np.asarray(x)[sl] for x in k)


def _sk_chunk(sk, sl):
    return [np.asarray(x)[sl] for x in jax.tree.leaves(sk)]


def _hitters(res):
    return {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }


async def _start_servers(cfg, port, ckpt_dir=None):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


async def _bring_up(cfg, port, ckpt_dir=None):
    live = {}
    live["s0"], live["s1"] = await _start_servers(cfg, port, ckpt_dir)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    return lead, c0, c1, live


async def _teardown(clients, live):
    for c in clients:
        await c.aclose()
    for s in live.values():
        await s.aclose()


def _batch_malicious(cfg, port, k0, k1, sk0, sk1):
    """Reference: the batch (upload_keys + run) malicious crawl every
    windowed result must be bit-exact against."""

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        await lead.upload_keys(k0, k1, sk0, sk1)
        res = await lead.run(N)
        alive = live["s0"].alive_keys.copy()
        await _teardown((c0, c1), live)
        return res, alive

    return asyncio.run(run())


def test_windowed_malicious_e2e_cheater_excluded_bit_exact(rng):
    """THE streaming-malicious contract: sketch material rides
    submit_keys into the window pool, the sealed window carries its own
    challenge-root commitment, crawl_window runs the malicious level
    loop — the cheater is excluded through the liveness gate and the
    results are bit-exact vs the batch malicious crawl."""
    port = BASE_PORT
    k0, k1, sk0, sk1 = _material(rng)
    cfg = _cfg(port)

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        wi = WindowedIngest(lead, checkpoint=False)
        for i in range(N):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
                sk0_chunk=_sk_chunk(sk0, slice(i, i + 1)),
                sk1_chunk=_sk_chunk(sk1, slice(i, i + 1)),
            )
        stats = await wi.seal_window()
        res = await wi.crawl_window(0)
        alive = live["s0"].alive_keys.copy()
        st = await c0.call("status")
        rep = obsreport.run_report(
            [live["s0"].obs, live["s1"].obs, lead.obs, wi.obs]
        )
        await _teardown((c0, c1), live)
        return res, alive, stats, st, rep

    res, alive, stats, st, rep = asyncio.run(run())
    # the sealed window committed a challenge root and announced it
    assert "sk_root" in stats and len(stats["sk_root"]) == 4
    want_alive = np.ones(N, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(alive, want_alive)
    want_res, want_alive_b = _batch_malicious(
        _cfg(port + 40), port + 40, k0, k1, sk0, sk1
    )
    np.testing.assert_array_equal(alive, want_alive_b)
    np.testing.assert_array_equal(res.counts, want_res.counts)
    np.testing.assert_array_equal(res.paths, want_res.paths)
    assert _hitters(res) == {(10,): 7, (11,): 7, (12,): 7}
    # the report grew the sketch section (the fused verify ran)
    assert rep["sketch"]["verify_seconds"] > 0
    assert rep["sketch"]["levels_verified"] >= 2
    # status surfaces the verify's shard layout (meshless here -> 1)
    assert st["mesh"] is None or st["mesh"]["sketch_shards"] >= 1


def test_windowed_malicious_kill_restart_replays_identical_challenge(
    rng, tmp_path
):
    """THE recovery leg: server 1 killed + restarted MID-CRAWL of a
    malicious window.  Recovery restores the ingest checkpoint (window
    root included), replays the journal (sketch chunks included),
    re-seals under the ORIGINAL root, and re-runs — the re-run replays
    the identical challenge sequence (the committed root survives the
    restart, so re-opening the window's Beaver slabs is a replay, never
    a second opening), the cheater stays excluded, and the results are
    bit-exact vs the fault-free batch crawl."""
    port = BASE_PORT + 100
    k0, k1, sk0, sk1 = _material(rng)
    cfg = _cfg(port)
    ck = tmp_path / "ck"
    ck.mkdir()

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port, ckpt_dir=str(ck))
        wi = WindowedIngest(lead)  # checkpointing ON
        for i in range(N):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
                sk0_chunk=_sk_chunk(sk0, slice(i, i + 1)),
                sk1_chunk=_sk_chunk(sk1, slice(i, i + 1)),
            )
        stats = await wi.seal_window()
        root_committed = np.array(stats["sk_root"], np.uint32)

        async def assassin():
            # kill s1 the moment the window crawl is underway
            while live["s1"].frontier is None:
                await asyncio.sleep(0.01)
            await live["s1"].aclose()
            await asyncio.sleep(0.3)
            live["s1"] = rpc.CollectorServer(1, cfg, ckpt_dir=str(ck))
            await live["s1"].start(
                "127.0.0.1", port + 10, "127.0.0.1", port + 11
            )

        kill = asyncio.create_task(assassin())
        res = await wi.crawl_window(0)
        await kill
        alive0 = live["s0"].alive_keys.copy()
        alive1 = live["s1"].alive_keys.copy()
        # the recovered crawl committed the ORIGINAL window root on
        # BOTH servers — the restarted one included (the identical-
        # challenge replay this test exists to pin)
        roots = (
            live["s0"]._default()._sketch_root.copy(),
            live["s1"]._default()._sketch_root.copy(),
        )
        rep = obsreport.run_report(
            [live["s0"].obs, live["s1"].obs, lead.obs, wi.obs]
        )
        await _teardown((c0, c1), live)
        return res, alive0, alive1, root_committed, roots, rep

    res, alive0, alive1, root_committed, roots, rep = asyncio.run(run())
    want_alive = np.ones(N, bool)
    want_alive[3] = False
    np.testing.assert_array_equal(alive0, want_alive)
    np.testing.assert_array_equal(alive1, want_alive)
    for r in roots:
        np.testing.assert_array_equal(r, root_committed)
    want_res, _ = _batch_malicious(
        _cfg(port + 40), port + 40, k0, k1, sk0, sk1
    )
    np.testing.assert_array_equal(res.counts, want_res.counts)
    np.testing.assert_array_equal(res.paths, want_res.paths)
    # the kill actually happened AND was recovered, visibly
    ing = rep["registries"]["ingest"]["counters"]
    assert ing["ingest_recoveries"]["total"] >= 1
    assert ing["ingest_journal_replays"]["total"] >= 1
