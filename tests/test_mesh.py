"""Multi-chip sharding tests on the virtual 8-device CPU mesh (conftest):
the sharded 2×4 (servers × data) protocol must produce byte-identical heavy
hitters to the in-process colocated driver.

Everything in this file — including the colocated reference driver — is
pinned to the CPU backend: mixing the axon TPU tunnel into the same process
as the virtual CPU mesh stalls nondeterministically (remote-compile calls
from a process that also initialized the host platform), which is what made
this file time out in rounds 1-2.  The driver's TPU behavior is covered by
tests/test_protocol.py; here it is only the parity oracle for the mesh."""

import jax
import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.parallel import mesh as meshmod
from fuzzyheavyhitters_tpu.protocol import driver
from fuzzyheavyhitters_tpu.utils import bits as bitutils


@pytest.fixture(scope="module")
def client_batch():
    rng = np.random.default_rng(7)
    L, d, n = 6, 2, 32
    centers = rng.integers(0, 1 << L, size=(3, d))
    pts = centers[rng.integers(0, 3, size=n)] + rng.integers(-1, 2, size=(n, d))
    pts = np.clip(pts, 0, (1 << L) - 1)
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    # host-side keygen: the jax engine's lax.scan compiles slowly on XLA:CPU,
    # and these tests exercise the mesh crawl, not keygen — gen_pair_np is
    # bit-identical (pinned by test_ibdcf.py::test_gen_pair_np_matches_gen_pair)
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
    return pts, k0, k1, L, d, n


def _as_dict(res):
    return {
        tuple(int(v) for v in row): int(c)
        for row, c in zip(res.decode_ints(), res.counts)
    }


@pytest.fixture(scope="module")
def colocated_result(client_batch, cpu_devices):
    """Reference counts from the in-process driver, computed on CPU."""
    pts, k0, k1, L, d, n = client_batch
    with jax.default_device(cpu_devices[0]):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=128)
        return _as_dict(lead.run(nreqs=n, threshold=0.1))


def test_mesh_matches_colocated_driver(client_batch, colocated_result, cpu_devices):
    _, k0, k1, _, _, n = client_batch
    assert colocated_result  # non-degenerate scenario

    m = meshmod.make_mesh(devices=cpu_devices)
    assert m.shape == {"servers": 2, "data": 4}
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128)
    got = _as_dict(meshmod.MeshLeader(runner).run(nreqs=n, threshold=0.1))
    assert got == colocated_result


@pytest.mark.slow
def test_mesh_two_devices(client_batch, colocated_result, cpu_devices):
    """Minimal mesh: just the 2-server axis, no data parallelism — the
    2-chip deployment shape from BASELINE.md's north star.  Marked slow:
    it re-compiles the whole crawl kernel family for a second mesh shape;
    the 2x4 mesh parity test covers the same code path."""
    _, k0, k1, _, _, n = client_batch
    m = meshmod.make_mesh(devices=cpu_devices[:2])
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128)
    got = _as_dict(meshmod.MeshLeader(runner).run(nreqs=n, threshold=0.1))
    assert got == colocated_result


def test_mesh_secure_matches_trusted(
    client_batch, colocated_result, cpu_devices, monkeypatch
):
    """The GC+OT 2PC on the 2×4 mesh (four ppermute transfers per level on
    the servers axis, FE62 inner levels + F255 last level) reconstructs the
    exact trusted-mode heavy hitters.  Same scenario as the trusted parity
    test, so the oracle and the trusted kernel family compile once for the
    module.  EQ_OT4 is forced OFF: at this n_dims=2 shape the default
    engine is now the 1-of-2^S table (covered by the ot4 test below and
    the socket suite), and THIS test is what keeps the mesh GC branch —
    the required path for S > secure.OT2S_MAX_S — exercised."""
    from fuzzyheavyhitters_tpu.protocol import secure

    monkeypatch.setattr(secure, "EQ_OT4", False)
    _, k0, k1, _, _, n = client_batch
    assert colocated_result

    m = meshmod.make_mesh(devices=cpu_devices)
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128, secure_exchange=True)
    got = _as_dict(meshmod.MeshLeader(runner).run(nreqs=n, threshold=0.1))
    assert got == colocated_result

    # regression pin (round-4 review finding): the ALTERNATING garbler's
    # per-level gc/b2a seeds must land in its own mesh row — with a zero
    # seed on the odd-level (garbler=1) side, the b2a share stream repeats
    # identically across crawls (the OT pads cancel out of the shares)
    sh_a = runner.level_count_shares(1)
    sh_b = runner.level_count_shares(1)
    assert not np.array_equal(sh_a, sh_b)


def test_mesh_secure_ot4_matches_trusted(cpu_devices):
    """n_dims = 1 -> S = 2: the mesh secure body takes the 1-of-4
    chosen-payload-OT fast path (2 ppermutes per level, no garbled
    circuit; secure.EQ_OT4) and must still reconstruct the exact
    trusted-mode heavy hitters, with the garbler alternating per level."""
    rng = np.random.default_rng(11)
    L, d, n = 5, 1, 32
    centers = rng.integers(0, 1 << L, size=(3, d))
    pts = np.clip(
        centers[rng.integers(0, 3, size=n)] + rng.integers(-1, 2, size=(n, d)),
        0, (1 << L) - 1,
    )
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")

    with jax.default_device(cpu_devices[0]):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=64)
        want = _as_dict(lead.run(nreqs=n, threshold=0.1))
    assert want

    from fuzzyheavyhitters_tpu.protocol import secure

    assert secure._ot4_use(2 * d)  # the default engine for 1-dim crawls
    m = meshmod.make_mesh(devices=cpu_devices)
    runner = meshmod.MeshRunner(m, k0, k1, f_max=64, secure_exchange=True)
    got = _as_dict(meshmod.MeshLeader(runner).run(nreqs=n, threshold=0.1))
    assert got == want


def test_odd_device_count_rejected(cpu_devices):
    with pytest.raises(AssertionError, match="even"):
        meshmod.make_mesh(devices=cpu_devices[:3])
