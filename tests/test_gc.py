"""Garbled-circuit equality tests vs the plaintext oracle, both roles in one
process — the reference's socketpair 2PC test shape (ref:
src/equalitytest.rs:222-266 ``eq_gc``), with the label hand-off done
directly from GarblerSecrets (the explicit-OT form) and via the Δ-OT
correlation (the fused form used by the live data plane)."""

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import gc, otext


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """All tests in this module run on the CPU backend (see conftest)."""
    yield


def _strings(rng, B, S):
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    flip = rng.integers(0, 2, size=B).astype(bool)
    y[flip, rng.integers(0, S, size=B)[flip]] ^= True
    return x, y, np.all(x == y, axis=1)


@pytest.mark.parametrize(
    "S",
    [
        1,
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(12, marks=pytest.mark.slow),
        33,
    ],
)
def test_garble_eval_roundtrip(rng, S):
    """mask ^ decoded == [x == y] for every batch entry (the contract of
    multiple_gb/ev_equality_test, equalitytest.rs:25-106).  S=1 (bare XNOR,
    no AND gates) and S=33 (odd leaf-count tree) are the edge shapes; the
    interior sizes ride the exhaustive (-m "") run."""
    B = 16
    x, y, eq = _strings(rng, B, S)
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    batch, secrets = gc.garble_equality(seed, x)
    ev_labels = np.where(
        y[..., None], np.asarray(secrets.ev_label1), np.asarray(secrets.ev_label0)
    )
    out = np.asarray(gc.eval_equality(batch, ev_labels))
    np.testing.assert_array_equal(np.asarray(secrets.mask) ^ out, eq)


def test_mask_distribution(rng):
    """Output masks are per-test random bits, not constants — the garbler's
    share must hide the plaintext result (equalitytest.rs:38-43).  (B, S)
    matches the roundtrip shape so the garble program compiles once; the
    seeded rng makes the B=16 any/all checks deterministic."""
    B, S = 16, 33
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    _, secrets = gc.garble_equality(seed, x)
    m = np.asarray(secrets.mask)
    assert m.any() and not m.all()
    # and masks differ across seeds
    _, secrets2 = gc.garble_equality(seed + 1, x)
    assert not np.array_equal(m, np.asarray(secrets2.mask))


def test_wrong_label_wrong_answer(rng):
    """Evaluating with a corrupted input label yields garbage, not the
    correct equality bit — sanity check that the tables actually bind.
    (B, S) matches the roundtrip shape (one compile)."""
    B, S = 16, 33
    x, y, eq = _strings(rng, B, S)
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    batch, secrets = gc.garble_equality(seed, x)
    ev_labels = np.where(
        y[..., None], np.asarray(secrets.ev_label1), np.asarray(secrets.ev_label0)
    ).copy()
    ev_labels[:, 0, :] ^= 0xDEADBEEF  # corrupt wire 0 everywhere
    out = np.asarray(gc.eval_equality(batch, ev_labels))
    assert not np.array_equal(np.asarray(secrets.mask) ^ out, eq)


def test_delta_garble_matches_plain(rng):
    """The Δ-OT form: labels delivered as T_j = Q_j ^ y_j*s must evaluate to
    the same shared equality as the explicit form."""
    snd, rcv = otext.inprocess_pair()
    B, S = 16, 33
    x, y, eq = _strings(rng, B, S)
    u, t_rows = rcv.extend(y.reshape(B * S))
    q = snd.extend(B * S, np.asarray(u))
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    batch, mask = gc.garble_equality_delta(
        snd.s_block, np.asarray(q).reshape(B, S, 4), seed, x
    )
    out = np.asarray(gc.eval_equality(batch, np.asarray(t_rows).reshape(B, S, 4)))
    np.testing.assert_array_equal(np.asarray(mask) ^ out, eq)
