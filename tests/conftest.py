"""Test harness: real-chip default with an 8-device virtual CPU mesh beside it.

Multi-chip sharding (the 2-server mesh axis plus client data-parallel axis)
is exercised on virtual CPU devices, per the reference's in-process
integration-test shape (two servers' state machines in one process,
ref: tests/collect_test.rs).  Everything else runs on the session's default
platform (the real TPU under axon; plain CPU elsewhere) — XLA:CPU both
compiles our ChaCha scans pathologically slowly at full optimization and
runs them slowly at reduced optimization, so the bulk of the suite stays on
the accelerator and only the sharding tests pay the CPU cost.

Mechanics: the session's sitecustomize imports JAX at interpreter start, so
JAX_PLATFORMS edits here are too late; jax.config still works.  XLA_FLAGS is
read lazily at first backend init, so the device-count and optimization
flags do land.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    # optimization_level=1: XLA:CPU's default pipeline takes minutes to
    # compile a lax.scan whose body contains the ChaCha expansion (253 s vs
    # 1.4 s measured); level 1 sidesteps the pathological pass.  Applies
    # only to the CPU backend (the TPU path compiles remotely).
    os.environ["XLA_FLAGS"] = (
        xla_flags
        + " --xla_force_host_platform_device_count=8"
        + " --xla_backend_optimization_level=1"
    ).strip()

import jax  # noqa: E402

_plats = os.environ.get("JAX_PLATFORMS", "") or "cpu"
if "cpu" not in _plats.split(","):
    jax.config.update("jax_platforms", _plats + ",cpu")
else:
    jax.config.update("jax_platforms", _plats)

# Persistent compilation cache: the suite is compile-bound (many small
# programs), so repeat runs should pay XLA compile costs once per machine.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_fhh")
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def has_tpu() -> bool:
    """Shared TPU probe (the pallas test modules and the retry hook all
    need the same answer — one copy, not three)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # no backend initialized -> not a TPU session
        return False


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    """Retry ``tpu_retry``-marked tests once when running against the remote
    TPU tunnel, so a transport hiccup is distinguishable from a real layout
    regression: a pass on immediate retry is reported as a warning (flake),
    a second failure surfaces the ORIGINAL error unchanged.  Round 4 lost a
    night to exactly this ambiguity (a parity test failed once at 21:49 and
    passed deterministically ever after)."""
    outcome = yield
    if outcome.excinfo is None or item.get_closest_marker("tpu_retry") is None:
        return
    if not has_tpu():
        return
    first_err = repr(outcome.excinfo[1])[:300]
    try:
        item.runtest()
    # fhh-lint: disable=broad-except (retry harness: must catch whatever
    # exception type the retried test raises; original error is re-reported)
    except Exception:
        return  # failed twice: deterministic — let the original error stand
    outcome.force_result(None)
    item.warn(
        pytest.PytestWarning(
            f"TPU tunnel flake: {item.nodeid} failed once "
            f"({first_err}) and passed on immediate retry"
        )
    )


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def cpu_default(cpu_devices):
    """Pin a test to the CPU backend.  Unit-scale tests use this: every
    remote TPU compile costs ~10 s through the tunnel regardless of program
    size, so compile-bound unit tests run on XLA:CPU (fast since the ChaCha
    fusion fence, ops/prg.py) while the protocol e2e tests keep exercising
    the real device."""
    with jax.default_device(cpu_devices[0]):
        yield


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) == 8
    return devs
