"""Test harness: real-chip default with an 8-device virtual CPU mesh beside it.

Multi-chip sharding (the 2-server mesh axis plus client data-parallel axis)
is exercised on virtual CPU devices, per the reference's in-process
integration-test shape (two servers' state machines in one process,
ref: tests/collect_test.rs).  Everything else runs on the session's default
platform (the real TPU under axon; plain CPU elsewhere) — XLA:CPU both
compiles our ChaCha scans pathologically slowly at full optimization and
runs them slowly at reduced optimization, so the bulk of the suite stays on
the accelerator and only the sharding tests pay the CPU cost.

Mechanics: the session's sitecustomize imports JAX at interpreter start, so
JAX_PLATFORMS edits here are too late; jax.config still works.  XLA_FLAGS is
read lazily at first backend init, so the device-count and optimization
flags do land.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    # optimization_level=1: XLA:CPU's default pipeline takes minutes to
    # compile a lax.scan whose body contains the ChaCha expansion (253 s vs
    # 1.4 s measured); level 1 sidesteps the pathological pass.  Applies
    # only to the CPU backend (the TPU path compiles remotely).
    os.environ["XLA_FLAGS"] = (
        xla_flags
        + " --xla_force_host_platform_device_count=8"
        + " --xla_backend_optimization_level=1"
    ).strip()

import jax  # noqa: E402

_plats = os.environ.get("JAX_PLATFORMS", "") or "cpu"
if "cpu" not in _plats.split(","):
    jax.config.update("jax_platforms", _plats + ",cpu")
else:
    jax.config.update("jax_platforms", _plats)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) == 8
    return devs
