"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip sharding (the 2-server mesh axis plus client data-parallel axis)
is exercised on virtual CPU devices, per the reference's in-process
integration-test shape (two servers' state machines in one process,
ref: tests/collect_test.rs).  Real-TPU paths are covered by bench.py.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
