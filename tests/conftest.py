"""Test harness: real-chip default with an 8-device virtual CPU mesh beside it.

Multi-chip sharding (the 2-server mesh axis plus client data-parallel axis)
is exercised on virtual CPU devices, per the reference's in-process
integration-test shape (two servers' state machines in one process,
ref: tests/collect_test.rs).  Everything else runs on the session's default
platform (the real TPU under axon; plain CPU elsewhere) — XLA:CPU both
compiles our ChaCha scans pathologically slowly at full optimization and
runs them slowly at reduced optimization, so the bulk of the suite stays on
the accelerator and only the sharding tests pay the CPU cost.

Mechanics: the session's sitecustomize imports JAX at interpreter start, so
JAX_PLATFORMS edits here are too late; jax.config still works.  XLA_FLAGS is
read lazily at first backend init, so the device-count and optimization
flags do land.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    # optimization_level=1: XLA:CPU's default pipeline takes minutes to
    # compile a lax.scan whose body contains the ChaCha expansion (253 s vs
    # 1.4 s measured); level 1 sidesteps the pathological pass.  Applies
    # only to the CPU backend (the TPU path compiles remotely).
    os.environ["XLA_FLAGS"] = (
        xla_flags
        + " --xla_force_host_platform_device_count=8"
        + " --xla_backend_optimization_level=1"
    ).strip()

import jax  # noqa: E402

_plats = os.environ.get("JAX_PLATFORMS", "") or "cpu"
if "cpu" not in _plats.split(","):
    jax.config.update("jax_platforms", _plats + ",cpu")
else:
    jax.config.update("jax_platforms", _plats)

# Persistent compilation cache: the suite is compile-bound (many small
# programs), so repeat runs should pay XLA compile costs once per machine.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.expanduser("~/.cache/jax_fhh")
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def has_tpu() -> bool:
    """Shared TPU probe (the pallas test modules and the retry hook all
    need the same answer — one copy, not three)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # no backend initialized -> not a TPU session
        return False


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    """Retry ``tpu_retry``-marked tests once when running against the remote
    TPU tunnel, so a transport hiccup is distinguishable from a real layout
    regression: a pass on immediate retry is reported as a warning (flake),
    a second failure surfaces the ORIGINAL error unchanged.  Round 4 lost a
    night to exactly this ambiguity (a parity test failed once at 21:49 and
    passed deterministically ever after)."""
    outcome = yield
    if outcome.excinfo is None or item.get_closest_marker("tpu_retry") is None:
        return
    if not has_tpu():
        return
    first_err = repr(outcome.excinfo[1])[:300]
    try:
        item.runtest()
    # fhh-lint: disable=broad-except (retry harness: must catch whatever
    # exception type the retried test raises; original error is re-reported)
    except Exception:
        return  # failed twice: deterministic — let the original error stand
    outcome.force_result(None)
    item.warn(
        pytest.PytestWarning(
            f"TPU tunnel flake: {item.nodeid} failed once "
            f"({first_err}) and passed on immediate retry"
        )
    )


def _listening_inodes():
    """Socket inodes this process holds that are in LISTEN state, via
    /proc (None where /proc is unavailable — the guard degrades to a
    no-op off Linux).  Two joins: /proc/self/fd names our socket
    inodes, /proc/net/tcp{,6} names the machine's listeners (state 0A);
    the intersection is exactly 'sockets WE are listening on'."""
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:
        return None
    ours = set()
    for fd in fds:
        try:
            tgt = os.readlink(os.path.join("/proc/self/fd", fd))
        except OSError:
            continue  # fd closed between listdir and readlink
        if tgt.startswith("socket:["):
            ours.add(tgt[len("socket:["):-1])
    listening = set()
    seen_table = False
    for table in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(table) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        seen_table = True
        for line in lines:
            parts = line.split()
            if len(parts) > 9 and parts[3] == "0A":  # TCP_LISTEN
                listening.add(parts[9])
    if not seen_table:
        return None
    return ours & listening


@pytest.fixture(autouse=True)
def no_leaked_listeners():
    """Every test must close the listening sockets it opens — the
    regression guard for the EADDRINUSE class where a leaked server
    socket poisons a later test's bind of the same port.  First in the
    fixture stack (conftest autouse), so per-test server fixtures tear
    down BEFORE the post-check; a leak surviving gc.collect() fails the
    leaking test itself, not the innocent victim that binds next."""
    before = _listening_inodes()
    yield
    if before is None:
        return
    after = _listening_inodes()
    if after is None:
        return
    leaked = after - before
    if leaked:
        import gc

        gc.collect()  # drop listeners kept alive only by cycles
        after = _listening_inodes()
        leaked = (after or set()) - before
    assert not leaked, (
        f"test leaked {len(leaked)} listening socket(s) "
        f"(/proc/net inode(s) {sorted(leaked)}) — close servers in the "
        "test (exporter tests: obs.exporter.stop(); asyncio servers: "
        "srv.close() + wait_closed())"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def cpu_default(cpu_devices):
    """Pin a test to the CPU backend.  Unit-scale tests use this: every
    remote TPU compile costs ~10 s through the tunnel regardless of program
    size, so compile-bound unit tests run on XLA:CPU (fast since the ChaCha
    fusion fence, ops/prg.py) while the protocol e2e tests keep exercising
    the real device."""
    with jax.default_device(cpu_devices[0]):
        yield


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices("cpu")
    assert len(devs) == 8
    return devs
