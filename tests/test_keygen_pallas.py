"""Fused Pallas keygen vs the NumPy mirror — bit-exact on the real chip.

Runs only where a TPU backend resolved (the Mosaic kernel has no CPU
compile path and interpret mode is orders of magnitude too slow for CI);
under this repo's axon environment the default backend IS the chip, so the
flagship kernel gets real coverage on every default suite run.
"""

import numpy as np
import pytest

import jax


from conftest import has_tpu as _has_tpu


pytestmark = [
    pytest.mark.skipif(not _has_tpu(), reason="needs a TPU backend"),
    pytest.mark.tpu_retry,
]


@pytest.mark.parametrize("derived", [False, True])
def test_pallas_keygen_bit_exact(rng, derived):
    from fuzzyheavyhitters_tpu.ops import ibdcf, keygen_pallas

    N, L = 700, 9  # exercises client padding (700 % 1024) and level padding
    seeds = rng.integers(0, 2**32, size=(N, 2, 4), dtype=np.uint32)
    alpha = rng.integers(0, 2, size=(N, L)).astype(bool)
    side = rng.integers(0, 2, size=N).astype(bool)
    w0, w1 = ibdcf.gen_pair_np(seeds, alpha, side, derived_bits=derived)
    g0, g1 = keygen_pallas.gen_pair_pallas(
        seeds, alpha, side, derived_bits=derived
    )
    for want, got in ((w0, g0), (w1, g1)):
        np.testing.assert_array_equal(np.asarray(got.cw_seed), want.cw_seed)
        np.testing.assert_array_equal(np.asarray(got.cw_bits), want.cw_bits)
        np.testing.assert_array_equal(np.asarray(got.cw_y_bits), want.cw_y_bits)
        np.testing.assert_array_equal(np.asarray(got.root_seed), want.root_seed)


def test_pallas_engine_selectable(rng):
    from fuzzyheavyhitters_tpu.ops import ibdcf

    pts = rng.integers(0, 2, size=(5, 1, 9)).astype(bool)
    # identical rng streams -> identical seeds -> the engines must agree
    k0, _ = ibdcf.gen_l_inf_ball(pts, 1, np.random.default_rng(42), engine="pallas")
    w0, _ = ibdcf.gen_l_inf_ball(pts, 1, np.random.default_rng(42), engine="np")
    np.testing.assert_array_equal(np.asarray(k0.cw_seed), np.asarray(w0.cw_seed))
    np.testing.assert_array_equal(np.asarray(k0.cw_bits), np.asarray(w0.cw_bits))


@pytest.mark.parametrize("planar_engine", [False, True])
def test_reexpand_advance_matches_cache_advance(rng, planar_engine, monkeypatch):
    """The re-expanding fallback `collect.advance` (rpc.py's prune-without-
    crawl path) produces the same frontier as the cache-gather advance, in
    BOTH engine layouts — the fallback's layout conversions are pinned here
    (its former Pallas eval kernel was retired in round 5; git history has
    it)."""
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import collect

    monkeypatch.setattr(collect, "EXPAND_PALLAS", planar_engine)
    n, d, L, F = 300, 2, 8, 4
    pts = rng.integers(0, 2, size=(n, d, L)).astype(bool)
    k0, _ = ibdcf.gen_l_inf_ball(pts, 1, rng, engine="np")
    f = collect.tree_init(k0, F)
    parent = jnp.asarray(np.array([0, 2, 1, 0], np.int32))
    pat = jnp.asarray(rng.integers(0, 2, size=(F, d)).astype(bool))
    _, ch = collect.expand_share_bits(k0, f, 0)
    a = collect.advance_from_children(ch, parent, pat, 3)
    b = collect.advance(k0, f, 0, parent, pat, 3)
    for name in ("seed", "bit", "y_bit"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.states, name)),
            np.asarray(getattr(b.states, name)),
        )
    np.testing.assert_array_equal(np.asarray(a.alive), np.asarray(b.alive))
