"""Workload-layer tests (host-only: samplers, codecs, writers)."""

import csv
import os
import numpy as np
import pytest

from fuzzyheavyhitters_tpu.workloads import covid, rides, strings
from fuzzyheavyhitters_tpu.utils import bits as bitutils


def test_sample_string_bits_shape_and_ascii(rng):
    bits = strings.sample_string_bits(rng, 56)
    assert bits.shape == (56,) and bits.dtype == bool
    # bytes decode back to alphanumeric ASCII (ref: leader.rs:38-44)
    by = np.packbits(bits.astype(np.uint8), bitorder="little").tobytes()
    assert by.decode("ascii").isalnum()


def test_zipf_is_skewed(rng):
    idx = strings.zipf_indices(rng, num_sites=50, exponent=1.03, nreqs=5000)
    assert idx.min() >= 0 and idx.max() < 50
    counts = np.bincount(idx, minlength=50)
    assert counts[0] > counts[10] > counts[40]  # heavy head


def test_zipf_workload_shapes(rng):
    pts, idx = strings.zipf_workload(
        rng, num_sites=10, data_len=32, n_dims=2, zipf_exponent=1.1, nreqs=20
    )
    assert pts.shape == (20, 2, 32)
    assert idx.shape == (20,)
    # same-site requests share the site prefix, differ (whp) in augmentation
    same = np.nonzero(idx == idx[0])[0]
    if len(same) > 1:
        a, b = pts[same[0]], pts[same[1]]
        assert np.array_equal(a[:, :24], b[:, :24])


def test_geo_codec_roundtrip_austin():
    """(ref: sample_driving_data.rs:149-163 test_austin_coords)"""
    lat, lon = 30.26, -97.74
    lat_i, lon_i = rides.geo_to_int(lat, lon)
    assert (lat_i, lon_i) == (3026, -9774)
    assert rides.int_to_geo(lat_i, lon_i) == (lat, lon)


def test_rides_csv_sampler(tmp_path, rng):
    path = tmp_path / "rides.csv"
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow([f"col{i}" for i in range(16)])
        for i in range(20):
            row = [""] * 16
            row[14] = str(30.0 + i / 100)  # start lat
            row[13] = str(-97.7 - i / 100)  # start lon
            w.writerow(row)
    pts = rides.sample_start_locations(str(path), 5, seed=3)
    assert pts.shape == (5, 2) and pts.dtype == np.int16
    assert np.all((pts[:, 0] >= 3000) & (pts[:, 0] <= 3020))
    assert np.all(pts[:, 1] <= -9770)


def test_synthetic_austin_fallback(tmp_path):
    pts = rides.load_or_synthesize_locations(str(tmp_path / "nope.csv"), 100, seed=1)
    assert pts.shape == (100, 2)
    # clustered near Austin
    assert abs(int(np.median(pts[:, 0])) - 3026) < 200
    assert abs(int(np.median(pts[:, 1])) + 9774) < 200


def test_save_heavy_hitters_roundtrip(tmp_path):
    coords = np.array([[3026, -9774], [3030, -9770]], dtype=np.int16)
    paths = np.stack(
        [
            np.stack([bitutils.i16_to_ob_bits(int(v)) for v in row])
            for row in coords
        ]
    )
    out = tmp_path / "hh.csv"
    rides.save_heavy_hitters(paths, str(out))
    rides.save_heavy_hitters(paths, str(out))  # append mode, single header
    with open(out) as f:
        lines = list(csv.reader(f))
    assert lines[0] == ["index", "latitude", "longitude"]
    assert len(lines) == 5
    assert [float(lines[1][1]), float(lines[1][2])] == [30.26, -97.74]


@pytest.fixture
def centroids_csv(tmp_path):
    path = tmp_path / "county_centroids.csv"
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["fips_code", "latitude", "longitude"])
        w.writerow(["48453", "30.33", "-97.78"])  # Travis County
        w.writerow(["06037", "34.31", "-118.23"])  # LA County
        w.writerow(["17031", "41.84", "-87.82"])  # Cook County
    return str(path)


def test_covid_sampler_fallback(centroids_csv, tmp_path):
    out = covid.sample_covid_locations(
        str(tmp_path / "absent.csv"), centroids_csv, 50, fuzz_factor=5.0, seed=9
    )
    assert out.shape == (50, 2, 64)
    lats = [covid.bool_vec_to_f64(out[i, 0]) for i in range(50)]
    lons = [covid.bool_vec_to_f64(out[i, 1]) for i in range(50)]
    # jittered but near one of the three centroids
    for lat, lon in zip(lats, lons):
        d = min(
            abs(lat - 30.33) + abs(lon + 97.78),
            abs(lat - 34.31) + abs(lon + 118.23),
            abs(lat - 41.84) + abs(lon + 87.82),
        )
        assert d < 0.2


def test_covid_sampler_with_case_csv(centroids_csv, tmp_path):
    case = tmp_path / "cases.csv"
    with open(case, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow([f"c{i}" for i in range(8)])
        for _ in range(30):
            row = [""] * 8
            row[5] = "48453"
            w.writerow(row)
    out = covid.sample_covid_locations(str(case), centroids_csv, 10, seed=2)
    assert out.shape == (10, 2, 64)
    assert covid.bool_vec_to_f64(out[0, 0]) == 30.33  # no fuzz -> exact centroid


def test_f64_bits_roundtrip():
    for v in (0.0, -97.74, 30.26, 1e-12, float(np.pi)):
        assert covid.bool_vec_to_f64(covid.f64_to_bool_vec(v)) == v


def test_visualization_scripts_render(tmp_path):
    """Both visualization counterparts render PNGs without the 9 GB raw
    inputs (ref: src/*_visualization.py; ours read the sampler fallbacks
    and the protocol's heavy-hitter output CSV)."""
    pytest.importorskip("matplotlib")
    from fuzzyheavyhitters_tpu.workloads import (
        covid_data_visualization as cviz,
        ride_austin_visualization as rviz,
        rides,
    )

    # synthesize a heavy-hitter CSV like the leader writes
    paths = np.zeros((3, 2, 16), bool)
    paths[:, :, 0] = True  # positive offset-binary coords
    hit_csv = tmp_path / "hh.csv"
    rides.save_heavy_hitters(paths, str(hit_csv))

    out = rviz.visualize(
        hitters_path=str(hit_csv),
        raw_path=str(tmp_path / "missing.csv"),  # forces synthetic fallback
        n=500,
        out_dir=str(tmp_path / "ride_plots"),
    )
    assert len(out) == 3 and all(os.path.getsize(p) > 1000 for p in out)

    out = cviz.visualize(
        centroids_path=os.path.join(
            os.path.dirname(__file__), "..", "data", "county_centroids.csv"
        ),
        cases_path=str(tmp_path / "missing.csv"),
        n=500,
        out_dir=str(tmp_path / "covid_plots"),
    )
    assert len(out) == 3 and all(os.path.getsize(p) > 1000 for p in out)


# ---------------------------------------------------------------------------
# Native streaming reservoir sampler (fuzzyheavyhitters_tpu/native)
# ---------------------------------------------------------------------------


def test_native_reservoir_sampler(tmp_path):
    from fuzzyheavyhitters_tpu import native

    if not native.available():
        pytest.skip("no C++ toolchain")
    p = tmp_path / "rides.csv"
    rows = [(30.0 + i * 0.01, -97.0 - i * 0.01) for i in range(50)]
    with open(p, "w") as f:
        f.write("h0,h1,h2\n")
        for lat, lon in rows:
            # col 1 = lon (quoted, like real exports), col 2 = lat
            f.write(f'x,"{lon}",{lat}\n')
    # k >= rows: every row comes back, in file order
    got = native.csv_reservoir_sample(str(p), col_a=2, col_b=1, k=100, seed=7)
    np.testing.assert_allclose(got, np.array(rows))
    # k < rows: deterministic for a seed, k rows, all from the file
    a = native.csv_reservoir_sample(str(p), col_a=2, col_b=1, k=8, seed=7)
    b = native.csv_reservoir_sample(str(p), col_a=2, col_b=1, k=8, seed=7)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (8, 2)
    all_rows = {tuple(r) for r in np.round(np.array(rows), 6)}
    assert all(tuple(r) in all_rows for r in np.round(a, 6))
    # different seed -> (almost surely) different reservoir
    c = native.csv_reservoir_sample(str(p), col_a=2, col_b=1, k=8, seed=8)
    assert not np.array_equal(a, c)


def test_rides_sampler_uses_native_path(tmp_path):
    from fuzzyheavyhitters_tpu.workloads import rides

    p = tmp_path / "RideAustin.csv"
    hdr = ",".join(f"c{i}" for i in range(16))
    with open(p, "w") as f:
        f.write(hdr + "\n")
        for i in range(20):
            row = ["0"] * 16
            row[13] = str(-97.70 - i * 0.01)  # start lon
            row[14] = str(30.20 + i * 0.01)  # start lat
            f.write(",".join(row) + "\n")
    out = rides.sample_start_locations(str(p), 5, seed=3)
    assert out.shape == (5, 2) and out.dtype == np.int16
    # centidegree range of the crafted coordinates
    assert np.all((out[:, 0] >= 3020) & (out[:, 0] <= 3040))
    assert np.all((out[:, 1] <= -9770) & (out[:, 1] >= -9790))
