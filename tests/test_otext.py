"""IKNP OT-extension tests: Δ-OT invariant, chosen-payload delivery,
stream-counter lockstep, and receiver privacy basics."""

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import otext


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """All tests in this module run on the CPU backend (see conftest)."""
    yield


@pytest.fixture(scope="module")
def pair():
    return otext.inprocess_pair()


def test_delta_ot_invariant(pair, rng):
    """T_j == Q_j ^ r_j*s — rows are correlated exactly by the sender's s
    (the free-XOR/Δ-OT contract the GC layer builds on)."""
    snd, rcv = pair
    m = 64
    r = rng.integers(0, 2, size=m).astype(bool)
    u, t = rcv.extend(r)
    q = snd.extend(m, np.asarray(u))
    s = snd.s_block
    want = np.where(r[:, None], np.asarray(q) ^ s, np.asarray(q))
    np.testing.assert_array_equal(np.asarray(t), want)


def test_chosen_payload_roundtrip(pair, rng):
    snd, rcv = pair
    m = 64
    r = rng.integers(0, 2, size=m).astype(bool)
    idx0 = rcv._recv
    u, t = rcv.extend(r)
    q = snd.extend(m, np.asarray(u))
    p0, p1 = snd.pads(q, 4, idx0)
    pr = rcv.pads(t, 4, idx0)
    m0 = rng.integers(0, 2**32, size=(m, 4), dtype=np.uint32)
    m1 = rng.integers(0, 2**32, size=(m, 4), dtype=np.uint32)
    c0 = m0 ^ np.asarray(p0)
    c1 = m1 ^ np.asarray(p1)
    got = np.where(r[:, None], c1, c0) ^ np.asarray(pr)
    np.testing.assert_array_equal(got, np.where(r[:, None], m1, m0))


def test_unchosen_pad_unlearnable(pair, rng):
    """The receiver's pad never matches the sender's other-message pad —
    (statistically: 2^-128 collision) — so the unchosen payload stays hidden."""
    snd, rcv = pair
    m = 64
    r = rng.integers(0, 2, size=m).astype(bool)
    idx0 = rcv._recv
    u, t = rcv.extend(r)
    q = snd.extend(m, np.asarray(u))
    p0, p1 = snd.pads(q, 4, idx0)
    pr = np.asarray(rcv.pads(t, 4, idx0))
    other = np.where(r[:, None], np.asarray(p0), np.asarray(p1))
    assert not np.any(np.all(pr == other, axis=1))


def test_counter_lockstep(pair, rng):
    """Back-to-back extensions stay correct (column streams advance in
    lockstep) and produce fresh correlations."""
    snd, rcv = pair
    outs = []
    for m in (64, 64, 64):  # same shape -> one compiled program, three stream windows
        r = rng.integers(0, 2, size=m).astype(bool)
        u, t = rcv.extend(r)
        q = snd.extend(m, np.asarray(u))
        want = np.where(r[:, None], np.asarray(q) ^ snd.s_block, np.asarray(q))
        np.testing.assert_array_equal(np.asarray(t), want)
        outs.append(np.asarray(q)[:7])
    assert not np.array_equal(outs[0], outs[1])
    assert not np.array_equal(outs[1], outs[2])


@pytest.mark.slow
def test_ragged_extend_sizes(pair, rng):
    """Non-block-multiple m values exercise the partial-word padding and
    counter-advance rounding directly (the default run covers the ragged
    path via the GC delta test's m=528; this sweeps it explicitly)."""
    snd, rcv = pair
    for m in (33, 32, 7, 77):
        r = rng.integers(0, 2, size=m).astype(bool)
        u, t = rcv.extend(r)
        q = snd.extend(m, np.asarray(u))
        want = np.where(r[:, None], np.asarray(q) ^ snd.s_block, np.asarray(q))
        np.testing.assert_array_equal(np.asarray(t), want)


def test_pack_unpack_roundtrip(rng):
    for m in (1, 31, 32, 33, 128, 129):
        bits = rng.integers(0, 2, size=m).astype(bool)
        words = np.asarray(otext.pack_bits(bits))
        assert words.shape == (-(-m // 32),)
        np.testing.assert_array_equal(
            np.asarray(otext.unpack_bits(words, m)), bits
        )


def test_fresh_s_bits_lsb_forced():
    s = otext.fresh_s_bits()
    assert s.shape == (128,) and s[0]
    assert otext.s_to_block(s)[0] & 1 == 1
