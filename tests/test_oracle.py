"""Semantic property tests for the pure-Python spec oracle.

These are the corrected, property-test versions of the reference's FSS unit
suite (ref: tests/ibdcf_tests.rs — whose own asserts mix bit orders; see
tests/oracle.py docstring).  Everything here uses MSB-first encodings, the
encoding the live protocol actually uses.
"""

import numpy as np
import pytest

import oracle
from oracle import (
    eval_prefix,
    gen_ibdcf,
    gen_interval,
    share_bit,
)


def msb_bits(nbits, v):
    return [(v >> i) & 1 == 1 for i in reversed(range(nbits))]


@pytest.fixture(params=[False, True], ids=["masked-bits", "derived-bits"])
def bits_mode(request, monkeypatch):
    monkeypatch.setattr(oracle, "DERIVED_BITS", request.param)
    return request.param


def test_single_dcf_full_domain(rng, bits_mode):
    """Exhaustive 5-bit sweep: share-XOR == strict comparison at full length
    (corrected form of ibdcf_tests.rs:4-39)."""
    nbits = 5
    for alpha in [0, 1, 10, 21, 30, 31]:
        for side in (False, True):
            k0, k1 = gen_ibdcf(msb_bits(nbits, alpha), side, rng)
            for x in range(1 << nbits):
                s0 = eval_prefix(k0, msb_bits(nbits, x))
                s1 = eval_prefix(k1, msb_bits(nbits, x))
                got = share_bit(s0) ^ share_bit(s1)
                want = (x < alpha) if side else (x > alpha)
                assert got == want, (alpha, side, x)


def test_t_bit_marks_alpha_path(rng, bits_mode):
    nbits = 5
    alpha = 19
    k0, k1 = gen_ibdcf(msb_bits(nbits, alpha), False, rng)
    for x in range(1 << nbits):
        s0 = eval_prefix(k0, msb_bits(nbits, x))
        s1 = eval_prefix(k1, msb_bits(nbits, x))
        assert (s0.bit ^ s1.bit) == (x == alpha)


def test_prefix_semantics(rng, bits_mode):
    """At prefix length j the comparison is against the bound's j-bit prefix."""
    nbits = 5
    alpha = 21
    for side in (False, True):
        k0, k1 = gen_ibdcf(msb_bits(nbits, alpha), side, rng)
        for x in range(1 << nbits):
            xb = msb_bits(nbits, x)
            for j in range(1, nbits + 1):
                s0 = eval_prefix(k0, xb[:j])
                s1 = eval_prefix(k1, xb[:j])
                got = share_bit(s0) ^ share_bit(s1)
                a_pre, x_pre = alpha >> (nbits - j), x >> (nbits - j)
                want = (x_pre < a_pre) if side else (x_pre > a_pre)
                assert got == want, (side, x, j)


def test_interval_membership(rng, bits_mode):
    """Share-string equality <=> inclusive interval membership
    (corrected form of ibdcf_tests.rs:294-356, incl. single-point,
    full-range, and edge intervals)."""
    nbits = 5
    cases = [(5, 10), (8, 8), (0, 31), (0, 0), (31, 31), (13, 22)]
    for left, right in cases:
        keys0, keys1 = gen_interval(msb_bits(nbits, left), msb_bits(nbits, right), rng)
        for x in range(1 << nbits):
            xb = msb_bits(nbits, x)
            str0 = [share_bit(eval_prefix(k, xb)) for k in keys0]
            str1 = [share_bit(eval_prefix(k, xb)) for k in keys1]
            inside = left <= x <= right
            assert (str0 == str1) == inside, (left, right, x)


def test_interval_prefix_membership_is_box_intersection(rng, bits_mode):
    """At level j, equality of share strings == [ball intersects prefix box]."""
    nbits = 5
    left, right = 6, 20
    keys0, keys1 = gen_interval(msb_bits(nbits, left), msb_bits(nbits, right), rng)
    for j in range(1, nbits + 1):
        for p in range(1 << j):
            pb = msb_bits(j, p)
            str0 = [share_bit(eval_prefix(k, pb)) for k in keys0]
            str1 = [share_bit(eval_prefix(k, pb)) for k in keys1]
            box_lo = p << (nbits - j)
            box_hi = box_lo + (1 << (nbits - j)) - 1
            intersects = not (box_hi < left or box_lo > right)
            assert (str0 == str1) == intersects, (j, p)


def test_incremental_matches_full(rng, bits_mode):
    """Incremental one-bit eval state equals from-scratch prefix eval
    (real-assert form of ibdcf_tests.rs:92-153)."""
    nbits = 6
    alpha = 37
    k0, _ = gen_ibdcf(msb_bits(nbits, alpha), True, rng)
    for x in [0, 5, 37, 63]:
        xb = msb_bits(nbits, x)
        state = oracle.eval_init(k0)
        for j, b in enumerate(xb):
            state = oracle.eval_bit(k0, state, bool(b))
            full = eval_prefix(k0, xb[: j + 1])
            assert (state.seed, state.bit, state.y_bit) == (full.seed, full.bit, full.y_bit)
