"""Process-level end-to-end smoke test: the README run shape.

Launches ``bin/server.py`` twice and ``bin/leader.py`` as REAL OS
processes on a rides-distribution config (the flagship i16 lat/lon
workload), then asserts the heavy-hitter CSV the leader wrote matches
the in-process driver oracle on the same deterministic client points.
The binaries are otherwise the one surface no test executes
(ref: README.md:38-60 run shape)."""

import json
import os
import subprocess
import sys

import numpy as np

import jax

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.workloads import rides

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_REQS = 32
PORT = 21701
CFG = {
    "data_len": 16,
    "n_dims": 2,
    "ball_size": 2,
    "addkey_batch_size": 16,
    "num_sites": 4,
    "threshold": 0.06,
    "zipf_exponent": 1.03,
    "server0": f"127.0.0.1:{PORT}",
    "server1": f"127.0.0.1:{PORT + 10}",
    "distribution": "rides",
    "f_max": 512,
    "backend": "cpu",
}


def _expected_csv(tmp_path):
    """Oracle: the colocated driver on the same deterministic points
    (tmp cwd has no RideAustin CSV -> the seed-42 synthetic sampler,
    exactly what the leader binary will sample)."""
    coords = rides.load_or_synthesize_locations(
        str(tmp_path / "nonexistent.csv"), N_REQS, seed=42
    )
    pts_bits = np.stack(
        [
            np.stack([bitutils.i16_to_ob_bits(int(v)) for v in row])
            for row in coords
        ]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, CFG["ball_size"], np.random.default_rng(5), engine="np")
    with jax.default_device(jax.devices("cpu")[0]):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(
            s0, s1, n_dims=2, data_len=16, f_max=CFG["f_max"]
        )
        res = lead.run(nreqs=N_REQS, threshold=CFG["threshold"])
    assert res.paths.shape[0] >= 1  # non-degenerate scenario
    out = tmp_path / "expected.csv"
    rides.save_heavy_hitters(res.paths, str(out))
    return out.read_text()


def test_binaries_end_to_end(tmp_path):
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(CFG))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_backend_optimization_level=1"
    ).strip()

    def spawn(mod, *args):
        return subprocess.Popen(
            [sys.executable, "-m", mod, "--config", str(cfg_path), *args],
            cwd=tmp_path, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    report_path = tmp_path / "leader_report.json"
    env["FHH_RUN_REPORT"] = str(report_path)  # one shared path: the leader
    # keeps it bare, each server claims a .s<id> sibling at startup
    s1 = spawn("fuzzyheavyhitters_tpu.bin.server", "--server_id", "1")
    s0 = spawn("fuzzyheavyhitters_tpu.bin.server", "--server_id", "0")
    lead = None
    try:
        lead = spawn("fuzzyheavyhitters_tpu.bin.leader", "-n", str(N_REQS))
        out, _ = lead.communicate(timeout=540)
        assert lead.returncode == 0, f"leader failed:\n{out[-4000:]}"
        assert "crawl.done" in out  # obs-layer telemetry line
        rep = json.loads(report_path.read_text())
        assert rep["schema"] == "fhh-run-report/1"
        assert "level" in rep["registries"]["leader"]["phases"]
        csv_path = tmp_path / "data" / "ride_heavy_hitters.csv"
        assert csv_path.exists(), out[-2000:]
        got = csv_path.read_text()
        # drain the servers: SIGTERM -> SystemExit(143) -> each writes its
        # OWN suffixed report instead of clobbering the leader's
        for p in (s0, s1):
            p.terminate()
        for p in (s0, s1):
            p.communicate(timeout=60)
        for sid in (0, 1):
            srep = json.loads(
                (tmp_path / f"leader_report.s{sid}.json").read_text()
            )
            assert f"server{sid}" in srep["registries"], sorted(
                srep["registries"]
            )
        assert json.loads(report_path.read_text()) == rep  # not clobbered
    finally:
        for p in (s0, s1, lead):
            if p is not None and p.poll() is None:
                p.kill()
    want = _expected_csv(tmp_path)
    assert got == want


def test_mesh_binary_rides_matches_socket_csv(tmp_path):
    """The pod entry point on the flagship rides workload writes the SAME
    heavy-hitter CSV as the socket deployment on identical client points
    (both sample seed-42 synthetic coords via the shared workloads
    sampler)."""
    cfg = dict(CFG)
    del cfg["backend"]  # mesh binary pins its platform via --platform
    cfg_path = tmp_path / "rides_mesh.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
        + " --xla_backend_optimization_level=1"
    ).strip()
    out = subprocess.run(
        [sys.executable, "-m", "fuzzyheavyhitters_tpu.bin.mesh",
         "--config", str(cfg_path), "-n", str(N_REQS), "--platform", "cpu",
         "--devices", "4"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    csv_path = tmp_path / "data" / "ride_heavy_hitters.csv"
    assert csv_path.exists(), out.stdout[-2000:]
    assert csv_path.read_text() == _expected_csv(tmp_path)


def test_mesh_binary_refuses_malicious(tmp_path):
    """malicious mode on the mesh is a DOCUMENTED refusal (one trust
    domain — sketch verification adds nothing there; the socket binaries
    carry the real path)."""
    cfg = dict(CFG, malicious=True)
    cfg_path = tmp_path / "mal.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "fuzzyheavyhitters_tpu.bin.mesh",
         "--config", str(cfg_path), "-n", "4", "--platform", "cpu"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode != 0
    assert "malicious mode refused" in out.stderr


def test_mesh_binary_smoke(tmp_path):
    """The pod-deployment entry point (bin/mesh.py) runs a zipf collection
    on the virtual 2x4 CPU mesh and prints heavy hitters."""
    cfg = {
        "data_len": 8,
        "n_dims": 1,
        "ball_size": 1,
        "addkey_batch_size": 16,
        "num_sites": 4,
        "threshold": 0.1,
        "zipf_exponent": 1.03,
        "server0": "127.0.0.1:1",
        "server1": "127.0.0.1:2",
        "distribution": "zipf",
        "f_max": 64,
    }
    cfg_path = tmp_path / "mesh.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
        + " --xla_backend_optimization_level=1"
    ).strip()
    out = subprocess.run(
        [sys.executable, "-m", "fuzzyheavyhitters_tpu.bin.mesh",
         "--config", str(cfg_path), "-n", "32", "--platform", "cpu"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    assert "crawl.done" in out.stdout + out.stderr  # obs telemetry line
    # NB no hitter-count assertion: the zipf workload appends 8 random
    # augmentation bits per request (leader.rs:331 parity), so leaf-level
    # hitters are luck at smoke scale; hitter correctness is pinned by the
    # driver-oracle tests, this test pins that the BINARY runs end to end
