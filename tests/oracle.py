"""Pure-Python specification oracle for the ibDCF scheme.

A slow, readable re-statement of the reference algorithm, written from the
protocol description (ref: src/ibDCF.rs:84-255, src/prg.rs:92-122), used only
by the test-suite to pin down semantics and to cross-check the JAX
implementation.  The PRG here is SHA-256-based (any length-doubling PRG yields
the same input/output *semantics*; only the key bits differ), but it
faithfully reproduces the reference's quirk of masking the low 4 bits of seed
byte 0 before expansion and deriving the t/y output bits from the masked byte
(prg.rs:97-104) — which makes those output bits constants.  Set
``DERIVED_BITS = True`` to use honest seed-derived bits instead; all semantic
tests must pass either way.

Empirically pinned semantics (full-domain sweeps + hand-trace, see
tests/test_oracle.py) — all comparisons lexicographic in evaluation order,
i.e. plain integer comparisons for MSB-first encodings:

- XOR of the two servers' share bits (y ^ t) for a side=True ("left") key on
  bound l:  [x <  l]   (strict);
- for a side=False ("right") key on bound r:  [x > r]  (strict);
- XOR of the t bits alone: [x == bound prefix];
- hence share-STRING equality across servers over (dim x {left,right}):
  l_i <= x_i <= r_i for every dim — inclusive ball membership — and at an
  internal tree level j, [ball intersects the node's prefix box].

Note: the reference's own `ibdcf_complete`/`test_individual_dcfs`/
`interval_test` asserts encode *different* (mutually inconsistent) claims and
cannot all pass as written — they feed LSB-first `u32_to_bits` encodings into
a lexicographic scheme.  The live protocol is unaffected: its workloads use
MSB-first encodings (ibDCF.rs:175-205, sample_driving_data.rs:25-27).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

SEED_LEN = 16
DERIVED_BITS = False  # reference-observed behavior: constant t/y PRG outputs


def _mask(seed: bytes) -> bytes:
    return bytes([seed[0] & 0xF0]) + seed[1:]


def prg_expand(seed: bytes) -> Tuple[bytes, bytes, Tuple[bool, bool], Tuple[bool, bool]]:
    """Length-doubling PRG: seed -> (left seed, right seed, t bits, y bits)."""
    key = _mask(seed)
    s_l = hashlib.sha256(key + b"L").digest()[:SEED_LEN]
    s_r = hashlib.sha256(key + b"R").digest()[:SEED_LEN]
    if DERIVED_BITS:
        h = hashlib.sha256(key + b"B").digest()[0]
        bits = (h & 1 == 0, h & 2 == 0)
        y_bits = (h & 4 == 0, h & 8 == 0)
    else:
        # prg.rs:103-104 reads the masked byte, so these are always True.
        bits = (key[0] & 0x1 == 0, key[0] & 0x2 == 0)
        y_bits = (key[0] & 0x4 == 0, key[0] & 0x8 == 0)
    return s_l, s_r, bits, y_bits


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


@dataclass
class CorWord:
    seed: bytes
    bits: Tuple[bool, bool]
    y_bits: Tuple[bool, bool]


@dataclass
class IbDcfKey:
    key_idx: bool
    root_seed: bytes
    cor_words: List[CorWord]


@dataclass
class EvalState:
    level: int
    seed: bytes
    bit: bool
    y_bit: bool


def gen_ibdcf(
    alpha_bits, side: bool, rng: np.random.Generator, prg=None
) -> Tuple[IbDcfKey, IbDcfKey]:
    """Keygen (ref: ibDCF.rs:84-119, 138-164)."""
    prg = prg or prg_expand
    seeds = [rng.bytes(SEED_LEN), rng.bytes(SEED_LEN)]
    bits = [False, True]
    cor_words = []
    root = list(seeds)
    for bit in list(np.asarray(alpha_bits, dtype=bool)):
        bit = bool(bit)
        data = [prg(seeds[0]), prg(seeds[1])]
        keep, lose = int(bit), int(not bit)
        cw = CorWord(
            seed=_xor(data[0][:2][lose], data[1][:2][lose]),
            bits=(
                data[0][2][0] ^ data[1][2][0] ^ bit ^ True,
                data[0][2][1] ^ data[1][2][1] ^ bit,
            ),
            y_bits=(
                data[0][3][0] ^ data[1][3][0] ^ (bit and not side),
                data[0][3][1] ^ data[1][3][1] ^ ((not bit) and side),
            ),
        )
        for p in (0, 1):
            new_seed = data[p][:2][keep]
            new_bit = data[p][2][keep]
            if bits[p]:
                new_seed = _xor(new_seed, cw.seed)
                new_bit ^= cw.bits[keep]
            seeds[p] = new_seed
            bits[p] = new_bit
        cor_words.append(cw)
    return (
        IbDcfKey(False, root[0], cor_words),
        IbDcfKey(True, root[1], list(cor_words)),
    )


def eval_init(key: IbDcfKey) -> EvalState:
    return EvalState(0, key.root_seed, key.key_idx, key.key_idx)


def eval_bit(key: IbDcfKey, state: EvalState, direction: bool, prg=None) -> EvalState:
    """One-bit incremental eval (ref: ibDCF.rs:208-227)."""
    s_l, s_r, tau_bits, tau_y = (prg or prg_expand)(state.seed)
    d = int(direction)
    seed = (s_l, s_r)[d]
    new_bit = tau_bits[d]
    new_y = tau_y[d]
    if state.bit:
        cw = key.cor_words[state.level]
        seed = _xor(seed, cw.seed)
        new_bit ^= cw.bits[d]
        new_y ^= cw.y_bits[d]
    new_y ^= state.y_bit
    return EvalState(state.level + 1, seed, new_bit, new_y)


def eval_prefix(key: IbDcfKey, idx, prg=None) -> EvalState:
    state = eval_init(key)
    for b in np.asarray(idx, dtype=bool):
        state = eval_bit(key, state, bool(b), prg=prg)
    return state


def share_bit(state: EvalState) -> bool:
    """The per-server FSS output share bit (ref: ibDCF.rs:249, collect.rs:399-404)."""
    return state.y_bit ^ state.bit


def gen_interval(left_bits, right_bits, rng, prg=None) -> Tuple[list, list]:
    """(left-DCF side=True on left bound, right-DCF side=False on right bound);
    returns per-server pairs (ref: ibDCF.rs:166-173)."""
    lk0, lk1 = gen_ibdcf(left_bits, True, rng, prg=prg)
    rk0, rk1 = gen_ibdcf(right_bits, False, rng, prg=prg)
    return [lk0, rk0], [lk1, rk1]
