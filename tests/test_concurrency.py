"""fhh-race: the interprocedural lock-discipline analyzer and its
runtime sanitizer twin.

Static half (analysis/concurrency.py): positive/negative fixtures per
rule — locked vs unlocked guarded access, transitive callee lock
inheritance through the module call graph, declared ``holds=`` dispatch
contracts, the VERIFIED ``atomic`` contract (flags the moment an await
appears), await-straddling snapshot reads including a reconstruction of
the PR-7 stale-window-id shape, released-then-reacquired locks, inline
module-global guards, scope, and suppressions — plus the repo
self-analysis-at-zero tier-1 gate and the guard-map drift tests tying
pyproject, LintConfig, and the runtime twin tables together.

Runtime half (utils/guards.py): GuardedState assertion semantics
(unlocked access raises, lock-held access passes, cross-task ownership
raises, ``unguarded(reason)`` windows), the off-by-default no-overhead
contract, arming via FHH_DEBUG_GUARDS and Config.debug_guards, a
sanitizer-armed CollectorServer raising on a deliberately unguarded
verb call, the seal-window concurrency regression the analyzer caught,
and a full socket e2e crawl running green with the sanitizer armed.
"""

import asyncio
import json
import os
import subprocess
import sys
import textwrap
from types import SimpleNamespace

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.analysis import (
    LintConfig,
    lint_paths,
    lint_source,
    load_baseline,
    load_config,
)
from fuzzyheavyhitters_tpu.analysis.baseline import removed_rules
from fuzzyheavyhitters_tpu.analysis.rules import RULES_BY_NAME
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import leader_rpc, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader, WindowedIngest
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils import guards
from fuzzyheavyhitters_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 24331

RACE_RULE_NAMES = ("guarded-state-unlocked", "stale-read-across-await")


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the sanitizer e2e exercises the same host-side RPC
    glue as test_rpc; its device programs are the shared crawl kernels."""
    yield


def _race(src, guard_map=None, rule=None,
          relpath="fuzzyheavyhitters_tpu/protocol/fake.py"):
    cfg = LintConfig()
    if guard_map is not None:
        cfg.guards = dict(guard_map)
    rules = (
        [RULES_BY_NAME[rule]]
        if rule
        else [RULES_BY_NAME[r] for r in RACE_RULE_NAMES]
    )
    return lint_source(textwrap.dedent(src), relpath, cfg, rules)


def _names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule: guarded-state-unlocked — lexical locks, call-graph inheritance,
# declared contracts
# ---------------------------------------------------------------------------


def test_unlocked_access_detected_locked_access_clean():
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            return self.state
        async def good(self):
            async with self._lk:
                return self.state
    """
    fs = _race(src, {"Srv.state": "_lk"}, rule="guarded-state-unlocked")
    assert _names(fs) == ["guarded-state-unlocked"]
    assert "Srv.bad" in fs[0].message and "'_lk'" in fs[0].message


def test_constructor_access_is_exempt():
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
            self.state += 1
    """
    assert _race(src, {"Srv.state": "_lk"}) == []


def test_transitive_callee_inherits_callers_locks():
    """A helper reached ONLY from inside lock blocks inherits them; the
    same helper also reached from an unlocked caller does not."""
    clean = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def verb(self):
            async with self._lk:
                return self._helper()
        async def verb2(self):
            async with self._lk:
                return self._helper() + 1
        def _helper(self):
            return self.state
    """
    assert _race(clean, {"Srv.state": "_lk"}) == []
    # now add an UNLOCKED call site: the meet over callers drops the lock
    leaky = clean.replace(
        "        def _helper(self):",
        "        async def bare(self):\n"
        "            return self._helper()\n"
        "        def _helper(self):",
    )
    assert leaky != clean
    fs = _race(leaky, {"Srv.state": "_lk"}, rule="guarded-state-unlocked")
    assert len(fs) == 1 and "Srv._helper" in fs[0].message


def test_holds_contract_silences_dispatched_verb():
    """`# fhh-race: holds=` declares the lock a dynamic dispatcher takes
    (the analyzer cannot see through getattr) — the runtime sanitizer is
    what validates the declaration."""
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        # fhh-race: holds=_lk
        async def verb(self):
            return self.state
    """
    assert _race(src, {"Srv.state": "_lk"}) == []


def test_atomic_contract_exempts_and_is_verified():
    """`# fhh-race: atomic` exempts a suspension-free function — and the
    analyzer VERIFIES the suspension-freedom, so adding an await to the
    'atomic' fast path flags immediately."""
    clean = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        # fhh-race: atomic (event-loop slice: append-only, no awaits)
        async def fast(self):
            self.state += 1
            return self.state
    """
    assert _race(clean, {"Srv.state": "_lk"}) == []
    rotted = clean.replace(
        "            return self.state",
        "            await asyncio.sleep(0)\n            return self.state",
    )
    fs = _race(rotted, {"Srv.state": "_lk"}, rule="guarded-state-unlocked")
    assert len(fs) == 1
    assert "suspension point" in fs[0].message and "await" in fs[0].message


def test_module_global_guard_inline_annotation():
    src = """
    import threading
    _lk = threading.Lock()
    _hits = 0  # fhh-guard: _hits=_lk
    def bump():
        global _hits
        with _lk:
            _hits += 1
    def bad():
        return _hits
    def shadowed():
        _hits = 5  # a LOCAL, not the guarded global
        return _hits
    """
    fs = _race(src, {}, rule="guarded-state-unlocked")
    assert len(fs) == 1 and "'_hits'" in fs[0].message
    assert "bad" in fs[0].message


def test_nested_function_binding_does_not_shadow_module_global():
    """A name bound only inside a NESTED def (parameter or local) lives
    in the inner scope — it must not exempt the outer function's
    unlocked read of the same-named guarded global (review-caught: an
    ast.walk swept nested bindings into the outer 'locals' set)."""
    src = """
    import threading
    _lk = threading.Lock()
    _hits = 0  # fhh-guard: _hits=_lk
    def bad_with_inner_shadow():
        def helper(_hits):
            return _hits  # the PARAMETER: inner scope, clean
        return _hits  # the GLOBAL, unlocked: must flag
    def clean_renamed_def():
        def _hits():
            return 0
        return _hits()  # the nested def's NAME is a real local binding
    """
    fs = _race(src, {}, rule="guarded-state-unlocked")
    assert len(fs) == 1 and "bad_with_inner_shadow" in fs[0].message


def test_rule_scoped_to_race_modules():
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            return self.state
    """
    assert _race(src, {"Srv.state": "_lk"},
                 relpath="fuzzyheavyhitters_tpu/workloads/w.py") == []


def test_suppression_with_justification():
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            # fhh-lint: disable=guarded-state-unlocked (fixture reason)
            return self.state
    """
    assert _race(src, {"Srv.state": "_lk"}) == []


# ---------------------------------------------------------------------------
# rule: stale-read-across-await — the snapshot/await/use atomicity break
# ---------------------------------------------------------------------------


def test_stale_read_across_await_detected():
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            w = self.state
            await self.net()
            return w
        async def net(self):
            pass
    """
    fs = _race(src, {"Srv.state": "_lk"}, rule="stale-read-across-await")
    assert len(fs) == 1
    assert "'w'" in fs[0].message and "'state'" in fs[0].message


def test_lock_held_across_await_is_fresh():
    """asyncio locks stay held through suspension: a snapshot taken and
    used entirely under the owning lock cannot go stale."""
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def good(self):
            async with self._lk:
                w = self.state
                await self.net()
                return w
        async def net(self):
            pass
    """
    assert _race(src, {"Srv.state": "_lk"}) == []


def test_lock_released_then_reacquired_is_stale():
    """Releasing and re-taking the lock around an await does NOT keep a
    pre-release snapshot fresh — the field may have moved in between."""
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            async with self._lk:
                w = self.state
            async with self._lk:
                return w
    """
    fs = _race(src, {"Srv.state": "_lk"}, rule="stale-read-across-await")
    assert len(fs) == 1 and "'w'" in fs[0].message


def test_reread_after_await_is_clean():
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def good(self):
            w = self.state
            await self.net()
            async with self._lk:
                w = self.state
                return w
        async def net(self):
            pass
    """
    assert _race(src, {"Srv.state": "_lk"},
                 rule="stale-read-across-await") == []


def test_every_stale_use_reports_not_just_the_first():
    """One finding PER stale use line, not per snapshot: a suppression
    on the first use must not silently absorb a later unsuppressed use
    of the same stale local (review-caught on the first cut, which set
    a per-taint reported flag)."""
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            w = self.state
            await self.net()
            self.log(w)
            return w
        def log(self, w):
            pass
        async def net(self):
            pass
    """
    fs = _race(src, {"Srv.state": "_lk"}, rule="stale-read-across-await")
    assert len(fs) == 2
    suppressed_first = src.replace(
        "self.log(w)",
        "self.log(w)  # fhh-lint: disable=stale-read-across-await "
        "(test: first use blessed)",
    )
    fs = _race(suppressed_first, {"Srv.state": "_lk"},
               rule="stale-read-across-await")
    assert len(fs) == 1  # the second use still fires on its own line


def test_stale_use_in_while_condition_detected():
    """The loop CONDITION re-evaluates after each body pass: a snapshot
    crossed by a body await is stale when the test runs again on
    iteration 2 (review-caught: the test expression was only visited
    before the body)."""
    src = """
    import asyncio
    class Srv:
        def __init__(self):
            self._lk = asyncio.Lock()
            self.state = 0
        async def bad(self):
            async with self._lk:
                w = self.state
            while w == self.state:
                await self.net()
        async def net(self):
            pass
    """
    fs = _race(src, {"Srv.state": "_lk"}, rule="stale-read-across-await")
    assert len(fs) == 1 and "'w'" in fs[0].message


_SEAL_SHAPE = """
import asyncio
class WIngest:
    def __init__(self):
        self._submit_lock = asyncio.Lock()
        self.window = 0
    async def seal(self):
        {read_outside}async with self._submit_lock:
            {read_inside}await self.call_both({{"window": w}})
            {advance_inside}
        {advance_outside}
    async def call_both(self, req):
        pass
"""


def test_pr7_stale_window_id_shape_fires_and_fixed_form_is_silent():
    """The exact bug class every review round hand-caught: the window id
    snapshotted BEFORE the lock, used to name the window after the
    acquire suspension (and the counter advanced from the stale value
    after release).  The fixed form — read and advance under one lock
    hold — is silent under both rules."""
    buggy = _SEAL_SHAPE.format(
        read_outside="w = self.window\n        ",
        read_inside="",
        advance_inside="pass",
        advance_outside="self.window = w + 1",
    )
    fs = _race(textwrap.dedent(buggy), {"WIngest.window": "_submit_lock"})
    assert "stale-read-across-await" in _names(fs)
    assert any("'window'" in f.message and "PR-7" in f.message for f in fs)
    fixed = _SEAL_SHAPE.format(
        read_outside="",
        read_inside="w = self.window\n            ",
        advance_inside="self.window = w + 1",
        advance_outside="",
    )
    assert _race(textwrap.dedent(fixed),
                 {"WIngest.window": "_submit_lock"}) == []


# ---------------------------------------------------------------------------
# guard-map plumbing: pyproject table, LintConfig, runtime twins
# ---------------------------------------------------------------------------


def test_guards_table_loads_from_pyproject(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.fhh-lint]\n"
        'race_modules = ["pkg"]\n'
        "[tool.fhh-lint.guards]\n"
        '"A.x" = "_lk"\n'
        '"A.y" = "_other"\n'
    )
    cfg = load_config(str(tmp_path))
    # the table REPLACES the shipped defaults (it must be able to retire
    # a binding), and dotted quoted keys parse
    assert cfg.guards == {"A.x": "_lk", "A.y": "_other"}
    assert cfg.race_modules == ("pkg",)


def test_guard_map_drift_pyproject_vs_runtime_twins():
    """One guard map, three copies: pyproject [tool.fhh-lint.guards]
    (operative), LintConfig defaults (covered by test_analysis's drift
    test), and the runtime twin tables the sanitizer arms.  This pins
    pyproject == runtime twins, so an attribute guarded statically is
    exactly the set asserted dynamically."""
    from fuzzyheavyhitters_tpu.protocol import fleet as fleetmod
    from fuzzyheavyhitters_tpu.protocol import sessions as sessmod

    cfg = load_config(REPO)
    want = {
        f"CollectorServer.{a}": lk for a, lk in rpc._SERVER_GUARDS.items()
    }
    want.update({
        f"CollectionSession.{a}": lk
        for a, lk in sessmod._SESSION_GUARDS.items()
    })
    want.update({
        f"WindowedIngest.{a}": lk
        for a, lk in leader_rpc._INGEST_GUARDS.items()
    })
    want.update({
        f"FleetDirectory.{a}": lk
        for a, lk in fleetmod._FLEET_GUARDS.items()
    })
    assert cfg.guards == want


def test_repo_race_self_analysis_at_zero():
    """Tier-1 gate: the interprocedural pass over the declared race scope
    reports ZERO findings — every verb carries its contract, every
    deliberately-unlocked site its verified atomic annotation or written
    suppression, and both real leader-side bugs are fixed."""
    cfg = load_config(REPO)
    race = [RULES_BY_NAME[r] for r in RACE_RULE_NAMES]
    findings, errors = lint_paths(list(cfg.race_modules), cfg, REPO,
                                  rules=race)
    assert errors == []
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# baseline hygiene: a rule rename must not read as a silent burn-down
# ---------------------------------------------------------------------------


def test_removed_rules_names_unknown_ids():
    counts = {
        "bare-print": {"a.py": 1},
        "old-rule": {"a.py": 2, "b.py": 1},
        "ghost-rule": {},
    }
    assert removed_rules(counts, RULES_BY_NAME) == [("old-rule", 2, 3)]


def test_update_baseline_reports_renamed_rule_ids(tmp_path):
    """--update-baseline names every baseline entry whose rule id no
    longer exists (a rename used to shrink the file silently) and drops
    them from the rewrite."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(x):\n    print(x)\n")
    base = tmp_path / "lint_baseline.json"
    base.write_text(json.dumps({
        "schema": "fhh-lint-baseline/1",
        "counts": {
            "renamed-away-rule": {"pkg/mod.py": 2, "pkg/other.py": 1},
            "bare-print": {"pkg/mod.py": 1},
        },
    }))
    (tmp_path / "pyproject.toml").write_text(
        "[tool.fhh-lint]\nprint_scope = [\"pkg\"]\n"
        "baseline = \"lint_baseline.json\"\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "fuzzyheavyhitters_tpu.analysis",
         "pkg", "--update-baseline", "--root", str(tmp_path)],
        cwd=str(tmp_path), capture_output=True, text=True, env=env,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "renamed-away-rule" in proc.stderr
    assert "3 finding(s) across 2 file(s)" in proc.stderr
    counts = load_baseline(str(base))
    assert counts == {"bare-print": {"pkg/mod.py": 1}}, counts


# ---------------------------------------------------------------------------
# runtime sanitizer: GuardedState semantics
# ---------------------------------------------------------------------------


class _Obj:
    def __init__(self):
        self._lk = asyncio.Lock()
        self.state = 0


def test_guarded_state_asserts_and_windows():
    obj = _Obj()
    assert guards.install(obj, {"state": "_lk"}, force=True)
    assert type(obj).__name__ == "Guarded_Obj"

    async def flow():
        with pytest.raises(guards.GuardViolation):
            _ = obj.state
        with pytest.raises(guards.GuardViolation):
            obj.state = 1
        async with obj._lk:
            obj.state = 2
            assert obj.state == 2
        with guards.unguarded("test window (mirrors a written suppression)"):
            assert obj.state == 2

    asyncio.run(flow())


def test_guarded_state_cross_task_ownership():
    """lock.locked() alone is not ownership: an access while ANOTHER task
    holds the lock is exactly the race the lock exists to prevent."""
    obj = _Obj()
    assert guards.install(obj, {"state": "_lk"}, force=True)

    async def flow():
        entered = asyncio.Event()

        async def holder():
            async with obj._lk:
                entered.set()
                await asyncio.sleep(0.05)

        h = asyncio.create_task(holder())
        await entered.wait()
        with pytest.raises(guards.GuardViolation):
            _ = obj.state
        await h

    asyncio.run(flow())


def test_sanitizer_off_by_default_no_overhead(monkeypatch):
    monkeypatch.delenv("FHH_DEBUG_GUARDS", raising=False)
    obj = _Obj()
    assert not guards.install(obj, {"state": "_lk"})
    # the class is untouched: attribute access stays a plain dict lookup,
    # no descriptor hop, no lock wrapping
    assert type(obj) is _Obj
    assert not hasattr(obj._lk, "_fhh_tracked")
    obj.state = 3
    assert obj.state == 3


def test_env_var_arms_install(monkeypatch):
    monkeypatch.setenv("FHH_DEBUG_GUARDS", "1")
    obj = _Obj()
    assert guards.enabled() and guards.install(obj, {"state": "_lk"})
    assert type(obj) is not _Obj


def test_unguarded_requires_reason():
    with pytest.raises(ValueError):
        with guards.unguarded(""):
            pass
    with pytest.raises(ValueError):
        with guards.unguarded("   "):
            pass


def test_sanitizer_raises_on_unlocked_server_access():
    """THE acceptance check: a sanitizer-armed CollectorServer refuses a
    deliberately unguarded access — a verb invoked directly, bypassing
    _dispatch's lock — and accepts the same verb with the lock held."""
    cfg = _cfg(debug_guards=True)
    s = rpc.CollectorServer(0, cfg)
    cs = s._table.default()

    async def flow():
        with pytest.raises(guards.GuardViolation):
            await s.reset({}, cs)  # bypasses _dispatch: lock not held
        async with cs._verb_lock:
            # same verb under the SESSION's owned lock: clean (verbs
            # serialize per collection session, not per server)
            assert await s.reset({}, cs)

    asyncio.run(flow())


# ---------------------------------------------------------------------------
# the seal-window regression fhh-race caught (leader_rpc.py)
# ---------------------------------------------------------------------------


class _StubClient:
    session_id = "sess"
    boot_id = "boot"


class _StubLead:
    """Minimal RpcLeader surface for WindowedIngest: seal verbs answer
    canned identical stats after a real suspension (forcing the racing
    interleave the old pre-lock window-id read was vulnerable to)."""

    def __init__(self):
        self.cfg = SimpleNamespace(debug_guards=False)
        self.c0, self.c1 = _StubClient(), _StubClient()
        self._boot_ids = {}
        self.sealed_reqs = []

    async def _both(self, verb, req):
        assert verb == "window_seal"
        self.sealed_reqs.append(dict(req))
        await asyncio.sleep(0.01)  # a real suspension point
        r = {"keys": 0, "subs": 0, "shed_keys": 0, "rejected": 0}
        return r, dict(r)


def test_concurrent_seals_advance_distinct_windows():
    """Regression for the fhh-race finding: two concurrent seal_window()
    calls must seal windows 0 and 1 and leave the counter at 2.  The old
    form read `self.window` BEFORE taking the submit lock and advanced
    it after release — the loser re-sealed window 0 and ROLLED THE
    COUNTER BACK to 1, wedging later submissions into a sealed window."""
    lead = _StubLead()
    wi = WindowedIngest(lead, checkpoint=False)

    async def flow():
        await asyncio.gather(wi.seal_window(), wi.seal_window())

    asyncio.run(flow())
    assert wi.window == 2
    assert sorted(r["window"] for r in lead.sealed_reqs) == [0, 1]
    assert set(wi._sealed) == {0, 1}


# ---------------------------------------------------------------------------
# e2e: full socket crawl, sanitizer armed, bit-identical to unarmed
# ---------------------------------------------------------------------------


def _cfg(**kw):
    defaults = dict(
        data_len=6,
        n_dims=1,
        ball_size=2,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.1,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{BASE_PORT}",
        server1=f"127.0.0.1:{BASE_PORT + 10}",
        distribution="zipf",
        f_max=128,
    )
    defaults.update(kw)
    return Config(**defaults)


async def _socket_crawl(cfg, keys0, keys1, nreqs, port0, port1):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    peer = port1 + 1
    t1 = asyncio.create_task(s1.start("127.0.0.1", port1, "127.0.0.1", peer))
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(s0.start("127.0.0.1", port0, "127.0.0.1", peer))
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port0)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port1)
    await asyncio.gather(t0, t1)
    lead = RpcLeader(cfg, c0, c1)
    await asyncio.gather(c0.call("reset"), c1.call("reset"))
    await lead.upload_keys(keys0, keys1)
    res = await lead.run(nreqs)
    await asyncio.gather(c0.aclose(), c1.aclose())
    await asyncio.gather(s0.aclose(), s1.aclose())
    return res


def test_e2e_socket_crawl_green_with_sanitizer(rng):
    """A full trusted crawl through the production verb path with the
    sanitizer armed (Config.debug_guards): every guarded access on both
    servers asserts its owning lock, and the results are bit-identical
    to the unarmed run — the sanitizer observes, never perturbs."""
    # (L, d, n, f_max) match test_rpc/test_protocol's d=1 scenarios so
    # the crawl kernels compile once across the suites
    L, n = 6, 40
    pts = np.concatenate(
        [np.full(32, 20), rng.integers(0, 1 << L, size=8)]
    )[:, None]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng)
    plain = asyncio.run(_socket_crawl(
        _cfg(), k0, k1, n, BASE_PORT, BASE_PORT + 10
    ))
    armed = asyncio.run(_socket_crawl(
        _cfg(debug_guards=True), k0, k1, n, BASE_PORT + 2, BASE_PORT + 12
    ))
    np.testing.assert_array_equal(plain.counts, armed.counts)
    np.testing.assert_array_equal(plain.paths, armed.paths)
