"""Field law + edge-case tests vs exact Python integer arithmetic.

Covers the reference's fastfield/field inline suites (ref:
src/fastfield.rs:432-559, src/field.rs:495-623) as property tests.
"""

import numpy as np
import pytest

import fuzzyheavyhitters_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp

from fuzzyheavyhitters_tpu.ops.fields import FE62, F255


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Unit-scale module: run on the CPU backend (see conftest)."""
    yield


P62 = FE62.P
P255 = F255.P


def _rand_ints(rng, n, bound):
    return [int(rng.integers(0, min(bound, 2**63))) if bound < 2**63
            else int.from_bytes(rng.bytes(32), "little") % bound
            for _ in range(n)]


EDGE62 = [0, 1, 2, (1 << 30), (1 << 30) + 1, (1 << 31), P62 - 1, P62 - 2, P62 // 2]


def test_fe62_add_sub_neg_mul(rng):
    xs = EDGE62 + [int(rng.integers(0, P62)) for _ in range(50)]
    ys = list(reversed(xs))
    a = FE62.new(jnp.array(xs, jnp.uint64))
    b = FE62.new(jnp.array(ys, jnp.uint64))
    got_add = FE62.to_numpy_ints(FE62.add(a, b))
    got_sub = FE62.to_numpy_ints(FE62.sub(a, b))
    got_neg = FE62.to_numpy_ints(FE62.neg(a))
    got_mul = FE62.to_numpy_ints(FE62.mul(a, b))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert got_add[i] == (x + y) % P62
        assert got_sub[i] == (x - y) % P62
        assert got_neg[i] == (-x) % P62
        assert got_mul[i] == (x * y) % P62, (x, y)


def test_fe62_new_accepts_any_u64(rng):
    xs = [0, 1, (1 << 62), (1 << 62) + 5, 2**64 - 1, P62, P62 + 1]
    got = FE62.to_numpy_ints(FE62.new(jnp.array(xs, jnp.uint64)))
    for i, x in enumerate(xs):
        assert got[i] == x % P62


def test_fe62_compare():
    a = FE62.new(jnp.array([5, P62 - 1, 7], jnp.uint64))
    b = FE62.new(jnp.array([5, 3, 9], jnp.uint64))
    assert list(np.asarray(FE62.ge(a, b))) == [True, True, False]


def test_fe62_sum(rng):
    xs = [int(rng.integers(0, P62)) for _ in range(1000)]
    got = int(FE62.to_numpy_ints(FE62.sum(FE62.new(jnp.array(xs, jnp.uint64)), axis=0)))
    assert got == sum(xs) % P62


def test_fe62_sample_shape_and_spread(rng):
    words = jnp.array(rng.integers(0, 2**32, size=(256, 4)), jnp.uint32)
    v = FE62.sample(words)
    vals = FE62.to_numpy_ints(v)
    assert len(set(vals.tolist())) > 250  # no collisions expected
    assert all(int(x) < P62 for x in vals)


def _f255_from_ints(xs):
    return jnp.stack([F255.from_int(x) for x in xs])


EDGE255 = [0, 1, 19, 38, (1 << 255) - 20, P255 - 1, P255 // 2, (1 << 256) % P255]


def test_f255_add_sub_neg(rng):
    xs = EDGE255 + [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(30)]
    ys = list(reversed(xs))
    a, b = _f255_from_ints(xs), _f255_from_ints(ys)
    got_add = F255.to_numpy_ints(F255.add(a, b))
    got_sub = F255.to_numpy_ints(F255.sub(a, b))
    got_neg = F255.to_numpy_ints(F255.neg(a))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert int(got_add[i]) == (x + y) % P255
        assert int(got_sub[i]) == (x - y) % P255
        assert int(got_neg[i]) == (-x) % P255


def test_f255_compare_and_eq():
    a = _f255_from_ints([5, P255 - 1, 7, 1 << 200])
    b = _f255_from_ints([5, 3, 9, (1 << 200) + 1])
    assert list(np.asarray(F255.ge(a, b))) == [True, True, False, False]
    assert list(np.asarray(F255.eq(a, b))) == [True, False, False, False]


def test_f255_sum(rng):
    xs = [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(33)]
    got = F255.to_numpy_ints(F255.sum(_f255_from_ints(xs), axis=0))
    assert int(got) == sum(xs) % P255


def test_f255_sample(rng):
    words = jnp.array(rng.integers(0, 2**32, size=(64, 8)), jnp.uint32)
    vals = F255.to_numpy_ints(F255.sample(words))
    assert all(int(x) < P255 for x in vals.ravel())


def test_share_reconstruct_roundtrip(rng):
    """share()/reconstruct semantics (ref: src/lib.rs:42-49): v = s1 - s0... the
    reference reconstructs leader-side as vals0 - vals1 (collect.rs:945-964);
    here: value v shared as (r + v, r)."""
    for F, P in [(FE62, P62), (F255, P255)]:
        v = 123456789 % P
        r = int.from_bytes(rng.bytes(16), "little") % P
        if F is FE62:
            s0 = F.add(F.from_int(r), F.from_int(v))
            s1 = F.from_int(r)
        else:
            s0 = F.add(F.from_int(r), F.from_int(v))
            s1 = F.from_int(r)
        rec = F.to_numpy_ints(F.sub(s0, s1))
        assert int(rec) == v
