"""Field law + edge-case tests vs exact Python integer arithmetic.

Covers the reference's fastfield/field inline suites (ref:
src/fastfield.rs:432-559, src/field.rs:495-623) as property tests.
"""

import numpy as np
import pytest

import fuzzyheavyhitters_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp

from fuzzyheavyhitters_tpu.ops.fields import FE62, F255


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Unit-scale module: run on the CPU backend (see conftest)."""
    yield


P62 = FE62.P
P255 = F255.P


def _rand_ints(rng, n, bound):
    return [int(rng.integers(0, min(bound, 2**63))) if bound < 2**63
            else int.from_bytes(rng.bytes(32), "little") % bound
            for _ in range(n)]


EDGE62 = [0, 1, 2, (1 << 30), (1 << 30) + 1, (1 << 31), P62 - 1, P62 - 2, P62 // 2]


def test_fe62_add_sub_neg_mul(rng):
    xs = EDGE62 + [int(rng.integers(0, P62)) for _ in range(50)]
    ys = list(reversed(xs))
    a = FE62.new(jnp.array(xs, jnp.uint64))
    b = FE62.new(jnp.array(ys, jnp.uint64))
    got_add = FE62.to_numpy_ints(FE62.add(a, b))
    got_sub = FE62.to_numpy_ints(FE62.sub(a, b))
    got_neg = FE62.to_numpy_ints(FE62.neg(a))
    got_mul = FE62.to_numpy_ints(FE62.mul(a, b))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert got_add[i] == (x + y) % P62
        assert got_sub[i] == (x - y) % P62
        assert got_neg[i] == (-x) % P62
        assert got_mul[i] == (x * y) % P62, (x, y)


def test_fe62_new_accepts_any_u64(rng):
    xs = [0, 1, (1 << 62), (1 << 62) + 5, 2**64 - 1, P62, P62 + 1]
    got = FE62.to_numpy_ints(FE62.new(jnp.array(xs, jnp.uint64)))
    for i, x in enumerate(xs):
        assert got[i] == x % P62


def test_fe62_compare():
    a = FE62.new(jnp.array([5, P62 - 1, 7], jnp.uint64))
    b = FE62.new(jnp.array([5, 3, 9], jnp.uint64))
    assert list(np.asarray(FE62.ge(a, b))) == [True, True, False]


def test_fe62_sum(rng):
    xs = [int(rng.integers(0, P62)) for _ in range(1000)]
    got = int(FE62.to_numpy_ints(FE62.sum(FE62.new(jnp.array(xs, jnp.uint64)), axis=0)))
    assert got == sum(xs) % P62


def test_fe62_sample_shape_and_spread(rng):
    words = jnp.array(rng.integers(0, 2**32, size=(256, 4)), jnp.uint32)
    v = FE62.sample(words)
    vals = FE62.to_numpy_ints(v)
    assert len(set(vals.tolist())) > 250  # no collisions expected
    assert all(int(x) < P62 for x in vals)


def _f255_from_ints(xs):
    return jnp.stack([F255.from_int(x) for x in xs])


EDGE255 = [0, 1, 19, 38, (1 << 255) - 20, P255 - 1, P255 // 2, (1 << 256) % P255]


def test_f255_add_sub_neg(rng):
    xs = EDGE255 + [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(30)]
    ys = list(reversed(xs))
    a, b = _f255_from_ints(xs), _f255_from_ints(ys)
    got_add = F255.to_numpy_ints(F255.add(a, b))
    got_sub = F255.to_numpy_ints(F255.sub(a, b))
    got_neg = F255.to_numpy_ints(F255.neg(a))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert int(got_add[i]) == (x + y) % P255
        assert int(got_sub[i]) == (x - y) % P255
        assert int(got_neg[i]) == (-x) % P255


def test_f255_compare_and_eq():
    a = _f255_from_ints([5, P255 - 1, 7, 1 << 200])
    b = _f255_from_ints([5, 3, 9, (1 << 200) + 1])
    assert list(np.asarray(F255.ge(a, b))) == [True, True, False, False]
    assert list(np.asarray(F255.eq(a, b))) == [True, False, False, False]


def test_f255_sum(rng):
    xs = [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(33)]
    got = F255.to_numpy_ints(F255.sum(_f255_from_ints(xs), axis=0))
    assert int(got) == sum(xs) % P255


def test_f255_sample(rng):
    words = jnp.array(rng.integers(0, 2**32, size=(64, 8)), jnp.uint32)
    vals = F255.to_numpy_ints(F255.sample(words))
    assert all(int(x) < P255 for x in vals.ravel())


def test_share_reconstruct_roundtrip(rng):
    """share()/reconstruct semantics (ref: src/lib.rs:42-49): v = s1 - s0... the
    reference reconstructs leader-side as vals0 - vals1 (collect.rs:945-964);
    here: value v shared as (r + v, r)."""
    for F, P in [(FE62, P62), (F255, P255)]:
        v = 123456789 % P
        r = int.from_bytes(rng.bytes(16), "little") % P
        if F is FE62:
            s0 = F.add(F.from_int(r), F.from_int(v))
            s1 = F.from_int(r)
        else:
            s0 = F.add(F.from_int(r), F.from_int(v))
            s1 = F.from_int(r)
        rec = F.to_numpy_ints(F.sub(s0, s1))
        assert int(rec) == v


# ---------------------------------------------------------------------------
# Round-2 surface: mul/recip laws vs Python bignums, U63, Dummy, Block codecs
# (ref law-test templates: fastfield.rs:432-559, field.rs:495-623)
# ---------------------------------------------------------------------------

from fuzzyheavyhitters_tpu.ops.fields import U63, Dummy  # noqa: E402

P63 = U63.P


def test_f255_mul_vs_bignum(rng):
    """8x8-limb mul incl. p-1, fold-boundary (values near 2^256/38 wrap) and
    random pairs — every product checked against exact Python ints."""
    xs = EDGE255 + [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(40)]
    ys = list(reversed(xs))
    a, b = _f255_from_ints(xs), _f255_from_ints(ys)
    got = F255.to_numpy_ints(F255.mul(a, b))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert int(got[i]) == (x * y) % P255, (x, y)


def test_f255_mul_field_laws(rng):
    xs = [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(8)]
    a = _f255_from_ints(xs)
    one = F255.from_int(1)
    # identity, commutativity, distributivity
    np.testing.assert_array_equal(np.asarray(F255.mul(a, one)), np.asarray(a))
    b = _f255_from_ints(list(reversed(xs)))
    np.testing.assert_array_equal(
        np.asarray(F255.mul(a, b)), np.asarray(F255.mul(b, a))
    )
    c = _f255_from_ints([(x * 7 + 3) % P255 for x in xs])
    lhs = F255.mul(a, F255.add(b, c))
    rhs = F255.add(F255.mul(a, b), F255.mul(a, c))
    np.testing.assert_array_equal(np.asarray(lhs), np.asarray(rhs))


def test_f255_recip(rng):
    xs = [1, 2, 19, P255 - 1] + [
        int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(6)
    ]
    xs = [x for x in xs if x != 0]
    a = _f255_from_ints(xs)
    prod = F255.to_numpy_ints(F255.mul(a, F255.recip(a)))
    assert all(int(p) == 1 for p in prod)
    # convention: recip(0) = 0
    z = F255.recip(F255.from_int(0))
    assert int(F255.to_numpy_ints(z)) == 0


def test_fe62_recip(rng):
    xs = [1, 2, P62 - 1, (1 << 30), (1 << 30) + 1] + [
        int(rng.integers(1, P62)) for _ in range(10)
    ]
    a = jnp.array(xs, jnp.uint64)
    prod = FE62.to_numpy_ints(FE62.mul(a, FE62.recip(a)))
    assert all(int(p) == 1 for p in prod)
    assert int(FE62.to_numpy_ints(FE62.recip(FE62.from_int(0)))) == 0


def test_u63_laws_vs_bignum(rng):
    """The reference's u64 group (MODULUS_64 = 2^63 - 25, field.rs:25-26)."""
    edge = [0, 1, 25, P63 - 1, P63 - 25, P63 // 2, (1 << 62)]
    xs = edge + [int(rng.integers(0, P63)) for _ in range(40)]
    ys = list(reversed(xs))
    a = jnp.array(xs, jnp.uint64)
    b = jnp.array(ys, jnp.uint64)
    got_add = U63.to_numpy_ints(U63.add(a, b))
    got_sub = U63.to_numpy_ints(U63.sub(a, b))
    got_mul = U63.to_numpy_ints(U63.mul(a, b))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert int(got_add[i]) == (x + y) % P63
        assert int(got_sub[i]) == (x - y) % P63
        assert int(got_mul[i]) == (x * y) % P63, (x, y)


def test_u63_sum_and_sample(rng):
    xs = [int(rng.integers(0, P63)) for _ in range(500)]
    got = int(U63.to_numpy_ints(U63.sum(jnp.array(xs, jnp.uint64), axis=0)))
    assert got == sum(xs) % P63
    words = jnp.array(rng.integers(0, 2**32, size=(128, 4)), jnp.uint32)
    vals = U63.to_numpy_ints(U63.sample(words))
    assert all(int(v) < P63 for v in vals)
    assert len(set(vals.tolist())) > 120


def test_dummy_group_is_inert(rng):
    a = Dummy.zeros((5,))
    assert not np.asarray(Dummy.add(a, a)).any()
    assert not np.asarray(Dummy.mul(a, a)).any()
    assert np.asarray(Dummy.eq(a, a)).all()
    assert not np.asarray(Dummy.sample(jnp.zeros((5, 4), jnp.uint32))).any()
    assert not np.asarray(Dummy.sum(jnp.zeros((3, 5), jnp.uint32), axis=0)).any()


def test_fe62_block_roundtrip(rng):
    """Block codec (OT payload format, ref: fastfield.rs:414-431)."""
    xs = EDGE62 + [int(rng.integers(0, P62)) for _ in range(20)]
    v = jnp.array(xs, jnp.uint64)
    blocks = FE62.to_blocks(v)
    assert blocks.shape == (len(xs), 4)
    back = FE62.to_numpy_ints(FE62.from_blocks(blocks))
    np.testing.assert_array_equal(back, np.array(xs, np.uint64))
    # high words fold mod p rather than being rejected
    hi = jnp.array([[1, 0, 1, 0]], jnp.uint32)
    folded = FE62.to_numpy_ints(FE62.from_blocks(hi))
    assert int(folded[0]) == (1 + (1 << 64)) % P62


def test_f255_blockpair_roundtrip(rng):
    """BlockPair codec (ref: field.rs:465-492 — F255 OT payloads are two
    128-bit blocks)."""
    xs = EDGE255 + [int.from_bytes(rng.bytes(32), "little") % P255 for _ in range(10)]
    v = _f255_from_ints(xs)
    blocks = F255.to_blocks(v)
    assert blocks.shape == (len(xs), 2, 4)
    back = F255.to_numpy_ints(F255.from_blocks(blocks))
    for i, x in enumerate(xs):
        assert int(back[i]) == x


# ---------------------------------------------------------------------------
# host (NumPy) twins: bit-identical with the device versions
# ---------------------------------------------------------------------------


def test_fe62_np_twins_match_device(rng):
    words = rng.integers(0, 2**32, size=(64, 4), dtype=np.uint32)
    host = FE62.np_sample(words)
    dev = np.asarray(FE62.sample(words))
    np.testing.assert_array_equal(host, dev)
    a = rng.integers(0, P62, size=64, dtype=np.uint64)
    b = rng.integers(0, P62, size=64, dtype=np.uint64)
    np.testing.assert_array_equal(
        FE62.np_add(a, b), np.asarray(FE62.add(a, b))
    )
    # lazily-reduced inputs (the representation FE62 ops produce)
    lazy = FE62.np_add(a, b)
    np.testing.assert_array_equal(
        FE62.np_add(lazy, b), np.asarray(FE62.add(lazy, b))
    )


def test_f255_np_twins_match_device(rng):
    words = rng.integers(0, 2**32, size=(32, 8), dtype=np.uint32)
    host = F255.np_sample(words)
    dev = np.asarray(F255.sample(words))
    np.testing.assert_array_equal(host, dev)
    a, b = F255.np_sample(words), F255.np_sample(words[::-1].copy())
    np.testing.assert_array_equal(
        F255.np_add(a, b), np.asarray(F255.add(jnp.asarray(a), jnp.asarray(b)))
    )
    # edge: operands near p force both the fold and the conditional sub
    top = np.tile(F255.np_sample(
        np.full((1, 8), 0xFFFFFFFF, np.uint32)
    ), (4, 1))
    np.testing.assert_array_equal(
        F255.np_add(top, top),
        np.asarray(F255.add(jnp.asarray(top), jnp.asarray(top))),
    )
