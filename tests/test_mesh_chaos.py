"""ICI-path chaos tests: fault injection for the device-mesh crawl.

The mesh has no sockets to sever — its whole two-party exchange is XLA
collectives — so faults are injected at the level boundaries the host
driver crosses (resilience.chaos.MeshChaos): a dropped data-parallel
shard (device state intact → re-run one level), a participant killed
mid-collective (device frontier clobbered → restore the last host
snapshot), and a delayed participant (no recovery — the level just
stalls).  The acceptance bar mirrors the socket path's: recovered runs
are BIT-IDENTICAL to fault-free ones, with the recovery visible in the
counters and the run report.

Shapes mirror tests/test_mesh.py (L=6, d=2, n=32, 2×4 mesh) so the crawl
kernel family compiles once across both files via the persistent cache.
Everything is pinned to the virtual CPU mesh (conftest) — this suite
must pass under ``JAX_PLATFORMS=cpu``.
"""

import numpy as np
import pytest

import jax

from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.parallel import mesh as meshmod
from fuzzyheavyhitters_tpu.protocol import driver
from fuzzyheavyhitters_tpu.resilience.chaos import (
    MeshChaos,
    MeshFaultError,
    MeshFaultSpec,
    parse_mesh_faults,
)
from fuzzyheavyhitters_tpu.utils import bits as bitutils


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------


def test_parse_mesh_faults_grammar():
    faults = parse_mesh_faults(
        "mesh:drop@level=3;mesh:kill@level=5;mesh:delay@level=1,ms=50"
    )
    assert [f.action for f in faults] == ["drop", "kill", "delay"]
    assert faults[0].at_level == 3
    assert faults[2].ms == 50
    assert parse_mesh_faults("") == [] and parse_mesh_faults(None) == []


@pytest.mark.parametrize(
    "bad",
    [
        "mesh:drop",  # no trigger
        "mesh:drop@ms=5",  # missing level=
        "mesh:explode@level=1",  # unknown action
        "plane:drop@level=1",  # wrong link
        "mesh:drop@level=-1",  # negative level
        "garbage",
    ],
)
def test_parse_mesh_faults_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_mesh_faults(bad)


def test_mesh_chaos_clauses_fire_once():
    """A fired clause must not re-trigger on the recovery re-run of the
    same level (the injector's twin of the proxy's consumed severs)."""

    class R:  # minimal runner stand-in
        frontier = object()
        _children = None

    chaos = MeshChaos([MeshFaultSpec("drop", 2)])
    chaos.before_level(R(), 0)  # below the trigger: nothing
    with pytest.raises(MeshFaultError) as ei:
        chaos.before_level(R(), 2)
    assert not ei.value.state_lost
    chaos.before_level(R(), 2)  # the re-run proceeds
    assert chaos.fired == [("drop", 2)]


# ---------------------------------------------------------------------------
# e2e recovery on the mesh
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def client_batch():
    rng = np.random.default_rng(7)
    L, d, n = 6, 2, 32
    centers = rng.integers(0, 1 << L, size=(3, d))
    pts = centers[rng.integers(0, 3, size=n)] + rng.integers(-1, 2, size=(n, d))
    pts = np.clip(pts, 0, (1 << L) - 1)
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
    return k0, k1, L, d, n


def _as_dict(res):
    return {
        tuple(int(v) for v in row): int(c)
        for row, c in zip(res.decode_ints(), res.counts)
    }


@pytest.fixture(scope="module")
def oracle(client_batch, cpu_devices):
    k0, k1, L, d, n = client_batch
    with jax.default_device(cpu_devices[0]):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=128)
        return _as_dict(lead.run(nreqs=n, threshold=0.1))


def test_mesh_drop_and_kill_recover_bit_identical(
    client_batch, oracle, cpu_devices
):
    """THE mesh acceptance scenario: one crawl suffers BOTH a dropped
    data-parallel shard (level re-run, device state intact) and a killed
    participant (device frontier lost → snapshot restore), plus a delay
    that must NOT trigger recovery — and still produces heavy hitters
    bit-identical to the fault-free run and the colocated oracle, with
    the recovery events visible in the run report."""
    k0, k1, L, d, n = client_batch
    assert oracle

    m = meshmod.make_mesh(devices=cpu_devices)
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128)
    lead = meshmod.MeshLeader(runner)
    res_ff = lead.run_supervised(n, 0.1, checkpoint_every=2)  # fault-free

    chaos = MeshChaos(
        parse_mesh_faults(
            "mesh:delay@level=1,ms=20;mesh:drop@level=2;mesh:kill@level=4"
        )
    )
    runner2 = meshmod.MeshRunner(m, k0, k1, f_max=128)
    lead2 = meshmod.MeshLeader(runner2)
    res = lead2.run_supervised(n, 0.1, checkpoint_every=2, chaos=chaos)

    assert _as_dict(res) == _as_dict(res_ff) == oracle
    np.testing.assert_array_equal(res.paths, res_ff.paths)
    np.testing.assert_array_equal(res.counts, res_ff.counts)

    # the faults fired and were matched to the right recovery:
    assert set(chaos.fired) == {("delay", 1), ("drop", 2), ("kill", 4)}
    assert lead2.obs.counter_value("recoveries") == 2  # delay is NOT one
    assert lead2.obs.counter_value("shards_rerun") == 1  # the drop
    assert lead2.obs.counter_value("levels_rerun") == 1  # the kill

    # ... and are distinguishable from a fault-free run in the report
    rep = obsreport.run_report([lead2.obs])
    assert rep["recovery"]["count"] == 2
    assert rep["recovery"]["shards_rerun"] == 1
    assert rep["recovery"]["levels_rerun"] == 1
    rep_ff = obsreport.run_report([lead.obs])
    assert rep_ff["recovery"]["count"] == 0


def test_mesh_kill_before_first_checkpoint_restarts(client_batch, oracle, cpu_devices):
    """A participant killed before any snapshot exists degrades to
    restart-from-scratch — the crawl, not the run, is lost."""
    k0, k1, L, d, n = client_batch
    m = meshmod.make_mesh(devices=cpu_devices)
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128)
    lead = meshmod.MeshLeader(runner)
    chaos = MeshChaos(parse_mesh_faults("mesh:kill@level=1"))
    res = lead.run_supervised(n, 0.1, checkpoint_every=4, chaos=chaos)
    assert _as_dict(res) == oracle
    assert lead.obs.counter_value("recoveries") == 1


def test_mesh_secure_recovers_bit_identical(client_batch, oracle, cpu_devices):
    """Secure (GC+OT over ppermute) mesh crawl under the same kill+drop
    schedule: share randomness differs per re-run, but the RECONSTRUCTED
    counts must be bit-identical to the trusted oracle."""
    k0, k1, L, d, n = client_batch
    m = meshmod.make_mesh(devices=cpu_devices)
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128, secure_exchange=True)
    lead = meshmod.MeshLeader(runner)
    chaos = MeshChaos(parse_mesh_faults("mesh:drop@level=1;mesh:kill@level=3"))
    res = lead.run_supervised(n, 0.1, checkpoint_every=2, chaos=chaos)
    assert _as_dict(res) == oracle
    assert lead.obs.counter_value("recoveries") == 2


def test_mesh_exhausted_recoveries_reraise(client_batch, cpu_devices):
    """An unrecoverable mesh (every level faulted) must surface the
    MeshFaultError after max_recoveries, not loop forever."""
    k0, k1, L, d, n = client_batch
    m = meshmod.make_mesh(devices=cpu_devices)
    runner = meshmod.MeshRunner(m, k0, k1, f_max=128)
    lead = meshmod.MeshLeader(runner)
    chaos = MeshChaos([MeshFaultSpec("drop", 0) for _ in range(9)])
    with pytest.raises(MeshFaultError):
        lead.run_supervised(n, 0.1, max_recoveries=3, chaos=chaos)
