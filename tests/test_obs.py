"""Telemetry-layer tests: registry semantics, structured log round-trips,
heartbeat lifecycle, the end-to-end run report on a trusted AND a secure
crawl (both socket servers in one process, so the two sides' data-plane
accounting can be asserted consistent against each other), and the guard
that no crawl-path module falls back to bare ``print`` telemetry."""

import asyncio
import gc
import io
import json
import os
import time

import numpy as np
import pytest

from fuzzyheavyhitters_tpu import obs
from fuzzyheavyhitters_tpu.obs import heartbeat as hbmod
from fuzzyheavyhitters_tpu.obs import logs as logsmod
from fuzzyheavyhitters_tpu.obs import metrics as obsmetrics
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

_PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "fuzzyheavyhitters_tpu",
)


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Unit-scale telemetry tests stay on the CPU backend (conftest)."""
    yield


@pytest.fixture
def log_sink():
    """Route emits into a StringIO for the duration of one test, then
    restore the env-derived defaults."""
    sink = io.StringIO()
    old = dict(logsmod._cfg)
    logsmod.configure(fmt="json", stream=sink, min_severity="debug")
    yield sink
    with logsmod._lock:
        logsmod._cfg.update(old)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counter_totals_and_levels():
    reg = obsmetrics.Registry("t-counters")
    reg.count("bytes", 10, level=0)
    reg.count("bytes", 5, level=0)
    reg.count("bytes", 7, level=3)
    reg.count("bytes", 1)  # no level, no active span: total-only
    assert reg.counter_value("bytes") == 23
    assert reg.counter_value("bytes", level=0) == 15
    assert reg.counter_value("bytes", level=3) == 7
    assert reg.counter_value("missing") == 0


def test_span_level_inheritance():
    """A counter incremented inside a span lands on the span's level —
    the mechanism that attributes data-plane bytes deep in the wire
    helpers to the level whose exchange sent them."""
    reg = obsmetrics.Registry("t-inherit")
    with reg.span("gc_ot", level=7):
        reg.count("data_bytes_sent", 100)
        with reg.span("inner"):  # level-less inner span: still level 7
            reg.count("data_bytes_sent", 11)
    assert reg.counter_value("data_bytes_sent", level=7) == 111


def test_span_timer_accumulation_and_current_span():
    reg = obsmetrics.Registry("t-timers")
    assert reg.current_span() is None
    with reg.span("fss", level=2) as sp:
        time.sleep(0.01)
        cur = reg.current_span()
        assert cur is sp and cur.name == "fss" and cur.level == 2
        assert cur.elapsed() > 0
    assert reg.current_span() is None
    assert reg.timer_seconds("fss") >= 0.01
    assert reg.timer_seconds("fss", level=2) >= 0.01
    with reg.span("fss", level=2):
        pass
    rep = reg.report()
    assert rep["phases"]["fss"]["count"] == 2
    assert set(rep["phases"]["fss"]["by_level"]) == {"2"}


def test_gauge_last_write_wins_and_reset():
    reg = obsmetrics.Registry("t-gauges")
    reg.gauge("survivors", 64, level=0)
    reg.gauge("survivors", 16, level=1)
    rep = reg.report()
    assert rep["gauges"]["survivors"]["last"] == 16
    assert rep["gauges"]["survivors"]["by_level"] == {"0": 64, "1": 16}
    reg.reset()
    assert reg.report() == {"counters": {}, "gauges": {}, "phases": {}}


def test_run_report_disambiguates_same_named_registries():
    """Two same-named registries (a second driver.Leader after a
    checkpoint restore) must both survive into the aggregate report,
    keyed deterministically by registration order — not silently
    overwrite each other."""
    a = obsmetrics.Registry("t-dup")
    b = obsmetrics.Registry("t-dup")
    a.count("writes", 1)
    b.count("writes", 2)
    doc = obs.run_report([a, b])
    assert doc["registries"]["t-dup"]["counters"]["writes"]["total"] == 1
    assert doc["registries"]["t-dup#2"]["counters"]["writes"]["total"] == 2
    # all_registries keeps name ties in registration order
    regs = [r for r in obsmetrics.all_registries() if r.name == "t-dup"]
    assert regs == [a, b]


def test_dropped_registry_final_snapshot_survives_into_report():
    """A registry whose owner is dropped still reaches the no-arg run
    report via its retained final snapshot — and retention is bounded,
    with overflow surfaced as ``dropped_registries`` (a long-lived
    process constructing one leader per collection must not grow the
    registry set or the report without bound)."""
    reg = obsmetrics.Registry("t-dropped")
    reg.count("writes", 5, level=3)
    seq = reg.seq
    del reg
    gc.collect()
    assert any(
        n == "t-dropped" and s == seq
        for n, s, _ in obsmetrics.final_snapshots()
    )
    doc = obs.run_report()
    keys = [k for k in doc["registries"] if k.split("#")[0] == "t-dropped"]
    assert keys, sorted(doc["registries"])
    snap = doc["registries"][keys[-1]]
    assert snap["counters"]["writes"]["total"] == 5
    assert snap["counters"]["writes"]["by_level"] == {"3": 5}

    # blow past the retention bound: the oldest snapshots fall off and
    # the report says how many (the cap is never silent)
    before = obsmetrics.final_dropped()
    for i in range(obsmetrics._MAX_FINAL + 5):
        r = obsmetrics.Registry("t-churn")
        r.count("n", i)
        del r
    gc.collect()
    assert len(obsmetrics.final_snapshots()) <= obsmetrics._MAX_FINAL
    assert obsmetrics.final_dropped() > before
    assert obs.run_report()["dropped_registries"] == obsmetrics.final_dropped()


def test_report_is_json_serializable():
    reg = obsmetrics.Registry("t-json")
    reg.count("n", np.int64(3), level=int(np.int32(1)))
    with reg.span("p", level=0):
        pass
    rt = json.loads(json.dumps(reg.report()))
    assert rt["counters"]["n"]["total"] == 3


def test_session_registry_churn_stays_bounded():
    """Satellite regression (PR 13): a long-lived multi-tenant server
    whose collections churn creates one ``server{N}:{key}`` registry per
    session — every dropped one lands in the SAME bounded final-snapshot
    retention as process registries (obs.metrics._MAX_FINAL, oldest
    discarded + counted), so neither the snapshot list nor the no-arg
    run report can grow without bound, and the report stays writable."""
    cap = obsmetrics._MAX_FINAL
    before_live = len(obsmetrics.all_registries())
    for i in range(cap + 40):
        r = obsmetrics.Registry(f"server0:churn{i}")
        r.count("pool_admitted_keys", i, level=0)
        r.observe("level_latency", 0.01)  # hists retained too
        del r
    gc.collect()
    snaps = obsmetrics.final_snapshots()
    assert len(snaps) <= cap
    # the newest churned sessions survived, the oldest fell off COUNTED
    names = [n for n, _s, _r in snaps]
    assert f"server0:churn{cap + 39}" in names
    assert obsmetrics.final_dropped() > 0
    doc = obs.run_report()
    # bounded report: at most cap retained snapshots + the live set
    assert len(doc["registries"]) <= cap + before_live + 8
    assert doc["dropped_registries"] == obsmetrics.final_dropped()
    # a retained per-session snapshot still carries its accounting
    # (counters AND the new latency histograms) into the report
    key = next(
        k for k in doc["registries"]
        if k.startswith(f"server0:churn{cap + 39}")
    )
    snap = doc["registries"][key]
    assert snap["counters"]["pool_admitted_keys"]["total"] == cap + 39
    assert snap["hists"]["level_latency"]["count"] == 1
    # and the sessions rollup keyed them without unbounded growth either
    assert len(doc["sessions"]["per_session"]) <= cap + 8


# ---------------------------------------------------------------------------
# structured logs
# ---------------------------------------------------------------------------


def test_json_lines_round_trip(log_sink):
    obs.emit("crawl.done", seconds=3.21, level=np.int64(5), n=np.uint32(7))
    obs.emit("level.phases", severity="debug", fss_s=np.float64(0.125))
    lines = log_sink.getvalue().strip().splitlines()
    assert len(lines) == 2
    recs = [json.loads(l) for l in lines]
    assert recs[0]["event"] == "crawl.done"
    assert recs[0]["seconds"] == 3.21
    assert recs[0]["level"] == 5 and recs[0]["n"] == 7  # numpy coerced
    assert recs[1]["sev"] == "debug" and recs[1]["fss_s"] == 0.125
    assert all("ts" in r for r in recs)


def test_severity_gating(log_sink):
    logsmod.configure(min_severity="warn")
    obs.emit("quiet", severity="info")
    obs.emit("loud", severity="error", code=1)
    lines = log_sink.getvalue().strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["event"] == "loud"


def test_bad_log_stream_path_degrades_to_stderr(monkeypatch):
    """A misconfigured FHH_LOG_STREAM path degrades logging to stderr
    (warned once) — it must never raise out of emit() and take down the
    crawl that telemetry exists to observe."""
    fake_err = io.StringIO()
    monkeypatch.setattr(logsmod.sys, "stderr", fake_err)
    old_cfg = dict(logsmod._cfg)
    old_opened = dict(logsmod._opened)
    logsmod._opened.update({"path": None, "file": None})
    logsmod.configure(
        fmt="json", stream="/nonexistent-dir/x.log", min_severity="info"
    )
    try:
        obs.emit("survives", code=1)
        obs.emit("survives.again", code=2)  # later emits don't re-raise
    finally:
        with logsmod._lock:
            logsmod._cfg.update(old_cfg)
        logsmod._opened.update(old_opened)
    out = fake_err.getvalue()
    assert out.count("cannot open log stream") == 1  # once, not per emit
    recs = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    assert {r["event"] for r in recs} == {"survives", "survives.again"}


def test_human_format_line():
    sink = io.StringIO()
    old = dict(logsmod._cfg)
    logsmod.configure(fmt="human", stream=sink, min_severity="info")
    try:
        obs.emit("keygen.report", n_keys=8, seconds=1.5)
    finally:
        with logsmod._lock:
            logsmod._cfg.update(old)
    line = sink.getvalue()
    assert "keygen.report" in line and "n_keys=8" in line


# ---------------------------------------------------------------------------
# heartbeat
# ---------------------------------------------------------------------------


def test_heartbeat_names_active_span_and_stops(log_sink):
    reg = obsmetrics.Registry("t-hb")
    hb = hbmod.Heartbeat(interval=0.02)
    hb.start()
    try:
        with reg.span("gc_ot", level=311):
            time.sleep(0.1)
    finally:
        hb.stop()
    hb.join(timeout=2)
    assert not hb.is_alive()  # stops cleanly, not just daemon-abandoned
    recs = [json.loads(l) for l in log_sink.getvalue().strip().splitlines()]
    beats = [
        r for r in recs
        if r["event"] == "heartbeat" and r.get("registry") == "t-hb"
    ]
    assert beats, recs  # a wedged span IS named in the log trail
    assert beats[0]["span"] == "gc_ot" and beats[0]["level"] == 311
    assert beats[0]["elapsed_s"] >= 0


def test_per_process_report_path_and_claim(monkeypatch):
    """Multi-process deployments (socket servers, 2-process mesh) inherit
    ONE FHH_RUN_REPORT path; each party claims a suffixed sibling so the
    last exiter cannot clobber the others' reports."""
    assert obs.per_process_report_path("/tmp/r.json", "s0") == "/tmp/r.s0.json"
    assert obs.per_process_report_path("/tmp/report", "p1") == "/tmp/report.p1"
    monkeypatch.setenv("FHH_RUN_REPORT", "/tmp/r.json")
    obs.claim_report_path("s1")
    assert os.environ["FHH_RUN_REPORT"] == "/tmp/r.s1.json"
    monkeypatch.delenv("FHH_RUN_REPORT")
    obs.claim_report_path("s1")  # no-op when unset
    assert "FHH_RUN_REPORT" not in os.environ


def test_exit_report_sigterm_contract(tmp_path, monkeypatch):
    """The binaries' shared exit contract: inside obs.exit_report() the
    SIGTERM disposition raises SystemExit(143) (so finally blocks run),
    and the run report is written on the way out — including an
    exceptional exit."""
    import signal

    path = tmp_path / "exit_report.json"
    monkeypatch.setenv("FHH_RUN_REPORT", str(path))
    monkeypatch.setenv("FHH_HEARTBEAT_S", "0")  # no thread for this test
    old = signal.getsignal(signal.SIGTERM)
    try:
        with pytest.raises(SystemExit) as e:
            with obs.exit_report():
                handler = signal.getsignal(signal.SIGTERM)
                handler(signal.SIGTERM, None)  # what a real TERM triggers
        assert e.value.code == 143
    finally:
        signal.signal(signal.SIGTERM, old)
    doc = json.loads(path.read_text())
    assert doc["schema"] == "fhh-run-report/1"


def test_start_heartbeat_env_disable(monkeypatch):
    monkeypatch.setenv("FHH_HEARTBEAT_S", "0")
    assert obs.start_heartbeat() is None


def test_start_heartbeat_singleton_and_stop(monkeypatch):
    monkeypatch.setenv("FHH_HEARTBEAT_S", "60")
    try:
        hb1 = obs.start_heartbeat()
        hb2 = obs.start_heartbeat()
        assert hb1 is hb2 and hb1.is_alive()
    finally:
        obs.stop_heartbeat()


# ---------------------------------------------------------------------------
# end-to-end run reports: trusted colocated driver + both socket modes
# ---------------------------------------------------------------------------


def _keys(L, n):
    rng = np.random.default_rng(7)
    pts = np.concatenate([np.full(n - 3, 5), rng.integers(0, 1 << L, 3)])[
        :, None
    ]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


def test_trusted_driver_run_report(tmp_path, monkeypatch):
    """The colocated driver's registry carries per-level phase seconds,
    fetch counts, and survivor gauges — and FHH_RUN_REPORT lands it all
    in one machine-readable document."""
    L, n = 2, 8
    k0, k1 = _keys(L, n)
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=16)
    res = lead.run(nreqs=n, threshold=0.3)
    assert res.paths.shape[0] >= 1

    rep = lead.obs.report()
    for phase in ("level", "fss", "field", "advance"):
        by_level = rep["phases"][phase]["by_level"]
        assert set(by_level) == {"0", "1"}, (phase, by_level)
        assert all(v >= 0 for v in by_level.values())
    # one counts fetch per level
    assert rep["counters"]["device_fetches"]["total"] == L
    assert set(rep["gauges"]["survivors"]["by_level"]) == {"0", "1"}

    path = tmp_path / "report.json"
    monkeypatch.setenv("FHH_RUN_REPORT", str(path))
    assert obs.maybe_write_run_report([lead.obs]) == str(path)
    doc = json.loads(path.read_text())
    assert doc["schema"] == "fhh-run-report/1"
    assert doc["registries"]["driver"]["phases"]["fss"]["by_level"]["1"] >= 0


@pytest.mark.parametrize("secure_exchange", [False, True], ids=["trusted", "secure"])
def test_socket_run_report_two_servers_consistent(secure_exchange):
    """Both collector servers in one process over real sockets: the run
    report's per-level phase keys, device-fetch counts, and data-plane
    byte counts are populated on BOTH sides, and one side's bytes sent
    equal the other's bytes received (same framed stream)."""
    L, n = 2, 12
    port = 21871 if secure_exchange else 21851
    k0, k1 = _keys(L, n)
    cfg = Config(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=8,
        num_sites=4, threshold=0.2, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=16, secure_exchange=secure_exchange,
    )

    async def run():
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
        )
        c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
        await asyncio.gather(t0, t1)
        lead = RpcLeader(cfg, c0, c1)
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        await lead.upload_keys(k0, k1)
        res = await lead.run(n)
        # close everything: a leaked listener (held alive by reference
        # cycles until a gc pass) keeps its PORT bound for an arbitrary
        # stretch of the suite — test_resilience's +220 scenario shares
        # this port family and failed EADDRINUSE on exactly that
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
        return res, lead, s0, s1

    res, lead, s0, s1 = asyncio.run(run())
    assert res.paths.shape[0] >= 1

    r0, r1 = s0.obs.report(), s1.obs.report()
    levels = {str(l) for l in range(L)}
    for rep in (r0, r1):
        for phase in ("fss", "gc_ot", "field"):
            assert levels <= set(rep["phases"][phase]["by_level"]), (
                phase, rep["phases"][phase]
            )
        assert rep["counters"]["device_fetches"]["total"] > 0
        assert rep["counters"]["data_bytes_sent"]["total"] > 0
        if secure_exchange:
            assert rep["counters"]["gc_tests"]["total"] > 0
            assert rep["gauges"]["ot_batch_size"]["last"] > 0
    # the two ends of one framed stream must agree byte-for-byte
    s0_sent = r0["counters"]["data_bytes_sent"]["total"]
    s1_recv = r1["counters"]["data_bytes_recv"]["total"]
    s1_sent = r1["counters"]["data_bytes_sent"]["total"]
    s0_recv = r0["counters"]["data_bytes_recv"]["total"]
    assert s0_sent == s1_recv and s1_sent == s0_recv
    if secure_exchange:  # both sides run the same per-level test batch
        assert (
            r0["counters"]["gc_tests"]["by_level"]
            == r1["counters"]["gc_tests"]["by_level"]
        )
    # leader-side registry: a level span per crawl level
    assert levels <= set(lead.obs.report()["phases"]["level"]["by_level"])
    # the aggregate document carries every component
    doc = obs.run_report([s0.obs, s1.obs, lead.obs])
    assert set(doc["registries"]) >= {"server0", "server1", "leader"}


# ---------------------------------------------------------------------------
# guard: no bare print() telemetry in crawl-path modules
# ---------------------------------------------------------------------------


def test_no_bare_print_in_package():
    """Crawl-path telemetry goes through obs.emit — a bare print() in the
    package is either a debug leftover or a regression to the stdout
    scraping this layer replaced.

    This guard's AST walk was generalized into fhh-lint's ``bare-print``
    rule; delegating keeps ONE allowlist (pyproject ``[tool.fhh-lint]``
    ``print_allowed``) instead of a drifting copy here.  The self-lint
    test in test_analysis.py enforces the full rule set; this asserts the
    specific print contract survives any baseline/severity tuning."""
    from fuzzyheavyhitters_tpu.analysis import lint_paths, load_config
    from fuzzyheavyhitters_tpu.analysis.rules import RULES_BY_NAME

    repo = os.path.dirname(_PKG)
    findings, errors = lint_paths(
        ["fuzzyheavyhitters_tpu"], load_config(repo), repo,
        rules=[RULES_BY_NAME["bare-print"]],
    )
    assert errors == []
    assert not findings, (
        "bare print() telemetry found (use fuzzyheavyhitters_tpu.obs.emit): "
        + ", ".join(f"{f.path}:{f.line}" for f in findings)
    )
