"""The secure-device bench's contention guard, pinned with canned timers.

The guard exists for the shared chip's multi-minute ~15x-slow windows
(bench.bench_secure_device): when any measured side lands far above the
secure/trusted design ratio, the bench waits once, re-measures every
affected side, and reports ratios computed from the post-retry numbers.
Those semantics (trigger condition, min-merge, retry flag, ratio
consistency) are pure control flow around the timer — so they are testable
on CPU by patching the steady-state timer with a scripted value sequence;
the level programs themselves still run once each (the correctness pin
inside the bench asserts secure counts == trusted counts on every engine).
"""

import os

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    # importing bench flips prg.CHACHA_UNROLL to the chip-friendly unrolled
    # form; import it FIRST (so its module-level assignment has happened),
    # then force the scan form back both for this test's compiles and for
    # the rest of the suite (the flag is process-global and read at trace
    # time — leaking True makes every later CPU compile pathologically slow)
    import bench  # noqa: F401

    from fuzzyheavyhitters_tpu.ops import prg

    prg.CHACHA_UNROLL = False
    yield
    prg.CHACHA_UNROLL = False


def test_contention_retry_min_merges_and_reports(monkeypatch):
    import bench
    from fuzzyheavyhitters_tpu.protocol import secure

    assert secure.EQ_OT4  # the S = 2 default: the gc-path A/B leg runs too

    # call order inside bench_secure_device on a CPU host (no Pallas GC,
    # with_l512=False): gc_path, fe62, f255, trusted -> guard trips ->
    # retry fe62, f255, gc_path, trusted -> 2x-bucket point
    script = iter([
        0.100,  # gc_path   (contended window)
        0.100,  # fe62      (contended window)
        0.020,  # f255      (contended window too: also > 8x trusted)
        0.001,  # trusted   -> fe62/trusted = 100 > 8: retry
        0.002,  # retry fe62
        0.003,  # retry f255
        0.004,  # retry gc_path
        0.001,  # retry trusted
        0.003,  # 2x bucket
    ])
    monkeypatch.setattr(
        bench, "_steady_state_seconds",
        lambda thunk, force, warm_force, iters=20, trials=3: next(script),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    out = bench.bench_secure_device(n=128, L=4, f_bucket=1, with_l512=False)

    assert out["contention_retry"] is True
    # min-merge: the retried (clean) numbers replace the contended ones
    assert out["secure_device_ms_per_level_fe62"] == 2.0
    assert out["secure_device_ms_per_level_f255"] == 3.0
    assert out["secure_device_ms_per_level_fe62_gc_path"] == 4.0
    assert out["trusted_same_shape_ms_per_level"] == 1.0
    # ratios are computed AFTER the retry, from the reported numbers
    assert out["secure_over_trusted_ratio"] == 2.0
    assert out["ot4_speedup_vs_gc_path"] == 2.0


def test_no_retry_on_clean_window(monkeypatch):
    import bench

    script = iter([
        0.004,  # gc_path
        0.003,  # fe62
        0.003,  # f255
        0.001,  # trusted -> ratio 3: no retry
        0.005,  # 2x bucket
    ])
    monkeypatch.setattr(
        bench, "_steady_state_seconds",
        lambda thunk, force, warm_force, iters=20, trials=3: next(script),
    )
    monkeypatch.setattr(
        bench.time, "sleep",
        lambda s: (_ for _ in ()).throw(AssertionError("slept on clean window")),
    )

    out = bench.bench_secure_device(n=128, L=4, f_bucket=1, with_l512=False)
    assert "contention_retry" not in out
    assert out["secure_over_trusted_ratio"] == 3.0
    np.testing.assert_allclose(out["ot4_speedup_vs_gc_path"], 4 / 3, rtol=0.02)


def _pids_with_cmdline(marker: str) -> list[int]:
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                if marker.encode() in f.read():
                    pids.append(int(pid))
        except OSError:
            pass  # raced a process exit
    return pids


@pytest.mark.skipif(
    not os.path.isdir("/proc"), reason="needs procfs to observe the child"
)
def test_subprocess_metric_kills_child_on_teardown():
    """A driver SIGTERM / Ctrl-C landing while the parent is blocked in
    communicate() must still TERM the child bench: the parent's
    SIGTERM->SystemExit handler raises a BaseException that skips the
    TimeoutExpired path, and a leaked child would keep crawling the
    accelerator after the bench is gone."""
    import signal

    import bench

    marker = f"fhh_teardown_probe_{os.getpid()}"
    old = signal.signal(
        signal.SIGALRM,
        lambda *_: (_ for _ in ()).throw(KeyboardInterrupt()),
    )
    try:
        signal.setitimer(signal.ITIMER_REAL, 1.0)
        with pytest.raises(KeyboardInterrupt):
            bench._subprocess_metric(
                f"import time  # {marker}\ntime.sleep(120)", timeout_s=60
            )
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)
    # the child was reaped before the interrupt propagated
    assert _pids_with_cmdline(marker) == []
