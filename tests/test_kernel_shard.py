"""Row-sharded secure kernel stage (parallel/kernel_shard.py): the
byte-identical-wire contract.

The multi-chip kernel stage partitions the whole-level planar test batch
along its row/block axis and runs IKNP extension + equality kernels +
b2a per mesh shard.  The contract under test: at EVERY shard count the
wire — the receiver's u-matrix and the sender's planar frame — is
byte-for-byte the single-device output (pad region included), the b2a
share values match per test, and the OT session cursors stay in
lockstep with a single-device peer.  Exercised on the conftest 8-device
CPU mesh; the Pallas engines run under shard_map in interpret mode
against the XLA twins (the per-shard parity oracle).
"""

import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

from fuzzyheavyhitters_tpu.ops import baseot, gc, otext
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.parallel import kernel_shard
from fuzzyheavyhitters_tpu.protocol import secure

# 8 planar blocks with a real pad region in play: every shard count in
# {2, 4, 8} divides the block count, the last shard carries the global
# pad slots, and B*S straddles a u-matrix word boundary
B = 8 * kernel_shard.BLOCK - 1234
S = 2  # n_dims = 1: the cheapest planar shape (the width is a static
# of every program; wider S re-runs the same sharding math per plane)


@pytest.fixture(scope="module")
def ot_material():
    s_bits = otext.fresh_s_bits()
    seeds0, seeds1, chosen = baseot.exchange(s_bits)
    return s_bits, seeds0, seeds1, chosen


def _pair(m):
    s_bits, seeds0, seeds1, chosen = m
    return (
        otext.OtExtSender(s_bits, chosen),
        otext.OtExtReceiver(seeds0, seeds1),
    )


@pytest.fixture(scope="module")
def flat_bits():
    rng = np.random.default_rng(0)
    return rng.integers(0, 2, size=(B, S)).astype(bool)


_SEEDZ = np.zeros(4, np.uint32)
GSEED = secure.derive_seed(_SEEDZ, 1, 0)
BSEED = secure.derive_seed(_SEEDZ, 2, 0)

# single-device references, one per (path, field) — shared across the
# shard-count legs (the reference is the expensive half of each case)
_refs: dict = {}


def _reference(m, flat, path, field):
    key = (path, field.__name__)
    if key not in _refs:
        snd, rcv = _pair(m)
        u, t_rows, idx0 = secure.ev_step1_fused(rcv, flat)
        u_np = np.asarray(u)
        msg, vals_s = secure.gb_step_level(
            snd, u_np, flat, GSEED, BSEED, field, 0, path=path
        )
        msg_np = np.asarray(msg)
        vals_r = secure.ev_open_level(
            t_rows, flat, msg_np, B, S, field, idx0, path=path
        )
        _refs[key] = (
            u_np, msg_np,
            np.asarray(field.canon(vals_s)), np.asarray(field.canon(vals_r)),
            snd.consumed, snd.stream_offset, rcv.consumed,
        )
    return _refs[key]


def _sharded_flat(ks, flat):
    fp = np.zeros((ks.bp, S), bool)
    fp[:B] = flat
    return jax.device_put(fp, ks.sharding(P(kernel_shard.DATA, None)))


@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
@pytest.mark.parametrize("path", ["ot2s", "gc"])
@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_wire_byte_identity(k, path, field, ot_material, flat_bits):
    """THE kernel-sharding acceptance: u-matrix and planar frame
    byte-identical to the single-device wire at shards {1, 2, 4, 8} on
    both equality paths and both fields, share values equal per test,
    session cursors in lockstep."""
    u_ref, msg_ref, vs_ref, vr_ref, s_cons, s_off, r_cons = _reference(
        ot_material, flat_bits, path, field
    )
    if k == 1:
        # k = 1 IS the reference path (bind refuses a 1-shard kernel
        # mesh; the server keeps the gather layout) — pin the refusal
        assert kernel_shard.bind(
            tuple(jax.devices()[:2]), B, S, budget=1
        ) is None
        return
    ks = kernel_shard.bind(tuple(jax.devices()[:k]), B, S, budget=k)
    assert ks is not None and ks.k == k
    snd, rcv = _pair(ot_material)
    fdev = _sharded_flat(ks, flat_bits)
    u_np, msg_np, vals_s, vals_r = kernel_shard.run_level_pair(
        ks, snd, rcv, fdev, fdev, GSEED, BSEED, field, 0, path
    )
    np.testing.assert_array_equal(u_np, u_ref)
    np.testing.assert_array_equal(msg_np, msg_ref)
    np.testing.assert_array_equal(
        np.asarray(field.canon(vals_s))[:B], vs_ref
    )
    np.testing.assert_array_equal(
        np.asarray(field.canon(vals_r))[:B], vr_ref
    )
    # lockstep: a sharded endpoint must present the same session cursors
    # as a single-device peer (the stream reads past the cursor for pad
    # rows never consume)
    assert snd.consumed == s_cons and snd.stream_offset == s_off
    assert rcv.consumed == r_cons


@pytest.mark.parametrize("path", ["ot2s", "gc"])
def test_pallas_under_shard_map_parity(path, ot_material):
    """shard_map-Pallas vs XLA-twin per-shard parity (interpret mode):
    the fused planar kernels run per shard under shard_map and emit the
    byte-identical wire — the engine contract of gc_pallas/otext_pallas
    extended to the sharded stage."""
    rng = np.random.default_rng(1)
    b = 2 * kernel_shard.BLOCK
    flat = rng.integers(0, 2, size=(b, S)).astype(bool)
    ks = kernel_shard.bind(tuple(jax.devices()[:2]), b, S, budget=2)
    fdev = jax.device_put(flat, ks.sharding(P(kernel_shard.DATA, None)))
    outs = {}
    for eng in ("xla", "pallas_interpret"):
        snd, rcv = _pair(ot_material)
        u_np, msg_np, _, vals_r = kernel_shard.run_level_pair(
            ks, snd, rcv, fdev, fdev, GSEED, BSEED, FE62, 0, path,
            engine=eng,
        )
        outs[eng] = (u_np, msg_np, np.asarray(FE62.canon(vals_r))[:b])
    for got, want in zip(outs["pallas_interpret"], outs["xla"]):
        np.testing.assert_array_equal(got, want)


def test_extend_rows_match_full_extension(ot_material):
    """Row-sharded extension slices: ``sender/receiver_extend_rows``
    reproduce exactly rows [row0, row0 + m) of a full extend — the
    32-word/16-block CTR alignment the planar shard layout guarantees."""
    m_total = 4096
    flat = np.zeros(m_total, bool)
    flat[::3] = True
    snd, rcv = _pair(ot_material)
    u, t = rcv.extend(flat)
    q = snd.extend(m_total, np.asarray(u))
    snd2, rcv2 = _pair(ot_material)
    for row0 in (0, 512, 2048):
        m = 1024
        w0 = row0 // 32
        u_slice, t_slice = otext.receiver_extend_rows(
            *rcv2.shard_state, flat[row0 : row0 + m], 0, row0, m
        )
        np.testing.assert_array_equal(
            np.asarray(t_slice), np.asarray(t)[row0 : row0 + m]
        )
        np.testing.assert_array_equal(
            np.asarray(u_slice), np.asarray(u)[:, w0 : w0 + m // 32]
        )
        q_slice = otext.sender_extend_rows(
            *snd2.shard_state, np.asarray(u)[:, w0 : w0 + m // 32], 0,
            row0, m,
        )
        np.testing.assert_array_equal(
            np.asarray(q_slice), np.asarray(q)[row0 : row0 + m]
        )


def test_carve_label_words_shard_slices():
    """Shard label/mask carving seeks the CTR stream to the exact words
    of the full draw — including the mask region's static intra-block
    offset (an odd B puts it mid-block) and the zero pad tests."""
    b, s = 20001, 2  # B*S*4 % 16 = 8: mask region starts mid-block
    bp = 3 * kernel_shard.BLOCK
    seed = np.arange(4, dtype=np.uint32)
    _, (X0,), mask = gc._carve_label_words(seed, b, s, 1, with_r=False)
    X0, mask = np.asarray(X0), np.asarray(mask)
    for t0, bloc in ((0, kernel_shard.BLOCK), (kernel_shard.BLOCK, 2 * kernel_shard.BLOCK)):
        X0s, masks = gc._carve_label_words_shard(seed, b, s, t0, bloc)
        X0s, masks = np.asarray(X0s), np.asarray(masks)
        hi = min(t0 + bloc, b)
        np.testing.assert_array_equal(X0s[: hi - t0], X0[t0:hi])
        np.testing.assert_array_equal(masks[: hi - t0], mask[t0:hi])
        # pad tests carve to zero (the wire's planar pad contract)
        assert not X0s[hi - t0 :].any() and not masks[hi - t0 :].any()
    assert t0 + bloc == bp  # the loop covered the whole padded frame
