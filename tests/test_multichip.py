"""Multi-chip collector servers: client-axis sharding over each server's
local device mesh (parallel/server_mesh.py + protocol/rpc.py).

Exercised on the 8-device virtual CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8``).  The contract under test:
sharding is a PHYSICAL layout — a sharded server is bit-identical to a
single-device one in every mode (trusted, secure on both equality-test
paths, malicious/sketch), the wire and the leader cannot tell them
apart, and a lost data device is recovered by re-sharding from the
host-side checkpoint (``shards_rerun``), never by a server-loss
recovery (``levels_rerun`` stays zero).
"""

import asyncio
import tempfile

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.parallel import kernel_shard, server_mesh
from fuzzyheavyhitters_tpu.protocol import rpc, sketch
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.resilience.chaos import (
    MeshChaos,
    parse_mesh_faults,
)
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

# below the kernel's ephemeral source-port range (32768+) INCLUDING the
# +8200 top offset: a leader-side client's ephemeral socket must never
# land on a later test's hard-coded listener port (EADDRINUSE flakes)
BASE_PORT = 23810

L, N_CLIENTS, D = 5, 12, 1


def _cfg(port_base, **kw):
    # f_max=8 keeps the per-bucket program ladder small on XLA:CPU (the
    # sharded variants each compile their own SPMD programs)
    defaults = dict(
        data_len=L,
        n_dims=D,
        ball_size=1,
        addkey_batch_size=12,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=8,
    )
    defaults.update(kw)
    return Config(**defaults)


@pytest.fixture(scope="module")
def client_keys():
    rng = np.random.default_rng(77)
    pts = np.concatenate(
        [np.full((N_CLIENTS - 4, D), 11),
         rng.integers(0, 1 << L, size=(4, D))]
    )
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return pts_bits, ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


@pytest.fixture(scope="module")
def sketch_keys(client_keys):
    rng = np.random.default_rng(78)
    pts_bits, _ = client_keys
    seeds = rng.integers(
        0, 2**32, size=(N_CLIENTS, D, 2, 4), dtype=np.uint32
    )
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    return sketch.gen(seeds, pts_bits, FE62, F255, cseed)


async def _crawl(cfg, port, k0, k1, sk0=None, sk1=None, *, warmup=False,
                 chaos=None, ckpt_dir=None, supervised=False,
                 n_clients=N_CLIENTS):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir, _mesh_chaos=chaos)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
    )
    await asyncio.gather(t0, t1)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    try:
        if supervised:
            res = await lead.run_supervised(
                n_clients, k0, k1, sk0, sk1, checkpoint_every=1,
                warmup=warmup,
            )
        else:
            await lead._both("reset")
            await lead.upload_keys(k0, k1, sk0, sk1)
            if warmup:
                await lead.warmup()
            res = await lead.run(n_clients)
        status0 = await c0.call("status")
        report = obsreport.run_report([s0.obs, s1.obs, lead.obs])
    finally:
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
    return res, status0, report


def _run(cfg, port, k0, k1, **kw):
    return asyncio.run(_crawl(cfg, port, k0, k1, **kw))


def test_largest_divisor_shard_binding():
    """Shard counts must tile the client batch: a prime batch degrades
    to one shard, non-divisible requests fall to the largest divisor."""
    f = server_mesh._largest_divisor_leq
    assert f(12, 4) == 4
    assert f(12, 8) == 6
    assert f(13, 8) == 1
    assert f(12, 1) == 1
    m = server_mesh.ServerMesh(4).bind(6)
    assert m.shards == 3 and m.occupancy() == [2, 2, 2]
    m.bind(12)
    assert m.shards == 4 and m.occupancy() == [3, 3, 3, 3]


@pytest.mark.parametrize(
    "mode",
    [
        "trusted",
        "secure_ot2s",
        "secure_gc",
        # ~40 s on one core; sketch sharding parity is also covered
        # by test_sketch_shard — tier-1 keeps the other three modes
        pytest.param("sketch", marks=pytest.mark.slow),
    ],
)
def test_sharded_vs_single_device_bit_identical(mode, client_keys,
                                                sketch_keys):
    """THE multichip acceptance: data_shards ∈ {2, 4} crawls are
    bit-identical to the single-device crawl — trusted, secure on BOTH
    equality-test paths, and malicious (sketch) mode — and the sharded
    servers report mesh health through ``status`` and the run report."""
    _, (k0, k1) = client_keys
    sk0 = sk1 = None
    kw = {}
    if mode == "secure_ot2s":
        kw = dict(secure_exchange=True, ot_path="ot2s")
    elif mode == "secure_gc":
        kw = dict(secure_exchange=True, ot_path="gc")
    elif mode == "sketch":
        sk0, sk1 = sketch_keys
    port = BASE_PORT + 40 * (
        ["trusted", "secure_ot2s", "secure_gc", "sketch"].index(mode)
    )
    base = None
    for i, shards in enumerate((1, 2, 4)):
        cfg = _cfg(port + 1200 * i, server_data_devices=shards, **kw)
        res, status0, report = _run(
            cfg, port + 1200 * i, k0, k1, sk0=sk0, sk1=sk1
        )
        assert res.paths.shape[0] >= 1
        if shards == 1:
            base = res
            assert status0["mesh"] is None
            assert "mesh" not in report
            continue
        # bit-identity: the leader-visible result is byte-for-byte the
        # single-device one (sharding is a physical layout, the 2PC
        # transcript and reconstruction never change)
        np.testing.assert_array_equal(base.paths, res.paths)
        np.testing.assert_array_equal(base.counts, res.counts)
        # mesh health: status names devices/shards/occupancy and the
        # run report rolls the mesh section up
        m = status0["mesh"]
        assert m["data_shards"] == shards
        assert m["shard_clients"] == [N_CLIENTS // shards] * shards
        assert m["ici_reduce_seconds"] > 0
        assert report["mesh"]["data_shards"] == shards
        assert report["mesh"]["ici_reduce_seconds"] > 0
        assert report["mesh"]["reshards"] == 0
        levels = report["mesh"]["by_level"]
        assert set(levels) == {str(lv) for lv in range(L)}


def test_device_loss_reshards_not_restarts(client_keys):
    """Kill one simulated data device mid-level (the 2-D mesh path's
    ``mesh:kill`` chaos clause reused): the server re-shards its
    frontier from the host-side checkpoint IN PLACE and re-runs the
    level's crawl inside the same verb — results bit-identical, the
    recovery section counts a shard re-run and ZERO level re-runs (a
    lost device is not a lost server: no restart, no scratch restart,
    no leader recovery wave)."""
    _, (k0, k1) = client_keys
    port = BASE_PORT + 600
    base, _, _ = _run(
        _cfg(port, server_data_devices=1, secure_exchange=True), port,
        k0, k1,
    )
    chaos = MeshChaos(parse_mesh_faults("mesh:kill@level=3"))
    with tempfile.TemporaryDirectory() as td:
        res, status0, report = _run(
            _cfg(port + 1200, server_data_devices=2, secure_exchange=True),
            port + 1200, k0, k1,
            chaos=chaos, ckpt_dir=td, supervised=True,
        )
    assert chaos.fired == [("kill", 3)]
    np.testing.assert_array_equal(base.paths, res.paths)
    np.testing.assert_array_equal(base.counts, res.counts)
    # the recovery happened at DEVICE granularity: one shard re-run, no
    # completed level re-ran, no supervisor recovery wave fired
    rec = report["recovery"]
    assert rec["shards_rerun"] >= 1
    assert rec["levels_rerun"] == 0
    assert rec["count"] == 0
    assert report["mesh"]["reshards"] == 1
    assert report["mesh"]["faults"] == 1
    assert status0["mesh"]["reshards"] == 1


def test_device_loss_without_checkpoint_escalates(client_keys):
    """A lost device with no checkpoint to re-shard from must surface
    loudly to the leader (supervisor-level recovery owns it), never
    silently crawl on clobbered state."""
    _, (k0, k1) = client_keys
    port = BASE_PORT + 3200
    chaos = MeshChaos(parse_mesh_faults("mesh:kill@level=2"))
    cfg = _cfg(port, server_data_devices=2)
    with pytest.raises(RuntimeError, match="no level-1 checkpoint"):
        _run(cfg, port, k0, k1, chaos=chaos)


L_K, N_K = 4, 1024  # kernel-sharded e2e shape: the last level's
# bucket-8 rung puts 16384 tests on the planar frame (2 blocks), so
# the deep level runs the ROW-SHARDED kernel stage while the shallow
# ones degrade to the gather path — both layouts in one crawl


@pytest.fixture(scope="module")
def kernel_keys():
    rng = np.random.default_rng(99)
    sites = np.arange(8) * 2  # spread leaves: >= 8 distinct paths
    pts = sites[rng.integers(0, 8, size=N_K)]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L_K, int(v)) for v in row]
         for row in pts[:, None]]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


def _kcfg(port, **kw):
    defaults = dict(
        data_len=L_K, n_dims=1, ball_size=1, addkey_batch_size=1024,
        num_sites=8, threshold=0.02, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=16, secure_exchange=True,
    )
    defaults.update(kw)
    return Config(**defaults)


def test_kernel_shard_binding_degrades():
    """A non-dividing planar batch degrades to fewer KERNEL shards
    instead of failing: the active count is the largest divisor of the
    block count that fits the budget, 1 = the gather path."""
    blk = kernel_shard.BLOCK
    assert kernel_shard.kernel_shards(8 * blk, 8) == 8
    assert kernel_shard.kernel_shards(6 * blk, 4) == 3  # 4 ∤ 6 -> 3
    assert kernel_shard.kernel_shards(3 * blk, 2) == 1  # prime-ish -> 1
    assert kernel_shard.kernel_shards(blk - 5, 8) == 1  # one block
    assert kernel_shard.kernel_shards(2 * blk, 0) == 1  # budget floor
    import jax

    devs = tuple(jax.devices()[:4])
    assert kernel_shard.bind(devs, blk, 2, 4) is None  # 1 shard = gather
    ks = kernel_shard.bind(devs, 2 * blk - 7, 2, 4)
    assert ks is not None and ks.k == 2 and ks.bp == 2 * blk


def test_kernel_sharded_crawl_bit_identical_with_device_kill(kernel_keys):
    """THE kernel-stage e2e: a crawl whose deep levels row-shard the
    secure kernels is bit-identical to the single-device crawl, shows
    the degradation ladder in the report (shallow levels gather at
    kernel_shards 1, deep levels shard at >= 2, kernel_gather ~ 0), and
    a device KILL at a kernel-sharded level recovers by in-server
    re-shard — levels_rerun stays ZERO."""
    k0, k1 = kernel_keys
    port = BASE_PORT + 5000
    base, st_b, _ = _run(
        _kcfg(port, server_data_devices=1), port, k0, k1, n_clients=N_K,
    )
    assert st_b["mesh"] is None
    chaos = MeshChaos(parse_mesh_faults("mesh:kill@level=3"))
    with tempfile.TemporaryDirectory() as td:
        res, status0, report = _run(
            _kcfg(port + 1200, server_data_devices=4), port + 1200,
            k0, k1, chaos=chaos, ckpt_dir=td, supervised=True,
            n_clients=N_K,
        )
    assert chaos.fired == [("kill", 3)]
    np.testing.assert_array_equal(base.paths, res.paths)
    np.testing.assert_array_equal(base.counts, res.counts)
    rec = report["recovery"]
    assert rec["shards_rerun"] >= 1
    assert rec["levels_rerun"] == 0
    mesh = report["mesh"]
    by = mesh["by_level"]
    # degradation ladder: level 0 (one node) gathers, the deep levels
    # run the sharded kernel stage
    assert by["0"]["kernel_shards"] == 1
    deep = max(v.get("kernel_shards", 0) for v in by.values())
    assert deep >= 2, f"kernel stage never sharded: {by}"
    assert mesh["kernel_shards"] >= 2  # last level's layout
    # the gather survives only on the shallow one-block levels: the
    # counter names them (the layout detector), and its cumulative
    # dispatch time must be noise, not a per-level stage
    assert mesh["kernel_gathers"] >= 1
    assert mesh["kernel_gather_seconds"] < 1.0
    assert status0["mesh"]["kernel_shards"] >= 2
    assert status0["mesh"]["kernel_shards_max"] >= 2
    assert status0["mesh"]["kernel_gather_seconds"] < 1.0
    sk = report["secure_kernels"]
    assert sk["kernel_shards"] >= 2
    assert sk["otext_seconds"] > 0 and sk["b2a_seconds"] > 0


@pytest.mark.slow  # ~27 s: same warm-ladder contract as the
# multichip/malicious warmed tests that stay in tier-1
def test_warmed_kernel_sharded_crawl_zero_fresh_compiles(kernel_keys):
    """The warmup contract extends to the ROW-SHARDED kernel ladder:
    after one warmed kernel-sharded secure crawl, a second identically-
    shaped warmed crawl triggers ZERO fresh XLA compiles — warmup
    compiles the sharded flat/extension/kernel/open/psum programs (both
    roles, both garbling signs) the live crawl dispatches."""
    from fuzzyheavyhitters_tpu.utils import compile_cache

    k0, k1 = kernel_keys
    port = BASE_PORT + 6000
    kw = dict(server_data_devices=4)
    _run(_kcfg(port, **kw), port, k0, k1, warmup=True, n_clients=N_K)
    before = compile_cache.backend_compiles()
    _, status0, _ = _run(_kcfg(port + 1200, **kw), port + 1200, k0, k1,
                         warmup=True, n_clients=N_K)
    fresh = compile_cache.backend_compiles() - before
    assert status0["mesh"]["kernel_shards"] >= 2  # the ladder engaged
    assert fresh == 0, (
        f"{fresh} fresh compiles in a warmed kernel-sharded crawl"
    )


def test_warmed_malicious_crawl_zero_fresh_compiles(client_keys,
                                                    sketch_keys):
    """The warmup contract extends to the MALICIOUS lane: after one
    warmed malicious (sketch) crawl on the sharded mesh, a second
    identically-shaped warmed crawl triggers ZERO fresh XLA compiles —
    warmup compiles the fused sharded cor/out/verdict chain per bucket
    rung, the level-0 full-width check, and the frontier-advance
    programs the live verify dispatches (rpc._warm_sketch +
    sketch_shard.warm_verify)."""
    from fuzzyheavyhitters_tpu.utils import compile_cache

    _, (k0, k1) = client_keys
    sk0, sk1 = sketch_keys
    port = BASE_PORT + 7000
    kw = dict(server_data_devices=2)
    _run(_cfg(port, **kw), port, k0, k1, sk0=sk0, sk1=sk1, warmup=True)
    before = compile_cache.backend_compiles()
    _, status0, rep = _run(
        _cfg(port + 1200, **kw), port + 1200, k0, k1, sk0=sk0, sk1=sk1,
        warmup=True,
    )
    fresh = compile_cache.backend_compiles() - before
    # the sharded verify engaged (2 data devices -> 2 sketch shards)
    assert status0["mesh"]["sketch_shards"] == 2
    assert rep["sketch"]["sketch_shards"] == 2
    assert rep["sketch"]["verify_seconds"] > 0
    assert fresh == 0, (
        f"{fresh} fresh compiles in a warmed malicious crawl"
    )


def test_warmed_multichip_crawl_zero_fresh_compiles(client_keys):
    """The warmup contract extends to the sharded ladder: after one
    warmed MULTI-CHIP secure crawl, a second identically-shaped warmed
    crawl (fresh servers, fresh sessions) triggers ZERO fresh XLA
    compiles — warmup compiles the sharded expand/reduce/2PC programs
    the live crawl dispatches, wire arrays round-tripped through host
    numpy exactly like the socket path."""
    from fuzzyheavyhitters_tpu.utils import compile_cache

    _, (k0, k1) = client_keys
    port = BASE_PORT + 4000
    kw = dict(server_data_devices=2, secure_exchange=True)
    _run(_cfg(port, **kw), port, k0, k1, warmup=True)
    before = compile_cache.backend_compiles()
    _run(_cfg(port + 1200, **kw), port + 1200, k0, k1, warmup=True)
    fresh = compile_cache.backend_compiles() - before
    assert fresh == 0, f"{fresh} fresh compiles in a warmed multichip crawl"
