"""Fused Pallas frontier expansion vs the XLA form — bit-exact on the
real chip (same TPU-only gating rationale as test_keygen_pallas.py).

The plane-major pack-in-kernel engine (ops/expand_pallas.py) is the
DEFAULT on real chips, so this parity test pins the whole pipeline —
packed share bits, child cache, gather-advance — against the XLA engine
at every step of a small crawl, in both PRG bit modes.  The shapes are
deliberately NOT multiples of the kernel group so the padded/broadcast
cw fallback path is the one under test; the N-periodic index-map path is
exercised by test_periodic_cw_path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


from conftest import has_tpu as _has_tpu


pytestmark = [
    pytest.mark.skipif(not _has_tpu(), reason="needs a TPU backend"),
    pytest.mark.tpu_retry,
]


def _seed_to_xla(planar):  # [4, d, 2, F, N] -> [F, N, d, 2, 4]
    return np.transpose(np.asarray(planar), (3, 4, 1, 2, 0))


def _bits_to_xla(planar):  # [d, 2, F, N] -> [F, N, d, 2]
    return np.transpose(np.asarray(planar), (2, 3, 0, 1))


def _check_children(ch_x, ch_p):
    """XLA EvalState cache vs PlanarChildren: same child states."""
    fl = np.asarray(ch_p.flags)
    for dir_, names in enumerate(
        [("bit", 0, "y_bit", 2), ("bit", 1, "y_bit", 3)]
    ):
        bname, bshift, yname, yshift = names
        np.testing.assert_array_equal(
            np.asarray(getattr(ch_x, bname))[..., dir_],
            _bits_to_xla((fl >> bshift) & 1) != 0,
        )
        np.testing.assert_array_equal(
            np.asarray(getattr(ch_x, yname))[..., dir_],
            _bits_to_xla((fl >> yshift) & 1) != 0,
        )
    # seed: planar [2, 4, d, 2, F, N] -> XLA [F, N, d, 2, dir, 4]
    sp = np.transpose(np.asarray(ch_p.seed), (4, 5, 2, 3, 0, 1))
    np.testing.assert_array_equal(np.asarray(ch_x.seed), sp)


@pytest.mark.parametrize("derived", [False, True])
def test_planar_engine_bit_exact(rng, derived):
    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import collect

    L, n, d = 12, 300, 2  # n*F not a multiple of the kernel group
    pts = rng.integers(0, 1 << L, size=(n, d))
    pts_bits = ((pts[..., None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    k0, _ = ibdcf.gen_l_inf_ball(pts_bits, 3, rng, engine="np")
    f_x = collect.tree_init(k0, 4, planar=False)
    f_p = collect.tree_init(k0, 4, planar=True)
    np.testing.assert_array_equal(
        _seed_to_xla(f_p.states.seed), np.asarray(f_x.states.seed)
    )
    parent = jnp.asarray(np.array([0, 1, 3, 0], np.int32))
    pat = jnp.asarray(rng.integers(0, 2, size=(4, d)).astype(bool))
    for lvl in (0, 7):
        p_x, ch_x = collect._expand_share_bits_jit(k0, f_x, lvl, derived, True, False)
        p_p, ch_p = collect._expand_share_bits_jit(k0, f_p, lvl, derived, True, True)
        np.testing.assert_array_equal(np.asarray(p_x), np.asarray(p_p))
        _check_children(ch_x, ch_p)
        a_x = collect._advance_children_jit(ch_x, parent, pat, 3, planar=False)
        a_p = collect._advance_children_jit(ch_p, parent, pat, 3, planar=True)
        np.testing.assert_array_equal(
            np.asarray(a_x.states.seed), _seed_to_xla(a_p.states.seed)
        )
        np.testing.assert_array_equal(
            np.asarray(a_x.states.bit), _bits_to_xla(a_p.states.bit)
        )
        np.testing.assert_array_equal(
            np.asarray(a_x.states.y_bit), _bits_to_xla(a_p.states.y_bit)
        )
        np.testing.assert_array_equal(np.asarray(a_x.alive), np.asarray(a_p.alive))
        f_x, f_p = a_x, a_p  # crawl on from the advanced frontiers


def test_last_level_packed_only(rng):
    """want_children=False (the last level) returns identical packed bits
    and no cache on both engines."""
    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import collect

    L, n = 10, 257
    pts = rng.integers(0, 2, size=(n, 1, L)).astype(bool)
    k0, _ = ibdcf.gen_l_inf_ball(pts, 2, rng, engine="np")
    f_x = collect.tree_init(k0, 2, planar=False)
    f_p = collect.tree_init(k0, 2, planar=True)
    p_x, ch_x = collect._expand_share_bits_jit(k0, f_x, 3, False, False, False)
    p_p, ch_p = collect._expand_share_bits_jit(k0, f_p, 3, False, False, True)
    assert ch_x is None and ch_p is None
    np.testing.assert_array_equal(np.asarray(p_x), np.asarray(p_p))


def test_periodic_cw_path(rng):
    """N a multiple of the kernel row group -> the modular-index-map cw
    path must agree with the XLA engine (the production 131k-client shape
    takes this branch; the other tests exercise the broadcast fallback)."""
    from fuzzyheavyhitters_tpu.ops import expand_pallas, ibdcf
    from fuzzyheavyhitters_tpu.protocol import collect

    n = expand_pallas.R_BLK * expand_pallas.GROUP  # one full block per node
    L, d = 6, 1
    pts = rng.integers(0, 2, size=(n, d, L)).astype(bool)
    k0, _ = ibdcf.gen_l_inf_ball(pts, 1, rng, engine="np")
    f_x = collect.tree_init(k0, 2, planar=False)
    f_p = collect.tree_init(k0, 2, planar=True)
    p_x, ch_x = collect._expand_share_bits_jit(k0, f_x, 2, True, True, False)
    p_p, ch_p = collect._expand_share_bits_jit(k0, f_p, 2, True, True, True)
    np.testing.assert_array_equal(np.asarray(p_x), np.asarray(p_p))
    _check_children(ch_x, ch_p)
