"""Fused Pallas frontier expansion vs the XLA form — bit-exact on the
real chip (same TPU-only gating rationale as test_keygen_pallas.py).

The planar engine (word-planar frontier seeds + ops/expand_pallas.py) is
the DEFAULT on real chips, so this parity test pins the whole planar
pipeline — expand share bits, child cache, gather-advance — against the
XLA engine at every step of a small crawl.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _has_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _has_tpu(), reason="needs a TPU backend")


@pytest.mark.parametrize("derived", [False, True])
def test_planar_engine_bit_exact(rng, derived):
    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import collect

    L, n, d = 12, 300, 2  # n*d*2*F not a multiple of the kernel group
    pts = rng.integers(0, 1 << L, size=(n, d))
    pts_bits = ((pts[..., None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    k0, _ = ibdcf.gen_l_inf_ball(pts_bits, 3, rng, engine="np")
    f_x = collect.tree_init(k0, 4, planar=False)
    f_p = collect.tree_init(k0, 4, planar=True)
    np.testing.assert_array_equal(
        np.asarray(jnp.moveaxis(f_p.states.seed, 0, -1)),
        np.asarray(f_x.states.seed),
    )
    parent = jnp.asarray(np.array([0, 1, 3, 0], np.int32))
    pat = jnp.asarray(rng.integers(0, 2, size=(4, d)).astype(bool))
    for lvl in (0, 7):
        p_x, ch_x = collect._expand_share_bits_jit(k0, f_x, lvl, derived, True, False)
        p_p, ch_p = collect._expand_share_bits_jit(k0, f_p, lvl, derived, True, True)
        np.testing.assert_array_equal(np.asarray(p_x), np.asarray(p_p))
        np.testing.assert_array_equal(np.asarray(ch_x.bit), np.asarray(ch_p.bit))
        np.testing.assert_array_equal(np.asarray(ch_x.y_bit), np.asarray(ch_p.y_bit))
        np.testing.assert_array_equal(
            np.asarray(ch_x.seed),
            np.asarray(jnp.moveaxis(ch_p.seed, 0, -1)),
        )
        a_x = collect._advance_children_jit(ch_x, parent, pat, 3, planar=False)
        a_p = collect._advance_children_jit(ch_p, parent, pat, 3, planar=True)
        np.testing.assert_array_equal(
            np.asarray(a_x.states.seed),
            np.asarray(jnp.moveaxis(a_p.states.seed, 0, -1)),
        )
        np.testing.assert_array_equal(
            np.asarray(a_x.states.bit), np.asarray(a_p.states.bit)
        )
        np.testing.assert_array_equal(
            np.asarray(a_x.states.y_bit), np.asarray(a_p.states.y_bit)
        )
        np.testing.assert_array_equal(np.asarray(a_x.alive), np.asarray(a_p.alive))
