"""Fused Pallas frontier expansion vs the XLA form — bit-exact on the
real chip (same TPU-only gating rationale as test_keygen_pallas.py).

The kernel is opt-in (collect.EXPAND_PALLAS, see the measured-layout-cost
note there); parity is pinned here so the option stays sound.
"""

import numpy as np
import pytest

import jax


def _has_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _has_tpu(), reason="needs a TPU backend")


@pytest.mark.parametrize("derived", [False, True])
def test_expand_pallas_bit_exact(rng, derived):
    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import collect

    L, n, d = 12, 300, 2  # n*d*2*F not a multiple of the kernel group
    pts = rng.integers(0, 1 << L, size=(n, d))
    pts_bits = ((pts[..., None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    k0, _ = ibdcf.gen_l_inf_ball(pts_bits, 3, rng, engine="np")
    f = collect.tree_init(k0, 4)
    for lvl in (0, 7):
        p_x, ch_x = collect._expand_share_bits_jit(k0, f, lvl, derived, True, False)
        p_p, ch_p = collect._expand_share_bits_jit(k0, f, lvl, derived, True, True)
        np.testing.assert_array_equal(np.asarray(p_x), np.asarray(p_p))
        for a, b in zip(ch_x, ch_p):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
