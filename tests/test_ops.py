"""fhh-ops suite: the live /metrics exporter, device-memory/compile
telemetry, the alert engine, the ``ops top`` CLI, and the crash-proof
resumable bench.

Three layers, cheapest first:

- pure units (render families, bucket round-trip, alert fire-once,
  devmem sampling, bench resume bookkeeping) — no sockets beyond an
  ephemeral loopback exporter;
- an in-process supervised bring-up proving the ``status`` verb and the
  trace ring carry a fired alert;
- process-level acceptance: the README run shape with the exporter live
  on leader + both servers (scrapes match the servers' own run-report
  registries, an injected tenant stall fires exactly once across every
  surface), a disabled-exporter server binding no telemetry socket, and
  a bench SIGTERMed mid-run resuming from its partial artifact.

The histogram round-trip pins the tentpole invariant: a Prometheus
scrape carries EXACTLY the information the run report computes its SLO
quantiles from (shared fixed buckets, obs/hist.py).
"""

import asyncio
import glob
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from fuzzyheavyhitters_tpu import obs
from fuzzyheavyhitters_tpu.obs import alerts, devmem, exporter
from fuzzyheavyhitters_tpu.obs import ops as fhhops
from fuzzyheavyhitters_tpu.obs import trace as tracemod
from fuzzyheavyhitters_tpu.obs.hist import Histogram
from fuzzyheavyhitters_tpu.obs.metrics import Registry, default_registry
from fuzzyheavyhitters_tpu.protocol import rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils.config import Config

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE_PORT = 22170  # in-process status test
E2E_PORT = 21871  # subprocess acceptance run (rpc plane)
E2E_METRICS = 21891  # subprocess acceptance run (/metrics plane)


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    """Every test starts and ends with a dark telemetry plane: no
    exporter, no fired alerts, warmup flag down.  (The compile listener
    itself is one-way per process and stays installed — it only counts.)"""
    monkeypatch.delenv(exporter.ENV_PORT, raising=False)
    monkeypatch.delenv(exporter.ENV_HOST, raising=False)
    exporter.stop()
    alerts._reset_for_tests()
    devmem._reset_for_tests()
    yield
    exporter.stop()
    alerts._reset_for_tests()
    devmem._reset_for_tests()


def _get(port, path="/metrics"):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read().decode()


# ---------------------------------------------------------------------------
# exporter: rendering
# ---------------------------------------------------------------------------


def test_render_families_types_and_labels():
    r = Registry("rtexp")
    r.count("rt_frames", 3)
    r.gauge("rt_depth_keys", 7)
    r.count("fresh_compiles:level")  # colon -> key label
    r.timer_add("rt_phase", 1.5)
    r.observe("level_latency", 0.01)
    t = Registry("server7:acme")  # per-session registry -> collection label
    t.gauge("rt_depth_keys", 9)
    text = exporter.render()
    samples = fhhops.parse_prometheus(text)
    by = {}
    for name, lb, v in samples:
        by.setdefault(name, []).append((lb, v))

    def one(name, **want):
        return [
            v for lb, v in by.get(name, [])
            if all(lb.get(k) == wv for k, wv in want.items())
        ]

    assert one("fhh_rt_frames_total", registry="rtexp") == [3.0]
    assert one("fhh_rt_depth_keys", registry="rtexp") == [7.0]
    assert one("fhh_rt_depth_keys", registry="server7", collection="acme") == [9.0]
    assert one("fhh_fresh_compiles_total", registry="rtexp", key="level") == [1.0]
    assert one("fhh_rt_phase_seconds_total", registry="rtexp") == [1.5]
    assert one("fhh_rt_phase_runs_total", registry="rtexp") == [1.0]
    # histogram family: cumulative buckets + +Inf + sum/count
    buckets = one("fhh_level_latency_seconds_bucket", registry="rtexp")
    assert buckets and buckets[-1] == 1.0
    infs = [
        v for lb, v in by["fhh_level_latency_seconds_bucket"]
        if lb.get("registry") == "rtexp" and lb.get("le") == "+Inf"
    ]
    assert infs == [1.0]
    assert one("fhh_level_latency_seconds_count", registry="rtexp") == [1.0]
    assert one("fhh_level_latency_seconds_sum", registry="rtexp") == [
        pytest.approx(0.01)
    ]
    # one TYPE header per family no matter how many registries contribute
    assert text.count("# TYPE fhh_rt_depth_keys gauge") == 1


def test_hist_bucket_roundtrip_matches_run_report_slo():
    """The satellite invariant: scrape both 'servers', rebuild each
    histogram from its ``_bucket`` series, merge bucketwise, and land on
    the same quantiles the run report computes by merging the live
    histograms themselves (shared BUCKET_BOUNDS make this exact)."""
    r0, r1 = Registry("hrt_s0"), Registry("hrt_s1")
    for v in (0.0003, 0.002, 0.015, 0.04, 0.09):
        r0.observe("level_latency", v)
    for v in (0.0008, 0.004, 0.02, 0.06, 0.1):
        r1.observe("level_latency", v)
    samples = fhhops.parse_prometheus(exporter.render())
    rebuilt = []
    for regname in ("hrt_s0", "hrt_s1"):
        buckets = [
            (lb, v) for name, lb, v in samples
            if name == "fhh_level_latency_seconds_bucket"
            and lb.get("registry") == regname
        ]
        (sum_s,) = [
            v for name, lb, v in samples
            if name == "fhh_level_latency_seconds_sum"
            and lb.get("registry") == regname
        ]
        (count,) = [
            v for name, lb, v in samples
            if name == "fhh_level_latency_seconds_count"
            and lb.get("registry") == regname
        ]
        assert count == 5.0
        rebuilt.append(fhhops.hist_from_series(buckets, sum_s, int(count)))
    merged = Histogram.merged(rebuilt)
    slo = obs.run_report(registries=[r0, r1])["slo"]["level_latency"]
    assert merged.count == slo["count"] == 10
    assert merged.sum == pytest.approx(slo["sum_s"], abs=1e-6)
    for q, key in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
        assert merged.quantile(q) == pytest.approx(slo[key], abs=1e-6)


def test_producers_prune_and_exception_isolation():
    calls = []

    def live():
        calls.append(1)
        return ["fhh_probe_total 1"]

    exporter.add_producer(live)
    exporter.add_producer(lambda: None)  # dead owner -> pruned
    def boom():
        raise RuntimeError("producer crash")
    exporter.add_producer(boom)
    text = exporter.render()
    assert "fhh_probe_total 1" in text
    text2 = exporter.render()  # pruned producer gone, crasher skipped again
    assert "fhh_probe_total 1" in text2
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# exporter: lifecycle
# ---------------------------------------------------------------------------


def test_exporter_lifecycle_bind_scrape_stop(monkeypatch):
    monkeypatch.setenv(exporter.ENV_PORT, "0")  # ephemeral: tests never collide
    port = exporter.maybe_start("s0")
    assert port and exporter.running() and exporter.port() == port
    assert exporter.maybe_start("s0") == port  # idempotent
    status, ctype, body = _get(port)
    assert status == 200
    assert ctype.startswith("text/plain; version=0.0.4")
    assert body.startswith("# TYPE fhh_")
    with pytest.raises(urllib.error.HTTPError):
        _get(port, "/other")
    exporter.stop()
    assert not exporter.running() and exporter.port() is None
    exporter.stop()  # second stop is a no-op


def test_exporter_disabled_without_env():
    assert exporter.maybe_start("s0") is None
    assert not exporter.running()


def test_exporter_degrades_on_bad_port(monkeypatch):
    monkeypatch.setenv(exporter.ENV_PORT, "not-a-port")
    assert exporter.maybe_start("leader") is None
    assert not exporter.running()


def test_exporter_degrades_on_bind_conflict(monkeypatch):
    blocker = socket.socket()
    try:
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken = blocker.getsockname()[1]
        monkeypatch.setenv(exporter.ENV_PORT, str(taken))
        assert exporter.maybe_start("leader") is None  # +0 offset == taken
        assert not exporter.running()
    finally:
        blocker.close()


# ---------------------------------------------------------------------------
# devmem: memory sampling + compile attribution
# ---------------------------------------------------------------------------


def test_devmem_sample_watermark_and_tree_nbytes():
    r = Registry("rtmem")
    x = jax.numpy.arange(1024, dtype=jax.numpy.int32)
    x.block_until_ready()
    in_use = devmem.sample(r, phase="rt_keygen")
    assert in_use >= 0
    assert r.gauge_value("hbm_in_use_bytes") == in_use
    assert r.gauge_value("hbm_watermark_bytes") >= in_use
    assert r.gauge_value("hbm_watermark_bytes:rt_keygen") >= in_use
    wm = r.gauge_value("hbm_watermark_bytes")
    devmem.sample(r, phase="rt_keygen")
    assert r.gauge_value("hbm_watermark_bytes") >= wm  # monotone
    del x
    assert devmem.tree_nbytes(None) == 0
    assert devmem.tree_nbytes(np.zeros((2, 3), np.float32)) == 24
    tree = {"a": np.zeros(4, np.int8), "b": [np.zeros(2, np.float64)]}
    assert devmem.tree_nbytes(tree) == 4 + 16


def test_compile_listener_attribution_and_warmup_alert():
    devmem.install_compile_listener()
    reg = default_registry()
    base_all = reg.counter_value("fresh_compiles")
    base_span = reg.counter_value("fresh_compiles:rt_compile_probe")
    with reg.span("rt_compile_probe"):
        # a FRESH jit callable always backend-compiles: the in-memory
        # cache is per-callable and tiny programs stay under the
        # persistent cache's 0.3 s floor (conftest)
        # fhh-lint: disable=recompile-churn (the recompile IS the fixture)
        jax.jit(lambda v: v * 2 + 1)(np.arange(8)).block_until_ready()
    assert reg.counter_value("fresh_compiles") > base_all
    assert reg.counter_value("fresh_compiles:rt_compile_probe") > base_span
    assert reg.timer_seconds("xla_compile") > 0
    # past the warmup ladder, a fresh compile is a named counted event
    # AND alert fodder
    base_post = reg.counter_value("fresh_compiles_post_warmup")
    devmem.note_warmup_done()
    assert devmem.warmup_done()
    with reg.span("rt_compile_probe"):
        # fhh-lint: disable=recompile-churn (the recompile IS the fixture)
        jax.jit(lambda v: v * 3 + 2)(np.arange(8)).block_until_ready()
    assert reg.counter_value("fresh_compiles_post_warmup") > base_post
    alerts.evaluate_registries([reg])
    assert any(rec["rule"] == "recompile_after_warmup" for rec in alerts.fired())


# ---------------------------------------------------------------------------
# alerts: rules + fire-once + surfaces
# ---------------------------------------------------------------------------


def test_tenant_stall_fires_once_across_evaluations(monkeypatch):
    monkeypatch.setenv(alerts.ENV_STALL_S[0], "0.5")
    rows = {
        "acme": {
            "last_progress_s": 2.0, "phase": "crawl",
            "level": 3, "queue_depth": 0,
        }
    }
    alerts.evaluate_sessions(rows, "server0")
    alerts.evaluate_sessions(rows, "server0")  # same (rule, subject): no-op
    fired = alerts.fired()
    assert len(fired) == 1
    rec = fired[0]
    assert rec["rule"] == "tenant_stall" and rec["subject"] == "server0/acme"
    assert rec["phase"] == "crawl" and rec["level"] == 3
    st = alerts.status_section()
    assert st["count"] == 1 and st["dropped"] == 0 and st["fired"] == fired
    lines = alerts.metrics_lines()
    assert 'fhh_alerts_fired_total{rule="tenant_stall"} 1' in lines
    assert sum("fhh_alert_active{" in ln for ln in lines) == 1
    # a DIFFERENT server's stall is its own subject
    alerts.evaluate_sessions(rows, "server1")
    assert len(alerts.fired()) == 2


def test_backlog_slo_and_hbm_rules(monkeypatch):
    monkeypatch.setenv(alerts.ENV_BACKLOG_KEYS[0], "10")
    alerts.evaluate_sessions(
        {"bulk": {"last_progress_s": 0.0, "queue_depth": 100}}, "server1"
    )
    r = Registry("rtslo")
    for _ in range(4):
        r.observe("level_latency", 5.0)  # p95 over the 2.0 s default budget
    r.gauge("hbm_in_use_bytes", 95.0)
    r.gauge("hbm_limit_bytes", 100.0)  # 0.95 > 0.9 default fraction
    alerts.evaluate_registries([r])
    rules = {rec["rule"] for rec in alerts.fired()}
    assert rules == {"ingest_backlog", "slo_burn", "hbm_high_water"}
    # the run report grows an alerts section only once something fired
    rep = obs.run_report(registries=[r])
    assert rep["alerts"]["count"] == 3
    assert {rec["rule"] for rec in rep["alerts"]["fired"]} == rules
    alerts._reset_for_tests()
    assert "alerts" not in obs.run_report(registries=[r])


# ---------------------------------------------------------------------------
# ops CLI: scrape -> merge -> one screen
# ---------------------------------------------------------------------------


def test_ops_top_renders_sessions_alerts_and_headlines(
    monkeypatch, capsys
):
    monkeypatch.setenv(exporter.ENV_PORT, "0")
    port = exporter.maybe_start("s0")
    r = Registry("rtops")
    r.count("data_bytes_sent", 4096)
    for v in (0.01, 0.02, 0.04):
        r.observe("level_latency", v)
    exporter.add_producer(lambda: [
        "# TYPE fhh_session_last_progress_seconds gauge",
        'fhh_session_last_progress_seconds{registry="rtops",collection="acme"} 3.5',
        'fhh_session_queue_depth_keys{registry="rtops",collection="acme"} 12',
    ])
    monkeypatch.setenv(alerts.ENV_STALL_S[0], "0.5")
    alerts.evaluate_sessions(
        {"acme": {"last_progress_s": 3.5, "queue_depth": 12}}, "rtops"
    )
    target = f"127.0.0.1:{port}"
    samples = fhhops.scrape(target)
    assert samples
    frame = fhhops.render_top({target: samples})
    assert frame.startswith("fhh-ops top")
    assert f"{target}(up)" in frame
    assert "!! tenant_stall" in frame and "rtops/acme" in frame
    assert "acme" in frame and "3.5s" in frame
    assert "fhh_data_bytes_sent_total 4096" in frame
    # the level-latency p95 column is reconstructed from the buckets
    # (the bare-registry histogram rides the "default" collection row)
    (hist_row,) = [
        ln for ln in frame.splitlines()
        if ln.startswith("rtops") and " default " in ln
    ]
    cols = hist_row.split()
    assert cols[4] == "3"  # three levels observed
    assert cols[5].endswith("s") and cols[5] != "-"
    # CLI: --once prints one frame; no targets is an error, not a hang
    assert fhhops.main(["top", "--targets", target, "--once"]) == 0
    out = capsys.readouterr().out
    assert "fhh-ops top" in out
    monkeypatch.setenv(exporter.ENV_PORT, "0")  # base 0 -> no default targets
    assert fhhops.main(["top", "--once"]) == 2
    assert fhhops.scrape("127.0.0.1:1") == []  # dead target -> row gap


# ---------------------------------------------------------------------------
# status verb + trace ring carry a fired alert (in-process bring-up)
# ---------------------------------------------------------------------------


def test_status_and_trace_carry_alert(cpu_default, monkeypatch, tmp_path):
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv(tracemod.ENV_DIR, str(trace_dir))
    tracemod._refresh()
    monkeypatch.setenv(alerts.ENV_STALL_S[0], "0.0")
    cfg = Config(
        data_len=5, n_dims=1, ball_size=1, addkey_batch_size=8,
        num_sites=4, threshold=0.2, zipf_exponent=1.03,
        server0=f"127.0.0.1:{BASE_PORT}",
        server1=f"127.0.0.1:{BASE_PORT + 10}",
        distribution="zipf", f_max=32,
    )

    async def run():
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", BASE_PORT + 10, "127.0.0.1", BASE_PORT + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", BASE_PORT, "127.0.0.1", BASE_PORT + 11)
        )
        await asyncio.gather(t0, t1)
        c0 = await rpc.CollectorClient.connect("127.0.0.1", BASE_PORT)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", BASE_PORT + 10)
        lead = RpcLeader(cfg, c0, c1)
        await lead._both("reset")  # binds the default session on both
        await asyncio.sleep(0.02)  # any nonzero gap beats the 0.0 budget
        st = await c0.call("status")
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
        return st

    try:
        st = asyncio.run(run())
        assert st["sessions"]["count"] >= 1
        stall = [
            rec for rec in st["alerts"]["fired"]
            if rec["rule"] == "tenant_stall"
        ]
        assert stall, st["alerts"]
        tracemod.flush()
        evs = tracemod.load_events(str(trace_dir))
        assert any(e.get("name") == "alert:tenant_stall" for e in evs)
    finally:
        monkeypatch.delenv(tracemod.ENV_DIR, raising=False)
        tracemod._refresh()


# ---------------------------------------------------------------------------
# bench: crash-proof resumable artifact bookkeeping (units)
# ---------------------------------------------------------------------------


def _import_bench():
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import bench
    return bench


def test_bench_partial_artifact_roundtrip(tmp_path):
    bench = _import_bench()
    saved_out, saved_partial = bench._OUT, dict(bench._PARTIAL)
    try:
        bench._OUT = str(tmp_path / "art.json")
        bench._PARTIAL.clear()
        bench._PARTIAL["keygen_sweep"] = {16: {"keys_per_s": 1.5}}
        bench._PARTIAL["keygen_headline"] = 123.4
        bench._PARTIAL["secure"] = {"xput": 9.0}
        bench._write_leg_artifact()
        doc = json.loads((tmp_path / "art.json").read_text())
        assert doc["partial"] is True and doc["reason"] == "in-progress"
        res = bench._load_resume(bench._OUT)
        # JSON stringifies the sweep's data_len keys; resume restores them
        assert res["keygen_sweep"] == {16: {"keys_per_s": 1.5}}
        assert res["keygen_headline"] == 123.4
        assert res["secure"] == {"xput": 9.0}
    finally:
        bench._OUT = saved_out
        bench._PARTIAL.clear()
        bench._PARTIAL.update(saved_partial)


def test_bench_load_resume_closed_manifest(tmp_path):
    bench = _import_bench()
    path = tmp_path / "bench_full.json"
    path.write_text(json.dumps({
        "value": 99.5,
        "extra": {
            "keygen_sweep": {"16": {"keys_per_s": 2.0}},
            "secure_crawl": {"xput": 7.0},
            "reference_key_bytes": 555,
            "crawl": {"wall_s": 1.0},
        },
    }))
    res = bench._load_resume(str(path))
    assert res["secure"] == {"xput": 7.0}  # final key mapped back to leg name
    assert "secure_crawl" not in res
    assert "reference_key_bytes" not in res  # derived, not a leg
    assert res["keygen_headline"] == 99.5
    assert res["keygen_sweep"] == {16: {"keys_per_s": 2.0}}
    assert res["crawl"] == {"wall_s": 1.0}
    assert bench._load_resume(str(tmp_path / "missing.json")) == {}
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench._load_resume(str(bad)) == {}


# ---------------------------------------------------------------------------
# process-level acceptance
# ---------------------------------------------------------------------------

E2E_CFG = {
    "data_len": 16,
    "n_dims": 2,
    "ball_size": 2,
    "addkey_batch_size": 16,
    "num_sites": 4,
    "threshold": 0.06,
    "zipf_exponent": 1.03,
    "server0": f"127.0.0.1:{E2E_PORT}",
    "server1": f"127.0.0.1:{E2E_PORT + 10}",
    "distribution": "rides",
    "f_max": 512,
    "backend": "cpu",
}
N_REQS = 32


def _e2e_env(tmp_path, **extra):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_backend_optimization_level=1"
    ).strip()
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _spawn(mod, cfg_path, tmp_path, env, *args):
    return subprocess.Popen(
        [sys.executable, "-m", mod, "--config", str(cfg_path), *args],
        cwd=tmp_path, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


@pytest.mark.slow  # ~35 s: three subprocess JAX boots + a real stall window
def test_ops_e2e_exporters_and_tenant_stall(tmp_path):
    """THE acceptance scenario: a supervised crawl through the binaries
    with the exporter live on all three processes.  Scraped series match
    the servers' own run-report registries; a tenant stall injected via
    a 0.5 s budget on server0 fires exactly once and shows up in the
    logs, the /metrics plane, and server0's run report."""
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(E2E_CFG))
    report_path = tmp_path / "leader_report.json"
    trace_dir = tmp_path / "trace"
    common = dict(
        FHH_RUN_REPORT=report_path,
        FHH_METRICS_PORT=E2E_METRICS,
        FHH_TRACE_DIR=trace_dir,
        # CPU levels can be seconds each (compiles): keep slo_burn out of
        # this scenario so tenant_stall is the ONLY deterministic alert
        FHH_ALERT_LEVEL_P95_S="1000",
    )
    env = _e2e_env(tmp_path, **common)
    env_s0 = _e2e_env(tmp_path, **common, FHH_ALERT_STALL_S="0.5")
    srv = "fuzzyheavyhitters_tpu.bin.server"
    s1 = _spawn(srv, cfg_path, tmp_path, env, "--server_id", "1")
    s0 = _spawn(srv, cfg_path, tmp_path, env_s0, "--server_id", "0")
    lead = None
    try:
        lead = _spawn(
            "fuzzyheavyhitters_tpu.bin.leader", cfg_path, tmp_path, env,
            "-n", str(N_REQS),
        )
        # scrape the LEADER while it is alive: its exporter binds before
        # arg validation, so samples appear as soon as python is up
        leader_seen = False
        deadline = time.monotonic() + 540
        while lead.poll() is None and time.monotonic() < deadline:
            samples = fhhops.scrape(f"127.0.0.1:{E2E_METRICS}")
            if any(lb.get("registry") == "leader" for _n, lb, _v in samples):
                leader_seen = True
                break
            time.sleep(0.25)
        out, _ = lead.communicate(timeout=540)
        assert lead.returncode == 0, f"leader failed:\n{out[-4000:]}"
        assert leader_seen, "never scraped a leader-registry series mid-run"
        assert "metrics.listening" in out
        time.sleep(1.0)  # idle past server0's 0.5 s stall budget
        t_s0 = f"127.0.0.1:{E2E_METRICS + 1}"
        t_s1 = f"127.0.0.1:{E2E_METRICS + 2}"
        # scrape 1 IS the evaluation tick that fires the stall; its alert
        # lines render before the session producer runs, so the fired
        # alert becomes visible from scrape 2 on — and stays at ONE
        fhhops.scrape(t_s0)
        scrape2 = fhhops.scrape(t_s0)
        scrape3 = fhhops.scrape(t_s0)
        for sc in (scrape2, scrape3):
            stalls = [
                (lb, v) for name, lb, v in sc
                if name == "fhh_alert_active"
                and lb.get("rule") == "tenant_stall"
            ]
            assert len(stalls) == 1, stalls
            assert stalls[0][0]["subject"].startswith("server0/")
            (fired_n,) = [
                v for name, lb, v in sc
                if name == "fhh_alerts_fired_total"
                and lb.get("rule") == "tenant_stall"
            ]
            assert fired_n == 1.0
        fhhops.scrape(t_s1)  # tick server1's evaluation too
        s1_samples = fhhops.scrape(t_s1)
        assert s1_samples  # exporter live on the second server too
        # server1 runs the default 120 s budget: no stall there (other
        # rules — e.g. recompile_after_warmup on a CPU run — may fire)
        assert not [
            1 for name, lb, _v in s1_samples
            if name == "fhh_alert_active" and lb.get("rule") == "tenant_stall"
        ]
        # counters on the wire == counters in the registry: compare the
        # scrape against the run report server0 writes at SIGTERM (the
        # data plane is idle between the two, so totals are stable)
        for p in (s0, s1):
            p.terminate()
        outs = {}
        for sid, p in (("s0", s0), ("s1", s1)):
            outs[sid], _ = p.communicate(timeout=60)
        # fhh-lint: disable=metric-naming (str.count over a log line, not a counter)
        assert outs["s0"].count("alert.tenant_stall") == 1
        assert "alert.tenant_stall" not in outs["s1"]
        for sid in ("s0", "s1"):
            assert "metrics.listening" in outs[sid]
        srep = json.loads((tmp_path / "leader_report.s0.json").read_text())
        rules = [rec["rule"] for rec in srep["alerts"]["fired"]]
        assert rules.count("tenant_stall") == 1
        want = {
            name: ent["total"]
            for name, ent in srep["registries"]["server0"]["counters"].items()
            if ":" not in name
        }
        got = {
            name[len("fhh_"):-len("_total")]: v
            for name, lb, v in scrape2
            # fhh-lint: disable=metric-naming (family-name prefix, not a series)
            if name.endswith("_total") and not name.startswith("fhh_alert")
            and lb.get("registry") == "server0" and "collection" not in lb
            and "key" not in lb and name.count("seconds_total") == 0
            and name.count("runs_total") == 0
        }
        shared = set(want) & set(got)
        assert shared, (sorted(want), sorted(got))
        for name in shared:
            assert got[name] == pytest.approx(want[name]), name
    finally:
        for p in (s0, s1, lead):
            if p is not None and p.poll() is None:
                p.kill()
    # the crawl itself was not disturbed: the README CSV landed
    assert (tmp_path / "data" / "ride_heavy_hitters.csv").exists()


def test_ops_e2e_disabled_binds_no_socket(tmp_path):
    """Without FHH_METRICS_PORT a server claims no telemetry socket at
    all — the metrics port stays connection-refused while the rpc plane
    is up, and no listening line is logged."""
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(E2E_CFG))
    env = _e2e_env(tmp_path)
    env.pop("FHH_METRICS_PORT", None)
    srv = "fuzzyheavyhitters_tpu.bin.server"
    s1 = _spawn(srv, cfg_path, tmp_path, env, "--server_id", "1")
    s0 = _spawn(srv, cfg_path, tmp_path, env, "--server_id", "0")
    try:
        deadline = time.monotonic() + 120
        up = False
        while time.monotonic() < deadline:
            try:
                socket.create_connection(("127.0.0.1", E2E_PORT), 0.5).close()
                up = True
                break
            except OSError:
                if s0.poll() is not None:
                    break
                time.sleep(0.25)
        assert up, "server0 rpc plane never came up"
        for off in (0, 1, 2):
            with pytest.raises(OSError):
                socket.create_connection(
                    ("127.0.0.1", E2E_METRICS + off), 0.5
                ).close()
        for p in (s0, s1):
            p.terminate()
        for p in (s0, s1):
            out, _ = p.communicate(timeout=60)
            assert "metrics.listening" not in out
    finally:
        for p in (s0, s1):
            if p.poll() is None:
                p.kill()


_SWEEP_LEXICON = {
    # the unambiguous subset of the lint secret_lexicon: "delta" and
    # "label"/"labels" are legitimate ops vocabulary on the telemetry
    # plane (fhh_hbm_delta_bytes; Prometheus labels) — the rest may
    # never name an exported series, label, or report row
    "seed", "seeds", "cw", "cws", "cwf", "cwv", "mac", "secret", "triples",
}


def _lexicon_hits(text):
    segs = [s for s in re.split(r"[^a-z0-9]+", str(text).lower()) if s]
    return [s for s in segs if s in _SWEEP_LEXICON]


def _sweep_json(doc, path=""):
    hits = []
    if isinstance(doc, dict):
        for k, v in doc.items():
            hits += [(f"{path}.{k}", h) for h in _lexicon_hits(k)]
            hits += _sweep_json(v, f"{path}.{k}")
    elif isinstance(doc, (list, tuple)):
        for i, v in enumerate(doc):
            hits += _sweep_json(v, f"{path}[{i}]")
    elif isinstance(doc, str):
        hits += [(path, h) for h in _lexicon_hits(doc)]
    return hits


@pytest.mark.slow  # ~40 s: three subprocess JAX boots (secure data plane)
def test_ops_e2e_taint_sweep_secure_crawl(tmp_path):
    """The fhh-taint acceptance sweep: a live three-process SECURE crawl
    under ``FHH_DEBUG_TAINT=1`` — every source constructor registers its
    buffer in the server processes and every obs sink boundary asserts
    in-process (a registered byte image crossing any exported surface
    would crash the crawl) — then the scraped /metrics planes and the
    run reports are swept from the OUTSIDE: no exported metric name,
    label key, label value, or report row may match the secret lexicon.
    The small resilience-suite shape keeps the CPU data plane fast."""
    port, mport = E2E_PORT + 40, E2E_METRICS + 6
    cfg = {
        "data_len": 5, "n_dims": 1, "ball_size": 1, "addkey_batch_size": 64,
        "num_sites": 4, "threshold": 0.05, "zipf_exponent": 1.0,
        "server0": f"127.0.0.1:{port}", "server1": f"127.0.0.1:{port + 10}",
        "distribution": "zipf", "f_max": 16, "backend": "cpu",
        "secure_exchange": True,
    }
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))
    report_path = tmp_path / "leader_report.json"
    common = dict(
        FHH_DEBUG_TAINT=1,
        FHH_RUN_REPORT=report_path,
        FHH_METRICS_PORT=mport,
        FHH_ALERT_LEVEL_P95_S="1000",
    )
    env = _e2e_env(tmp_path, **common)
    srv = "fuzzyheavyhitters_tpu.bin.server"
    s1 = _spawn(srv, cfg_path, tmp_path, env, "--server_id", "1")
    s0 = _spawn(srv, cfg_path, tmp_path, env, "--server_id", "0")
    lead = None
    try:
        lead = _spawn(
            "fuzzyheavyhitters_tpu.bin.leader", cfg_path, tmp_path, env,
            "-n", "16",
        )
        out, _ = lead.communicate(timeout=540)
        # the in-process half of the sweep: with the sanitizer live on
        # all three processes, a registered buffer reaching ANY sink
        # boundary raises TaintViolation and the crawl dies
        assert lead.returncode == 0, f"leader failed:\n{out[-4000:]}"
        assert "TaintViolation" not in out
        scrapes = {
            sid: fhhops.scrape(f"127.0.0.1:{mport + 1 + i}")
            for i, sid in enumerate(("s0", "s1"))
        }
        for p in (s0, s1):
            p.terminate()
        outs = {}
        for sid, p in (("s0", s0), ("s1", s1)):
            outs[sid], _ = p.communicate(timeout=60)
            assert "TaintViolation" not in outs[sid]
        # the outside half: sweep every exported surface for lexicon
        # matches — a series or label NAMED like key material is a leak
        # in the making even when today's bytes are clean
        for sid, samples in scrapes.items():
            assert samples, f"no samples scraped from {sid}"
            for name, labels, _v in samples:
                assert not _lexicon_hits(name), (sid, name)
                for k, v in labels.items():
                    assert not _lexicon_hits(k), (sid, name, k)
                    assert not _lexicon_hits(v), (sid, name, k, v)
        # and the session rows the servers persisted at SIGTERM
        for sid in ("s0", "s1"):
            srep_path = tmp_path / f"leader_report.{sid}.json"
            srep = json.loads(srep_path.read_text())
            assert "registries" in srep
            hits = _sweep_json(srep)
            assert not hits, (sid, hits[:5])
    finally:
        for p in (s0, s1, lead):
            if p is not None and p.poll() is None:
                p.kill()


@pytest.mark.slow  # ~3 min: two real bench invocations (smoke legs)
def test_bench_sigterm_partial_then_resume(tmp_path):
    """The crash-proof bench: SIGTERM mid-run leaves a valid artifact
    with every completed leg and ``"partial": true``; ``--resume`` skips
    the completed legs, runs the rest, and closes the manifest."""
    art = tmp_path / "art.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
        + " --xla_backend_optimization_level=1"
    ).strip()
    env["FHH_BENCH_SMOKE"] = "1"
    env.pop("FHH_RUN_REPORT", None)
    cmd = [
        sys.executable, os.path.join(_REPO, "bench.py"),
        "--out", str(art), "--sections", "secure",
    ]
    p = subprocess.Popen(
        cmd, cwd=tmp_path, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, start_new_session=True,
    )
    try:
        deadline = time.monotonic() + 540
        seen_keygen = False
        while time.monotonic() < deadline and p.poll() is None:
            if art.exists():
                try:
                    doc = json.loads(art.read_text())
                except ValueError:
                    doc = {}
                if "keygen_sweep" in doc.get("results", {}):
                    seen_keygen = True
                    break
            time.sleep(0.25)
        assert seen_keygen, "bench never wrote its first completed leg"
        os.killpg(p.pid, signal.SIGTERM)  # the whole group: children too
        out, _ = p.communicate(timeout=120)
    finally:
        if p.poll() is None:
            os.killpg(p.pid, signal.SIGKILL)
            p.communicate(timeout=60)
    doc = json.loads(art.read_text())  # valid JSON after the kill
    assert doc["partial"] is True
    assert "keygen_sweep" in doc["results"]
    # resume: completed legs skip, the remaining section runs, and the
    # manifest closes
    res = subprocess.run(
        cmd + ["--resume"], cwd=tmp_path, env=env, capture_output=True,
        text=True, timeout=540,
    )
    tail = res.stdout[-4000:] + res.stderr[-4000:]
    assert res.returncode == 0, tail
    log = res.stdout + res.stderr
    assert "resume-skip" in log, tail
    final = json.loads(art.read_text())
    assert "partial" not in final
    assert "secure_crawl" in final["extra"]
    assert "keygen_sweep" in final["extra"]
