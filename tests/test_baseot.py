"""Chou-Orlandi base-OT tests: seed agreement, sender-side secrecy of the
unchosen seed, and protocol-boundary input validation."""

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import baseot


def test_seed_agreement(rng):
    choices = rng.integers(0, 2, size=16).astype(bool)
    s0, s1, chosen = baseot.exchange(choices)
    want = np.where(choices[:, None], s1, s0)
    np.testing.assert_array_equal(chosen, want)


def test_unchosen_seed_differs(rng):
    choices = rng.integers(0, 2, size=8).astype(bool)
    s0, s1, chosen = baseot.exchange(choices)
    other = np.where(choices[:, None], s0, s1)
    assert not np.any(np.all(chosen == other, axis=1))


def test_seeds_index_separated():
    """Same choice bits, but per-index seeds are pairwise distinct — the OT
    index is folded into the seed hash (domain separation)."""
    choices = np.zeros(8, bool)
    s0, s1, chosen = baseot.exchange(choices)
    for arr in (s0, s1):
        assert len({row.tobytes() for row in arr}) == len(arr)


def test_decompress_rejects_malformed():
    with pytest.raises(ValueError, match="not a square|out of range"):
        baseot.decompress(b"\x02" + b"\x00" * 31)  # y=2: not on curve
    with pytest.raises(ValueError, match="out of range"):
        baseot.decompress(b"\xff" * 32)  # y >= p
    # a valid point still decodes
    p = baseot.decompress(baseot._compress(baseot.BASE))
    assert baseot._affine(p) == baseot._affine(baseot.BASE)


def test_message_passing_api_matches_exchange(rng):
    """The explicit two-round message API (what the socket handshake uses)
    agrees with the in-process convenience wrapper's contract."""
    choices = rng.integers(0, 2, size=4).astype(bool)
    sender = baseot.BaseOtSender()
    receiver = baseot.BaseOtReceiver(choices)
    r_msgs = receiver.round1(sender.round1())
    s0, s1 = sender.seeds([baseot.decompress(m) for m in r_msgs])
    chosen = receiver.seeds()
    np.testing.assert_array_equal(chosen, np.where(choices[:, None], s1, s0))
