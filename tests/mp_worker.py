"""Multi-process mesh worker (launched by test_mesh_multiprocess.py).

One OS process per mesh ROW: process p joins the distributed runtime,
supplies ONLY party p's key batch (MeshRunner.from_process_local), runs
the full crawl as SPMD host code, and prints the heavy hitters as a JSON
line.  With ``secure`` mode the GC+OT 2PC runs across the two processes'
devices with session material agreed from process 0.

Invoked as:  python tests/mp_worker.py <pid> <nproc> <coordinator> <secure>
(env must carry JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=<devices per process>).
"""

import json
import sys

import numpy as np


def main() -> None:
    pid, nproc, coord, secure = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4] == "1"
    )
    import jax

    # the session's sitecustomize imports jax at interpreter start, so the
    # JAX_PLATFORMS env var set by the spawner can be too late — pin the
    # platform via config before any backend initializes (conftest.py does
    # the same for the main test process)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=nproc, process_id=pid
    )
    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.parallel import mesh as meshmod
    from fuzzyheavyhitters_tpu.utils import bits as bitutils

    # the same deterministic scenario on both processes; each process KEEPS
    # only its own party's batch (the other party's keys never exist here)
    rng = np.random.default_rng(7)
    L, d, n = 6, 2, 32
    centers = rng.integers(0, 1 << L, size=(3, d))
    pts = centers[rng.integers(0, 3, size=n)] + rng.integers(-1, 2, size=(n, d))
    pts = np.clip(pts, 0, (1 << L) - 1)
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
    my_keys = k0 if pid == 0 else k1

    mesh = meshmod.make_mesh(devices=jax.devices())
    assert mesh.shape == {"servers": nproc, "data": len(jax.devices()) // nproc}
    runner = meshmod.MeshRunner.from_process_local(
        mesh, my_keys, f_max=128, secure_exchange=secure, min_bucket=8
    )
    res = meshmod.MeshLeader(runner).run(nreqs=n, threshold=0.1)
    out = {
        "pid": pid,
        "hitters": sorted(
            [[int(v) for v in row] + [int(c)]
             for row, c in zip(res.decode_ints(), res.counts)]
        ),
    }
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
