"""fhh-trace + SLO-histogram suite: distributed tracing across the
leader and both collector servers, the fixed-bucket latency histograms,
the status/run-report ``slo`` surfaces, trace behavior under faults
(reconnect replays record each span ONCE; a severed data plane marks
the open span error=true), the chip-profiler gating, and the
zero-cost-when-disabled contract (pinned like FHH_DEBUG_GUARDS).

Shapes mirror tests/test_resilience.py (L=5, d=1) so the crawl kernels
compile once across the suites.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from fuzzyheavyhitters_tpu import obs
from fuzzyheavyhitters_tpu.obs import hist as histmod
from fuzzyheavyhitters_tpu.obs import metrics as obsmetrics
from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.obs import trace as tracemod
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader, WindowedIngest
from fuzzyheavyhitters_tpu.resilience.chaos import ChaosProxy, parse_faults
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 24731


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(5)


@pytest.fixture
def trace_dir(tmp_path, monkeypatch):
    """Arm fhh-trace into a per-test directory; disarm + re-resolve on
    the way out so no other test sees a writer."""
    d = tmp_path / "trace"
    monkeypatch.setenv(tracemod.ENV_DIR, str(d))
    tracemod._refresh()
    yield d
    monkeypatch.delenv(tracemod.ENV_DIR, raising=False)
    tracemod._refresh()


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=5,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=32,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, L, n):
    pts = np.concatenate(
        [np.full(n - 4, 11), rng.integers(0, 1 << L, size=4)]
    )[:, None]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


async def _start_servers(cfg, port_base, ckpt_dir=None):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port_base + 10, "127.0.0.1", port_base + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port_base, "127.0.0.1", port_base + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


async def _bring_up(cfg, port, ckpt_dir=None, dial0=None):
    live = {}
    live["s0"], live["s1"] = await _start_servers(cfg, port, ckpt_dir)
    d0 = ("127.0.0.1", port) if dial0 is None else dial0
    c0 = await rpc.CollectorClient.connect(*d0)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    return lead, c0, c1, live


async def _teardown(clients, live, *proxies):
    for px in proxies:
        await px.stop()
    for c in clients:
        await c.aclose()
    for s in live.values():
        await s.aclose()


def _chunk(k, sl):
    return tuple(np.asarray(x)[sl] for x in k)


def _hitters(res):
    return {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }


def _events(trace_dir):
    tracemod.flush()
    return tracemod.load_events(str(trace_dir))


# ---------------------------------------------------------------------------
# histograms (obs/hist.py)
# ---------------------------------------------------------------------------


def test_hist_quantiles_and_exact_max():
    h = histmod.Histogram()
    for v in (0.001, 0.002, 0.004, 0.1, 0.1, 0.1, 5.0):
        h.observe(v)
    assert h.count == 7 and h.max == 5.0
    # quantile estimates are good to ~one bucket width (58%)
    assert 0.05 <= h.quantile(0.5) <= 0.16
    assert h.quantile(0.99) <= 5.0
    assert h.quantile(0.95) <= 5.0
    s = h.summary()
    assert s["count"] == 7 and s["max_s"] == 5.0
    assert histmod.Histogram().quantile(0.5) is None  # empty = None


def test_hist_merge_is_bucketwise_and_order_free():
    a, b = histmod.Histogram(), histmod.Histogram()
    for v in (0.01, 0.02, 0.03):
        a.observe(v)
    for v in (1.0, 2.0):
        b.observe(v)
    m1 = histmod.Histogram.merged([a, b])
    m2 = histmod.Histogram.merged([b, a, None])  # None tolerated
    assert m1.count == m2.count == 5
    assert m1.counts == m2.counts
    assert m1.quantile(0.95) == m2.quantile(0.95)


def test_hist_snapshot_round_trip_and_negative_clamp():
    h = histmod.Histogram()
    h.observe(-1.0)  # clamped, not a crash
    h.observe(float("nan"))
    h.observe(0.25)
    h2 = histmod.Histogram.from_snapshot(h.snapshot())
    assert h2.count == h.count and h2.counts == h.counts
    assert h2.quantile(0.99) == pytest.approx(h.quantile(0.99))


def test_registry_observe_reset_and_report_shape():
    reg = obsmetrics.Registry("t-hist")
    assert reg.report() == {"counters": {}, "gauges": {}, "phases": {}}
    reg.observe("level_latency", 0.05)
    reg.observe("rpc:tree_crawl", 0.002)
    rep = reg.report()
    assert rep["hists"]["level_latency"]["count"] == 1
    assert json.loads(json.dumps(rep))  # still json-serializable
    summ = reg.hists_summary()
    assert set(summ) == {"level_latency", "rpc:tree_crawl"}
    assert summ["level_latency"]["p95_s"] is not None
    reg.reset()
    # the hists key disappears with the histograms (pre-SLO shape)
    assert reg.report() == {"counters": {}, "gauges": {}, "phases": {}}


def test_report_slo_section_merges_across_registries():
    a = obsmetrics.Registry("t-slo-a")
    b = obsmetrics.Registry("t-slo-b")
    for v in (0.1, 0.2):
        a.observe("level_latency", v)
    b.observe("level_latency", 0.4)
    a.observe("rpc:status", 0.001)
    doc = obsreport.run_report([a, b])
    slo = doc["slo"]
    assert slo["level_latency"]["count"] == 3  # bucketwise merge
    assert set(slo["level_latency"]["by_registry"]) == {"t-slo-a", "t-slo-b"}
    assert slo["verbs"]["status"]["count"] == 1
    # no histograms anywhere -> no section at all
    empty = obsmetrics.Registry("t-slo-empty")
    assert "slo" not in obsreport.run_report([empty])


# ---------------------------------------------------------------------------
# zero-cost when disabled (the FHH_DEBUG_GUARDS contract)
# ---------------------------------------------------------------------------


def test_trace_disabled_is_structurally_zero_cost(tmp_path, monkeypatch):
    monkeypatch.delenv(tracemod.ENV_DIR, raising=False)
    tracemod._refresh()
    assert tracemod.enabled() is False
    reg = obsmetrics.Registry("t-off")
    with tracemod.root("crawl") as tid:
        assert tid is None  # no trace minted
        with reg.span("level", level=0):
            pass
    # no writer, no context, no files — the span path touched nothing
    assert tracemod._WRITER is None
    assert tracemod.current_ids() is None
    assert not list(tmp_path.iterdir())
    # and the per-span overhead is ONE flag read: span_begin is never
    # called (the _SpanCtx gate is trace.enabled())
    assert tracemod.wire_ctx() is None


def test_trace_bad_dir_degrades_without_killing_telemetry(monkeypatch):
    monkeypatch.setenv(tracemod.ENV_DIR, "/proc/noexist/denied")
    tracemod._refresh()
    reg = obsmetrics.Registry("t-bad-dir")
    with tracemod.root("crawl"):
        with reg.span("level", level=0):
            pass  # must not raise
    assert reg.timer_seconds("level") >= 0  # metrics still recorded
    monkeypatch.delenv(tracemod.ENV_DIR, raising=False)
    tracemod._refresh()


# ---------------------------------------------------------------------------
# span recording: parent chains, error marking, ring rotation
# ---------------------------------------------------------------------------


def test_span_parent_chain_and_error_flag(trace_dir):
    reg = obsmetrics.Registry("t-spans")
    with tracemod.root("crawl") as tid:
        assert tid is not None
        with reg.span("level", level=3):
            with reg.span("fss", level=3):
                pass
        with pytest.raises(ConnectionError):
            with reg.span("gc_ot", level=3):
                raise ConnectionError("data plane down")
    evs = _events(trace_dir)
    by_name = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(by_name) == {"level", "fss", "gc_ot"}
    assert by_name["fss"]["parent"] == by_name["level"]["span"]
    assert by_name["level"].get("parent") is None  # trace root
    assert by_name["gc_ot"].get("error") is True
    assert by_name["fss"].get("error") is None
    assert all(e["trace"] == tid for e in by_name.values())
    v = tracemod.validate(evs)
    assert v["ok"], v["errors"]


def test_nested_root_reuses_the_outer_trace(trace_dir):
    with tracemod.root("window") as outer:
        with tracemod.root("crawl") as inner:
            assert inner == outer  # one trace per outermost root
    with tracemod.root("crawl") as fresh:
        assert fresh != outer


def test_ring_rotation_bounds_the_segment(trace_dir, monkeypatch):
    monkeypatch.setenv(tracemod.ENV_RING, "2048")  # min clamp applies
    tracemod._refresh()
    reg = obsmetrics.Registry("t-ring")
    with tracemod.root("crawl"):
        for i in range(2500):
            with reg.span("fss", level=0):
                pass
    tracemod.flush()
    names = sorted(p.name for p in trace_dir.iterdir())
    assert any(n.endswith(".jsonl.1") for n in names)  # rotated once
    evs = tracemod.load_events(str(trace_dir))
    assert 0 < len(evs) <= 2 * 2048  # bounded at two segments


def test_merge_applies_clock_offsets(trace_dir):
    reg = obsmetrics.Registry("server0")
    with tracemod.root("crawl"):
        with reg.span("level", level=0):
            pass
    tracemod.note_clock("server0", offset_s=100.0, rtt_s=0.01)
    lead = obsmetrics.Registry("leader")
    with tracemod.root("crawl"):
        with lead.span("level", level=0):
            pass
    evs = _events(trace_dir)
    doc = tracemod.to_chrome(evs)
    assert doc["otherData"]["clock_offsets"] == {"server0": 100.0}
    comps = {
        e["pid"]: e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    s0 = next(e for e in xs if comps[e["pid"]] == "server0")
    ld = next(e for e in xs if comps[e["pid"]] == "leader")
    # uncorrected both spans share ~one wall-clock; corrected, server0's
    # sits ~100 s earlier on the merged (leader-time) timeline
    assert ld["ts"] - s0["ts"] > 90e6
    # a per-session registry corrects by its base component's offset
    assert tracemod._offset_for("server0:tenant", {"server0": 7.0}) == 7.0


# ---------------------------------------------------------------------------
# e2e: a supervised secure crawl produces ONE valid merged trace
# ---------------------------------------------------------------------------


def test_e2e_supervised_secure_crawl_trace_and_slo(rng, tmp_path, trace_dir):
    """THE acceptance scenario: leader + both socket servers under
    FHH_TRACE_DIR produce a merged Perfetto trace that validates —
    every span parented under ONE crawl trace id, leader and server
    components present, otext/eval/b2a secure-kernel child spans per
    level, clock-offset records measured — while ``status`` and the run
    report carry the level-latency/per-verb SLO histograms."""
    L, n = 5, 12
    port = BASE_PORT
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port, secure_exchange=True)

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        res = await lead.run_supervised(n, k0, k1)
        st = await c0.call("status")
        await _teardown((c0, c1), live)
        return res, st

    res, st = asyncio.run(run())
    assert _hitters(res)  # the crawl found its hitters

    evs = _events(trace_dir)
    verdict = tracemod.validate(evs)
    assert verdict["ok"], verdict["errors"]
    crawl_traces = [t for t in verdict["traces"] if t.startswith("crawl-")]
    assert len(crawl_traces) == 1  # ONE trace id for the whole crawl
    tid = crawl_traces[0]
    spans = [e for e in evs if e["ph"] == "X" and e.get("trace") == tid]
    comps = {e["comp"] for e in spans}
    assert {"leader", "server0", "server1"} <= comps
    # secure-kernel child spans present per level on the server tracks
    for name in ("otext", "b2a", "gc_ot", "fss", "field"):
        levels = {
            e.get("level")
            for e in spans
            if e["name"] == name and e["comp"].startswith("server")
        }
        assert levels >= set(range(L)), (name, levels)
    # every server phase span has a parent that exists (transitively up
    # to the leader's call span) — spot-check the chain shape
    by_id = {e["span"]: e for e in spans}
    otext = next(e for e in spans if e["name"] == "otext")
    chain = []
    cur = otext
    while cur.get("parent") is not None:
        cur = by_id[cur["parent"]]
        chain.append(cur["name"])
    assert any(c.startswith("verb:") for c in chain)  # server verb span
    assert chain[-1] == "level"  # rooted at the leader's level span
    assert by_id[otext["parent"]]["comp"] == otext["comp"]
    # clock handshake happened for both servers
    clocks = {e["comp"] for e in evs if e["ph"] == "C"}
    assert {"server0", "server1"} <= clocks

    # merged trace loads as Chrome JSON with per-component tracks
    out = tmp_path / "trace.json"
    verdict2 = tracemod.merge(str(trace_dir), str(out))
    assert verdict2["ok"]
    doc = json.loads(out.read_text())
    names = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {"leader", "server0", "server1"} <= names

    # SLO surfaces: status + run report
    slo = st["slo"]
    assert slo["level_latency"]["count"] >= L
    assert slo["level_latency"]["p95_s"] is not None
    assert any(k.startswith("rpc:") for k in slo)
    assert st["sessions"]["per_session"]["default"]["last_progress_s"] >= 0
    assert "clock" in st
    doc = obsreport.run_report()
    assert doc["slo"]["level_latency"]["p95_s"] is not None
    assert "tree_crawl" in doc["slo"]["verbs"]


# ---------------------------------------------------------------------------
# faults: replays record once; severed planes mark spans error=true
# ---------------------------------------------------------------------------


def test_trace_under_chaos_replay_records_each_span_once(
    rng, tmp_path, trace_dir
):
    """The PR-3 e2e chaos scenario with tracing ON: the leader↔s0 link
    is severed in the response direction (verb executed, response lost
    — the reconnect replays the SAME req_id AND the same trace span id)
    and s1 is killed/restarted at the first checkpoint.  The merged
    trace must validate, each server-side verb execution must appear
    EXACTLY once per (trace, parent, name) — the replay was answered
    from the dedup cache, not re-recorded — and the severed data plane
    leaves error=true spans, never dangling opens."""
    L, n = 5, 12
    port = BASE_PORT + 40
    pxport = port + 20
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port)
    ck = tmp_path / "ckpt"
    ck.mkdir()

    async def run():
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:sever@msg=9,dir=s2c"), link="ctl0",
        ).start()
        live = {}
        live["s0"], live["s1"] = await _start_servers(
            cfg, port, ckpt_dir=str(ck)
        )
        c0 = await rpc.CollectorClient.connect("127.0.0.1", pxport)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
        lead = RpcLeader(cfg, c0, c1)

        async def assassin():
            while lead.obs.counter_value("crawl_checkpoints") < 1:
                await asyncio.sleep(0)
            await live["s1"].aclose()
            await asyncio.sleep(0.3)
            live["s1"] = rpc.CollectorServer(1, cfg, ckpt_dir=str(ck))
            await live["s1"].start(
                "127.0.0.1", port + 10, "127.0.0.1", port + 11
            )

        kill = asyncio.create_task(assassin())
        res = await lead.run_supervised(n, k0, k1, checkpoint_every=2)
        await kill
        st0 = await c0.call("status")
        await _teardown((c0, c1), live, px)
        return res, lead, st0

    res, lead, st0 = asyncio.run(run())

    # the faults happened and the crawl still matched the oracle
    assert st0["dedup_hits"] >= 1
    assert lead.obs.counter_value("recoveries") >= 1
    want = driver.Leader(
        *driver.make_servers(k0, k1), n_dims=1, data_len=L, f_max=cfg.f_max
    ).run(nreqs=n, threshold=cfg.threshold)
    assert _hitters(res) == _hitters(want)

    evs = _events(trace_dir)
    verdict = tracemod.validate(evs)
    assert verdict["ok"], verdict["errors"]
    # replay dedup: a server-side verb execution is keyed by its parent
    # (the client call span, which replays VERBATIM) — if the severed
    # verb had re-executed, its (trace, parent, name) would repeat
    seen = {}
    for e in evs:
        if e["ph"] != "X" or not e["name"].startswith("verb:"):
            continue
        key = (e.get("trace"), e.get("parent"), e["name"], e["comp"])
        seen[key] = seen.get(key, 0) + 1
    assert seen, "no verb spans recorded"
    dupes = {k: c for k, c in seen.items() if c > 1}
    assert not dupes, f"replayed verbs re-recorded: {dupes}"
    # the killed server's data plane failed mid-exchange somewhere: the
    # unwound spans carry error=true instead of dangling open
    errs = [e for e in evs if e["ph"] == "X" and e.get("error")]
    assert errs, "no error-marked spans despite a sever + kill"


# ---------------------------------------------------------------------------
# windowed SLO: seal-to-hitters + ingest admit latency
# ---------------------------------------------------------------------------


def test_windowed_seal_to_hitters_histograms(rng, trace_dir):
    L, n = 5, 12
    port = BASE_PORT + 80
    k0, k1 = _client_keys(rng, L, n)
    cfg = _cfg(port)

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        wi = WindowedIngest(lead, checkpoint=False)
        for i in range(n):
            await wi.submit(
                f"c{i % 4}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        await wi.seal_window()
        res = await wi.crawl_window(0)
        st = await c0.call("status")
        s0 = live["s0"]
        driver_h = wi.obs.hist("seal_to_hitters")
        admit_h = wi.obs.hist("ingest_admit")
        server_h = s0.obs.hist("seal_to_hitters")
        await _teardown((c0, c1), live)
        return res, st, driver_h, admit_h, server_h

    res, st, driver_h, admit_h, server_h = asyncio.run(run())
    assert _hitters(res)
    # driver-side: one sealed window crawled -> one observation; admits
    # were counted per submission
    assert driver_h is not None and driver_h.count == 1
    assert driver_h.max > 0
    assert admit_h is not None and admit_h.count == n
    # server-side twin (final_shares observes from the pool's seal
    # instant), and it reaches the status slo section
    assert server_h is not None and server_h.count == 1
    assert st["slo"]["seal_to_hitters"]["count"] == 1
    # the report slo section rolls both views up
    doc = obsreport.run_report()
    assert doc["slo"]["seal_to_hitters"]["count"] >= 2
    assert doc["slo"]["ingest_admit"]["p95_s"] is not None
    # the window trace is distinct from nothing — one window trace id
    evs = _events(trace_dir)
    wins = {e.get("trace") for e in evs if str(e.get("trace", "")).startswith("window-")}
    assert len(wins) == 1


# ---------------------------------------------------------------------------
# per-session heartbeat gap (satellite: last_progress_s)
# ---------------------------------------------------------------------------


def test_last_progress_gap_names_the_wedged_tenant(rng):
    """A second collection uploads keys then goes idle; a later probe
    from ANOTHER session's connection shows tenant t2's
    ``last_progress_s`` growing while the probing session's stays ~0 —
    the wedged-tenant signal the satellite asks for, visible from
    ``status`` without reading logs."""
    port = BASE_PORT + 120
    k0, _k1 = _client_keys(rng, 5, 8)
    cfg = _cfg(port)

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        ct = await rpc.CollectorClient.connect(
            "127.0.0.1", port, collection="t2"
        )
        await ct.call("add_keys", {"keys": _chunk(k0, slice(0, 4))})
        await asyncio.sleep(0.3)  # t2 idles (its last verb completed)
        # a REAL verb progresses default; the status probes below must
        # NOT (a probe resetting the gap would mask the wedge signal)
        await c0.call("add_keys", {"keys": _chunk(k0, slice(4, 6))})
        await c0.call("status")
        st = await c0.call("status")  # probe on the DEFAULT session
        rows = st["sessions"]["per_session"]
        s0 = live["s0"]
        ts = s0.obs.gauge_value("last_progress_ts")
        await ct.aclose()
        await _teardown((c0, c1), live)
        return rows, ts

    rows, ts = asyncio.run(run())
    assert rows["t2"]["last_progress_s"] >= 0.25  # the gap grew
    assert rows["default"]["last_progress_s"] < 0.25  # real verb just ran
    assert ts is not None and abs(time.time() - ts) < 60
    # the run report's per-session row carries the age too — only for
    # NAMED collections (the default session rides the bare registries)
    reg = obsmetrics.Registry("server0:tenantX")
    reg.count("tenant_device_turns")
    reg.gauge("last_progress_ts", time.time() - 3.0)
    reg.timer_add("fss", 0.1, level=0)
    doc = obsreport.run_report([reg])
    row = doc["sessions"]["per_session"]["tenantX"]
    assert 2.0 <= row["last_progress_s"] <= 60.0


def test_status_probe_does_not_reset_the_gap_or_flood_verbs(rng):
    """Review regression: polling status must neither reset
    ``last_progress_s`` (it would mask the wedged-tenant signal it
    exists to read) nor pile probe counts into the rpc:* verbs table."""
    port = BASE_PORT + 160
    k0, _k1 = _client_keys(rng, 5, 8)
    cfg = _cfg(port)

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        await c0.call("add_keys", {"keys": _chunk(k0, slice(0, 2))})
        await asyncio.sleep(0.25)
        for _ in range(5):
            await c0.call("status")
        st = await c0.call("status")
        await _teardown((c0, c1), live)
        return st

    st = asyncio.run(run())
    # six probes later the gap still measures from the add_keys
    assert st["sessions"]["per_session"]["default"]["last_progress_s"] >= 0.2
    assert "rpc:status" not in st["slo"]
    assert "rpc:add_keys" in st["slo"]


def test_call_span_marks_server_error_responses(rng, trace_dir):
    """Review regression: a verb the SERVER failed (an __error__
    response, not a transport loss) must close the client call span
    error=true — filtering the merged timeline by error has to surface
    server-side failures too."""
    port = BASE_PORT + 200
    cfg = _cfg(port)

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        with tracemod.root("crawl"):
            with pytest.raises(RuntimeError, match="tree_init before"):
                await c0.call("tree_init", {})  # no keys: server refuses
        await _teardown((c0, c1), live)

    asyncio.run(run())
    evs = _events(trace_dir)
    call = next(e for e in evs if e.get("name") == "call:tree_init")
    assert call.get("error") is True
    verb = next(e for e in evs if e.get("name") == "verb:tree_init")
    assert verb.get("error") is True  # the span unwound by the raise


def test_clock_offsets_prefer_the_tightest_rtt():
    """Review regression: a chaos-era clock sample measured across a
    reconnect (huge rtt, bogus midpoint) must lose to a tight one."""
    evs = [
        {"ph": "C", "comp": "server0", "off": 40.0, "rtt": 80.0},
        {"ph": "C", "comp": "server0", "off": 0.002, "rtt": 0.001},
        {"ph": "C", "comp": "server0", "off": 39.0, "rtt": 78.0},
    ]
    assert tracemod.clock_offsets(evs) == {"server0": 0.002}
    # no rtt anywhere: median fallback
    evs = [
        {"ph": "C", "comp": "s", "off": v} for v in (1.0, 5.0, 9.0)
    ]
    assert tracemod.clock_offsets(evs) == {"s": 5.0}


def test_sealed_at_survives_ingest_checkpoint_round_trip(rng, tmp_path):
    """Review regression: the seal instant rides the ingest checkpoint,
    so a recovered window still observes its seal-to-hitters latency
    (the replayed seal verb is a no-op on an already-sealed pool and
    must not restamp the clock)."""
    port = BASE_PORT + 240
    k0, _k1 = _client_keys(rng, 5, 8)
    cfg = _cfg(port)
    s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))

    async def go():
        await s.submit_keys({
            "window": 0, "sub_id": "a", "client_id": "c",
            "keys": _chunk(k0, slice(0, 4)),
        })
        await s.window_seal({"window": 0})
        sealed_at = s._default()._ingest_pools[0].sealed_at
        await s.tree_checkpoint({"level": -1, "ingest_only": True})
        fresh = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await fresh.tree_restore({"level": -1})
        pool = fresh._default()._ingest_pools[0]
        return sealed_at, pool

    sealed_at, pool = asyncio.run(go())
    assert sealed_at is not None
    assert pool.sealed and pool.sealed_at == sealed_at


# ---------------------------------------------------------------------------
# chip-profiler gating (FHH_PROFILE / FHH_PROFILE_LEVELS)
# ---------------------------------------------------------------------------


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, d):
        self.calls.append(("start", d))

    def stop_trace(self):
        self.calls.append(("stop", None))


def test_profile_capture_gating(tmp_path, monkeypatch):
    import jax

    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)

    # unset: a no-op
    monkeypatch.delenv(tracemod.ENV_PROFILE, raising=False)
    with tracemod.profile_capture("crawl") as live:
        assert live is False
    assert fake.calls == []

    prof_dir = tmp_path / "prof"
    monkeypatch.setenv(tracemod.ENV_PROFILE, str(prof_dir))
    # whole-crawl mode: crawl captures, per-level hooks stand down
    with tracemod.profile_capture("level", level=3) as live:
        assert live is False
    with tracemod.profile_capture("crawl") as live:
        assert live is True
    assert fake.calls == [("start", str(prof_dir)), ("stop", None)]

    # level mode: only the named levels capture; crawl stands down
    fake.calls.clear()
    monkeypatch.setenv(tracemod.ENV_PROFILE_LEVELS, "2,5")
    with tracemod.profile_capture("crawl") as live:
        assert live is False
    with tracemod.profile_capture("level", level=3) as live:
        assert live is False
    with tracemod.profile_capture("level", level=5) as live:
        assert live is True
    assert fake.calls == [("start", str(prof_dir)), ("stop", None)]

    # captures recorded with kind/level and surfaced by the report
    caps = tracemod.profile_captures()
    assert len(caps) >= 2
    assert caps[-1]["kind"] == "level" and caps[-1]["level"] == 5
    doc = obsreport.run_report([obsmetrics.Registry("t-prof")])
    assert doc["slo"]["profile"][-1]["level"] == 5


def test_profile_capture_survives_profiler_failure(tmp_path, monkeypatch):
    import jax

    def boom(_d):
        raise RuntimeError("profiler busy")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    monkeypatch.setenv(tracemod.ENV_PROFILE, str(tmp_path / "p"))
    monkeypatch.delenv(tracemod.ENV_PROFILE_LEVELS, raising=False)
    with tracemod.profile_capture("crawl") as live:
        assert live is False  # degraded, never raised
