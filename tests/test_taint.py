"""fhh-taint: the interprocedural secret-flow pass (analysis/taint.py)
and its runtime shadow-taint twin (utils/taint_guard.py).

Four layers, cheapest first:

- static fixtures: per-rule positive/negative cases — multi-hop flows
  through helper returns, containers, and f-strings; secret-branch on a
  host bool; declassifier calls clearing taint; verified vs unverified
  ``declassified(reason)`` contracts;
- repo properties: the tree self-analyzes at ZERO with all three rules
  strict; lexical ``secret-to-sink`` findings inside taint_modules are
  a subset of the flow rule's (the supersession invariant); the three
  config copies (pyproject ``[tool.fhh-lint.taint]``,
  ``config._DEFAULT_TAINT``, ``taint_guard._DEFAULT_SOURCES``) cannot
  drift;
- runtime sanitizer units: register/check/declassified/reset, and the
  deliberate-injection legs — a secret pushed into the exporter's
  exposition document or a log line RAISES TaintViolation;
- the e2e leg: a real socket crawl (trusted AND secure) runs green
  under ``FHH_DEBUG_TAINT=1`` with the source constructors armed.

Shapes mirror tests/test_resilience.py (L=5, d=1) so the crawl kernels
compile once across the suites.
"""

import asyncio
import os
import textwrap

import numpy as np
import pytest

import jax  # noqa: F401  (backend selection happens via conftest fixtures)

from fuzzyheavyhitters_tpu.analysis import (
    LintConfig,
    lint_paths,
    lint_source,
    load_config,
)
from fuzzyheavyhitters_tpu.analysis.config import _DEFAULT_TAINT
from fuzzyheavyhitters_tpu.analysis.rules import RULES_BY_NAME
from fuzzyheavyhitters_tpu.utils import taint_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TAINT_RULE_NAMES = ("secret-to-sink-flow", "secret-branch", "unmasked-wire")


def _lint(src, relpath="fuzzyheavyhitters_tpu/protocol/fake.py", cfg=None,
          rule=None):
    cfg = cfg or LintConfig()
    rules = [RULES_BY_NAME[rule]] if rule else None
    return lint_source(textwrap.dedent(src), relpath, cfg, rules)


def _names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule: secret-to-sink-flow (static fixtures)
# ---------------------------------------------------------------------------


def test_flow_multi_hop_helper_container_fstring():
    """The tentpole shape: a declared source read flows through a helper
    RETURN, a dict, and an f-string hole into logs.emit — three hops the
    lexical rule cannot see (nothing at the sink is named like a
    secret).  The finding lands at the sink call site."""
    src = """
    from ..obs import logs

    def _bundle(sess):
        k = derive_seed(sess._sec_seed, "gc", 0, 0)
        return {"k": k}

    def run(sess):
        b = _bundle(sess)
        logs.emit("crawl.window", detail=f"material={b['k']}")
    """
    fs = _lint(src, rule="secret-to-sink-flow")
    assert _names(fs) == ["secret-to-sink-flow"]
    assert fs[0].line == 10


def test_flow_call_site_surfacing_into_leaking_helper():
    """An argument fed to a callee that forwards its parameter to a sink
    is a finding at the CALL SITE (interprocedural summaries)."""
    src = """
    from ..obs import logs

    def _log_it(payload):
        logs.emit("debug.payload", data=payload)

    def run(sess):
        _log_it(sess._sketch_seed)
    """
    fs = _lint(src, rule="secret-to-sink-flow")
    assert len(fs) == 1
    assert fs[0].line == 8  # the _log_it(...) call, not the emit


def test_flow_raise_is_a_sink():
    src = """
    def run(sess):
        s = ratchet_seed(sess, 3)
        raise ValueError(f"bad state: {s}")
    """
    fs = _lint(src, rule="secret-to-sink-flow")
    assert len(fs) == 1 and "exception" in fs[0].message


def test_flow_declassifier_clears_taint():
    """A declared declassifier (pad-XOR encryption, share opening) is a
    masking boundary: its return is public by protocol argument."""
    src = """
    from ..obs import logs

    def run(sess, pads):
        ct = ot2s_encrypt(sess._sec_seed, pads)
        logs.emit("gc.sent", ct=ct)
    """
    assert _lint(src, rule="secret-to-sink-flow") == []


def test_flow_metadata_and_none_checks_clean():
    """Shape/dtype/nbytes reads and `is None` tests carry no secret
    bytes — the precision carve-outs that let real obs code log buffer
    geometry without tripping the flow rule."""
    src = """
    from ..obs import logs

    def run(sess):
        seed = sess._sec_seed
        if seed is None:
            return
        logs.emit("gc.geom", shape=str(seed.shape), n=seed.nbytes)
    """
    assert _lint(src, rule="secret-to-sink-flow") == []


def test_flow_inline_source_marker():
    """`# fhh-taint: source` taints an assignment without a table entry
    — the annotation path for module-local secrets."""
    src = """
    from ..obs import logs

    def run(blob):
        key = blob[3:]  # fhh-taint: source
        logs.emit("x", k=key)
    """
    fs = _lint(src, rule="secret-to-sink-flow")
    assert len(fs) == 1 and fs[0].line == 6


# ---------------------------------------------------------------------------
# rule: secret-branch
# ---------------------------------------------------------------------------


def test_secret_branch_on_host_bool():
    src = """
    def run(sess):
        s = sess._sec_seed
        if s[0] == 3:
            return 1
        return 0
    """
    fs = _lint(src, rule="secret-branch")
    assert _names(fs) == ["secret-branch"]
    assert fs[0].line == 4


def test_secret_branch_none_check_clean():
    src = """
    def run(sess):
        s = sess._sec_seed
        if s is not None:
            return 1
        return 0
    """
    assert _lint(src, rule="secret-branch") == []


def test_secret_branch_through_helper_param():
    """Branching on a parameter that receives tainted data at a call
    site surfaces at the call site (summary: branch_params)."""
    src = """
    def _route(flag):
        if flag:
            return 1
        return 0

    def run(sess):
        return _route(sess._sec_seed[0] > 0)
    """
    fs = _lint(src, rule="secret-branch")
    assert len(fs) == 1 and fs[0].line == 8


# ---------------------------------------------------------------------------
# rule: unmasked-wire
# ---------------------------------------------------------------------------


def test_unmasked_wire_raw_seed_send():
    src = """
    class OtExtSender:
        def leak(self):
            self._send(self._seeds)
    """
    fs = _lint(src, rule="unmasked-wire")
    assert _names(fs) == ["unmasked-wire"]
    assert fs[0].line == 4


def test_unmasked_wire_masked_send_clean():
    src = """
    class OtExtSender:
        def ok(self, pads):
            self._send(ot2s_encrypt(self._seeds, pads))
    """
    assert _lint(src, rule="unmasked-wire") == []


# ---------------------------------------------------------------------------
# declassified(reason) contracts: checked, never trusted
# ---------------------------------------------------------------------------


def test_declassified_contract_verified_suppresses():
    """A contract naming a declared declassifier THAT IS CALLED in the
    enclosing function covers the finding on its line."""
    src = """
    from ..obs import logs

    def run(sess, shares):
        opened = ev_open_level(shares)
        seed = sess._sec_seed
        logs.emit("lvl", v=opened, s=str(seed))  # fhh-taint: declassified(ev_open_level)
    """
    assert _lint(src, rule="secret-to-sink-flow") == []


def test_declassified_contract_op_not_called_is_finding():
    """Naming a real declassifier that is NOT on the path is itself a
    finding — and does not cover the flow finding."""
    src = """
    from ..obs import logs

    def run(sess):
        seed = sess._sec_seed
        logs.emit("lvl", s=str(seed))  # fhh-taint: declassified(np_add)
    """
    fs = _lint(src, rule="secret-to-sink-flow")
    assert len(fs) == 2
    assert any("never called" in f.message for f in fs)


def test_declassified_contract_unknown_reason_is_finding():
    src = """
    from ..obs import logs

    def run(x):
        logs.emit("lvl", v=x)  # fhh-taint: declassified(because I said so)
    """
    fs = _lint(src, rule="secret-to-sink-flow")
    assert len(fs) == 1
    assert "names no declared declassifier" in fs[0].message


# ---------------------------------------------------------------------------
# repo properties: self-analysis at zero, supersession subset, no drift
# ---------------------------------------------------------------------------


def test_repo_self_taint_analysis_zero():
    """The tree is CLEAN under all three flow rules with no baseline
    entries — the tier-1 enforcement point for this subsystem (the full
    self-lint in test_analysis.py covers every rule; this one isolates
    the taint pass so its failures read as taint failures)."""
    cfg = load_config(REPO)
    rules = [RULES_BY_NAME[n] for n in TAINT_RULE_NAMES]
    findings, errors = lint_paths(
        [os.path.join(REPO, "fuzzyheavyhitters_tpu")], cfg, REPO, rules
    )
    assert errors == []
    assert findings == [], "\n".join(f.render() for f in findings)


def test_lexical_subset_of_flow_in_taint_modules():
    """Supersession invariant: inside taint_modules, every site the
    lexical secret-to-sink rule flags on the REAL tree must also be
    flagged by the flow rule — the lexical rule stays as a pre-filter
    and may never be the only thing standing between a true leak and
    the baseline.  (Fixtures where lexical fires and flow does not —
    e.g. logging `seed.shape` — are the flow rule's precision WINS,
    which is exactly why real findings must come from the flow rule.)"""
    cfg = load_config(REPO)
    scope = [os.path.join(REPO, m) for m in cfg.taint_modules]
    lex, _ = lint_paths(scope, cfg, REPO, [RULES_BY_NAME["secret-to-sink"]])
    flow, _ = lint_paths(
        scope, cfg, REPO, [RULES_BY_NAME["secret-to-sink-flow"]]
    )
    lex_sites = {(f.path, f.line) for f in lex}
    flow_sites = {(f.path, f.line) for f in flow}
    assert lex_sites <= flow_sites, lex_sites - flow_sites


def test_lexical_and_flow_agree_on_a_true_leak():
    """The subset property is not vacuous: on a genuine leak where the
    sink argument is NAMED like a secret, both rules fire at the same
    line."""
    src = """
    from ..obs import logs

    def run(sess):
        seed = sess._sec_seed
        logs.emit("oops", seed=seed)
    """
    lex = _lint(src, rule="secret-to-sink")
    flow = _lint(src, rule="secret-to-sink-flow")
    assert [f.line for f in lex] == [f.line for f in flow] == [6]


def test_taint_config_three_way_drift():
    """The three copies of the source model cannot drift:

    - pyproject ``[tool.fhh-lint.taint]`` (the operative copy) must load
      to exactly ``config._DEFAULT_TAINT`` (the in-tree default);
    - every runtime-registrable source in
      ``taint_guard._DEFAULT_SOURCES`` must be a DECLARED attr source
      (the static pass knows strictly more than the sanitizer — fn
      returns and device-resident state it cannot byte-match);
    - the scalar knobs (modules/sinks/wire/declassifiers) in pyproject
      must match the LintConfig defaults."""
    cfg = load_config(REPO)
    assert cfg.taint == _DEFAULT_TAINT
    runtime = set(taint_guard._DEFAULT_SOURCES)
    declared_attrs = {k for k in cfg.taint if "." in k}
    assert runtime <= declared_attrs, runtime - declared_attrs
    defaults = LintConfig()
    for knob in ("taint_modules", "taint_sinks", "taint_wire_calls",
                 "taint_declassifiers"):
        assert getattr(cfg, knob) == getattr(defaults, knob), knob


# ---------------------------------------------------------------------------
# runtime sanitizer: register / check / declassified / reset
# ---------------------------------------------------------------------------


@pytest.fixture
def armed(monkeypatch):
    """Sanitizer ON with a fresh registry; always disarmed after."""
    monkeypatch.setenv("FHH_DEBUG_TAINT", "1")
    taint_guard.reset()
    yield
    taint_guard.reset()


def _secret():
    return np.frombuffer(os.urandom(32), dtype=np.uint8)


def test_guard_off_is_inert(monkeypatch):
    monkeypatch.delenv("FHH_DEBUG_TAINT", raising=False)
    taint_guard.reset()
    s = _secret()
    taint_guard.register("CollectionSession._sec_seed", s)
    assert not taint_guard._armed
    taint_guard.check(s.tobytes(), sink="anywhere")  # no-op, no raise


def test_guard_catches_bytes_containment_hex_and_repr(armed):
    s = _secret()
    taint_guard.register("CollectionSession._sec_seed", s)
    raw = s.tobytes()
    for payload in (
        raw,                                  # byte-equal
        b"frame:" + raw + b":tail",           # byte-contained
        f"v={raw.hex()}",                     # hex interpolation
        f"arr={s}",                           # str(ndarray) interpolation
        {"lines": ["ok", {"deep": raw}]},     # nested containers
    ):
        with pytest.raises(taint_guard.TaintViolation) as ei:
            taint_guard.check(payload, sink="metrics-render")
        assert "CollectionSession._sec_seed" in str(ei.value)
    # innocent payloads pass
    taint_guard.check("all clear", sink="metrics-render")
    taint_guard.check({"n": 7, "rows": [os.urandom(8)]}, sink="x")


def test_guard_declassified_window_and_reason(armed):
    s = _secret()
    taint_guard.register("OtExtSender._seeds", s)
    with taint_guard.declassified("ot2s_encrypt pads cover this frame"):
        taint_guard.check(s.tobytes(), sink="wire")  # sanctioned
    with pytest.raises(taint_guard.TaintViolation):
        taint_guard.check(s.tobytes(), sink="wire")  # window closed
    with pytest.raises(ValueError):
        taint_guard.declassified("   ")


def test_guard_reset_disarms(armed):
    s = _secret()
    taint_guard.register("OtExtReceiver._seeds0", s)
    taint_guard.reset()
    taint_guard.check(s.tobytes(), sink="wire")  # registry gone


def test_guard_short_scalars_not_text_marked(armed):
    """A tiny buffer gets byte markers but no str() text marker (a
    2-char repr would trip on unrelated digits in any rendered line)."""
    tiny = np.frombuffer(b"\x07", dtype=np.uint8)
    taint_guard.register("CollectionSession._sketch_seed", tiny)
    taint_guard.check("value=7 elsewhere", sink="log-emit")  # no raise
    with pytest.raises(taint_guard.TaintViolation):
        taint_guard.check(b"\x07", sink="log-emit")  # bytes still caught


# ---------------------------------------------------------------------------
# deliberate injection: the obs sink boundaries really assert
# ---------------------------------------------------------------------------


def test_injection_exporter_render_raises(armed):
    from fuzzyheavyhitters_tpu.obs import exporter

    s = _secret()
    taint_guard.register("CollectionSession._sec_seed", s)
    leak = f'fhh_debug_dump{{blob="{s.tobytes().hex()}"}} 1'
    exporter.add_producer(lambda: [leak])
    try:
        with pytest.raises(taint_guard.TaintViolation) as ei:
            exporter.render()
        assert "metrics-render" in str(ei.value)
    finally:
        with exporter._lock:
            exporter._producers.clear()
    # with the leak gone the document renders (and is scanned) fine
    assert exporter.render() is not None


def test_injection_log_emit_raises(armed):
    from fuzzyheavyhitters_tpu.obs import logs

    s = _secret()
    taint_guard.register("CollectionSession._ratchet_digest", s)
    with pytest.raises(taint_guard.TaintViolation) as ei:
        logs.emit("debug.dump", blob=s.tobytes().hex())
    assert "log-emit" in str(ei.value)
    logs.emit("debug.dump", blob="0000")  # clean lines still flow


# ---------------------------------------------------------------------------
# e2e: a real socket crawl under FHH_DEBUG_TAINT=1
# ---------------------------------------------------------------------------

BASE_PORT = 27531


def _cfg(port, **kw):
    from fuzzyheavyhitters_tpu.utils.config import Config

    base = dict(
        data_len=5, n_dims=1, ball_size=1, addkey_batch_size=64,
        num_sites=4, threshold=0.05, zipf_exponent=1.0,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=16, backend="cpu",
    )
    base.update(kw)
    return Config(**base)


def _client_keys(seed, L, n):
    from fuzzyheavyhitters_tpu.ops import ibdcf

    r = np.random.default_rng(seed)
    sites = r.integers(0, 1 << L, size=4)
    pts = sites[r.integers(0, 4, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, r, engine="np")


@pytest.mark.parametrize("secure", [False, True], ids=["trusted", "secure"])
def test_e2e_crawl_green_under_taint_sanitizer(
    cpu_default, monkeypatch, secure
):
    """The whole point of the runtime twin: the REAL protocol — session
    handshake, (secure: base-OT + IKNP + GC data plane), crawl, sketch
    verify — runs green with every obs sink boundary asserting, because
    nothing the protocol legitimately renders contains registered
    source bytes.  The secure leg proves the OT/session constructors
    actually armed the sanitizer in-process."""
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader

    monkeypatch.setenv("FHH_DEBUG_TAINT", "1")
    taint_guard.reset()
    port = BASE_PORT + (0 if secure else 40)
    cfg = _cfg(port, secure_exchange=secure)
    k0, k1 = _client_keys(1234, 5, 12)

    async def run():
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
        )
        await asyncio.gather(t0, t1)
        c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
        lead = RpcLeader(cfg, c0, c1)
        await lead._both("reset")
        await lead.upload_keys(k0, k1)
        res = await lead.run(12)
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
        return res

    try:
        res = asyncio.run(run())
        assert res is not None
        if secure:
            # the source constructors really registered (session seed,
            # coin flip, OT endpoint state) — the crawl above exercised
            # every sink boundary with the sanitizer live
            assert taint_guard._armed
            assert taint_guard._byte_markers
    finally:
        taint_guard.reset()
