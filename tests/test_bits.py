"""Bit codec tests (ref semantics: src/lib.rs:191-239, sample_driving_data.rs:149-163)."""

import numpy as np

from fuzzyheavyhitters_tpu.utils import bits as B


def test_u32_roundtrip():
    assert list(B.u32_to_bits(0, 7)) == []
    assert list(B.u32_to_bits(2, 3)) == [True, True]
    assert list(B.u32_to_bits(2, 1)) == [True, False]
    assert B.bits_to_u32(B.msb_u32_to_bits(12, 1234)) == 1234


def test_string_roundtrip():
    s = "basfsdfwefwf"
    b = B.string_to_bits(s)
    assert b.size == len(s) * 8
    assert B.bits_to_string(b) == s
    assert list(B.string_to_bits("a")) == [True, False, False, False, False, True, True, False]


def test_all_bit_vectors_ordering():
    v = B.all_bit_vectors(2)
    assert v.shape == (4, 2)
    # pattern i has bit j = (i >> j) & 1  (lib.rs:125-129)
    assert [list(r) for r in v] == [
        [False, False],
        [True, False],
        [False, True],
        [True, True],
    ]


def test_bitstring_arithmetic_saturates():
    a = B.msb_u32_to_bits(8, 200)
    assert B.bits_to_u32(B.add_bitstrings(a, B.msb_u32_to_bits(8, 10))) == 210
    assert B.bits_to_u32(B.add_bitstrings(a, B.msb_u32_to_bits(8, 100))) == 255
    assert B.bits_to_u32(B.subtract_bitstrings(a, B.msb_u32_to_bits(8, 10))) == 190
    assert B.bits_to_u32(B.subtract_bitstrings(B.msb_u32_to_bits(8, 10), a)) == 0
    # width promotion: delta wider than alpha (ibDCF.rs:178 uses 32-bit delta)
    assert B.bits_to_u32(B.subtract_bitstrings(B.msb_u32_to_bits(8, 9), B.msb_u32_to_bits(32, 4))) == 5


def test_i16_bitvec_roundtrip():
    for v in [0, 1, -1, 3026, -9774, 32767, -32768]:
        assert B.bitvec_to_i16(B.i16_to_bitvec(v)) == v


def test_pack_bits_lsb():
    arr = np.array([[True, False, True], [False, True, True]])
    packed = B.pack_bits_lsb(arr)
    assert list(packed) == [0b101, 0b110]
