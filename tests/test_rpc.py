"""Control-plane tests: both collector servers + leader in one asyncio loop
(the reference's in-process duplex-socket 2PC test pattern,
ref: equalitytest.rs:222-266) — full 8-verb protocol over real TCP on
localhost, counts reconstructed from field-element shares."""

import asyncio

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 21131


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the RPC layer under test is host-side glue; its device
    programs are the same crawl kernels test_protocol.py compiles (shapes
    harmonized), and every remote-tunnel compile costs ~10 s flat."""
    yield


def _cfg(**kw):
    defaults = dict(
        data_len=6,
        n_dims=1,
        ball_size=2,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.1,
        zipf_exponent=1.03,
        server0="127.0.0.1:21131",
        server1="127.0.0.1:21141",
        distribution="zipf",
        f_max=128,
    )
    defaults.update(kw)
    return Config(**defaults)


async def _run_protocol(cfg, keys0, keys1, nreqs, port0, port1):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    peer_port = port1 + 1
    # server1 first (it listens on the data plane), then server0 dials —
    # the reference's startup ordering constraint (server.rs:344-354)
    t1 = asyncio.create_task(s1.start("127.0.0.1", port1, "127.0.0.1", peer_port))
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(s0.start("127.0.0.1", port0, "127.0.0.1", peer_port))
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port0)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port1)
    await asyncio.gather(t0, t1)

    lead = RpcLeader(cfg, c0, c1)
    await asyncio.gather(c0.call("reset"), c1.call("reset"))
    await lead.upload_keys(keys0, keys1)
    return await lead.run(nreqs)


def test_rpc_protocol_matches_colocated(rng):
    # (L, d, n, f_max) match test_protocol.py's d=1 scenarios so the crawl
    # kernels compile once for both files
    L, d, n = 6, 1, 40
    cfg = _cfg(data_len=L, n_dims=d)
    pts = np.concatenate([np.full(32, 20), rng.integers(0, 1 << L, size=8)])[:, None]
    pts_bits = np.array([[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts])
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, cfg.ball_size, rng)

    res = asyncio.run(_run_protocol(cfg, k0, k1, n, BASE_PORT, BASE_PORT + 10))
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }

    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=cfg.f_max)
    want_res = lead.run(nreqs=n, threshold=cfg.threshold)
    want = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(want_res.decode_ints(), want_res.counts)
    }
    assert got == want
    assert got  # the 16 stacked clients at 20 must clear the threshold


def test_share_masks_cancel():
    """Server0's and server1's mask streams are identical, so shares
    reconstruct exactly (the shared-seed trick, ref: server.rs:331-332)."""
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62

    r0 = rpc.mask_fe62(3, 10)
    r1 = rpc.mask_fe62(3, 10)
    np.testing.assert_array_equal(r0, r1)
    assert not np.array_equal(r0, rpc.mask_fe62(4, 10))  # level-keyed
    counts = np.arange(10).astype(np.uint64)
    rec = np.asarray(FE62.canon(FE62.sub(FE62.add(counts, r0), r1)))
    np.testing.assert_array_equal(rec, counts)

    m0 = rpc.mask_f255(2, 6)
    c = np.zeros((6, 8), np.uint32)
    c[:, 0] = np.arange(6)
    rec = np.asarray(F255.sub(F255.add(c, m0), rpc.mask_f255(2, 6)))
    np.testing.assert_array_equal(rec[:, 0], np.arange(6))
    assert not rec[:, 1:].any()


def test_reset_clears_state(rng):
    """reset → add_keys → tree_init works twice (ref: server.rs:64-69)."""

    async def flow():
        cfg = _cfg()
        s0 = rpc.CollectorServer(0, cfg)
        pts_bits = np.array([[bitutils.int_to_bits(6, 20)]])
        k0, _ = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
        for _ in range(2):
            await s0.reset({})
            await s0.add_keys({"keys": tuple(np.asarray(x) for x in k0)})
            await s0.tree_init({})
            assert s0.keys.cw_seed.shape[0] == 1
        return True

    assert asyncio.run(flow())


# ---------------------------------------------------------------------------
# failure paths the resilience layer builds on
# ---------------------------------------------------------------------------


def test_error_response_propagates_and_connection_survives(rng):
    """A verb that fails server-side comes back as an __error__ response
    raising RuntimeError at the caller — and the connection stays usable
    (the error is a RESPONSE, not a transport death)."""
    port = 21231

    async def flow():
        cfg = _cfg(
            server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}"
        )
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
        )
        await asyncio.gather(t0, t1)
        c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
        with pytest.raises(RuntimeError, match="tree_init before add_keys"):
            await c0.call("tree_init")
        # protocol errors are NOT retried (they would never succeed) and
        # the transport survives them
        assert c0.epoch == 1
        assert await c0.call("reset") is True
        await c0.aclose()
        await s0.aclose()
        await s1.aclose()

    asyncio.run(flow())


def test_read_loop_death_fails_inflight_futures():
    """Reader death must fail EVERY in-flight caller loudly (no future
    left dangling), and once redials exhaust, the call surfaces a
    ConnectionError — with the pending table empty (the send-failure /
    reader-death paths may not leak futures)."""
    port = 21251

    async def flow():
        conns = []

        async def half_server(reader, writer):
            # answer the hello, then die mid-protocol without responding
            req_id, verb, req = await rpc._recv(reader)
            assert verb == "__hello__"
            await rpc._send(writer, (req_id, {"boot_id": "fake"}))
            conns.append((reader, writer))
            await rpc._recv(reader)  # swallow one verb frame...
            writer.close()  # ...and hang up without answering

        srv = await asyncio.start_server(half_server, "127.0.0.1", port)
        from fuzzyheavyhitters_tpu.resilience import policy as respolicy

        c = await rpc.CollectorClient.connect(
            "127.0.0.1", port,
            dial_policy=respolicy.RetryPolicy(
                base_s=0.001, attempts=2, rand=lambda: 0.0
            ),
            budgets=respolicy.VerbBudgets(default_s=5.0, per_verb={}),
        )
        srv.close()  # no more accepts: redials must exhaust
        await srv.wait_closed()
        with pytest.raises(ConnectionError):
            await c.call("reset")
        assert c._pending == {}  # nothing leaked across the failed call
        await c.aclose()

    asyncio.run(flow())


def test_send_failure_pops_pending():
    """The _send-raises-mid-write path: the pending future is dropped so
    _pending cannot grow across failed calls (it used to leak one entry
    per failure), and a non-transport bug propagates unretried."""
    port = 21261

    async def flow():
        async def hello_only(reader, writer):
            req_id, verb, _ = await rpc._recv(reader)
            await rpc._send(writer, (req_id, {"boot_id": "fake"}))

        srv = await asyncio.start_server(hello_only, "127.0.0.1", port)
        c = await rpc.CollectorClient.connect("127.0.0.1", port)

        class Boom(Exception):
            pass

        real_send = rpc._send

        async def broken_send(writer, obj, count=None, flush=True):
            raise Boom("pickling exploded mid-write")

        rpc._send = broken_send
        try:
            with pytest.raises(Boom):
                await c.call("reset")
        finally:
            rpc._send = real_send
        assert c._pending == {}
        await c.aclose()
        srv.close()
        await srv.wait_closed()

    asyncio.run(flow())


def test_keepalive_sets_socket_options():
    """_keepalive arms SO_KEEPALIVE with the aggressive-ish probe timing
    on the data-plane socket (a silently-dead peer surfaces in ~2 min,
    not the kernel's ~2 h default)."""
    import socket

    port = 21271

    async def flow():
        async def server(reader, writer):
            await asyncio.sleep(0.2)
            writer.close()

        srv = await asyncio.start_server(server, "127.0.0.1", port)
        _, w = await asyncio.open_connection("127.0.0.1", port)
        rpc.CollectorServer._keepalive(w)
        sock = w.get_extra_info("socket")
        assert sock.getsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE) == 1
        for opt, want in (
            ("TCP_KEEPIDLE", 60), ("TCP_KEEPINTVL", 20), ("TCP_KEEPCNT", 3)
        ):
            if hasattr(socket, opt):
                assert sock.getsockopt(
                    socket.IPPROTO_TCP, getattr(socket, opt)
                ) == want
        w.close()
        srv.close()
        await srv.wait_closed()

    asyncio.run(flow())
