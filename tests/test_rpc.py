"""Control-plane tests: both collector servers + leader in one asyncio loop
(the reference's in-process duplex-socket 2PC test pattern,
ref: equalitytest.rs:222-266) — full 8-verb protocol over real TCP on
localhost, counts reconstructed from field-element shares."""

import asyncio

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.protocol import driver, rpc
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 39131


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the RPC layer under test is host-side glue; its device
    programs are the same crawl kernels test_protocol.py compiles (shapes
    harmonized), and every remote-tunnel compile costs ~10 s flat."""
    yield


def _cfg(**kw):
    defaults = dict(
        data_len=6,
        n_dims=1,
        ball_size=2,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.1,
        zipf_exponent=1.03,
        server0="127.0.0.1:39131",
        server1="127.0.0.1:39141",
        distribution="zipf",
        f_max=128,
    )
    defaults.update(kw)
    return Config(**defaults)


async def _run_protocol(cfg, keys0, keys1, nreqs, port0, port1):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    peer_port = port1 + 1
    # server1 first (it listens on the data plane), then server0 dials —
    # the reference's startup ordering constraint (server.rs:344-354)
    t1 = asyncio.create_task(s1.start("127.0.0.1", port1, "127.0.0.1", peer_port))
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(s0.start("127.0.0.1", port0, "127.0.0.1", peer_port))
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port0)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port1)
    await asyncio.gather(t0, t1)

    lead = RpcLeader(cfg, c0, c1)
    await asyncio.gather(c0.call("reset"), c1.call("reset"))
    await lead.upload_keys(keys0, keys1)
    return await lead.run(nreqs)


def test_rpc_protocol_matches_colocated(rng):
    # (L, d, n, f_max) match test_protocol.py's d=1 scenarios so the crawl
    # kernels compile once for both files
    L, d, n = 6, 1, 40
    cfg = _cfg(data_len=L, n_dims=d)
    pts = np.concatenate([np.full(32, 20), rng.integers(0, 1 << L, size=8)])[:, None]
    pts_bits = np.array([[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts])
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, cfg.ball_size, rng)

    res = asyncio.run(_run_protocol(cfg, k0, k1, n, BASE_PORT, BASE_PORT + 10))
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }

    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=d, data_len=L, f_max=cfg.f_max)
    want_res = lead.run(nreqs=n, threshold=cfg.threshold)
    want = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(want_res.decode_ints(), want_res.counts)
    }
    assert got == want
    assert got  # the 16 stacked clients at 20 must clear the threshold


def test_share_masks_cancel():
    """Server0's and server1's mask streams are identical, so shares
    reconstruct exactly (the shared-seed trick, ref: server.rs:331-332)."""
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62

    r0 = rpc.mask_fe62(3, 10)
    r1 = rpc.mask_fe62(3, 10)
    np.testing.assert_array_equal(r0, r1)
    assert not np.array_equal(r0, rpc.mask_fe62(4, 10))  # level-keyed
    counts = np.arange(10).astype(np.uint64)
    rec = np.asarray(FE62.canon(FE62.sub(FE62.add(counts, r0), r1)))
    np.testing.assert_array_equal(rec, counts)

    m0 = rpc.mask_f255(2, 6)
    c = np.zeros((6, 8), np.uint32)
    c[:, 0] = np.arange(6)
    rec = np.asarray(F255.sub(F255.add(c, m0), rpc.mask_f255(2, 6)))
    np.testing.assert_array_equal(rec[:, 0], np.arange(6))
    assert not rec[:, 1:].any()


def test_reset_clears_state(rng):
    """reset → add_keys → tree_init works twice (ref: server.rs:64-69)."""

    async def flow():
        cfg = _cfg()
        s0 = rpc.CollectorServer(0, cfg)
        pts_bits = np.array([[bitutils.int_to_bits(6, 20)]])
        k0, _ = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
        for _ in range(2):
            await s0.reset({})
            await s0.add_keys({"keys": tuple(np.asarray(x) for x in k0)})
            await s0.tree_init({})
            assert s0.keys.cw_seed.shape[0] == 1
        return True

    assert asyncio.run(flow())
