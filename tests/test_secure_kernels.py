"""Device-resident secure-kernel tests: the GF(2^128) algebra under the
1-of-2^S equality OT, ot_hash tweak-domain separation, engine parity of
the planar packed wire (XLA twins vs Pallas interpret), cross-parity of
the 1-of-2^S path against the GC path for S ∈ {2, 4, 6} on both fields,
mid-level ``idx_offset`` continuity across batches, the whole-level
socket flow (phase split, ot_path telemetry, whole-level vs sharded
bit-identity, a 2-dim oracle run), and the warmed-crawl
zero-fresh-compiles contract."""

import asyncio

import numpy as np
import pytest

import jax.numpy as jnp

from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import gc, gc_pallas, ibdcf, otext, otext_pallas
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.protocol import driver, rpc, secure
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 21531


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """All tests in this module run on the CPU backend (see conftest)."""
    yield


# ---------------------------------------------------------------------------
# GF(2^128) algebra: doubling, comb, offsets
# ---------------------------------------------------------------------------

_POLY = 0x87  # x^128 = x^7 + x^2 + x + 1 (otext.gf128_double's constant)


def _ref_int(block) -> int:
    return int.from_bytes(np.asarray(block, "<u4").tobytes(), "little")


def _ref_double(v: int) -> int:
    v <<= 1
    if v >> 128:
        v = (v ^ _POLY) & ((1 << 128) - 1)
    return v


def test_gf128_double_matches_bigint_reference(rng):
    x = rng.integers(0, 2**32, size=(32, 4), dtype=np.uint32)
    got = np.asarray(otext.gf128_double(x))
    for row, out in zip(x, got):
        assert _ref_int(out) == _ref_double(_ref_int(row))


def test_gf128_double_field_identities(rng):
    """Doubling is GF(2^128)-linear and invertible: 2(x^y) = 2x^2y, the
    map is injective on a sample, and 2^128 applications reduce to the
    known field element x^128 = 0x87 when starting from 1."""
    x = rng.integers(0, 2**32, size=(16, 4), dtype=np.uint32)
    y = rng.integers(0, 2**32, size=(16, 4), dtype=np.uint32)
    dbl = lambda a: np.asarray(otext.gf128_double(a))
    np.testing.assert_array_equal(dbl(x ^ y), dbl(x) ^ dbl(y))
    assert len({bytes(r) for r in dbl(x)}) == len(x)
    one = np.zeros((1, 4), np.uint32)
    one[0, 0] = 1
    acc = one
    for _ in range(128):
        acc = dbl(acc)
    assert _ref_int(acc[0]) == _POLY


def test_gf128_comb_is_the_coefficient_sum(rng):
    """comb(rows) == ⊕_j x^j·rows_j against the bigint reference, for
    every S the ot2s path ships."""
    for S in (2, 4, 6):
        rows = rng.integers(0, 2**32, size=(5, S, 4), dtype=np.uint32)
        got = np.asarray(otext.gf128_comb(rows))
        for b in range(5):
            want = 0
            for j in range(S):
                v = _ref_int(rows[b, j])
                for _ in range(j):
                    v = _ref_double(v)
                want ^= v
            assert _ref_int(got[b]) == want, (S, b)


def test_gf128_offsets_distinct_and_linear(rng):
    """The 2^S offset table is pairwise distinct (the 1-of-2^S privacy
    argument) and GF(2)-linear in the choice: o_c ^ o_c' == o_{c^c'}."""
    s = np.asarray(otext.s_to_block(otext.fresh_s_bits()))
    for S in (2, 4, 6):
        offs = np.asarray(otext.gf128_offsets(s, S))
        assert len({bytes(o) for o in offs}) == 1 << S, S
        c1, c2 = 0b0110 % (1 << S), 0b1011 % (1 << S)
        np.testing.assert_array_equal(offs[c1] ^ offs[c2], offs[c1 ^ c2])


# ---------------------------------------------------------------------------
# ot_hash: tweak-domain and index separation
# ---------------------------------------------------------------------------


def test_ot_hash_domain_separation(rng):
    """Identical rows at identical indices hash independently per
    tweak-domain — the property that lets the per-TEST 1-of-2^S pads
    share an index range with the per-ROW Δ-OT pads."""
    rows = rng.integers(0, 2**32, size=(8, 4), dtype=np.uint32)
    p0 = np.asarray(otext.ot_hash(rows, 4, 0))
    p1 = np.asarray(otext.ot_hash(rows, 4, 0, domain=secure._OT2S_DOMAIN))
    assert not np.array_equal(p0, p1)
    assert (p0 != p1).any(axis=1).all()  # every row separated


def test_ot_hash_index_separation_and_offset(rng):
    """The same row at different positions hashes differently, and
    ``idx_offset`` IS the position: H(row, idx_offset=k) equals row k of
    a batch hash starting at 0 — the invariant mid-level batch
    continuity rests on."""
    row = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    tiled = np.broadcast_to(row, (8, 4)).copy()
    pads = np.asarray(otext.ot_hash(tiled, 4, 0))
    assert len({bytes(p) for p in pads}) == 8
    single = np.asarray(otext.ot_hash(row[None], 4, 7))
    np.testing.assert_array_equal(single[0], pads[7])


# ---------------------------------------------------------------------------
# fused extension: extend+pads as one program
# ---------------------------------------------------------------------------


def test_extend_pads_matches_split_form(rng):
    """The one-dispatch extend_pads is bit-identical to extend followed
    by pads, on both roles, and advances the counters in lockstep."""
    snd, rcv = otext.inprocess_pair()
    m = 96
    r = rng.integers(0, 2, size=m).astype(bool)
    u, t, pad_r = rcv.extend_pads(r, 4)
    q, p0, p1 = snd.extend_pads(m, np.asarray(u), 4)
    np.testing.assert_array_equal(
        np.asarray(t),
        np.where(r[:, None], np.asarray(q) ^ snd.s_block, np.asarray(q)),
    )
    np.testing.assert_array_equal(
        np.asarray(pad_r), np.asarray(otext.ot_hash(t, 4, 0))
    )
    np.testing.assert_array_equal(
        np.asarray(pad_r),
        np.where(r[:, None], np.asarray(p1), np.asarray(p0)),
    )
    assert snd.consumed == rcv.consumed == m
    # second batch: the pad index base moved with the counters
    u2, t2, pad_r2 = rcv.extend_pads(r, 4)
    q2, p0b, p1b = snd.extend_pads(m, np.asarray(u2), 4)
    np.testing.assert_array_equal(
        np.asarray(pad_r2), np.asarray(otext.ot_hash(t2, 4, m))
    )
    assert snd.consumed == rcv.consumed == 2 * m


# ---------------------------------------------------------------------------
# 1-of-2^S: engine parity + cross-parity against the GC path
# ---------------------------------------------------------------------------


def _delta_rows(qr, y, s):
    """Receiver rows t_j = q_j ^ y_j·s from sender rows (the Δ-OT law)."""
    B, S = y.shape
    flat = np.where(
        y.reshape(B * S, 1), qr.reshape(B * S, 4) ^ s, qr.reshape(B * S, 4)
    )
    return flat.reshape(B, S, 4)


def _ot2s_planar_parity(rng, S, field):
    B = 40
    W = secure.payload_words(field)
    s = np.asarray(otext.s_to_block(otext.fresh_s_bits()))
    qr = rng.integers(0, 2**32, size=(B, S, 4), dtype=np.uint32)
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    y[::3] = ~y[::3]
    m0 = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    m1 = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    msg_x = np.asarray(secure._ot2s_encrypt_packed_xla(
        jnp.asarray(qr), jnp.asarray(s), jnp.asarray(x), jnp.asarray(m0),
        jnp.asarray(m1), W, 17,
    ))
    msg_p = np.asarray(otext_pallas.ot2s_encrypt(
        qr, s, x, m0, m1, W, 17, domain=secure._OT2S_DOMAIN, interpret=True
    ))
    np.testing.assert_array_equal(msg_x, msg_p)
    tr = _delta_rows(qr, y, s)
    pay_x = np.asarray(secure._ot2s_decrypt_packed_xla(
        jnp.asarray(tr), jnp.asarray(y), jnp.asarray(msg_x), S, W, 17
    ))
    pay_p = np.asarray(otext_pallas.ot2s_decrypt(
        tr, y, msg_p, W, 17, domain=secure._OT2S_DOMAIN, interpret=True
    ))
    np.testing.assert_array_equal(pay_x, pay_p)
    eq = np.all(x == y, axis=1)
    np.testing.assert_array_equal(pay_x, np.where(eq[:, None], m1, m0))


@pytest.mark.parametrize("S", [2, 4])
@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
def test_ot2s_planar_engine_parity(rng, S, field):
    """The planar wire buffer is BYTE-identical between the XLA twin and
    the Pallas kernel (interpret mode), padding included, and opens to
    the right payload."""
    _ot2s_planar_parity(rng, S, field)


@pytest.mark.slow
@pytest.mark.parametrize("field", [FE62, F255], ids=["FE62", "F255"])
def test_ot2s_planar_engine_parity_s6(rng, field):
    """S = 6 engine parity (slow-marked: the 64-choice interpret-mode
    kernel compiles in tens of seconds on XLA:CPU)."""
    _ot2s_planar_parity(rng, 6, field)


def test_gc_packed_engine_parity(rng):
    """The packed whole-level garbled message is byte-identical between
    the XLA twin and the Pallas kernel, and its eval twins agree."""
    B, S, W = 24, 4, 4
    s = np.asarray(otext.s_to_block(otext.fresh_s_bits()))
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    Y0 = rng.integers(0, 2**32, size=(B, S, 4), dtype=np.uint32)
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    m0 = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    m1 = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    msg_x, mask_x = gc._garble_equality_payload_packed_xla(
        jnp.asarray(s), jnp.asarray(Y0), jnp.asarray(seed), jnp.asarray(x),
        jnp.asarray(m0), jnp.asarray(m1), W, 3,
    )
    msg_p, mask_p = gc_pallas.garble_equality_payload_packed(
        s, Y0, seed, x, m0, m1, W, 3, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(msg_x), np.asarray(msg_p))
    np.testing.assert_array_equal(np.asarray(mask_x), np.asarray(mask_p))
    assert np.asarray(msg_x).size == gc_pallas.packed_msg_words(B, S, W)
    ev = Y0 ^ np.where(x[..., None], s, np.zeros(4, np.uint32))
    e_x, pay_x = gc._eval_equality_payload_packed_xla(
        msg_x, jnp.asarray(ev), S, W, 3
    )
    e_p, pay_p = gc_pallas.eval_equality_payload_packed(
        np.asarray(msg_p), ev, W, 3, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(e_x), np.asarray(e_p))
    np.testing.assert_array_equal(np.asarray(pay_x), np.asarray(pay_p))
    np.testing.assert_array_equal(np.asarray(pay_x), m1)  # y == x: all equal


@pytest.mark.parametrize(
    # every (S, field) pair; the garbler sign (a ±1 in the payload pair,
    # path-independent) is swept once at the cheapest shape
    "S,field,garbler",
    [
        pytest.param(s, f, 0, id=f"S{s}-{fn}-g0")
        for s in (2, 4, 6) for f, fn in ((FE62, "FE62"), (F255, "F255"))
    ] + [pytest.param(2, FE62, 1, id="S2-FE62-g1")],
)
def test_ot2s_cross_parity_with_gc_path(rng, S, field, garbler):
    """THE satellite contract: the 1-of-2^S whole-level flow is
    BIT-IDENTICAL to the GC whole-level flow — not just the
    reconstructed [x == y] but both sides' additive shares (same
    b2a seed -> same r0/r1 stream), for S ∈ {2, 4, 6} on FE62 and F255,
    whichever side garbles."""
    B = 30
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    flip = rng.integers(0, 2, size=B).astype(bool)
    y[flip, rng.integers(0, S, size=B)[flip]] ^= True
    eq = np.all(x == y, axis=1)
    gs = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    bs = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    shares = {}
    for path in ("ot2s", "gc"):
        snd, rcv = otext.inprocess_pair()
        u, t, idx0 = secure.ev_step1_fused(rcv, y)
        msg, v_gb = secure.gb_step_level(
            snd, np.asarray(u), x, gs, bs, field, garbler, path=path
        )
        v_ev = secure.ev_open_level(
            t, y, np.asarray(msg), B, S, field, idx0, path=path
        )
        v0, v1 = (v_gb, v_ev) if garbler == 0 else (v_ev, v_gb)
        diff = np.asarray(field.canon(field.sub(v0, v1)))
        if field is F255:
            np.testing.assert_array_equal(diff[:, 0], eq.astype(np.uint32))
            assert not diff[:, 1:].any()
        else:
            np.testing.assert_array_equal(diff, eq.astype(np.uint64))
        shares[path] = (
            np.asarray(field.canon(v0)), np.asarray(field.canon(v1))
        )
    np.testing.assert_array_equal(shares["ot2s"][0], shares["gc"][0])
    np.testing.assert_array_equal(shares["ot2s"][1], shares["gc"][1])


@pytest.mark.parametrize("path", ["ot2s", "gc"])
def test_mid_level_idx_offset_continuity(rng, path):
    """Two successive whole-level batches on ONE extension session: the
    pad index base advances with the consumed counter, so identical
    inputs produce different wire bytes (no pad reuse) while both
    batches open correctly — the mid-level continuity the sharded /
    multi-level crawl depends on."""
    field = FE62
    B, S = 20, 4
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    y = x.copy()
    y[::4] = ~y[::4]
    eq = np.all(x == y, axis=1)
    gs = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    bs = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    snd, rcv = otext.inprocess_pair()
    msgs = []
    for batch in range(2):
        u, t, idx0 = secure.ev_step1_fused(rcv, y)
        assert idx0 == batch * B * S  # the counter IS the index base
        msg, v0 = secure.gb_step_level(
            snd, np.asarray(u), x, gs, bs, field, 0, path=path
        )
        v1 = secure.ev_open_level(
            t, y, np.asarray(msg), B, S, field, idx0, path=path
        )
        diff = np.asarray(field.canon(field.sub(v0, v1)))
        np.testing.assert_array_equal(diff, eq.astype(np.uint64))
        msgs.append(np.asarray(msg))
    assert snd.consumed == rcv.consumed == 2 * B * S
    # same inputs, same seeds — but a moved index base: every pad (and
    # with it the wire) must differ, or batch 2 would reuse batch 1's
    assert not np.array_equal(msgs[0], msgs[1])


# ---------------------------------------------------------------------------
# Socket flow: whole-level crawl, phase split, 2-dim oracle, warm compile
# ---------------------------------------------------------------------------


def _cfg(port_base, **kw):
    # f_max=8 keeps the warmup ladder (and with it the per-bucket compile
    # space these tests pay on XLA:CPU) to four rungs; the crawls here
    # never outgrow it
    defaults = dict(
        data_len=5,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=8,
        secure_exchange=True,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, L, n, d=1):
    pts = np.concatenate(
        [np.full((n - 4, d), 11), rng.integers(0, 1 << L, size=(4, d))]
    )
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return pts_bits, ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


async def _run_crawl(cfg, port, k0, k1, nreqs=12, warmup=False):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
    )
    await asyncio.gather(t0, t1)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    await lead.upload_keys(k0, k1)
    if warmup:
        await lead.warmup()
    res = await lead.run(nreqs)
    for c in (c0, c1):
        await c.aclose()
    return res, lead, (s0, s1)


def _crawl(cfg, port, k0, k1, **kw):
    async def go():
        res, lead, servers = await _run_crawl(cfg, port, k0, k1, **kw)
        for s in servers:
            await s.aclose()
        return res, lead, servers

    return asyncio.run(go())


def test_whole_level_crawl_phase_split_and_parity(rng):
    """The default secure crawl runs WHOLE-LEVEL (one GC/OT batch per
    level even with crawl_shard_nodes set, no pipeline telemetry), its
    results are bit-identical to the GC-path form, and the run report
    carries the full secure-kernel split: otext/b2a busy, garble/eval
    present-but-zero on the ot2s path, the ot_path counters, and the
    rolled-up ``secure_kernels`` section.  (Whole-level vs SHARDED
    secure parity is pinned by test_pipeline's secure leg.)"""
    L, n = 5, 12
    _, (k0, k1) = _client_keys(rng, L, n)
    res_whole, lead_w, servers = _crawl(
        _cfg(BASE_PORT, crawl_shard_nodes=1, crawl_pipeline_depth=3),
        BASE_PORT, k0, k1,
    )
    # whole-level collapsed the sharded pipeline: no pipeline telemetry
    assert lead_w.obs.timer_seconds("pipeline_overlap") == 0.0
    rep = obsreport.run_report(
        [lead_w.obs, servers[0].obs, servers[1].obs]
    )
    assert "pipeline" not in rep
    sk = rep["secure_kernels"]
    assert sk["ot_path"] == "ot2s"
    assert sk["levels_ot2s"] == 2 * L and sk["levels_gc"] == 0
    assert sk["otext_seconds"] > 0.0 and sk["b2a_seconds"] > 0.0
    assert sk["garble_seconds"] == 0.0 and sk["eval_seconds"] == 0.0
    for s in servers:  # all four phases materialized on BOTH registries
        phases = s.obs.report()["phases"]
        for name in ("otext", "garble", "eval", "b2a"):
            assert name in phases, name
    res_gc, _, gc_servers = _crawl(
        _cfg(BASE_PORT + 80, ot_path="gc"), BASE_PORT + 80, k0, k1
    )
    assert res_whole.counts.size  # real hitters: a real compare
    np.testing.assert_array_equal(res_whole.counts, res_gc.counts)
    np.testing.assert_array_equal(res_whole.paths, res_gc.paths)
    # the GC-path run reports its path + nonzero circuit phases
    rep_gc = obsreport.run_report([s.obs for s in gc_servers])
    assert rep_gc["secure_kernels"]["ot_path"] == "gc"
    assert rep_gc["secure_kernels"]["garble_seconds"] > 0.0
    assert rep_gc["secure_kernels"]["eval_seconds"] > 0.0


def test_two_dim_secure_crawl_matches_trusted_oracle(rng):
    """n_dims = 2 -> S = 4: the generalized 1-of-16 path through the
    full socket flow matches the trusted-mode driver bit-for-bit — the
    multi-dimensional crawl really does skip the garbled circuit."""
    L, n, d = 4, 12, 2
    pts_bits, (k0, k1) = _client_keys(rng, L, n, d=d)
    # 2^d-way branching needs frontier headroom past the 1-dim default
    cfg = _cfg(BASE_PORT + 120, data_len=L, n_dims=d, f_max=32)
    res, _, servers = _crawl(cfg, BASE_PORT + 120, k0, k1)
    rep = obsreport.run_report([s.obs for s in servers])
    assert rep["secure_kernels"]["ot_path"] == "ot2s"  # no GC engaged
    got = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }
    s0, s1 = driver.make_servers(k0, k1)
    want_res = driver.Leader(
        s0, s1, n_dims=d, data_len=L, f_max=cfg.f_max
    ).run(nreqs=n, threshold=cfg.threshold)
    want = {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(want_res.decode_ints(), want_res.counts)
    }
    assert got == want and got


def test_warmed_secure_crawl_triggers_zero_fresh_compiles(rng):
    """THE warmup-completeness contract: after one warmed crawl has run,
    a second crawl over the same shapes triggers ZERO fresh XLA backend
    compiles (utils/compile_cache.backend_compiles).  Catches every
    per-batch recompile regression at once: a counter leaking into a
    static arg, a fresh jit wrapper per call, or a warmup hole in the
    fused otext/ot2s/gc program ladder (the OT counters, crawl counter,
    and session seeds all differ between the two crawls, so anything
    shape-stable that recompiles on VALUES fails here loudly)."""
    from fuzzyheavyhitters_tpu.utils import compile_cache

    L, n = 5, 12
    _, (k0, k1) = _client_keys(rng, L, n)
    res1, _, _ = _crawl(
        _cfg(BASE_PORT + 160), BASE_PORT + 160, k0, k1, warmup=True
    )
    before = compile_cache.backend_compiles()
    res2, _, _ = _crawl(
        _cfg(BASE_PORT + 200), BASE_PORT + 200, k0, k1, warmup=True
    )
    fresh = compile_cache.backend_compiles() - before
    np.testing.assert_array_equal(res1.counts, res2.counts)
    np.testing.assert_array_equal(res1.paths, res2.paths)
    assert fresh == 0, f"{fresh} fresh compiles in a fully-warmed crawl"
