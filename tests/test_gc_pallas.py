"""Fused Pallas garble/eval kernels vs the XLA engine — bit-exact on the
real chip (same TPU-only gating rationale as test_keygen_pallas.py).

The Pallas pair is the DEFAULT payload-GC engine on real chips
(ops/gc.GC_PALLAS), and it draws the garbler's labels + mask from the
same PRG stream as the XLA form, so entire ``GarbledEqBatch``es must
match word-for-word: tables (tree order), active input labels, decode
bits, payload ciphertexts, and the evaluator's opened payloads.  Shapes
cover the production case (S=2, the 1-dim L∞ string pair), the covid
shape (S=4), an odd tree (S=3), both payload widths (FE62 W=4, F255
W=8), and non-block-multiple batch sizes (the pad path).
"""

import numpy as np
import pytest

from conftest import has_tpu as _has_tpu


pytestmark = [
    pytest.mark.skipif(not _has_tpu(), reason="needs a TPU backend"),
    pytest.mark.tpu_retry,
]


@pytest.mark.parametrize(
    "B,S,W", [(1000, 2, 4), (4096, 2, 8), (300, 4, 4), (513, 3, 4)]
)
def test_payload_engines_bit_exact(rng, B, S, W):
    from fuzzyheavyhitters_tpu.ops import gc, gc_pallas

    R = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    R[0] |= 1  # lsb(R) = 1 (free-XOR point-and-permute)
    Y0 = rng.integers(0, 2**32, size=(B, S, 4), dtype=np.uint32)
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    mv0 = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    mv1 = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    idx0 = 977

    bx, cx, mx = gc._garble_equality_payload_xla(
        R, Y0, seed, x, mv0, mv1, W, idx0
    )
    bp, cp, mp = gc_pallas.garble_equality_payload(
        R, Y0, seed, x, mv0, mv1, W, idx0
    )
    np.testing.assert_array_equal(np.asarray(bx.tables), np.asarray(bp.tables))
    np.testing.assert_array_equal(
        np.asarray(bx.gb_labels), np.asarray(bp.gb_labels)
    )
    np.testing.assert_array_equal(np.asarray(bx.decode), np.asarray(bp.decode))
    np.testing.assert_array_equal(np.asarray(cx), np.asarray(cp))
    np.testing.assert_array_equal(np.asarray(mx), np.asarray(mp))

    # evaluator: active labels for a random peer string y
    y = rng.integers(0, 2, size=(B, S)).astype(bool)
    evl = np.asarray(Y0) ^ (y[..., None] * np.asarray(R))
    ex, px = gc._eval_equality_payload_xla(bx, evl, cx, W, idx0)
    ep, pp = gc_pallas.eval_equality_payload(bx, evl, cx, W, idx0)
    np.testing.assert_array_equal(np.asarray(ex), np.asarray(ep))
    np.testing.assert_array_equal(np.asarray(px), np.asarray(pp))

    # protocol semantics survive the engine: mask ^ e == [x == y]
    eq = (x == y).all(axis=1)
    np.testing.assert_array_equal(np.asarray(mx) ^ np.asarray(ep), eq)


def test_dispatcher_selects_pallas_on_chip(rng):
    """gc.garble_equality_payload routes through the kernel engine on a
    real chip by default, and the flag restores the XLA path."""
    from fuzzyheavyhitters_tpu.ops import gc

    assert gc.GC_PALLAS and gc._pallas_engine()
    B, S, W = 64, 2, 4
    R = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    R[0] |= 1
    Y0 = rng.integers(0, 2**32, size=(B, S, 4), dtype=np.uint32)
    seed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    x = rng.integers(0, 2, size=(B, S)).astype(bool)
    mv = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    b1, c1, m1 = gc.garble_equality_payload(R, Y0, seed, x, mv, mv, W, 0)
    gc.GC_PALLAS = False
    try:
        b2, c2, m2 = gc.garble_equality_payload(R, Y0, seed, x, mv, mv, W, 0)
    finally:
        gc.GC_PALLAS = True
    np.testing.assert_array_equal(np.asarray(b1.tables), np.asarray(b2.tables))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
