"""Elastic collector fleet: placement, live migration, whole-host failover.

The fleet layer's acceptance surface (protocol/fleet.py + the
``session_export``/``session_import`` verb pair + ``WindowedIngest.migrate``
/ ``failover_to``):

- THE migration e2e: a secure (GC/OT) and a malicious/sketch collection
  each migrated between two host pairs MID-STREAM — heavy-hitter sets
  bit-identical to the never-migrated run, exactly-once ingest asserted
  through the journal-replay dedup hits, and the sketch leg's replayed
  window re-opening the IDENTICAL pre-migration challenge root;
- THE failover e2e: a ``host:kill`` chaos clause kills a whole pair
  mid-crawl — the orphaned session resumes on the surviving pair from
  its newest checkpoint, bit-identical to fault-free, with the new
  ``fleet`` sections asserted in ``status`` and the run report;
- migration edge cases: torn export blob refused validate-before-mutate
  style, mid-level export refused, double-import refused by the
  (boot, epoch) stamp, and a migrated window's reservoir RNG continuing
  the identical shed stream;
- :class:`FleetDirectory` units: file-based registration scan,
  least-loaded placement, dead-boot probing.

Shapes mirror tests/test_ingest.py (L=5, d=1) so the crawl kernels
compile once across the suites.
"""

import asyncio
import contextlib
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fuzzyheavyhitters_tpu.obs import alerts as obsalerts
from fuzzyheavyhitters_tpu.obs import metrics as obsmetrics
from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.protocol import rpc, sketch
from fuzzyheavyhitters_tpu.protocol.fleet import (
    FleetDirectory,
    FleetPlacer,
    HostPair,
)
from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
    IngestOverloadedError,
    MultiCollectionDriver,
    RpcLeader,
    WindowedIngest,
)
from fuzzyheavyhitters_tpu.resilience import policy as respolicy
from fuzzyheavyhitters_tpu.resilience.chaos import (
    HostChaos,
    HostFaultSpec,
    parse_host_faults,
)
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 27131

L, N = 5, 12


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """CPU backend: the fleet layer is host-side glue over the same crawl
    kernels the other protocol suites compile."""
    yield


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=L,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=32,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, n=N):
    pts = np.concatenate(
        [np.full(n - 4, 11), rng.integers(0, 1 << L, size=4)]
    )[:, None]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


def _chunk(k, sl):
    return tuple(np.asarray(x)[sl] for x in k)


def _sk_chunk(sk, sl):
    return [np.asarray(x)[sl] for x in jax.tree.leaves(sk)]


def _hitters(res):
    return {
        tuple(int(v) for v in r): int(c)
        for r, c in zip(res.decode_ints(), res.counts)
    }


async def _start_pair(cfg, port, ckpt_dir=None):
    s0 = rpc.CollectorServer(0, cfg, ckpt_dir=ckpt_dir)
    s1 = rpc.CollectorServer(1, cfg, ckpt_dir=ckpt_dir)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


async def _bring_up(cfg, port, ckpt_dir=None):
    """Source-pair bring-up: clients + leader, reset included (fresh
    session)."""
    live = {}
    live["s0"], live["s1"] = await _start_pair(cfg, port, ckpt_dir)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    return lead, c0, c1, live


async def _bring_up_dest(cfg, port, ckpt_dir):
    """Destination-pair bring-up: NO reset — a reset's ckpt_clear would
    delete the shared-namespace blobs the transfer is about to import."""
    live = {}
    live["s0"], live["s1"] = await _start_pair(cfg, port, ckpt_dir)
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    lead = RpcLeader(cfg, c0, c1)
    return lead, c0, c1, live


async def _teardown(clients, *lives):
    for c in clients:
        await c.aclose()
    for live in lives:
        for s in live.values():
            await s.aclose()


# ---------------------------------------------------------------------------
# host chaos grammar
# ---------------------------------------------------------------------------


def test_parse_host_faults_grammar():
    faults = parse_host_faults("host:kill@window=2; host:kill@window=5")
    assert faults == [
        HostFaultSpec(action="kill", at_window=2),
        HostFaultSpec(action="kill", at_window=5),
    ]
    assert parse_host_faults("") == []
    with pytest.raises(ValueError, match="host:kill@window=N"):
        parse_host_faults("host:kill")
    with pytest.raises(ValueError, match="must target 'host'"):
        parse_host_faults("mesh:kill@window=1")
    with pytest.raises(ValueError, match="unknown host chaos action"):
        parse_host_faults("host:pause@window=1")
    with pytest.raises(ValueError, match="unknown host chaos arg"):
        parse_host_faults("host:kill@level=1")


def test_host_chaos_fires_once_per_clause():
    hc = HostChaos(parse_host_faults("host:kill@window=1"))
    assert hc.before_window(0) is False
    assert hc.before_window(1) is True  # fires at its boundary...
    assert hc.before_window(2) is False  # ...and is consumed
    assert hc.fired == [("kill", 1)]


# ---------------------------------------------------------------------------
# FleetDirectory units: scan, placement, probe
# ---------------------------------------------------------------------------


def _reg_row(d, pair, sid, boot, capacity=4):
    import json

    path = d / f"{pair}_s{sid}.json"
    path.write_text(json.dumps({
        "pair": pair, "server_id": sid, "host": "127.0.0.1",
        "port": 1000 + sid, "boot_id": boot, "capacity": capacity,
        "ts": 1.0,
    }))


def test_directory_scan_folds_halves_and_skips_torn(tmp_path):
    _reg_row(tmp_path, "pairA", 0, "bootA0", capacity=2)
    _reg_row(tmp_path, "pairA", 1, "bootA1", capacity=2)
    _reg_row(tmp_path, "pairB", 0, "bootB0")  # half a pair: still booting
    (tmp_path / "torn_s0.json").write_text("{\"pair\": \"to")  # torn write

    async def run():
        fd = FleetDirectory(fleet_dir=str(tmp_path))
        n = await fd.scan()
        pairs = await fd.pairs()
        # load signals survive a re-scan (scan replaces rows, the probe
        # loop owns the signals)
        await fd.note_load("pairA", stall_fill_ratio=0.5,
                           max_progress_age_s=3.0)
        await fd.scan()
        return n, pairs, await fd.pairs()

    n, pairs, rescanned = asyncio.run(run())
    assert n == 1
    assert [p.name for p in pairs] == ["pairA"]
    assert (pairs[0].boot0, pairs[0].boot1) == ("bootA0", "bootA1")
    assert pairs[0].capacity == 2
    assert rescanned[0].stall_fill_ratio == 0.5
    assert rescanned[0].max_progress_age_s == 3.0


def test_placement_prefers_least_loaded_pair():
    async def run():
        fd = FleetDirectory()
        await fd.register(HostPair(name="A", capacity=1))
        await fd.register(HostPair(name="B", capacity=4))
        p1 = await fd.place("t1")  # tie on load ratio -> name order
        p2 = await fd.place("t2")  # A is at 1/1: B wins
        p3 = await fd.place("t3")  # B at 1/4 still beats A at 1/1
        # stall pressure breaks a load-ratio tie
        await fd.register(HostPair(name="C", capacity=4))
        await fd.note_load("C", stall_fill_ratio=0.9)
        p4 = await fd.place("t4")  # B (2/4, no stall) beats C (0/4? no --
        # C is 0/4 vs B 2/4: C wins on ratio despite the stall signal)
        await fd.mark_dead("C")
        p5 = await fd.place("t5", exclude=("A",))
        st = await fd.status()
        return [p.name for p in (p1, p2, p3, p4, p5)], st

    names, st = asyncio.run(run())
    assert names == ["A", "B", "B", "C", "B"]
    assert st["placements"]["t1"] == "A"
    assert st["pairs"]["C"]["alive"] is False
    # no live candidate left -> loud refusal
    async def none_left():
        fd = FleetDirectory()
        await fd.register(HostPair(name="X", alive=False))
        with pytest.raises(RuntimeError, match="no live pair"):
            await fd.place("t")

    asyncio.run(none_left())


def test_probe_marks_dead_on_error_and_on_changed_boot():
    async def run():
        fd = FleetDirectory()
        await fd.register(HostPair(name="up", boot0="b0", boot1="b1"))
        await fd.register(HostPair(name="rebooted", boot0="b0", boot1="b1"))
        await fd.register(HostPair(name="down", boot0="b0", boot1="b1"))
        await fd.move("tenant", "down")

        async def probe_fn(name):
            if name == "down":
                raise ConnectionError("unreachable")
            if name == "rebooted":
                return {"boot0": "b0", "boot1": "NEW"}
            return {"boot0": "b0", "boot1": "b1"}

        died = await fd.probe(probe_fn)
        return sorted(died), await fd.orphans_of("down"), await fd.status()

    died, orphans, st = asyncio.run(run())
    assert died == ["down", "rebooted"]
    assert orphans == ["tenant"]
    assert st["pairs"]["up"]["alive"] is True
    assert st["pairs"]["down"]["alive"] is False


# ---------------------------------------------------------------------------
# migration edge cases (validate-before-mutate, stamps, quiesce, RNG)
# ---------------------------------------------------------------------------


def test_session_export_refuses_mid_level_and_without_ckpt_dir(tmp_path):
    port = BASE_PORT + 400
    k0, _ = _client_keys(np.random.default_rng(3))

    async def run():
        bare = rpc.CollectorServer(0, _cfg(port))
        with pytest.raises(RuntimeError, match="no checkpoint dir"):
            await bare.session_export({})
        s = rpc.CollectorServer(0, _cfg(port), ckpt_dir=str(tmp_path))
        await s.submit_keys(
            {"window": 0, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        s._default()._children = []  # in-flight expand cache = mid-level
        with pytest.raises(RuntimeError, match="mid-level"):
            await s.session_export({})
        s._default()._children = None
        x = await s.session_export({})
        assert x["epoch"] == 1 and os.path.exists(x["path"])

    asyncio.run(run())


def test_session_import_refuses_torn_blob_without_mutating(tmp_path):
    port = BASE_PORT + 410
    k0, _ = _client_keys(np.random.default_rng(4))
    cfg = _cfg(port)

    async def run():
        src = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await src.submit_keys(
            {"window": 0, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        x = await src.session_export({})
        blob = open(x["path"], "rb").read()
        with open(x["path"], "wb") as f:
            f.write(blob[: len(blob) // 2])  # torn mid-write
        dst = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        with pytest.raises(RuntimeError, match="corrupt or truncated"):
            await dst.session_import(
                {"path": x["path"], "boot": x["boot"], "epoch": x["epoch"]}
            )
        # live state untouched on BOTH hosts
        assert dst._default()._ingest_pools == {}
        assert len(src._default()._ingest_pools[0].entries) == 1

    asyncio.run(run())


def test_session_import_refuses_wrong_stamp_and_double_import(tmp_path):
    port = BASE_PORT + 420
    k0, _ = _client_keys(np.random.default_rng(5))
    cfg = _cfg(port)

    async def run():
        src = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await src.submit_keys(
            {"window": 0, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        x = await src.session_export({})
        dst = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        with pytest.raises(RuntimeError, match="stale file"):
            await dst.session_import(
                {"path": x["path"], "boot": x["boot"], "epoch": 99}
            )
        got = await dst.session_import(
            {"path": x["path"], "boot": x["boot"], "epoch": x["epoch"]}
        )
        assert got["windows"] == [0]
        # a (boot, epoch) stamp imports at most once: double-applying
        # would double-land the in-flight sub_id replays
        with pytest.raises(RuntimeError, match="already imported"):
            await dst.session_import(
                {"path": x["path"], "boot": x["boot"], "epoch": x["epoch"]}
            )

    asyncio.run(run())


def test_retire_requires_matching_epoch_and_drops_sealed_pools(tmp_path):
    """The bounded-retention satellite: a migrated-away session's SEALED
    pools (which idle eviction never drops — only empty ones evict) are
    dropped by the post-transfer retire."""
    port = BASE_PORT + 430
    k0, _ = _client_keys(np.random.default_rng(6))
    cfg = _cfg(port)

    async def run():
        s = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await s.submit_keys(
            {"window": 0, "sub_id": "a", "client_id": "c",
             "keys": _chunk(k0, slice(0, 2))}
        )
        await s.window_seal({"window": 0})
        x = await s.session_export({})
        with pytest.raises(RuntimeError, match="retire epoch"):
            await s.session_export({"retire": True, "epoch": 99})
        with pytest.raises(RuntimeError, match="retire epoch"):
            await s.session_export({"retire": True})  # no epoch at all
        assert len(s._default()._ingest_pools) == 1  # refusals mutated nothing
        r = await s.session_export({"retire": True, "epoch": x["epoch"]})
        assert r == {"retired": True, "pools_dropped": 1}
        assert s._default()._ingest_pools == {}
        assert not os.path.exists(x["path"])

    asyncio.run(run())


def test_migrated_reservoir_continues_identical_shed_stream(tmp_path):
    """A migrated window's reservoir RNG state rides the export blob:
    the destination's future shed decisions continue the source's stream
    EXACTLY (same slots, same seal stats) — sampling uniformity survives
    the move."""
    port = BASE_PORT + 440
    k0, _ = _client_keys(np.random.default_rng(8))
    cfg = _cfg(
        port, ingest_window_keys=4, ingest_shed="reservoir", ingest_seed=17
    )

    async def run():
        src = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        for i in range(8):  # fill + engage the sampler
            await src.submit_keys(
                {"window": 0, "sub_id": f"s{i}", "client_id": "c",
                 "keys": _chunk(k0, slice(i, i + 1))}
            )
        x = await src.session_export({})
        dst = rpc.CollectorServer(0, cfg, ckpt_dir=str(tmp_path))
        await dst.session_import(
            {"path": x["path"], "boot": x["boot"], "epoch": x["epoch"]}
        )
        want, got = [], []
        for srv, out in ((src, want), (dst, got)):
            for i in range(8, 12):
                out.append(await srv.submit_keys(
                    {"window": 0, "sub_id": f"f{i}", "client_id": "c",
                     "keys": _chunk(k0, slice(i, i + 1))}
                ))
        assert got == want
        st_src = await src.window_seal({"window": 0})
        st_dst = await dst.window_seal({"window": 0})
        assert st_src == st_dst

    asyncio.run(run())


def test_scheduler_fleet_load_signals():
    """TenantScheduler exposes the pair-half placement signals in the
    shape FleetDirectory.note_load consumes; a retired session's stale
    progress stamp is forgotten (it must not pin the age signal)."""
    from fuzzyheavyhitters_tpu.protocol.tenancy import TenantScheduler

    sched = TenantScheduler()
    assert sched.fleet_load(now=100.0) == {
        "stall_fill_ratio": 0.0, "max_progress_age_s": 0.0,
    }
    sched.note_dispatch("ta")
    load = sched.fleet_load(now=time.time() + 5.0)
    assert 5.0 <= load["max_progress_age_s"] < 6.0
    sched.forget("ta")
    assert sched.fleet_load(now=100.0)["max_progress_age_s"] == 0.0


# ---------------------------------------------------------------------------
# fleet observability plumbing
# ---------------------------------------------------------------------------


def test_fleet_report_section_present_only_with_fleet_activity():
    reg = obsmetrics.Registry("t-fleet-rep")
    reg.count("session_failovers")
    reg.count("placement_decisions", 2)
    rep = obsreport.run_report([reg])
    assert rep["fleet"]["session_failovers"] == 1
    assert rep["fleet"]["placement_decisions"] == 2
    quiet = obsmetrics.Registry("t-fleet-rep-quiet")
    quiet.count("keys_uploaded", 5)
    assert "fleet" not in obsreport.run_report([quiet])


def test_migration_stuck_alert_fires_on_aged_inflight_gauge():
    obsalerts._reset_for_tests()
    reg = obsmetrics.Registry("t-fleet-alert")
    reg.gauge("migration_inflight_since", time.time() - 500.0)
    obsalerts.evaluate_registries([reg])
    fired = [r for r in obsalerts.fired() if r["rule"] == "migration_stuck"]
    assert fired and fired[0]["subject"] == "t-fleet-alert"
    assert fired[0]["inflight_s"] > 120
    # a cleared gauge (the placer zeroes it on ANY outcome) never fires
    obsalerts._reset_for_tests()
    reg2 = obsmetrics.Registry("t-fleet-alert-clear")
    reg2.gauge("migration_inflight_since", 0.0)
    obsalerts.evaluate_registries([reg2])
    assert not [
        r for r in obsalerts.fired() if r["rule"] == "migration_stuck"
    ]
    obsalerts._reset_for_tests()


# ---------------------------------------------------------------------------
# THE migration e2e: secure leg + malicious/sketch leg
# ---------------------------------------------------------------------------


def _windowed_control(cfg, port, submit_plan, crawl_windows):
    """Never-migrated reference: the same submission/seal sequence on a
    single pair — what every migrated run must be bit-identical to."""

    async def run():
        lead, c0, c1, live = await _bring_up(cfg, port)
        wi = WindowedIngest(lead, checkpoint=False)
        for step in submit_plan:
            if step == "seal":
                await wi.seal_window()
            else:
                await wi.submit(*step[0], **step[1])
        out = [await wi.crawl_window(w) for w in crawl_windows]
        await _teardown((c0, c1), live)
        return out

    return asyncio.run(run())


@pytest.mark.slow  # ~20 s: full secure e2e on three host pairs
def test_migration_mid_stream_secure_bit_identical(rng, tmp_path):
    """THE migration e2e (secure leg): a GC/OT collection is migrated
    between host pairs mid-stream — window 0 sealed on the source,
    window 1 in flight — then BOTH windows crawl on the destination.
    Heavy hitters are bit-identical to the never-migrated run, the
    journal replay's dedup hits prove exactly-once ingest, the source's
    retained pools are dropped, and the fleet sections land in status +
    run report."""
    port_a, port_b = BASE_PORT, BASE_PORT + 40
    k0, k1 = _client_keys(rng)
    ck = tmp_path / "ck"
    ck.mkdir()
    cfg_a = _cfg(port_a, secure_exchange=True)
    cfg_b = _cfg(port_b, secure_exchange=True)

    plan = []
    for i in range(6):
        plan.append(((f"c{i}", _chunk(k0, slice(i, i + 1)),
                      _chunk(k1, slice(i, i + 1))), {}))
    plan.append("seal")
    for i in range(6, 12):
        plan.append(((f"c{i}", _chunk(k0, slice(i, i + 1)),
                      _chunk(k1, slice(i, i + 1))), {}))
    plan.append("seal")

    async def run():
        lead_a, c0a, c1a, live_a = await _bring_up(
            cfg_a, port_a, ckpt_dir=str(ck)
        )
        wi = WindowedIngest(lead_a)  # checkpointing ON
        for i in range(6):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        await wi.seal_window()
        for i in range(6, 9):  # window 1 in flight at migration time
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        lead_b, c0b, c1b, live_b = await _bring_up_dest(
            cfg_b, port_b, str(ck)
        )
        fd = FleetDirectory()
        await fd.register(HostPair(name="A"))
        await fd.register(HostPair(name="B"))
        await fd.move("default", "A")
        placer = FleetPlacer(fd)
        stats = await placer.migrate(wi, lead_b, session="default", dest="B")
        for i in range(9, 12):  # the stream continues on the destination
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        res0 = await wi.crawl_window(0)
        await wi.seal_window()
        res1 = await wi.crawl_window(1)
        dup_hits = int(
            live_b["s0"]._default().obs.counter_value("pool_dup_submits")
        )
        replays = int(wi.obs.counter_value("ingest_journal_replays"))
        src_pools = dict(live_a["s0"]._default()._ingest_pools)
        st = await c0b.call("status")
        rep = obsreport.run_report([wi.obs, placer.obs])
        pstat = placer.status()
        fstat = await fd.status()
        await _teardown((c0a, c1a, c0b, c1b), live_a, live_b)
        return (res0, res1, stats, dup_hits, replays, src_pools, st, rep,
                pstat, fstat)

    (res0, res1, stats, dup_hits, replays, src_pools, st, rep, pstat,
     fstat) = asyncio.run(run())
    # the export carried both windows; every journaled sub_id replayed
    # onto the destination and deduped against the imported verdicts
    assert stats["windows"] == [0, 1]
    assert stats["replayed"] == 9
    assert replays >= 9  # counted per destination server
    assert dup_hits >= 9  # exactly-once: replays hit recorded verdicts
    # the source's retained pools (sealed window 0 included) are gone
    assert src_pools == {}
    # fleet observability: status verb, placer, directory, run report
    assert st["fleet"]["session_imports"] == 1
    assert st["fleet"]["boot_id"]
    assert set(st["fleet"]["load"]) == {
        "stall_fill_ratio", "max_progress_age_s",
    }
    assert pstat["session_migrations"] == 1
    assert pstat["migration_inflight_since"] == 0.0
    assert fstat["placements"]["default"] == "B"
    assert rep["fleet"]["session_migrations"] == 1
    assert rep["fleet"]["session_imports"] == 0  # server regs not passed
    # bit-identity vs the never-migrated windowed run
    want0, want1 = _windowed_control(
        _cfg(port_a + 80, secure_exchange=True), port_a + 80, plan, (0, 1)
    )
    np.testing.assert_array_equal(res0.counts, want0.counts)
    np.testing.assert_array_equal(res0.paths, want0.paths)
    np.testing.assert_array_equal(res1.counts, want1.counts)
    np.testing.assert_array_equal(res1.paths, want1.paths)
    assert _hitters(res0) == _hitters(want0)
    assert _hitters(res1) == _hitters(want1)


def _sketch_material(rng):
    """12 clients (8 clustered at 11), client 3's dim-0 sketch payload
    forged at level 2 — the additive-attack shape test_sketch pins."""
    pts = np.array([[11]] * 8 + [[25], [2], [50], [60]])
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")
    seeds = rng.integers(0, 2**32, size=(N, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketch.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)
    bad = np.asarray(sk0.key.cw_val).copy()
    bad[3, 0, 2, 0] = (int(bad[3, 0, 2, 0]) + 1) % FE62.P
    j = jnp.asarray(bad)
    sk0 = sk0._replace(key=sk0.key._replace(cw_val=j))
    sk1 = sk1._replace(key=sk1.key._replace(cw_val=j))
    return k0, k1, sk0, sk1


@pytest.mark.slow  # ~16 s: malicious sketch e2e on three host pairs
def test_migration_malicious_replays_identical_challenge(rng, tmp_path):
    """THE migration e2e (malicious/sketch leg): a malicious-mode window
    sealed on the source pair — its challenge root committed — migrates
    and CRAWLS on the destination pair.  The replayed window re-opens
    the IDENTICAL pre-migration challenge (the committed root, not a
    fresh derivation), the cheater stays excluded, and the results are
    bit-identical to the never-migrated run."""
    port_a, port_b = BASE_PORT + 120, BASE_PORT + 160
    k0, k1, sk0, sk1 = _sketch_material(rng)
    ck = tmp_path / "ck"
    ck.mkdir()
    cfg_a = _cfg(port_a, malicious=True, threshold=0.5, addkey_batch_size=12)
    cfg_b = _cfg(port_b, malicious=True, threshold=0.5, addkey_batch_size=12)

    async def run():
        lead_a, c0a, c1a, live_a = await _bring_up(
            cfg_a, port_a, ckpt_dir=str(ck)
        )
        wi = WindowedIngest(lead_a)
        for i in range(N):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
                sk0_chunk=_sk_chunk(sk0, slice(i, i + 1)),
                sk1_chunk=_sk_chunk(sk1, slice(i, i + 1)),
            )
        stats = await wi.seal_window()  # commits the challenge root
        # window 1 traffic in flight at migration time (exactly-once
        # covered by the journal replay; window 1 itself never crawls)
        for i in range(3):
            await wi.submit(
                f"w1c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
                sk0_chunk=_sk_chunk(sk0, slice(i, i + 1)),
                sk1_chunk=_sk_chunk(sk1, slice(i, i + 1)),
            )
        lead_b, c0b, c1b, live_b = await _bring_up_dest(
            cfg_b, port_b, str(ck)
        )
        fd = FleetDirectory()
        await fd.register(HostPair(name="A"))
        await fd.register(HostPair(name="B"))
        await fd.move("default", "A")
        placer = FleetPlacer(fd)
        await placer.migrate(wi, lead_b, session="default", dest="B")
        res = await wi.crawl_window(0)
        alive = live_b["s0"].alive_keys.copy()
        roots = (
            live_b["s0"]._default()._sketch_root.copy(),
            live_b["s1"]._default()._sketch_root.copy(),
        )
        await _teardown((c0a, c1a, c0b, c1b), live_a, live_b)
        return res, alive, roots, stats

    res, alive, roots, stats = asyncio.run(run())
    # the destination's crawl committed the PRE-MIGRATION challenge root
    # on both servers: re-opening the window's Beaver slabs was a
    # replay of the identical challenge, never a second opening
    root_committed = np.array(stats["sk_root"], np.uint32)
    np.testing.assert_array_equal(roots[0], root_committed)
    np.testing.assert_array_equal(roots[1], root_committed)
    want_alive = np.ones(N, bool)
    want_alive[3] = False  # the cheater stays excluded across the move
    np.testing.assert_array_equal(alive, want_alive)
    # bit-identity vs the never-migrated run of the same window
    plan = [((f"c{i}", _chunk(k0, slice(i, i + 1)),
              _chunk(k1, slice(i, i + 1))),
             dict(sk0_chunk=_sk_chunk(sk0, slice(i, i + 1)),
                  sk1_chunk=_sk_chunk(sk1, slice(i, i + 1))))
            for i in range(N)]
    plan.append("seal")
    (want,) = _windowed_control(
        _cfg(port_a + 80, malicious=True, threshold=0.5,
             addkey_batch_size=12),
        port_a + 80, plan, (0,),
    )
    np.testing.assert_array_equal(res.counts, want.counts)
    np.testing.assert_array_equal(res.paths, want.paths)
    assert _hitters(res) == _hitters(want)


# ---------------------------------------------------------------------------
# THE failover e2e: host:kill chaos, orphan recovery on the survivor
# ---------------------------------------------------------------------------


def test_host_kill_failover_resumes_on_survivor_bit_identical(rng, tmp_path):
    """THE failover e2e: a ``host:kill`` chaos clause kills the whole
    source pair mid-crawl; the supervisor probe marks its boot ids dead,
    and the orphaned session resumes on the surviving pair from its
    newest banked checkpoint + journal replay — results bit-identical to
    the fault-free run, with the ``fleet`` sections (failovers,
    placement decisions) asserted in the placer, the ``status`` verb,
    and the run report."""
    port_a, port_b = BASE_PORT + 240, BASE_PORT + 280
    k0, k1 = _client_keys(rng)
    ck = tmp_path / "ck"
    ck.mkdir()
    cfg_a, cfg_b = _cfg(port_a), _cfg(port_b)

    plan = []
    for i in range(6):
        plan.append(((f"c{i}", _chunk(k0, slice(i, i + 1)),
                      _chunk(k1, slice(i, i + 1))), {}))
    plan.append("seal")
    for i in range(6, 12):
        plan.append(((f"c{i}", _chunk(k0, slice(i, i + 1)),
                      _chunk(k1, slice(i, i + 1))), {}))
    plan.append("seal")

    async def run():
        lead_a, c0a, c1a, live_a = await _bring_up(
            cfg_a, port_a, ckpt_dir=str(ck)
        )
        lead_b, c0b, c1b, live_b = await _bring_up_dest(
            cfg_b, port_b, str(ck)
        )
        fd = FleetDirectory()
        await fd.register(HostPair(
            name="A", boot0=live_a["s0"]._boot_id,
            boot1=live_a["s1"]._boot_id,
        ))
        await fd.register(HostPair(
            name="B", boot0=live_b["s0"]._boot_id,
            boot1=live_b["s1"]._boot_id,
        ))
        placer = FleetPlacer(fd)
        dest0 = await placer.place("default")
        assert dest0.name == "A"  # tie-break places on A first
        wi = WindowedIngest(lead_a)  # checkpointing ON
        for i in range(6):
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        await wi.seal_window()  # banks the newest ingest checkpoint
        for i in range(6, 9):  # post-checkpoint traffic: journal-only
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        # the chaos schedule says window 0's crawl dies with its host
        hc = HostChaos(parse_host_faults("host:kill@window=0"))
        if hc.before_window(0):
            for s in live_a.values():
                await s.aclose()

        async def probe_fn(name):
            if name == "A":
                raise ConnectionError("host pair unreachable")
            return {"boot0": live_b["s0"]._boot_id,
                    "boot1": live_b["s1"]._boot_id}

        died = await fd.probe(probe_fn)
        assert died == ["A"]

        async def make_ingest(session, dest):
            assert session == "default" and dest.name == "B"
            return wi, lead_b

        moved = await placer.recover_dead_pair("A", make_ingest)
        for i in range(9, 12):  # the stream resumes on the survivor
            await wi.submit(
                f"c{i}", _chunk(k0, slice(i, i + 1)),
                _chunk(k1, slice(i, i + 1)),
            )
        res0 = await wi.crawl_window(0)
        await wi.seal_window()
        res1 = await wi.crawl_window(1)
        dup_hits = int(
            live_b["s0"]._default().obs.counter_value("pool_dup_submits")
        )
        st = await c0b.call("status")
        rep = obsreport.run_report(
            [wi.obs, placer.obs, live_b["s0"]._default().obs]
        )
        pstat = placer.status()
        fstat = await fd.status()
        await _teardown((c0a, c1a, c0b, c1b), live_b)
        return (res0, res1, moved, hc.fired, dup_hits, st, rep, pstat,
                fstat)

    (res0, res1, moved, fired, dup_hits, st, rep, pstat, fstat) = (
        asyncio.run(run())
    )
    assert fired == [("kill", 0)]  # the chaos clause drove the kill
    assert moved["default"]["imported"] is True
    assert moved["default"]["replayed"] == 9
    # exactly-once: checkpointed submissions replay as dups, the
    # journal tail (post-checkpoint) lands fresh
    assert dup_hits >= 6
    # fleet sections: placer, directory, status verb, run report
    assert pstat["session_failovers"] == 1
    assert pstat["placement_decisions"] >= 2  # initial place + re-place
    assert fstat["placements"]["default"] == "B"
    assert fstat["pairs"]["A"]["alive"] is False
    assert st["fleet"]["session_imports"] >= 1
    assert rep["fleet"]["session_failovers"] >= 1
    assert rep["fleet"]["placement_decisions"] >= 2
    assert rep["fleet"]["session_imports"] >= 1
    # bit-identity vs the fault-free run
    want0, want1 = _windowed_control(_cfg(port_a + 120), port_a + 120,
                                     plan, (0, 1))
    np.testing.assert_array_equal(res0.counts, want0.counts)
    np.testing.assert_array_equal(res0.paths, want0.paths)
    np.testing.assert_array_equal(res1.counts, want1.counts)
    np.testing.assert_array_equal(res1.paths, want1.paths)
    assert _hitters(res0) == _hitters(want0)
    assert _hitters(res1) == _hitters(want1)


# ---------------------------------------------------------------------------
# the chaos.sh host:kill leg: flood tenant A, kill the pair mid-crawl
# of tenant B, fail B over to the survivor (scripts/chaos.sh re-runs
# this leg under FHH_DEBUG_GUARDS=1 and FHH_DEBUG_TAINT=1)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~7 s: flood + host-kill chaos leg (chaos.sh re-runs it)
def test_host_kill_mid_crawl_under_flood_tenant_b_bit_identical(tmp_path):
    """Tenant A floods its per-session gate while tenant B's window-0
    crawl is UNDERWAY; a ``host:kill`` clause kills the whole pair
    mid-crawl.  Tenant B fails over to the surviving pair and re-runs
    the window from its banked ingest checkpoint + journal — results
    bit-identical to the fault-free run, ``session_failovers >= 1`` in
    the run report's ``fleet`` section."""
    port_a, port_b = BASE_PORT + 480, BASE_PORT + 520
    kA = _client_keys(np.random.default_rng(31), 64)
    kB = _client_keys(np.random.default_rng(32))
    ck = tmp_path / "ck"
    ck.mkdir()
    flood_kw = dict(ingest_rate_keys_per_s=200.0, ingest_burst_keys=16)
    cfg_a, cfg_b = _cfg(port_a, **flood_kw), _cfg(port_b, **flood_kw)

    plan = []
    for i in range(6):
        plan.append(((f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                      _chunk(kB[1], slice(i, i + 1))), {}))
    plan.append("seal")
    for i in range(6, 12):
        plan.append(((f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                      _chunk(kB[1], slice(i, i + 1))), {}))
    plan.append("seal")

    async def run():
        live_a = {}
        live_a["s0"], live_a["s1"] = await _start_pair(
            cfg_a, port_a, ckpt_dir=str(ck)
        )
        live_b = {}
        live_b["s0"], live_b["s1"] = await _start_pair(
            cfg_b, port_b, ckpt_dir=str(ck)
        )
        drv = MultiCollectionDriver(
            cfg_a, "127.0.0.1", port_a, "127.0.0.1", port_a + 10
        )
        leadA = await drv.open("ta")
        leadB = await drv.open("tb")
        wiA = WindowedIngest(
            leadA, checkpoint=False,
            policy=respolicy.RetryPolicy(
                base_s=0.001, cap_s=0.002, factor=1.0, attempts=2
            ),
        )
        wiB = WindowedIngest(leadB)  # checkpointing ON
        for i in range(6):
            await wiB.submit(
                f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                _chunk(kB[1], slice(i, i + 1)),
            )
        await wiB.seal_window()  # banks tb's ingest checkpoint
        for i in range(6, 9):  # window 1 in flight: journal-only
            await wiB.submit(
                f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                _chunk(kB[1], slice(i, i + 1)),
            )

        fd = FleetDirectory()
        await fd.register(HostPair(
            name="A", boot0=live_a["s0"]._boot_id,
            boot1=live_a["s1"]._boot_id,
        ))
        await fd.register(HostPair(
            name="B", boot0=live_b["s0"]._boot_id,
            boot1=live_b["s1"]._boot_id,
        ))
        await fd.move("tb", "A")
        placer = FleetPlacer(fd)
        hc = HostChaos(parse_host_faults("host:kill@window=0"))

        async def flood():
            for i in range(0, 64, 8):
                try:
                    await wiA.submit(
                        "flooder", _chunk(kA[0], slice(i, i + 8)),
                        _chunk(kA[1], slice(i, i + 8)),
                    )
                except (IngestOverloadedError,
                        *respolicy.TRANSIENT_ERRORS, RuntimeError):
                    pass  # Overloaded, or the pair died under us
                await asyncio.sleep(0.005)

        crawl = asyncio.create_task(wiB.crawl_window(0, max_recoveries=0))
        fl = asyncio.create_task(flood())
        # kill once tb's crawl is actually billing device time on s1
        while True:
            cs = live_a["s1"]._table.peek("tb")
            if cs is not None and cs.obs.timer_seconds("fss") > 0:
                break
            await asyncio.sleep(0.01)
        assert hc.before_window(0)
        for s in live_a.values():
            await s.aclose()
        with pytest.raises((ConnectionError, TimeoutError, RuntimeError)):
            await crawl  # the in-flight crawl died with its host
        fl.cancel()  # the flooder's host is gone; stop its redial loop
        with contextlib.suppress(asyncio.CancelledError):
            await fl

        async def probe_fn(name):
            if name == "A":
                raise ConnectionError("host pair unreachable")
            return {"boot0": live_b["s0"]._boot_id,
                    "boot1": live_b["s1"]._boot_id}

        assert await fd.probe(probe_fn) == ["A"]
        extra = []

        async def make_ingest(session, dest):
            assert session == "tb" and dest.name == "B"
            c0 = await rpc.CollectorClient.connect(
                "127.0.0.1", port_b, collection=session
            )
            c1 = await rpc.CollectorClient.connect(
                "127.0.0.1", port_b + 10, collection=session
            )
            extra.extend((c0, c1))
            return wiB, RpcLeader(cfg_b, c0, c1)

        moved = await placer.recover_dead_pair("A", make_ingest)
        assert moved["tb"]["imported"] is True
        for i in range(9, 12):  # tb's stream resumes on the survivor
            await wiB.submit(
                f"b{i}", _chunk(kB[0], slice(i, i + 1)),
                _chunk(kB[1], slice(i, i + 1)),
            )
        res0 = await wiB.crawl_window(0)
        await wiB.seal_window()
        res1 = await wiB.crawl_window(1)
        rep = obsreport.run_report(
            [wiB.obs, placer.obs, live_b["s0"]._table.peek("tb").obs]
        )
        fired = list(hc.fired)
        await drv.close()
        for c in extra:
            await c.aclose()
        for s in live_b.values():
            await s.aclose()
        return res0, res1, rep, fired

    res0, res1, rep, fired = asyncio.run(run())
    assert fired == [("kill", 0)]
    assert rep["fleet"]["session_failovers"] >= 1
    assert rep["fleet"]["session_imports"] >= 1
    want0, want1 = _windowed_control(
        _cfg(port_a + 80, **flood_kw), port_a + 80, plan, (0, 1)
    )
    np.testing.assert_array_equal(res0.counts, want0.counts)
    np.testing.assert_array_equal(res0.paths, want0.paths)
    np.testing.assert_array_equal(res1.counts, want1.counts)
    np.testing.assert_array_equal(res1.paths, want1.paths)
    assert _hitters(res0) == _hitters(want0)
    assert _hitters(res1) == _hitters(want1)
