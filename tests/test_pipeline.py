"""Pipelined secure crawl: parity, depth sweep, quiesce chaos, warmup,
report schema, and the bench budget helpers.

The pipeline (protocol/leader_rpc.py `_crawl_level_pipelined` + the
server-side expand/open stage split in protocol/rpc.py) is a pure
scheduling change: up to ``crawl_pipeline_depth`` span verbs in flight
with in-order reassembly, span k+1's FSS expansion dispatched at frame
arrival while span k's GC/OT exchange rides the data plane.  Every test
here pins the contract that matters: results are BIT-IDENTICAL to the
sequential PR-4 path in all three modes, depth 1 IS the sequential path,
and a mid-flight fault quiesces into the sequential retry with the
recovery counters visible in the run report.
"""

import asyncio

import numpy as np
import pytest

from fuzzyheavyhitters_tpu.obs import metrics as obsmetrics
from fuzzyheavyhitters_tpu.obs import report as obsreport
from fuzzyheavyhitters_tpu.ops import ibdcf
from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
from fuzzyheavyhitters_tpu.protocol import rpc
from fuzzyheavyhitters_tpu.protocol import sketch as sketchmod
from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
from fuzzyheavyhitters_tpu.resilience import policy as respolicy
from fuzzyheavyhitters_tpu.resilience.chaos import ChaosProxy, parse_faults
from fuzzyheavyhitters_tpu.utils import bits as bitutils
from fuzzyheavyhitters_tpu.utils.config import Config

BASE_PORT = 20431


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    # protocol-shape tests: every program is tiny, the tunnel compile
    # cost would dominate — pin to XLA:CPU like the other suites
    yield


def _cfg(port_base, **kw):
    defaults = dict(
        data_len=5,
        n_dims=1,
        ball_size=1,
        addkey_batch_size=8,
        num_sites=4,
        threshold=0.2,
        zipf_exponent=1.03,
        server0=f"127.0.0.1:{port_base}",
        server1=f"127.0.0.1:{port_base + 10}",
        distribution="zipf",
        f_max=32,
    )
    defaults.update(kw)
    return Config(**defaults)


def _client_keys(rng, L, n):
    pts = np.concatenate(
        [np.full(n - 4, 11), rng.integers(0, 1 << L, size=4)]
    )[:, None]
    pts_bits = np.array(
        [[bitutils.int_to_bits(L, int(v)) for v in row] for row in pts]
    )
    return pts_bits, ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")


async def _start_servers(cfg, port_base):
    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port_base + 10, "127.0.0.1", port_base + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(
        s0.start("127.0.0.1", port_base, "127.0.0.1", port_base + 11)
    )
    await asyncio.gather(t0, t1)
    return s0, s1


async def _run_crawl(cfg, port, k0, k1, sk0=None, sk1=None, nreqs=12,
                     dial0=None, budgets=None, warmup=False):
    """One unsupervised crawl; returns (result, leader, servers)."""
    s0, s1 = await _start_servers(cfg, port)
    host0, p0 = ("127.0.0.1", port) if dial0 is None else dial0
    c0 = await rpc.CollectorClient.connect(host0, p0, budgets=budgets)
    c1 = await rpc.CollectorClient.connect(
        "127.0.0.1", port + 10, budgets=budgets
    )
    lead = RpcLeader(cfg, c0, c1)
    await lead._both("reset")
    await lead.upload_keys(k0, k1, sk0, sk1)
    if warmup:
        await lead.warmup()
    res = await lead.run(nreqs)
    for c in (c0, c1):
        await c.aclose()
    return res, lead, (s0, s1)


async def _teardown(servers):
    for s in servers:
        await s.aclose()


def _crawl(cfg, port, k0, k1, **kw):
    async def go():
        res, lead, servers = await _run_crawl(cfg, port, k0, k1, **kw)
        await _teardown(servers)
        return res, lead

    return asyncio.run(go())


@pytest.mark.parametrize(
    "mode", ["trusted", "secure", "sketch"],
)
def test_pipelined_matches_sequential_bit_identical(rng, mode):
    """THE parity contract: a pipelined sharded crawl returns bit-identical
    paths and counts to the sequential whole-level crawl — in trusted,
    secure, and malicious (sketch) modes."""
    L, n = 5, 12
    base = BASE_PORT + {"trusted": 0, "secure": 60, "sketch": 120}[mode]
    pts_bits, (k0, k1) = _client_keys(rng, L, n)
    sk0 = sk1 = None
    kw = {}
    if mode == "secure":
        # secure_whole_level=False: this test exercises the SHARDED
        # secure pipeline (the whole-level default collapses a secure
        # level to one span — covered by test_secure_kernels.py)
        kw.update(secure_exchange=True, secure_whole_level=False)
    if mode == "sketch":
        kw.update(malicious=True, threshold=0.5, addkey_batch_size=12)
        seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
        cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
        sk0, sk1 = sketchmod.gen(seeds, pts_bits[:, 0, :], FE62, F255, cseed)

    res_seq, _ = _crawl(
        _cfg(base, crawl_shard_nodes=0, **kw), base, k0, k1,
        sk0=sk0, sk1=sk1,
    )
    res_pipe, lead = _crawl(
        _cfg(base + 20, crawl_shard_nodes=1, crawl_pipeline_depth=3, **kw),
        base + 20, k0, k1, sk0=sk0, sk1=sk1,
    )
    assert res_seq.counts.size  # the crawl found hitters: a real compare
    np.testing.assert_array_equal(res_pipe.counts, res_seq.counts)
    np.testing.assert_array_equal(res_pipe.paths, res_seq.paths)
    # the pipeline actually engaged (levels with >= 2 spans exist at L=5)
    assert lead.obs.counter_value("pipeline_faults") == 0
    assert lead.obs.timer_seconds("pipeline_overlap") >= 0.0
    rep = obsreport.run_report([lead.obs])
    # last-write-wins gauge, clamped to the final level's span count
    assert 2 <= rep["pipeline"]["depth"] <= 3
    assert rep["pipeline"]["faults"] == 0


def test_depth_one_is_the_sequential_path(rng):
    """crawl_pipeline_depth=1 must BE the PR-4 sequential path: identical
    results AND none of the pipeline telemetry (no pipeline section in
    the run report), so depth 1 deployments are provably unchanged."""
    L, n = 5, 12
    base = BASE_PORT + 180
    _, (k0, k1) = _client_keys(rng, L, n)
    res_whole, _ = _crawl(_cfg(base), base, k0, k1)
    res_d1, lead = _crawl(
        _cfg(base + 20, crawl_shard_nodes=1, crawl_pipeline_depth=1),
        base + 20, k0, k1,
    )
    np.testing.assert_array_equal(res_d1.counts, res_whole.counts)
    np.testing.assert_array_equal(res_d1.paths, res_whole.paths)
    assert lead.obs.timer_seconds("pipeline_overlap") == 0.0
    assert "pipeline" not in obsreport.run_report([lead.obs])


@pytest.mark.parametrize("depth", [2, 4, 8])
def test_pipeline_depth_sweep(rng, depth):
    """Every depth reassembles the same bits (the window size must only
    change scheduling, never data)."""
    L, n = 5, 12
    base = BASE_PORT + 240 + 40 * depth
    _, (k0, k1) = _client_keys(rng, L, n)
    res_seq, _ = _crawl(_cfg(base), base, k0, k1)
    res, _ = _crawl(
        _cfg(base + 20, crawl_shard_nodes=1, crawl_pipeline_depth=depth),
        base + 20, k0, k1,
    )
    np.testing.assert_array_equal(res.counts, res_seq.counts)
    np.testing.assert_array_equal(res.paths, res_seq.paths)


def test_pipeline_fault_quiesces_to_sequential(rng):
    """THE chaos contract: a span request black-holed mid-flight inside a
    pipelined level times out, the pipeline quiesces (plane_break on both
    servers -> plane_reset), the level re-runs sequentially, and the
    results are bit-identical to the fault-free crawl — with the fault
    and re-runs visible in the counters (pipeline_faults >= 1,
    shards_rerun >= 1)."""
    L, n = 5, 12
    port = BASE_PORT + 620
    pxport = port + 25
    _, (k0, k1) = _client_keys(rng, L, n)
    cfg = _cfg(port, crawl_shard_nodes=1, crawl_pipeline_depth=3)
    budgets = respolicy.VerbBudgets(default_s=8.0, per_verb={})

    res_ff, _ = _crawl(
        _cfg(port + 40, crawl_shard_nodes=1, crawl_pipeline_depth=3),
        port + 40, k0, k1,
    )

    async def faulty():
        # c2s frames on ctl0: 1 hello, 2 reset, 3-4 add_keys, 5 tree_init,
        # 6 L0 crawl (1 span), 7 L0 prune, then level 1's spans (8, 9):
        # black-hole the SECOND span of the first pipelined level
        px = await ChaosProxy(
            "127.0.0.1", pxport, "127.0.0.1", port,
            parse_faults("ctl0:blackhole@msg=9,count=1"), link="ctl0",
        ).start()
        res, lead, servers = await _run_crawl(
            cfg, port, k0, k1, dial0=("127.0.0.1", pxport), budgets=budgets
        )
        counters = {
            "faults": lead.obs.counter_value("pipeline_faults"),
            "shards_rerun": lead.obs.counter_value("shards_rerun"),
            "breaks": sum(
                s.obs.counter_value("plane_breaks") for s in servers
            ),
        }
        rep = obsreport.run_report(
            [lead.obs, servers[0].obs, servers[1].obs]
        )
        await px.stop()
        await _teardown(servers)
        return res, counters, rep

    res, counters, rep = asyncio.run(faulty())
    np.testing.assert_array_equal(res.counts, res_ff.counts)
    np.testing.assert_array_equal(res.paths, res_ff.paths)
    assert counters["faults"] >= 1
    assert counters["shards_rerun"] >= 1
    assert counters["breaks"] >= 2  # both servers' planes were broken
    assert rep["pipeline"]["faults"] >= 1
    assert rep["recovery"]["shards_rerun"] >= 1


def test_warmup_verb_compiles_without_touching_state(rng):
    """The per-f_bucket warmup runs the whole kernel chain on throwaway
    sessions: results after warmup are identical to a cold crawl, and
    warmup before add_keys is a loud server error."""
    L, n = 5, 12
    base = BASE_PORT + 700
    _, (k0, k1) = _client_keys(rng, L, n)
    res_cold, _ = _crawl(
        _cfg(base, secure_exchange=True), base, k0, k1
    )
    res_warm, lead = _crawl(
        _cfg(base + 20, secure_exchange=True), base + 20, k0, k1,
        warmup=True,
    )
    np.testing.assert_array_equal(res_warm.counts, res_cold.counts)
    np.testing.assert_array_equal(res_warm.paths, res_cold.paths)
    assert lead.obs.timer_seconds("warmup") > 0.0

    async def no_keys():
        cfg = _cfg(base + 40)
        s0, s1 = await _start_servers(cfg, base + 40)
        c0 = await rpc.CollectorClient.connect("127.0.0.1", base + 40)
        await c0.call("reset")
        with pytest.raises(RuntimeError, match="warmup before add_keys"):
            await c0.call("warmup", {"f_buckets": [1, 2]})
        await c0.aclose()
        await _teardown((s0, s1))

    asyncio.run(no_keys())


def test_pipeline_report_section_schema():
    """run_report rolls the pipeline metrics into a top-level section
    with per-level {depth, overlap_seconds, stalls} — and omits the
    section entirely when no pipelined crawl ran."""
    reg = obsmetrics.Registry("leader-test")
    reg.gauge("pipeline_depth", 4, level=3)
    reg.timer_add("pipeline_overlap", 1.5, level=3)
    reg.count("pipeline_stalls", 2, level=3)
    reg.count("pipeline_faults", 1, level=3)
    rep = obsreport.run_report([reg])
    pipe = rep["pipeline"]
    assert pipe["depth"] == 4
    assert pipe["overlap_seconds"] == pytest.approx(1.5)
    assert pipe["stalls"] == 2 and pipe["faults"] == 1
    assert pipe["by_level"]["3"] == {
        "depth": 4, "overlap_seconds": 1.5, "stalls": 2,
    }
    clean = obsmetrics.Registry("leader-clean")
    clean.count("recoveries", 0)
    assert "pipeline" not in obsreport.run_report([clean])


def test_compile_cache_enable(tmp_path, monkeypatch):
    """FHH_COMPILE_CACHE wires jax's persistent compilation cache; unset
    means disabled; the first successful enable wins (idempotent)."""
    import jax

    from fuzzyheavyhitters_tpu.utils import compile_cache

    # snapshot every jax.config knob enable() mutates and restore them
    # after: this test used to leave the PROCESS-WIDE compilation cache
    # pointed at its deleted tmp_path, so every module that ran after
    # test_pipeline recompiled cold — the compile-bound back half of the
    # suite (secure_kernels, sketch) inflated 3-5x and blew the tier-1
    # wall-clock budget
    restore = {
        knob: getattr(jax.config, knob)
        for knob in (
            "jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
        )
        if hasattr(jax.config, knob)
    }
    try:
        monkeypatch.setattr(compile_cache, "_enabled", None)
        monkeypatch.delenv("FHH_COMPILE_CACHE", raising=False)
        assert compile_cache.enable() is None

        cache = tmp_path / "xla-cache"
        monkeypatch.setenv("FHH_COMPILE_CACHE", str(cache))
        assert compile_cache.enable() == str(cache)
        assert cache.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(cache)
        # idempotent: a second call (different arg) returns the winner
        assert compile_cache.enable(str(tmp_path / "other")) == str(cache)
    finally:
        for knob, val in restore.items():
            jax.config.update(knob, val)


def test_bench_budget_and_compact_line(monkeypatch):
    """bench.py's budget + compact-final-line helpers: the compact extra
    keeps each section's acceptance scalars (and error/skip markers) and
    drops the bulk, and the budget clock counts down from module start."""
    import bench

    extra = {
        "keygen_sweep": {"512": {"keys_per_sec": 1.0}},
        "reference_key_bytes": {"512": 10265},
        "secure_crawl": {
            "secure_clients_per_sec": 112.5,
            "ms_per_level_e2e": 750.0,
            "secure_kernel": {
                "ot_path": "ot2s",
                "phase_otext_seconds": 0.4,
                "phase_garble_seconds": 0.0,
                "phase_eval_seconds": 0.0,
                "phase_b2a_seconds": 0.9,
            },
            "whole_level_speedup_vs_pipelined": 3.2,
            "sequential_clients_per_sec": 56.0,
            "pipeline_speedup": 2.01,
            "pipeline": {"depth": 4, "overlap_seconds": 9.1, "stalls": 0},
            "hitters": 40,
            "data_plane_mbytes_sent": 12.0,
        },
        "crawl_hbm_max": {"skipped": "budget"},
        "covid": {"error": "timeout after 540s", "partial_thing": 1},
        "upload": {"upload_keys_per_sec": 3e5, "n_keys": 10**6},
        "ingest": {
            "ingest_keys_per_sec": 150000.0,
            "concurrent_keys_per_sec": 90000.0,
            "windows": 2,
            "shed": 0,
            "rejected": 3,
            "bit_identical_vs_batch": True,
            "report_ingest": {"admitted": 65536, "keys_per_sec": 150000.0},
            "window_crawl_seconds": 4.2,
            "n_keys": 65536,
        },
        "sketch": {
            "malicious_overhead_vs_semi_honest": 1.31,
            "sketch_clients_per_sec": 85.9,
            "semi_honest_clients_per_sec": 112.5,
            "bit_identical": True,
            "sketch_shards": 8,
            "verify_seconds": 0.412,
            "clients_per_sec_by_shards": {"1": 60.1, "8": 85.9},
            "skipped_shards": {},
            "n_clients": 1024,
        },
    }
    compact = bench._compact_extra(extra)
    assert "keygen_sweep" not in compact
    assert compact["secure_crawl"]["secure_clients_per_sec"] == 112.5
    assert compact["secure_crawl"]["secure_kernel"]["ot_path"] == "ot2s"
    assert compact["secure_crawl"]["whole_level_speedup_vs_pipelined"] == 3.2
    # the bulky blocks stay out of the compact line
    assert "pipeline" not in compact["secure_crawl"]
    assert "hitters" not in compact["secure_crawl"]
    assert compact["crawl_hbm_max"] == {"skipped": "budget"}
    assert compact["covid"] == {"error": "timeout after 540s"}
    assert compact["upload"] == {"upload_keys_per_sec": 3e5}
    # the streaming front-door section rides the line, scalars only
    assert compact["ingest"]["ingest_keys_per_sec"] == 150000.0
    assert compact["ingest"]["bit_identical_vs_batch"] is True
    assert "report_ingest" not in compact["ingest"]
    # the malicious-sketch section: overhead headline + rate + the
    # bit-identity gate ride the line; the per-shard sweep stays out
    assert compact["sketch"]["malicious_overhead_vs_semi_honest"] == 1.31
    assert compact["sketch"]["sketch_clients_per_sec"] == 85.9
    assert compact["sketch"]["bit_identical"] is True
    assert compact["sketch"]["sketch_shards"] == 8
    assert "clients_per_sec_by_shards" not in compact["sketch"]
    # the compact line stays far under the harness's stdout tail capture
    import json

    assert len(json.dumps(compact)) < 1800

    monkeypatch.setattr(bench, "BENCH_BUDGET_S", 100.0)
    monkeypatch.setattr(bench, "_BENCH_T0", bench.time.monotonic() - 30.0)
    assert 69.0 < bench._budget_left() < 71.0
