"""ibDCF tests.

Three layers, mirroring the reference's FSS unit suite (SURVEY.md §4,
ref: tests/ibdcf_tests.rs) but with real assertions:

1. bit-exact parity of the batched JAX keygen/eval against the pure-Python
   spec oracle with the SAME ChaCha PRG injected;
2. semantic full-domain sweeps (share XOR == strict comparisons; interval
   membership; multi-dim ball membership) on the JAX path alone;
3. both PRG bit modes (reference-observed constants and derived bits).
"""

import jax
import jax.numpy as jnp
import numpy as np
import oracle
import pytest

from fuzzyheavyhitters_tpu.ops import ibdcf, prg
from fuzzyheavyhitters_tpu.ops.ibdcf import IbDcfKeyBatch
from fuzzyheavyhitters_tpu.utils import bits as bitutils


@pytest.fixture(autouse=True)
def _module_cpu(cpu_default):
    """Unit-scale module: run on the CPU backend (see conftest)."""
    yield



def key_from_oracle(k: oracle.IbDcfKey) -> ibdcf.IbDcfKeyBatch:
    return ibdcf.IbDcfKeyBatch(
        key_idx=np.asarray(k.key_idx),
        root_seed=prg.seeds_from_bytes(k.root_seed)[0],
        cw_seed=np.stack([prg.seeds_from_bytes(c.seed)[0] for c in k.cor_words]),
        cw_bits=np.array([c.bits for c in k.cor_words]),
        cw_y_bits=np.array([c.y_bits for c in k.cor_words]),
    )


def int_bits(L, x):
    return bitutils.int_to_bits(L, x)


def test_keygen_matches_oracle_bit_exact(rng):
    L = 12
    for side in (True, False):
        alpha = rng.integers(0, 2, size=L).astype(bool)
        seeds = rng.integers(0, 2**32, size=(2, 4), dtype=np.uint32)
        # oracle with identical roots + chacha prg
        o_rng = _FixedSeeds([prg.seed_to_bytes(seeds[0]), prg.seed_to_bytes(seeds[1])])
        ok0, ok1 = oracle.gen_ibdcf(alpha, side, o_rng, prg=prg.np_expand_bytes)
        jk0, jk1 = ibdcf.gen_pair(seeds, alpha, side)
        for ok, jk in ((ok0, jk0), (ok1, jk1)):
            ek = key_from_oracle(ok)
            np.testing.assert_array_equal(np.asarray(jk.root_seed), ek.root_seed)
            np.testing.assert_array_equal(np.asarray(jk.cw_seed), ek.cw_seed)
            np.testing.assert_array_equal(np.asarray(jk.cw_bits), ek.cw_bits)
            np.testing.assert_array_equal(np.asarray(jk.cw_y_bits), ek.cw_y_bits)


def test_gen_pair_np_matches_gen_pair(rng):
    """The host-side keygen mirror must stay bit-identical to the device
    scan — mesh tests and client simulators depend on interchangeability."""
    n, d, L = 5, 2, 9
    alpha = rng.integers(0, 2, size=(n, d, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(n, d, 2, 4), dtype=np.uint32)
    side = rng.integers(0, 2, size=(n, d)).astype(bool)
    for derived in (False, True):
        jk = ibdcf._gen_pair_jit(seeds, alpha, side, derived)
        nk = ibdcf.gen_pair_np(seeds, alpha, side, derived)
        for p in range(2):
            for name in IbDcfKeyBatch._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(jk[p], name)),
                    np.asarray(getattr(nk[p], name)),
                    err_msg=f"party {p} field {name} derived={derived}",
                )


class _FixedSeeds:
    """np.random.Generator stand-in feeding predetermined 16-byte seeds."""

    def __init__(self, seeds):
        self._seeds = list(seeds)

    def bytes(self, n):
        assert n == 16
        return self._seeds.pop(0)


def test_eval_matches_oracle_bit_exact(rng):
    L = 10
    alpha = rng.integers(0, 2, size=L).astype(bool)
    seeds = rng.integers(0, 2**32, size=(2, 4), dtype=np.uint32)
    o_rng = _FixedSeeds([prg.seed_to_bytes(seeds[0]), prg.seed_to_bytes(seeds[1])])
    ok0, ok1 = oracle.gen_ibdcf(alpha, True, o_rng, prg=prg.np_expand_bytes)
    jk0, jk1 = ibdcf.gen_pair(seeds, alpha, True)
    for x in rng.integers(0, 1 << L, size=32):
        xb = int_bits(L, int(x))
        for ok, jk in ((ok0, jk0), (ok1, jk1)):
            os = oracle.eval_prefix(ok, xb, prg=prg.np_expand_bytes)
            js = ibdcf.eval_full(jk, xb)
            assert prg.seed_to_bytes(js.seed) == os.seed
            assert bool(js.bit) == os.bit
            assert bool(js.y_bit) == os.y_bit


@pytest.mark.parametrize("derived", [False, True])
def test_semantics_full_domain(rng, derived, monkeypatch):
    """XOR of share bits == [x < b] (side=True) / [x > b] (side=False), every
    (bound, input) pair in a 6-bit domain — the JAX twin of the oracle's
    pinned semantics (ref model: tests/ibdcf_tests.rs:4-39)."""
    monkeypatch.setattr(prg, "DERIVED_BITS", derived)
    L = 6
    n = 1 << L
    bounds = np.arange(n)
    # batch all bounds at once: alpha [n, L]
    alpha = np.stack([int_bits(L, int(b)) for b in bounds])
    seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
    xs = np.stack([int_bits(L, x) for x in range(n)])  # [n_x, L]
    for side in (True, False):
        k0, k1 = ibdcf.gen_pair(seeds, alpha, np.full(n, side))
        sweep = jax.vmap(
            lambda xb, k: ibdcf.share_bit(
                ibdcf.eval_full(k, jnp.broadcast_to(xb, (n, L)))
            ),
            in_axes=(0, None),
        )
        got = np.asarray(sweep(xs, k0)) ^ np.asarray(sweep(xs, k1))  # [n_x, n]
        want = (
            np.arange(n)[:, None] < bounds[None, :]
            if side
            else np.arange(n)[:, None] > bounds[None, :]
        )
        np.testing.assert_array_equal(got, want)


def test_interval_membership(rng):
    """Share-bit equality across parties == inclusive interval membership
    (ref model: tests/ibdcf_tests.rs:294-356 incl. single-point and edge
    intervals)."""
    L = 6
    cases = [(3, 17), (0, 63), (5, 5), (0, 0), (63, 63), (10, 40)]
    lo = np.stack([int_bits(L, a) for a, _ in cases])
    hi = np.stack([int_bits(L, b) for _, b in cases])
    (l0, r0), (l1, r1) = ibdcf.gen_interval(lo, hi, rng)
    nc = len(cases)
    xs = np.stack([int_bits(L, x) for x in range(1 << L)])
    sweep = jax.vmap(
        lambda xb, k: ibdcf.share_bit(
            ibdcf.eval_full(k, jnp.broadcast_to(xb, (nc, L)))
        ),
        in_axes=(0, None),
    )
    bits0 = np.stack([np.asarray(sweep(xs, k)) for k in (l0, r0)], axis=-1)
    bits1 = np.stack([np.asarray(sweep(xs, k)) for k in (l1, r1)], axis=-1)
    inside = np.all(bits0 == bits1, axis=-1)  # [n_x, nc]
    want = np.array(
        [[a <= x <= b for a, b in cases] for x in range(1 << L)]
    )
    np.testing.assert_array_equal(inside, want)


def test_ball_bounds_saturation():
    L = 8
    pts = np.stack([int_bits(L, v) for v in (0, 3, 128, 250, 255)])
    lo, hi = ibdcf.ball_bounds(pts, 10)
    lo_i = [bitutils.bits_to_int(r) for r in lo]
    hi_i = [bitutils.bits_to_int(r) for r in hi]
    assert lo_i == [0, 0, 118, 240, 245]
    assert hi_i == [10, 13, 138, 255, 255]


def test_l_inf_ball_membership(rng):
    """2-dim ball: share-string equality over (dim, side) == all dims within
    ball — the fuzzy-membership predicate the servers evaluate
    (ref: ibDCF.rs:175-188, collect.rs:393-410)."""
    L = 5
    pts = np.array([[7, 9], [0, 31], [16, 16]])  # [N, n_dims]
    size = 3
    pts_bits = np.stack(
        [np.stack([int_bits(L, int(v)) for v in row]) for row in pts]
    )  # [N, 2, L]
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, size, rng)
    assert k0.batch_shape == (3, 2, 2)
    n = 1 << L
    grid = np.array([(x, y) for x in range(n) for y in range(n)])  # [n², 2]
    qs = np.stack(
        [np.stack([int_bits(L, int(v)) for v in row]) for row in grid]
    )  # [n², 2, L]
    sweep = jax.vmap(
        lambda q, k: ibdcf.share_bit(
            ibdcf.eval_full(
                k, jnp.broadcast_to(q[None, :, None, :], (3, 2, 2, L))
            )
        ),
        in_axes=(0, None),
    )
    s0 = np.asarray(sweep(qs, k0))  # [n², 3, 2, 2]
    s1 = np.asarray(sweep(qs, k1))
    inside = np.all(s0 == s1, axis=(2, 3))  # [n², 3]
    # saturating bounds: clamp expected window at domain edges
    lo = np.clip(pts - size, 0, n - 1)
    hi = np.clip(pts + size, 0, n - 1)
    want = np.all(
        (grid[:, None, :] >= lo[None]) & (grid[:, None, :] <= hi[None]), axis=2
    )
    np.testing.assert_array_equal(inside, want)


def test_coords_ball_roundtrip(rng):
    """i16 coords variant: negative coordinates, clamping at the i16 edges
    (ref: ibDCF.rs:189-205); queries use the same offset-binary encoding."""
    coords = np.array([[-100, 200], [32760, -32765]])
    k0, k1 = ibdcf.gen_l_inf_ball_from_coords(coords, 16, rng)
    assert k0.batch_shape == (2, 2, 2)
    assert k0.data_len == 16
    enc = lambda v: bitutils.i16_to_ob_bits(int(v))
    q = np.stack([np.stack([enc(v) for v in row]) for row in coords])  # [N,d,16]
    qb = np.repeat(q[:, :, None, :], 2, axis=2)
    s0 = np.asarray(ibdcf.share_bit(ibdcf.eval_full(k0, qb)))
    s1 = np.asarray(ibdcf.share_bit(ibdcf.eval_full(k1, qb)))
    assert np.all(np.all(s0 == s1, axis=(1, 2)))


def test_coords_ball_zero_crossing(rng):
    """A ball whose interval crosses zero must contain its center and respect
    its edges — broken under the reference's raw two's-complement encoding
    (negatives sort above positives lexicographically), fixed here by
    offset-binary."""
    coords = np.array([[5]])
    k0, k1 = ibdcf.gen_l_inf_ball_from_coords(coords, 16, rng)
    member = []
    for q in (-12, -11, 5, 21, 22, 0):
        qb = bitutils.i16_to_ob_bits(q)[None, None, None, :].repeat(2, axis=2)
        s0 = np.asarray(ibdcf.share_bit(ibdcf.eval_full(k0, qb)))
        s1 = np.asarray(ibdcf.share_bit(ibdcf.eval_full(k1, qb)))
        member.append(bool(np.all(s0 == s1)))
    assert member == [False, True, True, True, False, True]


def test_ob_codec_roundtrip():
    for v in (-32768, -1, 0, 1, 32767, -12345):
        assert bitutils.ob_bits_to_i16(bitutils.i16_to_ob_bits(v)) == v
    # order-preservation: encoding order == signed order
    vals = [-32768, -100, -1, 0, 1, 99, 32767]
    encs = [bitutils.bits_to_int(bitutils.i16_to_ob_bits(v)) for v in vals]
    assert encs == sorted(encs)


def test_prefix_semantics_internal_levels(rng):
    """At internal levels the share XOR of a single left key equals the
    strict prefix comparison — the property the tree crawl relies on level by
    level (ref: collect.rs:94-119; oracle docstring)."""
    L = 6
    b = 0b101101
    alpha = int_bits(L, b)
    seeds = rng.integers(0, 2**32, size=(2, 4), dtype=np.uint32)
    k0, k1 = ibdcf.gen_pair(seeds, alpha, True)
    for plen in range(2, L + 1):
        n = 1 << plen
        xb = np.stack([int_bits(plen, x) for x in range(n)])  # [n, plen]
        shares = []
        for k in (k0, k1):
            st = ibdcf.EvalState(
                seed=jnp.broadcast_to(k.root_seed, (n, 4)),
                bit=jnp.broadcast_to(k.key_idx, (n,)),
                y_bit=jnp.broadcast_to(k.key_idx, (n,)),
            )
            for lvl in range(plen):
                st = ibdcf.eval_bit(ibdcf.level_cw(k, lvl), st, xb[:, lvl])
            shares.append(np.asarray(ibdcf.share_bit(st)))
        got = shares[0] ^ shares[1]
        want = np.arange(n) < (b >> (L - plen))
        np.testing.assert_array_equal(got, want)
