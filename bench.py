"""Headline benchmark: ibDCF key generation throughput at data_len=512.

Reference baseline: 99.97 µs/key single-threaded with AES-NI
(≈10,003 keys/s; src/bin/benchmarks/ibDCFbench.csv:5, BASELINE.md), the
north-star metric "client-keys/sec/chip at data_len=512".

Prints ONE JSON line: value = keys/s on one chip, vs_baseline = speedup
over the reference CPU number.
"""

import json
import time

import numpy as np

BASELINE_KEYS_PER_SEC = 1e6 / 99.97  # ibDCFbench.csv:5 (data_len=512)


def main():
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf

    rng = np.random.default_rng(0)
    n, L = 8192, 512
    alpha = rng.integers(0, 2, size=(n, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
    side = np.ones(n, bool)
    alpha, seeds, side = map(jax.device_put, (alpha, seeds, side))

    def run():
        k0, _ = ibdcf.gen_pair(seeds, alpha, side)
        # reduce on device; fetching the scalar forces completion (the
        # tunnel's block_until_ready under-reports otherwise)
        return int(jnp.sum(k0.cw_seed.astype(jnp.uint32)))

    run()  # compile + warm
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        run()
    dt = (time.perf_counter() - t0) / iters
    keys_per_sec = n / dt

    print(
        json.dumps(
            {
                "metric": "ibdcf_keygen_keys_per_sec_at_data_len_512",
                "value": round(keys_per_sec, 1),
                "unit": "keys/s/chip",
                "vs_baseline": round(keys_per_sec / BASELINE_KEYS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
