"""Headline benchmarks on the real chip.

Prints ONE JSON line.  Headline metric (continuity with rounds 1-2 and the
north star "client-keys/sec/chip at data_len=512"): ibDCF keygen
throughput vs the reference's single-threaded AES-NI baseline
(99.97 µs/key, src/bin/benchmarks/ibDCFbench.csv:5, BASELINE.md).  The
``extra`` field carries the rest of the reference's benchmark surface:

- the full keygen sweep data_len ∈ {64, 256, 512, 1024} with per-key wire
  bytes (the ibDCFbench.rs:55-70 sweep + bincode size column);
- ``aggregate_clients_per_sec``: the SERVER hot loop — a full
  data_len=512 trusted-mode crawl (expand -> exchange -> count ->
  threshold -> prune/advance per level) over N clients on one chip.

HBM plan at N = 1M clients (north star: 1M clients < 10 s on v5e-8): the
frontier state is ``EvalState[F, N, d, 2]`` = seeds u32[...,4] + 2 bool
tensors ≈ 18 B per (node, client, dim, side).  At d=1, F=64:
64·1e6·1·2·18 B ≈ 2.3 GB, and the transient packed-bit tensor is
F·N·4 B = 256 MB — both fit a single v5e chip's 16 GB HBM.  Key material
is L·18 B + 16 B per (client, dim, side): at L=512 ≈ 9.2 KB/key·side,
i.e. ~18.5 GB for 1M clients' full batches — sharded over the 8-chip data
axis (parallel/mesh.py) that is ~2.3 GB/chip.  No component scales with
2^d beyond the [F, 2^d] count tensor.
"""

import json
import time

import numpy as np

from fuzzyheavyhitters_tpu.ops import prg as _prg

# bench targets the real chip: unrolled ChaCha rounds are ~6% faster there
# (the scan form is the compile-friendly default for test hosts, ops/prg.py)
_prg.CHACHA_UNROLL = True

BASELINE_US_PER_KEY = {64: None, 128: 25.92, 256: 50.47, 512: 99.97, 1024: 216.25}
BASELINE_KEYS_PER_SEC = 1e6 / 99.97  # ibDCFbench.csv:5 (data_len=512)
# reference per-key wire bytes (bincode), ibDCFbench.csv
BASELINE_KEY_BYTES = {128: 2585, 256: 5145, 512: 10265, 1024: 20505}


def _key_wire_bytes(k0) -> int:
    """Per-key bytes of our wire format (one key = one (client, dim, side)
    slice of the batch; cf. the reference's bincode size probe,
    ibDCFbench.rs:67)."""
    per = 0
    for leaf in k0:
        a = np.asarray(leaf)
        per += a[0].nbytes if a.ndim else a.nbytes
    return per


def bench_keygen(jax, jnp, ibdcf, rng, sweep=(64, 256, 512, 1024), n=8192):
    rows = {}
    headline = None
    for L in sweep:
        alpha = rng.integers(0, 2, size=(n, L)).astype(bool)
        seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
        side = np.ones(n, bool)
        alpha_d, seeds_d, side_d = map(jax.device_put, (alpha, seeds, side))

        def run():
            k0, _ = ibdcf.gen_pair(seeds_d, alpha_d, side_d)
            # reduce on device; fetching the scalar forces completion (the
            # tunnel's block_until_ready under-reports otherwise)
            return int(jnp.sum(k0.cw_seed.astype(jnp.uint32))), k0

        _, k0 = run()  # compile + warm
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            run()
        dt = (time.perf_counter() - t0) / iters
        keys_per_sec = n / dt
        base = BASELINE_US_PER_KEY.get(L)
        rows[L] = {
            "keys_per_sec": round(keys_per_sec, 1),
            "us_per_key": round(1e6 / keys_per_sec, 3),
            "key_bytes": _key_wire_bytes(k0),
            "vs_baseline": round(keys_per_sec / (1e6 / base), 2) if base else None,
        }
        if L == 512:
            headline = keys_per_sec
    return headline, rows


def bench_crawl(ibdcf, driver, rng, n=8192, L=512, f_max=64):
    """Server hot loop: full L-level trusted-mode crawl on one chip.

    Zipf-like scenario: clients cluster on a handful of sites so the
    frontier stays small (the production regime) while every level still
    expands/compares all N clients."""
    n_sites = 4
    sites = rng.integers(0, 2, size=(n_sites, 1, L)).astype(bool)
    pts_bits = sites[rng.integers(0, n_sites, size=n)]
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine="np")
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=f_max)
    res = lead.run(nreqs=n, threshold=0.05)  # warm + compile (2 programs)
    assert res.paths.shape[0] >= n_sites  # sites (+ball neighbours) survive

    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=f_max)
    t0 = time.perf_counter()
    res = lead.run(nreqs=n, threshold=0.05)
    dt = time.perf_counter() - t0
    return {
        "aggregate_clients_per_sec": round(n / dt, 1),
        "crawl_seconds": round(dt, 3),
        "n_clients": n,
        "data_len": L,
        "levels_per_sec": round(L / dt, 2),
        "hitters": int(res.paths.shape[0]),
        "projected_1m_clients_seconds_1chip": round(dt * (1_000_000 / n), 1),
    }


def bench_upload(n=100_000, L=16, batch=1000, port=39731):
    """100k-key ingest benchmark: leader -> two servers over localhost TCP
    with the id'd pipelined framing (ref: leader.rs:340-364's 1000
    in-flight batches).  Host-side only — add_keys appends buffers; the
    device sees keys once at tree_init."""
    import asyncio

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(1)
    alpha = rng.integers(0, 2, size=(n, 1, 2, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(n, 1, 2, 2, 4), dtype=np.uint32)
    side = np.broadcast_to(np.array([True, False]), (n, 1, 2))
    k0, k1 = ibdcf.gen_pair_np(seeds, alpha, side)

    cfg = Config(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=batch,
        num_sites=4, threshold=0.1, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=32,
    )

    async def run():
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", port, "127.0.0.1", port + 11)
        )
        c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
        c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
        await asyncio.gather(t0, t1)
        lead = RpcLeader(cfg, c0, c1)
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        t = time.perf_counter()
        await lead.upload_keys(k0, k1)
        return time.perf_counter() - t

    dt = asyncio.run(run())
    # _key_wire_bytes slices only the client axis, so for these [n, 1, 2]
    # interval batches it already covers both sides = one server's payload
    per_key_bytes = _key_wire_bytes(k0)
    return {
        "upload_keys_per_sec": round(n / dt, 1),
        "upload_seconds": round(dt, 3),
        "n_keys": n,
        "addkey_batch_size": batch,
        "approx_mb_per_sec": round(n * per_key_bytes / dt / 1e6, 1),
    }


def _crawl_subprocess(timeout_s: int = 420):
    """Run the crawl benchmark in a child process with a hard timeout so a
    stalled accelerator tunnel can never take down the whole bench run
    (the keygen headline must always print)."""
    import subprocess
    import sys

    code = (
        "import json, numpy as np, bench;"
        "from fuzzyheavyhitters_tpu.ops import ibdcf;"
        "from fuzzyheavyhitters_tpu.protocol import driver;"
        "print(json.dumps(bench.bench_crawl(ibdcf, driver,"
        " np.random.default_rng(0))))"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=timeout_s,
            text=True,
            cwd=__file__.rsplit("/", 1)[0],
        )
        line = out.stdout.strip().splitlines()[-1]
        return json.loads(line)
    except Exception as e:  # timeout, crash, parse failure
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def main():
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf

    rng = np.random.default_rng(0)
    headline, sweep = bench_keygen(jax, jnp, ibdcf, rng)
    crawl = _crawl_subprocess()
    try:
        upload = bench_upload()
    except Exception as e:
        upload = {"error": f"{type(e).__name__}: {e}"[:200]}

    print(
        json.dumps(
            {
                "metric": "ibdcf_keygen_keys_per_sec_at_data_len_512",
                "value": round(headline, 1),
                "unit": "keys/s/chip",
                "vs_baseline": round(headline / BASELINE_KEYS_PER_SEC, 2),
                "extra": {
                    "keygen_sweep": sweep,
                    "reference_key_bytes": BASELINE_KEY_BYTES,
                    "crawl": crawl,
                    "upload": upload,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
