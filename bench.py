"""Headline benchmarks on the real chip.

Prints ONE JSON line.  Headline metric (continuity with rounds 1-2 and the
north star "client-keys/sec/chip at data_len=512"): ibDCF keygen
throughput vs the reference's single-threaded AES-NI baseline
(99.97 µs/key, src/bin/benchmarks/ibDCFbench.csv:5, BASELINE.md).  The
``extra`` field carries the rest of the reference's benchmark surface:

- the full keygen sweep data_len ∈ {64, 256, 512, 1024} with per-key wire
  bytes (the ibDCFbench.rs:55-70 sweep + bincode size column);
- ``aggregate_clients_per_sec``: the SERVER hot loop — a full
  data_len=512 trusted-mode crawl (expand -> exchange -> count ->
  threshold -> prune/advance per level) over N clients on one chip,
  measured back-to-back on BOTH expand engines (pack-in-kernel Pallas
  default vs XLA);
- ``crawl_hbm_max``: a REAL measured crawl (no projections) at the
  1-chip HBM maximum on BASELINE config 4's workload shape (zipf 10000
  sites, t=0.001, L=512) via the streaming mode — host-resident keys,
  per-level cw upload, chunked re-expand advance;
- ``secure_crawl``: the level loop with the REAL GC+OT data plane between
  two in-process collector servers over localhost sockets (e2e — the
  fused output-label b2a makes a level ONE protocol round trip; through
  the remote-chip tunnel it is still floored by ~3 device<->host round
  trips/level, see ``secure_device`` for the deployment-shape number);
- ``secure_device``: the whole per-level 2PC as one on-chip program at
  flagship shape (>= 65k clients, L >= 64, plus an L=512-key level) —
  the 1-chip stand-in for the 2-chip mesh deployment;
- ``multichip``: secure clients/sec with each collector server's client
  axis sharded over 1/2/4/8 local data devices
  (``Config.server_data_devices``, parallel/server_mesh.py), every leg
  gated on bit-identity vs the single-device leg, with the pre-wire ICI
  reduction's seconds on the compact line;
- ``hbm``: the 1M-client HBM plan VALIDATED by allocation — the L=512
  key batch at the largest bench N actually lives on the chip, 3 levels
  run, and bytes/client are measured, not derived;
- ``hash_margin``: measured garbling cost at ChaCha rounds 8/12/20 (the
  margin note in ops/prg.py cites these);
- ``upload``: 1M-key control-plane ingest through the rolling window.
"""

import json
import os
import tempfile
import time

import numpy as np

from fuzzyheavyhitters_tpu.ops import prg as _prg
from fuzzyheavyhitters_tpu.utils import compile_cache as _compile_cache

# bench targets the real chip: unrolled ChaCha rounds are ~6% faster there
# (the scan form is the compile-friendly default for test hosts, ops/prg.py)
_prg.CHACHA_UNROLL = True

# wall-clock budget: the whole bench must finish (and print its final
# parseable JSON line) inside this many seconds.  The harness runs bench
# under an external `timeout` that KILLs shortly after its TERM — a bench
# that overruns leaves NO artifact (BENCH_r05: rc=124, no JSON) — so the
# budget proactively trims the LATER, more expensive sections instead:
# each skipped section reports {"skipped": "budget"} and the final line
# still prints.  Override with FHH_BENCH_BUDGET=<seconds>.
BENCH_BUDGET_S = float(os.environ.get("FHH_BENCH_BUDGET", "3000"))
# seconds held back for the final artifact (report write + JSON print)
_BUDGET_RESERVE_S = 45.0
_BENCH_T0 = time.monotonic()
# CI smoke mode: tiny shapes, CPU-safe engines, heavyweight sections
# skipped — exercises the end-to-end bench contract (JSON line, budget,
# telemetry) in minutes on any host (scripts/bench_smoke.sh)
BENCH_SMOKE = os.environ.get("FHH_BENCH_SMOKE", "0") != "0"


def _budget_left() -> float:
    return BENCH_BUDGET_S - (time.monotonic() - _BENCH_T0)


# child sections import this module first thing: pick up the parent's
# FHH_COMPILE_CACHE (main() defaults it) before any jit runs.  A no-op
# when the env var is unset (tests importing bench see no side effect).
_compile_cache.enable()


BASELINE_US_PER_KEY = {64: None, 128: 25.92, 256: 50.47, 512: 99.97, 1024: 216.25}
BASELINE_KEYS_PER_SEC = 1e6 / 99.97  # ibDCFbench.csv:5 (data_len=512)
# reference per-key wire bytes (bincode), ibDCFbench.csv
BASELINE_KEY_BYTES = {128: 2585, 256: 5145, 512: 10265, 1024: 20505}


def _keygen_engine() -> str:
    """Fused Pallas kernel on a real chip; the host NumPy mirror elsewhere
    (no Mosaic on XLA:CPU — and the jax scan engine compiles pathologically
    there, see tests/conftest.py)."""
    from fuzzyheavyhitters_tpu.ops import ibdcf

    return ibdcf.best_engine()


def _key_wire_bytes(k0) -> int:
    """Per-key bytes of our wire format (one key = one (client, dim, side)
    slice of the batch; cf. the reference's bincode size probe,
    ibDCFbench.rs:67).  Metadata-only — fetching the batch to count bytes
    would pull GBs through the tunnel's ~30 MB/s download path."""
    per = 0
    for leaf in k0:
        shape, itemsize = leaf.shape, leaf.dtype.itemsize
        per += itemsize * int(np.prod(shape[1:])) if shape else itemsize
    return per


def _time_of(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _steady_state_seconds(thunk, force, warm_force, iters=20, trials=3):
    """Min-of-trials per-launch seconds for a device thunk.

    Queues ``iters`` launches and forces them with ONE sync whose value
    depends on every launch (``force`` maps the list of outputs to a host
    int).  A per-iteration scalar fetch adds a full tunnel round trip to
    each measurement (~100 ms — 3x the kernel itself at bench sizes); a
    bare block_until_ready through the tunnel returns before the device
    finishes.  The dependent sync is honest and amortized; the MIN over
    trials strips the tunnel's additive queueing noise (which otherwise
    swings results 3-5x)."""
    warm_force(thunk())  # compile + warm
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        force([thunk() for _ in range(iters)])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _throughput(jnp, gen, seeds_d, alpha_d, side_d, n, iters=32, trials=3):
    """Steady-state keygen keys/sec (see _steady_state_seconds).

    The queued thunk reduces the generated keys to ONE device scalar
    inside the same jit program: the sum depends on the whole (opaque)
    keygen kernel, so nothing is dead-code-eliminated, but the ~20 B/key
    cw tensors are program-internal temporaries — freed as each launch
    retires — so a DEEP queue (amortizing the end-of-batch fetch RTT over
    ``iters``) coexists with production-sized batches instead of trading
    off against HBM for queued outputs."""
    import jax

    k0, _ = gen(seeds_d, alpha_d, side_d)  # un-queued: the wire-size probe

    @jax.jit
    def summed(s, a, sd):
        return jnp.sum(gen(s, a, sd)[0].cw_seed.astype(jnp.uint32))

    best = _steady_state_seconds(
        lambda: summed(seeds_d, alpha_d, side_d),
        lambda outs: int(sum(outs[1:], start=outs[0])),
        lambda o: int(o),
        iters=iters,
        trials=trials,
    )
    return n / best, k0


def bench_keygen(jax, jnp, ibdcf, rng, sweep=(64, 128, 256, 512, 1024)):
    from fuzzyheavyhitters_tpu.ops.keygen_pallas import gen_pair_pallas

    rows = {}
    headline = None
    for L in sweep:
        # PRODUCTION-shaped batches: the leader generates keys 32k-128k at
        # a time (bench_crawl_hbm_max, bin/leader.py's report).  Small
        # batches measure the tunnel's per-launch dispatch overhead, not
        # the kernel — observed to swing 1-15 ms by day, which at n=8192
        # (5.8 ms of kernel work) once read as a 3x kernel "regression".
        # The ~20 B/key outputs are launch-internal temporaries (see
        # _throughput), so the queue stays DEEP at these sizes.
        n = 131072 if L >= 1024 else 262144
        alpha = rng.integers(0, 2, size=(n, L)).astype(bool)
        seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
        side = np.ones(n, bool)
        alpha_d, seeds_d, side_d = map(jax.device_put, (alpha, seeds, side))

        keys_per_sec, k0 = _throughput(
            jnp, gen_pair_pallas, seeds_d, alpha_d, side_d, n,
            trials=6 if L == 512 else 3,  # headline: more min-of-trials
            # insurance against the tunnel's cross-run queueing variance
        )
        base = BASELINE_US_PER_KEY.get(L)
        rows[L] = {
            "keys_per_sec": round(keys_per_sec, 1),
            "us_per_key": round(1e6 / keys_per_sec, 3),
            "key_bytes": _key_wire_bytes(k0),
            "n": n,
            "vs_baseline": round(keys_per_sec / (1e6 / base), 2) if base else None,
        }
        if L == 512:  # headline size: also compare the scan engine (each
            # extra engine compile costs ~30 s through the tunnel)
            scan_kps, _ = _throughput(
                jnp, ibdcf.gen_pair, seeds_d, alpha_d, side_d, n, iters=6
            )
            rows[L]["scan_engine_keys_per_sec"] = round(scan_kps, 1)
            headline = keys_per_sec
    return headline, rows


def write_keygen_csv(rows: dict, path: str = "ibDCFbench_tpu.csv"):
    """Emit the sweep in the shape of the reference's one shipped benchmark
    artifact (ibDCFbench.rs:57-68 -> ibDCFbench.csv: string_length,
    number_keys, time, avg_time, size)."""
    with open(path, "w") as f:
        f.write("string_length,number_keys,time,avg_time,size\n")
        for L in sorted(rows):
            r = rows[L]
            avg = 1.0 / r["keys_per_sec"]
            n = r["n"]
            f.write(f"{L},{n},{avg * n},{avg},{r['key_bytes']}\n")


def bench_crawl(ibdcf, driver, rng, n=131072, L=512, f_max=64):
    """Server hot loop: full L-level trusted-mode crawl on one chip.

    Zipf-like scenario: clients cluster on a handful of sites so the
    frontier stays small (the production regime) while every level still
    expands/compares all N clients.  Round-4 shape of the measurement:

    - the frontier is BUCKETED (collect.bucket_for) and advance is a
      gather from the expand-time child cache — per-level work is sized
      to survivors, with no second PRG pass;
    - N = 131072 so per-level COMPUTE dominates the tunnel's per-dispatch
      floor (~2 ms/launch; at the old N=8192 that floor was most of the
      measured "device" time, silently inflating the 1M projection 16x
      more than compute justifies);
    - the level pipeline is ONE jitted program (both servers' expand +
      counts + both advances), matching the production mesh path where
      counts_body is a single XLA dispatch per level (parallel/mesh.py).
    """
    n_sites = 4
    sites = rng.integers(0, 2, size=(n_sites, 1, L)).astype(bool)
    pts_bits = sites[rng.integers(0, n_sites, size=n)]
    # keygen on the chip (the fused kernel): host NumPy keygen for 512-bit
    # interval pairs at this N takes hours on a 1-core host
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())

    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.protocol import collect

    timed_levels = min(64, L)

    def run_slice(levels):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=f_max)
        lead.tree_init()
        t0 = time.perf_counter()
        for lvl in range(levels):
            n_alive = lead.run_level(lvl, nreqs=n, threshold=0.05)
            assert n_alive >= 1  # early levels hold few nodes (2^level caps)
        return time.perf_counter() - t0, n_alive, s0, s1

    def measure_engine(want_fit=True):
        """Steady-state per-level seconds under the CURRENT engine knob.

        Warm slice compiles every bucket size of the steady crawl
        (1 -> 2 -> 4 ... as the sites' prefixes separate); the second,
        timed, slice replays the same buckets; then the device-only level
        pipeline runs on the steady-state frontier the slice left behind
        (idempotent: same inputs each launch) — ONE fused program covering
        BOTH servers, so the per-server cost is half of this.
        """
        run_slice(timed_levels)
        dt_slice, n_alive, s0, s1 = run_slice(timed_levels)
        # by level 64 the 4 random sites' prefixes are distinct w.h.p.,
        # and each survives with its ball neighbours
        assert n_alive >= n_sites
        masks = jnp.asarray(collect.pattern_masks(1))
        alive = jnp.asarray(s0.alive_keys)
        nb = collect.bucket_for(n_alive, f_max)
        parent = jnp.zeros(nb, jnp.int32)
        pat = jnp.zeros((nb, 1), bool)

        @jax.jit
        def one_level(keys0, f0, keys1, f1, lvl):
            p0, ch0 = collect.expand_share_bits(keys0, f0, lvl)
            p1, ch1 = collect.expand_share_bits(keys1, f1, lvl)
            cnt = collect.counts_by_pattern(p0, p1, masks, alive, f0.alive)
            nf0 = collect.advance_from_children(ch0, parent, pat, n_alive)
            nf1 = collect.advance_from_children(ch1, parent, pat, n_alive)
            return cnt, nf0, nf1

        # 64 queued launches per sync: the tunnel's end-of-batch fetch
        # costs a full round trip (~150 ms) — at 16 launches that RTT was
        # ~10 ms/level of pure measurement artifact
        best = _steady_state_seconds(
            lambda: one_level(s0.keys, s0.frontier, s1.keys, s1.frontier,
                              timed_levels),
            lambda outs: int(sum(jnp.sum(c[0, 0]) for c, _, _ in outs)),
            lambda o: int(jnp.sum(o[0])),
            iters=64,
        )

        if not want_fit:  # A/B comparison pass: skip the 2x-bucket point
            return best, None, dt_slice, s0.frontier.f_bucket

        # second point at DOUBLE the frontier bucket (same keys, same
        # clients — per-client work doubles): separates the per-launch
        # dispatch overhead (measured 1-7 ms day-to-day through the
        # tunnel) from the kernel's marginal cost, for honest
        # amortized projections (linear n/dt scaling charges the 1M
        # target the 131k run's overhead 7.6x over)
        def grow(fr):
            st = fr.states
            if collect._expand_engine():  # planar [.., F, N] node axis -4/-2
                dup = lambda a, ax: jnp.concatenate([a, a], axis=ax)
                states = type(st)(
                    seed=dup(st.seed, -2), bit=dup(st.bit, -2),
                    y_bit=dup(st.y_bit, -2),
                )
            else:
                dup = lambda a: jnp.concatenate([a, a], axis=0)
                states = type(st)(*[dup(x) for x in st])
            return collect.Frontier(
                states=states, alive=jnp.concatenate([fr.alive, fr.alive])
            )

        f0b, f1b = grow(s0.frontier), grow(s1.frontier)
        parent2 = jnp.zeros(2 * nb, jnp.int32)
        pat2 = jnp.zeros((2 * nb, 1), bool)

        @jax.jit
        def one_level2(keys0, fr0, keys1, fr1, lvl):
            p0, ch0 = collect.expand_share_bits(keys0, fr0, lvl)
            p1, ch1 = collect.expand_share_bits(keys1, fr1, lvl)
            cnt = collect.counts_by_pattern(p0, p1, masks, alive, fr0.alive)
            nf0 = collect.advance_from_children(ch0, parent2, pat2, 2 * n_alive)
            nf1 = collect.advance_from_children(ch1, parent2, pat2, 2 * n_alive)
            return cnt, nf0, nf1

        one_level2(s0.keys, f0b, s1.keys, f1b, timed_levels)
        # SAME iters as the first point: the end-of-batch sync RTT
        # amortizes identically into both, so the two-point difference
        # isolates the marginal cost instead of absorbing RTT/iters skew
        best2 = _steady_state_seconds(
            lambda: one_level2(s0.keys, f0b, s1.keys, f1b, timed_levels),
            lambda outs: int(sum(jnp.sum(c[0, 0]) for c, _, _ in outs)),
            lambda o: int(jnp.sum(o[0])),
            iters=64,
        )
        return best, best2, dt_slice, s0.frontier.f_bucket

    # back-to-back engine A/B (the only meaningful comparison on the
    # shared chip, whose throughput swings ~4x by hour): the XLA engine
    # first, then the pack-in-kernel Pallas engine — the default — last,
    # so the headline numbers come from the default engine's run.  On a
    # CPU-only host both knob settings resolve to the XLA engine
    # (collect._expand_engine), so the A/B would compare a thing to
    # itself — skip it and report one engine.
    default_engine = collect.EXPAND_PALLAS
    collect.EXPAND_PALLAS = True
    two_engines = collect._expand_engine()
    try:
        if two_engines:
            collect.EXPAND_PALLAS = False
            best_xla, _, _, _ = measure_engine(want_fit=False)
            collect.EXPAND_PALLAS = True
        best, best2, dt_slice, f_bucket = measure_engine()
    finally:
        collect.EXPAND_PALLAS = default_engine
    dt = best * L
    ab = (
        {
            "ms_per_level_device_xla_engine": round(best_xla * 1000, 3),
            "engine_speedup_vs_xla": round(best_xla / best, 2),
        }
        if two_engines
        else {}
    )
    # launch-overhead split from the two bucket points: per-client
    # marginal cost = best2 - best (the doubled bucket doubles every
    # client's states), fixed per-launch = the remainder.  The naive
    # linear projection charges the 1M target the fixed overhead
    # (1M/n)x; the amortized projections charge it once per launch.
    # If chip noise makes best2 <= best the fit is DEGENERATE — fall
    # back to the (conservative) linear projection and say so, rather
    # than reporting 1M clients as free.
    fit_ok = best2 > best
    if fit_ok:
        marg = best2 - best  # per n clients at f_bucket
        fixed = max(best - marg, 0.0)
        t_1m_level = fixed + marg * (1_000_000 / n)
        t_125k_level = fixed + marg * (125_000 / n)
    else:
        t_1m_level = best * (1_000_000 / n)
        t_125k_level = best * max(125_000 / n, 1.0)
        fixed = 0.0
    return {
        "aggregate_clients_per_sec": round(n / dt, 1),
        "crawl_seconds_device": round(dt, 3),
        "ms_per_level_device": round(best * 1000, 3),
        **ab,
        "ms_per_level_device_2x_bucket": round(best2 * 1000, 3),
        "launch_overhead_ms": round(fixed * 1000, 3),
        "overhead_fit_degenerate": not fit_ok,
        "ms_per_level_e2e_tunnel": round(dt_slice / timed_levels * 1000, 2),
        "timed_levels_e2e": timed_levels,
        "n_clients": n,
        "data_len": L,
        "f_bucket_steady": int(f_bucket),
        "levels_per_sec": round(L / dt, 2),
        "projected_1m_clients_seconds_1chip": round(dt * (1_000_000 / n), 1),
        # compute-amortized: one launch per level carries all clients (the
        # streaming mode's regime; 1M clients' keys need ~2 chips of HBM
        # or host streaming, so this is the COMPUTE bound, overhead paid
        # once per level, marginal cost scaled from the measured 2-point
        # fit above)
        "projected_1m_clients_seconds_1chip_amortized": round(
            t_1m_level * L, 1
        ),
        # the north star (BASELINE.json): clients are data-parallel over
        # the mesh's `data` axis (parallel/mesh.py) — per-level cross-chip
        # traffic is one psum of the [F, 2^d] count shares, microseconds
        # against a multi-ms level — so 8 chips each crawl 125k clients
        # in parallel, each paying the per-launch overhead once per level
        "projected_1m_clients_seconds_v5e8": round(t_125k_level * L, 1),
    }



def bench_crawl_hbm_max(rng, n=196608, L=512, sites=10000, threshold=0.001,
                        zipf_exp=1.03, ball=2, aug=8):
    """REAL measured crawl at the 1-chip HBM maximum — no projections.

    BASELINE.json config 4's workload shape (zipf over 10000 sites,
    data_len=512, threshold=0.001) at the largest client count one chip
    can hold with BOTH servers colocated.  The round-4 HBM plan projected
    ~663k clients from per-SERVER key bytes; this chip carries both
    parties, and the binding constraint is frontier state
    (F x N x d x 2 x 18 B x 2 servers x old+new), not keys: the
    thresholded frontier is ~103 nodes steady (measured), but near the
    LEAVES the ball-size-2 neighbourhoods multiply survivors ~4x (103 ->
    421 hitters -> bucket 512), and that late-crawl spike is what sizes
    memory — 320k clients OOMed around level 450 on exactly it; 196k is
    the measured fit.  The run uses the STREAMING mode
    (protocol/driver.py): keys live in host RAM (8 GB for both servers),
    each level uploads only its ~40 B/client cw slice (double-buffered
    behind the expands), and advance re-expands survivors chunk-wise
    (collect.advance_from_cw) so the transient stays bounded.  Keygen runs
    chunked on the chip and lands key chunks in host RAM as it goes.

    Every number reported is measured wall-clock, INCLUDING the Python
    client simulation, keygen + device->host key fetch, and per-level
    host thresholding; per-level compile costs (first occurrence of each
    bucket shape) are inside the e2e time, so the steady-state rate is
    reported as the median level."""
    import jax

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import driver
    from fuzzyheavyhitters_tpu.workloads import strings

    t0 = time.perf_counter()
    pts, _ = strings.zipf_workload(rng, sites, L, 1, zipf_exp, n, aug)
    t_sim = time.perf_counter() - t0

    # chunked keygen: the full cw tensor (2 x 9.4 GB) cannot sit on the
    # chip next to the crawl; generate 32k clients at a time and fetch
    host = lambda k: type(k)(*[np.asarray(x) for x in k])
    t0 = time.perf_counter()
    ch = 32768
    parts = []
    for i in range(0, n, ch):
        k0c, k1c = ibdcf.gen_l_inf_ball(
            pts[i : i + ch], ball, rng, engine=_keygen_engine()
        )
        parts.append((host(k0c), host(k1c)))
        del k0c, k1c
    cat = lambda xs: type(xs[0])(
        *[np.concatenate([np.asarray(l) for l in leaves], axis=0)
          for leaves in zip(*xs)]
    )
    k0 = cat([p[0] for p in parts])
    k1 = cat([p[1] for p in parts])
    del parts
    t_keygen = time.perf_counter() - t0

    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(
        s0, s1, n_dims=1, data_len=L, f_max=1024, min_bucket=128,
        stream=True, stream_chunk=32,
    )
    lead.tree_init()
    t0 = time.perf_counter()
    level_s = []
    for lvl in range(L):
        t1 = time.perf_counter()
        n_alive = lead.run_level(lvl, nreqs=n, threshold=threshold)
        level_s.append(time.perf_counter() - t1)
        if lvl % 64 == 0:
            from fuzzyheavyhitters_tpu import obs

            obs.emit(
                "bench.level", level=lvl, alive=int(n_alive),
                seconds=round(level_s[-1], 2),
            )
        if n_alive == 0:
            break
    dt = time.perf_counter() - t0
    med = float(np.median(level_s))
    # per-phase split from the driver's telemetry registry (obs layer):
    # fss = expand, field = counts/threshold, advance = frontier rebuild.
    # Leaf phases only — the enclosing "level" span is their sum and
    # would double-count for any consumer adding the reported phases.
    rep_phases = lead.obs.report()["phases"]
    phase_seconds = {
        k: round(rep_phases[k]["seconds"], 2)
        for k in ("fss", "field", "advance")
        if k in rep_phases
    }
    return {
        "n_clients": n,
        "data_len": L,
        "num_sites": sites,
        "threshold": threshold,
        "phase_seconds": phase_seconds,
        "device_fetches": int(lead.obs.counter_value("device_fetches")),
        "hitters": int(lead.n_nodes),
        "crawl_seconds_e2e": round(dt, 1),
        "clients_per_sec_e2e": round(n / dt, 1),
        "ms_per_level_median": round(med * 1000, 1),
        "clients_per_sec_steady": round(n / (med * L), 1),
        "levels_run": len(level_s),
        "f_bucket_steady": int(s0.frontier.f_bucket),
        "client_sim_seconds": round(t_sim, 2),
        "keygen_and_fetch_seconds": round(t_keygen, 1),
        "host_key_gbytes_both_servers": round(
            sum(np.asarray(x).nbytes for k in (k0, k1) for x in k) / 1e9, 2
        ),
    }


def bench_covid(n=8192, L=64, n_counties=64, ball=1, threshold=0.01):
    """COVID-geo workload end to end on the chip: the f64-bit domain
    (data_len=64, n_dims=2 — ref: sample_covid_data.rs:32-35) through the
    full driver crawl.  The reference's own covid call site is commented
    out (leader.rs:367-371), so this is parity-plus: a hot-county centroid
    file, jitterless sampling (same-county clients are bit-identical
    f64s), counts exact.  Reports measured e2e wall including sampling."""
    import os
    import tempfile

    import jax

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import driver
    from fuzzyheavyhitters_tpu.workloads import covid

    rng = np.random.default_rng(11)
    with tempfile.TemporaryDirectory() as td:
        cpath = os.path.join(td, "county_centroids.csv")
        with open(cpath, "w") as f:
            f.write("fips_code,latitude,longitude\n")
            for i in range(n_counties):
                f.write(
                    f"{10000 + i},{25 + 25 * rng.random():.4f},"
                    f"{-120 + 50 * rng.random():.4f}\n"
                )
        t0 = time.perf_counter()
        pts = covid.sample_covid_locations(
            os.path.join(td, "absent.csv"), cpath, n, fuzz_factor=None, seed=7
        )
        k0, k1 = ibdcf.gen_l_inf_ball(pts, ball, rng, engine=_keygen_engine())
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(
            s0, s1, n_dims=2, data_len=L, f_max=2048, min_bucket=64
        )
        res = lead.run(nreqs=n, threshold=threshold)
        jax.block_until_ready(s0.frontier.states.bit)
        dt = time.perf_counter() - t0
    assert res.paths.shape[0] >= n_counties  # every hot county + ulp ball
    return {
        "covid_crawl_seconds_e2e": round(dt, 2),
        "covid_clients_per_sec": round(n / dt, 1),
        "n_clients": n,
        "data_len": L,
        "n_dims": 2,
        "hitters": int(res.paths.shape[0]),
    }


async def _bring_up_pair(cfg, port):
    """Two collector servers + leader-side clients in this process:
    s1 first (it listens on the data plane at port+11), then s0 dials —
    the reference's startup ordering (server.rs:344-354).  Returns
    (leader, c0, c1) with both servers reset."""
    import asyncio

    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader

    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(s0.start("127.0.0.1", port, "127.0.0.1", port + 11))
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    await asyncio.gather(t0, t1)
    lead = RpcLeader(cfg, c0, c1)
    await asyncio.gather(c0.call("reset"), c1.call("reset"))
    return lead, c0, c1, s0, s1


def bench_secure(n=1024, L=12, port=21831, shard_nodes=4, pipeline_depth=4):
    """Secure-mode aggregate crawl: both collector servers in one process
    with the REAL 2PC data plane (secure_exchange=true), full level loop
    over localhost sockets on the default device.  End-to-end wall time.
    A level is ONE protocol round trip — ev u -> sender's whole-level
    planar message (the 1-of-2^S chosen-payload table at this 1-dim
    shape; the packed garbled batch past secure.OT2S_MAX_S) — so the
    tunnel floor is ~3 serial device<->host fetches per level (u, table,
    shares) at the reported ``device_fetch_rtt_ms`` (~0.1 s).  Still a
    lower bound on what adjacent hardware achieves;
    ``bench_secure_device`` is the adjacent-chip number.
    Ref seam: collect.rs:419-482 inside tree_crawl.

    Round-7 shape: the HEADLINE run is WHOLE-LEVEL — every (node,
    client) wire of a level garbles/evaluates as one fused device
    program per side (``secure_whole_level``, the default), with the
    secure-kernel phase split (otext/garble/eval/b2a) captured from the
    server registries.  Three comparison legs ride along on the same
    warmed servers: the round-6 sharded+pipelined run, its sequential
    form (``pipeline_speedup`` keeps its meaning), and a GC-path
    (``ot_path="gc"``) sequential reference — and ALL results are
    asserted bit-identical before anything is reported, so the fused
    1-of-2^S path never reports numbers it didn't earn.  Compiles are
    excluded from every timing via the per-``f_bucket`` warmup verb
    (plus ``FHH_COMPILE_CACHE``).  NB: the planar wire pads every GC/OT
    batch to ``gc_pallas.padded_tests`` (8192 tests), so at tiny smoke
    shapes the SHARDED leg pays the padding floor once per span and its
    ``pipeline_speedup`` reads < 1 — meaningful only at production
    shapes where spans amortize the floor."""
    import asyncio
    import dataclasses

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(3)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )  # [n, 1, L] MSB-first
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())

    cfg = Config(
        data_len=L, n_dims=1, ball_size=2, addkey_batch_size=1024,
        num_sites=8, threshold=0.05, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=64, secure_exchange=True,
        crawl_shard_nodes=shard_nodes, crawl_pipeline_depth=pipeline_depth,
    )

    async def run():
        lead, c0, c1, s0, s1 = await _bring_up_pair(cfg, port)

        async def timed_leg(leg_cfg, warm=False):
            leg = RpcLeader(leg_cfg, c0, c1)
            await asyncio.gather(c0.call("reset"), c1.call("reset"))
            await leg.upload_keys(k0, k1)
            if warm:
                # legs whose shapes the headline warmup cannot cover
                # (span-sized sharded programs, the GC path) warm their
                # own program ladder OFF the timed clock
                await leg.warmup()
            t = time.perf_counter()
            res = await leg.run(n)
            return res, time.perf_counter() - t, leg

        await lead.upload_keys(k0, k1)
        await lead.warmup()  # per-f_bucket compiles, off the clock
        res = await lead.run(n)  # warm: any residual compile/trace cost
        assert res.paths.shape[0] >= 1
        # timed HEADLINE: whole-level fused kernels (the default config)
        res_w, dt_w, _ = await timed_leg(cfg)
        # secure-kernel phase split of the timed run, BOTH servers (the
        # garbler role alternates per level, so each registry holds half
        # of every phase; reset above cleared the warm run's accounting)
        rep = s0.obs.report()
        rep1 = s1.obs.report()
        # per-level latency SLO of the timed headline run (obs.hist):
        # both servers' fixed-bucket histograms merge bucket-wise
        from fuzzyheavyhitters_tpu.obs.hist import Histogram

        lv = Histogram.merged(
            [s0.obs.hist("level_latency"), s1.obs.hist("level_latency")]
        )
        slo = {
            "level_p50_ms": round(1000 * (lv.quantile(0.5) or 0.0), 2),
            "level_p95_ms": round(1000 * (lv.quantile(0.95) or 0.0), 2),
            "level_max_ms": round(1000 * lv.max, 2),
        }
        # timed sharded+pipelined comparison (the round-6 headline);
        # the pipeline telemetry lives entirely on this leg's own fresh
        # leader registry (the whole-level legs emit none)
        pipe_cfg = dataclasses.replace(cfg, secure_whole_level=False)
        res_p, dt_p, pipe_lead = await timed_leg(pipe_cfg, warm=True)
        overlap = pipe_lead.obs.timer_seconds("pipeline_overlap")
        stalls = int(pipe_lead.obs.counter_value("pipeline_stalls"))
        # timed SEQUENTIAL comparison (PR-4 path, same warmed servers)
        res_s, dt_s, _ = await timed_leg(
            dataclasses.replace(
                cfg, crawl_shard_nodes=0, crawl_pipeline_depth=1,
                secure_whole_level=False,
            )
        )
        # GC-path sequential reference: the fused 1-of-2^S headline must
        # be bit-identical to the garbled-circuit oracle before any
        # number is reported
        res_g, dt_g, _ = await timed_leg(
            dataclasses.replace(
                cfg, ot_path="gc", crawl_shard_nodes=0,
                crawl_pipeline_depth=1,
            ),
            warm=True,
        )
        for other in (res_p, res_s, res_g):
            assert np.array_equal(res_w.counts, other.counts)
            assert np.array_equal(res_w.paths, other.paths)
        return (dt_w, dt_p, dt_s, dt_g, overlap, stalls,
                int(res_w.paths.shape[0]), rep, rep1, slo)

    (dt, dt_pipe, dt_seq, dt_gc, overlap_s, stalls, hitters, rep,
     rep1, slo) = asyncio.run(run())
    phases, ctrs = rep["phases"], rep["counters"]
    zero = {"seconds": 0.0, "total": 0}
    fss, gcot, fld = (
        round(phases.get(k, zero)["seconds"], 3)
        for k in ("fss", "gc_ot", "field")
    )
    # secure-kernel split: sum both servers' registries per phase; the
    # path taken comes from the ot_path_* counters (ot2s at this 1-dim
    # shape unless EQ_OT4 is off)
    kernel = {}
    for k in ("otext", "garble", "eval", "b2a"):
        kernel[f"phase_{k}_seconds"] = round(
            phases.get(k, zero)["seconds"]
            + rep1["phases"].get(k, zero)["seconds"], 3
        )
    n_ot2s = int(ctrs.get("ot_path_ot2s", zero)["total"])
    n_gc = int(ctrs.get("ot_path_gc", zero)["total"])
    kernel["ot_path"] = (
        "mixed" if (n_ot2s and n_gc) else ("gc" if n_gc else "ot2s")
    )
    gc_tests = int(ctrs.get("gc_tests", zero)["total"])
    # the e2e floor: every device->host fetch in the serial 2PC message
    # flow costs one of these (≈6 per level after round-4's packing)
    import jax.numpy as jnp

    a = jnp.zeros(4, jnp.uint32) + 1
    np.asarray(a)  # warm
    rtt = min(
        _time_of(lambda: np.asarray(a + i)) for i in range(3)
    )
    return {
        "secure_clients_per_sec": round(n / dt, 1),
        "secure_crawl_seconds": round(dt, 3),
        "n_clients": n,
        "data_len": L,
        "ms_per_level_e2e": round(dt / L * 1000, 2),
        "hitters": hitters,
        # the whole-level fused-kernel phase split + path of the timed
        # headline run — the ROADMAP's acceptance instrument
        "secure_kernel": kernel,
        # per-level latency quantiles (obs.hist histograms, both servers
        # merged) — the measurement campaign's SLO headline
        "slo": slo,
        # whole-level vs the round-6 sharded+pipelined path, and the
        # garbled-circuit sequential oracle everything was asserted
        # bit-identical against
        "whole_level_speedup_vs_pipelined": round(dt_pipe / dt, 2),
        "gc_reference_clients_per_sec": round(n / dt_gc, 1),
        # pipelined-vs-sequential on the same warmed servers (results
        # asserted bit-identical inside the run)
        "pipelined_clients_per_sec": round(n / dt_pipe, 1),
        "sequential_clients_per_sec": round(n / dt_seq, 1),
        "sequential_ms_per_level": round(dt_seq / L * 1000, 2),
        "pipeline_speedup": round(dt_seq / dt_pipe, 2),
        "pipeline": {
            "depth": cfg.crawl_pipeline_depth,
            "shard_nodes": cfg.crawl_shard_nodes,
            "overlap_seconds": round(overlap_s, 3),
            "stalls": stalls,
        },
        # measured equality tests of the timed run (batches are sized to
        # the live frontier bucket, not f_max)
        "gc_tests_per_level": round(gc_tests / L, 1),
        # server-0 accumulated 3-phase split (ref taxonomy,
        # collect.rs:412-503); remainder vs secure_crawl_seconds is
        # control-plane + pickling + event-loop time
        "phase_fss_seconds": fss,
        "phase_gc_ot_seconds": gcot,
        "phase_field_seconds": fld,
        "device_fetch_rtt_ms": round(rtt * 1000, 1),
        # data-plane accounting from the same registry: fetch COUNT is the
        # remote-tunnel floor the rpc.py docstring states — now measured
        "device_fetches": int(ctrs.get("device_fetches", zero)["total"]),
        "data_plane_mbytes_sent": round(
            ctrs.get("data_bytes_sent", zero)["total"] / 1e6, 2
        ),
        "data_plane_mbytes_recv": round(
            ctrs.get("data_bytes_recv", zero)["total"] / 1e6, 2
        ),
    }


def bench_radix(n=1024, L=12, port=23431, radices=(1, 2, 3)):
    """Radix-2^k level fusion sweep (``Config.crawl_radix_bits``): the
    same secure crawl at k = 1, 2, 3 bits per round trip, each k on its
    own warmed server pair.  The fused rounds widen the equality strings
    to S' = 2k (ot2s at this 1-dim shape) and cut the crawl to
    ceil(L/k) round trips — the win is the per-round fixed cost
    (control-plane verbs, device<->host fetches, OT/GC handshakes) paid
    ceil(L/k) times instead of L.

    Identity gate first, numbers second: every k's heavy-hitter counts
    AND paths are asserted bit-identical to the k=1 run before anything
    is reported, and the per-server ``rpc:{verb}`` histograms must show
    exactly ceil(L/k) crawl verbs — a sweep that cheated on either
    contract reports nothing.  Timings exclude compiles (per-radix
    warmup ladder + FHH_COMPILE_CACHE, same policy as bench_secure).

    NB: over loopback a round trip costs ~0, while the fused ot2s
    tables grow 4^k rows per dim — so smoke shapes legitimately report
    ``speedup_vs_k1`` < 1.  The fusion wins where the tentpole aims:
    real inter-site tunnels whose per-round fixed cost (RTT + the ~3
    serial device<->host fetches bench_secure documents) dwarfs the
    wider table, where cutting L rounds to ceil(L/k) is the headline."""
    import asyncio
    import dataclasses
    import math

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(3)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )  # [n, 1, L] MSB-first
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())

    base_cfg = Config(
        data_len=L, n_dims=1, ball_size=2, addkey_batch_size=1024,
        num_sites=8, threshold=0.05, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=64, secure_exchange=True,
    )

    def crawl_verbs(server):
        hists = server._default().obs.report()["hists"]
        return sum(
            hists[v]["count"]
            for v in ("rpc:tree_crawl", "rpc:tree_crawl_last")
            if v in hists
        )

    async def leg(k, leg_port):
        cfg = dataclasses.replace(
            base_cfg,
            crawl_radix_bits=k,
            server0=f"127.0.0.1:{leg_port}",
            server1=f"127.0.0.1:{leg_port + 10}",
        )
        lead, c0, c1, s0, s1 = await _bring_up_pair(cfg, leg_port)
        await lead.upload_keys(k0, k1)
        await lead.warmup()  # per-radix program ladder, off the clock
        await lead.run(n)  # warm: residual compile/trace cost
        # reset clears the warm run's verb accounting, so the histograms
        # below count the TIMED crawl's round trips alone
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        await lead.upload_keys(k0, k1)
        t = time.perf_counter()
        res = await lead.run(n)
        dt = time.perf_counter() - t
        verbs = (crawl_verbs(s0), crawl_verbs(s1))
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
        return res, dt, verbs

    async def run():
        out = {}
        for i, k in enumerate(radices):
            out[k] = await leg(k, port + 40 * i)
        return out

    legs = asyncio.run(run())
    base_res, base_dt, _ = legs[1]
    assert base_res.paths.shape[0] >= 1
    rounds_want = {k: math.ceil(L / k) for k in radices}
    sweep = {}
    for k, (res, dt, verbs) in legs.items():
        # the identity gate: a fused crawl that drifted from the k=1
        # sets/paths — or issued more round trips than it claims —
        # reports NOTHING
        assert np.array_equal(base_res.counts, res.counts), k
        assert np.array_equal(base_res.paths, res.paths), k
        assert verbs == (rounds_want[k], rounds_want[k]), (k, verbs)
        sweep[k] = {
            "crawl_seconds": round(dt, 3),
            "clients_per_sec": round(n / dt, 1),
            "round_trips": rounds_want[k],
            "ms_per_round_trip": round(dt / rounds_want[k] * 1000, 2),
            "speedup_vs_k1": round(base_dt / dt, 2),
        }
    best_k = min(legs, key=lambda k: legs[k][1])
    return {
        "n_clients": n,
        "data_len": L,
        "radix_sweep": {str(k): v for k, v in sweep.items()},
        "best_k": int(best_k),
        # bit levels crawled per round trip at the best k — the fused
        # crawl's level rate multiplier over one-bit-per-round
        "level_rate_x_k": round(L / rounds_want[best_k], 2),
        "speedup_vs_k1": sweep[best_k]["speedup_vs_k1"],
        "bit_identical": True,
    }


def bench_multichip(n=1024, L=12, port=22231, shards=(1, 2, 4, 8),
                    f_max=64, kernel_shards=(1, 2, 4, 8)):
    """Multi-chip collector servers: secure clients/sec as each server's
    client axis shards over 1/2/4/8 LOCAL data devices
    (parallel/server_mesh.py — ``Config.server_data_devices``).  Every
    sharded leg is asserted BIT-IDENTICAL to the single-device leg
    before any number is reported (sharding is a physical layout; the
    2PC transcript never changes), and the highest-shard leg's
    ``ici_reduce_seconds`` (the pre-wire psum, fetch-synced) rides the
    compact line next to ``data_shards``.  Shard counts beyond the
    visible device count (or not dividing the client batch) are
    reported as skipped, not silently dropped — on a CPU host run under
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the smoke
    path) all four legs run.

    KERNEL-SHARDED legs (PR 10): at the top feasible data-shard count, a
    second sweep varies ``Config.secure_kernel_shards`` over
    ``kernel_shards`` — 1 pins the gather-to-one-device kernel stage
    (the pre-PR-10 layout), higher caps run the row-sharded IKNP +
    equality kernels (parallel/kernel_shard.py).  Each leg is
    bit-identity-gated like the data legs;
    ``whole_level_speedup_vs_gathered`` is the top kernel leg's rate
    over the gathered leg's, and ``kernel_gather_seconds`` (should read
    ~0 on the sharded legs' deep levels) rides the compact line."""
    import asyncio
    import jax

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.parallel import server_mesh
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(5)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())

    def leg_cfg(p, k, ks=0):
        return Config(
            data_len=L, n_dims=1, ball_size=2, addkey_batch_size=1024,
            num_sites=8, threshold=0.05, zipf_exponent=1.03,
            server0=f"127.0.0.1:{p}", server1=f"127.0.0.1:{p + 10}",
            distribution="zipf", f_max=f_max, secure_exchange=True,
            server_data_devices=k, secure_kernel_shards=ks,
        )

    n_devices = len(jax.devices())

    async def one_leg(k, p, ks=0):
        cfg = leg_cfg(p, k, ks)
        lead, c0, c1, s0, s1 = await _bring_up_pair(cfg, p)
        try:
            await lead.upload_keys(k0, k1)
            await lead.warmup()  # sharded program ladder, off the clock
            res = await lead.run(n)  # warm residual trace/dispatch cost
            await lead._both("reset")
            await lead.upload_keys(k0, k1)
            t = time.perf_counter()
            res = await lead.run(n)
            dt = time.perf_counter() - t
            ici = (
                s0.obs.timer_seconds("ici_reduce")
                + s1.obs.timer_seconds("ici_reduce")
            )
            st = await c0.call("status")
            # kernel_shards_max (the deepest sharding the crawl
            # engaged) comes from the status verb like every other
            # mesh-health number — the wire interface, not a reach into
            # the in-process registry
            return res, dt, ici, st.get("mesh")
        finally:
            for c in (c0, c1):
                await c.aclose()
            for s in (s0, s1):
                await s.aclose()

    rates: dict = {}
    skipped: dict = {}
    base_res = None
    top = (1, 0.0, None)  # (shards, ici_reduce_s, mesh status)
    for i, k in enumerate(shards):
        if k > n_devices:
            skipped[str(k)] = "devices"
            continue
        if server_mesh._largest_divisor_leq(n, k) != k:
            skipped[str(k)] = "batch"
            continue
        # the data-shard sweep pins the GATHERED kernel stage (kernel
        # cap 1) so its legs measure exactly what PR 8 measured; the
        # kernel sweep below owns the sharded-kernel comparison
        res, dt, ici, mesh_st = asyncio.run(one_leg(k, port + 40 * i, ks=1))
        rates[str(k)] = round(n / dt, 1)
        if base_res is None:
            base_res = res
        else:
            # gate: a sharded leg that is not bit-identical to the
            # single-device leg reports nothing
            assert np.array_equal(base_res.counts, res.counts)
            assert np.array_equal(base_res.paths, res.paths)
        if k >= top[0]:
            top = (k, ici, mesh_st)
    # kernel-sharded sweep at the top feasible data-shard count: vary
    # the secure_kernel_shards cap, 1 = the gathered baseline
    kernel_rates: dict = {}
    kernel_skipped: dict = {}
    k_top_status = None
    k_engaged = None
    kg_seconds = None
    data_top = top[0]
    for j, s in enumerate(kernel_shards):
        if base_res is None or data_top < 2:
            kernel_skipped[str(s)] = "devices"
            continue
        if s > data_top:
            kernel_skipped[str(s)] = "devices"
            continue
        if s == 1 and str(data_top) in rates:
            # the gathered baseline IS the data sweep's top leg (the
            # data legs pin kernel cap 1) — reuse its rate instead of
            # re-running an identical warmed server pair
            kernel_rates["1"] = rates[str(data_top)]
            continue
        res, dt, ici, mesh_st = asyncio.run(
            one_leg(data_top, port + 2000 + 40 * j, ks=s)
        )
        assert np.array_equal(base_res.counts, res.counts)
        assert np.array_equal(base_res.paths, res.paths)
        kernel_rates[str(s)] = round(n / dt, 1)
        if mesh_st is not None:
            k_top_status = mesh_st
            if s > 1:
                k_engaged = mesh_st.get("kernel_shards_max")
                kg_seconds = mesh_st.get("kernel_gather_seconds")
    speedup = None
    if len(kernel_rates) > 1 and kernel_rates.get("1"):
        best = max(
            v for s, v in kernel_rates.items() if s != "1"
        )
        speedup = round(best / kernel_rates["1"], 3)
    return {
        "bit_identical": base_res is not None and len(rates) > 1,
        "data_shards": top[0],
        "ici_reduce_seconds": round(top[1], 3),
        "secure_clients_per_sec": rates,
        "skipped_shards": skipped,
        # kernel-sharded legs (bit-identity-gated like the data legs)
        "kernel_shards": k_engaged,
        "kernel_clients_per_sec": kernel_rates,
        "kernel_gather_seconds": kg_seconds,
        "whole_level_speedup_vs_gathered": speedup,
        "kernel_skipped": kernel_skipped,
        "n_clients": n,
        "data_len": L,
        "n_devices": n_devices,
        "mesh_status": k_top_status or top[2],
    }


def bench_sketch(n=1024, L=12, port=23031, shards=(1, 2, 4, 8),
                 data_devices=8, secure=True):
    """Malicious-secure sketch verification in the fast lane
    (parallel/sketch_shard.py): the headline is
    ``malicious_overhead_vs_semi_honest`` — one crawl WITH the sketch
    gates (MAC'd payload DPFs verified per level, the device-resident
    fused verify) over the identical crawl WITHOUT them, same config,
    same warmed servers per leg.  A sharded sweep varies
    ``Config.sketch_shards`` over ``shards`` on an
    ``data_devices``-wide data mesh; every sharded leg is gated TWICE
    before anything is reported:

    - DIRECTLY: the trusted challenge stream (r + rand rows, by CTR
      seek) and the cor-share wire bytes at shard count k are asserted
      byte-identical to the single fused program's, per field — the
      check that catches a seek bug e2e results cannot (honest clients
      pass under ANY challenge, so result equality alone is blind to a
      perturbed stream);
    - E2E: the sharded leg's heavy hitters, paths, AND the per-client
      liveness vector are asserted bit-identical to the unsharded
      malicious leg's.

    Clients are honest here (the overhead number should price the
    checks, not a cheater's exclusion); cheater-detection parity is
    tier-1's job (tests/test_sketch_shard.py)."""
    import asyncio

    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
    from fuzzyheavyhitters_tpu.parallel import server_mesh, sketch_shard
    from fuzzyheavyhitters_tpu.protocol import mpc, sketch as sketchmod
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(9)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )  # [n, 1, L] MSB-first
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())
    seeds = rng.integers(0, 2**32, size=(n, 1, 2, 4), dtype=np.uint32)
    cseed = rng.integers(0, 2**32, size=4, dtype=np.uint32)
    sk0, sk1 = sketchmod.gen(seeds, pts_bits, FE62, F255, cseed)

    def leg_cfg(p, sketch_k):
        return Config(
            data_len=L, n_dims=1, ball_size=2, addkey_batch_size=1024,
            num_sites=8, threshold=0.05, zipf_exponent=1.03,
            server0=f"127.0.0.1:{p}", server1=f"127.0.0.1:{p + 10}",
            distribution="zipf", f_max=64, secure_exchange=secure,
            malicious=True, server_data_devices=data_devices,
            sketch_shards=sketch_k,
        )

    n_devices = len(jax.devices())

    def direct_gate(k) -> None:
        """Challenge stream + cor wire at shard count k vs the single
        fused program — byte-identical or the leg reports nothing."""
        devs = tuple(jax.local_devices()[:k])
        ss = sketch_shard.bind(devs, n, 1, k)
        assert ss is not None and ss.k == k, (k, ss)
        m, lvl = 8, 3
        for field in (FE62, F255):
            r_ref, rands_ref = sketchmod.shared_r_stream(
                field, cseed, lvl, m, n
            )
            r, ra = sketch_shard.stream_parts(ss, field, cseed, lvl, m, n, 1)
            assert np.array_equal(np.asarray(r_ref), r)
            assert np.array_equal(np.asarray(rands_ref), ra)
            w = 8 if field.limb_shape else 4
            pairs = field.sample(jnp.asarray(rng.integers(
                0, 2**32, size=(m, n, 1, 2, w), dtype=np.uint32
            )))
            trip, _ = mpc.gen_triples(field, (n, 1, mpc.CHECKS), cseed)
            mk = field.sample(jnp.asarray(rng.integers(
                0, 2**32, size=(n, w), dtype=np.uint32
            )))
            mk2 = field.mul(mk, mk)
            cor_1, _ = sketch_shard.cor_state(
                None, field, pairs, trip, mk, mk2, cseed, lvl
            )
            cor_k, _ = sketch_shard.cor_state(
                ss, field, pairs, trip, mk, mk2, cseed, lvl
            )
            assert np.array_equal(
                sketch_shard.wire(cor_1), sketch_shard.wire(cor_k)
            ), (field.__name__, k)

    async def one_leg(p, sketch_k, with_sketch=True):
        cfg = leg_cfg(p, sketch_k)
        lead, c0, c1, s0, s1 = await _bring_up_pair(cfg, p)
        try:
            sks = (sk0, sk1) if with_sketch else (None, None)
            await lead.upload_keys(k0, k1, *sks)
            await lead.warmup()  # fused verify ladder, off the clock
            res = await lead.run(n)  # warm residual trace/dispatch cost
            await lead._both("reset")
            await lead.upload_keys(k0, k1, *sks)
            t = time.perf_counter()
            res = await lead.run(n)
            dt = time.perf_counter() - t
            alive = None if not with_sketch else s0.alive_keys.copy()
            sketch_s = (
                s0.obs.timer_seconds("sketch")
                + s1.obs.timer_seconds("sketch")
            )
            st = await c0.call("status")
            return res, dt, alive, sketch_s, (st.get("mesh") or {})
        finally:
            for c in (c0, c1):
                await c.aclose()
            for s in (s0, s1):
                await s.aclose()

    # semi-honest reference: the identical crawl without the sketch
    # gates (same shapes, same warmed servers-per-leg discipline)
    res_semi, dt_semi, _, _, _ = asyncio.run(one_leg(port, 1, False))
    rates: dict = {}
    skipped: dict = {}
    base_res = None
    base_alive = None
    top = (0, None, None)  # (shards, dt, verify seconds)
    for i, k in enumerate(shards):
        if k > 1 and (
            k > n_devices
            or server_mesh._largest_divisor_leq(n, k) != k
        ):
            skipped[str(k)] = "devices" if k > n_devices else "batch"
            continue
        if k > 1:
            direct_gate(k)
        res, dt, alive, sketch_s, mesh_st = asyncio.run(
            one_leg(port + 100 + 40 * i, k)
        )
        if k > 1 and (mesh_st.get("sketch_shards") or 1) != k:
            # the server's mesh could not hold k shards (fewer visible
            # devices than requested): report it skipped, never as a
            # sharded number it didn't earn
            skipped[str(k)] = "devices"
            continue
        rates[str(k)] = round(n / dt, 1)
        if base_res is None:
            base_res, base_alive = res, alive
        else:
            # e2e gate: hitters, paths, AND liveness bit-identical to
            # the unsharded malicious leg
            assert np.array_equal(base_res.counts, res.counts)
            assert np.array_equal(base_res.paths, res.paths)
            assert np.array_equal(base_alive, alive)
        if k >= top[0]:
            top = (k, dt, sketch_s)
    dt_mal = top[1]
    if base_res is not None:
        # honest clients: the malicious legs' outputs must equal the
        # semi-honest reference's (the checks gate liveness, they never
        # perturb counts), and every client must survive its checks
        assert np.array_equal(base_res.counts, res_semi.counts)
        assert np.array_equal(base_res.paths, res_semi.paths)
        assert base_alive is not None and bool(base_alive.all())
    return {
        "bit_identical": base_res is not None and len(rates) >= 1,
        "malicious_overhead_vs_semi_honest": (
            None if dt_mal is None else round(dt_mal / dt_semi, 3)
        ),
        "sketch_clients_per_sec": (
            None if dt_mal is None else round(n / dt_mal, 1)
        ),
        "semi_honest_clients_per_sec": round(n / dt_semi, 1),
        "sketch_shards": top[0],
        "clients_per_sec_by_shards": rates,
        "verify_seconds": (
            None if top[2] is None else round(top[2], 3)
        ),
        "skipped_shards": skipped,
        "secure_exchange": bool(secure),
        "n_clients": n,
        "data_len": L,
        "n_devices": n_devices,
    }


def bench_secure_device(n=65536, L=64, f_bucket=4, with_l512=True):
    """Device-resident secure-crawl measurement at FLAGSHIP shape: the
    WHOLE per-level 2PC — both parties' expand, label extension, garbling,
    evaluation, output-label b2a (the fused flow the socket path ships),
    alive-gated share sums — as ONE jitted program on one chip.

    This is the 1-chip stand-in for the 2-chip mesh deployment
    (parallel/mesh.py runs the same math with the messages as ``ppermute``
    transfers): it measures what the 2PC costs where the north star runs
    it — chips adjacent to the servers — while ``bench_secure`` measures
    the socket e2e, which through the remote-chip tunnel is floored by
    device<->host round trips, not by the protocol.  Shape: n >= 65k
    clients, L >= 64, the steady zipf frontier bucket; ``with_l512`` adds
    one level on data_len=512 keys (per-level 2PC cost is L-independent —
    the measurement demonstrates it).  GC-table HBM bytes are reported
    for the garbled batch + payload ciphertexts."""
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import baseot, gc, ibdcf, otext
    from fuzzyheavyhitters_tpu.ops import prg as prgmod
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
    from fuzzyheavyhitters_tpu.protocol import collect, secure

    rng = np.random.default_rng(3)
    d = 1
    C, S = 1 << d, 2 * d
    B = f_bucket * C * n  # headline-shape test count (gc_bytes, report)

    s_bits = otext.fresh_s_bits()
    seeds0, seeds1, chosen = baseot.exchange(s_bits)
    s_bits_d = jnp.asarray(s_bits.astype(np.uint32))
    sm_snd = jnp.asarray(chosen.astype(np.uint32))
    sm_rcv = jnp.asarray(seeds0.astype(np.uint32))
    sa_rcv = jnp.asarray(seeds1.astype(np.uint32))
    gseed = jnp.asarray(np.frombuffer(b"bench-gc-seed..!", "<u4").copy())
    bseed = jnp.asarray(np.frombuffer(b"bench-b2aseed.!!", "<u4").copy())

    def make_keys(data_len):
        sites = rng.integers(0, 2, size=(8, 1, data_len)).astype(bool)
        pts_bits = sites[rng.integers(0, 8, size=n)]
        k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())
        # steady-state frontier: f_bucket slots (root states replicated;
        # the 2PC math is state-value-independent), all nodes+keys live
        f0 = collect.tree_init(k0, f_bucket)._replace(alive=jnp.ones(f_bucket, bool))
        f1 = collect.tree_init(k1, f_bucket)._replace(alive=jnp.ones(f_bucket, bool))
        return k0, k1, f0, f1

    k0, k1, f0, f1 = make_keys(L)
    alive_keys = jnp.ones(n, bool)

    def level_fn(field, fb=f_bucket, eq_ot4=None):
        limb = field.limb_shape
        W = secure.payload_words(field)
        B = fb * C * n
        m = B * S
        w = jnp.asarray(
            secure.alive_weight(np.ones(fb, bool), np.ones(n, bool), C)
        )
        if eq_ot4 is None:
            eq_ot4 = secure._ot4_use(S)

        @jax.jit
        def run(keys0, fr0, keys1, fr1, lvl):
            p0, _ = collect.expand_share_bits(keys0, fr0, lvl, want_children=False)
            p1, _ = collect.expand_share_bits(keys1, fr1, lvl, want_children=False)
            flat0 = secure.child_strings(p0, d).reshape(B, S)  # garbler x
            flat1 = secure.child_strings(p1, d).reshape(B, S)  # evaluator y
            off = jnp.uint32(0)
            u, t_rows = otext._receiver_extend(
                sm_rcv, sa_rcv, flat1.reshape(m), off, m
            )
            q = otext._sender_extend(sm_snd, s_bits_d, u, off, m)
            s_block = otext.pack_bits(s_bits_d)
            r_words = prgmod.stream_words(bseed, B * W).reshape(B, W)
            r0 = field.sample(r_words)
            r1 = field.add(r0, field.from_int(1))
            w0, w1 = secure.field_to_words(field, r0), secure.field_to_words(field, r1)
            if eq_ot4:
                # S = 2 fast path: 1-of-4 chosen-payload OT, no circuit
                cts4 = secure.ot4_encrypt(
                    q.reshape(B, S, 4), s_block, flat0, w1, w0, W, 0
                )
                pay = secure.ot4_decrypt(
                    t_rows.reshape(B, S, 4), flat1, cts4, W, 0
                )
            else:
                # GC + fused output-label b2a (the parity path's math)
                batch, cts, _mask = gc.garble_equality_payload(
                    s_block, q.reshape(B, S, 4), gseed, flat0, w1, w0, W, 0
                )
                _, pay = gc.eval_equality_payload(
                    batch, t_rows.reshape(B, S, 4), cts, W, 0
                )
            v1 = secure.words_to_field(field, pay)
            sh0 = secure.node_share_sums(
                field, r1.reshape((fb, C, n) + limb), w
            )
            sh1 = secure.node_share_sums(
                field, v1.reshape((fb, C, n) + limb), w
            )
            return sh0, sh1

        return run

    def _lvl_seconds(run_fn, *args, iters=32):
        """Steady-state s/level: one dependent fetch over the first output
        leaf of every queued launch (see _steady_state_seconds)."""
        first = lambda o: jnp.ravel(
            jax.tree_util.tree_leaves(o)[0]
        )[0].astype(jnp.uint64)
        return _steady_state_seconds(
            lambda: run_fn(*args),
            lambda outs: int(sum(first(o) for o in outs)),
            lambda o: int(first(o)),
            iters=iters,
        )

    # engine A/B, non-default engines first and the default LAST so the
    # headline numbers come from the default engine's run (the crawl
    # bench's convention — only back-to-back comparisons mean anything on
    # the shared chip): the GC+fused-b2a path (the reference-parity
    # protocol shape, S-general) vs the S = 2 1-of-4-OT fast path
    # (secure.EQ_OT4, the production default for 1-dim crawls)
    from fuzzyheavyhitters_tpu.ops import gc as gcmod

    best_xla_gc = None
    best_gc_path = None
    if gcmod._pallas_engine():  # GC path on the XLA gc engine
        gcmod.GC_PALLAS = False
        try:
            run_x = level_fn(FE62, eq_ot4=False)
            run_x(k0, f0, k1, f1, 0)  # warm/compile
            best_xla_gc = _lvl_seconds(run_x, k0, f0, k1, f1, 0)
        finally:
            gcmod.GC_PALLAS = True
    if secure._ot4_use(S):  # GC path on its default engine (the ot4
        # headline's comparison point; identical to the headline otherwise)
        run_g = level_fn(FE62, eq_ot4=False)
        run_g(k0, f0, k1, f1, 0)  # warm/compile
        best_gc_path = _lvl_seconds(run_g, k0, f0, k1, f1, 0)

    results = {}
    for name, field in (("fe62", FE62), ("f255", F255)):
        run = level_fn(field)
        # correctness pin: reconstructed counts == trusted compare
        sh0, sh1 = run(k0, f0, k1, f1, 0)
        v = np.asarray(field.canon(field.sub(sh0, sh1)))
        counts = v[..., 0] if field is F255 else v
        masks = collect.pattern_masks(d)
        p0, _ = collect.expand_share_bits(k0, f0, 0, want_children=False)
        p1, _ = collect.expand_share_bits(k1, f1, 0, want_children=False)
        want = np.asarray(collect.counts_by_pattern(
            p0, p1, jnp.asarray(masks), alive_keys, jnp.ones(f_bucket, bool)
        ))
        assert np.array_equal(counts.astype(np.uint64), want.astype(np.uint64))
        results[name] = _lvl_seconds(run, k0, f0, k1, f1, 0)
    out_extra = {}
    if with_l512:
        k0b, k1b, f0b, f1b = make_keys(512)
        run = level_fn(FE62)
        run(k0b, f0b, k1b, f1b, 100)  # warm/compile the L=512 key shapes
        best512 = _lvl_seconds(run, k0b, f0b, k1b, f1b, 100, iters=16)
        out_extra["secure_device_ms_per_level_fe62_L512_keys"] = round(
            best512 * 1000, 3
        )
    # trusted-mode comparator at the SAME shape (both expands + plaintext
    # pattern counts — what secure mode replaces with GC+OT), so the
    # secure-vs-trusted cost ratio is explicit and same-run
    masks = jnp.asarray(collect.pattern_masks(d))
    a_keys = jnp.ones(n, bool)
    a_nodes = jnp.ones(f_bucket, bool)

    @jax.jit
    def trusted_level(keys0, fr0, keys1, fr1, lvl):
        p0, _ = collect.expand_share_bits(keys0, fr0, lvl, want_children=False)
        p1, _ = collect.expand_share_bits(keys1, fr1, lvl, want_children=False)
        return collect.counts_by_pattern(p0, p1, masks, a_keys, a_nodes)

    trusted_level(k0, f0, k1, f1, 0)
    best_trusted = _lvl_seconds(trusted_level, k0, f0, k1, f1, 0)
    # Contention guard: the shared chip occasionally hits multi-minute
    # windows where memory-heavy programs run ~15x slow (observed: the
    # same secure level measuring 19 ms and 294 ms an hour apart while
    # the small hash-margin garble held steady).  The design floor of
    # secure/trusted is ~3x (GC path ~4x); a ratio far above it flags
    # such a window, so wait it out once and re-measure every affected
    # side, reporting that the retry happened — min-of-trials inside one
    # window can't see this.  The speedup ratios are computed AFTER this
    # guard so they always compare the numbers actually reported.
    def _contended(x):
        return x is not None and x / best_trusted > 8

    if (_contended(results["fe62"]) or _contended(results["f255"])
            or _contended(best_gc_path) or _contended(best_xla_gc)):
        time.sleep(75)
        run_r = level_fn(FE62)
        run_r(k0, f0, k1, f1, 0)
        results["fe62"] = min(results["fe62"],
                              _lvl_seconds(run_r, k0, f0, k1, f1, 0))
        run_r5 = level_fn(F255)
        run_r5(k0, f0, k1, f1, 0)
        results["f255"] = min(results["f255"],
                              _lvl_seconds(run_r5, k0, f0, k1, f1, 0))
        if best_gc_path is not None:
            run_g2 = level_fn(FE62, eq_ot4=False)
            run_g2(k0, f0, k1, f1, 0)
            best_gc_path = min(best_gc_path,
                               _lvl_seconds(run_g2, k0, f0, k1, f1, 0))
        if best_xla_gc is not None:
            # run_x is still in scope and already compiled (the GC engine
            # was dispatched at ITS trace time, so no flag toggle needed)
            best_xla_gc = min(best_xla_gc,
                              _lvl_seconds(run_x, k0, f0, k1, f1, 0))
        best_trusted = min(best_trusted,
                           _lvl_seconds(trusted_level, k0, f0, k1, f1, 0))
        out_extra["contention_retry"] = True
    out_extra["trusted_same_shape_ms_per_level"] = round(best_trusted * 1000, 3)
    out_extra["secure_over_trusted_ratio"] = round(
        results["fe62"] / best_trusted, 2
    )
    if best_gc_path is not None:
        out_extra["secure_device_ms_per_level_fe62_gc_path"] = round(
            best_gc_path * 1000, 3
        )
        out_extra["ot4_speedup_vs_gc_path"] = round(
            best_gc_path / results["fe62"], 2
        )
    if best_xla_gc is not None:
        out_extra["secure_device_ms_per_level_fe62_xla_gc"] = round(
            best_xla_gc * 1000, 3
        )
        out_extra["gc_engine_speedup_vs_xla"] = round(
            best_xla_gc / (best_gc_path if best_gc_path is not None
                           else results["fe62"]), 2
        )

    # second point at DOUBLE the bucket (same keys/clients, 2x the 2PC
    # work): splits the per-launch dispatch overhead from the marginal
    # per-test cost, as in bench_crawl's two-point fit
    f0b = collect.tree_init(k0, 2 * f_bucket)._replace(
        alive=jnp.ones(2 * f_bucket, bool)
    )
    f1b = collect.tree_init(k1, 2 * f_bucket)._replace(
        alive=jnp.ones(2 * f_bucket, bool)
    )
    run2 = level_fn(FE62, fb=2 * f_bucket)
    run2(k0, f0b, k1, f1b, 0)
    # same iters as the fb=f_bucket point (RTT amortizes identically)
    best2 = _lvl_seconds(run2, k0, f0b, k1, f1b, 0)
    # raw fit (may go negative under chip noise — that honestly flags a
    # degenerate measurement rather than reporting extra work as free)
    marg = best2 - results["fe62"]
    out_extra["secure_device_ms_per_level_fe62_2x_bucket"] = round(
        best2 * 1000, 3
    )
    out_extra["secure_device_marginal_ns_per_test"] = round(
        marg / (f_bucket * C * n) * 1e9, 2
    )

    total = results["fe62"] * (L - 1) + results["f255"]
    # data-plane batch resident per level (FE62 words): the 1-of-4 payload
    # table on the fast path, garbled batch + payload ciphertexts on GC
    if secure._ot4_use(S):
        gc_bytes = B * 4 * 4 * 4  # cts uint32[4, B, W=4]
    else:
        gc_bytes = B * ((S - 1) * 2 * 16 + S * 16 + 4 + 2 * 4 * 4)
    return {
        "secure_device_clients_per_sec": round(n / total, 1),
        "secure_device_ms_per_level_fe62": round(results["fe62"] * 1000, 3),
        "secure_device_ms_per_level_f255": round(results["f255"] * 1000, 3),
        "secure_device_crawl_seconds": round(total, 3),
        "n_clients": n,
        "data_len": L,
        "f_bucket": f_bucket,
        "gc_tests_per_level": B,
        "gc_batch_mbytes_per_level_fe62": round(gc_bytes / 1e6, 1),
        **out_extra,
    }


def bench_hbm(n=196608, L=512, levels=3, f_max=64):
    """HBM scale validation: ACTUALLY allocate the L=512 key batch for the
    largest N this bench holds on one chip (both servers' batches — the
    1-chip driver shape, so one server's real footprint is half), run 3
    crawl levels on it, and report measured bytes — replacing the round-3
    plan that was arithmetic, not a measurement."""
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import driver

    rng = np.random.default_rng(0)
    sites = rng.integers(0, 2, size=(4, 1, L)).astype(bool)
    pts_bits = sites[rng.integers(0, 4, size=n)]
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())
    jax.block_until_ready(k0.cw_seed)
    key_bytes = sum(
        leaf.nbytes for k in (k0, k1) for leaf in jax.tree.leaves(k)
    )
    per_client_per_server = key_bytes / 2 / n
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=f_max)
    lead.tree_init()
    for lvl in range(levels):  # warm (compiles the small-bucket shapes)
        lead.run_level(lvl, nreqs=n, threshold=0.05)
    lead.tree_init()
    t0 = time.perf_counter()
    for lvl in range(levels):
        n_alive = lead.run_level(lvl, nreqs=n, threshold=0.05)
    dt = time.perf_counter() - t0
    assert n_alive >= 1
    # one v5e chip has 16 GB; leave 15% headroom for transients
    max_n_one_server = int(16e9 * 0.85 / per_client_per_server)
    return {
        "n_clients_allocated": n,
        "levels_run": levels,
        "key_gbytes_on_chip_both_servers": round(key_bytes / 1e9, 2),
        "measured_key_bytes_per_client_per_server": round(
            per_client_per_server, 1
        ),
        "ms_per_level_e2e": round(dt / levels * 1000, 2),
        "projected_max_clients_one_chip_16gb": max_n_one_server,
        "chips_for_1m_clients_keys": round(1e6 / max_n_one_server, 2),
    }


def bench_hash_margin(B=131072, S=2):
    """Measured cost of the ChaCha round count in the GC hash role (the
    correlation-robust hash of garbling; ops/prg.py N_ROUNDS note): one
    garble of a [B, S] equality batch at 8 / 12 / 20 rounds."""
    import secrets as pysecrets

    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import gc, prg

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2, size=(B, S)).astype(bool))
    y0 = jnp.asarray(rng.integers(0, 2**32, size=(B, S, 4), dtype=np.uint32))
    s_block = jnp.asarray(
        rng.integers(0, 2**32, size=4, dtype=np.uint32)
    )
    seed = jnp.asarray(np.frombuffer(pysecrets.token_bytes(16), "<u4").copy())
    out = {"gc_batch": B * S}
    for rounds in (8, 12, 20):
        prg.N_ROUNDS = rounds
        jax.clear_caches()  # N_ROUNDS is read at trace time
        best = _steady_state_seconds(
            lambda: gc.garble_equality_delta(s_block, y0, seed, x)[0].tables,
            lambda outs: int(sum(jnp.sum(o[0, 0]) for o in outs)),
            lambda o: int(jnp.sum(o[0, 0])),
            iters=32,
        )
        out[f"garble_ms_rounds_{rounds}"] = round(best * 1000, 3)
    prg.N_ROUNDS = 8
    jax.clear_caches()
    return out


def bench_upload(n=1_000_000, L=16, batch=4000, port=21731):
    """1M-key ingest benchmark: leader -> two servers over localhost TCP
    with the ROLLING upload window (leader_rpc.upload_keys; ref:
    leader.rs:340-364's 1000 in-flight batches).  Host-side only —
    add_keys appends buffers; the device sees keys once at tree_init."""
    import asyncio

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(1)
    alpha = rng.integers(0, 2, size=(n, 1, 2, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(n, 1, 2, 2, 4), dtype=np.uint32)
    side = np.broadcast_to(np.array([True, False]), (n, 1, 2))
    # HOST keygen on purpose: this bench measures control-plane ingest, and
    # the keys must be host-resident contiguous buffers (client-axis chunk
    # slices then pickle zero-copy).  Measured: chip keygen + tunnel fetch
    # yields NON-contiguous leaves whose chunks copy on every pickle
    # (368 MB/s vs 2.8 GB/s), and at L=16 the fetch alone dwarfs host
    # keygen time.
    k0, k1 = ibdcf.gen_pair_np(seeds, alpha, side)

    cfg = Config(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=batch,
        num_sites=4, threshold=0.1, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=32,
    )

    async def run():
        lead, c0, c1, _, _ = await _bring_up_pair(cfg, port)
        t = time.perf_counter()
        await lead.upload_keys(k0, k1)
        return time.perf_counter() - t

    dt = asyncio.run(run())
    # _key_wire_bytes slices only the client axis, so for these [n, 1, 2]
    # interval batches it already covers both sides = one server's payload
    per_key_bytes = _key_wire_bytes(k0)
    return {
        "upload_keys_per_sec": round(n / dt, 1),
        "upload_seconds": round(dt, 3),
        "n_keys": n,
        "addkey_batch_size": batch,
        "approx_mb_per_sec": round(n * per_key_bytes / dt / 1e6, 1),
    }


def bench_ingest(n=65536, L=12, chunk=256, port=21931, threshold=0.05):
    """Streaming front-door benchmark (ROADMAP "Streaming ingestion",
    ≥ 100k keys/sec acceptance): clients submit key chunks continuously
    through the admission-controlled ``submit_keys`` verb into tumbling
    windows; window 0 is sealed and crawled while window 1 keeps
    ingesting CONCURRENTLY (``submit_keys`` bypasses the servers' verb
    lock).  Reports the sustained admission rate for both phases — pure
    ingest and ingest-during-crawl — plus the windowed crawl seconds,
    and asserts the windowed window-0 result BIT-IDENTICAL to a batch
    (``upload_keys`` + ``run``) crawl over the same admitted key set
    before reporting anything.  Host-side ingest: keys stream as numpy
    buffers; the device sees them once at each ``window_load``."""
    import asyncio

    from fuzzyheavyhitters_tpu.obs import report as obsreport
    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import WindowedIngest
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(5)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )  # [n, 1, L] MSB-first
    # host NumPy keygen on purpose (like bench_upload): ingest is a
    # control-plane path and the chunks must be host-contiguous buffers
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 1, rng, engine="np")

    def mkcfg(p):
        return Config(
            data_len=L, n_dims=1, ball_size=1, addkey_batch_size=1024,
            num_sites=8, threshold=threshold, zipf_exponent=1.03,
            server0=f"127.0.0.1:{p}", server1=f"127.0.0.1:{p + 10}",
            distribution="zipf", f_max=64,
            ingest_window_keys=max(n, 1 << 20),
        )

    half = n // 2

    def chunks(lo, hi):
        for i, c0_lo in enumerate(range(lo, hi, chunk)):
            sl = slice(c0_lo, min(c0_lo + chunk, hi))
            yield (
                f"site{i % 8}",
                tuple(np.asarray(x)[sl] for x in k0),
                tuple(np.asarray(x)[sl] for x in k1),
            )

    out = {}

    async def run():
        lead, c0, c1, s0, s1 = await _bring_up_pair(mkcfg(port), port)
        wi = WindowedIngest(lead, checkpoint=False)
        # window 0: pure ingest throughput
        t0 = time.perf_counter()
        for cid, a, b in chunks(0, half):
            await wi.submit(cid, a, b)
        dt_ingest = time.perf_counter() - t0
        stats0 = await wi.seal_window()
        # window 1 ingests WHILE window 0's crawl runs
        async def pump():
            t = time.perf_counter()
            for cid, a, b in chunks(half, n):
                await wi.submit(cid, a, b)
            return time.perf_counter() - t

        t_crawl = time.perf_counter()
        crawl_task = asyncio.create_task(wi.crawl_window(0))
        dt_concurrent = await pump()
        res0 = await crawl_task
        dt_crawl = time.perf_counter() - t_crawl
        stats1 = await wi.seal_window()
        rep = obsreport.run_report([wi.obs])
        ing = rep.get("ingest") or {}
        out["ingest_keys_per_sec"] = round(half / dt_ingest, 1)
        out["concurrent_keys_per_sec"] = round((n - half) / dt_concurrent, 1)
        out["window_crawl_seconds"] = round(dt_crawl, 3)
        out["windows"] = int(ing.get("windows", 2))
        out["admitted"] = int(ing.get("admitted", 0))
        out["shed"] = int(stats0["shed_keys"]) + int(stats1["shed_keys"])
        out["rejected"] = int(ing.get("rejected", 0))
        out["n_keys"] = n
        out["chunk_keys"] = chunk
        out["report_ingest"] = ing
        # SLO quantiles of the streaming run (obs.hist): the window's
        # seal-to-hitters latency (driver clock), the e2e admit latency
        # (gate + mirror + backoffs), and the servers' per-level crawl
        # latency — the always-on dashboard's first-class metrics
        from fuzzyheavyhitters_tpu.obs.hist import Histogram

        sh = wi.obs.hist("seal_to_hitters") or Histogram()
        adm = wi.obs.hist("ingest_admit") or Histogram()
        lv = Histogram.merged(
            [s0.obs.hist("level_latency"), s1.obs.hist("level_latency")]
        )
        out["slo"] = {
            "seal_to_hitters_p50_s": round(sh.quantile(0.5) or 0.0, 4),
            "seal_to_hitters_p95_s": round(sh.quantile(0.95) or 0.0, 4),
            "admit_p95_ms": round(1000 * (adm.quantile(0.95) or 0.0), 3),
            "level_p95_ms": round(1000 * (lv.quantile(0.95) or 0.0), 2),
        }
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
        return res0

    async def batch():
        from fuzzyheavyhitters_tpu.ops.ibdcf import IbDcfKeyBatch

        lead, c0, c1, s0, s1 = await _bring_up_pair(mkcfg(port + 40), port + 40)
        bk0 = IbDcfKeyBatch(*(np.asarray(x)[:half] for x in k0))
        bk1 = IbDcfKeyBatch(*(np.asarray(x)[:half] for x in k1))
        await lead.upload_keys(bk0, bk1)
        res = await lead.run(half)
        for c in (c0, c1):
            await c.aclose()
        for s in (s0, s1):
            await s.aclose()
        return res

    res_windowed = asyncio.run(run())
    res_batch = asyncio.run(batch())
    # the number is only reported once the windowed path EARNED it
    if not (
        np.array_equal(res_windowed.counts, res_batch.counts)
        and np.array_equal(res_windowed.paths, res_batch.paths)
    ):
        raise AssertionError(
            "windowed window-0 crawl diverged from the batch crawl over "
            "the same admitted keys"
        )
    out["bit_identical_vs_batch"] = True
    return out


def bench_multitenant(n=1024, L=10, port=22531, tenant_counts=(1, 2, 4),
                      threshold=0.05):
    """Multi-tenant collection sessions (protocol/sessions.py): N
    concurrent collections on ONE server pair, each its own session
    (own frontier, own OT streams, own ingest gate), device work
    interleaved by the TenantScheduler.  Reports aggregate SECURE
    clients/sec at 1/2/4 concurrent collections vs the solo baseline,
    plus the stall-fill ratio (device turns that ran while another
    tenant waited on the GC/OT wire — the ``pipeline_stalls`` gap a
    second tenant fills).  Every tenant's heavy-hitter set is asserted
    BIT-IDENTICAL to its solo single-session run before anything is
    reported."""
    import asyncio

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import (
        MultiCollectionDriver,
    )
    from fuzzyheavyhitters_tpu.utils.config import Config

    def mkcfg(p):
        return Config(
            data_len=L, n_dims=1, ball_size=1, addkey_batch_size=2048,
            num_sites=8, threshold=threshold, zipf_exponent=1.03,
            server0=f"127.0.0.1:{p}", server1=f"127.0.0.1:{p + 10}",
            distribution="zipf", f_max=64, backend="cpu",
            secure_exchange=True,
        )

    max_t = max(tenant_counts)
    keysets = []
    for i in range(max_t):
        r = np.random.default_rng(50 + i)
        sites = r.integers(0, 1 << L, size=8)
        pts = sites[r.integers(0, 8, size=n)]
        pts_bits = (
            ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
        )
        keysets.append(ibdcf.gen_l_inf_ball(pts_bits, 1, r, engine="np"))

    async def _pair(p):
        cfg = mkcfg(p)
        s0 = rpc.CollectorServer(0, cfg)
        s1 = rpc.CollectorServer(1, cfg)
        t1 = asyncio.create_task(
            s1.start("127.0.0.1", p + 10, "127.0.0.1", p + 11)
        )
        await asyncio.sleep(0.05)
        t0 = asyncio.create_task(
            s0.start("127.0.0.1", p, "127.0.0.1", p + 11)
        )
        await asyncio.gather(t0, t1)
        return cfg, s0, s1

    async def leg(p, idxs):
        """The collections named by keyset indices ``idxs``, concurrent
        on one fresh pair; returns (results by collection, crawl wall
        seconds, scheduler stats)."""
        cfg, s0, s1 = await _pair(p)
        drv = MultiCollectionDriver(
            cfg, "127.0.0.1", p, "127.0.0.1", p + 10
        )
        leads = {}
        for i in idxs:
            key = f"t{i}" if len(idxs) > 1 else "default"
            lead = await drv.open(key)
            await lead.upload_keys(*keysets[i])
            await lead.warmup()  # WarmLadder dedups across tenants
            leads[key] = (lead, i)
        t0 = time.perf_counter()
        out = await asyncio.gather(
            *(lead.run(n) for lead, _ in leads.values())
        )
        wall = time.perf_counter() - t0
        st = await next(iter(leads.values()))[0].c0.call("status")
        await drv.close()
        for s in (s0, s1):
            await s.aclose()
        results = {
            key: res for (key, (_, i)), res in zip(leads.items(), out)
        }
        return results, wall, st["sessions"]["scheduler"]

    # solo references: each keyset alone on a fresh pair
    solo = {}
    solo_wall = None
    for i in range(max_t):
        res, wall, _sched = asyncio.run(leg(port + 100 + 20 * i, [i]))
        solo[i] = res["default"]
        if i == 0:
            solo_wall = wall
    solo_rate = n / solo_wall

    out = {
        "n_clients_per_tenant": n,
        "data_len": L,
        "solo_clients_per_sec": round(solo_rate, 1),
        "tenants": {},
    }
    for idx, k in enumerate(tenant_counts):
        if k == 1:
            out["tenants"]["1"] = {
                "aggregate_clients_per_sec": round(solo_rate, 1),
                "speedup_vs_solo": 1.0,
                "stall_fill_ratio": 0.0,
            }
            continue
        results, wall, sched = asyncio.run(
            leg(port + 300 + 40 * idx, list(range(k)))
        )
        for i in range(k):
            got = results[f"t{i}"]
            want = solo[i]
            if not (
                np.array_equal(got.counts, want.counts)
                and np.array_equal(got.paths, want.paths)
            ):
                raise AssertionError(
                    f"tenant t{i} of the {k}-collection leg diverged "
                    "from its solo run"
                )
        agg = k * n / wall
        out["tenants"][str(k)] = {
            "aggregate_clients_per_sec": round(agg, 1),
            "speedup_vs_solo": round(agg / solo_rate, 3),
            "stall_fill_ratio": sched["fill_ratio"],
            "stall_fills": sched["stall_fills"],
            "device_turns": sched["device_turns"],
        }
    top = str(max(tenant_counts))
    out["aggregate_clients_per_sec"] = (
        out["tenants"][top]["aggregate_clients_per_sec"]
    )
    out["aggregate_speedup_vs_solo"] = (
        out["tenants"][top]["speedup_vs_solo"]
    )
    out["stall_fill_ratio"] = out["tenants"][top]["stall_fill_ratio"]
    out["bit_identical_vs_solo"] = True
    return out


# sections of the run that already finished, keyed by metric name — what
# the SIGTERM handler dumps so a timed-out bench still reports them
_PARTIAL: dict = {}

# artifact path (--out).  The PARENT owns the file: _child_init clears
# this in bench children so a TERMed child's last-gasp dump can never
# clobber the parent's per-leg artifact (child telemetry travels on the
# stdout contract instead, folded in by _subprocess_metric).
_OUT: str | None = "bench_full.json"


def _atomic_json(path: str, doc: dict) -> None:
    """tmp + rename so a kill mid-write leaves the PREVIOUS artifact
    intact, never a truncated JSON file — the whole point of writing
    per leg is that the file on disk is valid at every instant."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        pass


def _write_leg_artifact() -> None:
    """Crash-proof bench: every completed leg lands in the on-disk
    artifact AS IT FINISHES, in the partial form (``"partial": true``
    until main() closes the manifest with the final document).  A bench
    killed at any point leaves a valid artifact carrying every leg that
    completed, and ``--resume`` picks up from exactly there."""
    if _OUT is None:
        return
    _atomic_json(_OUT, {
        "partial": True,
        "reason": "in-progress",
        "results": dict(_PARTIAL),
    })


def _load_resume(path: str) -> dict:
    """Previously-completed legs from an existing artifact: the partial
    form's ``results`` or — resuming over a CLOSED manifest — the final
    form's ``extra`` (mapping its ``secure_crawl`` key back to the
    ``secure`` leg name the partial path uses)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict):
        return {}
    if doc.get("partial"):
        res = dict(doc.get("results") or {})
    else:
        res = dict(doc.get("extra") or {})
        res.pop("reference_key_bytes", None)
        if "secure_crawl" in res:
            res["secure"] = res.pop("secure_crawl")
        if "keygen_sweep" in res and "value" in doc:
            res["keygen_headline"] = doc["value"]
    sweep = res.get("keygen_sweep")
    if isinstance(sweep, dict):
        try:  # JSON round-trips the data_len keys as strings
            res["keygen_sweep"] = {int(k): v for k, v in sweep.items()}
        except (TypeError, ValueError):
            pass
    return res


def _dump_partial(reason: str = "sigterm") -> dict:
    """Last-gasp artifact: finished sections plus the telemetry run
    report — the FULL document goes to the ``--out`` artifact (and the
    telemetry to ``$FHH_RUN_REPORT`` when set); the LAST stdout line (the
    bench output contract) carries the COMPACT form, because the harness
    keeps only a short stdout tail and an oversized line parses as
    nothing at all (BENCH_r04)."""
    from fuzzyheavyhitters_tpu import obs

    rep = {
        "partial": True,
        "reason": reason,
        "results": dict(_PARTIAL),
        "telemetry": obs.run_report(),
    }
    if _OUT is not None:
        _atomic_json(_OUT, rep)
    compact = {
        "partial": True,
        "reason": reason,
        "results": _compact_extra(
            {
                k: v
                for k, v in _PARTIAL.items()
                if k not in ("keygen_sweep", "keygen_headline")
            }
        ),
        "sections_done": sorted(_PARTIAL),
    }
    print(json.dumps(compact), flush=True)
    try:
        obs.maybe_write_run_report()
    except Exception:
        pass
    return rep


def _install_sigterm_partial() -> None:
    """SIGTERM -> partial results + telemetry report on stdout, exit 124.
    Installed by main() AND prepended to every child bench process: the
    driver's ``timeout`` command TERMs the run, and before this an rc=124
    bench left nothing but an XLA warning (BENCH_r05) — now it leaves the
    per-level phase seconds and byte counts accumulated up to the kill.
    Also starts the heartbeat: a wedged bench streams the active phase +
    level to stderr every 60 s, so even a SIGKILL leaves a trail naming
    where it died.

    The handler only raises SystemExit; the dump runs from an atexit hook
    once the stack has unwound.  Dumping inside the handler would grab the
    non-reentrant registry/log locks from a signal frame — if the TERM
    lands while the interrupted code holds one (every obs call does,
    briefly), the dump deadlocks until the parent's grace expires and the
    SIGKILL destroys the artifact this exists to save."""
    import atexit
    import signal
    import sys

    from fuzzyheavyhitters_tpu import obs

    obs.start_heartbeat(60.0)
    terminated = []

    def handler(_sig, _frame):
        terminated.append("sigterm")
        raise SystemExit(124)

    def on_exit():
        if terminated:  # normal exits keep the last-stdout-line contract
            _dump_partial(terminated[0])
        else:
            # the $FHH_RUN_REPORT artifact is promised for EVERY run, not
            # just killed ones — write it without touching stdout
            try:
                obs.maybe_write_run_report()
            except Exception:
                pass

    # Ctrl-C must leave the artifact too: SIGINT has no handler here (the
    # default KeyboardInterrupt keeps child teardown working), but one
    # reaching the top level runs excepthook before atexit — mark it so
    # on_exit dumps the finished sections + telemetry it would otherwise
    # silently discard
    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        if issubclass(tp, KeyboardInterrupt):
            terminated.append("interrupt")
        prev_hook(tp, val, tb)

    sys.excepthook = hook
    atexit.register(on_exit)
    signal.signal(signal.SIGTERM, handler)


def _child_init() -> None:
    """Per-child preamble (prepended by _subprocess_metric): the SIGTERM
    partial contract, plus the live /metrics exporter when
    ``FHH_METRICS_PORT`` is set — the PARENT never binds (it only
    orchestrates; the registries worth scraping live in the children,
    which run serially so the base port never conflicts).  The child's
    artifact path is cleared: its partial dump rides the stdout contract
    only, never the parent's per-leg artifact file."""
    global _OUT

    _OUT = None
    _install_sigterm_partial()
    from fuzzyheavyhitters_tpu.obs import exporter

    exporter.maybe_start("bench")


def _subprocess_metric(code: str, timeout_s: int):
    """Run one benchmark in a child process with a hard timeout so a
    stalled accelerator tunnel (or a hung socket loop) can never take down
    the whole bench run — the keygen headline must always print.  On
    timeout the child gets SIGTERM first (its handler prints partial
    results + the telemetry report as its last stdout line) and SIGKILL
    only if it ignores that for 20 s."""
    import subprocess
    import sys

    code = "import bench; bench._child_init();" + code
    # $FHH_RUN_REPORT belongs to the PARENT: a TERMed child would write
    # the file too, and the parent's own exit dump then clobbers it.
    # Child telemetry travels on the stdout contract (last JSON line)
    # instead, which the parent folds into its partial dump.
    env = {k: v for k, v in os.environ.items() if k != "FHH_RUN_REPORT"}
    try:
        p = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=__file__.rsplit("/", 1)[0],
            env=env,
        )
        timed_out = False
        try:
            out, err = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            timed_out = True
            p.terminate()  # SIGTERM: the child dumps partial + telemetry
            try:
                out, err = p.communicate(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
        except BaseException:
            # The parent is being torn down (driver SIGTERM -> SystemExit,
            # Ctrl-C) while blocked in communicate(): pass TERM down so the
            # grandchild stops crawling the accelerator and dumps its own
            # partial + telemetry — folded into _PARTIAL so the parent's
            # last-gasp dump (_dump_partial) carries the wedged section's
            # phase/level accounting out with it.  Grace is SHORT: the
            # harness `timeout -k 10` SIGKILLs the parent 10 s after its
            # TERM, and a 20 s wait here meant the parent died before
            # dumping anything (BENCH_r05: rc=124 with no JSON at all).
            p.terminate()
            try:
                out, _ = p.communicate(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            lines = (out or "").strip().splitlines()
            if lines:
                try:
                    _PARTIAL["interrupted"] = json.loads(lines[-1])
                except ValueError:
                    _PARTIAL["interrupted"] = {"stdout_tail": lines[-1][:500]}
            raise
        lines = (out or "").strip().splitlines()
        if not lines:  # child died before printing — surface its stderr
            tail = (err or "").strip().splitlines()[-3:]
            return {"error": f"child rc={p.returncode}: " + " | ".join(tail)}
        res = json.loads(lines[-1])
        if timed_out and isinstance(res, dict):
            res.setdefault("error", f"timeout after {timeout_s}s")
        return res
    except Exception as e:  # spawn failure, parse failure
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def bench_keygen_smoke(rng, L=64, n=2048):
    """CPU-safe keygen timing for smoke mode (scripts/bench_smoke.sh):
    the host NumPy engine over a tiny batch — exercises the keygen
    section's shape of the contract (headline number + sweep row), not
    the chip throughput."""
    from fuzzyheavyhitters_tpu.ops import ibdcf

    alpha = rng.integers(0, 2, size=(n, 1, 2, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(n, 1, 2, 2, 4), dtype=np.uint32)
    side = np.broadcast_to(np.array([True, False]), (n, 1, 2))
    ibdcf.gen_pair_np(seeds[:64], alpha[:64], side[:64])  # warm
    t0 = time.perf_counter()
    k0, _ = ibdcf.gen_pair_np(seeds, alpha, side)
    dt = time.perf_counter() - t0
    kps = n / dt
    return kps, {
        L: {
            "keys_per_sec": round(kps, 1),
            "us_per_key": round(1e6 / kps, 3),
            "key_bytes": _key_wire_bytes(k0),
            "n": n,
            "vs_baseline": None,
            "smoke": True,
        }
    }


# headline scalars each section contributes to the COMPACT final line
# (the harness captures only a short stdout tail, so the final JSON line
# must stay small — BENCH_r04 printed a 3.5 KB line and parsed as null)
_COMPACT_KEYS = {
    "crawl": ("aggregate_clients_per_sec", "ms_per_level_device"),
    "crawl_hbm_max": ("clients_per_sec_steady", "crawl_seconds_e2e"),
    "secure_crawl": (
        "secure_clients_per_sec", "ms_per_level_e2e", "secure_kernel",
        "whole_level_speedup_vs_pipelined",
        "sequential_clients_per_sec", "pipeline_speedup", "slo",
    ),
    # _PARTIAL's key for the same section (the partial-dump path)
    "secure": (
        "secure_clients_per_sec", "ms_per_level_e2e", "secure_kernel",
        "whole_level_speedup_vs_pipelined",
        "sequential_clients_per_sec", "pipeline_speedup", "slo",
    ),
    "secure_device": (
        "secure_device_clients_per_sec", "secure_device_ms_per_level_fe62",
    ),
    "hbm": ("projected_max_clients_one_chip_16gb",),
    "covid": ("covid_clients_per_sec",),
    "hash_margin": ("garble_ms_rounds_8",),
    "upload": ("upload_keys_per_sec",),
    "ingest": (
        "ingest_keys_per_sec", "concurrent_keys_per_sec", "windows",
        "shed", "rejected", "bit_identical_vs_batch", "slo",
    ),
    "multichip": (
        "secure_clients_per_sec", "data_shards", "ici_reduce_seconds",
        "bit_identical", "kernel_shards", "kernel_clients_per_sec",
        "kernel_gather_seconds", "whole_level_speedup_vs_gathered",
    ),
    "multitenant": (
        "aggregate_clients_per_sec", "aggregate_speedup_vs_solo",
        "solo_clients_per_sec", "stall_fill_ratio",
        "bit_identical_vs_solo",
    ),
    "sketch": (
        "malicious_overhead_vs_semi_honest", "sketch_clients_per_sec",
        "semi_honest_clients_per_sec", "bit_identical", "sketch_shards",
        "verify_seconds",
    ),
    "radix": (
        "level_rate_x_k", "speedup_vs_k1", "best_k", "bit_identical",
    ),
}


def _compact_extra(full_extra: dict) -> dict:
    """Headline scalars only — every section keyed by its full name with
    its acceptance-relevant numbers, plus error/skip markers, so the
    parsed line answers 'how fast / what failed' without the detail the
    full artifact (bench_full.json / first stdout line) carries."""
    out = {}
    for name, res in full_extra.items():
        if name in ("keygen_sweep", "reference_key_bytes"):
            continue
        if not isinstance(res, dict):
            out[name] = res
            continue
        if "skipped" in res or "error" in res:
            out[name] = {
                k: res[k] for k in ("skipped", "error") if k in res
            }
            continue
        keep = _COMPACT_KEYS.get(name, ())
        out[name] = {k: res[k] for k in keep if k in res}
    return out


def main(argv=None):
    global _OUT
    import argparse

    from fuzzyheavyhitters_tpu import obs

    ap = argparse.ArgumentParser(
        description="fuzzy-heavy-hitters benchmark suite"
    )
    ap.add_argument(
        "--out", default="bench_full.json",
        help="artifact path (written atomically after every leg)",
    )
    ap.add_argument(
        "--resume", action="store_true",
        help="skip legs already present in --out (skipped/errored legs "
             "rerun); a closed manifest resumes too",
    )
    ap.add_argument(
        "--sections", default=None,
        help="comma list of leg names to run; the rest report "
             '{"skipped": "sections"}',
    )
    ap.add_argument(
        "--force", action="store_true",
        help="overwrite a non-empty --out artifact (without this, an "
             "existing results file is refused unless --resume extends "
             "it)",
    )
    args = ap.parse_args(argv)
    _OUT = args.out
    if (
        not args.force
        and not args.resume
        and os.path.exists(_OUT)
        and os.path.getsize(_OUT) > 0
    ):
        ap.error(
            f"{_OUT} already holds results — pass --resume to extend "
            "it or --force to overwrite"
        )
    only = (
        {s.strip() for s in args.sections.split(",") if s.strip()}
        if args.sections
        else None
    )

    # one persistent compile cache shared by the parent and every child
    # section (the children inherit the env var): the per-bucket crawl
    # programs compile once per HLO, not once per subprocess — the
    # compile churn that pushed BENCH_r05 past its budget
    os.environ.setdefault(
        "FHH_COMPILE_CACHE",
        os.path.join(tempfile.gettempdir(), "fhh-compile-cache"),
    )
    _compile_cache.enable()
    _install_sigterm_partial()
    if args.resume:
        _PARTIAL.update(_load_resume(_OUT))
        if _PARTIAL:
            obs.emit(
                "bench.resume", path=_OUT,
                legs=sorted(
                    k for k in _PARTIAL
                    if k not in ("keygen_sweep", "keygen_headline")
                ),
            )
    rng = np.random.default_rng(0)
    if (
        args.resume
        and "keygen_sweep" in _PARTIAL
        and "keygen_headline" in _PARTIAL
    ):
        obs.emit("bench.leg", name="keygen", status="resume-skip")
        headline = float(_PARTIAL["keygen_headline"])
        sweep = _PARTIAL["keygen_sweep"]
    else:
        obs.emit("bench.leg", name="keygen", status="run")
        if BENCH_SMOKE:
            headline, sweep = bench_keygen_smoke(rng)
        else:
            import jax
            import jax.numpy as jnp

            from fuzzyheavyhitters_tpu.ops import ibdcf

            headline, sweep = bench_keygen(jax, jnp, ibdcf, rng)
        _PARTIAL["keygen_sweep"] = sweep
        _PARTIAL["keygen_headline"] = round(headline, 1)
        _write_leg_artifact()

    def section(name, code, timeout_s, smoke_code=None):
        """One subprocess section under the wall-clock budget: a section
        that cannot fit in the time left (reserve included) is skipped
        with a marker instead of risking the whole artifact.  Completed
        legs land in the artifact immediately (_write_leg_artifact); on
        --resume a leg already present (and not a skip/error marker)
        returns its recorded result without rerunning."""
        prev = _PARTIAL.get(name)
        if (
            args.resume
            and prev is not None
            and not (
                isinstance(prev, dict)
                and ("skipped" in prev or "error" in prev)
            )
        ):
            obs.emit("bench.leg", name=name, status="resume-skip")
            return prev
        if only is not None and name not in only:
            res = {"skipped": "sections"}
        elif BENCH_SMOKE and smoke_code is None:
            res = {"skipped": "smoke"}
        else:
            rem = _budget_left() - _BUDGET_RESERVE_S
            if rem < 60:
                res = {"skipped": "budget"}
            else:
                obs.emit("bench.leg", name=name, status="run")
                res = _subprocess_metric(
                    smoke_code if BENCH_SMOKE else code,
                    timeout_s=int(min(timeout_s, rem)),
                )
        _PARTIAL[name] = res
        _write_leg_artifact()
        return res

    # budget-trim order: the acceptance-critical secure sections run
    # right after the keygen headline; the long-tail crawl_hbm_max runs
    # LAST so a tight budget trims it first, not the headline metrics
    secure = section(
        "secure",
        "import json, bench;print(json.dumps(bench.bench_secure()))",
        # headroom for the FIRST round's warmup compiles (the per-bucket
        # ladder × both fields); later rounds hit FHH_COMPILE_CACHE
        timeout_s=720,
        smoke_code=(
            "import json, bench;"
            "print(json.dumps(bench.bench_secure(n=64, L=6, shard_nodes=1,"
            " pipeline_depth=3)))"
        ),
    )
    radix = section(
        "radix",
        "import json, bench;print(json.dumps(bench.bench_radix()))",
        # three warmed secure pairs (k = 1, 2, 3), each with its own
        # fused-shape warmup ladder; later runs hit FHH_COMPILE_CACHE
        timeout_s=900,
        smoke_code=(
            "import json, bench;"
            "print(json.dumps(bench.bench_radix(n=64, L=6)))"
        ),
    )
    multichip = section(
        "multichip",
        "import json, bench;print(json.dumps(bench.bench_multichip()))",
        # warmed legs: 1/2/4/8 data shards plus the kernel-sharded sweep
        # at the top count, each its own server pair with its own
        # sharded program ladder
        timeout_s=900,
        # f_max=32 trims one warmup-ladder rung per leg per field
        # (the zipf smoke frontier peaks at 28 survivors) — the smoke
        # budget must leave room for the ingest section after this;
        # n=512 puts every bucket-16 rung at 16384 tests = 2 planar
        # blocks, so the kernel-sharded legs engage (kernel_shards=2)
        # without depending on the borderline bucket-32 survivors
        smoke_code=(
            "import json, bench;"
            "print(json.dumps(bench.bench_multichip(n=512, L=5,"
            " shards=(1, 2, 4), f_max=32, kernel_shards=(1, 2))))"
        ),
    )
    sketch = section(
        "sketch",
        "import json, bench;print(json.dumps(bench.bench_sketch()))",
        # semi-honest reference + the sketch_shards sweep, each leg its
        # own warmed server pair (fused verify ladder via warmup)
        timeout_s=900,
        # smoke: trusted exchange keeps the compile load inside the
        # budget; the sketch lane (fused verify, sharded legs, both
        # gates) is identical either way
        smoke_code=(
            "import json, bench;"
            "print(json.dumps(bench.bench_sketch(n=64, L=6,"
            " shards=(1, 2), secure=False)))"
        ),
    )
    secure_device = section(
        "secure_device",
        "import json, bench;print(json.dumps(bench.bench_secure_device()))",
        # headroom for the contention-retry path (see bench_secure_device)
        timeout_s=1500,
    )
    crawl = section(
        "crawl",
        "import json, numpy as np, bench;"
        "from fuzzyheavyhitters_tpu.ops import ibdcf;"
        "from fuzzyheavyhitters_tpu.protocol import driver;"
        "print(json.dumps(bench.bench_crawl(ibdcf, driver,"
        " np.random.default_rng(0))))",
        timeout_s=540,
    )
    hbm = section(
        "hbm",
        "import json, bench;print(json.dumps(bench.bench_hbm()))",
        timeout_s=540,
    )
    covid = section(
        "covid",
        "import json, bench;print(json.dumps(bench.bench_covid()))",
        timeout_s=540,
    )
    hash_margin = section(
        "hash_margin",
        "import json, bench;print(json.dumps(bench.bench_hash_margin()))",
        timeout_s=540,
    )
    upload = section(
        "upload",
        "import json, bench;print(json.dumps(bench.bench_upload()))",
        timeout_s=540,
    )
    ingest = section(
        "ingest",
        "import json, bench;print(json.dumps(bench.bench_ingest()))",
        timeout_s=540,
        # smoke: tiny window pair, still concurrent + bit-identity-gated
        smoke_code=(
            "import json, bench;"
            "print(json.dumps(bench.bench_ingest(n=512, L=6, chunk=32,"
            " threshold=0.2)))"
        ),
    )
    multitenant = section(
        "multitenant",
        "import json, bench;print(json.dumps(bench.bench_multitenant()))",
        # 4 solo legs + the 2- and 4-tenant legs, each a fresh secure
        # server pair; warmup rides the shared WarmLadder + compile cache
        timeout_s=900,
        smoke_code=(
            "import json, bench;"
            "print(json.dumps(bench.bench_multitenant(n=64, L=6,"
            " tenant_counts=(1, 2), threshold=0.2)))"
        ),
    )
    crawl_hbm_max = section(
        "crawl_hbm_max",
        "import json, numpy as np, bench;"
        "print(json.dumps(bench.bench_crawl_hbm_max(np.random.default_rng(17))))",
        # a REAL 512-level run is ~10 min of crawl, but the one-time 8 GB
        # key fetch rides the tunnel's ~20-35 MB/s DOWNLOAD path (measured;
        # uploads do 200 MB/s) — budget for the slow-tunnel case
        timeout_s=2700,
    )
    try:
        # smoke mode must not clobber the tracked chip reference rows
        # with its tiny np-engine sweep (the CSV is the cross-round
        # keygen continuity artifact)
        if not BENCH_SMOKE:
            write_keygen_csv(sweep)
    except Exception:
        pass

    extra = {
        "keygen_sweep": sweep,
        "reference_key_bytes": BASELINE_KEY_BYTES,
        "crawl": crawl,
        "crawl_hbm_max": crawl_hbm_max,
        "secure_crawl": secure,
        "radix": radix,
        "multichip": multichip,
        "sketch": sketch,
        "secure_device": secure_device,
        "hbm": hbm,
        "covid": covid,
        "hash_margin": hash_margin,
        "upload": upload,
        "ingest": ingest,
        "multitenant": multitenant,
    }
    head = {
        "metric": "ibdcf_keygen_keys_per_sec_at_data_len_512",
        "value": round(headline, 1),
        "unit": "keys/s/chip",
        "vs_baseline": round(headline / BASELINE_KEYS_PER_SEC, 2),
    }
    if BENCH_SMOKE:
        head["metric"] = "ibdcf_keygen_keys_per_sec_smoke_np"
        head["vs_baseline"] = None
    budget_info = {
        "budget_s": BENCH_BUDGET_S,
        "elapsed_s": round(time.monotonic() - _BENCH_T0, 1),
        "smoke": BENCH_SMOKE,
    }
    full = dict(head, extra=extra, budget=budget_info)
    # full artifact: closing the manifest — the atomic rewrite replaces
    # the per-leg partial form (no "partial" key ever again) — plus the
    # first stdout line (for humans and transcripts); NOT the last line,
    # which must stay parseable
    _atomic_json(_OUT, full)
    print(json.dumps(full), flush=True)
    # the LAST stdout line is the machine contract: the harness keeps a
    # short tail, so it gets the compact form (headline + per-section
    # acceptance scalars), guaranteed to stay small
    print(
        json.dumps(dict(head, extra=_compact_extra(extra), budget=budget_info)),
        flush=True,
    )


if __name__ == "__main__":
    main()
