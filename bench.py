"""Headline benchmarks on the real chip.

Prints ONE JSON line.  Headline metric (continuity with rounds 1-2 and the
north star "client-keys/sec/chip at data_len=512"): ibDCF keygen
throughput vs the reference's single-threaded AES-NI baseline
(99.97 µs/key, src/bin/benchmarks/ibDCFbench.csv:5, BASELINE.md).  The
``extra`` field carries the rest of the reference's benchmark surface:

- the full keygen sweep data_len ∈ {64, 256, 512, 1024} with per-key wire
  bytes (the ibDCFbench.rs:55-70 sweep + bincode size column);
- ``aggregate_clients_per_sec``: the SERVER hot loop — a full
  data_len=512 trusted-mode crawl (expand -> exchange -> count ->
  threshold -> prune/advance per level) over N clients on one chip;
- ``secure_crawl``: the same loop with the REAL GC+OT data plane between
  two in-process collector servers over localhost sockets (e2e — through
  the remote-chip tunnel this is floored by ~0.12 s per device<->host
  round trip, see ``secure_device`` for the deployment-shape number);
- ``secure_device``: the whole per-level 2PC as one on-chip program (the
  1-chip stand-in for the 2-chip mesh deployment);
- ``hbm``: the 1M-client HBM plan VALIDATED by allocation — the L=512
  key batch at the largest bench N actually lives on the chip, 3 levels
  run, and bytes/client are measured, not derived;
- ``hash_margin``: measured garbling cost at ChaCha rounds 8/12/20 (the
  margin note in ops/prg.py cites these);
- ``upload``: 1M-key control-plane ingest through the rolling window.
"""

import json
import time

import numpy as np

from fuzzyheavyhitters_tpu.ops import prg as _prg

# bench targets the real chip: unrolled ChaCha rounds are ~6% faster there
# (the scan form is the compile-friendly default for test hosts, ops/prg.py)
_prg.CHACHA_UNROLL = True

BASELINE_US_PER_KEY = {64: None, 128: 25.92, 256: 50.47, 512: 99.97, 1024: 216.25}
BASELINE_KEYS_PER_SEC = 1e6 / 99.97  # ibDCFbench.csv:5 (data_len=512)
# reference per-key wire bytes (bincode), ibDCFbench.csv
BASELINE_KEY_BYTES = {128: 2585, 256: 5145, 512: 10265, 1024: 20505}


def _keygen_engine() -> str:
    """Fused Pallas kernel on a real chip; the host NumPy mirror elsewhere
    (no Mosaic on XLA:CPU — and the jax scan engine compiles pathologically
    there, see tests/conftest.py)."""
    from fuzzyheavyhitters_tpu.ops import ibdcf

    return ibdcf.best_engine()


def _key_wire_bytes(k0) -> int:
    """Per-key bytes of our wire format (one key = one (client, dim, side)
    slice of the batch; cf. the reference's bincode size probe,
    ibDCFbench.rs:67)."""
    per = 0
    for leaf in k0:
        a = np.asarray(leaf)
        per += a[0].nbytes if a.ndim else a.nbytes
    return per


def _time_of(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _steady_state_seconds(thunk, force, warm_force, iters=20, trials=3):
    """Min-of-trials per-launch seconds for a device thunk.

    Queues ``iters`` launches and forces them with ONE sync whose value
    depends on every launch (``force`` maps the list of outputs to a host
    int).  A per-iteration scalar fetch adds a full tunnel round trip to
    each measurement (~100 ms — 3x the kernel itself at bench sizes); a
    bare block_until_ready through the tunnel returns before the device
    finishes.  The dependent sync is honest and amortized; the MIN over
    trials strips the tunnel's additive queueing noise (which otherwise
    swings results 3-5x)."""
    warm_force(thunk())  # compile + warm
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        force([thunk() for _ in range(iters)])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _throughput(jnp, gen, seeds_d, alpha_d, side_d, n, iters=20, trials=3):
    """Steady-state keygen keys/sec (see _steady_state_seconds)."""
    k0, _ = gen(seeds_d, alpha_d, side_d)
    best = _steady_state_seconds(
        lambda: gen(seeds_d, alpha_d, side_d)[0],
        lambda outs: int(
            sum(jnp.sum(o.cw_seed[0, 0, 0].astype(jnp.uint32)) for o in outs)
        ),
        lambda k: int(jnp.sum(k.cw_seed.astype(jnp.uint32))),
        iters=iters,
        trials=trials,
    )
    return n / best, k0


def bench_keygen(jax, jnp, ibdcf, rng, sweep=(64, 128, 256, 512, 1024), n=8192):
    from fuzzyheavyhitters_tpu.ops.keygen_pallas import gen_pair_pallas

    rows = {}
    headline = None
    for L in sweep:
        alpha = rng.integers(0, 2, size=(n, L)).astype(bool)
        seeds = rng.integers(0, 2**32, size=(n, 2, 4), dtype=np.uint32)
        side = np.ones(n, bool)
        alpha_d, seeds_d, side_d = map(jax.device_put, (alpha, seeds, side))

        keys_per_sec, k0 = _throughput(
            jnp, gen_pair_pallas, seeds_d, alpha_d, side_d, n,
            iters=64,  # deep queue: amortize the end-of-batch fetch RTT
            trials=6 if L == 512 else 3,  # headline: more min-of-trials
            # insurance against the tunnel's cross-run queueing variance
        )
        base = BASELINE_US_PER_KEY.get(L)
        rows[L] = {
            "keys_per_sec": round(keys_per_sec, 1),
            "us_per_key": round(1e6 / keys_per_sec, 3),
            "key_bytes": _key_wire_bytes(k0),
            "vs_baseline": round(keys_per_sec / (1e6 / base), 2) if base else None,
        }
        if L == 512:  # headline size: also compare the scan engine (each
            # extra engine compile costs ~30 s through the tunnel)
            scan_kps, _ = _throughput(
                jnp, ibdcf.gen_pair, seeds_d, alpha_d, side_d, n, iters=5
            )
            rows[L]["scan_engine_keys_per_sec"] = round(scan_kps, 1)
            headline = keys_per_sec
    return headline, rows


def write_keygen_csv(rows: dict, n: int, path: str = "ibDCFbench_tpu.csv"):
    """Emit the sweep in the shape of the reference's one shipped benchmark
    artifact (ibDCFbench.rs:57-68 -> ibDCFbench.csv: string_length,
    number_keys, time, avg_time, size)."""
    with open(path, "w") as f:
        f.write("string_length,number_keys,time,avg_time,size\n")
        for L in sorted(rows):
            r = rows[L]
            avg = 1.0 / r["keys_per_sec"]
            f.write(f"{L},{n},{avg * n},{avg},{r['key_bytes']}\n")


def bench_crawl(ibdcf, driver, rng, n=131072, L=512, f_max=64):
    """Server hot loop: full L-level trusted-mode crawl on one chip.

    Zipf-like scenario: clients cluster on a handful of sites so the
    frontier stays small (the production regime) while every level still
    expands/compares all N clients.  Round-4 shape of the measurement:

    - the frontier is BUCKETED (collect.bucket_for) and advance is a
      gather from the expand-time child cache — per-level work is sized
      to survivors, with no second PRG pass;
    - N = 131072 so per-level COMPUTE dominates the tunnel's per-dispatch
      floor (~2 ms/launch; at the old N=8192 that floor was most of the
      measured "device" time, silently inflating the 1M projection 16x
      more than compute justifies);
    - the level pipeline is ONE jitted program (both servers' expand +
      counts + both advances), matching the production mesh path where
      counts_body is a single XLA dispatch per level (parallel/mesh.py).
    """
    n_sites = 4
    sites = rng.integers(0, 2, size=(n_sites, 1, L)).astype(bool)
    pts_bits = sites[rng.integers(0, n_sites, size=n)]
    # keygen on the chip (the fused kernel): host NumPy keygen for 512-bit
    # interval pairs at this N takes hours on a 1-core host
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())

    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.protocol import collect

    timed_levels = min(64, L)

    def run_slice(levels):
        s0, s1 = driver.make_servers(k0, k1)
        lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=f_max)
        lead.tree_init()
        t0 = time.perf_counter()
        for lvl in range(levels):
            n_alive = lead.run_level(lvl, nreqs=n, threshold=0.05)
            assert n_alive >= 1  # early levels hold few nodes (2^level caps)
        return time.perf_counter() - t0, n_alive, s0, s1

    def measure_engine():
        """Steady-state per-level seconds under the CURRENT engine knob.

        Warm slice compiles every bucket size of the steady crawl
        (1 -> 2 -> 4 ... as the sites' prefixes separate); the second,
        timed, slice replays the same buckets; then the device-only level
        pipeline runs on the steady-state frontier the slice left behind
        (idempotent: same inputs each launch) — ONE fused program covering
        BOTH servers, so the per-server cost is half of this.
        """
        run_slice(timed_levels)
        dt_slice, n_alive, s0, s1 = run_slice(timed_levels)
        # by level 64 the 4 random sites' prefixes are distinct w.h.p.,
        # and each survives with its ball neighbours
        assert n_alive >= n_sites
        masks = jnp.asarray(collect.pattern_masks(1))
        alive = jnp.asarray(s0.alive_keys)
        nb = collect.bucket_for(n_alive, f_max)
        parent = jnp.zeros(nb, jnp.int32)
        pat = jnp.zeros((nb, 1), bool)

        @jax.jit
        def one_level(keys0, f0, keys1, f1, lvl):
            p0, ch0 = collect.expand_share_bits(keys0, f0, lvl)
            p1, ch1 = collect.expand_share_bits(keys1, f1, lvl)
            cnt = collect.counts_by_pattern(p0, p1, masks, alive, f0.alive)
            nf0 = collect.advance_from_children(ch0, parent, pat, n_alive)
            nf1 = collect.advance_from_children(ch1, parent, pat, n_alive)
            return cnt, nf0, nf1

        # 64 queued launches per sync: the tunnel's end-of-batch fetch
        # costs a full round trip (~150 ms) — at 16 launches that RTT was
        # ~10 ms/level of pure measurement artifact
        best = _steady_state_seconds(
            lambda: one_level(s0.keys, s0.frontier, s1.keys, s1.frontier,
                              timed_levels),
            lambda outs: int(sum(jnp.sum(c[0, 0]) for c, _, _ in outs)),
            lambda o: int(jnp.sum(o[0])),
            iters=64,
        )
        return best, dt_slice, s0.frontier.f_bucket

    # back-to-back engine A/B (the only meaningful comparison on the
    # shared chip, whose throughput swings ~4x by hour): the XLA engine
    # first, then the pack-in-kernel Pallas engine — the default — last,
    # so the headline numbers come from the default engine's run.  On a
    # CPU-only host both knob settings resolve to the XLA engine
    # (collect._expand_engine), so the A/B would compare a thing to
    # itself — skip it and report one engine.
    default_engine = collect.EXPAND_PALLAS
    collect.EXPAND_PALLAS = True
    two_engines = collect._expand_engine()
    try:
        if two_engines:
            collect.EXPAND_PALLAS = False
            best_xla, _, _ = measure_engine()
            collect.EXPAND_PALLAS = True
        best, dt_slice, f_bucket = measure_engine()
    finally:
        collect.EXPAND_PALLAS = default_engine
    dt = best * L
    ab = (
        {
            "ms_per_level_device_xla_engine": round(best_xla * 1000, 3),
            "engine_speedup_vs_xla": round(best_xla / best, 2),
        }
        if two_engines
        else {}
    )
    return {
        "aggregate_clients_per_sec": round(n / dt, 1),
        "crawl_seconds_device": round(dt, 3),
        "ms_per_level_device": round(best * 1000, 3),
        **ab,
        "ms_per_level_e2e_tunnel": round(dt_slice / timed_levels * 1000, 2),
        "timed_levels_e2e": timed_levels,
        "n_clients": n,
        "data_len": L,
        "f_bucket_steady": int(f_bucket),
        "levels_per_sec": round(L / dt, 2),
        "projected_1m_clients_seconds_1chip": round(dt * (1_000_000 / n), 1),
    }



async def _bring_up_pair(cfg, port):
    """Two collector servers + leader-side clients in this process:
    s1 first (it listens on the data plane at port+11), then s0 dials —
    the reference's startup ordering (server.rs:344-354).  Returns
    (leader, c0, c1) with both servers reset."""
    import asyncio

    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader

    s0 = rpc.CollectorServer(0, cfg)
    s1 = rpc.CollectorServer(1, cfg)
    t1 = asyncio.create_task(
        s1.start("127.0.0.1", port + 10, "127.0.0.1", port + 11)
    )
    await asyncio.sleep(0.05)
    t0 = asyncio.create_task(s0.start("127.0.0.1", port, "127.0.0.1", port + 11))
    c0 = await rpc.CollectorClient.connect("127.0.0.1", port)
    c1 = await rpc.CollectorClient.connect("127.0.0.1", port + 10)
    await asyncio.gather(t0, t1)
    lead = RpcLeader(cfg, c0, c1)
    await asyncio.gather(c0.call("reset"), c1.call("reset"))
    return lead, c0, c1, s0, s1


def bench_secure(n=1024, L=12, port=39831):
    """Secure-mode aggregate crawl: both collector servers in one process
    with the REAL GC+OT data plane (secure_exchange=true), full level loop
    over localhost sockets on the default device.  End-to-end wall time —
    floored by ~6 serial device<->host fetches per level at the reported
    ``device_fetch_rtt_ms`` (the tunnel's ~0.12 s), so it is a lower bound
    on what adjacent hardware achieves; ``bench_secure_device`` is the
    adjacent-chip number.  Batch amortization measured at n=8192: 146
    clients/s (2.4x this config's rate) before payload transfer costs
    take over.  Ref seam: collect.rs:419-482 inside tree_crawl."""
    import asyncio
    import contextlib
    import io

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(3)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = (
        ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    )  # [n, 1, L] MSB-first
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())

    cfg = Config(
        data_len=L, n_dims=1, ball_size=2, addkey_batch_size=1024,
        num_sites=8, threshold=0.05, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=64, secure_exchange=True,
    )

    async def run():
        lead, c0, c1, s0, _ = await _bring_up_pair(cfg, port)
        await lead.upload_keys(k0, k1)
        res = await lead.run(n)  # warm: compiles every secure program
        assert res.paths.shape[0] >= 1
        await asyncio.gather(c0.call("reset"), c1.call("reset"))
        await lead.upload_keys(k0, k1)
        t = time.perf_counter()
        res = await lead.run(n)
        dt = time.perf_counter() - t
        return dt, int(res.paths.shape[0]), int(s0._gc_tests), list(s0._phase_seconds)

    with contextlib.redirect_stdout(io.StringIO()):  # phase-timer prints
        dt, hitters, gc_tests, phases = asyncio.run(run())
    fss, gcot, fld = (round(p, 3) for p in phases)
    # the e2e floor: every device->host fetch in the serial 2PC message
    # flow costs one of these (≈6 per level after round-4's packing)
    import jax.numpy as jnp

    a = jnp.zeros(4, jnp.uint32) + 1
    np.asarray(a)  # warm
    rtt = min(
        _time_of(lambda: np.asarray(a + i)) for i in range(3)
    )
    return {
        "secure_clients_per_sec": round(n / dt, 1),
        "secure_crawl_seconds": round(dt, 3),
        "n_clients": n,
        "data_len": L,
        "ms_per_level_e2e": round(dt / L * 1000, 2),
        "hitters": hitters,
        # measured equality tests of the timed run (batches are sized to
        # the live frontier bucket, not f_max)
        "gc_tests_per_level": round(gc_tests / L, 1),
        # server-0 accumulated 3-phase split (ref taxonomy,
        # collect.rs:412-503); remainder vs secure_crawl_seconds is
        # control-plane + pickling + event-loop time
        "phase_fss_seconds": fss,
        "phase_gc_ot_seconds": gcot,
        "phase_field_seconds": fld,
        "device_fetch_rtt_ms": round(rtt * 1000, 1),
    }


def bench_secure_device(n=1024, L=12, f_bucket=16):
    """Device-resident secure-crawl measurement: the WHOLE per-level 2PC —
    both parties' expand, label extension, garbling, evaluation, b2a,
    alive-gated share sums — as ONE jitted program on one chip, with the
    four data-plane messages as in-program values.

    This is the 1-chip stand-in for the 2-chip mesh deployment
    (parallel/mesh.py runs the identical math with the messages as
    ``ppermute`` transfers): it measures what the 2PC costs where the
    north star runs it — chips adjacent to the servers — while
    ``bench_secure`` measures the socket e2e, which through the remote
    - chip tunnel is floored by ~0.12 s per device<->host round trip
    (8-10 of them per level), not by the protocol."""
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import baseot, gc, ibdcf, otext
    from fuzzyheavyhitters_tpu.ops.fields import F255, FE62
    from fuzzyheavyhitters_tpu.protocol import collect, secure

    rng = np.random.default_rng(3)
    sites = rng.integers(0, 1 << L, size=8)
    pts = sites[rng.integers(0, 8, size=n)]
    pts_bits = ((pts[:, None, None] >> np.arange(L - 1, -1, -1)) & 1) > 0
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())
    d = 1
    C, S = 1 << d, 2 * d
    B = f_bucket * C * n
    m = B * S

    # steady-state frontier shape: f_bucket slots (root states replicated;
    # the 2PC math is state-value-independent), all nodes+keys live so the
    # gating work is fully exercised
    f0 = collect.tree_init(k0, f_bucket)._replace(alive=jnp.ones(f_bucket, bool))
    f1 = collect.tree_init(k1, f_bucket)._replace(alive=jnp.ones(f_bucket, bool))
    alive_keys = jnp.ones(n, bool)
    w = jnp.asarray(secure.alive_weight(np.ones(f_bucket, bool), np.ones(n, bool), C))

    s_bits = otext.fresh_s_bits()
    seeds0, seeds1, chosen = baseot.exchange(s_bits)
    s_bits_d = jnp.asarray(s_bits.astype(np.uint32))
    sm_snd = jnp.asarray(chosen.astype(np.uint32))
    sm_rcv = jnp.asarray(seeds0.astype(np.uint32))
    sa_rcv = jnp.asarray(seeds1.astype(np.uint32))
    gseed = jnp.asarray(np.frombuffer(b"bench-gc-seed..!", "<u4").copy())
    bseed = jnp.asarray(np.frombuffer(b"bench-b2aseed.!!", "<u4").copy())
    derived = _prg.DERIVED_BITS

    def level_fn(field):
        limb = field.limb_shape

        @jax.jit
        def run(keys0, fr0, keys1, fr1, lvl):
            p0, _ = collect.expand_share_bits(keys0, fr0, lvl, want_children=False)
            p1, _ = collect.expand_share_bits(keys1, fr1, lvl, want_children=False)
            flat0 = secure.child_strings(p0, d).reshape(B, S)  # garbler x
            flat1 = secure.child_strings(p1, d).reshape(B, S)  # evaluator y
            off = jnp.uint32(0)
            u, t_rows = otext._receiver_extend(
                sm_rcv, sa_rcv, flat1.reshape(m), off, m
            )
            q = otext._sender_extend(sm_snd, s_bits_d, u, off, m)
            s_block = otext.pack_bits(s_bits_d)
            batch, mask = gc.garble_equality_delta(
                s_block, q.reshape(B, S, 4), gseed, flat0
            )
            e = gc.eval_equality(batch, t_rows.reshape(B, S, 4))
            w_cols = -(-m // 32)
            off2 = off + (-(-w_cols // 16))
            u2, t2_rows = otext._receiver_extend(sm_rcv, sa_rcv, e, off2, B)
            q2 = otext._sender_extend(sm_snd, s_bits_d, u2, off2, B)
            idx0 = m
            c0, c1, r1 = secure.b2a_encrypt(
                field, q2, s_block, mask, bseed, idx0
            )
            v1 = secure.b2a_decrypt(field, t2_rows, idx0, c0, c1, e)
            sh0 = secure.node_share_sums(
                field, r1.reshape((f_bucket, C, n) + limb), w
            )
            sh1 = secure.node_share_sums(
                field, v1.reshape((f_bucket, C, n) + limb), w
            )
            return sh0, sh1

        return run

    import jax.numpy as jnp  # noqa: F811

    results = {}
    for name, field in (("fe62", FE62), ("f255", F255)):
        run = level_fn(field)
        # correctness pin: reconstructed counts == trusted compare
        sh0, sh1 = run(k0, f0, k1, f1, 0)
        v = np.asarray(field.canon(field.sub(sh0, sh1)))
        counts = v[..., 0] if field is F255 else v
        masks = collect.pattern_masks(d)
        p0, _ = collect.expand_share_bits(k0, f0, 0)
        p1, _ = collect.expand_share_bits(k1, f1, 0)
        want = np.asarray(collect.counts_by_pattern(
            p0, p1, jnp.asarray(masks), alive_keys, jnp.ones(f_bucket, bool)
        ))
        assert np.array_equal(counts.astype(np.uint64), want.astype(np.uint64))
        best = _steady_state_seconds(
            lambda: run(k0, f0, k1, f1, 0),
            lambda outs: int(sum(jnp.sum(jnp.asarray(o[0])[0, 0]) for o in outs)),
            lambda o: int(jnp.sum(jnp.asarray(o[0])[0, 0])),
            iters=32,
        )
        results[name] = best
    total = results["fe62"] * (L - 1) + results["f255"]
    return {
        "secure_device_clients_per_sec": round(n / total, 1),
        "secure_device_ms_per_level_fe62": round(results["fe62"] * 1000, 3),
        "secure_device_ms_per_level_f255": round(results["f255"] * 1000, 3),
        "secure_device_crawl_seconds": round(total, 3),
        "n_clients": n,
        "data_len": L,
        "f_bucket": f_bucket,
        "gc_tests_per_level": B,
    }


def bench_hbm(n=196608, L=512, levels=3, f_max=64):
    """HBM scale validation: ACTUALLY allocate the L=512 key batch for the
    largest N this bench holds on one chip (both servers' batches — the
    1-chip driver shape, so one server's real footprint is half), run 3
    crawl levels on it, and report measured bytes — replacing the round-3
    plan that was arithmetic, not a measurement."""
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import driver

    rng = np.random.default_rng(0)
    sites = rng.integers(0, 2, size=(4, 1, L)).astype(bool)
    pts_bits = sites[rng.integers(0, 4, size=n)]
    k0, k1 = ibdcf.gen_l_inf_ball(pts_bits, 2, rng, engine=_keygen_engine())
    jax.block_until_ready(k0.cw_seed)
    key_bytes = sum(
        leaf.nbytes for k in (k0, k1) for leaf in jax.tree.leaves(k)
    )
    per_client_per_server = key_bytes / 2 / n
    s0, s1 = driver.make_servers(k0, k1)
    lead = driver.Leader(s0, s1, n_dims=1, data_len=L, f_max=f_max)
    lead.tree_init()
    for lvl in range(levels):  # warm (compiles the small-bucket shapes)
        lead.run_level(lvl, nreqs=n, threshold=0.05)
    lead.tree_init()
    t0 = time.perf_counter()
    for lvl in range(levels):
        n_alive = lead.run_level(lvl, nreqs=n, threshold=0.05)
    dt = time.perf_counter() - t0
    assert n_alive >= 1
    # one v5e chip has 16 GB; leave 15% headroom for transients
    max_n_one_server = int(16e9 * 0.85 / per_client_per_server)
    return {
        "n_clients_allocated": n,
        "levels_run": levels,
        "key_gbytes_on_chip_both_servers": round(key_bytes / 1e9, 2),
        "measured_key_bytes_per_client_per_server": round(
            per_client_per_server, 1
        ),
        "ms_per_level_e2e": round(dt / levels * 1000, 2),
        "projected_max_clients_one_chip_16gb": max_n_one_server,
        "chips_for_1m_clients_keys": round(1e6 / max_n_one_server, 2),
    }


def bench_hash_margin(B=131072, S=2):
    """Measured cost of the ChaCha round count in the GC hash role (the
    correlation-robust hash of garbling; ops/prg.py N_ROUNDS note): one
    garble of a [B, S] equality batch at 8 / 12 / 20 rounds."""
    import secrets as pysecrets

    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import gc, prg

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2, size=(B, S)).astype(bool))
    y0 = jnp.asarray(rng.integers(0, 2**32, size=(B, S, 4), dtype=np.uint32))
    s_block = jnp.asarray(
        rng.integers(0, 2**32, size=4, dtype=np.uint32)
    )
    seed = jnp.asarray(np.frombuffer(pysecrets.token_bytes(16), "<u4").copy())
    out = {"gc_batch": B * S}
    for rounds in (8, 12, 20):
        prg.N_ROUNDS = rounds
        jax.clear_caches()  # N_ROUNDS is read at trace time
        best = _steady_state_seconds(
            lambda: gc.garble_equality_delta(s_block, y0, seed, x)[0].tables,
            lambda outs: int(sum(jnp.sum(o[0, 0]) for o in outs)),
            lambda o: int(jnp.sum(o[0, 0])),
            iters=32,
        )
        out[f"garble_ms_rounds_{rounds}"] = round(best * 1000, 3)
    prg.N_ROUNDS = 8
    jax.clear_caches()
    return out


def bench_upload(n=1_000_000, L=16, batch=4000, port=39731):
    """1M-key ingest benchmark: leader -> two servers over localhost TCP
    with the ROLLING upload window (leader_rpc.upload_keys; ref:
    leader.rs:340-364's 1000 in-flight batches).  Host-side only —
    add_keys appends buffers; the device sees keys once at tree_init."""
    import asyncio

    from fuzzyheavyhitters_tpu.ops import ibdcf
    from fuzzyheavyhitters_tpu.protocol import rpc
    from fuzzyheavyhitters_tpu.protocol.leader_rpc import RpcLeader
    from fuzzyheavyhitters_tpu.utils.config import Config

    rng = np.random.default_rng(1)
    alpha = rng.integers(0, 2, size=(n, 1, 2, L)).astype(bool)
    seeds = rng.integers(0, 2**32, size=(n, 1, 2, 2, 4), dtype=np.uint32)
    side = np.broadcast_to(np.array([True, False]), (n, 1, 2))
    # HOST keygen on purpose: this bench measures control-plane ingest, and
    # the keys must be host-resident contiguous buffers (client-axis chunk
    # slices then pickle zero-copy).  Measured: chip keygen + tunnel fetch
    # yields NON-contiguous leaves whose chunks copy on every pickle
    # (368 MB/s vs 2.8 GB/s), and at L=16 the fetch alone dwarfs host
    # keygen time.
    k0, k1 = ibdcf.gen_pair_np(seeds, alpha, side)

    cfg = Config(
        data_len=L, n_dims=1, ball_size=1, addkey_batch_size=batch,
        num_sites=4, threshold=0.1, zipf_exponent=1.03,
        server0=f"127.0.0.1:{port}", server1=f"127.0.0.1:{port + 10}",
        distribution="zipf", f_max=32,
    )

    async def run():
        lead, c0, c1, _, _ = await _bring_up_pair(cfg, port)
        t = time.perf_counter()
        await lead.upload_keys(k0, k1)
        return time.perf_counter() - t

    dt = asyncio.run(run())
    # _key_wire_bytes slices only the client axis, so for these [n, 1, 2]
    # interval batches it already covers both sides = one server's payload
    per_key_bytes = _key_wire_bytes(k0)
    return {
        "upload_keys_per_sec": round(n / dt, 1),
        "upload_seconds": round(dt, 3),
        "n_keys": n,
        "addkey_batch_size": batch,
        "approx_mb_per_sec": round(n * per_key_bytes / dt / 1e6, 1),
    }


def _subprocess_metric(code: str, timeout_s: int):
    """Run one benchmark in a child process with a hard timeout so a
    stalled accelerator tunnel (or a hung socket loop) can never take down
    the whole bench run — the keygen headline must always print."""
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=timeout_s,
            text=True,
            cwd=__file__.rsplit("/", 1)[0],
        )
        lines = out.stdout.strip().splitlines()
        if not lines:  # child died before printing — surface its stderr
            tail = (out.stderr or "").strip().splitlines()[-3:]
            return {"error": f"child rc={out.returncode}: " + " | ".join(tail)}
        return json.loads(lines[-1])
    except Exception as e:  # timeout, crash, parse failure
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def main():
    import jax
    import jax.numpy as jnp

    from fuzzyheavyhitters_tpu.ops import ibdcf

    rng = np.random.default_rng(0)
    headline, sweep = bench_keygen(jax, jnp, ibdcf, rng)
    crawl = _subprocess_metric(
        "import json, numpy as np, bench;"
        "from fuzzyheavyhitters_tpu.ops import ibdcf;"
        "from fuzzyheavyhitters_tpu.protocol import driver;"
        "print(json.dumps(bench.bench_crawl(ibdcf, driver,"
        " np.random.default_rng(0))))",
        timeout_s=540,
    )
    secure = _subprocess_metric(
        "import json, bench;"
        "print(json.dumps(bench.bench_secure()))",
        timeout_s=540,
    )
    secure_device = _subprocess_metric(
        "import json, bench;"
        "print(json.dumps(bench.bench_secure_device()))",
        timeout_s=540,
    )
    hbm = _subprocess_metric(
        "import json, bench;"
        "print(json.dumps(bench.bench_hbm()))",
        timeout_s=540,
    )
    hash_margin = _subprocess_metric(
        "import json, bench;"
        "print(json.dumps(bench.bench_hash_margin()))",
        timeout_s=540,
    )
    upload = _subprocess_metric(
        "import json, bench;"
        "print(json.dumps(bench.bench_upload()))",
        timeout_s=540,
    )
    try:
        write_keygen_csv(sweep, 8192)
    except Exception:
        pass

    print(
        json.dumps(
            {
                "metric": "ibdcf_keygen_keys_per_sec_at_data_len_512",
                "value": round(headline, 1),
                "unit": "keys/s/chip",
                "vs_baseline": round(headline / BASELINE_KEYS_PER_SEC, 2),
                "extra": {
                    "keygen_sweep": sweep,
                    "reference_key_bytes": BASELINE_KEY_BYTES,
                    "crawl": crawl,
                    "secure_crawl": secure,
                    "secure_device": secure_device,
                    "hbm": hbm,
                    "hash_margin": hash_margin,
                    "upload": upload,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
