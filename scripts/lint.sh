#!/usr/bin/env bash
# fhh-lint strict run with a machine-readable artifact.
#
# Usage: scripts/lint.sh [artifact.json]
#   - exits 0 iff the tree has ZERO non-baselined findings (any severity)
#   - writes the JSON report to $1 (default: lint_report.json)
#
# The same check runs inside tier-1 via tests/test_analysis.py's self-lint
# test; this script is the standalone/CI entry point.
set -euo pipefail
cd "$(dirname "$0")/.."

artifact="${1:-lint_report.json}"

rc=0
python -m fuzzyheavyhitters_tpu.analysis \
    fuzzyheavyhitters_tpu tests \
    --strict --format json > "$artifact" || rc=$?

python - "$artifact" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
# the artifact must prove the interprocedural fhh-race AND fhh-taint
# passes ran (the rule list is part of the report schema exactly for
# this assert)
race = {"guarded-state-unlocked", "stale-read-across-await"}
taint = {"secret-to-sink-flow", "secret-branch", "unmasked-wire"}
missing = (race | taint) - set(doc.get("rules", []))
if missing:
    print(f"fhh-lint: interprocedural pass MISSING from artifact: {sorted(missing)}")
    sys.exit(1)
print(
    f"fhh-lint: {len(doc['findings'])} new, "
    f"{doc['baselined']} baselined, "
    f"{len(doc['stale_baseline'])} stale baseline entries, "
    f"fhh-race + fhh-taint passes active "
    f"-> {sys.argv[1]}"
)
EOF
exit $rc
