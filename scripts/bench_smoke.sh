#!/usr/bin/env bash
# Budgeted bench smoke: the CI guard for the bench output contract.
#
# Usage: scripts/bench_smoke.sh [budget_seconds]
#   - runs `python bench.py` in SMOKE mode (FHH_BENCH_SMOKE=1: tiny
#     CPU-safe shapes — np-engine keygen + a small pipelined secure
#     crawl with its sequential bit-identity assertion, the streaming
#     ingest pair, and the multichip sharded legs on the 8-device
#     virtual mesh; the heavyweight chip sections report
#     {"skipped": "smoke"}) under a wall-clock budget
#     (FHH_BENCH_BUDGET, default 600 s)
#   - FAILS unless the bench exits rc=0 AND its last stdout line is
#     parseable JSON carrying the headline metric — exactly what the
#     harness needs (BENCH_r04 printed an oversized line that parsed as
#     null; BENCH_r05 timed out with no line at all; both fail here)
#   - also asserts the line stays under the harness's ~2000-byte stdout
#     tail capture
set -uo pipefail
cd "$(dirname "$0")/.."

budget="${1:-600}"
out="$(mktemp)"

# distributed tracing on for the whole smoke run: every bench child
# process appends to its own ring under $trace_dir, and the merged
# Perfetto trace must VALIDATE afterwards (fhh-trace structural gate)
trace_dir="$(mktemp -d)"
export FHH_TRACE_DIR="$trace_dir"

# 8 virtual host devices so the multichip section's 2- and 4-shard legs
# run on a CPU host (same mesh the tier-1 suite exercises);
# optimization_level=1 sidesteps XLA:CPU's pathological ChaCha-scan pass
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8 --xla_backend_optimization_level=1"
fi

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" FHH_BENCH_SMOKE=1 \
    FHH_BENCH_BUDGET="$budget" \
    timeout -k 10 "$((budget + 60))" python bench.py > "$out" 2> "$out.err"
rc=$?
if [ $rc -ne 0 ]; then
    echo "bench_smoke: bench.py exited rc=$rc" >&2
    tail -5 "$out.err" >&2
    rm -f "$out" "$out.err"
    rm -rf "$trace_dir"
    exit 1
fi

# merged trace must load AND validate: every parented event's parent
# exists, no negative durations, clock offsets sane (obs/trace.py)
if ! python -m fuzzyheavyhitters_tpu.obs.trace merge \
        -d "$trace_dir" -o "$trace_dir/trace.json" > "$trace_dir/verdict.json"
then
    echo "bench_smoke: merged fhh-trace FAILED validation" >&2
    tail -20 "$trace_dir/verdict.json" >&2
    rm -f "$out" "$out.err"; rm -rf "$trace_dir"
    exit 1
fi
if ! python - "$trace_dir/verdict.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v["ok"], v["errors"][:3]
assert v["spans"] > 0, "tracing was on but no spans were recorded"
assert v["traces"], "no trace ids minted (leaders should mint per crawl)"
print(
    f"bench_smoke trace OK: {v['spans']} spans, "
    f"{len(v['traces'])} traces, components={v['components'][:6]}"
)
EOF
then
    echo "bench_smoke: trace verdict assertions FAILED" >&2
    rm -f "$out" "$out.err"; rm -rf "$trace_dir"
    exit 1
fi

python - "$out" <<'EOF'
import json, sys

lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
assert lines, "bench printed nothing"
last = lines[-1]
assert len(last) < 2000, (
    f"final JSON line is {len(last)} bytes — exceeds the harness's "
    "stdout tail capture and would parse as null"
)
doc = json.loads(last)
assert "metric" in doc and doc.get("value") is not None, doc
sc = doc.get("extra", {}).get("secure_crawl", {})
assert "secure_clients_per_sec" in sc, (
    "secure_crawl section missing from the compact line: " + last[:300]
)
sk = sc.get("secure_kernel", {})
assert "ot_path" in sk and all(
    f"phase_{p}_seconds" in sk for p in ("otext", "garble", "eval", "b2a")
), (
    "secure_kernel phase split (phase_otext/garble/eval/b2a + ot_path) "
    "missing from the compact line: " + last[:300]
)
slo = sc.get("slo", {})
assert slo.get("level_p95_ms") is not None, (
    "secure_crawl slo (p95 per-level latency, obs.hist histograms) "
    "missing from the compact line: " + last[:300]
)
ing = doc.get("extra", {}).get("ingest", {})
assert "ingest_keys_per_sec" in ing and ing.get("bit_identical_vs_batch"), (
    "ingest section (streaming front door: keys/sec + batch bit-identity) "
    "missing from the compact line: " + last[:300]
)
islo = ing.get("slo", {})
assert islo.get("seal_to_hitters_p95_s") is not None, (
    "ingest slo (seal-to-hitters p95 — the windowed SLO headline) "
    "missing from the compact line: " + last[:300]
)
mc = doc.get("extra", {}).get("multichip", {})
assert mc.get("bit_identical") and mc.get("data_shards", 0) >= 2, (
    "multichip section (client-axis sharding: bit-identity at "
    ">= 2 data shards) missing from the compact line: " + last[:300]
)
assert "ici_reduce_seconds" in mc and "secure_clients_per_sec" in mc, (
    "multichip section missing ici_reduce_seconds / per-shard rates: "
    + last[:300]
)
assert (mc.get("kernel_shards") or 0) >= 2, (
    "kernel-sharded legs never engaged (kernel_shards < 2 — the "
    "row-sharded IKNP/equality stage, parallel/kernel_shard.py): "
    + last[:300]
)
assert "kernel_clients_per_sec" in mc and "kernel_gather_seconds" in mc, (
    "multichip section missing the kernel-sharded leg keys: " + last[:300]
)
assert mc.get("whole_level_speedup_vs_gathered") is not None, (
    "whole_level_speedup_vs_gathered missing (sharded-vs-gathered "
    "kernel comparison): " + last[:300]
)
skb = doc.get("extra", {}).get("sketch", {})
assert skb.get("bit_identical"), (
    "sketch section (malicious-secure verify: sharded legs gated "
    "bit-identical to the unsharded path) missing from the compact "
    "line: " + last[:300]
)
assert skb.get("malicious_overhead_vs_semi_honest") is not None, (
    "sketch overhead headline (malicious_overhead_vs_semi_honest) "
    "missing from the compact line: " + last[:300]
)
assert skb.get("sketch_clients_per_sec") is not None, (
    "sketch clients_per_sec missing from the compact line: " + last[:300]
)
assert (skb.get("sketch_shards") or 0) >= 2, (
    "sharded sketch legs never engaged (sketch_shards < 2 — the "
    "row-sharded verify, parallel/sketch_shard.py): " + last[:300]
)
mt = doc.get("extra", {}).get("multitenant", {})
assert mt.get("bit_identical_vs_solo"), (
    "multitenant section (per-collection sessions: bit-identity of "
    "every tenant vs its solo run) missing from the compact line: "
    + last[:300]
)
assert "aggregate_clients_per_sec" in mt and "stall_fill_ratio" in mt, (
    "multitenant section missing aggregate rate / stall-fill ratio: "
    + last[:300]
)
print(
    "bench_smoke OK: "
    f"{doc['metric']}={doc['value']}, "
    f"secure_clients_per_sec={sc['secure_clients_per_sec']}, "
    f"ot_path={sk['ot_path']}, "
    f"pipeline_speedup={sc.get('pipeline_speedup')}, "
    f"ingest_keys_per_sec={ing['ingest_keys_per_sec']}, "
    f"multichip_shards={mc['data_shards']} "
    f"(rates={mc['secure_clients_per_sec']}), "
    f"kernel_shards={mc['kernel_shards']} "
    f"(speedup_vs_gathered={mc['whole_level_speedup_vs_gathered']}), "
    f"multitenant_agg={mt['aggregate_clients_per_sec']} "
    f"(fill_ratio={mt['stall_fill_ratio']}), "
    f"sketch_overhead={skb['malicious_overhead_vs_semi_honest']} "
    f"(shards={skb['sketch_shards']}), "
    f"slo_level_p95_ms={slo['level_p95_ms']}, "
    f"seal_to_hitters_p95_s={islo['seal_to_hitters_p95_s']}, "
    f"line={len(last)}B, elapsed={doc.get('budget', {}).get('elapsed_s')}s"
)
EOF
rc=$?
rm -f "$out" "$out.err"
rm -rf "$trace_dir"
exit $rc
