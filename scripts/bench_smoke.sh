#!/usr/bin/env bash
# Budgeted bench smoke: the CI guard for the bench output contract.
#
# Usage: scripts/bench_smoke.sh [budget_seconds]
#   - runs `python bench.py` in SMOKE mode (FHH_BENCH_SMOKE=1: tiny
#     CPU-safe shapes — np-engine keygen + a small pipelined secure
#     crawl with its sequential bit-identity assertion, the streaming
#     ingest pair, and the multichip sharded legs on the 8-device
#     virtual mesh; the heavyweight chip sections report
#     {"skipped": "smoke"}) under a wall-clock budget
#     (FHH_BENCH_BUDGET, default 600 s)
#   - FAILS unless the bench exits rc=0 AND its last stdout line is
#     parseable JSON carrying the headline metric — exactly what the
#     harness needs (BENCH_r04 printed an oversized line that parsed as
#     null; BENCH_r05 timed out with no line at all; both fail here)
#   - also asserts the line stays under the harness's ~2000-byte stdout
#     tail capture
#   - runs with the live /metrics plane ON (FHH_METRICS_PORT): a sidecar
#     scraper polls the bench children mid-run and the UNION of series
#     it sees must cover the ops tentpole (level-latency buckets from a
#     server registry, the sharded-sketch gauge, live session rows) — a
#     bench that goes dark on the wire fails even if its numbers land
#   - FAILS if the final --out artifact is still marked "partial": true
#     (the crash-proof manifest must CLOSE on a clean run)
set -uo pipefail
cd "$(dirname "$0")/.."

budget="${1:-600}"
out="$(mktemp)"

# live telemetry plane for the whole run: every bench child claims the
# base port ("bench" tag, +0) while it holds the serial leg slot
metrics_port="${FHH_METRICS_PORT:-29817}"
export FHH_METRICS_PORT="$metrics_port"
artifact="$(mktemp -u).bench.json"
union="$(mktemp)"

# distributed tracing on for the whole smoke run: every bench child
# process appends to its own ring under $trace_dir, and the merged
# Perfetto trace must VALIDATE afterwards (fhh-trace structural gate)
trace_dir="$(mktemp -d)"
export FHH_TRACE_DIR="$trace_dir"

# 8 virtual host devices so the multichip section's 2- and 4-shard legs
# run on a CPU host (same mesh the tier-1 suite exercises);
# optimization_level=1 sidesteps XLA:CPU's pathological ChaCha-scan pass
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8 --xla_backend_optimization_level=1"
fi

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" FHH_BENCH_SMOKE=1 \
    FHH_BENCH_BUDGET="$budget" \
    timeout -k 10 "$((budget + 60))" python bench.py --out "$artifact" \
    > "$out" 2> "$out.err" &
bench_pid=$!

# mid-run scraper: accumulate the union of every fhh_ series (and its
# registry label) the live exporter shows while the bench runs — gaps
# between serial children just read as refused connections
python - "$bench_pid" "$metrics_port" "$union" <<'EOF'
import os, sys, time, urllib.request

pid, port, union_path = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
seen = set()
def alive(p):
    try:
        os.kill(p, 0)
        return True
    except OSError:
        return False
while alive(pid):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=1
        ) as resp:
            text = resp.read().decode("utf-8", "replace")
        for line in text.splitlines():
            if not line.startswith("fhh_"):
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            seen.add(name)
            if 'registry="' in line:
                reg = line.split('registry="', 1)[1].split('"', 1)[0]
                seen.add(f"{name}@{reg}")
    except Exception:
        # a child exiting mid-response raises IncompleteRead (not
        # OSError); any scrape failure is just a gap, never fatal
        pass
    # persist incrementally: a scraper crash must not zero the union
    with open(union_path, "w") as f:
        f.write("\n".join(sorted(seen)))
    time.sleep(1.0)
EOF

wait "$bench_pid"
rc=$?
if [ $rc -ne 0 ]; then
    echo "bench_smoke: bench.py exited rc=$rc" >&2
    tail -5 "$out.err" >&2
    rm -f "$out" "$out.err" "$union" "$artifact"
    rm -rf "$trace_dir"
    exit 1
fi

# the live plane carried the tentpole series: per-level SLO buckets off
# a server registry, the sharded malicious-verify gauge, session rows
if ! python - "$union" <<'EOF'
import sys

seen = set(open(sys.argv[1]).read().splitlines())
required = [
    "fhh_level_latency_seconds_bucket@server0",
    "fhh_sketch_shards",
    "fhh_session_last_progress_seconds",
]
missing = [r for r in required if r not in seen]
assert not missing, (
    f"required /metrics series never seen mid-run: {missing} "
    f"(union carried {len(seen)} series)"
)
print(f"bench_smoke metrics OK: union of {len(seen)} live series")
EOF
then
    echo "bench_smoke: live /metrics union gate FAILED" >&2
    rm -f "$out" "$out.err" "$union" "$artifact"
    rm -rf "$trace_dir"
    exit 1
fi

# the crash-proof manifest must CLOSE on a clean run
if ! python - "$artifact" <<'EOF'
import json, sys

doc = json.load(open(sys.argv[1]))
assert not doc.get("partial"), (
    "bench exited rc=0 but its artifact is still partial "
    f"(reason={doc.get('reason')!r}, legs={sorted(doc.get('results', {}))})"
)
print("bench_smoke artifact OK: manifest closed")
EOF
then
    echo "bench_smoke: final artifact still marked partial" >&2
    rm -f "$out" "$out.err" "$union" "$artifact"
    rm -rf "$trace_dir"
    exit 1
fi

# merged trace must load AND validate: every parented event's parent
# exists, no negative durations, clock offsets sane (obs/trace.py)
if ! python -m fuzzyheavyhitters_tpu.obs.trace merge \
        -d "$trace_dir" -o "$trace_dir/trace.json" > "$trace_dir/verdict.json"
then
    echo "bench_smoke: merged fhh-trace FAILED validation" >&2
    tail -20 "$trace_dir/verdict.json" >&2
    rm -f "$out" "$out.err" "$union" "$artifact"; rm -rf "$trace_dir"
    exit 1
fi
if ! python - "$trace_dir/verdict.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v["ok"], v["errors"][:3]
assert v["spans"] > 0, "tracing was on but no spans were recorded"
assert v["traces"], "no trace ids minted (leaders should mint per crawl)"
print(
    f"bench_smoke trace OK: {v['spans']} spans, "
    f"{len(v['traces'])} traces, components={v['components'][:6]}"
)
EOF
then
    echo "bench_smoke: trace verdict assertions FAILED" >&2
    rm -f "$out" "$out.err" "$union" "$artifact"; rm -rf "$trace_dir"
    exit 1
fi

python - "$out" <<'EOF'
import json, sys

lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
assert lines, "bench printed nothing"
last = lines[-1]
assert len(last) < 2000, (
    f"final JSON line is {len(last)} bytes — exceeds the harness's "
    "stdout tail capture and would parse as null"
)
doc = json.loads(last)
assert "metric" in doc and doc.get("value") is not None, doc
sc = doc.get("extra", {}).get("secure_crawl", {})
assert "secure_clients_per_sec" in sc, (
    "secure_crawl section missing from the compact line: " + last[:300]
)
sk = sc.get("secure_kernel", {})
assert "ot_path" in sk and all(
    f"phase_{p}_seconds" in sk for p in ("otext", "garble", "eval", "b2a")
), (
    "secure_kernel phase split (phase_otext/garble/eval/b2a + ot_path) "
    "missing from the compact line: " + last[:300]
)
slo = sc.get("slo", {})
assert slo.get("level_p95_ms") is not None, (
    "secure_crawl slo (p95 per-level latency, obs.hist histograms) "
    "missing from the compact line: " + last[:300]
)
ing = doc.get("extra", {}).get("ingest", {})
assert "ingest_keys_per_sec" in ing and ing.get("bit_identical_vs_batch"), (
    "ingest section (streaming front door: keys/sec + batch bit-identity) "
    "missing from the compact line: " + last[:300]
)
islo = ing.get("slo", {})
assert islo.get("seal_to_hitters_p95_s") is not None, (
    "ingest slo (seal-to-hitters p95 — the windowed SLO headline) "
    "missing from the compact line: " + last[:300]
)
mc = doc.get("extra", {}).get("multichip", {})
assert mc.get("bit_identical") and mc.get("data_shards", 0) >= 2, (
    "multichip section (client-axis sharding: bit-identity at "
    ">= 2 data shards) missing from the compact line: " + last[:300]
)
assert "ici_reduce_seconds" in mc and "secure_clients_per_sec" in mc, (
    "multichip section missing ici_reduce_seconds / per-shard rates: "
    + last[:300]
)
assert (mc.get("kernel_shards") or 0) >= 2, (
    "kernel-sharded legs never engaged (kernel_shards < 2 — the "
    "row-sharded IKNP/equality stage, parallel/kernel_shard.py): "
    + last[:300]
)
assert "kernel_clients_per_sec" in mc and "kernel_gather_seconds" in mc, (
    "multichip section missing the kernel-sharded leg keys: " + last[:300]
)
assert mc.get("whole_level_speedup_vs_gathered") is not None, (
    "whole_level_speedup_vs_gathered missing (sharded-vs-gathered "
    "kernel comparison): " + last[:300]
)
skb = doc.get("extra", {}).get("sketch", {})
assert skb.get("bit_identical"), (
    "sketch section (malicious-secure verify: sharded legs gated "
    "bit-identical to the unsharded path) missing from the compact "
    "line: " + last[:300]
)
assert skb.get("malicious_overhead_vs_semi_honest") is not None, (
    "sketch overhead headline (malicious_overhead_vs_semi_honest) "
    "missing from the compact line: " + last[:300]
)
assert skb.get("sketch_clients_per_sec") is not None, (
    "sketch clients_per_sec missing from the compact line: " + last[:300]
)
assert (skb.get("sketch_shards") or 0) >= 2, (
    "sharded sketch legs never engaged (sketch_shards < 2 — the "
    "row-sharded verify, parallel/sketch_shard.py): " + last[:300]
)
rx = doc.get("extra", {}).get("radix", {})
assert rx.get("bit_identical"), (
    "radix section (radix-2^k level fusion: k-sweep gated bit-identical "
    "to k=1) missing from the compact line: " + last[:300]
)
assert rx.get("level_rate_x_k") is not None and (
    rx.get("speedup_vs_k1") is not None
), (
    "radix headline keys (level_rate_x_k / speedup_vs_k1) missing from "
    "the compact line: " + last[:300]
)
mt = doc.get("extra", {}).get("multitenant", {})
assert mt.get("bit_identical_vs_solo"), (
    "multitenant section (per-collection sessions: bit-identity of "
    "every tenant vs its solo run) missing from the compact line: "
    + last[:300]
)
assert "aggregate_clients_per_sec" in mt and "stall_fill_ratio" in mt, (
    "multitenant section missing aggregate rate / stall-fill ratio: "
    + last[:300]
)
print(
    "bench_smoke OK: "
    f"{doc['metric']}={doc['value']}, "
    f"secure_clients_per_sec={sc['secure_clients_per_sec']}, "
    f"ot_path={sk['ot_path']}, "
    f"pipeline_speedup={sc.get('pipeline_speedup')}, "
    f"ingest_keys_per_sec={ing['ingest_keys_per_sec']}, "
    f"multichip_shards={mc['data_shards']} "
    f"(rates={mc['secure_clients_per_sec']}), "
    f"kernel_shards={mc['kernel_shards']} "
    f"(speedup_vs_gathered={mc['whole_level_speedup_vs_gathered']}), "
    f"multitenant_agg={mt['aggregate_clients_per_sec']} "
    f"(fill_ratio={mt['stall_fill_ratio']}), "
    f"sketch_overhead={skb['malicious_overhead_vs_semi_honest']} "
    f"(shards={skb['sketch_shards']}), "
    f"radix_level_rate={rx['level_rate_x_k']} "
    f"(speedup_vs_k1={rx['speedup_vs_k1']}), "
    f"slo_level_p95_ms={slo['level_p95_ms']}, "
    f"seal_to_hitters_p95_s={islo['seal_to_hitters_p95_s']}, "
    f"line={len(last)}B, elapsed={doc.get('budget', {}).get('elapsed_s')}s"
)
EOF
rc=$?
rm -f "$out" "$out.err" "$union" "$artifact"
rm -rf "$trace_dir"
exit $rc
