#!/usr/bin/env bash
# Budgeted bench smoke: the CI guard for the bench output contract.
#
# Usage: scripts/bench_smoke.sh [budget_seconds]
#   - runs `python bench.py` in SMOKE mode (FHH_BENCH_SMOKE=1: tiny
#     CPU-safe shapes — np-engine keygen + a small pipelined secure
#     crawl with its sequential bit-identity assertion; the heavyweight
#     chip sections report {"skipped": "smoke"}) under a wall-clock
#     budget (FHH_BENCH_BUDGET, default 480 s)
#   - FAILS unless the bench exits rc=0 AND its last stdout line is
#     parseable JSON carrying the headline metric — exactly what the
#     harness needs (BENCH_r04 printed an oversized line that parsed as
#     null; BENCH_r05 timed out with no line at all; both fail here)
#   - also asserts the line stays under the harness's ~2000-byte stdout
#     tail capture
set -uo pipefail
cd "$(dirname "$0")/.."

budget="${1:-480}"
out="$(mktemp)"

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" FHH_BENCH_SMOKE=1 \
    FHH_BENCH_BUDGET="$budget" \
    timeout -k 10 "$((budget + 60))" python bench.py > "$out" 2> "$out.err"
rc=$?
if [ $rc -ne 0 ]; then
    echo "bench_smoke: bench.py exited rc=$rc" >&2
    tail -5 "$out.err" >&2
    rm -f "$out" "$out.err"
    exit 1
fi

python - "$out" <<'EOF'
import json, sys

lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
assert lines, "bench printed nothing"
last = lines[-1]
assert len(last) < 2000, (
    f"final JSON line is {len(last)} bytes — exceeds the harness's "
    "stdout tail capture and would parse as null"
)
doc = json.loads(last)
assert "metric" in doc and doc.get("value") is not None, doc
sc = doc.get("extra", {}).get("secure_crawl", {})
assert "secure_clients_per_sec" in sc, (
    "secure_crawl section missing from the compact line: " + last[:300]
)
sk = sc.get("secure_kernel", {})
assert "ot_path" in sk and all(
    f"phase_{p}_seconds" in sk for p in ("otext", "garble", "eval", "b2a")
), (
    "secure_kernel phase split (phase_otext/garble/eval/b2a + ot_path) "
    "missing from the compact line: " + last[:300]
)
ing = doc.get("extra", {}).get("ingest", {})
assert "ingest_keys_per_sec" in ing and ing.get("bit_identical_vs_batch"), (
    "ingest section (streaming front door: keys/sec + batch bit-identity) "
    "missing from the compact line: " + last[:300]
)
print(
    "bench_smoke OK: "
    f"{doc['metric']}={doc['value']}, "
    f"secure_clients_per_sec={sc['secure_clients_per_sec']}, "
    f"ot_path={sk['ot_path']}, "
    f"pipeline_speedup={sc.get('pipeline_speedup')}, "
    f"ingest_keys_per_sec={ing['ingest_keys_per_sec']}, "
    f"line={len(last)}B, elapsed={doc.get('budget', {}).get('elapsed_s')}s"
)
EOF
rc=$?
rm -f "$out" "$out.err"
exit $rc
