#!/usr/bin/env bash
# Chaos-proxy recovery suite with a machine-readable artifact.
#
# Usage: scripts/chaos.sh [artifact.json]
#   - runs the full fault-injection/recovery surface on the CPU backend:
#     the socket-path suite (tests/test_resilience.py — control/data
#     plane chaos, sketch recovery via the challenge ratchet, sharded
#     mid-level retry), the mesh/ICI suite (tests/test_mesh_chaos.py),
#     the streaming-ingest suite (tests/test_ingest.py — admission
#     control, flood/slowclient chaos, kill-mid-window recovery), AND
#     the multi-chip suite (tests/test_multichip.py — sharded-vs-single
#     bit-identity, device-loss re-shard recovery), AND the multi-tenant
#     suite (tests/test_sessions.py — N=4 concurrent collections
#     bit-identical to solo, per-session gate isolation, the
#     flood-A + kill/restart-s1 tenant-isolation leg), AND the
#     malicious-sketch suite (tests/test_sketch_shard.py — the sharded
#     verify bit-identity matrix and the WINDOWED-MALICIOUS recovery
#     leg: kill/restart mid-window, the re-run replaying the identical
#     committed challenge root), AND the collector-fleet suite
#     (tests/test_fleet.py — live session migration, whole-host
#     host:kill failover: tenant A floods while the whole pair dies
#     mid-crawl of tenant B, B resumes bit-identical on the survivor),
#     INCLUDING the slow-marked multi-fault storm tier-1 skips
#   - writes a JSON artifact ({passed, failed, duration_s, tests}) to $1
#     (default: chaos_report.json); exits non-zero on any failure
#
# The fixed fault schedules live in the tests themselves (deterministic
# frame-ordinal / level triggers — see resilience/chaos.py for the
# FHH_FAULTS and FHH_MESH_FAULTS grammars); this script is the
# standalone/CI entry point, the same suites run (minus slow) inside
# tier-1.
set -uo pipefail
cd "$(dirname "$0")/.."

artifact="${1:-chaos_report.json}"
report="$(mktemp)"

JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py tests/test_mesh_chaos.py tests/test_ingest.py \
    tests/test_multichip.py tests/test_sessions.py tests/test_sketch_shard.py \
    tests/test_fleet.py tests/test_radix.py \
    -m "" -q \
    -p no:cacheprovider --junitxml="$report"
rc=$?

# fhh-race runtime sanitizer stage: re-run one trusted + one secure e2e
# chaos recovery scenario with FHH_DEBUG_GUARDS=1, so every guarded-
# attribute access on the servers asserts its owning lock mid-fault —
# the dynamic validation of the static guard map under real chaos
# (utils/guards.py; the scenarios flow through the socket verb path, so
# the lock discipline is exactly the production one)
JAX_PLATFORMS=cpu FHH_DEBUG_GUARDS=1 python -m pytest \
    "tests/test_resilience.py::test_e2e_chaos_recovery_bit_identical" \
    "tests/test_sessions.py::test_tenant_isolation_flood_and_kill_restart_mid_crawl" \
    "tests/test_fleet.py::test_host_kill_mid_crawl_under_flood_tenant_b_bit_identical" \
    -q -p no:cacheprovider
guards_rc=$?
if [ $guards_rc -ne 0 ]; then
    echo "chaos suite: FHH_DEBUG_GUARDS sanitizer stage FAILED" >&2
    rc=1
fi

# fhh-taint runtime sanitizer stage: the same trusted + secure e2e
# recovery legs with FHH_DEBUG_TAINT=1, so the session/OT secret
# buffers register at their constructors and every obs sink boundary
# (log emit, metrics render, trace record, alert fire, report build)
# asserts no registered byte image crosses — the dynamic validation of
# the static secret-flow pass under real chaos (utils/taint_guard.py)
JAX_PLATFORMS=cpu FHH_DEBUG_TAINT=1 python -m pytest \
    "tests/test_resilience.py::test_e2e_chaos_recovery_bit_identical" \
    "tests/test_sessions.py::test_tenant_isolation_flood_and_kill_restart_mid_crawl" \
    "tests/test_fleet.py::test_host_kill_mid_crawl_under_flood_tenant_b_bit_identical" \
    -q -p no:cacheprovider
taint_rc=$?
if [ $taint_rc -ne 0 ]; then
    echo "chaos suite: FHH_DEBUG_TAINT sanitizer stage FAILED" >&2
    rc=1
fi

# fhh-trace stage: re-run one e2e chaos-recovery leg with distributed
# tracing ON, then merge + structurally validate the trace — a recovery
# wave (reconnect replays, plane resets, level re-runs) must still
# produce a parent-consistent single-trace timeline (obs/trace.py)
trace_dir="$(mktemp -d)"
JAX_PLATFORMS=cpu FHH_TRACE_DIR="$trace_dir" python -m pytest \
    "tests/test_resilience.py::test_e2e_chaos_recovery_bit_identical" \
    -q -p no:cacheprovider
trace_rc=$?
if [ $trace_rc -eq 0 ]; then
    python -m fuzzyheavyhitters_tpu.obs.trace merge \
        -d "$trace_dir" -o "$trace_dir/trace.json" > /dev/null \
        || trace_rc=$?
fi
if [ $trace_rc -ne 0 ]; then
    echo "chaos suite: traced e2e leg / trace validation FAILED" >&2
    rc=1
fi
rm -rf "$trace_dir"

python - "$report" "$artifact" "$guards_rc" "$trace_rc" "$taint_rc" <<'EOF'
import json, sys
import xml.etree.ElementTree as ET

suite = ET.parse(sys.argv[1]).getroot().find("testsuite")
tests = [
    {
        "name": f"{c.get('classname')}::{c.get('name')}",
        "time_s": float(c.get("time", 0)),
        "outcome": (
            "failed" if c.find("failure") is not None or c.find("error") is not None
            else "skipped" if c.find("skipped") is not None else "passed"
        ),
    }
    for c in suite.iter("testcase")
]
doc = {
    "schema": "fhh-chaos-report/1",
    "passed": sum(t["outcome"] == "passed" for t in tests),
    "failed": sum(t["outcome"] == "failed" for t in tests),
    "skipped": sum(t["outcome"] == "skipped" for t in tests),
    "duration_s": round(float(suite.get("time", 0)), 2),
    "debug_guards": "passed" if sys.argv[3] == "0" else "failed",
    "trace_validation": "passed" if sys.argv[4] == "0" else "failed",
    "debug_taint": "passed" if sys.argv[5] == "0" else "failed",
    # the collector-fleet legs (migration + host:kill failover), folded
    # out of the main run so fleet health is one key deep
    "fleet": {
        t["name"].split("::")[-1]: t["outcome"]
        for t in tests
        if "test_fleet" in t["name"]
        and ("migration" in t["name"] or "host_kill" in t["name"])
    },
    "tests": tests,
}
json.dump(doc, open(sys.argv[2], "w"), indent=1)
print(
    f"chaos suite: {doc['passed']} passed, {doc['failed']} failed, "
    f"{doc['skipped']} skipped in {doc['duration_s']}s, "
    f"debug_guards={doc['debug_guards']}, "
    f"trace_validation={doc['trace_validation']}, "
    f"debug_taint={doc['debug_taint']} -> {sys.argv[2]}"
)
EOF
rm -f "$report"

# bench output contract (part of the same CI gate): a budget or
# final-JSON-line regression — the rc=124/empty-tail failure mode — must
# fail HERE, not in the next harness round.  Skippable for a quick
# chaos-only loop with FHH_SKIP_BENCH_SMOKE=1.
if [ "${FHH_SKIP_BENCH_SMOKE:-0}" != "1" ]; then
    if scripts/bench_smoke.sh; then
        python - "$artifact" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
doc["bench_smoke"] = "passed"
json.dump(doc, open(sys.argv[1], "w"), indent=1)
EOF
    else
        echo "chaos suite: bench_smoke FAILED" >&2
        python - "$artifact" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
doc["bench_smoke"] = "failed"
json.dump(doc, open(sys.argv[1], "w"), indent=1)
EOF
        rc=1
    fi
fi
exit $rc
