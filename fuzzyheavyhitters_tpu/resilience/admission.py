"""Admission control for the streaming ingest front door.

The overload vocabulary every ingest path speaks (protocol/rpc.py's
``submit_keys`` verb is the consumer): a submission is ADMITTED into the
window pool, SHED (reservoir mode replaced it or dropped it — the pool
stays a seeded uniform sample of everything offered), or REJECTED with a
retryable ``Overloaded`` verdict the client's RetryPolicy backs off on.
The design invariant, per the robustness charter: the server degrades
GRACEFULLY — bounded pools, explicit verdicts, deterministic sampling —
never by unbounded queueing or silent drops.

Pieces:

- :class:`TokenBucket` — keys-per-second rate limiting with an
  injectable clock, so tests drive it deterministically (a seeded
  ``ManualClock``) and production uses ``time.monotonic``.
- :class:`WindowAdmission` — one ingest window's admission state:
  per-client key quotas, the bounded pool occupancy, and (in reservoir
  shed mode) the seeded incremental reservoir from
  :mod:`fuzzyheavyhitters_tpu.native` deciding slot placement.
- :class:`AdmissionController` — the server-wide gate combining the
  temporal rate limit (shared across windows: rate is about time, not
  window identity) with the per-window state; ``admit`` returns a
  :class:`Verdict`.

Determinism contract: given the same seed and the same SEQUENCE of
submissions, every decision (including reservoir slots) is identical —
that is what lets the gate server's verdicts be mirrored to its peer and
replayed after a restart (the reservoir RNG state is checkpointable via
``Reservoir.state()``).

Why rejection is not an error: an ``Overloaded`` verdict is a successful
RPC response (it replays identically from the dedup cache), and each new
client ATTEMPT is a new call — so backoff-and-retry re-runs admission
against refilled tokens instead of being answered with a stale cached
rejection.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from .. import native

# shed policies (Config.ingest_shed)
SHED_REJECT = "reject"
SHED_RESERVOIR = "reservoir"
SHED_POLICIES = (SHED_REJECT, SHED_RESERVOIR)


class ManualClock:
    """Deterministic clock for tests: ``advance(s)`` moves time forward;
    calling the instance returns the current reading (the same shape as
    ``time.monotonic``)."""

    def __init__(self, t0: float = 0.0):
        self._t = float(t0)

    def advance(self, s: float) -> None:
        self._t += float(s)

    def __call__(self) -> float:
        return self._t


@dataclass
class TokenBucket:
    """Classic token bucket in KEYS (not submissions): ``rate_per_s``
    tokens accrue continuously up to ``burst``; ``try_take(n)`` spends n
    or refuses.  ``wait_s(n)`` names the refill horizon — the retryable
    verdict's ``retry_after_s`` hint, so a backing-off client sleeps an
    informed amount instead of a blind guess."""

    rate_per_s: float
    burst: float
    clock: object = field(default=time.monotonic, repr=False)

    def __post_init__(self):
        if self.rate_per_s <= 0:
            raise ValueError("token bucket rate must be positive")
        self.burst = max(float(self.burst), 1.0)
        self.tokens = self.burst
        self._last = float(self.clock())

    def _refill(self) -> None:
        now = float(self.clock())
        if now > self._last:
            self.tokens = min(
                self.burst, self.tokens + (now - self._last) * self.rate_per_s
            )
        self._last = now

    def try_take(self, n: float) -> bool:
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def wait_s(self, n: float) -> float:
        """Seconds until ``n`` tokens could be available (0 when they
        already are).  Honest by construction: callers reject n > burst
        outright (scope "burst") instead of asking for a horizon the
        bucket can never reach."""
        self._refill()
        return max(0.0, (n - self.tokens) / self.rate_per_s)


@dataclass(frozen=True)
class Verdict:
    """One submission's fate.  ``admitted`` with ``slot is None`` means
    append to the pool in arrival order; ``admitted`` with a slot means
    replace that reservoir slot (shedding its occupant); not admitted
    with ``shed`` means the reservoir dropped this submission (a
    SUCCESSFUL outcome — the pool remains a uniform sample); not admitted
    with a ``scope`` means Overloaded: retryable, back off
    ``retry_after_s`` and try again."""

    admitted: bool
    slot: int | None = None
    shed: bool = False
    scope: str | None = None  # "rate" | "quota" | "capacity"
    retry_after_s: float = 0.0


class WindowAdmission:
    """Per-window admission state: client quota ledger + pool occupancy
    + the reservoir (reservoir shed mode only, created lazily at first
    overflow so under-capacity windows never touch the RNG).

    Reservoir mode requires a FIXED submission chunk size (the first
    admitted submission sets it; mismatched sizes are capacity-rejected
    BEFORE any RNG offer): the slot table then bounds the pool exactly
    (slots x chunk) and slot replacement can never grow it — and the
    reject happens pre-offer, so the sampling stream stays a pure
    function of the admitted-or-offered sequence."""

    def __init__(self, *, max_keys: int, client_quota: int, shed: str,
                 seed: int):
        if shed not in SHED_POLICIES:
            raise ValueError(f"unknown shed policy {shed!r}")
        self.max_keys = int(max_keys)
        self.client_quota = int(client_quota)
        self.shed = shed
        self.seed = int(seed)
        self.client_keys: dict[str, int] = {}
        self.subs = 0  # admitted submissions (reservoir slot capacity)
        self.keys = 0  # keys currently pooled
        self.sub_keys: int | None = None  # fixed chunk size (reservoir)
        self.reservoir: native.Reservoir | None = None
        # draws consumed by journal-replayed verdicts BEFORE the (re-)
        # engagement of the sampler (recovery without a post-engagement
        # checkpoint): the engagement fast-forward includes them so the
        # stream continues where the first life left off
        self.pending_draws = 0

    def _charge(self, client_id: str, n_keys: int) -> None:
        if client_id is not None:
            self.client_keys[client_id] = (
                self.client_keys.get(client_id, 0) + n_keys
            )

    def precheck(self, client_id: str, n_keys: int) -> Verdict | None:
        """READ-ONLY rejection checks, run before the shared rate bucket
        is charged: a submission doomed by its own quota, an impossible
        size, or a full reject-mode window must not drain the tokens
        honest clients are queueing on (a flooder stalls itself, not
        them).  Returns the rejection, or None to proceed."""
        if (
            self.client_quota > 0
            and client_id is not None
            and self.client_keys.get(client_id, 0) + n_keys > self.client_quota
        ):
            return Verdict(False, scope="quota")
        if self.shed == SHED_RESERVOIR:
            if self.sub_keys is not None and n_keys != self.sub_keys:
                # the slot-table bound rests on uniform chunks — a
                # mismatched size can never be admitted to this window
                return Verdict(False, scope="capacity")
            if self.sub_keys is None and n_keys > self.max_keys:
                return Verdict(False, scope="capacity")
        elif self.reservoir is None and self.keys + n_keys > self.max_keys:
            return Verdict(False, scope="capacity")
        return None

    def decide(self, client_id: str, n_keys: int) -> Verdict:
        """The commit half of one submission's decision (run
        :meth:`precheck` first — the controller's ``admit`` does).
        Mutates the ledgers on admit/shed so the decision sequence is
        the state."""
        early = self.precheck(client_id, n_keys)
        if early is not None:
            return early
        if self.reservoir is None and self.keys + n_keys <= self.max_keys:
            self._charge(client_id, n_keys)
            self.keys += n_keys
            self.subs += 1
            if self.shed == SHED_RESERVOIR and self.sub_keys is None:
                self.sub_keys = n_keys
            return Verdict(True, slot=None)
        if self.shed == SHED_REJECT:
            return Verdict(False, scope="capacity")
        # reservoir shed: the pool is FULL — from here on the slot table
        # (capacity = submissions admitted so far) is a uniform sample of
        # every offer.  Deterministic given (seed, offer sequence); the
        # precheck guarantees subs >= 1 and a size-matched chunk here.
        if self.reservoir is None:
            self.reservoir = native.Reservoir(self.subs, self.seed)
            # the fill phase already happened (the appends above): fast-
            # forward the stream past it so offer #subs+1 is the first
            # replacement draw, exactly like a one-shot reservoir's —
            # plus any draws journal-replayed verdicts consumed before
            # this (re-)engagement
            self.reservoir.offer(self.reservoir.k + self.pending_draws)
            self.pending_draws = 0
        slot = int(self.reservoir.offer(1)[0])
        if slot < 0:
            return Verdict(False, shed=True)
        self._charge(client_id, n_keys)
        return Verdict(True, slot=slot)


class AdmissionController:
    """The server-wide front-door gate.  One temporal token bucket across
    windows; per-window state created on first touch via :meth:`window`
    (bounded by the caller — protocol/rpc.py retains a fixed number of
    live windows)."""

    def __init__(self, *, max_window_keys: int, rate_keys_per_s: float = 0.0,
                 burst_keys: int = 4096, client_quota: int = 0,
                 shed: str = SHED_REJECT, seed: int = 0,
                 clock=time.monotonic):
        if max_window_keys <= 0:
            raise ValueError("max_window_keys must be positive (bounded pool)")
        if shed not in SHED_POLICIES:
            raise ValueError(f"unknown shed policy {shed!r}")
        self.max_window_keys = int(max_window_keys)
        self.client_quota = int(client_quota)
        self.shed = shed
        self.seed = int(seed)
        self.bucket = (
            TokenBucket(rate_keys_per_s, burst_keys, clock=clock)
            if rate_keys_per_s > 0
            else None
        )

    def window(self, window: int) -> WindowAdmission:
        return WindowAdmission(
            max_keys=self.max_window_keys,
            client_quota=self.client_quota,
            shed=self.shed,
            # per-window seed: windows sample independently but each is
            # reproducible from (seed, window) alone
            seed=(self.seed * 0x9E3779B9 + int(window)) & ((1 << 64) - 1),
        )

    def admit(self, wa: WindowAdmission, client_id: str,
              n_keys: int) -> Verdict:
        """Read-only prechecks (quota, impossible sizes, full
        reject-mode windows) run FIRST so a doomed submission never
        drains the shared rate bucket — a quota-blocked flooder's
        retries must not convert into rate rejections for honest
        clients.  Then the temporal rate limit, then the window's commit
        decision.  A rejection never touches the window state, so a
        backed-off retry replays against the same deterministic window
        sequence."""
        early = wa.precheck(client_id, n_keys)
        if early is not None:
            return early
        if self.bucket is not None:
            if n_keys > self.bucket.burst:
                # no refill horizon ever covers this chunk: reject with
                # a distinct scope instead of promising a wait that
                # cannot be kept (split the chunk or raise the burst)
                return Verdict(False, scope="burst")
            if not self.bucket.try_take(n_keys):
                return Verdict(
                    False, scope="rate",
                    retry_after_s=self.bucket.wait_s(n_keys),
                )
        return wa.decide(client_id, n_keys)

    async def admit_offloaded(self, wa: WindowAdmission, client_id: str,
                              n_keys: int, *, gate: asyncio.Lock) -> Verdict:
        """:meth:`admit` off the shared event loop: the bucket/quota/
        reservoir arithmetic runs in the default executor behind the
        caller's per-session ``gate``, so a flooding tenant's admission
        math occupies a worker thread, not the server loop — other
        tenants' verbs (and other sessions' admissions) keep
        interleaving.  The gate serializes decisions PER SESSION: the
        determinism contract (module doc) is about the decision
        SEQUENCE, and two interleaved executor runs against one window's
        ledgers would fork it."""
        async with gate:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                None, self.admit, wa, client_id, n_keys
            )
