"""Fault-tolerance layer for the distributed crawl.

Three pieces, shared by the control plane (leader↔server RPC), the data
plane (server↔server socket), and the leader's crawl supervision:

- :mod:`.policy` — the ONE retry/deadline vocabulary: exponential
  backoff with full jitter (:class:`RetryPolicy`), wall-clock budgets
  (:class:`Deadline`), per-verb budget tables (:class:`VerbBudgets`),
  and the transient-vs-fatal error classifier every retry loop consults
  (:func:`is_transient`).  Replaces the fixed-sleep dial loops that used
  to live in protocol/rpc.py.
- :mod:`.chaos` — a frame-aware fault-injection proxy for recovery
  tests: sits between leader↔server or server↔server sockets and
  severs, delays, black-holes, truncates, floods (duplicate delivery),
  or slow-trickles frames on a deterministic ``FHH_FAULTS`` schedule
  (grammar in :func:`chaos.parse_faults`).
- :mod:`.admission` — overload control for the streaming ingest front
  door: token-bucket rate limits, per-client window quotas, bounded
  pools, and the reject-vs-reservoir shed policies behind
  protocol/rpc.py's ``submit_keys`` verb.
- the reconnecting client + idempotent verb replay live in
  protocol/rpc.py itself (they ARE the transport), built on this
  module's policy vocabulary; leader-side crawl supervision lives in
  protocol/leader_rpc.py (:meth:`RpcLeader.run_supervised`) and the
  windowed ingest driver beside it (:class:`WindowedIngest`).

Every recovery event emits ``resilience.*`` telemetry: retry counts,
reconnect epochs, replayed verbs, restored/re-run levels.
"""

from .admission import (
    AdmissionController,
    ManualClock,
    TokenBucket,
    Verdict,
    WindowAdmission,
)
from .chaos import ChaosProxy, FaultSpec, parse_faults
from .policy import (
    Deadline,
    RetryPolicy,
    VerbBudgets,
    is_transient,
    retry_async,
)

__all__ = [
    "AdmissionController",
    "ChaosProxy",
    "Deadline",
    "FaultSpec",
    "ManualClock",
    "RetryPolicy",
    "TokenBucket",
    "Verdict",
    "VerbBudgets",
    "WindowAdmission",
    "is_transient",
    "parse_faults",
    "retry_async",
]
