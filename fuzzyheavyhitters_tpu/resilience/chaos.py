"""Chaos proxy: deterministic fault injection between crawl sockets.

An asyncio TCP proxy that understands the control/data-plane framing
(8-byte little-endian length prefix, protocol/rpc.py ``_HDR``) and can
therefore trigger faults at exact FRAME boundaries — "sever the leader's
link right after the 12th request" is reproducible, where byte- or
time-triggered faults are not.

Fault grammar (the ``FHH_FAULTS`` env spec; ';'-separated clauses)::

    <link>:<action>@msg=<N>[,key=value...]

    link    label the proxy was constructed with (e.g. ctl0, ctl1, plane)
    action  sever | delay | blackhole | truncate | flood | slowclient
    msg=N   fire when the Nth frame (1-indexed, per direction) arrives
    dir=    c2s (default) | s2c — which direction's frame counter triggers
    ms=M    delay/slowclient: forward M milliseconds late (default 200)
    count=K blackhole: drop K consecutive frames then resume;
            flood: deliver K EXTRA copies of the trigger frame;
            slowclient: trickle K consecutive frames (default 1;
            sever/truncate ignore it — the connection is gone after one)

Actions:

- ``sever``     — close both sides mid-stream (RST-ish: the peer sees a
  reset/EOF).  The listener stays up: a reconnecting client redials
  through the same proxy and gets a clean new pipe.
- ``delay``     — hold one frame for ``ms`` before forwarding (tests
  deadline headroom without killing anything).
- ``blackhole`` — read and DROP ``count`` frames silently; the
  connection stays open (tests the per-verb wall-clock budgets: the
  caller must time out rather than hang forever).
- ``truncate``  — forward only half of the frame's payload bytes, then
  sever (tests the torn-frame path: the reader must classify the
  corrupt/short frame as transport loss, not crash).
- ``flood``     — deliver the trigger frame 1 + ``count`` times (the
  at-least-once delivery pathology made real: a duplicated
  ``submit_keys``/verb frame must be absorbed by the replay dedup /
  recorded-verdict machinery, never double-applied).
- ``slowclient`` — trickle the next ``count`` frames ``ms`` late EACH
  (a slow or throttled client; tests that a slow producer stalls only
  itself — the crawl and other clients keep moving).

Each accepted connection gets an independent pump per direction.  Frame
ORDINALS are per connection and per direction (deterministic: TCP orders
each direction), but the fault clauses themselves are consumed
PROXY-GLOBALLY — a sever that fired once does not re-arm on the redial
(otherwise a reconnecting client would be severed at the same ordinal of
every fresh connection, forever).  Chain clauses for multi-fault
schedules; ``ChaosProxy.sever_now()`` gives imperative test control.
"""

from __future__ import annotations

import asyncio
import struct
import time
from dataclasses import dataclass, field

from .. import obs

_HDR = struct.Struct("<Q")  # mirror protocol/rpc.py framing

_ACTIONS = ("sever", "delay", "blackhole", "truncate", "flood", "slowclient")
_DIRS = ("c2s", "s2c")


@dataclass(frozen=True)
class FaultSpec:
    link: str
    action: str
    at_msg: int  # 1-indexed frame ordinal that triggers the fault
    direction: str = "c2s"
    ms: int = 200
    count: int = 1

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r}")
        if self.direction not in _DIRS:
            raise ValueError(f"unknown chaos direction {self.direction!r}")
        if self.at_msg < 1:
            raise ValueError("msg= trigger is 1-indexed")


def parse_faults(spec: str) -> list[FaultSpec]:
    """Parse an ``FHH_FAULTS`` spec string (grammar above).  Empty/blank
    specs parse to no faults; malformed clauses raise ValueError loudly —
    a chaos schedule that silently no-ops would make a recovery test pass
    for the wrong reason."""
    out: list[FaultSpec] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            head, args = clause.split("@", 1)
            link, action = head.split(":", 1)
        except ValueError:
            raise ValueError(
                f"bad chaos clause {clause!r} (want link:action@msg=N[,k=v...])"
            ) from None
        kw: dict = {}
        for part in args.split(","):
            k, _, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if k == "msg":
                kw["at_msg"] = int(v)
            elif k == "dir":
                kw["direction"] = v
            elif k in ("ms", "count"):
                kw[k] = int(v)
            else:
                raise ValueError(f"unknown chaos arg {k!r} in {clause!r}")
        if "at_msg" not in kw:
            raise ValueError(f"chaos clause {clause!r} missing msg= trigger")
        out.append(FaultSpec(link=link.strip(), action=action.strip(), **kw))
    return out


class ChaosProxy:
    """One listener forwarding to one target, applying the fault clauses
    whose ``link`` matches this proxy's label.

    Construct, ``await start()``, point the client at ``listen_port``.
    The proxy survives severs (the listener stays bound) so reconnect
    paths are exercised end-to-end through the same chokepoint.
    """

    def __init__(
        self,
        listen_host: str,
        listen_port: int,
        target_host: str,
        target_port: int,
        faults: list[FaultSpec] | None = None,
        link: str = "link",
    ):
        self.listen_host, self.listen_port = listen_host, listen_port
        self.target_host, self.target_port = target_host, target_port
        self.link = link
        self.faults = [f for f in (faults or []) if f.link == link]
        self._srv: asyncio.AbstractServer | None = None
        self._conns: set[tuple] = set()
        self._pumps: set[asyncio.Task] = set()
        # armed faults are consumed proxy-globally: [spec, remaining_fires]
        # (blackhole/slowclient fire once per frame for count frames; the
        # rest fire once — flood's count multiplies within its one fire)
        self._armed: list[list] = [
            [f, f.count if f.action in ("blackhole", "slowclient") else 1]
            for f in self.faults
        ]
        self.frames = {"c2s": 0, "s2c": 0}  # lifetime totals, all conns
        self.fired: list[tuple[str, str, int]] = []  # (action, dir, msg#)

    async def start(self) -> "ChaosProxy":
        self._srv = await asyncio.start_server(
            self._on_client, self.listen_host, self.listen_port
        )
        return self

    async def stop(self) -> None:
        if self._srv is not None:
            self._srv.close()
            await self._srv.wait_closed()
        self.sever_now()
        for t in list(self._pumps):
            t.cancel()
        for t in list(self._pumps):
            try:
                await t
            # fhh-lint: disable=broad-except (teardown: a pump dying of
            # ANY error while being torn down is expected, not reportable)
            except (asyncio.CancelledError, Exception):
                pass

    def sever_now(self) -> None:
        """Imperatively cut every live connection (keeps listening)."""
        for pair in list(self._conns):
            for w in pair:
                if not w.is_closing():
                    w.close()
        self._conns.clear()

    # -- internals --------------------------------------------------------

    async def _on_client(self, c_reader, c_writer):
        try:
            s_reader, s_writer = await asyncio.wait_for(
                asyncio.open_connection(self.target_host, self.target_port),
                5.0,
            )
        except (OSError, asyncio.TimeoutError):
            c_writer.close()
            return
        pair = (c_writer, s_writer)
        self._conns.add(pair)
        state = _ConnState(self)
        for direction, rd, wr in (
            ("c2s", c_reader, s_writer),
            ("s2c", s_reader, c_writer),
        ):
            t = asyncio.create_task(self._pump(state, direction, rd, wr, pair))
            self._pumps.add(t)
            t.add_done_callback(self._pumps.discard)

    def _sever_pair(self, pair) -> None:
        for w in pair:
            if not w.is_closing():
                w.close()
        self._conns.discard(pair)

    async def _pump(self, state, direction, reader, writer, pair):
        """Forward frames one at a time, consulting the schedule at each
        frame boundary.  Any transport error on either side ends the pump
        (and severs the pair: half-open proxies would hide real severs)."""
        try:
            while True:
                # fhh-lint: disable=unbounded-await (proxy pump: a chaos
                # proxy must never impose its own deadline — the system
                # under test owns all timeout behavior)
                hdr = await reader.readexactly(_HDR.size)
                (n,) = _HDR.unpack(hdr)
                # fhh-lint: disable=unbounded-await (as above)
                body = await reader.readexactly(n)
                msg_no = state.next_msg(direction)
                self.frames[direction] += 1
                fault = state.fault_for(direction, msg_no)
                if fault is not None:
                    self.fired.append((fault.action, direction, msg_no))
                    obs.emit(
                        "resilience.chaos_fired",
                        severity="debug",
                        link=self.link,
                        action=fault.action,
                        direction=direction,
                        msg=msg_no,
                    )
                    # fault events become trace instants: the injected
                    # sever/blackhole shows up ON the merged timeline at
                    # the exact frame it fired, next to the spans it
                    # errored (obs.trace; no-op when tracing is off)
                    obs.trace.instant(
                        f"chaos.{fault.action}", comp=f"chaos:{self.link}",
                        direction=direction, msg=msg_no,
                    )
                    if fault.action == "sever":
                        self._sever_pair(pair)
                        return
                    if fault.action == "blackhole":
                        continue  # drop the frame; connection stays up
                    if fault.action == "truncate":
                        writer.write(hdr + body[: max(1, n // 2)])
                        await writer.drain()
                        self._sever_pair(pair)
                        return
                    if fault.action in ("delay", "slowclient"):
                        await asyncio.sleep(fault.ms / 1000.0)
                    if fault.action == "flood":
                        # duplicate delivery: the frame arrives count
                        # EXTRA times (at-least-once made real) — the
                        # original forward below is the +1
                        for _ in range(max(1, fault.count)):
                            writer.write(hdr + body)
                        await writer.drain()
                writer.write(hdr + body)
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            self._sever_pair(pair)


class _ConnState:
    """Per-connection frame counters; fault consumption lives on the
    proxy (``_armed``) so a fired fault stays fired across redials.  A
    blackhole of ``count=K`` drops the next K frames matching its
    direction once its trigger ordinal is reached."""

    def __init__(self, proxy: ChaosProxy):
        self.counts = {"c2s": 0, "s2c": 0}
        self._proxy = proxy

    def next_msg(self, direction: str) -> int:
        self.counts[direction] += 1
        return self.counts[direction]

    def fault_for(self, direction: str, msg_no: int) -> FaultSpec | None:
        for ent in self._proxy._armed:
            f, remaining = ent
            if remaining <= 0 or f.direction != direction:
                continue
            if msg_no >= f.at_msg:
                ent[1] -= 1
                return f
        return None


# ---------------------------------------------------------------------------
# Mesh (ICI) chaos: in-process fault injection for parallel/mesh.py
#
# The mesh path has no sockets to proxy — the whole two-party exchange is
# XLA collectives (ppermute/psum) inside compiled programs, so faults are
# injected at the LEVEL boundaries the host-side driver crosses anyway
# (MeshLeader.run_supervised consults the injector before each level's
# collective dispatch).  Three surrogates for the real ICI failure modes:
#
# - ``drop``  — a dropped data-parallel shard: the level's collective
#   result cannot be trusted; device state (the frontier) is intact, so
#   recovery is "re-run the level" — the shard-granular cost.
# - ``kill``  — a donor device killed mid-all-gather: the injector
#   CLOBBERS the runner's device-resident frontier (the in-process
#   equivalent of losing a participating chip's HBM), so recovery must
#   restore from the last host checkpoint.
# - ``delay`` — a slow participant: the level stalls ``ms`` milliseconds
#   but completes; recovery must NOT trigger (tests the absence of
#   spurious rollbacks).
#
# Grammar (``FHH_MESH_FAULTS``): ``mesh:<action>@level=<N>[,ms=M]``,
# ';'-separated, consumed once each like the proxy's clauses.
# ---------------------------------------------------------------------------

_MESH_ACTIONS = ("drop", "kill", "delay")


class MeshFaultError(RuntimeError):
    """An injected (or detected) mesh-collective fault; ``state_lost``
    tells the supervisor whether the device-resident frontier survived
    (drop: re-run the level) or not (kill: restore a checkpoint)."""

    def __init__(self, msg: str, state_lost: bool = False):
        super().__init__(msg)
        self.state_lost = state_lost


@dataclass(frozen=True)
class MeshFaultSpec:
    action: str
    at_level: int
    ms: int = 200

    def __post_init__(self):
        if self.action not in _MESH_ACTIONS:
            raise ValueError(f"unknown mesh chaos action {self.action!r}")
        if self.at_level < 0:
            raise ValueError("level= trigger must be >= 0")


def parse_mesh_faults(spec: str) -> list:
    """Parse an ``FHH_MESH_FAULTS`` spec (grammar above).  Blank specs
    parse to no faults; malformed clauses raise ValueError loudly, same
    contract as :func:`parse_faults`."""
    out: list[MeshFaultSpec] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            head, args = clause.split("@", 1)
            link, action = head.split(":", 1)
        except ValueError:
            raise ValueError(
                f"bad mesh chaos clause {clause!r} "
                "(want mesh:action@level=N[,ms=M])"
            ) from None
        if link.strip() != "mesh":
            raise ValueError(f"mesh chaos clause {clause!r} must target 'mesh'")
        kw: dict = {}
        for part in args.split(","):
            k, _, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if k == "level":
                kw["at_level"] = int(v)
            elif k == "ms":
                kw["ms"] = int(v)
            else:
                raise ValueError(f"unknown mesh chaos arg {k!r} in {clause!r}")
        if "at_level" not in kw:
            raise ValueError(f"mesh chaos clause {clause!r} missing level=")
        out.append(MeshFaultSpec(action=action.strip(), **kw))
    return out


class MeshChaos:
    """Consumed-once mesh fault schedule.  ``before_level(runner, level)``
    is the hook :class:`parallel.mesh.MeshLeader` calls at each level
    entry; a clause whose ``at_level`` has been reached fires exactly
    once (re-run levels do not re-trigger it — the recovery must be able
    to make progress, exactly like the proxy's fired severs)."""

    def __init__(self, faults: list | None = None):
        self._armed: list[MeshFaultSpec] = list(faults or [])
        self.fired: list[tuple[str, int]] = []  # (action, level)

    def before_level(self, runner, level: int) -> None:
        for f in list(self._armed):
            if level < f.at_level:
                continue
            self._armed.remove(f)
            self.fired.append((f.action, level))
            obs.emit(
                "resilience.mesh_chaos_fired",
                severity="debug",
                action=f.action,
                level=level,
            )
            # mesh faults are trace instants too (see ChaosProxy._pump)
            obs.trace.instant(
                f"chaos.mesh_{f.action}", comp="chaos:mesh", level=level,
            )
            if f.action == "delay":
                time.sleep(f.ms / 1000.0)
                continue
            if f.action == "kill":
                # the donor's HBM is gone: clobber the device frontier so
                # any recovery short of a checkpoint restore fails loudly
                runner.frontier = None
                runner._children = None
                raise MeshFaultError(
                    f"mesh participant killed mid-collective at level "
                    f"{level}", state_lost=True,
                )
            raise MeshFaultError(
                f"data-parallel shard dropped at level {level}",
                state_lost=False,
            )


# ---------------------------------------------------------------------------
# Host chaos: whole-collector-pair loss (the fleet failover drill)
#
# Above the connection layer (ChaosProxy severs one link) and the device
# layer (MeshChaos clobbers one participant) sits the host: BOTH servers
# of a collector pair vanishing at once — a rack power loss, a preempted
# VM pair.  The surrogate is driven by the windowed ingest driver at its
# window boundaries (the same place the mesh injector uses level
# boundaries): a clause whose ``at_window`` has been reached fires once,
# and the harness kills the whole pair — the supervisor's probe then
# sees dead boot ids and fails the orphaned sessions over to a surviving
# pair (protocol/fleet.py) from their newest checkpoints.
#
# Grammar (``FHH_HOST_FAULTS``): ``host:kill@window=<N>``, ';'-separated,
# consumed once each like the mesh clauses.
# ---------------------------------------------------------------------------

_HOST_ACTIONS = ("kill",)


@dataclass(frozen=True)
class HostFaultSpec:
    action: str
    at_window: int

    def __post_init__(self):
        if self.action not in _HOST_ACTIONS:
            raise ValueError(f"unknown host chaos action {self.action!r}")
        if self.at_window < 0:
            raise ValueError("window= trigger must be >= 0")


def parse_host_faults(spec: str) -> list:
    """Parse an ``FHH_HOST_FAULTS`` spec (grammar above).  Blank specs
    parse to no faults; malformed clauses raise ValueError loudly, same
    contract as :func:`parse_faults`/:func:`parse_mesh_faults`."""
    out: list[HostFaultSpec] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            head, args = clause.split("@", 1)
            link, action = head.split(":", 1)
        except ValueError:
            raise ValueError(
                f"bad host chaos clause {clause!r} "
                "(want host:kill@window=N)"
            ) from None
        if link.strip() != "host":
            raise ValueError(f"host chaos clause {clause!r} must target 'host'")
        kw: dict = {}
        for part in args.split(","):
            k, _, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if k == "window":
                kw["at_window"] = int(v)
            else:
                raise ValueError(f"unknown host chaos arg {k!r} in {clause!r}")
        if "at_window" not in kw:
            raise ValueError(f"host chaos clause {clause!r} missing window=")
        out.append(HostFaultSpec(action=action.strip(), **kw))
    return out


class HostChaos:
    """Consumed-once host-pair fault schedule.  ``before_window(w)``
    returns True when a clause fires for this boundary — the caller
    (test harness / supervisor drill) then kills the whole pair; the
    injector itself stays process-agnostic because "a host" may be two
    in-process servers (tests) or two real processes (bin/server)."""

    def __init__(self, faults: list | None = None):
        self._armed: list[HostFaultSpec] = list(faults or [])
        self.fired: list[tuple[str, int]] = []  # (action, window)

    def before_window(self, window: int) -> bool:
        hit = False
        for f in list(self._armed):
            if window < f.at_window:
                continue
            self._armed.remove(f)
            self.fired.append((f.action, window))
            obs.emit(
                "resilience.host_chaos_fired",
                severity="debug",
                action=f.action,
                window=window,
            )
            obs.trace.instant(
                f"chaos.host_{f.action}", comp="chaos:host", level=window,
            )
            hit = True
        return hit


@dataclass
class ChaosLinks:
    """Convenience bundle for the standard three-link topology: leader→s0,
    leader→s1, s0→s1 data plane — built from one ``FHH_FAULTS`` string.
    ``await start()`` brings all three up; address helpers give the
    through-proxy endpoints the leader/server configs should dial."""

    listen_host: str
    base_port: int  # three consecutive ports: ctl0, ctl1, plane
    ctl0_target: tuple[str, int]
    ctl1_target: tuple[str, int]
    plane_target: tuple[str, int]
    faults: list[FaultSpec] = field(default_factory=list)
    proxies: dict = field(default_factory=dict)

    async def start(self) -> "ChaosLinks":
        for i, (link, tgt) in enumerate(
            (
                ("ctl0", self.ctl0_target),
                ("ctl1", self.ctl1_target),
                ("plane", self.plane_target),
            )
        ):
            p = ChaosProxy(
                self.listen_host,
                self.base_port + i,
                tgt[0],
                tgt[1],
                self.faults,
                link=link,
            )
            self.proxies[link] = await p.start()
        return self

    async def stop(self) -> None:
        for p in self.proxies.values():
            await p.stop()

    def addr(self, link: str) -> tuple[str, int]:
        order = ("ctl0", "ctl1", "plane")
        return self.listen_host, self.base_port + order.index(link)
