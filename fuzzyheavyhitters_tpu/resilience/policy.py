"""Retry/deadline policy: the shared vocabulary for every recovery loop.

The transport layer (protocol/rpc.py), the leader supervision
(protocol/leader_rpc.py), and the chaos tests all speak these types, so
"how long do we wait, how often do we retry, which failures are worth
retrying" is decided in ONE place instead of three fixed-sleep loops with
three hardcoded answers.

Design points:

- **Full jitter** (AWS architecture-blog style): the k-th delay is
  ``uniform(0, min(cap, base·factor^k))``.  Two leaders redialing the
  same restarted server must not reconnect in lockstep.
- **Deadlines compose with retries**: a :class:`Deadline` is a wall-clock
  budget shared across every attempt (dial + send + response), not a
  per-attempt timeout; :meth:`RetryPolicy.delays` stops yielding when the
  deadline cannot fit another attempt.
- **Classification is a default, not a straitjacket**: transient =
  transport-shaped (reset/EOF/refused/timeout/corrupt frame — exactly
  the set ``CollectorClient._read_loop`` treats as connection loss).
  Protocol errors (a server ``__error__`` response, a verb rejecting a
  request) are FATAL to the retry loop: replaying them cannot succeed
  and may not be idempotent-safe at a semantic level the dedup cache
  can't see.
"""

from __future__ import annotations

import asyncio
import pickle
import random
import time
from dataclasses import dataclass, field

from .. import obs

# transport-shaped failures: retrying/redialing has a chance of working.
# asyncio.IncompleteReadError subclasses EOFError; ConnectionError and
# TimeoutError both subclass OSError on 3.10+... except asyncio.TimeoutError
# which aliases TimeoutError from 3.11 only — list both explicitly.
TRANSIENT_ERRORS: tuple[type[BaseException], ...] = (
    ConnectionError,
    EOFError,  # covers asyncio.IncompleteReadError
    OSError,
    TimeoutError,
    asyncio.TimeoutError,
    pickle.UnpicklingError,  # torn/corrupt frame == transport loss
)


def is_transient(exc: BaseException) -> bool:
    """True when ``exc`` is worth a redial/replay.  Everything else is a
    bug or a protocol-level rejection: replaying it burns the budget and
    can mask real failures."""
    return isinstance(exc, TRANSIENT_ERRORS)


class Deadline:
    """A wall-clock budget anchored at construction.  ``budget_s=None``
    means unbounded (every query returns "plenty left")."""

    __slots__ = ("budget_s", "_t0")

    def __init__(self, budget_s: float | None):
        self.budget_s = budget_s
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self) -> float | None:
        """Seconds left (clamped at 0.0), or None when unbounded."""
        if self.budget_s is None:
            return None
        return max(0.0, self.budget_s - self.elapsed())

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0.0

    async def wait_for(self, aw):
        """``asyncio.wait_for`` bounded by what's LEFT of this budget (not
        a fresh per-call timeout): retries share the budget."""
        return await asyncio.wait_for(aw, self.remaining())


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter.

    ``attempts`` counts tries, not retries: ``attempts=1`` means no retry
    at all.  ``rand`` is injectable so tests get deterministic schedules
    (pass ``lambda: 1.0`` for the undithered envelope, ``lambda: 0.0``
    for zero-sleep retries)."""

    base_s: float = 0.05
    cap_s: float = 2.0
    factor: float = 2.0
    attempts: int = 8
    rand: object = field(default=random.random, repr=False, compare=False)

    def delay(self, attempt: int) -> float:
        """Backoff before try ``attempt + 1`` (attempt is 0-indexed)."""
        env = min(self.cap_s, self.base_s * (self.factor ** attempt))
        return env * float(self.rand())

    def delays(self, deadline: Deadline | None = None):
        """Yield the sleep before each RETRY (attempts - 1 values),
        stopping early once ``deadline`` has expired."""
        for attempt in range(self.attempts - 1):
            if deadline is not None and deadline.expired():
                return
            yield self.delay(attempt)


@dataclass(frozen=True)
class VerbBudgets:
    """Per-verb wall-clock budgets for control-plane calls.

    Budgets bound the WHOLE call — every redial, replay, and the server's
    execution — so they must dominate worst-case legitimate latency, not
    typical latency: a first ``tree_crawl`` through a remote-chip tunnel
    pays a multi-minute XLA compile, and ``add_keys`` upload windows ride
    behind hundreds of in-flight peers.  The point is to convert an
    infinite hang (black-holed frames, a wedged peer) into a loud
    TimeoutError on a scale of minutes, not to police fast verbs."""

    default_s: float = 1800.0
    per_verb: dict = field(
        default_factory=lambda: {
            # cheap state verbs: no device work beyond a reset
            "reset": 300.0,
            "__hello__": 60.0,
            "status": 60.0,
            # dial + handshake verbs: bounded by the dial policy inside,
            # the budget is just the loud-failure backstop
            "plane_reset": 600.0,
        }
    )

    def budget(self, verb: str) -> float:
        return float(self.per_verb.get(verb, self.default_s))

    def deadline(self, verb: str) -> Deadline:
        return Deadline(self.budget(verb))


async def retry_async(
    fn,
    policy: RetryPolicy,
    *,
    what: str = "operation",
    deadline: Deadline | None = None,
    classify=is_transient,
):
    """Run ``await fn()`` under ``policy``: transient failures back off
    (full jitter) and retry until attempts or the shared ``deadline``
    run out; fatal failures and exhaustion re-raise the LAST error.

    Emits ``resilience.retry`` per retry so recovery behavior is visible
    in the structured log/run report, never only in a debugger."""
    attempt = 0
    while True:
        try:
            return await fn()
        except BaseException as e:  # classified below; re-raised when fatal
            if not classify(e):
                raise
            attempt += 1
            out_of_tries = attempt >= policy.attempts
            out_of_time = deadline is not None and deadline.expired()
            if out_of_tries or out_of_time:
                raise
            delay = policy.delay(attempt - 1)
            obs.emit(
                "resilience.retry",
                severity="debug",
                what=what,
                attempt=attempt,
                delay_s=round(delay, 4),
                error=f"{type(e).__name__}: {e}",
            )
            await asyncio.sleep(delay)


# the default dial policy: ~10 s of redialing (sum of undithered envelope
# ≈ 0.05·(1+2+4) + 2·6 ≈ 12 s ceiling, typically ~6 s with jitter) — the
# window a supervised restart or a chaos-severed listener needs to come
# back, without stalling a genuinely-down server for minutes
DIAL_POLICY = RetryPolicy(base_s=0.05, cap_s=2.0, factor=2.0, attempts=10)

# one TCP connect attempt: the OS SYN timeout is minutes; a LAN/localhost
# dial that hasn't completed in 5 s is dead — fail it and let the policy
# back off and redial
DIAL_TIMEOUT_S = 5.0

# mid-level shard retry (leader_rpc._shard_call): a transient data-plane
# fault re-keys the plane and re-runs JUST the lost shard.  Few attempts
# on purpose — each retry already rides the client's own redial/replay
# machinery, and a span that fails three times is a server problem the
# full recovery path (checkpoint rollback) owns
SHARD_POLICY = RetryPolicy(base_s=0.05, cap_s=1.0, factor=2.0, attempts=3)
