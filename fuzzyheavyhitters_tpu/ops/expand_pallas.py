"""Fused Pallas frontier expansion — the crawl's dominant chip op.

``expand_share_bits`` (protocol/collect.py) is one ChaCha expansion per
(node, client, dim, side) state emitting BOTH children (the batched twin
of the reference's per-node re-evaluation loop, ref: collect.rs:378-410,
ibDCF.rs:208-227).  With the frontier bucketed and advance turned into a
gather, this expansion IS the level, so it gets the keygen kernel's
layout family (ops/keygen_pallas.py: state index spread over (row,
sublane, lane), cipher words as [R_BLK, 8, LANES] vregs).

Round-4 measured status (v5e, B = 1M states): the kernel body beats the
XLA level (~5 ms vs ~16 ms) but the word-planar glue — [B, 4] seed
transposes in and two child-seed transposes out — costs ~25 ms, so the
end-to-end call LOSES to XLA (~37 ms) and ``collect.EXPAND_PALLAS``
defaults False.  The glue-free variant (slice the minor seed axis
in-kernel) hangs the Mosaic compiler.  Flipping the default requires
keeping frontier seeds word-planar across the crawl; kept in-tree,
bit-exact and parity-tested, as that fast path's kernel.

Scope: a pure flat map over B states — the caller keeps the correction-
word broadcast over nodes, reshapes, and the share-bit packing in XLA
(bandwidth-trivial next to the cipher).  Emits both-direction child
seeds (t-corrected), t-bits, and y-bits: exactly the child-state cache +
share-bit inputs of collect._expand_share_bits_jit, bit-exact in both
PRG bit modes (tests/test_expand_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .keygen_pallas import LANES, SUB, _chacha16

# row-groups per grid step.  Small on purpose: this kernel's blocks are
# output-heavy (two child-seed planes), and at R_BLK=32 a block's footprint
# (~13 MB) fills VMEM, serializing DMA against compute — measured 11 ms vs
# 5 ms at R_BLK=4 for the same 1M-state batch.
R_BLK = 4


def _kernel(derived_bits: bool,
            seed_ref, t_ref, y_ref, cws_ref, cwbl_ref, cwbr_ref,
            cwyl_ref, cwyr_ref,
            osl_ref, osr_ref, obl_ref, obr_ref, oyl_ref, oyr_ref):
    """One row block, all u32 (flags as 0/1 words, selects as XOR-masks;
    Mosaic rejects vector i1).  seed/cw_seed u32[4, R_BLK, 8, LANES],
    everything else u32[R_BLK, 8, LANES]."""
    t = t_ref[...]
    tm = jnp.uint32(0) - t
    blk = [seed_ref[w] for w in range(4)]
    blk[0] = blk[0] & jnp.uint32(0xFFFFFFF0)  # prg.rs:97 mask
    out = _chacha16(blk)
    for w in range(4):  # both children, t-gated seed correction
        osl_ref[w] = out[w] ^ (tm & cws_ref[w])
        osr_ref[w] = out[4 + w] ^ (tm & cws_ref[w])
    if derived_bits:
        w8 = out[8]
        b_l, b_r = (w8 & 1) ^ 1, ((w8 >> 1) & 1) ^ 1
        y_l, y_r = ((w8 >> 2) & 1) ^ 1, ((w8 >> 3) & 1) ^ 1
    else:  # the reference's masked-byte constants (prg.rs:103-104)
        b_l = b_r = y_l = y_r = jnp.full(t.shape, 1, jnp.uint32)
    y = y_ref[...]
    obl_ref[...] = b_l ^ (t & cwbl_ref[...])
    obr_ref[...] = b_r ^ (t & cwbr_ref[...])
    oyl_ref[...] = y_l ^ (t & cwyl_ref[...]) ^ y
    oyr_ref[...] = y_r ^ (t & cwyr_ref[...]) ^ y


@partial(jax.jit, static_argnames=("derived_bits",))
def expand_flat(seed, t, y, cw_seed, cwb_l, cwb_r, cwy_l, cwy_r,
                derived_bits: bool):
    """Expand B flat states into both children.

    seed/cw_seed: u32[B, 4]; t, y, cwb_l/r, cwy_l/r: bool[B].
    Returns (seed_l, seed_r u32[B, 4], bit_l, bit_r, y_l, y_r bool[B]) —
    the per-direction outputs of collect's expand recurrence (child seed
    already t-corrected, y accumulated along the path).
    """
    from jax.experimental import pallas as pl

    B = seed.shape[0]
    group = SUB * LANES
    pad = (-B) % (group * R_BLK)
    bp = B + pad
    rows = bp // group

    def flags(a):
        a = jnp.asarray(a, jnp.uint32)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad,), jnp.uint32)])
        return a.reshape(rows, SUB, LANES)

    def words(a):
        a = jnp.asarray(a, jnp.uint32)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad, 4), jnp.uint32)])
        return jnp.transpose(a.reshape(rows, SUB, LANES, 4), (3, 0, 1, 2))

    z = np.int32(0)
    spec4 = pl.BlockSpec((4, R_BLK, SUB, LANES), lambda j: (z, j, z, z))
    spec1 = pl.BlockSpec((R_BLK, SUB, LANES), lambda j: (j, z, z))
    s4 = jax.ShapeDtypeStruct((4, rows, SUB, LANES), jnp.uint32)
    s1 = jax.ShapeDtypeStruct((rows, SUB, LANES), jnp.uint32)
    sl, sr, bl, br, yl, yr = pl.pallas_call(
        partial(_kernel, derived_bits),
        grid=(rows // R_BLK,),
        in_specs=[spec4, spec1, spec1, spec4, spec1, spec1, spec1, spec1],
        out_specs=[spec4, spec4, spec1, spec1, spec1, spec1],
        out_shape=[s4, s4, s1, s1, s1, s1],
    )(words(seed), flags(t), flags(y), words(cw_seed),
      flags(cwb_l), flags(cwb_r), flags(cwy_l), flags(cwy_r))
    unw = lambda a: jnp.transpose(a, (1, 2, 3, 0)).reshape(bp, 4)[:B]
    unf = lambda a: a.reshape(bp)[:B] != 0
    return unw(sl), unw(sr), unf(bl), unf(br), unf(yl), unf(yr)
