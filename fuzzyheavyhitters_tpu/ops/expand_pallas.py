"""Fused Pallas frontier expansion — the crawl's dominant chip op.

``expand_share_bits`` (protocol/collect.py) is one ChaCha expansion per
(node, client, dim, side) state emitting BOTH children (the batched twin
of the reference's per-node re-evaluation loop, ref: collect.rs:378-410,
ibDCF.rs:208-227).  With the frontier bucketed and advance turned into a
gather, this expansion IS the level, so it gets the keygen kernel's
layout family (ops/keygen_pallas.py: state index spread over (row,
sublane, lane), cipher words as [R_BLK, 8, LANES] vregs).

Lesson of the round-4 engine (word-planar seeds, share-bit packing left
to XLA): the kernel body beat the XLA level, but XLA cannot fuse the
pack/cache glue across a ``pallas_call`` boundary, and the unfused
elementwise surround ate the win.  This engine therefore moves the WHOLE
per-level recurrence into one kernel:

- **plane-major layout**: the frontier state axis order is
  ``[d, 2, F, N]`` — one (dim, side) *plane* per leading index — so one
  kernel block sees all ``d2 = d*2`` planes of the same (node, client)
  rows and can combine them;
- **packed share bits emitted in-kernel**: the ``uint32[F, N]`` packed
  tensor (bit ``dim*4 + side*2 + dir``, collect._bit_positions) is a
  kernel output, not an XLA epilogue — the round-4 glue is gone;
- **flag words packed**: the per-plane t/y bits travel as ONE u32 operand
  (bit 0 = t, bit 1 = y) and the per-plane cw bits as one u32
  (cwb_l|cwb_r|cwy_l|cwy_r at bits 0..3), halving the operand count of
  the round-4 kernel (7 refs vs 14);
- **correction words ride an N-periodic BlockSpec**: cw tensors are
  per-(client, plane) and broadcast over the node axis; when ``N`` is a
  multiple of the block group the kernel re-reads the same cw block via a
  modular index map (no materialized broadcast); otherwise the wrapper
  materializes the broadcast (small-N test shapes only).

Emits the packed share bits plus the both-direction child cache
(t-corrected child seeds, child t/y flag words): exactly what
collect._expand_share_bits_jit needs, bit-exact in both PRG bit modes
(tests/test_expand_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .keygen_pallas import LANES, SUB, _chacha16

# row-groups per grid step.  Swept on-chip at the production shape
# (B = 524288 rows x 2 planes): 4 -> 4.22 ms, 8 -> 3.91, 16 -> 3.97,
# 32 -> 4.03 — this kernel's packed flag words keep blocks slim enough
# that R_BLK=8 fits VMEM comfortably (the round-4 kernel's 14 fat refs
# forced R_BLK=4).
R_BLK = 8
GROUP = SUB * LANES  # states per row


def _kernel(d2: int, derived_bits: bool, want_children: bool,
            seed_ref, flags_ref, cws_ref, cwf_ref,
            packed_ref, *child_refs):
    """One row block over all d2 planes; all u32 (flags as 0/1 bit-fields,
    selects as XOR-masks; Mosaic rejects vector i1).

    seed_ref/cws_ref u32[4*d2, R_BLK, 8, LANES] (word-major: plane p of
    word w at index ``w*d2 + p``); flags_ref/cwf_ref
    u32[d2, R_BLK, 8, LANES]; packed_ref u32[R_BLK, 8, LANES]; child_refs
    (if want_children) = (oseeds u32[8*d2, R_BLK, 8, LANES] at index
    ``(dir*4 + w)*d2 + p``, oflags u32[d2, R_BLK, 8, LANES]).
    """
    if want_children:
        oseeds_ref, oflags_ref = child_refs
    packed = None
    one = jnp.uint32(1)
    # compute in collapsed 2-D [R_BLK*8, LANES] vregs: the 3-D block form
    # costs ~7% on-chip (measured back-to-back, bit-exact either way)
    sh2 = (R_BLK * SUB, LANES)
    sh3 = (R_BLK, SUB, LANES)
    for p in range(d2):
        f = flags_ref[p].reshape(sh2)
        t = f & one
        y = (f >> 1) & one
        tm = jnp.uint32(0) - t
        blk = [seed_ref[w * d2 + p].reshape(sh2) for w in range(4)]
        blk[0] = blk[0] & jnp.uint32(0xFFFFFFF0)  # prg.rs:97 mask
        out = _chacha16(blk)
        if want_children:
            for w in range(4):  # both children, t-gated seed correction
                cw = cws_ref[w * d2 + p].reshape(sh2)
                oseeds_ref[w * d2 + p] = (out[w] ^ (tm & cw)).reshape(sh3)
                oseeds_ref[(4 + w) * d2 + p] = (out[4 + w] ^ (tm & cw)).reshape(sh3)
        if derived_bits:
            w8 = out[8]
            b_l, b_r = (w8 & one) ^ one, ((w8 >> 1) & one) ^ one
            y_l, y_r = ((w8 >> 2) & one) ^ one, ((w8 >> 3) & one) ^ one
        else:  # the reference's masked-byte constants (prg.rs:103-104)
            b_l = b_r = y_l = y_r = jnp.full(t.shape, 1, jnp.uint32)
        cf = cwf_ref[p].reshape(sh2)
        bl = b_l ^ (t & (cf & one))
        br = b_r ^ (t & ((cf >> 1) & one))
        yl = y_l ^ (t & ((cf >> 2) & one)) ^ y
        yr = y_r ^ (t & ((cf >> 3) & one)) ^ y
        if want_children:
            oflags_ref[p] = (bl | (br << 1) | (yl << 2) | (yr << 3)).reshape(sh3)
        # share bit = y ^ t per direction, packed at dim*4 + side*2 + dir
        # (collect._bit_positions; plane p = dim*2 + side)
        contrib = ((bl ^ yl) << (2 * p)) | ((br ^ yr) << (2 * p + 1))
        packed = contrib if packed is None else packed | contrib
    packed_ref[...] = packed.reshape(sh3)


@partial(jax.jit, static_argnames=("derived_bits", "want_children"))
def expand_packed(seed_p, t, y, cws_n, cwf_n, derived_bits: bool,
                  want_children: bool = True):
    """Expand B = F*N (node, client) rows across all d2 planes in one call.

    seed_p: u32[4, d2, B] plane-major frontier seeds;
    t, y:   bool/u32[d2, B] per-plane eval-state bits;
    cws_n:  u32[4, d2, N] per-client correction seeds for this level;
    cwf_n:  u32[d2, N] packed cw bits (cwb_l|cwb_r<<1|cwy_l<<2|cwy_r<<3).

    Returns ``(packed u32[B], oseeds, oflags)`` — oseeds u32[2, 4, d2, B]
    (leading axis = direction, t-corrected child seeds), oflags u32[d2, B]
    (bl|br<<1|yl<<2|yr<<3, y accumulated along the path); both None when
    ``want_children=False`` (the last level).
    """
    from jax.experimental import pallas as pl

    d2, B = t.shape[0], t.shape[1]
    N = cwf_n.shape[-1]
    blk_rows = R_BLK * GROUP  # states per grid step

    flags = jnp.asarray(t, jnp.uint32) | (jnp.asarray(y, jnp.uint32) << 1)
    seed_p = jnp.asarray(seed_p, jnp.uint32).reshape(4 * d2, B)
    cws_n = jnp.asarray(cws_n, jnp.uint32).reshape(4 * d2, N)
    cwf_n = jnp.asarray(cwf_n, jnp.uint32)

    periodic = (N % blk_rows == 0) and (B % N == 0)
    if periodic:
        bp, pad = B, 0
        cws_op = cws_n.reshape(4 * d2, N // GROUP, SUB, LANES)
        cwf_op = cwf_n.reshape(d2, N // GROUP, SUB, LANES)
        nblk = np.int32(N // blk_rows)
        cw_j = lambda j: j % nblk
    else:  # small/test shapes: materialize the node-axis broadcast
        pad = (-B) % blk_rows
        bp = B + pad
        reps = -(-bp // N)
        tile = lambda a: jnp.tile(a, (1,) * (a.ndim - 1) + (reps,))[..., :bp]
        cws_op = tile(cws_n).reshape(4 * d2, bp // GROUP, SUB, LANES)
        cwf_op = tile(cwf_n).reshape(d2, bp // GROUP, SUB, LANES)
        cw_j = lambda j: j
    rows = bp // GROUP

    def padded(a):
        if pad:
            a = jnp.concatenate(
                [a, jnp.zeros(a.shape[:-1] + (pad,), jnp.uint32)], axis=-1
            )
        return a.reshape(a.shape[:-1] + (rows, SUB, LANES))

    z = np.int32(0)
    spec_seed = pl.BlockSpec((4 * d2, R_BLK, SUB, LANES),
                             lambda j: (z, j, z, z))
    spec_flag = pl.BlockSpec((d2, R_BLK, SUB, LANES), lambda j: (z, j, z, z))
    spec_cws = pl.BlockSpec((4 * d2, R_BLK, SUB, LANES),
                            lambda j: (z, cw_j(j), z, z))
    spec_cwf = pl.BlockSpec((d2, R_BLK, SUB, LANES),
                            lambda j: (z, cw_j(j), z, z))
    spec_pack = pl.BlockSpec((R_BLK, SUB, LANES), lambda j: (j, z, z))
    out_specs = [spec_pack]
    out_shape = [jax.ShapeDtypeStruct((rows, SUB, LANES), jnp.uint32)]
    if want_children:
        out_specs += [
            pl.BlockSpec((8 * d2, R_BLK, SUB, LANES), lambda j: (z, j, z, z)),
            spec_flag,
        ]
        out_shape += [
            jax.ShapeDtypeStruct((8 * d2, rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((d2, rows, SUB, LANES), jnp.uint32),
        ]
    outs = pl.pallas_call(
        partial(_kernel, d2, derived_bits, want_children),
        grid=(rows // R_BLK,),
        in_specs=[spec_seed, spec_flag, spec_cws, spec_cwf],
        out_specs=out_specs,
        out_shape=out_shape,
    )(padded(seed_p), padded(flags), cws_op, cwf_op)
    packed = outs[0].reshape(bp)[:B]
    if not want_children:
        return packed, None, None
    # [8*d2, bp] -> [2, 4, d2, B]: index (dir*4 + w)*d2 + p is exactly the
    # row-major order of (dir, word, plane)
    oseeds = outs[1].reshape(2, 4, d2, bp)[..., :B]
    oflags = outs[2].reshape(d2, bp)[:, :B]
    return packed, oseeds, oflags
