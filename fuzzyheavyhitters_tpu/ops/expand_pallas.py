"""Fused Pallas frontier expansion — the crawl's dominant chip op.

``expand_share_bits`` (protocol/collect.py) is one ChaCha expansion per
(node, client, dim, side) state emitting BOTH children (the batched twin
of the reference's per-node re-evaluation loop, ref: collect.rs:378-410,
ibDCF.rs:208-227).  With the frontier bucketed and advance turned into a
gather, this expansion IS the level, so it gets the keygen kernel's
layout family (ops/keygen_pallas.py: state index spread over (row,
sublane, lane), cipher words as [R_BLK, 8, LANES] vregs).

Round-4 measured status (v5e, B = 1M states): the kernel body beats the
XLA level (~5 ms vs ~16 ms), but interleaved ``[B, 4]`` seeds need
word-planar transposes in and out costing ~25 ms — so the production
path is :func:`expand_flat_planar`, with frontier seeds kept WORD-PLANAR
``[4, ...]`` across the whole crawl (protocol/collect.py's planar
engine): every layout step is a reshape, never a transpose.  The
interleaved :func:`expand_flat` survives only for its parity test; the
in-kernel minor-axis-slice variant (no planar state at all) hangs the
Mosaic compiler and is not used.

Scope: a pure flat map over B states — the caller keeps the correction-
word broadcast over nodes, reshapes, and the share-bit packing in XLA
(bandwidth-trivial next to the cipher).  Emits both-direction child
seeds (t-corrected), t-bits, and y-bits: exactly the child-state cache +
share-bit inputs of collect._expand_share_bits_jit, bit-exact in both
PRG bit modes (tests/test_expand_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .keygen_pallas import LANES, SUB, _chacha16

# row-groups per grid step.  Small on purpose: this kernel's blocks are
# output-heavy (two child-seed planes), and at R_BLK=32 a block's footprint
# (~13 MB) fills VMEM, serializing DMA against compute — measured 11 ms vs
# 5 ms at R_BLK=4 for the same 1M-state batch.
R_BLK = 4


def _kernel(derived_bits: bool,
            seed_ref, t_ref, y_ref, cws_ref, cwbl_ref, cwbr_ref,
            cwyl_ref, cwyr_ref,
            osl_ref, osr_ref, obl_ref, obr_ref, oyl_ref, oyr_ref):
    """One row block, all u32 (flags as 0/1 words, selects as XOR-masks;
    Mosaic rejects vector i1).  seed/cw_seed u32[4, R_BLK, 8, LANES],
    everything else u32[R_BLK, 8, LANES]."""
    t = t_ref[...]
    tm = jnp.uint32(0) - t
    blk = [seed_ref[w] for w in range(4)]
    blk[0] = blk[0] & jnp.uint32(0xFFFFFFF0)  # prg.rs:97 mask
    out = _chacha16(blk)
    for w in range(4):  # both children, t-gated seed correction
        osl_ref[w] = out[w] ^ (tm & cws_ref[w])
        osr_ref[w] = out[4 + w] ^ (tm & cws_ref[w])
    if derived_bits:
        w8 = out[8]
        b_l, b_r = (w8 & 1) ^ 1, ((w8 >> 1) & 1) ^ 1
        y_l, y_r = ((w8 >> 2) & 1) ^ 1, ((w8 >> 3) & 1) ^ 1
    else:  # the reference's masked-byte constants (prg.rs:103-104)
        b_l = b_r = y_l = y_r = jnp.full(t.shape, 1, jnp.uint32)
    y = y_ref[...]
    obl_ref[...] = b_l ^ (t & cwbl_ref[...])
    obr_ref[...] = b_r ^ (t & cwbr_ref[...])
    oyl_ref[...] = y_l ^ (t & cwyl_ref[...]) ^ y
    oyr_ref[...] = y_r ^ (t & cwyr_ref[...]) ^ y


def _padded_rows(B: int) -> tuple[int, int]:
    group = SUB * LANES
    pad = (-B) % (group * R_BLK)
    return B + pad, (B + pad) // group


@partial(jax.jit, static_argnames=("derived_bits",))
def expand_flat_planar(seed_p, t, y, cws_p, cwb_l, cwb_r, cwy_l, cwy_r,
                       derived_bits: bool):
    """Expand B flat states into both children, word-planar operands.

    seed_p/cws_p: u32[4, B] (word-planar); t, y, cwb_l/r, cwy_l/r:
    bool/u32[B].  Returns (seed_l, seed_r u32[4, B] planar, bit_l, bit_r,
    y_l, y_r bool[B]) — the per-direction outputs of collect's expand
    recurrence (child seed already t-corrected, y accumulated along the
    path).  All layout work is reshape-only: the caller keeps seeds
    planar across the crawl, so no transpose ever materializes.
    """
    from jax.experimental import pallas as pl

    B = seed_p.shape[1]
    bp, rows = _padded_rows(B)
    pad = bp - B

    def flags(a):
        a = jnp.asarray(a, jnp.uint32)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad,), jnp.uint32)])
        return a.reshape(rows, SUB, LANES)

    def words(a):  # u32[4, B] -> [4, rows, SUB, LANES], reshape only
        a = jnp.asarray(a, jnp.uint32)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((4, pad), jnp.uint32)], axis=1)
        return a.reshape(4, rows, SUB, LANES)

    z = np.int32(0)
    spec4 = pl.BlockSpec((4, R_BLK, SUB, LANES), lambda j: (z, j, z, z))
    spec1 = pl.BlockSpec((R_BLK, SUB, LANES), lambda j: (j, z, z))
    s4 = jax.ShapeDtypeStruct((4, rows, SUB, LANES), jnp.uint32)
    s1 = jax.ShapeDtypeStruct((rows, SUB, LANES), jnp.uint32)
    sl, sr, bl, br, yl, yr = pl.pallas_call(
        partial(_kernel, derived_bits),
        grid=(rows // R_BLK,),
        in_specs=[spec4, spec1, spec1, spec4, spec1, spec1, spec1, spec1],
        out_specs=[spec4, spec4, spec1, spec1, spec1, spec1],
        out_shape=[s4, s4, s1, s1, s1, s1],
    )(words(seed_p), flags(t), flags(y), words(cws_p),
      flags(cwb_l), flags(cwb_r), flags(cwy_l), flags(cwy_r))
    unw = lambda a: a.reshape(4, bp)[:, :B]
    unf = lambda a: a.reshape(bp)[:B] != 0
    return unw(sl), unw(sr), unf(bl), unf(br), unf(yl), unf(yr)


@partial(jax.jit, static_argnames=("derived_bits",))
def expand_flat(seed, t, y, cw_seed, cwb_l, cwb_r, cwy_l, cwy_r,
                derived_bits: bool):
    """Interleaved-layout entry point ([B, 4] seeds): transposes to the
    planar form and back.  Measured SLOWER than the XLA expand end to end
    (the transposes dominate) — kept for the bit-exactness parity test;
    production uses :func:`expand_flat_planar`."""
    tr = lambda a: jnp.transpose(jnp.asarray(a, jnp.uint32), (1, 0))
    sl, sr, bl, br, yl, yr = expand_flat_planar(
        tr(seed), t, y, tr(cw_seed), cwb_l, cwb_r, cwy_l, cwy_r, derived_bits
    )
    return tr(sl), tr(sr), bl, br, yl, yr
