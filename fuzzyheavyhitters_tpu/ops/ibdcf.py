"""ibDCF — interval-bound Distributed Comparison Functions as tensor batches.

The reference implements one key at a time with per-bit Rust loops
(ref: src/ibDCF.rs:84-164 keygen, 208-236 eval).  Here a *batch* of keys is a
pytree of arrays with arbitrary leading batch dims (clients × dims × sides…):
keygen is one ``lax.scan`` over the ``data_len`` levels with every key in the
batch advancing together, and the per-level incremental eval
(ref: ibDCF.rs:208-227) is one fused batched expression — the per-key loops of
the reference become single device programs.

Key material layout (SURVEY.md §7 data layout):

- ``root_seed``  uint32[..., 4]          (128-bit seed per key)
- ``cw_seed``    uint32[..., L, 4]       (per-level correction seeds)
- ``cw_bits``    bool[..., L, 2]         (t-bit corrections, left/right)
- ``cw_y_bits``  bool[..., L, 2]         (y-bit corrections, left/right)
- ``key_idx``    bool[...]               (which party: False=0, True=1)

Semantics (pinned by tests/oracle.py and its full-domain sweeps): with keys on
bound ``b``, XOR of the two parties' share bits (``y_bit ^ bit``) after
evaluating MSB-first input ``x`` is ``[x < b]`` for a side=True ("left") key
and ``[x > b]`` for side=False ("right"); share-string equality over
(dim × {left,right}) therefore encodes inclusive L∞-ball membership.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import prg
from ..utils import bits as bitutils


class IbDcfKeyBatch(NamedTuple):
    """A batch of ibDCF keys for ONE party (ref: ibDCF.rs:17-21)."""

    key_idx: jax.Array  # bool[...]
    root_seed: jax.Array  # uint32[..., 4]
    cw_seed: jax.Array  # uint32[..., L, 4]
    cw_bits: jax.Array  # bool[..., L, 2]
    cw_y_bits: jax.Array  # bool[..., L, 2]

    @property
    def data_len(self) -> int:
        return self.cw_seed.shape[-2]

    @property
    def batch_shape(self):
        return self.cw_seed.shape[:-2]


class EvalState(NamedTuple):
    """Per-key incremental evaluation state (ref: ibDCF.rs:25-30).

    The level index lives with the caller (the whole batch is always at the
    same level, so it is a host-side scalar, not a tensor).
    """

    seed: jax.Array  # uint32[..., 4]
    bit: jax.Array  # bool[...]
    y_bit: jax.Array  # bool[...]


def _bxor(a, b):
    return jnp.logical_xor(a, b)


def gen_pair(
    init_seeds: jax.Array, alpha_bits: jax.Array, side: jax.Array
) -> tuple[IbDcfKeyBatch, IbDcfKeyBatch]:
    """Generate both parties' key batches in one scan over levels.

    init_seeds: uint32[..., 2, 4] fresh random root seeds (party axis of 2);
    alpha_bits: bool[..., L] MSB-first bound per key;
    side:       bool[...] True = "left"/less-than key (ref: ibDCF.rs:138-164).

    Returns (party0 batch, party1 batch) sharing identical correction words
    (ref: ibDCF.rs:84-119 ``gen_cor_word`` — the per-level recurrence).
    """
    # PRG bit mode resolved eagerly so it participates in the jit cache key
    # (a trace must never bake in a stale prg.DERIVED_BITS).
    return _gen_pair_jit(init_seeds, alpha_bits, side, prg.DERIVED_BITS)


@partial(jax.jit, static_argnames=("derived_bits",))
def _gen_pair_jit(init_seeds, alpha_bits, side, derived_bits):
    init_seeds = jnp.asarray(init_seeds, jnp.uint32)
    alpha_bits = jnp.asarray(alpha_bits, bool)
    side = jnp.broadcast_to(jnp.asarray(side, bool), alpha_bits.shape[:-1])
    batch = alpha_bits.shape[:-1]
    assert init_seeds.shape == batch + (2, 4), (init_seeds.shape, batch)

    def step(carry, alpha_bit):
        seeds, tbits = carry  # uint32[..., 2, 4], bool[..., 2]
        s_l, s_r, d_bits, d_y = prg.expand(seeds, derived_bits)  # [..., 2, 4]
        keep = alpha_bit  # bool[...]
        k = keep[..., None]
        # lose-direction child seeds XOR across parties (ibDCF.rs:95-97)
        cw_seed = jnp.where(
            k, s_l[..., 0, :] ^ s_l[..., 1, :], s_r[..., 0, :] ^ s_r[..., 1, :]
        )
        cw_bits = jnp.stack(
            [
                _bxor(_bxor(d_bits[..., 0, 0], d_bits[..., 1, 0]), ~keep),
                _bxor(_bxor(d_bits[..., 0, 1], d_bits[..., 1, 1]), keep),
            ],
            axis=-1,
        )  # (ibDCF.rs:99-101: t_l ^= !bit… here bit^1 on left, bit on right)
        cw_y_bits = jnp.stack(
            [
                _bxor(_bxor(d_y[..., 0, 0], d_y[..., 1, 0]), keep & ~side),
                _bxor(_bxor(d_y[..., 0, 1], d_y[..., 1, 1]), ~keep & side),
            ],
            axis=-1,
        )  # (ibDCF.rs:103-108: side-dependent payload bits)
        # each party keeps the alpha-direction child (ibDCF.rs:109-117)
        kept_seed = jnp.where(k[..., None, :], s_r, s_l)  # [..., 2, 4]
        kept_bit = jnp.where(k, d_bits[..., 1], d_bits[..., 0])  # [..., 2]
        t = tbits[..., None]  # correction applies iff party's t-bit set
        new_seeds = jnp.where(t, kept_seed ^ cw_seed[..., None, :], kept_seed)
        cw_keep_bit = jnp.where(keep, cw_bits[..., 1], cw_bits[..., 0])
        new_tbits = _bxor(kept_bit, tbits & cw_keep_bit[..., None])
        return (new_seeds, new_tbits), (cw_seed, cw_bits, cw_y_bits)

    init_tbits = jnp.broadcast_to(
        jnp.array([False, True]), batch + (2,)
    )  # party 0 starts t=0, party 1 t=1 (ibDCF.rs:143-146)
    alpha_first = jnp.moveaxis(alpha_bits, -1, 0)
    (_, _), (cw_seed, cw_bits, cw_y_bits) = jax.lax.scan(
        step, (init_seeds, init_tbits), alpha_first
    )
    # scan stacks the level axis first; move it to its [..., L, …] slot
    cw_seed = jnp.moveaxis(cw_seed, 0, -2)
    cw_bits = jnp.moveaxis(cw_bits, 0, -2)
    cw_y_bits = jnp.moveaxis(cw_y_bits, 0, -2)

    def mk(p: int) -> IbDcfKeyBatch:
        return IbDcfKeyBatch(
            key_idx=jnp.broadcast_to(jnp.asarray(bool(p)), batch),
            root_seed=init_seeds[..., p, :],
            cw_seed=cw_seed,
            cw_bits=cw_bits,
            cw_y_bits=cw_y_bits,
        )

    return mk(0), mk(1)


def gen_pair_np(
    init_seeds: np.ndarray,
    alpha_bits: np.ndarray,
    side: np.ndarray,
    derived_bits: bool | None = None,
) -> tuple[IbDcfKeyBatch, IbDcfKeyBatch]:
    """NumPy mirror of :func:`gen_pair` — bit-identical key batches.

    The level recurrence runs as a Python loop over ``L`` with every key in
    the batch advancing as vectorized numpy — no device, no compilation.
    Used by host-side client simulation and by CPU-mesh dryruns/tests, where
    compiling the keygen scan on XLA:CPU is pathologically slow
    (tests/conftest.py documents the measurement).
    """
    if derived_bits is None:
        derived_bits = prg.DERIVED_BITS
    init_seeds = np.asarray(init_seeds, np.uint32)
    alpha = np.asarray(alpha_bits, bool)
    batch = alpha.shape[:-1]
    side = np.broadcast_to(np.asarray(side, bool), batch)
    L = alpha.shape[-1]
    assert init_seeds.shape == batch + (2, 4), (init_seeds.shape, batch)

    seeds = init_seeds.copy()  # [..., 2, 4]
    tbits = np.broadcast_to(np.array([False, True]), batch + (2,)).copy()
    cw_seed = np.empty(batch + (L, 4), np.uint32)
    cw_bits = np.empty(batch + (L, 2), bool)
    cw_y = np.empty(batch + (L, 2), bool)
    for lvl in range(L):
        s_l, s_r, d_bits, d_y = prg.np_expand(seeds, derived_bits)
        keep = alpha[..., lvl]  # bool[...]
        k1 = keep[..., None]
        cw_seed[..., lvl, :] = np.where(
            k1, s_l[..., 0, :] ^ s_l[..., 1, :], s_r[..., 0, :] ^ s_r[..., 1, :]
        )
        cw_bits[..., lvl, 0] = d_bits[..., 0, 0] ^ d_bits[..., 1, 0] ^ ~keep
        cw_bits[..., lvl, 1] = d_bits[..., 0, 1] ^ d_bits[..., 1, 1] ^ keep
        cw_y[..., lvl, 0] = d_y[..., 0, 0] ^ d_y[..., 1, 0] ^ (keep & ~side)
        cw_y[..., lvl, 1] = d_y[..., 0, 1] ^ d_y[..., 1, 1] ^ (~keep & side)
        kept_seed = np.where(keep[..., None, None], s_r, s_l)  # [..., 2, 4]
        kept_bit = np.where(k1, d_bits[..., 1], d_bits[..., 0])  # [..., 2]
        seeds = np.where(
            tbits[..., None], kept_seed ^ cw_seed[..., lvl, None, :], kept_seed
        )
        cw_keep_bit = np.where(keep, cw_bits[..., lvl, 1], cw_bits[..., lvl, 0])
        tbits = kept_bit ^ (tbits & cw_keep_bit[..., None])

    def mk(p: int) -> IbDcfKeyBatch:
        return IbDcfKeyBatch(
            key_idx=np.broadcast_to(np.bool_(bool(p)), batch),
            root_seed=init_seeds[..., p, :],
            cw_seed=cw_seed,
            cw_bits=cw_bits,
            cw_y_bits=cw_y,
        )

    return mk(0), mk(1)


@jax.jit
def eval_init(key: IbDcfKeyBatch) -> EvalState:
    """Root state: seed = root seed, t = y = key_idx (ref: ibDCF.rs:229-236)."""
    return EvalState(
        seed=key.root_seed,
        bit=jnp.asarray(key.key_idx, bool),
        y_bit=jnp.asarray(key.key_idx, bool),
    )


def level_cw(key: IbDcfKeyBatch, level):
    """Correction word(s) at one level: (seed[...,4], bits[...,2], y[...,2]).

    ``level`` may be a traced scalar (for use under scan/while); concrete
    levels are bounds-checked here because JAX's dynamic gather would
    silently clamp an out-of-range index to the last level."""
    if isinstance(level, (int, np.integer)) and not 0 <= level < key.data_len:
        raise IndexError(f"level {level} out of range for data_len {key.data_len}")
    take = lambda a: jax.lax.dynamic_index_in_dim(a, level, axis=a.ndim - 2, keepdims=False)
    return take(key.cw_seed), take(key.cw_bits), take(key.cw_y_bits)


def eval_bit(cw, state: EvalState, direction: jax.Array) -> EvalState:
    """Advance every key in the batch one level (ref: ibDCF.rs:208-227).

    ``cw`` is the output of :func:`level_cw` for the current level;
    ``direction``: bool[...] — the input bit taken at this level (True=right).
    One PRG expansion + masked XORs; no branches, fully batched.
    """
    return _eval_bit_jit(cw, state, direction, prg.DERIVED_BITS)


@partial(jax.jit, static_argnames=("derived_bits",))
def _eval_bit_jit(cw, state: EvalState, direction, derived_bits) -> EvalState:
    cw_seed, cw_bits, cw_y = cw
    direction = jnp.asarray(direction, bool)
    s_l, s_r, tau_bits, tau_y = prg.expand(state.seed, derived_bits)
    d = direction[..., None]
    seed = jnp.where(d, s_r, s_l)
    new_bit = jnp.where(direction, tau_bits[..., 1], tau_bits[..., 0])
    new_y = jnp.where(direction, tau_y[..., 1], tau_y[..., 0])
    cw_bit_d = jnp.where(direction, cw_bits[..., 1], cw_bits[..., 0])
    cw_y_d = jnp.where(direction, cw_y[..., 1], cw_y[..., 0])
    t = state.bit
    seed = jnp.where(t[..., None], seed ^ cw_seed, seed)
    new_bit = _bxor(new_bit, t & cw_bit_d)
    new_y = _bxor(new_y, t & cw_y_d)
    new_y = _bxor(new_y, state.y_bit)  # y accumulates along the path
    return EvalState(seed=seed, bit=new_bit, y_bit=new_y)


def eval_full(key: IbDcfKeyBatch, idx_bits: jax.Array) -> EvalState:
    """Evaluate the whole MSB-first input in one scan (ref: ibDCF.rs:229-255
    ``eval`` / the per-level loop of eval_str at ibDCF.rs:120-131)."""
    return _eval_full_jit(key, idx_bits, prg.DERIVED_BITS)


@partial(jax.jit, static_argnames=("derived_bits",))
def _eval_full_jit(key: IbDcfKeyBatch, idx_bits, derived_bits) -> EvalState:
    idx_bits = jnp.asarray(idx_bits, bool)
    assert idx_bits.shape[-1] == key.data_len

    def step(state, inp):
        direction, cw_seed, cw_bits, cw_y = inp
        new = _eval_bit_jit((cw_seed, cw_bits, cw_y), state, direction, derived_bits)
        return new, None

    # level axis first so scan hands each step its own level's CWs directly
    xs = (
        jnp.moveaxis(idx_bits, -1, 0),
        jnp.moveaxis(key.cw_seed, -2, 0),
        jnp.moveaxis(key.cw_bits, -2, 0),
        jnp.moveaxis(key.cw_y_bits, -2, 0),
    )
    state, _ = jax.lax.scan(step, eval_init(key), xs)
    return state


def share_bit(state: EvalState) -> jax.Array:
    """Per-party FSS output share bit (ref: ibDCF.rs:249, collect.rs:399-404)."""
    return _bxor(state.y_bit, state.bit)


# ---------------------------------------------------------------------------
# Interval / L∞-ball key generation (client-side, host-facing API)
# ---------------------------------------------------------------------------


def _rng_seeds(rng: np.random.Generator, shape) -> np.ndarray:
    return rng.integers(0, 1 << 32, size=tuple(shape) + (2, 4), dtype=np.uint32)


def best_engine() -> str:
    """Fastest keygen engine for the current default backend: the fused
    Pallas kernel (ops/keygen_pallas.py) on an accelerator, the numpy
    mirror on host CPU (where the XLA:CPU scan compile dominates).  The
    deployment binaries (bin/leader.py, bin/mesh.py) select through this
    so the headline keygen throughput never ships on the slow scan engine."""
    from ..utils import effective_platform

    return "np" if effective_platform() == "cpu" else "pallas"


def _gen(engine: str):
    """Select the keygen implementation: "jax" (device scan), "np" (host),
    or "pallas" (the fused single-kernel TPU engine, ops/keygen_pallas.py —
    ~5x the scan engine's throughput on the chip)."""
    if engine == "jax":
        return gen_pair
    if engine == "np":
        return gen_pair_np
    if engine == "pallas":
        from .keygen_pallas import gen_pair_pallas

        return gen_pair_pallas
    raise ValueError(f"unknown keygen engine {engine!r}")


def gen_interval(
    left_bits, right_bits, rng: np.random.Generator, engine: str = "jax"
) -> tuple[tuple[IbDcfKeyBatch, IbDcfKeyBatch], tuple[IbDcfKeyBatch, IbDcfKeyBatch]]:
    """Interval keys: (left-DCF side=True on the left bound, right-DCF
    side=False on the right bound), batched (ref: ibDCF.rs:166-173).

    left_bits/right_bits: bool[..., L].  Returns per-party
    ``((left0, right0), (left1, right1))`` key batches.
    """
    left_bits = np.asarray(left_bits, bool)
    right_bits = np.asarray(right_bits, bool)
    g = _gen(engine)
    l0, l1 = g(_rng_seeds(rng, left_bits.shape[:-1]), left_bits, True)
    r0, r1 = g(_rng_seeds(rng, right_bits.shape[:-1]), right_bits, False)
    return (l0, r0), (l1, r1)


def ball_bounds(points_bits, ball_size: int) -> tuple[np.ndarray, np.ndarray]:
    """Saturating ``point ∓ ball_size`` per dimension on MSB-first bitstrings.

    points_bits: bool[..., L].  Vectorized ripple carry/borrow over the L bit
    positions (host-side numpy; L ≤ 1024 so the Python loop is over bits, not
    clients).  Saturation at the domain edges replaces the reference's
    grow-on-carry / wraparound (ref: src/lib.rs:131-183) — see
    utils/bits.py for the rationale.
    """
    points = np.asarray(points_bits, bool)
    L = points.shape[-1]
    delta = bitutils.int_to_bits(L, min(ball_size, (1 << L) - 1))
    lo = np.empty_like(points)
    hi = np.empty_like(points)
    borrow = np.zeros(points.shape[:-1], bool)
    carry = np.zeros(points.shape[:-1], bool)
    for i in reversed(range(L)):  # LSB-first ripple
        p = points[..., i]
        d = bool(delta[i])
        diff = p ^ d ^ borrow
        borrow = (~p & (d | borrow)) | (d & borrow)
        lo[..., i] = diff
        s = p ^ d ^ carry
        carry = (p & d) | (carry & (p | d))
        hi[..., i] = s
    lo[borrow] = False  # saturate: point - size < 0  -> 0
    hi[carry] = True  # saturate: point + size >= 2^L -> 2^L - 1
    return lo, hi


def gen_l_inf_ball(
    points_bits, ball_size: int, rng: np.random.Generator, engine: str = "jax"
) -> tuple[IbDcfKeyBatch, IbDcfKeyBatch]:
    """L∞-ball keys around MSB-first points (ref: ibDCF.rs:175-188).

    points_bits: bool[N, n_dims, L].  Returns the two parties' key batches of
    shape [N, n_dims, 2] where the trailing axis is (left-DCF, right-DCF) —
    a client's full submission for one server, as one pytree.
    """
    points = np.asarray(points_bits, bool)
    lo, hi = ball_bounds(points, ball_size)
    # stack (left bound w/ side=True, right bound w/ side=False) on axis -2
    alpha = np.stack([lo, hi], axis=-2)  # [N, n_dims, 2, L]
    side = np.broadcast_to(
        np.array([True, False]), alpha.shape[:-1]
    )  # left-DCF then right-DCF
    return _gen(engine)(_rng_seeds(rng, alpha.shape[:-1]), alpha, side)


def gen_l_inf_ball_from_coords(
    coords: np.ndarray, ball_size: int, rng: np.random.Generator, engine: str = "jax"
) -> tuple[IbDcfKeyBatch, IbDcfKeyBatch]:
    """i16 coordinate variant with clamping (ref: ibDCF.rs:189-205).

    coords: int array [N, n_dims] of i16 centidegree values; bounds are
    ``coord ∓ ball_size`` clamped to the i16 range, then encoded as 16-bit
    MSB-first **offset-binary** bitstrings (sign bit flipped — see
    utils/bits.py ``i16_to_ob_bits``).  Deliberate divergence from the
    reference, which feeds raw two's-complement bits
    (sample_driving_data.rs:25-29) into the lexicographic comparator; there,
    any interval crossing zero is unsatisfiable (negatives sort above
    positives as unsigned strings) — latent upstream because the RideAustin
    coordinates never cross zero.  Offset-binary makes unsigned string order
    equal signed order, so zero-crossing balls work; tree paths decode back
    via ``ob_bits_to_i16``.
    """
    coords = np.asarray(coords, np.int64)
    lo = np.clip(coords - ball_size, -(1 << 15), (1 << 15) - 1)
    hi = np.clip(coords + ball_size, -(1 << 15), (1 << 15) - 1)
    to_bits = lambda v: (
        (((v[..., None] & 0xFFFF) ^ 0x8000).astype(np.uint32)
         >> np.arange(15, -1, -1)) & 1
    ).astype(bool)
    alpha = np.stack([to_bits(lo), to_bits(hi)], axis=-2)  # [N, d, 2, 16]
    side = np.broadcast_to(np.array([True, False]), alpha.shape[:-1])
    return _gen(engine)(_rng_seeds(rng, alpha.shape[:-1]), alpha, side)
