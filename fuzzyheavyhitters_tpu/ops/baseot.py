"""Base oblivious transfers: Chou-Orlandi "simplest OT" over Ed25519.

The reference takes its base OTs from ocelot's Alsz OT-extension setup
(ref: src/collect.rs:10-11, 454-461; the swanky stack runs Chou-Orlandi
style base OTs under the hood).  Here the ~128 base OTs per server pair run
entirely host-side in pure Python — they are a one-time, millisecond-scale
setup cost; the per-level OT *extension* is where the volume lives and that
runs as device kernels (ops/otext.py).

Protocol (Chou-Orlandi 2015, semi-honest use):

- sender:   a <- Z_L,  A = aB                         (publishes A)
- receiver: b_i <- Z_L, R_i = c_i*A + b_i*B           (publishes R_i)
- sender:   k0_i = H(a*R_i), k1_i = H(a*R_i - a*A)
- receiver: k(c_i) = H(b_i*A)

so k0_i = k1_i's twin is unlearnable without the receiver's b_i, and the
sender never sees c_i.  H = SHA-256 over the compressed point, truncated to
a 128-bit seed (the OT-extension base seeds).

Curve arithmetic is textbook Ed25519 (twisted Edwards, a = -1) in extended
coordinates with Python ints — ~40 lines, self-checked at import time
against the curve equation and the base-point order.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

import numpy as np

P = 2**255 - 19
L_ORDER = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P

# standard Ed25519 base point
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960


@dataclass(frozen=True)
class Point:
    """Extended coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z."""

    x: int
    y: int
    z: int
    t: int


IDENTITY = Point(0, 1, 1, 0)
BASE = Point(_BX, _BY, 1, (_BX * _BY) % P)


def _add(p: Point, q: Point) -> Point:
    # add-2008-hwcd-3 for a = -1
    a = (p.y - p.x) * (q.y - q.x) % P
    b = (p.y + p.x) * (q.y + q.x) % P
    c = p.t * 2 * D * q.t % P
    d = p.z * 2 * q.z % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return Point(e * f % P, g * h % P, f * g % P, e * h % P)


def _neg(p: Point) -> Point:
    return Point((-p.x) % P, p.y, p.z, (-p.t) % P)


def _mul(k: int, p: Point) -> Point:
    q = IDENTITY
    while k:
        if k & 1:
            q = _add(q, p)
        p = _add(p, p)
        k >>= 1
    return q


def _affine(p: Point) -> tuple[int, int]:
    zi = pow(p.z, P - 2, P)
    return (p.x * zi) % P, (p.y * zi) % P


def _compress(p: Point) -> bytes:
    x, y = _affine(p)
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _self_check() -> None:
    x, y = _affine(BASE)
    assert (-x * x + y * y - 1 - D * x * x * y * y) % P == 0, "base point off-curve"
    assert _affine(_mul(L_ORDER, BASE)) == (0, 1), "base point order mismatch"


_self_check()


def _seed_from_point(p: Point, idx: int) -> np.ndarray:
    """H(index, point) -> 128-bit seed.  The OT index is part of the hash
    input (standard Chou-Orlandi domain separation) so identical points at
    different indices / instances cannot yield identical seeds."""
    data = b"fhh-baseot-v1" + idx.to_bytes(4, "little") + _compress(p)
    digest = hashlib.sha256(data).digest()[:16]
    return np.frombuffer(digest, dtype="<u4").copy()


# ---------------------------------------------------------------------------
# Message-passing API: each side advances with the peer's last message.
# (sender round 1) -> A -> (receiver round) -> [R_i] -> (sender round 2)
# ---------------------------------------------------------------------------


class BaseOtSender:
    """Holds the sender state across the two host round-trips."""

    def __init__(self, rng: secrets.SystemRandom | None = None):
        self._rand = rng or secrets.SystemRandom()
        self._a = self._rand.randrange(1, L_ORDER)
        self._A = _mul(self._a, BASE)

    def round1(self) -> bytes:
        return _compress(self._A)

    def seeds(self, r_points: list[Point]) -> tuple[np.ndarray, np.ndarray]:
        """[R_i] -> (seeds0 uint32[n, 4], seeds1 uint32[n, 4])."""
        neg_aA = _neg(_mul(self._a, self._A))
        k0, k1 = [], []
        for i, r in enumerate(r_points):
            ar = _mul(self._a, r)
            k0.append(_seed_from_point(ar, i))
            k1.append(_seed_from_point(_add(ar, neg_aA), i))
        return np.stack(k0), np.stack(k1)


def decompress(data: bytes) -> Point:
    """Decode a compressed point; raises ValueError on malformed peer input
    (never ``assert`` — a protocol-boundary check must survive ``-O``)."""
    raw = int.from_bytes(data, "little")
    y = raw & ((1 << 255) - 1)
    sign = raw >> 255
    if y >= P:
        raise ValueError("invalid point encoding: y out of range")
    # x^2 = (y^2 - 1) / (d y^2 + 1)
    num = (y * y - 1) % P
    den = (D * y * y + 1) % P
    x2 = num * pow(den, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
    if (x * x - x2) % P != 0:
        raise ValueError("invalid point encoding: not a square")
    if x == 0 and sign:
        raise ValueError("invalid point encoding: sign bit on x = 0")
    if x & 1 != sign:
        x = P - x
    return Point(x, y, 1, (x * y) % P)


_decompress = decompress  # back-compat alias


class BaseOtReceiver:
    """Receiver with choice bits; produces R_i points and the chosen seeds."""

    def __init__(self, choices: np.ndarray, rng: secrets.SystemRandom | None = None):
        self._rand = rng or secrets.SystemRandom()
        self.choices = np.asarray(choices, bool)
        self._bs = [self._rand.randrange(1, L_ORDER) for _ in self.choices]

    def round1(self, sender_msg: bytes) -> list[bytes]:
        A = _decompress(sender_msg)
        self._A = A
        out = []
        for c, b in zip(self.choices, self._bs):
            r = _mul(b, BASE)
            if c:
                r = _add(r, A)
            out.append(_compress(r))
        return out

    def seeds(self) -> np.ndarray:
        """uint32[n, 4] — seed k(c_i) for each choice."""
        return np.stack(
            [_seed_from_point(_mul(b, self._A), i) for i, b in enumerate(self._bs)]
        )


def exchange(
    choices: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run both sides in-process (tests / colocated servers).

    Returns (seeds0, seeds1, chosen) with chosen[i] == seeds{choices[i]}[i].
    """
    sender = BaseOtSender()
    receiver = BaseOtReceiver(choices)
    r_msgs = receiver.round1(sender.round1())
    s0, s1 = sender.seeds([_decompress(m) for m in r_msgs])
    return s0, s1, receiver.seeds()
