"""Fused Pallas garbling/evaluation — the secure level's dominant chip op.

``gc.garble_equality_payload`` / ``gc.eval_equality_payload`` (the
output-label-b2a flow every secure deployment path ships) are
glue-bound as XLA programs, exactly like the round-4 expand engine was:
the hash math is a handful of ChaCha permutations per test, but every
stacked ``_hash_many`` call, ``_maskw`` select, table stack, and pad XOR
materializes another ``[B, 4]`` tensor in HBM.  Measured on-chip
(bench.bench_hash_margin, BENCH_r04): garbling cost is nearly flat in
the ChaCha round count — i.e. it is bandwidth, not cipher arithmetic.

This module runs the WHOLE garble (resp. eval) batch as one kernel in
the expand engine's layout family (ops/expand_pallas.py): tests spread
over (row, sublane, lane), every label word a full ``[R_BLK*8, LANES]``
vreg, the AND-tree unrolled over wire planes in-kernel:

- garbler: XNOR relabel, half-gates tree (4 hashes/gate), output decode,
  and the b2a payload ciphertexts under the output-wire labels — all
  without leaving VMEM;
- evaluator: tree eval (2 hashes/gate), decode share, payload-pad open.

Randomness stays OUTSIDE the kernel: the garbler's own labels + mask
bits come from the same ``gc._carve_label_words`` stream draw as the XLA
engine, so both engines are BIT-EXACT for identical inputs — the parity
test compares entire ``GarbledEqBatch``es (tests/test_gc_pallas.py), and
a mid-crawl engine switch is sound (the wire format does not change).

Ref seam: src/equalitytest.rs:25-191 (the per-core swanky garbler this
batched kernel replaces) driven from src/collect.rs:419-482.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import gc, otext
from .keygen_pallas import LANES, SUB, _chacha16

R_BLK = 8  # row-groups per grid step (sweep note: bench.bench_secure_device)
GROUP = SUB * LANES  # tests per row


def padded_tests(B: int) -> int:
    """Tests per kernel invocation round up to the grid block (R_BLK
    row-groups of SUB*LANES tests).  The planar WIRE format (the packed
    whole-level message below) carries this padding — a deterministic
    function of B, so both endpoints agree on sizes without negotiation."""
    blk = R_BLK * GROUP
    return B + (-B) % blk


def packed_msg_words(B: int, S: int, W: int) -> int:
    """u32 words of one packed whole-level garbled message (plane order
    tables | gb_labels | decode | cts, each plane ``padded_tests(B)``
    words)."""
    return ((S - 1) * 8 + 4 * S + 1 + 2 * W) * padded_tests(B)


def _sel(bit, a, b):
    """bit ? a : b on u32 vregs (bit is a 0/1 word)."""
    return b ^ ((jnp.uint32(0) - bit) & (a ^ b))


def _gate_hash(label, gid: int, half: int):
    """In-kernel twin of gc._hash_many for ONE label set: label is a list
    of 4 word-vregs; tweak words (gid, half, T2, T3) XOR in before the
    fixed-key ChaCha permutation; returns the first 4 output words."""
    g = jnp.uint32(gid)
    h = jnp.uint32(half)
    blk = [
        label[0] ^ g,
        label[1] ^ h,
        label[2] ^ jnp.uint32(gc._TWEAK2),
        label[3] ^ jnp.uint32(gc._TWEAK3),
    ]
    return _chacha16(blk)[:4]


def _ot_pad(rows, idx, n_words: int):
    """In-kernel twin of otext.ot_hash: rows = 4 word-vregs, idx = the
    per-test OT index vreg (already offset)."""
    blk = [
        rows[0] ^ idx,
        rows[1] ^ jnp.uint32(otext._OT_TWEAK1),
        rows[2] ^ jnp.uint32(otext._OT_TWEAK2),
        rows[3] ^ jnp.uint32(otext._OT_TWEAK3),
    ]
    return _chacha16(blk)[:n_words]


def _lsb01(w):
    return w & jnp.uint32(1)


def _garble_kernel(S: int, W: int, sc_ref,
                   x0_ref, y0_ref, xb_ref, mask_ref, mv0_ref, mv1_ref,
                   tab_ref, gbl_ref, dec_ref, cts_ref):
    """One row block of B equality tests, all S wire planes.

    Planar blocks (leading plane axis, then [R_BLK, 8, LANES] rows):
    x0/y0 ``u32[4*S]`` planes at index ``s*4 + w``; xb ``u32[S]`` 0/1
    planes; mask ``u32`` 0/1; mv0/mv1 ``u32[W]``; tables
    ``u32[(S-1)*2*4]`` at ``(gate*2 + t)*4 + w`` (tree order, exactly
    _and_tree_garble's concatenation); gbl ``u32[4*S]``; dec ``u32`` 0/1;
    cts ``u32[2*W]`` at ``c*W + w``.  sc_ref (SMEM u32[5]): R words 0..3,
    idx_offset at 4.
    """
    from jax.experimental import pallas as pl

    sh2 = (R_BLK * SUB, LANES)
    sh3 = (R_BLK, SUB, LANES)
    R = [sc_ref[w] for w in range(4)]

    # wires: Z0_s = X0_s ^ Y0_s ^ R  (free XNOR relabel)
    wires = [
        [x0_ref[s * 4 + w].reshape(sh2) ^ y0_ref[s * 4 + w].reshape(sh2) ^ R[w]
         for w in range(4)]
        for s in range(S)
    ]
    # half-gates AND-tree, python-unrolled (gate order = _and_tree_garble)
    gate = 0
    while len(wires) > 1:
        k = len(wires) // 2
        nxt = []
        for i in range(k):
            A0, B0 = wires[2 * i], wires[2 * i + 1]
            pa, pb = _lsb01(A0[0]), _lsb01(B0[0])
            HA0 = _gate_hash(A0, gate + i, 0)
            HA1 = _gate_hash([a ^ r for a, r in zip(A0, R)], gate + i, 0)
            HB0 = _gate_hash(B0, gate + i, 1)
            HB1 = _gate_hash([b ^ r for b, r in zip(B0, R)], gate + i, 1)
            pbm = jnp.uint32(0) - pb
            pam = jnp.uint32(0) - pa
            C0 = []
            for w in range(4):
                TG = HA0[w] ^ HA1[w] ^ (pbm & R[w])
                WG = HA0[w] ^ (pam & TG)
                TE = HB0[w] ^ HB1[w] ^ A0[w]
                WE = HB0[w] ^ (pbm & (TE ^ A0[w]))
                tab_ref[((gate + i) * 2 + 0) * 4 + w] = TG.reshape(sh3)
                tab_ref[((gate + i) * 2 + 1) * 4 + w] = TE.reshape(sh3)
                C0.append(WG ^ WE)
            nxt.append(C0)
        gate += k
        wires = nxt + wires[2 * k:]
    out0 = wires[0]

    # output decode bit (pre-masked) + the garbler's active input labels
    dec_ref[0] = (_lsb01(out0[0]) ^ mask_ref[0].reshape(sh2)).reshape(sh3)
    for s in range(S):
        xm = jnp.uint32(0) - xb_ref[s].reshape(sh2)
        for w in range(4):
            gbl_ref[s * 4 + w] = (
                x0_ref[s * 4 + w].reshape(sh2) ^ (xm & R[w])
            ).reshape(sh3)

    # b2a payload ciphertexts under the two output labels (gc.garble_
    # equality_payload): pad_v = H_ot(out0 [^ R], idx); ct slot = select bit
    idx = (
        jnp.uint32(pl.program_id(0) * R_BLK * SUB * LANES)
        + jax.lax.broadcasted_iota(jnp.uint32, sh2, 0) * jnp.uint32(LANES)
        + jax.lax.broadcasted_iota(jnp.uint32, sh2, 1)
        + sc_ref[4]
    )
    pad0 = _ot_pad(out0, idx, W)
    pad1 = _ot_pad([o ^ r for o, r in zip(out0, R)], idx, W)
    p = _lsb01(out0[0])
    for w in range(W):
        c0 = mv0_ref[w].reshape(sh2) ^ pad0[w]
        c1 = mv1_ref[w].reshape(sh2) ^ pad1[w]
        cts_ref[0 * W + w] = _sel(p, c1, c0).reshape(sh3)
        cts_ref[1 * W + w] = _sel(p, c0, c1).reshape(sh3)


def _eval_kernel(S: int, W: int, sc_ref,
                 gbl_ref, evl_ref, tab_ref, dec_ref, cts_ref,
                 e_ref, pay_ref):
    """Evaluator twin: active labels in, XOR share + opened payload out."""
    from jax.experimental import pallas as pl

    sh2 = (R_BLK * SUB, LANES)
    sh3 = (R_BLK, SUB, LANES)
    wires = [
        [gbl_ref[s * 4 + w].reshape(sh2) ^ evl_ref[s * 4 + w].reshape(sh2)
         for w in range(4)]
        for s in range(S)
    ]
    gate = 0
    while len(wires) > 1:
        k = len(wires) // 2
        nxt = []
        for i in range(k):
            A, B = wires[2 * i], wires[2 * i + 1]
            HA = _gate_hash(A, gate + i, 0)
            HB = _gate_hash(B, gate + i, 1)
            am = jnp.uint32(0) - _lsb01(A[0])
            bm = jnp.uint32(0) - _lsb01(B[0])
            C = []
            for w in range(4):
                TG = tab_ref[((gate + i) * 2 + 0) * 4 + w].reshape(sh2)
                TE = tab_ref[((gate + i) * 2 + 1) * 4 + w].reshape(sh2)
                WG = HA[w] ^ (am & TG)
                WE = HB[w] ^ (bm & (TE ^ A[w]))
                C.append(WG ^ WE)
            nxt.append(C)
        gate += k
        wires = nxt + wires[2 * k:]
    out = wires[0]

    s_bit = _lsb01(out[0])
    e_ref[0] = (s_bit ^ dec_ref[0].reshape(sh2)).reshape(sh3)
    idx = (
        jnp.uint32(pl.program_id(0) * R_BLK * SUB * LANES)
        + jax.lax.broadcasted_iota(jnp.uint32, sh2, 0) * jnp.uint32(LANES)
        + jax.lax.broadcasted_iota(jnp.uint32, sh2, 1)
        + sc_ref[0]
    )
    pad = _ot_pad(out, idx, W)
    for w in range(W):
        ct = _sel(s_bit, cts_ref[1 * W + w].reshape(sh2),
                  cts_ref[0 * W + w].reshape(sh2))
        pay_ref[w] = (ct ^ pad[w]).reshape(sh3)


def _planarize(a, B: int, bp: int):
    """[B, ...trailing] -> planar u32[prod(trailing), rows, 8, LANES]."""
    a = jnp.asarray(a, jnp.uint32)
    k = int(np.prod(a.shape[1:])) if a.ndim > 1 else 1
    a = a.reshape(B, k).T  # [k, B]
    if bp != B:
        a = jnp.concatenate(
            [a, jnp.zeros((k, bp - B), jnp.uint32)], axis=-1
        )
    return a.reshape(k, bp // GROUP, SUB, LANES)


def _unplanarize(a, B: int):
    """planar u32[k, rows, 8, LANES] -> [B, k]."""
    k = a.shape[0]
    return a.reshape(k, -1).T[:B]


def _garble_call(R, Y0, X0, mask, x_bits, m_v0, m_v1, idx_offset,
                 S: int, W: int, interpret: bool):
    """Shared pallas_call builder: planarize inputs, run the garble
    kernel, return the RAW planar outputs [tables, gb_labels, decode,
    cts] — the packed wire path ravels them as-is; the compat path
    unplanarizes back to test-major tensors."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = x_bits.shape[0]
    bp = padded_tests(B)
    rows = bp // GROUP

    sc = jnp.concatenate([
        jnp.asarray(R, jnp.uint32),
        jnp.asarray(idx_offset, jnp.uint32).reshape(1),
    ])
    ops = [
        _planarize(X0, B, bp),
        _planarize(Y0, B, bp),
        _planarize(jnp.asarray(x_bits, jnp.uint32), B, bp),
        _planarize(jnp.asarray(mask, jnp.uint32), B, bp),
        _planarize(m_v0, B, bp),
        _planarize(m_v1, B, bp),
    ]
    z = np.int32(0)
    spec = lambda k: pl.BlockSpec((k, R_BLK, SUB, LANES),
                                  lambda j: (z, j, z, z))
    n_tab = (S - 1) * 2 * 4
    # explicit i32 index map: the package enables x64, and Mosaic rejects
    # the i64 indices an auto-generated trivial map would return
    sc_spec = pl.BlockSpec((5,), lambda j: (z,), memory_space=pltpu.SMEM)
    outs = pl.pallas_call(
        partial(_garble_kernel, S, W),
        grid=(rows // R_BLK,),
        in_specs=[sc_spec,
                  spec(4 * S), spec(4 * S), spec(S), spec(1),
                  spec(W), spec(W)],
        out_specs=[spec(n_tab), spec(4 * S), spec(1), spec(2 * W)],
        out_shape=[
            jax.ShapeDtypeStruct((n_tab, rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((4 * S, rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((1, rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((2 * W, rows, SUB, LANES), jnp.uint32),
        ],
        interpret=interpret,
    )(sc, *ops)
    return outs


@partial(jax.jit, static_argnames=("S", "W", "interpret"))
def _garble_planar(R, Y0, X0, mask, x_bits, m_v0, m_v1, idx_offset,
                   S: int, W: int, interpret: bool):
    B = x_bits.shape[0]
    outs = _garble_call(R, Y0, X0, mask, x_bits, m_v0, m_v1, idx_offset,
                        S, W, interpret)
    tables = _unplanarize(outs[0], B).reshape(B, S - 1, 2, 4)
    gb_labels = _unplanarize(outs[1], B).reshape(B, S, 4)
    decode = _unplanarize(outs[2], B).reshape(B) != 0
    cts = _unplanarize(outs[3], B).reshape(B, 2, W).transpose(1, 0, 2)
    return gc.GarbledEqBatch(tables=tables, gb_labels=gb_labels,
                             decode=decode), cts


@partial(jax.jit, static_argnames=("S", "W", "interpret"))
def _garble_packed(R, Y0, X0, mask, x_bits, m_v0, m_v1, idx_offset,
                   S: int, W: int, interpret: bool):
    """Whole-level fused garble→pack: the kernel's planar outputs ravel
    straight into the wire buffer — no unplanarize transposes, no
    test-major re-pack; one concatenation is the only copy between the
    garble kernel and the data-plane fetch."""
    outs = _garble_call(R, Y0, X0, mask, x_bits, m_v0, m_v1, idx_offset,
                        S, W, interpret)
    return jnp.concatenate([jnp.ravel(o) for o in outs])


def _eval_call(sc, gbl, evl, tab, dec, cts, S: int, W: int,
               interpret: bool):
    """Shared pallas_call builder for the eval kernel: all inputs already
    planar ``[k, rows, SUB, LANES]``; returns (e planes, payload planes)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = gbl.shape[1]
    n_tab = (S - 1) * 2 * 4
    z = np.int32(0)
    spec = lambda k: pl.BlockSpec((k, R_BLK, SUB, LANES),
                                  lambda j: (z, j, z, z))
    sc_spec = pl.BlockSpec((1,), lambda j: (z,), memory_space=pltpu.SMEM)
    return pl.pallas_call(
        partial(_eval_kernel, S, W),
        grid=(rows // R_BLK,),
        in_specs=[sc_spec,
                  spec(4 * S), spec(4 * S), spec(n_tab), spec(1),
                  spec(2 * W)],
        out_specs=[spec(1), spec(W)],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((W, rows, SUB, LANES), jnp.uint32),
        ],
        interpret=interpret,
    )(sc, gbl, evl, tab, dec, cts)


@partial(jax.jit, static_argnames=("S", "W", "interpret"))
def _eval_planar(tables, gb_labels, decode, ev_labels, cts, idx_offset,
                 S: int, W: int, interpret: bool):
    B = gb_labels.shape[0]
    bp = padded_tests(B)
    sc = jnp.asarray(idx_offset, jnp.uint32).reshape(1)
    outs = _eval_call(
        sc,
        _planarize(gb_labels, B, bp),
        _planarize(ev_labels, B, bp),
        _planarize(tables, B, bp),
        _planarize(jnp.asarray(decode, jnp.uint32), B, bp),
        _planarize(jnp.transpose(jnp.asarray(cts, jnp.uint32), (1, 0, 2)),
                   B, bp),
        S, W, interpret,
    )
    e = _unplanarize(outs[0], B).reshape(B) != 0
    pay = _unplanarize(outs[1], B).reshape(B, W)
    return e, pay


def _split_packed(msg, B: int, S: int, W: int):
    """Packed wire buffer -> the four planar plane stacks (pure reshapes
    of contiguous slices — no transposes)."""
    bp = padded_tests(B)
    rows = bp // GROUP
    n_tab = (S - 1) * 2 * 4
    sizes = [n_tab, 4 * S, 1, 2 * W]
    parts, base = [], 0
    for k in sizes:
        parts.append(msg[base : base + k * bp].reshape(k, rows, SUB, LANES))
        base += k * bp
    return parts


@partial(jax.jit, static_argnames=("S", "W", "interpret"))
def _eval_packed(msg, ev_labels, idx_offset, S: int, W: int,
                 interpret: bool):
    """Whole-level fused unpack→eval: the wire buffer's planes feed the
    kernel directly (reshape-slices, no unplanarize) — only the
    evaluator's OWN labels planarize, once."""
    B = ev_labels.shape[0]
    bp = padded_tests(B)
    tab, gbl, dec, cts = _split_packed(jnp.asarray(msg, jnp.uint32), B, S, W)
    sc = jnp.asarray(idx_offset, jnp.uint32).reshape(1)
    outs = _eval_call(
        sc, gbl, _planarize(ev_labels, B, bp), tab, dec, cts,
        S, W, interpret,
    )
    e = _unplanarize(outs[0], B).reshape(B) != 0
    pay = _unplanarize(outs[1], B).reshape(B, W)
    return e, pay


def garble_equality_payload(R, Y0, seed, x_bits, m_v0, m_v1,
                            n_words: int, idx_offset, interpret: bool = False):
    """Drop-in for :func:`gc.garble_equality_payload` — bit-exact.

    The garbler's own labels + mask come from the SAME PRG stream draw
    (gc._carve_label_words), so the emitted batch, ciphertexts, and mask
    are word-for-word identical to the XLA engine's."""
    x_bits = jnp.asarray(x_bits, bool)
    B, S = x_bits.shape
    if S < 2:  # S=1 has no AND gates; the XLA form covers it (gc.py's
        # dispatcher never routes it here)
        raise ValueError("gc_pallas requires S >= 2 wire strings")
    _, (X0,), mask = gc._carve_label_words(seed, B, S, 1, with_r=False)
    batch, cts = _garble_planar(
        jnp.asarray(R, jnp.uint32), jnp.asarray(Y0, jnp.uint32), X0, mask,
        x_bits, jnp.asarray(m_v0, jnp.uint32), jnp.asarray(m_v1, jnp.uint32),
        idx_offset, S, n_words, interpret,
    )
    return batch, cts, mask


def eval_equality_payload(batch: gc.GarbledEqBatch, ev_labels, cts,
                          n_words: int, idx_offset, interpret: bool = False):
    """Drop-in for :func:`gc.eval_equality_payload` — bit-exact."""
    B, S = batch.gb_labels.shape[:2]
    if S < 2:
        raise ValueError("gc_pallas requires S >= 2 wire strings")
    return _eval_planar(
        jnp.asarray(batch.tables, jnp.uint32),
        jnp.asarray(batch.gb_labels, jnp.uint32),
        jnp.asarray(batch.decode),
        jnp.asarray(ev_labels, jnp.uint32),
        jnp.asarray(cts, jnp.uint32),
        idx_offset, S, n_words, interpret,
    )


def garble_equality_payload_packed(R, Y0, seed, x_bits, m_v0, m_v1,
                                   n_words: int, idx_offset,
                                   interpret: bool = False):
    """Whole-level garble with the PACKED planar wire output: returns
    (msg u32[packed_msg_words(B, S, W)], mask bool[B]).  The message is
    the kernel's plane stack raveled in place — no intermediate label
    tensor ever re-transposes to test-major layout between garbling and
    the data-plane fetch.  Byte-identical to the XLA twin
    (gc._garble_equality_payload_packed_xla)."""
    x_bits = jnp.asarray(x_bits, bool)
    B, S = x_bits.shape
    if S < 2:
        raise ValueError("gc_pallas requires S >= 2 wire strings")
    _, (X0,), mask = gc._carve_label_words(seed, B, S, 1, with_r=False)
    msg = _garble_packed(
        jnp.asarray(R, jnp.uint32), jnp.asarray(Y0, jnp.uint32), X0, mask,
        x_bits, jnp.asarray(m_v0, jnp.uint32), jnp.asarray(m_v1, jnp.uint32),
        idx_offset, S, n_words, interpret,
    )
    return msg, mask


def eval_equality_payload_packed(msg, ev_labels, n_words: int, idx_offset,
                                 interpret: bool = False):
    """Whole-level unpack→eval twin: consumes the packed planar wire
    buffer directly.  Returns (e bool[B], payload u32[B, n_words])."""
    ev_labels = jnp.asarray(ev_labels, jnp.uint32)
    B, S = ev_labels.shape[:2]
    if S < 2:
        raise ValueError("gc_pallas requires S >= 2 wire strings")
    return _eval_packed(msg, ev_labels, idx_offset, S, n_words, interpret)


# -- row-sharded (shard_map) entries ----------------------------------------
#
# Under the multi-chip kernel stage (parallel/kernel_shard.py) each mesh
# shard garbles/evaluates its own whole-planar-block slice of the level:
# inputs arrive ALREADY sliced and zero-padded (labels + mask from
# gc._carve_label_words_shard, Y0 from the row-sharded extension), and
# ``idx_offset`` is the session base PLUS the shard's global test offset
# — a TRACED value (lax.axis_index), which the kernels already accept
# (it rides SMEM).  Because each shard's extent is a whole number of
# R_BLK*GROUP blocks, the pallas grid and the planar layout need no
# per-shard padding, and the per-shard buffers concatenate along the row
# axis into the byte-identical single-device wire.


def garble_packed_planes(R, Y0, X0, mask, x_bits, m_v0, m_v1,
                         n_words: int, idx_offset, interpret: bool = False):
    """Presliced packed garble (the per-shard form of
    :func:`garble_equality_payload_packed`): the caller supplies the
    garbler labels + mask instead of a seed.  Returns the raveled planar
    buffer for this extent."""
    return _garble_packed(
        jnp.asarray(R, jnp.uint32), jnp.asarray(Y0, jnp.uint32),
        jnp.asarray(X0, jnp.uint32), jnp.asarray(mask, jnp.uint32),
        jnp.asarray(x_bits, bool), jnp.asarray(m_v0, jnp.uint32),
        jnp.asarray(m_v1, jnp.uint32), idx_offset,
        jnp.asarray(x_bits, bool).shape[1], n_words, interpret,
    )
