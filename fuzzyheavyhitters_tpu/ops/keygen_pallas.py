"""Fused Pallas keygen: the whole ibDCF level recurrence as ONE TPU kernel.

The ``lax.scan`` keygen (ops/ibdcf.py, ref: ibDCF.rs:138-164) is
latency-bound, not compute-bound: each of the ``data_len`` scan steps costs
a fixed XLA dispatch overhead that dwarfs its few microseconds of VPU work
(measured: 8192 keys x 512 levels ~= 0.22 ms/step, ~1% of HBM bound).  This
kernel runs the entire recurrence inside one ``pallas_call``:

- the per-client state (two parties' seeds + t-bits) lives in registers /
  VMEM across all levels — nothing round-trips to HBM between levels;
- clients are laid out as ``(8 sublanes, LANES lanes)`` tiles so every
  ChaCha word is a full native VPU vreg — the 16-word cipher state is 16
  register arrays and the diagonal round is pure variable renaming (the
  scalar-form ChaCha, but each "scalar" is a [8, LANES] vector);
- correction words stream out to VMEM blocks per level (dynamic stores on
  the untiled leading axis are cheap).

Bit-exactness is pinned against ``gen_pair_np`` (tests/test_ibdcf.py); the
public wrapper returns the same ``IbDcfKeyBatch`` pytrees as the scan
engine.  Select with ``engine="pallas"`` in the ibdcf keygen entry points.

Reference semantics carried over (same recurrence as ops/ibdcf.py):
``gen_cor_word`` per level (ibDCF.rs:84-119), party-0 t=0 / party-1 t=1
roots (ibDCF.rs:143-146), masked-seed expansion (prg.rs:97), and both bit
modes (the reference's constant-bit quirk and honest derived bits).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import prg
from .ibdcf import IbDcfKeyBatch

SUB = 8  # sublanes per client tile
LANES = 128  # lanes per client tile (SUB * LANES clients per grid step)
TILE = SUB * LANES
L_BLK = 64  # levels per grid step (bounds the VMEM block footprint)


_qr = prg._quarter_round  # one quarter-round implementation everywhere


def _chacha16(blk):
    """blk: list of 4 uint32 arrays (the input block words, any shape).

    Returns the 16 output words as register arrays — the scalar-form ChaCha
    (prg.chacha_block's math exactly), unrolled: inside one kernel there is
    no XLA-compile pressure, and renamed-variable diagonal rounds beat any
    roll/permute on the VPU.
    """
    shape = blk[0].shape
    x = [jnp.full(shape, w, jnp.uint32) for w in prg._SIGMA + prg._FIXED_KEY]
    x += list(blk)
    init = list(x)
    for _ in range(prg.N_ROUNDS // 2):
        x[0], x[4], x[8], x[12] = _qr(x[0], x[4], x[8], x[12])
        x[1], x[5], x[9], x[13] = _qr(x[1], x[5], x[9], x[13])
        x[2], x[6], x[10], x[14] = _qr(x[2], x[6], x[10], x[14])
        x[3], x[7], x[11], x[15] = _qr(x[3], x[7], x[11], x[15])
        x[0], x[5], x[10], x[15] = _qr(x[0], x[5], x[10], x[15])
        x[1], x[6], x[11], x[12] = _qr(x[1], x[6], x[11], x[12])
        x[2], x[7], x[8], x[13] = _qr(x[2], x[7], x[8], x[13])
        x[3], x[4], x[9], x[14] = _qr(x[3], x[4], x[9], x[14])
    return [a + b for a, b in zip(x, init)]


def _kernel(derived_bits: bool,
            seeds_ref, alpha_ref, side_ref,
            cw_seed_ref, cw_b_ref, cw_y_ref,
            seed_scr, tb_scr):
    """One (client tile, level block) grid step.

    Block shapes: seeds u32[2, 4, 8, LANES], alpha u32[L_BLK, 8, LANES]
    (0/1), side u32[8, LANES] (0/1) -> cw_seed u32[L_BLK, 4, 8, LANES],
    cw_b/cw_y u32[L_BLK, 2, 8, LANES] (0/1 words; the wrapper casts to
    bool).  The level axis rides grid dim 1 (fastest-iterating on TPU), and
    the recurrence state carries across level blocks in VMEM scratch
    (``seed_scr`` u32[2, 4, 8, LANES], ``tb_scr`` u32[2, 8, LANES]),
    re-initialized whenever a new client tile starts.

    Everything stays uint32 — bit flags as 0/1 words, selects as XOR-masks
    (``b ^ (mask & (a ^ b))`` with ``mask = 0 - flag``).  Mosaic's vector i1
    paths are what the remote compiler rejects, so no bool vectors appear.
    """
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(1) == 0)
    def _init_tile():
        seed_scr[...] = seeds_ref[...]
        tb_scr[0] = jnp.zeros((SUB, LANES), jnp.uint32)
        tb_scr[1] = jnp.ones((SUB, LANES), jnp.uint32)

    side = side_ref[...]  # 0/1
    one = jnp.uint32(1)

    def sel(flag01, a, b):
        """flag ? a : b, element-wise on u32 (flag is a 0/1 word)."""
        m = jnp.uint32(0) - flag01
        return b ^ (m & (a ^ b))

    def level(l, carry):
        seeds, tbits = carry  # u32[2, 4, 8, LANES], u32[2, 8, LANES]

        def expand(p):
            blk = [seeds[p, w] for w in range(4)]
            blk[0] = blk[0] & jnp.uint32(0xFFFFFFF0)  # prg.rs:97 mask
            out = _chacha16(blk)
            if derived_bits:
                w8 = out[8]
                bits = ((w8 & 1) ^ 1, ((w8 >> 1) & 1) ^ 1)
                ybits = (((w8 >> 2) & 1) ^ 1, ((w8 >> 3) & 1) ^ 1)
            else:  # the reference's masked-byte constants (prg.rs:103-104)
                o = jnp.full((SUB, LANES), 1, jnp.uint32)
                bits, ybits = (o, o), (o, o)
            return out[0:4], out[4:8], bits, ybits

        sl0, sr0, b0, y0 = expand(0)
        sl1, sr1, b1, y1 = expand(1)
        keep = alpha_ref[l]  # [8, LANES] 0/1

        cw_seed_w = [sel(keep, a ^ b, c ^ d)
                     for a, b, c, d in zip(sl0, sl1, sr0, sr1)]
        cw_b_l = b0[0] ^ b1[0] ^ keep ^ one
        cw_b_r = b0[1] ^ b1[1] ^ keep
        cw_y_l = y0[0] ^ y1[0] ^ (keep & (side ^ one))
        cw_y_r = y0[1] ^ y1[1] ^ ((keep ^ one) & side)

        for w in range(4):
            cw_seed_ref[l, w] = cw_seed_w[w]
        cw_b_ref[l, 0] = cw_b_l
        cw_b_ref[l, 1] = cw_b_r
        cw_y_ref[l, 0] = cw_y_l
        cw_y_ref[l, 1] = cw_y_r

        cw_keep = sel(keep, cw_b_r, cw_b_l)
        new_seeds = []
        new_tbits = []
        for p, (sl, sr, b) in enumerate(((sl0, sr0, b0), (sl1, sr1, b1))):
            t = tbits[p]  # 0/1
            tm = jnp.uint32(0) - t
            kept = [sel(keep, r, a) for a, r in zip(sl, sr)]
            ns = [k ^ (tm & c) for k, c in zip(kept, cw_seed_w)]
            kb = sel(keep, b[1], b[0])
            nt = kb ^ (t & cw_keep)
            new_seeds.append(jnp.stack(ns))
            new_tbits.append(nt)
        return jnp.stack(new_seeds), jnp.stack(new_tbits)

    # i32 bounds: the package enables jax_enable_x64, and Mosaic rejects the
    # i64 loop counter plain python ints would produce here
    new_seeds, new_tbits = jax.lax.fori_loop(
        np.int32(0), np.int32(L_BLK), level, (seed_scr[...], tb_scr[...])
    )
    seed_scr[...] = new_seeds
    tb_scr[...] = new_tbits


@partial(jax.jit, static_argnames=("derived_bits", "interpret"))
def _gen_pallas(init_seeds, alpha_bits, side, derived_bits, interpret=False):
    """init_seeds u32[N, 2, 4], alpha bool[N, L], side bool[N] ->
    (cw_seed u32[N, L, 4], cw_bits bool[N, L, 2], cw_y bool[N, L, 2])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, L = alpha_bits.shape
    pad = (-N) % TILE
    n_pad = N + pad
    l_pad = (-L) % L_BLK
    Lp = L + l_pad
    if pad:
        init_seeds = jnp.concatenate(
            [init_seeds, jnp.zeros((pad, 2, 4), jnp.uint32)]
        )
        alpha_bits = jnp.concatenate([alpha_bits, jnp.zeros((pad, L), bool)])
        side = jnp.concatenate([side, jnp.zeros((pad,), bool)])
    if l_pad:
        # padded levels advance the recurrence into rows the wrapper slices
        # off — the discarded state never feeds a kept output
        alpha_bits = jnp.concatenate(
            [alpha_bits, jnp.zeros((n_pad, l_pad), bool)], axis=1
        )
    tiles = n_pad // TILE
    l_blocks = Lp // L_BLK

    # client-minor relayout: [n_pad, ...] -> [tiles, ..., SUB, LANES]
    seeds_t = jnp.transpose(
        init_seeds.reshape(tiles, SUB, LANES, 2, 4), (0, 3, 4, 1, 2)
    )  # [tiles, 2, 4, SUB, LANES]
    alpha_t = jnp.transpose(
        alpha_bits.reshape(tiles, SUB, LANES, Lp), (0, 3, 1, 2)
    ).astype(jnp.uint32)  # [tiles, Lp, SUB, LANES]
    side_t = side.reshape(tiles, SUB, LANES).astype(jnp.uint32)

    # level blocks ride grid dim 1 (fastest on TPU), so each client tile
    # walks its levels in order with the recurrence state held in scratch
    grid = (tiles, l_blocks)
    kern = partial(_kernel, derived_bits)
    # index maps return i32 zeros: jax_enable_x64 is on package-wide, and
    # Mosaic's remote compiler rejects i64 block indices
    z = np.int32(0)
    cw_seed, cw_b, cw_y = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, 2, 4, SUB, LANES), lambda i, j: (i, z, z, z, z)),
            pl.BlockSpec((None, L_BLK, SUB, LANES), lambda i, j: (i, j, z, z)),
            pl.BlockSpec((None, SUB, LANES), lambda i, j: (i, z, z)),
        ],
        out_specs=[
            pl.BlockSpec((None, L_BLK, 4, SUB, LANES), lambda i, j: (i, j, z, z, z)),
            pl.BlockSpec((None, L_BLK, 2, SUB, LANES), lambda i, j: (i, j, z, z, z)),
            pl.BlockSpec((None, L_BLK, 2, SUB, LANES), lambda i, j: (i, j, z, z, z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tiles, Lp, 4, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((tiles, Lp, 2, SUB, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((tiles, Lp, 2, SUB, LANES), jnp.uint32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 4, SUB, LANES), jnp.uint32),
            pltpu.VMEM((2, SUB, LANES), jnp.uint32),
        ],
        interpret=interpret,
    )(seeds_t, alpha_t, side_t)

    # back to the standard [N, L, k] layout
    def back(a, k):
        a = jnp.transpose(a, (0, 3, 4, 1, 2))  # [tiles, SUB, LANES, Lp, k]
        return a.reshape(n_pad, Lp, k)[:N, :L]

    return back(cw_seed, 4), back(cw_b, 2) != 0, back(cw_y, 2) != 0


def gen_pair_pallas(
    init_seeds, alpha_bits, side, derived_bits: bool | None = None,
    interpret: bool = False,
) -> tuple[IbDcfKeyBatch, IbDcfKeyBatch]:
    """Drop-in for :func:`ibdcf.gen_pair` with arbitrary batch dims.

    Flattens the batch to [N, L], runs the fused kernel, reshapes back.
    """
    if derived_bits is None:
        derived_bits = prg.DERIVED_BITS
    init_seeds = jnp.asarray(init_seeds, jnp.uint32)
    alpha = jnp.asarray(alpha_bits, bool)
    batch = alpha.shape[:-1]
    L = alpha.shape[-1]
    side_b = jnp.broadcast_to(jnp.asarray(side, bool), batch)
    n = int(np.prod(batch)) if batch else 1
    cw_seed, cw_b, cw_y = _gen_pallas(
        init_seeds.reshape(n, 2, 4), alpha.reshape(n, L),
        side_b.reshape(n), derived_bits, interpret,
    )

    def mk(p: int) -> IbDcfKeyBatch:
        return IbDcfKeyBatch(
            key_idx=jnp.broadcast_to(jnp.asarray(bool(p)), batch),
            root_seed=init_seeds[..., p, :],
            cw_seed=cw_seed.reshape(batch + (L, 4)),
            cw_bits=cw_b.reshape(batch + (L, 2)),
            cw_y_bits=cw_y.reshape(batch + (L, 2)),
        )

    return mk(0), mk(1)
