"""Fused Pallas OT-extension payload kernels (the 1-of-2^S hot stage).

Where the per-level OT cost actually lives after the whole-level
restructure (protocol/secure.py): not in the IKNP matrix itself — the
column PRG, u-XOR, and packed butterfly transpose already run as ONE
jitted XLA program per extension (``otext._receiver_extend`` /
``_sender_extend``, with ``extend_pads`` fusing the pad hash into the
same dispatch) — but in the chosen-payload stage that multiplies per
test: the 1-of-2^S equality OT hashes 2^S pads per test and builds the
ciphertext table, which as glue-bound XLA ops materializes a fresh
``[2^S, B, ...]`` tensor per step (comb, offsets broadcast, pads,
select, XOR — five HBM passes at the flagship batch).

The butterfly transpose stays in XLA deliberately: it is a cross-lane
bit permutation (32×32 tile shuffles), which Mosaic's vreg model prices
as relayouts per stage, while the measured packed-XLA form is already
~5x cheaper than the naive transpose and a single fused program.  The
kernels here take the transposed rows and run everything AFTER them —
GF(2^128) row-combine (Horner doubling ladder), 2^S offset pads, the
payload select, and the ciphertext XOR — in one VMEM-resident pass, in
the expand/gc_pallas planar layout family (tests spread over
(row, sublane, lane); every 128-bit block word a full vreg plane).

Engine contract, exactly like ops/gc_pallas.py: the XLA twins in
protocol/secure.py (``ot2s_encrypt``/``ot2s_decrypt``) compute identical
bits — the planar wire buffers are word-for-word engine-independent, and
tests/test_secure_kernels.py pins parity in interpret mode on CPU.

Row-sharded use (parallel/kernel_shard.py): both kernels are presliced-
input programs already — each mesh shard calls :func:`ot2s_encrypt` /
:func:`ot2s_decrypt` on its own whole-planar-block slice of the level
under ``shard_map``, with ``idx_offset`` = session base + the shard's
global test offset (a traced ``lax.axis_index`` expression; it rides
SMEM).  Shard extents are whole R_BLK*GROUP blocks, so ``padded_tests``
is the identity per shard and the per-shard planar buffers concatenate
along the row axis into the byte-identical single-device wire.

Ref seam: ocelot's chosen-payload OT consumption in src/collect.rs:439-471,
generalized from per-wire 1-of-2 to the per-test 1-of-2^S equality table.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import otext
from .gc_pallas import (
    GROUP, R_BLK, _ot_pad, _planarize, _unplanarize, padded_tests,
)
from .keygen_pallas import LANES, SUB


def _dbl(a):
    """In-kernel gf128_double on a 4-word-vreg list (otext.gf128_double's
    shift-with-carry, word-planar form)."""
    hi = a[3] >> 31
    out = [(a[0] << 1) ^ (hi * jnp.uint32(0x87))]
    for k in (1, 2, 3):
        out.append((a[k] << 1) | (a[k - 1] >> 31))
    return out


def _comb(rows):
    """In-kernel gf128_comb over a list of 4-word-vreg labels (Horner)."""
    acc = rows[-1]
    for j in range(len(rows) - 2, -1, -1):
        acc = [c ^ r for c, r in zip(_dbl(acc), rows[j])]
    return acc


def _test_idx(sc_ref, pos, sh2):
    """Per-test OT pad index vreg: global test index + the batch base
    (SMEM word ``pos``) — the planar twin of ``idx0 + arange(B)``."""
    from jax.experimental import pallas as pl

    return (
        jnp.uint32(pl.program_id(0) * R_BLK * SUB * LANES)
        + jax.lax.broadcasted_iota(jnp.uint32, sh2, 0) * jnp.uint32(LANES)
        + jax.lax.broadcasted_iota(jnp.uint32, sh2, 1)
        + sc_ref[pos]
    )


def _ot2s_enc_kernel(S: int, W: int, sc_ref,
                     q_ref, x_ref, mv0_ref, mv1_ref, cts_ref):
    """Grid step (row block j, choice c): comb the S Q-rows, hash choice
    c's offset pad, select payload m_{[x == c]}, XOR — writing choice
    c's W ciphertext planes.  The choice axis rides the GRID (not an
    unrolled in-kernel loop): one hash per kernel body keeps the program
    2^S times smaller (an unrolled S=6 body — 64 inlined ChaCha
    permutations — compiled pathologically slowly), and the q/x/m block
    index maps are constant along c, so the inputs stay VMEM-resident
    across the inner c steps (one HBM read per row block, not 2^S).

    Planar blocks: q ``u32[4*S]`` planes at ``s*4 + w``; x ``u32[S]`` 0/1
    planes; mv0/mv1 ``u32[W]``; out block = choice c's ``u32[W]`` planes
    of the ``u32[2^S * W]``-plane ciphertext stack (plane ``c*W + w``).
    sc_ref (SMEM u32[4*2^S + 1]): the offset table ``o_c`` words at
    ``4*c + w`` (otext.gf128_offsets order), idx_offset last."""
    from jax.experimental import pallas as pl

    sh2 = (R_BLK * SUB, LANES)
    sh3 = (R_BLK, SUB, LANES)
    c = pl.program_id(1)
    rows = [
        [q_ref[s * 4 + w].reshape(sh2) for w in range(4)] for s in range(S)
    ]
    comb = _comb(rows)
    x_int = x_ref[0].reshape(sh2)
    for j in range(1, S):
        x_int = x_int | (x_ref[j].reshape(sh2) << j)
    idx = _test_idx(sc_ref, 4 * (1 << S), sh2)
    off = [sc_ref[4 * c + w] for w in range(4)]
    pad = _ot_pad([cw ^ ow for cw, ow in zip(comb, off)], idx, W)
    eqm = jnp.uint32(0) - (x_int == c.astype(jnp.uint32)).astype(jnp.uint32)
    for w in range(W):
        m0 = mv0_ref[w].reshape(sh2)
        m1 = mv1_ref[w].reshape(sh2)
        mw = m0 ^ (eqm & (m0 ^ m1))  # x == c ? m1 : m0
        cts_ref[w] = (mw ^ pad[w]).reshape(sh3)


def _ot2s_dec_kernel(S: int, W: int, sc_ref,
                     t_ref, y_ref, cts_ref, pay_ref):
    """Receiver twin: comb the T-rows (= Q-comb ^ o_y), one pad, one-hot
    XOR-select of ciphertext slot y, open.  sc_ref (SMEM u32[1]): idx0.

    Like the encrypt kernel, the 2^S choice axis rides the GRID: the cts
    input block is ONE choice's W planes per step (at S=6/W=8 the full
    stack is 2^S·W = 512 planes — 16 MiB per block, past VMEM), and the
    output block's index map is constant along c, so the payload planes
    stay VMEM-resident and XOR-accumulate the one-hot select across the
    inner c steps; the final step opens the pad."""
    from jax.experimental import pallas as pl

    sh2 = (R_BLK * SUB, LANES)
    sh3 = (R_BLK, SUB, LANES)
    c = pl.program_id(1)
    # program_id-derived values hoisted OUT of the pl.when branches
    # (interpret mode resolves the primitive only at kernel top level)
    idx = _test_idx(sc_ref, 0, sh2)
    y_int = y_ref[0].reshape(sh2)
    for j in range(1, S):
        y_int = y_int | (y_ref[j].reshape(sh2) << j)
    eqm = jnp.uint32(0) - (y_int == c.astype(jnp.uint32)).astype(jnp.uint32)
    contrib = [eqm & cts_ref[w].reshape(sh2) for w in range(W)]

    @pl.when(c == 0)
    def _init():
        for w in range(W):
            pay_ref[w] = contrib[w].reshape(sh3)

    @pl.when(c != 0)
    def _accumulate():
        # exactly one c matches per test, so XOR-accumulation selects it
        for w in range(W):
            pay_ref[w] = (
                pay_ref[w].reshape(sh2) ^ contrib[w]
            ).reshape(sh3)

    @pl.when(c == (1 << S) - 1)
    def _open():
        rows = [
            [t_ref[s * 4 + w].reshape(sh2) for w in range(4)]
            for s in range(S)
        ]
        pad = _ot_pad(_comb(rows), idx, W)
        for w in range(W):
            pay_ref[w] = (pay_ref[w].reshape(sh2) ^ pad[w]).reshape(sh3)


@partial(jax.jit, static_argnames=("S", "W", "domain", "interpret"))
def _enc_planar(q_rows, s_block, x_bits, m_v0, m_v1, idx_offset,
                S: int, W: int, domain: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = x_bits.shape[0]
    bp = padded_tests(B)
    rows = bp // GROUP
    # gc_pallas._ot_pad hashes with the FIXED tweak word 1; the XLA
    # ot_hash XORs ``domain`` into that same word.  The offset table XORs
    # into the identical hash-input word, so folding the domain into
    # word 1 of every offset (c = 0's offset becomes (0, domain, 0, 0))
    # reproduces ot_hash(comb ^ o_c, domain=domain) bit-exactly.
    offs = otext.gf128_offsets(s_block, S)
    offs = offs.at[:, 1].set(offs[:, 1] ^ jnp.uint32(domain))
    sc = jnp.concatenate([
        jnp.ravel(offs),
        jnp.asarray(idx_offset, jnp.uint32).reshape(1),
    ])
    ops = [
        _planarize(q_rows, B, bp),
        _planarize(jnp.asarray(x_bits, jnp.uint32), B, bp),
        _planarize(m_v0, B, bp),
        _planarize(m_v1, B, bp),
    ]
    z = np.int32(0)
    spec = lambda k: pl.BlockSpec((k, R_BLK, SUB, LANES),
                                  lambda j, c: (z, j, z, z))
    sc_spec = pl.BlockSpec(
        (4 * (1 << S) + 1,), lambda j, c: (z,), memory_space=pltpu.SMEM
    )
    n_cts = (1 << S) * W
    # choice axis on the grid (innermost): the out block's plane index
    # follows c while every input block index stays put — Pallas then
    # keeps the inputs VMEM-resident across the 2^S inner steps
    out_spec = pl.BlockSpec((W, R_BLK, SUB, LANES),
                            lambda j, c: (c, j, z, z))
    (cts,) = pl.pallas_call(
        partial(_ot2s_enc_kernel, S, W),
        grid=(rows // R_BLK, 1 << S),
        in_specs=[sc_spec, spec(4 * S), spec(S), spec(W), spec(W)],
        out_specs=[out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n_cts, rows, SUB, LANES), jnp.uint32)
        ],
        interpret=interpret,
    )(sc, *ops)
    return jnp.ravel(cts)


@partial(jax.jit, static_argnames=("S", "W", "domain", "interpret"))
def _dec_planar(t_rows, y_bits, msg, idx_offset,
                S: int, W: int, domain: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = y_bits.shape[0]
    bp = padded_tests(B)
    rows = bp // GROUP
    n_cts = (1 << S) * W
    sc = jnp.asarray(idx_offset, jnp.uint32).reshape(1)
    # receiver-side domain fold: the kernel hashes comb(t) under the
    # fixed tweak; comb is linear with coefficient x^0 = 1 on row 0, so
    # XORing the domain into row 0's word 1 lands it on comb's word 1 —
    # the same place the XLA ot_hash tweak puts it.
    t_rows = jnp.asarray(t_rows, jnp.uint32)
    t_rows = t_rows.at[:, 0, 1].set(t_rows[:, 0, 1] ^ jnp.uint32(domain))
    ops = [
        _planarize(t_rows, B, bp),
        _planarize(jnp.asarray(y_bits, jnp.uint32), B, bp),
        jnp.asarray(msg, jnp.uint32).reshape(n_cts, rows, SUB, LANES),
    ]
    z = np.int32(0)
    spec = lambda k: pl.BlockSpec((k, R_BLK, SUB, LANES),
                                  lambda j, c: (z, j, z, z))
    sc_spec = pl.BlockSpec((1,), lambda j, c: (z,),
                           memory_space=pltpu.SMEM)
    # choice axis on the grid: the cts block follows c (one choice's W
    # planes in VMEM at a time), the payload output block does not (it
    # accumulates across the inner c steps)
    cts_spec = pl.BlockSpec((W, R_BLK, SUB, LANES),
                            lambda j, c: (c, j, z, z))
    (pay,) = pl.pallas_call(
        partial(_ot2s_dec_kernel, S, W),
        grid=(rows // R_BLK, 1 << S),
        in_specs=[sc_spec, spec(4 * S), spec(S), cts_spec],
        out_specs=[spec(W)],
        out_shape=[
            jax.ShapeDtypeStruct((W, rows, SUB, LANES), jnp.uint32)
        ],
        interpret=interpret,
    )(sc, *ops)
    return _unplanarize(pay, B).reshape(B, W)


def ot2s_encrypt(q_rows, s_block, x_flat, m_v0, m_v1, n_words: int,
                 idx_offset, domain: int, interpret: bool = False):
    """Planar-wire 1-of-2^S sender table — bit-exact with the XLA form in
    protocol/secure.py.  Returns the raveled planar ciphertext planes
    ``u32[(2^S·n_words)·padded_tests(B)]``."""
    q_rows = jnp.asarray(q_rows, jnp.uint32)
    B, S = q_rows.shape[0], q_rows.shape[1]
    return _enc_planar(
        q_rows, jnp.asarray(s_block, jnp.uint32), jnp.asarray(x_flat, bool),
        jnp.asarray(m_v0, jnp.uint32), jnp.asarray(m_v1, jnp.uint32),
        idx_offset, S, n_words, domain, interpret,
    )


def ot2s_decrypt(t_rows, y_flat, msg, n_words: int, idx_offset,
                 domain: int, interpret: bool = False):
    """Planar-wire 1-of-2^S receiver open — returns uint32[B, n_words]."""
    t_rows = jnp.asarray(t_rows, jnp.uint32)
    B, S = t_rows.shape[0], t_rows.shape[1]
    return _dec_planar(
        t_rows, jnp.asarray(y_flat, bool), jnp.asarray(msg, jnp.uint32),
        idx_offset, S, n_words, domain, interpret,
    )
