"""Payload-carrying all-prefix DPF as batched tensor programs.

The reference's malicious-secure sketch rides on an all-prefix Distributed
Point Function whose per-level payload is a *field value pair* ``(x, k·x)``
(ref: src/sketch.rs:8-24 — its ``dpf::DPFKey<(T,T),(U,U)>`` comes from the
upstream counttree ancestor; the file itself is absent from the reference
tree, so this is a re-derivation of the standard BGI16 construction with
the reference's conventions).  A client's vector at tree level j is one-hot
at ``prefix(alpha, j)``; the two servers' value shares satisfy

    share_0 + share_1 = value_j   at the on-path prefix,
    share_0 + share_1 = 0         everywhere else,

with ``share_b = (-1)^b * (convert(seed) + t * cw_val[j])``.

Layout mirrors ops/ibdcf.py: a key batch is a pytree with arbitrary batch
dims, keygen is one ``lax.scan`` over levels, eval is an incremental
per-level state advance.  ``convert`` (seed -> field element lanes) is the
ChaCha CTR stream with a domain-separation tweak so it never collides with
the expansion PRG (the reference separates these as AES-MMO vs AES-CTR,
prg.rs:92-122 vs 184-270).

Two payload lanes carry ``(x, k·x)`` per level; the last level converts in
the big field (ref: SketchDPFKey's (T, U) split).  The DPF here uses the
honest seed-derived t-bits (prg derived_bits=True path) — the reference's
masked-bit quirk is an ibDCF-only artifact.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import prg

# domain-separation tweak for convert() vs the expansion PRG
_CONVERT_TWEAK = (0x6B8B4567, 0x327B23C6)  # XORed into seed words 2,3


class DpfKeyBatch(NamedTuple):
    """One party's batch of payload DPF keys.

    cw_val:      inner-level value CWs, field T elements [..., L-1, lanes]
    cw_val_last: last-level value CW, field U elements [..., lanes(, limbs)]
    """

    key_idx: jax.Array  # bool[...]
    root_seed: jax.Array  # uint32[..., 4]
    cw_seed: jax.Array  # uint32[..., L, 4]
    cw_t: jax.Array  # bool[..., L, 2] (left/right t corrections)
    cw_val: jax.Array
    cw_val_last: jax.Array

    @property
    def data_len(self) -> int:
        return self.cw_seed.shape[-2]


class DpfEvalState(NamedTuple):
    seed: jax.Array  # uint32[..., 4]
    t: jax.Array  # bool[...]


def convert(seed: jax.Array, field, lanes: int) -> jax.Array:
    """seed uint32[..., 4] -> field elements [..., lanes(, limbs)].

    The seed is tweaked before streaming so convert() output is independent
    of the expansion PRG's output on the same seed."""
    tweaked = jnp.asarray(seed, jnp.uint32)
    tweaked = tweaked.at[..., 2].set(tweaked[..., 2] ^ np.uint32(_CONVERT_TWEAK[0]))
    tweaked = tweaked.at[..., 3].set(tweaked[..., 3] ^ np.uint32(_CONVERT_TWEAK[1]))
    w = 8 if field.limb_shape else 4
    words = prg.stream_words(tweaked, lanes * w)
    return field.sample(words.reshape(words.shape[:-1] + (lanes, w)))


def _neg_if(field, cond, v):
    return jnp.where(
        cond[..., None] if field.limb_shape else cond, field.neg(v), v
    )


@partial(jax.jit, static_argnames=("field_t", "field_u", "lanes"))
def _gen_pair_jit(init_seeds, alpha_bits, values, values_last, field_t, field_u, lanes):
    init_seeds = jnp.asarray(init_seeds, jnp.uint32)
    alpha_bits = jnp.asarray(alpha_bits, bool)
    batch = alpha_bits.shape[:-1]
    L = alpha_bits.shape[-1]
    assert init_seeds.shape == batch + (2, 4)
    assert values.shape[: len(batch)] == batch and values.shape[-2] == L - 1

    def step(carry, inp):
        seeds, ts = carry  # uint32[..., 2, 4], bool[..., 2]
        alpha = inp
        s_l, s_r, d_bits, _ = prg.expand(seeds, True)  # honest t-bits
        k = alpha[..., None]
        cw_seed = jnp.where(
            k, s_l[..., 0, :] ^ s_l[..., 1, :], s_r[..., 0, :] ^ s_r[..., 1, :]
        )
        # t corrections: on-path child t-shares must differ, off-path agree
        cw_t = jnp.stack(
            [
                d_bits[..., 0, 0] ^ d_bits[..., 1, 0] ^ alpha ^ True,
                d_bits[..., 0, 1] ^ d_bits[..., 1, 1] ^ alpha,
            ],
            axis=-1,
        )
        kept_seed = jnp.where(k[..., None, :], s_r, s_l)  # [..., 2, 4]
        kept_t = jnp.where(k, d_bits[..., 1], d_bits[..., 0])  # [..., 2]
        new_seeds = jnp.where(ts[..., None], kept_seed ^ cw_seed[..., None, :], kept_seed)
        cw_t_keep = jnp.where(alpha, cw_t[..., 1], cw_t[..., 0])
        new_ts = kept_t ^ (ts & cw_t_keep[..., None])
        return (new_seeds, new_ts), (cw_seed, cw_t, new_seeds, new_ts)

    init_ts = jnp.broadcast_to(jnp.array([False, True]), batch + (2,))
    alpha_first = jnp.moveaxis(alpha_bits, -1, 0)
    (final_seeds, final_ts), (cw_seed, cw_t, lvl_seeds, lvl_ts) = jax.lax.scan(
        step, (init_seeds, init_ts), alpha_first
    )
    cw_seed = jnp.moveaxis(cw_seed, 0, -2)
    cw_t = jnp.moveaxis(cw_t, 0, -2)

    # value CWs from the post-correction level seeds (inner levels in T)
    def val_cw(field, seeds2, t1, value):
        w0 = convert(seeds2[..., 0, :], field, lanes)
        w1 = convert(seeds2[..., 1, :], field, lanes)
        cw = field.add(field.sub(value, w0), w1)
        return _neg_if(field, t1, cw)

    inner_seeds = jnp.moveaxis(lvl_seeds, 0, -3)[..., : L - 1, :, :]  # [..., L-1, 2, 4]
    inner_t1 = jnp.moveaxis(lvl_ts, 0, -2)[..., : L - 1, 1]  # [..., L-1]
    # values: [..., L-1, lanes(, limbs)]
    cw_val = val_cw(
        field_t,
        inner_seeds,
        inner_t1[..., None],  # broadcast over lanes
        values,
    )
    cw_val_last = val_cw(
        field_u, final_seeds, final_ts[..., 1, None], values_last
    )

    def mk(p: int) -> DpfKeyBatch:
        return DpfKeyBatch(
            key_idx=jnp.broadcast_to(jnp.asarray(bool(p)), batch),
            root_seed=init_seeds[..., p, :],
            cw_seed=cw_seed,
            cw_t=cw_t,
            cw_val=cw_val,
            cw_val_last=cw_val_last,
        )

    return mk(0), mk(1)


def gen_pair(init_seeds, alpha_bits, values, values_last, field_t, field_u, lanes=2):
    """Generate both parties' payload-DPF batches.

    init_seeds:  uint32[..., 2, 4]; alpha_bits: bool[..., L];
    values:      field_t[..., L-1, lanes] per-level payloads;
    values_last: field_u[..., lanes] leaf payload.
    """
    return _gen_pair_jit(
        init_seeds, alpha_bits, values, values_last, field_t, field_u, lanes
    )


@jax.jit
def eval_init(key: DpfKeyBatch) -> DpfEvalState:
    return DpfEvalState(
        seed=key.root_seed, t=jnp.asarray(key.key_idx, bool)
    )


def level_cw(key: DpfKeyBatch, level):
    take = lambda a: jax.lax.dynamic_index_in_dim(
        a, level, axis=a.ndim - 2, keepdims=False
    )
    return take(key.cw_seed), take(key.cw_t)


@partial(jax.jit, static_argnames=("field", "lanes"))
def eval_bit(cw, state: DpfEvalState, direction, cw_val_level, key_idx, field, lanes):
    """Advance one level and emit this level's value share.

    cw:           output of :func:`level_cw`;
    direction:    bool[...] child taken (True = right);
    cw_val_level: this level's value CW (field[..., lanes]);
    Returns (new state, value share field[..., lanes]) with
    ``share = (-1)^key_idx * (convert(seed') + t' * cw_val)``.
    """
    cw_seed, cw_t = cw
    direction = jnp.asarray(direction, bool)
    s_l, s_r, d_bits, _ = prg.expand(state.seed, True)
    d = direction[..., None]
    seed = jnp.where(d, s_r, s_l)
    t = jnp.where(direction, d_bits[..., 1], d_bits[..., 0])
    cw_t_d = jnp.where(direction, cw_t[..., 1], cw_t[..., 0])
    seed = jnp.where(state.t[..., None], seed ^ cw_seed, seed)
    t = t ^ (state.t & cw_t_d)
    new = DpfEvalState(seed=seed, t=t)

    w = convert(seed, field, lanes)
    tb = t[..., None]  # broadcast over the lanes axis
    mask = tb[..., None] if field.limb_shape else tb  # ... and limbs
    share = field.add(w, jnp.where(mask, cw_val_level, 0))
    neg = jnp.asarray(key_idx, bool)[..., None]
    return new, _neg_if(field, neg, share)
