"""IKNP OT extension as batched device tensor ops.

The reference consumes OT extension through ocelot's ``AlszSender`` /
``AlszReceiver`` (ref: src/collect.rs:10-11, 454-461) — per-thread Rust
state machines over TCP channels.  The TPU-native redesign observes that the
whole IKNP03 extension is three tensor primitives — column PRG expansion,
bit-matrix transpose, and XOR — plus one correlation-robust hash, all of
which batch perfectly on device:

- 128 **base OTs** (ops/baseot.py, Chou-Orlandi on the host) seed the
  extension; the extension *sender* played base-OT *receiver* with its
  secret choice vector ``s`` and vice versa (the standard IKNP role flip).
- To extend to ``m`` OTs: the receiver, with choice bits ``r``, derives
  column streams ``t_i = G(k0_i)`` and sends ``u_i = t_i ^ G(k1_i) ^ r``;
  the sender derives ``q_i = G(k_{s_i}) ^ s_i·u_i``.  Row-wise,
  ``Q_j = T_j ^ r_j·s`` — a 1-of-2 correlated OT on 128-bit rows.
- **Δ-OT view** (no hash): ``T_j`` IS the receiver's choice-selected label
  when the sender uses ``Q_j`` as its zero-label with global offset ``s``.
  The GC layer exploits this by setting its free-XOR offset ``R = s`` —
  evaluator input labels then arrive with zero extra messages (ops/gc.py).
- **Chosen-payload view**: pads ``H(j, Q_j)`` / ``H(j, Q_j ^ s)`` encrypt
  arbitrary per-OT payloads (the b2a field blocks of collect.rs:439-471);
  the receiver recovers its choice with ``H(j, T_j)``.  H is the fixed-key
  ChaCha hash (ops/prg.py) with an OT-specific tweak.

Semi-honest security, matching the reference's use (its Alsz instantiation
is the malicious-OT variant of IKNP, but the surrounding protocol is
semi-honest; ref: equalitytest.rs uses twopac semi-honest garbling).

Both parties must call ``extend`` the same number of times with the same
``m`` — the PRG stream counters advance in lockstep (like the shared
channel position in the reference's ocelot session).
"""

from __future__ import annotations

import secrets
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import taint_guard
from . import baseot, prg

KAPPA = 128  # security parameter: base-OT count == row width in bits

# OT-hash tweak constants (words 1..3); word 0 carries the OT index.
# Distinct from the GC gate-hash tweak (ops/gc.py) by construction.
_OT_TWEAK1 = 0x4F545F31
_OT_TWEAK2 = 0xB7E15162
_OT_TWEAK3 = 0x8AED2A6B


def pack_bits(bits: jax.Array) -> jax.Array:
    """bool[..., m] -> uint32[..., ceil(m/32)] little-endian bit packing."""
    bits = jnp.asarray(bits, bool)
    m = bits.shape[-1]
    w = -(-m // 32)
    pad = jnp.zeros(bits.shape[:-1] + (w * 32 - m,), bool)
    b = jnp.concatenate([bits, pad], axis=-1).reshape(bits.shape[:-1] + (w, 32))
    return jnp.sum(
        b.astype(jnp.uint32) << jnp.arange(32, dtype=jnp.uint32), axis=-1,
        dtype=jnp.uint32,
    )


def unpack_bits(words: jax.Array, m: int) -> jax.Array:
    """uint32[..., w] -> bool[..., m] (inverse of :func:`pack_bits`)."""
    words = jnp.asarray(words, jnp.uint32)
    idx = jnp.arange(m)
    return ((words[..., idx // 32] >> (idx % 32).astype(jnp.uint32)) & 1).astype(bool)


@partial(jax.jit, static_argnames=("m",))
def _transpose_pack(cols: jax.Array, m: int) -> jax.Array:
    """Column-major bit matrix -> packed 128-bit rows.

    cols: uint32[128, W] where bit j of cols[i] is entry (row j, column i).
    Returns uint32[m, 4]: row j's 128 column bits packed into 4 words.

    PACKED 32x32 butterfly transpose (the Hacker's Delight 7-3 network,
    little-endian orientation, vectorized over all word tiles): 5 stages
    of shift/mask/XOR on u32 words.  The data never unpacks to booleans —
    the naive unpack->T->pack form materialized a [128, m] bool matrix
    (128 MB at the 1M-OT production batch) and was the single most
    expensive op of the secure level (measured: extension 31.8 ms of a
    44 ms level at m=1M; packed form ~5x cheaper end-to-end).
    """
    w = cols.shape[1]
    x = jnp.asarray(cols, jnp.uint32).reshape(4, 32, w)
    for j, msk in ((16, 0x0000FFFF), (8, 0x00FF00FF), (4, 0x0F0F0F0F),
                   (2, 0x33333333), (1, 0x55555555)):
        # pair word k (bit-rows) with word k+j; swap the complementary
        # j-wide bit blocks between them
        x = x.reshape(4, 32 // (2 * j), 2, j, w)
        a0, a1 = x[:, :, 0], x[:, :, 1]
        t = ((a0 >> j) ^ a1) & jnp.uint32(msk)
        a0 = a0 ^ (t << j)
        a1 = a1 ^ t
        x = jnp.stack([a0, a1], axis=2).reshape(4, 32, w)
    # x[k, r, wj] -> out[j = wj*32 + r, word k]
    return jnp.transpose(x, (2, 1, 0)).reshape(w * 32, 4)[:m]


@partial(jax.jit, static_argnames=("w",))
def _col_words(seeds: jax.Array, w: int, offset) -> jax.Array:
    """Per-column PRG streams: uint32[128, 4] seeds -> uint32[128, w]."""
    nb = -(-w // 16)
    blocks = prg.stream_blocks(seeds, nb, offset)  # [128, nb, 16]
    return blocks.reshape(128, nb * 16)[:, :w]


def _receiver_extend_core(seeds0, seeds1, choices, offset, m):
    w = -(-m // 32)
    t = _col_words(seeds0, w, offset)
    g1 = _col_words(seeds1, w, offset)
    r_words = pack_bits(jnp.asarray(choices, bool))  # [w]
    u = t ^ g1 ^ r_words[None, :]
    return u, _transpose_pack(t, m)


def _sender_extend_core(seeds, s_bits, u, offset, m):
    w = -(-m // 32)
    g = _col_words(seeds, w, offset)
    q = g ^ jnp.where(jnp.asarray(s_bits, bool)[:, None], u, jnp.uint32(0))
    return _transpose_pack(q, m)


_receiver_extend = partial(jax.jit, static_argnames=("m",))(
    _receiver_extend_core
)
_sender_extend = partial(jax.jit, static_argnames=("m",))(_sender_extend_core)


# Row-sharded extension (the multi-chip kernel stage,
# parallel/kernel_shard.py): the column PRG streams are CTR-mode and the
# packed butterfly transpose is word-local, so rows [row0, row0 + m) of a
# full-width extension are computable independently given only the
# matching column-word slice — shard i of a shard_mapped extension calls
# these with its own (row0, m) and reproduces EXACTLY the rows a
# single-device extend of the whole batch would have produced (the wire
# and every pad index stay byte-identical; tier-1 asserts it).
#
# Alignment contract: ``row0`` must be a multiple of 512 rows (= 16
# stream words = one ChaCha block per column), so the per-shard stream
# reads start on a block boundary; ``m`` must be a multiple of 32 so the
# column-word slice is exact.  Both are static facts of the planar shard
# layout (shards are whole 8192-test planar blocks and S >= 1), checked
# by the caller — row0 itself may be a TRACED value (lax.axis_index).
# Rows past the session's real batch read stream blocks the cursor has
# not consumed yet (the uniform per-shard shape covers the planar pad
# region); callers MUST zero-mask those rows before anything derived
# from them becomes wire-visible, and the session cursor only ever
# advances by the real batch (:meth:`OtExtSender.advance`).


def sender_extend_rows(seeds, s_bits, u_cols, base_off, row0, m: int):
    """Q rows [row0, row0 + m) of a full extension: ``u_cols`` is the
    column-word slice ``u[:, row0//32 : row0//32 + m//32]``; ``base_off``
    is the session's pre-batch stream block offset
    (:attr:`OtExtSender.stream_offset`)."""
    return _sender_extend_core(seeds, s_bits, u_cols, base_off + row0 // 512, m)


def receiver_extend_rows(seeds0, seeds1, choices, base_off, row0, m: int):
    """(u column-word slice, T rows) for rows [row0, row0 + m): the
    receiver twin of :func:`sender_extend_rows` (``choices`` is the
    shard's own m choice bits)."""
    return _receiver_extend_core(
        seeds0, seeds1, choices, base_off + row0 // 512, m
    )


# Fused extension+hash: the column PRG, the u-XOR, the packed butterfly
# transpose, and the chosen-payload pad hash of one batch as a SINGLE
# jitted program per role — one device dispatch, no [m, 4] row tensor
# round-tripping HBM between a separately-dispatched extend and its
# ot_hash (the three-dispatch shape the per-level b2a flow used to run).
# The stream offset and pad index base enter as TRACED scalars, so batch
# N+1 of a session reuses the compiled program — per-batch bookkeeping
# never recompiles and never syncs the host.


@partial(jax.jit, static_argnames=("m", "n_words", "domain"))
def _receiver_extend_pads(seeds0, seeds1, choices, offset, idx0, m,
                          n_words, domain):
    u, t = _receiver_extend_core(seeds0, seeds1, choices, offset, m)
    return u, t, ot_hash(t, n_words, idx0, domain=domain)


@partial(jax.jit, static_argnames=("m", "n_words", "domain"))
def _sender_extend_pads(seeds, s_bits, s_block, u, offset, idx0, m,
                        n_words, domain):
    q = _sender_extend_core(seeds, s_bits, u, offset, m)
    p0 = ot_hash(q, n_words, idx0, domain=domain)
    p1 = ot_hash(q ^ s_block[None, :], n_words, idx0, domain=domain)
    return q, p0, p1


@partial(jax.jit, static_argnames=("n_words", "domain"))
def ot_hash(rows: jax.Array, n_words: int, idx_offset=0,
            domain: int = 0) -> jax.Array:
    """Correlation-robust hash of 128-bit rows -> uint32[..., n_words] pads.

    The per-row OT index is folded into the tweak so identical rows at
    different positions hash independently (the `H(j, ·)` of IKNP).
    ``domain`` separates distinct protocol uses that might share an index
    range (e.g. the 1-of-4 per-TEST pads vs per-ROW Δ-OT pads of the same
    extension batch); it XORs into tweak word 1.
    """
    rows = jnp.asarray(rows, jnp.uint32)
    m = rows.shape[-2]
    idx = jnp.arange(m, dtype=jnp.uint32) + jnp.asarray(idx_offset, jnp.uint32)
    shape = rows.shape[:-1]
    tweak = jnp.stack(
        [
            jnp.broadcast_to(idx, shape),
            jnp.full(shape, _OT_TWEAK1 ^ domain, jnp.uint32),
            jnp.full(shape, _OT_TWEAK2, jnp.uint32),
            jnp.full(shape, _OT_TWEAK3, jnp.uint32),
        ],
        axis=-1,
    )
    # fusion fence before slicing (see prg._expand_jit's rationale)
    return jax.lax.optimization_barrier(prg.chacha_block(rows ^ tweak))[..., :n_words]


def gf128_double(x: jax.Array) -> jax.Array:
    """Multiply 128-bit blocks by x in GF(2^128) (poly x^128+x^7+x^2+x+1).

    Blocks are uint32[..., 4] little-endian (bit 0 = lsb of word 0 — the
    :func:`pack_bits` orientation).  One shift-with-carry across the four
    words plus a conditional XOR of the reduction constant 0x87.  Used to
    combine S Δ-OT rows into one hash input with distinct coefficients
    (the 1-of-2^S chosen-payload OT of protocol/secure.py): the 2^S
    sender offsets ``⊕_j c_j·x^j·s`` are pairwise distinct for any
    s != 0 because the map c -> Σ c_j x^j is injective on polynomials of
    degree < 128 and multiplication by s is invertible (see
    :func:`gf128_offsets`).
    """
    x = jnp.asarray(x, jnp.uint32)
    hi = x[..., 3] >> 31  # the outgoing x^127 bit
    shifted = (x << 1) | jnp.concatenate(
        [jnp.zeros_like(x[..., :1]), x[..., :3] >> 31], axis=-1
    )
    return shifted.at[..., 0].set(shifted[..., 0] ^ hi * jnp.uint32(0x87))


def gf128_comb(rows: jax.Array) -> jax.Array:
    """Combine S stacked 128-bit rows with distinct GF(2^128) coefficients:
    uint32[..., S, 4] -> ``⊕_j x^j · rows[..., j, :]`` as uint32[..., 4].

    Horner form — S-1 doublings total, no 2^S table.  This is the
    receiver/sender row-combine of the 1-of-2^S chosen-payload OT
    (protocol/secure.py): for Δ-OT rows ``t_j = q_j ^ y_j·s`` the
    combination satisfies ``comb(t) = comb(q) ^ o_y`` with ``o_y`` the
    offset :func:`gf128_offsets` assigns to choice ``y``.
    """
    rows = jnp.asarray(rows, jnp.uint32)
    S = rows.shape[-2]
    acc = rows[..., S - 1, :]
    for j in range(S - 2, -1, -1):
        acc = gf128_double(acc) ^ rows[..., j, :]
    return acc


def gf128_offsets(s_block: jax.Array, S: int) -> jax.Array:
    """uint32[2^S, 4] — every linear combination ``o_c = ⊕_j c_j·x^j·s``
    of the doubling ladder of ``s`` (bit j of c, little-endian, picks
    ``x^j·s``).  Pairwise distinct for any s != 0: ``o_c ^ o_c' =
    (Σ (c_j ^ c'_j) x^j)·s`` and a nonzero polynomial of degree < 128
    evaluated at x is a nonzero field element (x's minimal polynomial has
    degree 128), so the product with an invertible s cannot vanish.
    Generalizes the 1-of-4 table {0, s, 2s, s^2s} to arbitrary S."""
    s = jnp.asarray(s_block, jnp.uint32)
    pows = [s]
    for _ in range(S - 1):
        pows.append(gf128_double(pows[-1]))
    c = jnp.arange(1 << S, dtype=jnp.uint32)
    offs = jnp.zeros((1 << S, 4), jnp.uint32)
    for j in range(S):
        pick = ((c >> j) & 1).astype(bool)[:, None]
        offs = offs ^ jnp.where(pick, pows[j][None, :], jnp.uint32(0))
    return offs


def s_to_block(s_bits: np.ndarray) -> np.ndarray:
    """bool[128] -> uint32[4] — the sender's ``s`` as a label-sized block."""
    return np.asarray(pack_bits(np.asarray(s_bits, bool)))


class OtExtSender:
    """Extension sender: holds ``s`` and the base seeds chosen by ``s``.

    ``s_bits[0]`` is forced to 1 so ``s`` doubles as a free-XOR offset R
    with lsb(R)=1 (point-and-permute; ops/gc.py garbles with R = s).
    """

    def __init__(self, s_bits: np.ndarray, seeds: np.ndarray):
        s_bits = np.asarray(s_bits, bool)
        if s_bits.shape != (KAPPA,) or not s_bits[0]:
            raise ValueError("need 128 choice bits with lsb(s) = 1")
        if seeds.shape != (KAPPA, 4):
            # interpolate the precomputed shape, not the seed array: key
            # material must never reach exception messages (fhh-lint
            # secret-to-sink)
            got_shape = tuple(int(x) for x in seeds.shape)
            raise ValueError(f"need uint32[128, 4] base seeds, got {got_shape}")
        taint_guard.register("OtExtSender.s_bits", s_bits)
        taint_guard.register("OtExtSender._seeds", np.asarray(seeds))
        self.s_bits = s_bits
        self.s_block = s_to_block(s_bits)  # uint32[4]
        self._seeds = jnp.asarray(seeds, jnp.uint32)
        self._s_dev = jnp.asarray(s_bits)
        self._off = 0
        self._sent = 0

    @property
    def consumed(self) -> int:
        """Total OTs extended so far — the pad-tweak index base for the next
        batch (both endpoints' ``consumed`` advance in lockstep)."""
        return self._sent

    @property
    def stream_offset(self) -> int:
        """Per-column stream position in ChaCha blocks — the ``base_off``
        a row-sharded extension (:func:`sender_extend_rows`) seeks from."""
        return self._off

    @property
    def shard_state(self) -> tuple:
        """(seeds, s_bits device array) — the raw extension state a
        shard_mapped row-sharded extend consumes (parallel/kernel_shard)."""
        return self._seeds, self._s_dev

    def advance(self, m: int) -> None:
        """Advance the session cursors past an ``m``-row batch extended
        OUT-OF-BAND (the row-sharded extension computes the rows itself
        from :attr:`shard_state`): identical bookkeeping to
        :meth:`extend`, so a sharded endpoint stays in lockstep with a
        single-device peer."""
        w = -(-m // 32)
        self._off += -(-w // 16)  # blocks consumed from each column stream
        self._sent += m

    def extend(self, m: int, u_msg) -> jax.Array:
        """Peer's u-matrix -> Q rows uint32[m, 4] (Q_j = T_j ^ r_j·s)."""
        q = _sender_extend(self._seeds, self._s_dev, jnp.asarray(u_msg), self._off, m)
        self.advance(m)
        return q

    def pads(self, q_rows: jax.Array, n_words: int, idx_offset: int):
        """(pad0, pad1) uint32[m, n_words] for chosen-payload OT."""
        p0 = ot_hash(q_rows, n_words, idx_offset)
        p1 = ot_hash(q_rows ^ jnp.asarray(self.s_block), n_words, idx_offset)
        return p0, p1

    def extend_pads(self, m: int, u_msg, n_words: int, domain: int = 0):
        """:meth:`extend` + :meth:`pads` as ONE jitted program: returns
        (Q rows uint32[m, 4], pad0, pad1 uint32[m, n_words]).  The pad
        index base is this batch's pre-extension ``consumed`` counter —
        the same convention every chosen-payload flow uses — folded in
        on device, so extension and hash share one dispatch and the
        rows never surface between them."""
        q, p0, p1 = _sender_extend_pads(
            self._seeds, self._s_dev, jnp.asarray(self.s_block),
            jnp.asarray(u_msg), self._off, self._sent, m, n_words, domain,
        )
        self.advance(m)
        return q, p0, p1


class OtExtReceiver:
    """Extension receiver: holds both base-seed columns (it played base-OT
    sender), produces the u message and its T rows per batch."""

    def __init__(self, seeds0: np.ndarray, seeds1: np.ndarray):
        if seeds0.shape != (KAPPA, 4) or seeds1.shape != (KAPPA, 4):
            raise ValueError("need two uint32[128, 4] base-seed columns")
        taint_guard.register("OtExtReceiver._seeds0", np.asarray(seeds0))
        taint_guard.register("OtExtReceiver._seeds1", np.asarray(seeds1))
        self._seeds0 = jnp.asarray(seeds0, jnp.uint32)
        self._seeds1 = jnp.asarray(seeds1, jnp.uint32)
        self._off = 0
        self._recv = 0

    @property
    def consumed(self) -> int:
        """Total OTs extended so far (see OtExtSender.consumed)."""
        return self._recv

    @property
    def stream_offset(self) -> int:
        """Stream position in blocks (see OtExtSender.stream_offset)."""
        return self._off

    @property
    def shard_state(self) -> tuple:
        """(seeds0, seeds1) for a row-sharded extend
        (:func:`receiver_extend_rows`, parallel/kernel_shard)."""
        return self._seeds0, self._seeds1

    def advance(self, m: int) -> None:
        """Out-of-band cursor bookkeeping (see OtExtSender.advance)."""
        w = -(-m // 32)
        self._off += -(-w // 16)
        self._recv += m

    def extend(self, choices) -> tuple[jax.Array, jax.Array]:
        """choices bool[m] -> (u message uint32[128, ceil(m/32)],
        T rows uint32[m, 4]).  T_j is the Δ-OT label for choice r_j."""
        choices = jnp.asarray(choices, bool)
        m = choices.shape[0]
        u, t = _receiver_extend(self._seeds0, self._seeds1, choices, self._off, m)
        self.advance(m)
        return u, t

    def pads(self, t_rows: jax.Array, n_words: int, idx_offset: int) -> jax.Array:
        """uint32[m, n_words] — the receiver's chosen pad H(j, T_j)."""
        return ot_hash(t_rows, n_words, idx_offset)

    def extend_pads(self, choices, n_words: int, domain: int = 0):
        """:meth:`extend` + :meth:`pads` as ONE jitted program: returns
        (u message, T rows uint32[m, 4], pad uint32[m, n_words]) with the
        pad index base = this batch's pre-extension ``consumed`` counter
        (the sender's :meth:`OtExtSender.extend_pads` twin)."""
        choices = jnp.asarray(choices, bool)
        m = choices.shape[0]
        u, t, pad = _receiver_extend_pads(
            self._seeds0, self._seeds1, choices, self._off, self._recv,
            m, n_words, domain,
        )
        self.advance(m)
        return u, t, pad


def fresh_s_bits(rng: secrets.SystemRandom | None = None) -> np.ndarray:
    """Random sender choice vector with lsb forced to 1 (free-XOR ready)."""
    rand = rng or secrets.SystemRandom()
    bits = np.array([bool(rand.getrandbits(1)) for _ in range(KAPPA)])
    bits[0] = True
    return bits


def inprocess_pair() -> tuple[OtExtSender, OtExtReceiver]:
    """Run the base-OT setup in-process (tests / colocated mesh parties)."""
    s_bits = fresh_s_bits()
    seeds0, seeds1, chosen = baseot.exchange(s_bits)
    return OtExtSender(s_bits, chosen), OtExtReceiver(seeds0, seeds1)
