"""Garbled-circuit equality tests as batched TPU tensor kernels.

The reference garbles per-string equality circuits with the swanky
``fancy-garbling`` stack over per-core TCP channels (ref:
src/equalitytest.rs:25-191, driven from src/collect.rs:419-437).  Its
circuit is bitwise XNOR + an AND-tree, with the garbler XOR-masking each
result by a random bit so the output is XOR-shared between the parties
(equalitytest.rs:38-43, 148-161).

TPU-native redesign — nothing is per-gate or per-wire at runtime; a whole
batch of B equality tests over S-bit strings garbles/evaluates as a handful
of fused tensor ops:

- **Wire labels** are 128-bit blocks ``uint32[..., 4]`` drawn from the
  ChaCha stream (ops/prg.py) — the same substrate the reference's AES-128
  labels live on.
- **Free-XOR** (Kolesnikov-Schneider): a global offset ``R`` with
  ``lsb(R)=1``; XOR and NOT gates cost nothing.  XNOR(x_i, y_i) is the
  free relabeling ``Z0_i = X0_i ^ Y0_i ^ R``.
- **Half-gates AND** (Zahur-Rosulek-Evans 2015): two ciphertexts per AND
  gate, hashed with the fixed-key ChaCha block function as the
  correlation-robust hash ``H(label, tweak)`` — the TPU analogue of the
  fixed-key-AES garbling hash.  The S-leaf AND-tree runs as ``ceil(log2 S)``
  *batched* gate layers.
- **Masked output**: instead of feeding the garbler's mask bit as an extra
  circuit input wire (the reference's extra wire per test,
  equalitytest.rs:38-43, 153-160), the mask folds into the output decode
  bit — identical XOR-share semantics, zero extra gates.

The evaluator receives the garbler's input labels directly and its own via
OT, exactly the reference's wire-exchange split (equalitytest.rs:68-82,
109-125).  Two delivery modes:

- ``garble_equality`` draws everything (R, X0, Y0, masks) from a seed; the
  evaluator label pairs come back in ``GarblerSecrets`` for an explicit
  payload OT — the self-contained form (tests, small batches).
- ``garble_equality_delta`` takes ``R`` and the evaluator zero-labels
  ``Y0`` externally, for the Δ-OT fusion with IKNP extension
  (ops/otext.py): the garbler sets ``R = s`` and ``Y0_j = Q_j``, so the
  receiver's ``T_j = Q_j ^ y_j·s`` *is* its active input label — labels
  arrive with zero messages beyond the extension's u-matrix.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import prg

LABEL_WORDS = 4  # 128-bit labels

# Engine for the payload garble/eval pair (the hot ops of every secure
# deployment path): True (the default on real chips) routes them through
# the fused word-planar Pallas kernels (ops/gc_pallas.py) — BIT-EXACT
# with the XLA form (the garbler's labels come from the same stream
# draw), so the wire format and every test vector are engine-independent.
# False (and any CPU host — no Mosaic there) keeps the XLA programs.
GC_PALLAS: bool = True


def _pallas_engine() -> bool:
    from ..utils import effective_platform

    return GC_PALLAS and effective_platform() != "cpu"

# hash-tweak constants (words 2/3 of the tweak block): arbitrary fixed
# odd constants so GC hashing never collides with the PRG's other uses
_TWEAK2 = 0x9E3779B9
_TWEAK3 = 0x7F4A7C15


def _hash_many(labels: jax.Array, gate_ids: jax.Array, halves) -> jax.Array:
    """Correlation-robust hash H(label, tweak) over m stacked label sets.

    labels: uint32[m, ..., 4]; halves: length-m ints (per-set half-gate
    selector).  tweak = (gate id, half selector, const, const) XORed into
    each label block before the fixed-key ChaCha permutation; the
    feed-forward add makes the map non-invertible (the Davies-Meyer role,
    as in fixed-key-AES garbling).  One stacked call per gate layer keeps
    the ChaCha op count — the dominant XLA compile cost of GC programs —
    at one block-function instance per layer instead of m.
    """
    labels = jnp.asarray(labels, jnp.uint32)
    g = jnp.asarray(gate_ids, jnp.uint32)  # [k], right-aligned broadcast
    tweak = jnp.stack(
        [g, jnp.zeros_like(g), jnp.full_like(g, _TWEAK2), jnp.full_like(g, _TWEAK3)],
        axis=-1,
    )  # [k, 4]
    m = labels.shape[0]
    h = jnp.asarray(halves, jnp.uint32).reshape((m,) + (1,) * (labels.ndim - 2))
    x = labels ^ tweak
    x = x.at[..., 1].set(x[..., 1] ^ h)  # half selector = tweak word 1
    # fusion fence before slicing (see prg._expand_jit's rationale)
    return jax.lax.optimization_barrier(prg.chacha_block(x))[..., :4]


def _maskw(bit: jax.Array, block: jax.Array) -> jax.Array:
    """bit ? block : 0, broadcasting bit over the trailing word axis."""
    return jnp.where(bit[..., None], block, jnp.zeros_like(block))


def _lsb(label: jax.Array) -> jax.Array:
    return (label[..., 0] & 1).astype(bool)


class GarbledEqBatch(NamedTuple):
    """Everything the evaluator needs except its own input labels.

    tables:    uint32[B, S-1, 2, 4] — (T_G, T_E) per AND gate, tree order;
    gb_labels: uint32[B, S, 4]      — the garbler's active input labels;
    decode:    bool[B]              — output decode bit, pre-XORed with the
                                      garbler's random mask (share 0).
    """

    tables: jax.Array
    gb_labels: jax.Array
    decode: jax.Array


class GarblerSecrets(NamedTuple):
    """Garbler-side secrets: its output share + the evaluator label pairs
    to feed the label OT (choice bit = evaluator's input bit)."""

    mask: jax.Array  # bool[B] — garbler's XOR share of each result
    ev_label0: jax.Array  # uint32[B, S, 4] — labels for y_i = 0
    ev_label1: jax.Array  # uint32[B, S, 4] — labels for y_i = 1


def _and_tree_garble(wires0, R):
    """AND-reduce zero-labels [B, S, 4] -> ([B, 4], tables [B, S-1, 2, 4])."""
    tables = []
    gate = 0
    while wires0.shape[-2] > 1:
        k = wires0.shape[-2] // 2
        A0 = wires0[..., 0 : 2 * k : 2, :]
        B0 = wires0[..., 1 : 2 * k : 2, :]
        gids = jnp.arange(gate, gate + k, dtype=jnp.uint32)
        pa, pb = _lsb(A0), _lsb(B0)
        Rb = R[..., None, :]
        HA0, HA1, HB0, HB1 = _hash_many(
            jnp.stack([A0, A0 ^ Rb, B0, B0 ^ Rb]), gids, (0, 0, 1, 1)
        )
        TG = HA0 ^ HA1 ^ _maskw(pb, Rb)
        WG = HA0 ^ _maskw(pa, TG)
        TE = HB0 ^ HB1 ^ A0
        WE = HB0 ^ _maskw(pb, TE ^ A0)
        C0 = WG ^ WE
        tables.append(jnp.stack([TG, TE], axis=-2))  # [B, k, 2, 4]
        gate += k
        wires0 = jnp.concatenate([C0, wires0[..., 2 * k :, :]], axis=-2)
    if not tables:  # S == 1: a bare XNOR, no AND gates
        tables = [jnp.zeros(wires0.shape[:-2] + (0, 2, 4), jnp.uint32)]
    return wires0[..., 0, :], jnp.concatenate(tables, axis=-3)


def _and_tree_eval(wires, tables):
    """Evaluator twin of :func:`_and_tree_garble` on active labels."""
    gate = 0
    while wires.shape[-2] > 1:
        k = wires.shape[-2] // 2
        A = wires[..., 0 : 2 * k : 2, :]
        B = wires[..., 1 : 2 * k : 2, :]
        gids = jnp.arange(gate, gate + k, dtype=jnp.uint32)
        TG = tables[..., gate : gate + k, 0, :]
        TE = tables[..., gate : gate + k, 1, :]
        HA, HB = _hash_many(jnp.stack([A, B]), gids, (0, 1))
        WG = HA ^ _maskw(_lsb(A), TG)
        WE = HB ^ _maskw(_lsb(B), TE ^ A)
        C = WG ^ WE
        gate += k
        wires = jnp.concatenate([C, wires[..., 2 * k :, :]], axis=-2)
    return wires[..., 0, :]


def _carve_label_words(seed, B: int, S: int, n_label_sets: int, with_r: bool):
    """Draw [optional R] + ``n_label_sets`` [B, S, 4] label blocks + B mask
    bits from the PRG stream — the shared randomness layout of both garble
    entry points."""
    r_words = 4 if with_r else 0
    n_words = r_words + n_label_sets * B * S * 4 + ((B + 31) // 32)
    words = prg.stream_words(jnp.asarray(seed, jnp.uint32), n_words)
    R = words[:4].at[0].set(words[0] | 1) if with_r else None  # lsb(R) = 1
    base = r_words
    sets = [
        words[base + k * B * S * 4 : base + (k + 1) * B * S * 4].reshape(B, S, 4)
        for k in range(n_label_sets)
    ]
    mask_words = words[base + n_label_sets * B * S * 4 :]
    mask = (
        (mask_words[jnp.arange(B) // 32] >> (jnp.arange(B) % 32)) & 1
    ).astype(bool)
    return R, sets, mask


def _carve_label_words_shard(seed, B: int, S: int, t0, bloc: int):
    """Tests [t0, t0 + bloc) of the ``with_r=False`` single-set draw of
    :func:`_carve_label_words` — the row-sharded kernel stage's slice of
    the garbler's label/mask randomness (parallel/kernel_shard.py).

    The stream is CTR-mode (prg.stream_blocks seeks by block), so the
    slice is computed without materializing the full draw: the label
    region of shard i starts at stream word ``t0*S*4`` and the mask-bit
    region at word ``B*S*4 + t0//32`` — ``t0`` (which may be TRACED:
    lax.axis_index × a static shard extent) must be a multiple of 512
    tests so both regions start block-aligned after the static
    intra-block offset of the mask region is folded in.  Tests at or past
    ``B`` (the planar pad region) come back ZERO, exactly matching the
    single-device twin's ``_pad_tests`` padding — byte-identity of the
    packed wire holds shard-for-shard.

    Returns (X0 uint32[bloc, S, 4], mask bool[bloc]).
    """
    seed = jnp.asarray(seed, jnp.uint32)
    # int64 (the package enables x64): the label-region word seek below
    # multiplies t0 by S*4 — int32 wraps at ~134M padded tests at S=4,
    # inside the 1M-client flagship scale
    t0 = jnp.asarray(t0, jnp.int64)
    live = t0 + jnp.arange(bloc) < B  # global pad tests carve to zero
    # label region: words [t0*S*4, (t0+bloc)*S*4) — t0*S*4 ≡ 0 (mod 16)
    nb = bloc * S * 4 // 16
    lab = prg.stream_blocks(seed, nb, t0 * (S * 4) // 16)
    X0 = lab.reshape(bloc, S, 4)
    X0 = jnp.where(live[:, None, None], X0, jnp.uint32(0))
    # mask region: starts at global word M0 = B*S*4 (static, any residue
    # mod 16); the shard needs words [M0 + t0//32, M0 + t0//32 + bloc//32)
    # — t0//32 is a multiple of 16, so the intra-block offset is the
    # STATIC M0 % 16 and the blocks seek from (M0 - M0%16)//16 + t0//512
    M0 = B * S * 4
    intra = M0 % 16
    cw = (bloc + 31) // 32
    nb2 = -(-(intra + cw) // 16)
    mwords = prg.stream_blocks(
        seed, nb2, (M0 - intra) // 16 + t0 // 512
    ).reshape(nb2 * 16)[intra : intra + cw]
    i = jnp.arange(bloc)
    mask = ((mwords[i // 32] >> (i % 32).astype(jnp.uint32)) & 1).astype(bool)
    return X0, mask & live


def _garble_core(R, X0, Y0, mask, x_bits):
    """Shared garbling core: labels + offset in, (batch, output zero-labels)
    out — ``out0`` is what payload delivery hashes (see
    :func:`garble_equality_payload`)."""
    B = x_bits.shape[0]
    Z0 = X0 ^ Y0 ^ R  # XNOR relabel (free): Z0_i = X0_i ^ Y0_i ^ R
    out0, tables = _and_tree_garble(Z0, jnp.broadcast_to(R, (B, 4)))
    decode = _lsb(out0) ^ mask
    gb_labels = X0 ^ _maskw(x_bits, R)
    return GarbledEqBatch(tables=tables, gb_labels=gb_labels, decode=decode), out0


@jax.jit
def garble_equality(
    seed: jax.Array, x_bits: jax.Array
) -> tuple[GarbledEqBatch, GarblerSecrets]:
    """Garble B equality tests over S-bit strings in one batched program.

    seed:   uint32[4] fresh randomness seed (labels + offset + masks);
    x_bits: bool[B, S] the garbler's share-bit strings.

    The result's XOR shares are (secrets.mask, evaluator's decoded bit):
    ``mask ^ decoded == [x == y]`` — the contract of the reference's
    ``multiple_gb/ev_equality_test`` pair (equalitytest.rs:25-106).
    """
    x_bits = jnp.asarray(x_bits, bool)
    B, S = x_bits.shape
    # label material: R + X0[B,S] + Y0[B,S] labels + B mask bits
    R, (X0, Y0), mask = _carve_label_words(seed, B, S, 2, with_r=True)
    batch, _ = _garble_core(R, X0, Y0, mask, x_bits)
    return batch, GarblerSecrets(mask=mask, ev_label0=Y0, ev_label1=Y0 ^ R)


@jax.jit
def garble_equality_delta(
    R: jax.Array, Y0: jax.Array, seed: jax.Array, x_bits: jax.Array
) -> tuple[GarbledEqBatch, jax.Array]:
    """Garble with Δ-OT-supplied evaluator labels (see module docstring).

    R:      uint32[4] global offset = the OT-extension sender's ``s``
            (lsb must be 1 — otext.fresh_s_bits guarantees it);
    Y0:     uint32[B, S, 4] evaluator zero-labels = the extension's Q rows;
    seed:   uint32[4] randomness for the garbler's own labels + masks;
    x_bits: bool[B, S].

    Returns (batch, mask): ``mask`` is the garbler's XOR output share.
    """
    x_bits = jnp.asarray(x_bits, bool)
    B, S = x_bits.shape
    _, (X0,), mask = _carve_label_words(seed, B, S, 1, with_r=False)
    R = jnp.asarray(R, jnp.uint32)
    batch, _ = _garble_core(R, X0, jnp.asarray(Y0, jnp.uint32), mask, x_bits)
    return batch, mask


@jax.jit
def eval_equality(batch: GarbledEqBatch, ev_labels: jax.Array) -> jax.Array:
    """Evaluate a garbled batch with the evaluator's OT-received labels.

    ev_labels: uint32[B, S, 4].  Returns bool[B] — the evaluator's XOR
    share of each equality result (= eq ^ garbler mask).
    """
    z = batch.gb_labels ^ ev_labels  # active labels of the XNOR wires
    out = _and_tree_eval(z, batch.tables)
    return _lsb(out) ^ batch.decode


def garble_equality_payload(R, Y0, seed, x_bits, m_v0, m_v1,
                            n_words: int, idx_offset):
    """Engine dispatcher — the fused Pallas kernel on a real chip (module
    flag ``GC_PALLAS``), the XLA program otherwise; outputs are bit-exact
    either way.  See :func:`_garble_equality_payload_xla` for semantics."""
    if jnp.asarray(x_bits).shape[1] >= 2 and _pallas_engine():
        from . import gc_pallas

        return gc_pallas.garble_equality_payload(
            R, Y0, seed, x_bits, m_v0, m_v1, n_words, idx_offset
        )
    return _garble_equality_payload_xla(
        R, Y0, seed, x_bits, m_v0, m_v1, n_words, idx_offset
    )


def eval_equality_payload(batch: GarbledEqBatch, ev_labels, cts,
                          n_words: int, idx_offset):
    """Engine dispatcher twin of :func:`garble_equality_payload`."""
    if batch.gb_labels.shape[1] >= 2 and _pallas_engine():
        from . import gc_pallas

        return gc_pallas.eval_equality_payload(
            batch, ev_labels, cts, n_words, idx_offset
        )
    return _eval_equality_payload_xla(batch, ev_labels, cts, n_words, idx_offset)


@partial(jax.jit, static_argnames=("n_words",))
def _garble_equality_payload_xla(R, Y0, seed, x_bits, m_v0, m_v1,
                                 n_words: int, idx_offset):
    """:func:`garble_equality_delta` + payload delivery riding the OUTPUT
    wire labels: the evaluator's garbled output label IS its 1-of-2 OT
    choice, so the separate b2a OT round (and with it a full protocol
    round trip) disappears.

    m_v0/m_v1: uint32[B, n_words] — the payload the evaluator must learn
    when the output wire carries semantic value 0 / 1 (value 1 = strings
    equal).  Ciphertexts are indexed by the label's select (lsb) bit and
    encrypted under ``H(out_label, idx)`` with the OT-domain hash — the
    same circular-correlation-robustness assumption the Δ-OT pads already
    rest on (labels differ by R = s).  ``idx_offset`` must be unique per
    (session, batch) like any OT pad index; the caller uses the extension
    session's consumed counter.

    Returns (batch, cts uint32[2, B, n_words], mask bool[B]).
    """
    from .otext import ot_hash

    x_bits = jnp.asarray(x_bits, bool)
    B, S = x_bits.shape
    _, (X0,), mask = _carve_label_words(seed, B, S, 1, with_r=False)
    R = jnp.asarray(R, jnp.uint32)
    batch, out0 = _garble_core(R, X0, jnp.asarray(Y0, jnp.uint32), mask, x_bits)
    h0 = ot_hash(out0, n_words, idx_offset)  # pad for the v=0 label
    h1 = ot_hash(out0 ^ R, n_words, idx_offset)
    c_v0 = jnp.asarray(m_v0, jnp.uint32) ^ h0
    c_v1 = jnp.asarray(m_v1, jnp.uint32) ^ h1
    p = _lsb(out0)[:, None]  # select bit of the v=0 label
    cts = jnp.stack([jnp.where(p, c_v1, c_v0), jnp.where(p, c_v0, c_v1)])
    return batch, cts, mask


@partial(jax.jit, static_argnames=("n_words",))
def _eval_equality_payload_xla(batch: GarbledEqBatch, ev_labels, cts,
                               n_words: int, idx_offset):
    """Evaluate and open the output-label payload in one pass.

    Returns (e bool[B] — the evaluator's XOR share, payload uint32[B,
    n_words] — m_v for the actual output value v, which the evaluator
    learns without learning v)."""
    from .otext import ot_hash

    z = batch.gb_labels ^ jnp.asarray(ev_labels, jnp.uint32)
    out = _and_tree_eval(z, batch.tables)
    s = _lsb(out)
    pad = ot_hash(out, n_words, idx_offset)
    ct = jnp.where(s[:, None], cts[1], cts[0])
    return s ^ batch.decode, ct ^ pad


# ---------------------------------------------------------------------------
# Whole-level PACKED flow: the planar wire format (gc_pallas layout)
# ---------------------------------------------------------------------------
#
# The packed entry points emit/consume the garbled message as the planar
# plane stack of ops/gc_pallas.py (``tables | gb_labels | decode | cts``
# planes, each ``padded_tests(B)`` words).  On the Pallas engine that
# buffer is the kernel's output raveled in place — the garble→pack and
# unpack→eval transposes of the test-major wire format disappear.  The
# XLA twins here planarize explicitly and are BYTE-IDENTICAL, so the wire
# format (like every GC test vector) stays engine-independent and a
# CPU-engine endpoint interoperates with a Pallas-engine one.


def _pad_tests(a, bp: int):
    """Zero-pad the leading (test) axis to ``bp`` — the XLA twin garbles
    the padded slots exactly like the Pallas kernel does (zero-padded
    planar inputs), so the packed wire buffers are BYTE-identical
    engine-to-engine, padding included.  The receiver discards the pad
    slots either way."""
    B = a.shape[0]
    if bp == B:
        return a
    return jnp.concatenate(
        [a, jnp.zeros((bp - B,) + a.shape[1:], a.dtype)]
    )


def _garble_packed_planes_xla(R, Y0, X0, mask, x_bits, m_v0, m_v1,
                              n_words: int, idx_offset):
    """The packed-garble math AFTER label carving: every input already at
    the full planar extent (``x_bits.shape[0]`` a multiple of the planar
    block, pad slots zero).  Shared by the single-device twin below
    (which carves then pads) and the row-sharded kernel stage
    (parallel/kernel_shard.py — each shard feeds its
    :func:`_carve_label_words_shard` slice and a TRACED ``idx_offset``),
    so the planar wire bytes come from exactly one defining form.
    Returns the raveled planar buffer (tables | gb_labels | decode |
    cts planes)."""
    from . import gc_pallas
    from .otext import ot_hash

    bp = x_bits.shape[0]
    batch, out0 = _garble_core(R, X0, Y0, mask, x_bits)
    h0 = ot_hash(out0, n_words, idx_offset)
    h1 = ot_hash(out0 ^ R, n_words, idx_offset)
    c_v0 = jnp.asarray(m_v0, jnp.uint32) ^ h0
    c_v1 = jnp.asarray(m_v1, jnp.uint32) ^ h1
    p = _lsb(out0)[:, None]
    cts = jnp.stack([jnp.where(p, c_v1, c_v0), jnp.where(p, c_v0, c_v1)])
    parts = [
        gc_pallas._planarize(batch.tables, bp, bp),
        gc_pallas._planarize(batch.gb_labels, bp, bp),
        gc_pallas._planarize(jnp.asarray(batch.decode, jnp.uint32), bp, bp),
        gc_pallas._planarize(jnp.transpose(cts, (1, 0, 2)), bp, bp),
    ]
    return jnp.concatenate([jnp.ravel(p_) for p_ in parts])


@partial(jax.jit, static_argnames=("n_words",))
def _garble_equality_payload_packed_xla(R, Y0, seed, x_bits, m_v0, m_v1,
                                        n_words: int, idx_offset):
    from . import gc_pallas

    x_bits = jnp.asarray(x_bits, bool)
    B, S = x_bits.shape
    bp = gc_pallas.padded_tests(B)
    # the garbler's own labels + mask are drawn for the REAL B tests
    # (the same stream draw as every other engine/flow), then padded —
    # matching the kernel's zero-padded planar inputs bit for bit
    _, (X0,), mask = _carve_label_words(seed, B, S, 1, with_r=False)
    R = jnp.asarray(R, jnp.uint32)
    msg = _garble_packed_planes_xla(
        R, _pad_tests(jnp.asarray(Y0, jnp.uint32), bp), _pad_tests(X0, bp),
        _pad_tests(mask, bp), _pad_tests(x_bits, bp),
        _pad_tests(jnp.asarray(m_v0, jnp.uint32), bp),
        _pad_tests(jnp.asarray(m_v1, jnp.uint32), bp),
        n_words, idx_offset,
    )
    return msg, mask


@partial(jax.jit, static_argnames=("S", "n_words"))
def _eval_equality_payload_packed_xla(msg, ev_labels, S: int,
                                      n_words: int, idx_offset):
    from . import gc_pallas

    ev_labels = jnp.asarray(ev_labels, jnp.uint32)
    B = ev_labels.shape[0]
    tab, gbl, dec, ctsp = gc_pallas._split_packed(
        jnp.asarray(msg, jnp.uint32), B, S, n_words
    )
    batch = GarbledEqBatch(
        tables=gc_pallas._unplanarize(tab, B).reshape(B, S - 1, 2, 4),
        gb_labels=gc_pallas._unplanarize(gbl, B).reshape(B, S, 4),
        decode=gc_pallas._unplanarize(dec, B).reshape(B) != 0,
    )
    cts = gc_pallas._unplanarize(ctsp, B).reshape(B, 2, n_words)
    cts = jnp.transpose(cts, (1, 0, 2))
    return _eval_equality_payload_xla(
        batch, ev_labels, cts, n_words, idx_offset
    )


def garble_equality_payload_packed(R, Y0, seed, x_bits, m_v0, m_v1,
                                   n_words: int, idx_offset):
    """Engine dispatcher for the whole-level packed garble (byte-identical
    planar wire either way).  Returns (msg, mask)."""
    if jnp.asarray(x_bits).shape[1] >= 2 and _pallas_engine():
        from . import gc_pallas

        return gc_pallas.garble_equality_payload_packed(
            R, Y0, seed, x_bits, m_v0, m_v1, n_words, idx_offset
        )
    return _garble_equality_payload_packed_xla(
        R, Y0, seed, x_bits, m_v0, m_v1, n_words, idx_offset
    )


def eval_equality_payload_packed(msg, ev_labels, n_words: int, idx_offset):
    """Engine dispatcher twin of :func:`garble_equality_payload_packed`.
    Returns (e bool[B], payload uint32[B, n_words])."""
    ev_labels = jnp.asarray(ev_labels, jnp.uint32)
    S = ev_labels.shape[1]
    if S >= 2 and _pallas_engine():
        from . import gc_pallas

        return gc_pallas.eval_equality_payload_packed(
            msg, ev_labels, n_words, idx_offset
        )
    return _eval_equality_payload_packed_xla(
        msg, ev_labels, S, n_words, idx_offset
    )
