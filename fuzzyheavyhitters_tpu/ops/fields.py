"""Prime fields as JAX dtype modules.

Two fields, mirroring the reference's dual-field design (inner tree levels in
a fast 62-bit field, final level in a 255-bit field):

- ``FE62``: p = 2^62 - 2^30 - 1 on ``uint64`` tensors with the same lazy
  bit-reduction representation as the reference (ref: src/fastfield.rs:24-107)
  — shifts and masks only, no division, XLA/TPU-friendly.
- ``F255``: p = 2^255 - 19 on ``uint32[..., 8]`` little-endian limb tensors
  (ref: src/field.rs:19 — its comment says 2^255-10 but the hex constant
  ``7fff...ffed`` is 2^255-19; we match the constant).  Values are kept
  canonical (< p); ops are fixed 8-limb carry chains.

Both expose the same functional surface (zeros/from_int/add/sub/neg/canon/
ge/sample/pack...), so the aggregation engine is generic over the level
field.  ``sample`` maps uniform random words to near-uniform field elements
with O(2^-62) statistical bias — data-independent shapes (no rejection
loops), unlike the reference's host-side rejection sampling
(ref: src/field.rs:251-264), which cannot be expressed as a fixed-shape
device program.
"""

from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

_M62 = (1 << 62) - 1
_P62 = (1 << 62) - (1 << 30) - 1


class FE62:
    """p = 2^62 - 2^30 - 1 over uint64, lazily reduced (val <= ~2^62)."""

    P = _P62
    dtype = jnp.uint64
    limb_shape = ()  # scalar per element

    @staticmethod
    def _bit_reduce(v):
        # 2^62 === 2^30 + 1 (mod p)   (fastfield.rs:86-95)
        excess = v >> 62
        low = v & jnp.uint64(_M62)
        return low + excess + (excess << 30)

    @classmethod
    def new(cls, v):
        return cls._bit_reduce(jnp.asarray(v, jnp.uint64))

    @classmethod
    def zeros(cls, shape):
        return jnp.zeros(shape, jnp.uint64)

    @classmethod
    def from_int(cls, x: int):
        return jnp.asarray(x % cls.P, jnp.uint64)

    @classmethod
    def canon(cls, v):
        """Fully-reduced value in [0, p)  (fastfield.rs:100-107, 147-152)."""
        v = cls._bit_reduce(cls._bit_reduce(v))
        return jnp.where(v >= cls.P, v - cls.P, v)

    @classmethod
    def add(cls, a, b):
        return cls._bit_reduce(a + b)

    @classmethod
    def neg(cls, a):
        return cls._bit_reduce(jnp.uint64(2 * cls.P) - a)

    @classmethod
    def sub(cls, a, b):
        return cls.add(a, cls.neg(b))

    @classmethod
    def mul(cls, a, b):
        """Full 124-bit product reduced mod p, u64 ops only."""
        a = cls._bit_reduce(cls._bit_reduce(a))  # < 2^62
        b = cls._bit_reduce(cls._bit_reduce(b))
        mask32 = jnp.uint64(0xFFFFFFFF)
        a0, a1 = a & mask32, a >> 32  # a1 < 2^30
        b0, b1 = b & mask32, b >> 32
        t0 = a0 * b0
        t1 = a0 * b1 + a1 * b0  # < 2^63
        t2 = a1 * b1  # < 2^60
        t1 = t1 + (t0 >> 32)
        c0 = t0 & mask32
        t2 = t2 + (t1 >> 32)  # < 2^61
        c1 = t1 & mask32
        # product = c0 + c1*2^32 + t2*2^64 ; split at bit 62
        low = ((c1 & jnp.uint64(0x3FFFFFFF)) << 32) | c0
        high = (t2 << 2) | (c1 >> 30)
        # product === low + high*(2^30 + 1) (mod p); split high to keep u64
        h0, h1 = high & mask32, high >> 32
        r = cls._bit_reduce(low + high)
        r = cls._bit_reduce(r + (h0 << 30))
        r = cls._bit_reduce(r + (h1 << 30))
        return cls._bit_reduce(r + h1)

    @classmethod
    def pow_const(cls, a, e: int):
        """a^e for a Python-int exponent: square-and-multiply as a
        ``lax.scan`` over the exponent bits (LSB-first), so the compiled
        graph is one square + one select-multiply regardless of exponent
        size — an unrolled chain at 255-bit exponents is a ~10^5-op graph
        that the TPU compiler cannot digest."""
        return _pow_scan(cls, jnp.asarray(a, jnp.uint64), e)

    @classmethod
    def recip(cls, a):
        """Multiplicative inverse by Fermat: a^(p-2)  (ref: fastfield.rs:154
        ``recip`` — same exponentiation-by-squaring construction).
        recip(0) = 0 (garbage-in convention, as in the reference)."""
        return cls.pow_const(a, cls.P - 2)

    @classmethod
    def ge(cls, a, b):
        return cls.canon(a) >= cls.canon(b)

    @classmethod
    def eq(cls, a, b):
        return cls.canon(a) == cls.canon(b)

    # -- Block codec (OT payloads travel as 128-bit blocks; ref:
    # fastfield.rs:414-431 Block (de)serialization) ----------------------

    @classmethod
    def to_blocks(cls, v) -> "jax.Array":
        """[...] canonical values -> uint32[..., 4] little-endian blocks."""
        v = cls.canon(v)
        lo = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (v >> 32).astype(jnp.uint32)
        zeros = jnp.zeros_like(lo)
        return jnp.stack([lo, hi, zeros, zeros], axis=-1)

    @classmethod
    def from_blocks(cls, blocks) -> "jax.Array":
        """uint32[..., 4] blocks -> field values (upper words ignored mod p)."""
        blocks = jnp.asarray(blocks, jnp.uint64)
        lo = blocks[..., 0] | (blocks[..., 1] << 32)
        hi = blocks[..., 2] | (blocks[..., 3] << 32)
        return cls.add(
            cls._bit_reduce(lo), cls.mul(cls.new(hi), cls.from_int((1 << 64) % cls.P))
        )

    @classmethod
    def sample(cls, words):
        """uniform uint32[..., 4] -> near-uniform field elements [...]."""
        words = jnp.asarray(words, jnp.uint64)
        lo = (words[..., 0] | (words[..., 1] << 32)) & jnp.uint64(_M62)
        hi = words[..., 2] | (words[..., 3] << 32)
        # value = hi*2^62 + lo (mod p): 126 uniform bits -> bias ~2^-64
        mask32 = jnp.uint64(0xFFFFFFFF)
        h0, h1 = hi & mask32, hi >> 32
        r = cls._bit_reduce(lo + hi)
        r = cls._bit_reduce(r + (h0 << 30))
        r = cls._bit_reduce(r + (h1 << 30))
        return cls._bit_reduce(r + h1)

    @classmethod
    def sum(cls, v, *, axis):
        """Modular sum along ``axis`` for up to ~2^31 canonical terms.

        Splits into 32-bit halves so the plain integer sums cannot overflow
        u64, then recombines mod p — one reduction for the whole axis instead
        of the reference's per-element add chain (collect.rs:487-501).
        """
        v = cls._bit_reduce(cls._bit_reduce(v))  # < 2^62
        mask32 = jnp.uint64(0xFFFFFFFF)
        lo = jnp.sum(v & mask32, axis=axis)
        hi = jnp.sum(v >> 32, axis=axis)
        return cls.add(cls._bit_reduce(lo), cls.mul(cls.new(hi), cls.from_int(1 << 32)))

    @classmethod
    def to_numpy_ints(cls, v) -> np.ndarray:
        # cls.canon is already jitted at import (_jit_field_methods);
        # re-wrapping it here built a fresh compile cache per call
        return np.asarray(cls.canon(v), dtype=np.uint64)

    # -- host (NumPy) twins: bit-identical math with no device round trip,
    # for per-level host-side derivations (the shared wire masks in
    # protocol/rpc.py) where a device sample + fetch costs a tunnel RTT --

    @staticmethod
    def _np_bit_reduce(v: np.ndarray) -> np.ndarray:
        excess = v >> np.uint64(62)
        low = v & np.uint64(_M62)
        return low + excess + (excess << np.uint64(30))

    @classmethod
    def np_add(cls, a, b) -> np.ndarray:
        return cls._np_bit_reduce(
            np.asarray(a, np.uint64) + np.asarray(b, np.uint64)
        )

    @classmethod
    def np_sample(cls, words) -> np.ndarray:
        """Host twin of :meth:`sample` (same bit-for-bit mapping)."""
        w = np.asarray(words, np.uint64)
        lo = (w[..., 0] | (w[..., 1] << np.uint64(32))) & np.uint64(_M62)
        hi = w[..., 2] | (w[..., 3] << np.uint64(32))
        mask32 = np.uint64(0xFFFFFFFF)
        h0, h1 = hi & mask32, hi >> np.uint64(32)
        r = cls._np_bit_reduce(lo + hi)
        r = cls._np_bit_reduce(r + (h0 << np.uint64(30)))
        r = cls._np_bit_reduce(r + (h1 << np.uint64(30)))
        return cls._np_bit_reduce(r + h1)


_P255 = (1 << 255) - 19
_P255_LIMBS = tuple((_P255 >> (32 * i)) & 0xFFFFFFFF for i in range(8))


class F255:
    """p = 2^255 - 19 over uint32[..., 8] little-endian limbs, canonical."""

    P = _P255
    dtype = jnp.uint32
    limb_shape = (8,)

    @classmethod
    def zeros(cls, shape):
        return jnp.zeros(tuple(shape) + (8,), jnp.uint32)

    @classmethod
    def from_int(cls, x: int):
        x %= cls.P
        return jnp.array([(x >> (32 * i)) & 0xFFFFFFFF for i in range(8)], jnp.uint32)

    @staticmethod
    def _carry_chain(limbs64):
        """[..., 8] uint64 partial sums -> (uint32 limbs, carry_out uint64)."""
        out = []
        carry = jnp.zeros_like(limbs64[..., 0])
        for i in range(8):
            s = limbs64[..., i] + carry
            out.append(s & jnp.uint64(0xFFFFFFFF))
            carry = s >> 32
        return jnp.stack(out, axis=-1), carry

    @classmethod
    def _sub_p_if(cls, limbs, cond):
        """Conditionally subtract p (borrow chain); cond broadcast over limbs."""
        p = jnp.array(_P255_LIMBS, jnp.uint64)
        out = []
        borrow = jnp.zeros_like(limbs[..., 0].astype(jnp.uint64))
        for i in range(8):
            d = limbs[..., i].astype(jnp.uint64) - p[i] - borrow
            out.append(d & jnp.uint64(0xFFFFFFFF))
            borrow = (d >> 63) & jnp.uint64(1)  # underflow wraps high bit
        sub = jnp.stack(out, axis=-1).astype(jnp.uint32)
        return jnp.where(cond[..., None], sub, limbs)

    @classmethod
    def _geq_p(cls, limbs):
        ge = jnp.ones(limbs.shape[:-1], bool)
        decided = jnp.zeros(limbs.shape[:-1], bool)
        for i in reversed(range(8)):
            li = limbs[..., i]
            pi = jnp.uint32(_P255_LIMBS[i])
            gt = ~decided & (li > pi)
            lt = ~decided & (li < pi)
            ge = jnp.where(lt, False, jnp.where(gt, True, ge))
            decided = decided | gt | lt
        return ge

    @classmethod
    def add(cls, a, b):
        s64 = a.astype(jnp.uint64) + b.astype(jnp.uint64)
        limbs, carry = cls._carry_chain(s64)
        # carry*2^256 === carry*38 (mod p); carry <= 1 so one more chain settles
        limbs = cls._carry_chain(limbs.astype(jnp.uint64).at[..., 0].add(carry * 38))[0]
        limbs = limbs.astype(jnp.uint32)
        return cls._sub_p_if(limbs, cls._geq_p(limbs))

    @classmethod
    def neg(cls, a):
        p = jnp.array(_P255_LIMBS, jnp.uint64)
        out = []
        borrow = jnp.zeros_like(a[..., 0].astype(jnp.uint64))
        for i in range(8):
            d = p[i] - a[..., i].astype(jnp.uint64) - borrow
            out.append(d & jnp.uint64(0xFFFFFFFF))
            borrow = (d >> 63) & jnp.uint64(1)
        r = jnp.stack(out, axis=-1).astype(jnp.uint32)
        # p - 0 = p === 0: canonicalize
        return cls._sub_p_if(r, cls._geq_p(r))

    @classmethod
    def sub(cls, a, b):
        return cls.add(a, cls.neg(b))

    @classmethod
    def mul(cls, a, b):
        """Schoolbook 8x8 limb product + 2^256 === 38 folding (ref:
        field.rs:339-343 ``mul`` over BigUint; here a fixed-width carry
        network of u64 ops only, no bignums, XLA-friendly).

        Column sums split each 64-bit partial product into lo/hi words so no
        intermediate exceeds u64 (max 8 terms of < 2^32 each per column).
        """
        a64 = jnp.asarray(a, jnp.uint32).astype(jnp.uint64)
        b64 = jnp.asarray(b, jnp.uint32).astype(jnp.uint64)
        mask32 = jnp.uint64(0xFFFFFFFF)
        batch = jnp.broadcast_shapes(a64.shape[:-1], b64.shape[:-1])
        cols_lo = [jnp.zeros(batch, jnp.uint64) for _ in range(17)]
        cols_hi = [jnp.zeros(batch, jnp.uint64) for _ in range(17)]
        for i in range(8):
            for j in range(8):
                p = a64[..., i] * b64[..., j]
                k = i + j
                cols_lo[k] = cols_lo[k] + (p & mask32)
                cols_hi[k + 1] = cols_hi[k + 1] + (p >> 32)
        # carry-propagate into 16 product limbs (value < 2^512)
        limbs16 = []
        carry = jnp.zeros(batch, jnp.uint64)
        for k in range(16):
            s = cols_lo[k] + cols_hi[k] + carry
            limbs16.append(s & mask32)
            carry = s >> 32
        # fold: product = L + 2^256*H === L + 38*H (mod p)
        out = []
        carry = jnp.zeros(batch, jnp.uint64)
        for k in range(8):
            s = limbs16[k] + limbs16[k + 8] * jnp.uint64(38) + carry
            out.append(s & mask32)
            carry = s >> 32
        # carry < 103; fold 38*carry back in, twice: the first re-fold can
        # itself overflow 2^256 only when the value was within 38*103 of it,
        # leaving a wrapped value < 4000 — so the second re-fold cannot carry.
        for _ in range(2):
            c2 = carry * jnp.uint64(38)
            limbs = []
            for k in range(8):
                s = out[k] + c2
                limbs.append(s & mask32)
                c2 = s >> 32
            out, carry = limbs, c2
        r = jnp.stack(out, axis=-1).astype(jnp.uint32)
        r = cls._sub_p_if(r, cls._geq_p(r))
        return cls._sub_p_if(r, cls._geq_p(r))

    @classmethod
    def pow_const(cls, a, e: int):
        """a^e for a Python-int exponent (scan over exponent bits, see
        FE62.pow_const for why scan rather than unrolling)."""
        return _pow_scan(cls, jnp.asarray(a, jnp.uint32), e)

    @classmethod
    def recip(cls, a):
        """Multiplicative inverse by Fermat: a^(p-2); recip(0) = 0.  The
        reference's FieldElm has no inverse (field.rs) — added here for the
        sketch/MPC layer's field-law completeness."""
        return cls.pow_const(a, cls.P - 2)

    @classmethod
    def canon(cls, a):
        return a

    @classmethod
    def ge(cls, a, b):
        """a >= b on canonical values, limbwise big-endian compare."""
        ge = jnp.ones(a.shape[:-1], bool)
        decided = jnp.zeros(a.shape[:-1], bool)
        for i in reversed(range(8)):
            gt = ~decided & (a[..., i] > b[..., i])
            lt = ~decided & (a[..., i] < b[..., i])
            ge = jnp.where(lt, False, jnp.where(gt, True, ge))
            decided = decided | gt | lt
        return ge

    @classmethod
    def eq(cls, a, b):
        return jnp.all(a == b, axis=-1)

    @classmethod
    def sample(cls, words):
        """uniform uint32[..., 8] -> field elements [..., 8] (bias ~2^-250)."""
        limbs = jnp.asarray(words, jnp.uint32)
        limbs = cls._sub_p_if(limbs, cls._geq_p(limbs))
        limbs = cls._sub_p_if(limbs, cls._geq_p(limbs))
        return limbs

    # -- host (NumPy) twins (see FE62: per-level host derivations must not
    # cost a device round trip) --------------------------------------------

    @classmethod
    def _np_geq_p(cls, limbs: np.ndarray) -> np.ndarray:
        ge = np.ones(limbs.shape[:-1], bool)
        decided = np.zeros(limbs.shape[:-1], bool)
        for i in reversed(range(8)):
            li = limbs[..., i]
            pi = np.uint32(_P255_LIMBS[i])
            gt = ~decided & (li > pi)
            lt = ~decided & (li < pi)
            ge = np.where(lt, False, np.where(gt, True, ge))
            decided = decided | gt | lt
        return ge

    @classmethod
    def _np_sub_p_if(cls, limbs: np.ndarray, cond: np.ndarray) -> np.ndarray:
        p = np.array(_P255_LIMBS, np.uint64)
        out = np.zeros(limbs.shape, np.uint64)
        borrow = np.zeros(limbs.shape[:-1], np.uint64)
        for i in range(8):
            d = limbs[..., i].astype(np.uint64) - p[i] - borrow
            out[..., i] = d & np.uint64(0xFFFFFFFF)
            borrow = (d >> np.uint64(63)) & np.uint64(1)
        return np.where(cond[..., None], out.astype(np.uint32), limbs)

    @staticmethod
    def _np_carry_chain(limbs64: np.ndarray):
        out = np.zeros(limbs64.shape, np.uint64)
        carry = np.zeros(limbs64.shape[:-1], np.uint64)
        for i in range(8):
            s = limbs64[..., i] + carry
            out[..., i] = s & np.uint64(0xFFFFFFFF)
            carry = s >> np.uint64(32)
        return out, carry

    @classmethod
    def np_add(cls, a, b) -> np.ndarray:
        s64 = np.asarray(a, np.uint32).astype(np.uint64) + np.asarray(
            b, np.uint32
        ).astype(np.uint64)
        limbs, carry = cls._np_carry_chain(s64)
        limbs[..., 0] += carry * np.uint64(38)  # 2^256 === 38 (mod p)
        limbs = cls._np_carry_chain(limbs)[0].astype(np.uint32)
        return cls._np_sub_p_if(limbs, cls._np_geq_p(limbs))

    @classmethod
    def np_sample(cls, words) -> np.ndarray:
        """Host twin of :meth:`sample` (same bit-for-bit mapping)."""
        limbs = np.asarray(words, np.uint32)
        limbs = cls._np_sub_p_if(limbs, cls._np_geq_p(limbs))
        return cls._np_sub_p_if(limbs, cls._np_geq_p(limbs))

    @classmethod
    def sum(cls, v, *, axis):
        """Modular sum along ``axis`` via pairwise tree reduction."""
        axis = axis % (v.ndim - 1)
        v = jnp.moveaxis(v, axis, 0)
        while v.shape[0] > 1:
            n = v.shape[0]
            if n % 2:
                v = jnp.concatenate([v, cls.zeros((1,) + v.shape[1:-1])], axis=0)
                n += 1
            v = cls.add(v[: n // 2], v[n // 2 :])
        return v[0]

    @classmethod
    def to_numpy_ints(cls, v) -> np.ndarray:
        limbs = np.asarray(v, dtype=np.uint64)
        flat = limbs.reshape(-1, 8)
        out = np.array(
            [sum(int(row[i]) << (32 * i) for i in range(8)) for row in flat],
            dtype=object,
        )
        return out.reshape(limbs.shape[:-1])

    # -- BlockPair codec (ref: field.rs:465-492 — F255 OT payloads travel
    # as two 128-bit blocks) ---------------------------------------------

    @classmethod
    def to_blocks(cls, v) -> "jax.Array":
        """[..., 8] limbs -> uint32[..., 2, 4] block pairs (low block first,
        little-endian words — our canonical layout; the reference uses
        big-endian bytes, a serialization detail with no protocol effect)."""
        v = jnp.asarray(v, jnp.uint32)
        return v.reshape(v.shape[:-1] + (2, 4))

    @classmethod
    def from_blocks(cls, blocks) -> "jax.Array":
        """uint32[..., 2, 4] block pairs -> [..., 8] limbs (mod-p folded)."""
        blocks = jnp.asarray(blocks, jnp.uint32)
        limbs = blocks.reshape(blocks.shape[:-2] + (8,))
        limbs = cls._sub_p_if(limbs, cls._geq_p(limbs))
        return cls._sub_p_if(limbs, cls._geq_p(limbs))


def _pow_scan(field, a, e: int):
    """Shared square-and-multiply scan over the bits of a Python int."""
    if e == 0:
        one = field.from_int(1)
        return jnp.broadcast_to(one, a.shape[: a.ndim - len(field.limb_shape)] + one.shape)
    bits = jnp.asarray([(e >> i) & 1 for i in range(e.bit_length())], bool)
    one = jnp.broadcast_to(
        field.from_int(1), a.shape[: a.ndim - len(field.limb_shape)] + field.limb_shape
    ).astype(a.dtype)

    def step(carry, bit):
        result, base = carry
        taken = field.mul(result, base)
        result = jnp.where(bit, taken, result)
        return (result, field.mul(base, base)), None

    (result, _), _ = jax.lax.scan(step, (one, a), bits)
    return result


_P63 = (1 << 63) - 25
_M63 = (1 << 63) - 1


class U63:
    """p = 2^63 - 25 on uint64, canonical values — the reference's ``Group``
    impl for u64 (ref: field.rs:25-26, 128-188: MODULUS_64 = 2^63 - 25)."""

    P = _P63
    dtype = jnp.uint64
    limb_shape = ()

    @staticmethod
    def _reduce63(v):
        # 2^63 === 25 (mod p); one bit of excess folds in 25 at a time
        return (v & jnp.uint64(_M63)) + jnp.uint64(25) * (v >> 63)

    @classmethod
    def canon(cls, v):
        v = cls._reduce63(cls._reduce63(v))
        return jnp.where(v >= cls.P, v - cls.P, v)

    @classmethod
    def zeros(cls, shape):
        return jnp.zeros(shape, jnp.uint64)

    @classmethod
    def from_int(cls, x: int):
        return jnp.asarray(x % cls.P, jnp.uint64)

    @classmethod
    def add(cls, a, b):
        # canonical inputs sum below 2^64; settle back to canonical
        return cls.canon(jnp.asarray(a, jnp.uint64) + jnp.asarray(b, jnp.uint64))

    @classmethod
    def neg(cls, a):
        return cls.canon(jnp.uint64(cls.P) - jnp.asarray(a, jnp.uint64))

    @classmethod
    def sub(cls, a, b):
        return cls.add(a, cls.neg(b))

    @classmethod
    def mul(cls, a, b):
        """126-bit product via 32-bit split, folded with 2^64 === 50."""
        a = cls.canon(jnp.asarray(a, jnp.uint64))
        b = cls.canon(jnp.asarray(b, jnp.uint64))
        mask32 = jnp.uint64(0xFFFFFFFF)
        a0, a1 = a & mask32, a >> 32  # a1 < 2^31
        b0, b1 = b & mask32, b >> 32
        t0 = a0 * b0
        t1 = a0 * b1 + a1 * b0  # < 2^64 - 2^33
        t2 = a1 * b1  # < 2^62
        t1 = t1 + (t0 >> 32)
        t2 = t2 + (t1 >> 32)
        t_low = (t0 & mask32) | ((t1 & mask32) << 32)  # product mod 2^64
        # product = t_low + t2*2^64 === t_low + 50*t2; decompose t2 to stay
        # in u64: 50*t2 = 50*t2l + (50*t2h mod p)*2^32-ish chains below
        t2l, t2h = t2 & mask32, t2 >> 32  # t2h < 2^30
        u = jnp.uint64(50) * t2h  # < 2^36
        ul, uh = u & mask32, u >> 32  # uh < 2^4
        r = cls._reduce63(cls._reduce63(t_low))
        r = cls.add(r, cls.canon(jnp.uint64(50) * t2l))
        r = cls.add(r, cls.canon(ul << 32))
        return cls.add(r, jnp.uint64(50) * uh)

    @classmethod
    def eq(cls, a, b):
        return cls.canon(a) == cls.canon(b)

    @classmethod
    def sample(cls, words):
        """uniform uint32[..., 4] -> near-uniform field elements (shaped
        device sampling; the reference rejection-samples host-side,
        field.rs:168-175)."""
        words = jnp.asarray(words, jnp.uint64)
        lo = (words[..., 0] | (words[..., 1] << 32)) & jnp.uint64(_M63)
        hi = words[..., 2] | (words[..., 3] << 32)
        return cls.add(cls._reduce63(lo), cls.mul(cls.canon(hi), cls.from_int(1 << 32)))

    @classmethod
    def sum(cls, v, *, axis):
        v = cls.canon(jnp.asarray(v, jnp.uint64))
        mask32 = jnp.uint64(0xFFFFFFFF)
        lo = jnp.sum(v & mask32, axis=axis)
        hi = jnp.sum(v >> 32, axis=axis)
        return cls.add(cls.canon(lo), cls.mul(cls.canon(hi), cls.from_int(1 << 32)))

    @classmethod
    def to_numpy_ints(cls, v) -> np.ndarray:
        # cls.canon is already jitted at import (_jit_field_methods)
        return np.asarray(cls.canon(v), dtype=np.uint64)


class Dummy:
    """The reference's no-op group (ref: field.rs:44-126): every op returns
    zero; used to stub a field slot out of a generic protocol."""

    P = 1
    dtype = jnp.uint32
    limb_shape = ()

    zeros = staticmethod(lambda shape: jnp.zeros(shape, jnp.uint32))
    from_int = staticmethod(lambda x: jnp.uint32(0))
    canon = staticmethod(lambda v: jnp.zeros_like(v))
    add = staticmethod(lambda a, b: jnp.zeros_like(a))
    sub = staticmethod(lambda a, b: jnp.zeros_like(a))
    neg = staticmethod(lambda a: jnp.zeros_like(a))
    mul = staticmethod(lambda a, b: jnp.zeros_like(a))
    eq = staticmethod(lambda a, b: jnp.ones(jnp.asarray(a).shape, bool))
    sample = staticmethod(lambda words: jnp.zeros(jnp.asarray(words).shape[:-1], jnp.uint32))

    @staticmethod
    def sum(v, *, axis):
        return jnp.zeros(tuple(np.delete(np.asarray(v.shape), axis)), jnp.uint32)


def _jit_field_methods():
    """Jit the eager entry points once per class; composing jitted calls inside
    a larger jit still inlines and fuses (XLA treats them as nested calls)."""
    for klass, names in (
        (
            FE62,
            ["new", "canon", "add", "neg", "sub", "mul", "recip", "ge", "eq",
             "sample", "to_blocks", "from_blocks"],
        ),
        (
            F255,
            ["add", "neg", "sub", "mul", "recip", "ge", "eq", "sample",
             "to_blocks", "from_blocks"],
        ),
        (U63, ["canon", "add", "neg", "sub", "mul", "eq", "sample"]),
    ):
        for name in names:
            # fhh-lint: disable=recompile-churn (runs once, at import)
            setattr(klass, name, staticmethod(jax.jit(getattr(klass, name))))
        setattr(
            klass,
            "sum",
            # fhh-lint: disable=recompile-churn (runs once, at import)
            staticmethod(jax.jit(getattr(klass, "sum"), static_argnames=("axis",))),
        )


_jit_field_methods()
